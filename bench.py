"""Benchmark: tutorial-parity Transformer LM training throughput.

Workload = the reference's headline config (``/root/reference/main.py:101-120``:
WikiText-2 LM, batch 32, bptt 128, emsize 2048, nhid 2048, nlayers 16,
nhead 32, chunks 4, checkpoint=except_last) driven through the framework's
training hot path — the schedule-table executor (``ScheduledPipeline``,
schedule='1f1b': hand-scheduled forward+backward, exact per-micro-batch
checkpoint policy; at one device the tables specialize to straight-line code
at trace time) — full train step (forward + in-pipeline loss + backward +
grad-clip + Adam).

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.
``vs_baseline`` is pipelined throughput / plain single-chip throughput of
the identical computation: the plain step processes the same ``CHUNKS``
micro-batches by gradient accumulation (what a single-device user runs when
the full batch does not fit). Both honest accumulation programs are timed —
scan with uniform remat, and a Python-unrolled loop with the exact
per-micro-batch policy — and the FASTER one is the denominator, so the
ratio never flatters the pipeline; >= 1.0 means the machinery adds no
overhead on top of the best plain program (per-style timings in the
``baseline_sec_per_step`` key). ``vs_fullbatch`` (extra key) compares
against one full-batch step instead (granularity difference included). The
reference publishes no numbers (BASELINE.md), so baselines are measured,
not copied.

Note on the optimizer: the tutorial driver uses Adam at lr=5.0 (reference
``main.py:183``, reproduced faithfully as the Trainer default and divergent
at full scale — see ``--lr`` help); throughput is lr-independent, so this
benchmark uses adam(1e-4) purely so ``final_loss`` stays finite and the
convergence sanity check means something.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import optax

from pipe_tpu.core import microbatch as mb
from pipe_tpu.core.schedule import bubble_fraction
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
# The MFU arithmetic lives in obs.telemetry (shared with live-training
# StepReports); re-exported here for backward compatibility.
from pipe_tpu.obs.telemetry import (StepReport, device_memory_peaks,
                                    peak_flops_per_chip,
                                    train_flops_per_token)
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.scheduled import ScheduledPipeline
from pipe_tpu.parallel.spmd import stack_stage_params
from pipe_tpu.utils.rng import make_key

CHUNKS = int(os.environ.get("BENCH_CHUNKS", "4"))
BATCH = int(os.environ.get("BENCH_BATCH", "32"))
# `python main.py except_last` parity: at 520M params the no-remat config
# does not fit one 16G chip (the reference used 2 larger GPUs), so remat is
# the realistic headline mode; override with BENCH_CHECKPOINT=never etc.
CHECKPOINT = os.environ.get("BENCH_CHECKPOINT", "except_last")
# Selective remat for the RECOMPUTE micro-batches (a jax.checkpoint_policies
# member name, e.g. "dots_saveable"): saves matmul outputs at forward,
# recomputes only the elementwise remainder at backward — trades a little
# HBM for most of the recompute FLOPs while keeping the exact per-micro-
# batch mode semantics. "none" disables (full recompute, the reference's
# all-or-nothing behavior).
REMAT_POLICY = os.environ.get("BENCH_REMAT_POLICY", "dots_saveable")


def tutorial_config(platform: str) -> LMConfig:
    if platform == "tpu":
        return LMConfig(compute_dtype=jnp.bfloat16)  # full 520M-param config
    # CPU/dev fallback: same structure, small dims, so the script stays runnable.
    return LMConfig(vocab=1024, d_model=128, nhead=4, d_ff=256, n_layers=8,
                    seq_len=64)


def make_step(model, sched, tx):
    def train_step(params, opt_state, x, w, key):
        sp, prep, postp = params
        loss, grads = sched.loss_and_grad(sp, prep, postp, x, w, key=key)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return jax.jit(train_step, donate_argnums=(0, 1))


def make_plain_step(model, tx, microbatches: int = 1, style: str = "scan"):
    """The unpipelined ideal: same model, no pipeline machinery.

    ``microbatches > 1`` processes the batch as that many gradient-
    accumulation steps — the single-device equivalent of the pipeline's
    micro-batching, with identical matmul shapes. Two honest variants, both
    timed by main() with the FASTER one as the ``vs_baseline`` denominator:

    * ``style='scan'`` — what a single-device user actually writes:
      ``lax.scan`` over micro-batches with a uniform remat policy (a scan
      body cannot vary remat per iteration — the exact per-micro-batch
      except_last policy is precisely what the schedule-table executor adds
      over this program).
    * ``style='unrolled'`` — a Python-unrolled loop with the exact
      per-micro-batch policy (equal recompute to the pipelined step;
      measured slower than 'scan' on v5e at tutorial scale despite doing
      ~1/m less recompute — XLA schedules the rolled loop better).
    """

    def make_forward(remat: bool):
        def forward(params, tokens, targets, key):
            from pipe_tpu.core.partition import StageCtx
            sp, prep, postp = params
            ctx = StageCtx(key=key, train=True)
            h = model.pre_fn(prep, tokens, ctx)

            def block_fn(blocks, k, h):
                return model.stage_fn(blocks, h, StageCtx(key=k, train=True))

            body = jax.checkpoint(block_fn) if remat else block_fn
            for j, blocks in enumerate(sp):
                h = body(blocks, ctx.fold(j).key, h)
            per_row = model.loss_post_fn(postp, h, {"targets": targets},
                                         ctx.fold(99))
            return jnp.mean(per_row)

        return jax.value_and_grad(forward)

    grad_remat = make_forward(CHECKPOINT != "never")
    grad_exact_last = make_forward(False)

    def grad_for(i):
        if CHECKPOINT == "except_last" and i == microbatches - 1:
            return grad_exact_last
        return grad_remat

    def train_step(params, opt_state, tokens, targets, key):
        if microbatches == 1:
            loss, grads = grad_for(0)(params, tokens, targets, key)
        elif style == "scan":
            mb_tok = tokens.reshape(microbatches, -1, tokens.shape[-1])
            mb_tgt = targets.reshape(microbatches, -1, targets.shape[-1])

            def acc(carry, inp):
                g_sum, l_sum = carry
                t, tg, i = inp
                l, g = grad_remat(params, t, tg, jax.random.fold_in(key, i))
                return (jax.tree_util.tree_map(jnp.add, g_sum, g),
                        l_sum + l), None

            zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
            (grads, l_sum), _ = jax.lax.scan(
                acc, (zeros, 0.0),
                (mb_tok, mb_tgt, jnp.arange(microbatches)))
            grads = jax.tree_util.tree_map(
                lambda g: g / microbatches, grads)
            loss = l_sum / microbatches
        else:
            mb_tok = tokens.reshape(microbatches, -1, tokens.shape[-1])
            mb_tgt = targets.reshape(microbatches, -1, targets.shape[-1])
            grads = jax.tree_util.tree_map(jnp.zeros_like, params)
            loss = 0.0
            for i in range(microbatches):
                l, g = grad_for(i)(params, mb_tok[i], mb_tgt[i],
                                   jax.random.fold_in(key, i))
                grads = jax.tree_util.tree_map(jnp.add, grads, g)
                loss = loss + l
            grads = jax.tree_util.tree_map(
                lambda g: g / microbatches, grads)
            loss = loss / microbatches
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return jax.jit(train_step, donate_argnums=(0, 1))


def with_retries(fn, attempts: int = 3, wait_s: float = 20.0):
    """Run fn(), retrying transient remote-compile tunnel failures.

    The axon PJRT bridge intermittently drops fresh compile requests
    (INTERNAL: remote_compile: response body closed); a short pause and a
    retry succeeds (and usually hits the compile cache).
    """
    for attempt in range(attempts):
        try:
            return fn()
        except Exception as e:  # jax.errors.JaxRuntimeError and kin
            if attempt == attempts - 1 or "INTERNAL" not in str(e):
                raise
            print(f"transient backend error (attempt {attempt + 1}): "
                  f"{str(e)[:120]}", file=sys.stderr)
            time.sleep(wait_s)


def time_steps(step_fn, params, opt_state, args, warmup=2, iters=8):
    """Per-step wall time with a ONE-STEP-LAGGED host value fetch.

    Every step's loss is still read back to the host (real computed data —
    immune to async-dispatch/readiness quirks of remote-execution PJRT
    bridges), but step i's fetch happens while step i+1 executes, so the
    host<->device round-trip overlaps compute instead of serializing after
    it. Over the remote-TPU tunnel the synchronous fetch costs ~95 ms/step
    (~30% of a step) of pure RTT that never touches the chip; measured
    lagged == bulk ``block_until_ready`` timing to <1%.
    """
    for _ in range(warmup):
        params, opt_state, loss = step_fn(params, opt_state, *args)
    float(loss)
    prev = None
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss = step_fn(params, opt_state, *args)
        if prev is not None:
            float(prev)
        prev = loss
    last = float(prev)
    return (time.perf_counter() - t0) / iters, last


def trend_vs_prior_round(here, bubble_multistage):
    """Trend vs the prior committed round: load the newest BENCH_r*.json
    and put the head-to-head cpu8-probe deltas IN the output, so a
    regression has to be explained in the artifact rather than noticed by
    a diff-reader. Known history: the r4->r5 cpu8 probe slowdown (1f1b
    1.300 -> 1.630 s/step) happened on an unchanged executor path and
    reversed to 0.985 s/step in the round-7 quiet-host run
    (MULTISTAGE_r07.json) — measurement-host contention, not a code
    regression (FRONTDOOR_r07 records the same effect inflating
    co-resident compiled programs up to ~1.8x, which is why that probe now
    isolates each program in its own subprocess)."""
    import glob

    rounds = sorted(glob.glob(os.path.join(here, "BENCH_r*.json")))
    if not rounds:
        return None
    prior_path = rounds[-1]
    with open(prior_path) as f:
        prior = json.load(f)
    prior = prior.get("parsed", prior)
    prior_ms = ((prior.get("measured_bubble_multistage") or {})
                .get("schedules") or {})
    cur_ms = ((bubble_multistage or {}).get("schedules") or {})
    sched_trend = {}
    for name in sorted(set(prior_ms) & set(cur_ms)):
        p_sec = prior_ms[name].get("sec_per_step")
        c_sec = cur_ms[name].get("sec_per_step")
        if p_sec and c_sec:
            sched_trend[name] = {"prior_sec": p_sec, "sec": c_sec,
                                 "speedup": round(p_sec / c_sec, 4)}
    trend = {
        "prior": os.path.basename(prior_path)[:-len(".json")],
        "tokens_per_sec_prior": prior.get("value"),
        "cpu8_probe": sched_trend,
    }
    print(f"trend vs {trend['prior']} (cpu8 probe)", file=sys.stderr)
    print(f"  {'schedule':<14} {'prior':>9} {'now':>9} {'speedup':>8}",
          file=sys.stderr)
    for name, row in sched_trend.items():
        print(f"  {name:<14} {row['prior_sec']:>9.5f} "
              f"{row['sec']:>9.5f} {row['speedup']:>7.2f}x",
              file=sys.stderr)
    return trend


def main():
    # Hard-disable telemetry for every program this process times: the
    # null registry hands back shared no-op instruments, so not even
    # trace-time counter bumps ride the bench hot path, and the claim
    # "disabled telemetry is zero-cost here" is enforced rather than
    # assumed (tests/test_overlap_transport.py pins the lowered HLO of a
    # step as byte-identical under default vs null registry).
    from pipe_tpu.obs.telemetry import null_registry, set_registry
    set_registry(null_registry())

    platform = jax.default_backend()
    n_chips = jax.device_count()
    cfg = tutorial_config(platform)
    n_stages = 1  # bench chip count decides the pipeline depth
    for cand in (8, 4, 2, 1):
        if n_chips % cand == 0 and cand <= n_chips and cfg.n_layers % cand == 0:
            n_stages = cand
            break
    mesh = make_mesh(n_stages, 1, devices=jax.devices()[:n_stages])

    model = PipelinedLM(cfg, n_stages)
    stage_params, pre_params, post_params = model.init(jax.random.key(0))
    # plain_params is the never-donated master copy; every timed step gets
    # fresh buffers from it (steps donate their inputs, and a retry after a
    # transient tunnel failure must not see deleted buffers).
    plain_params = (stage_params, pre_params, post_params)

    def fresh(stacked: bool):
        # jnp.stack already allocates new buffers for the stage tree, so
        # only the (donated) pre/post trees need explicit copies there.
        copy = lambda t: jax.tree_util.tree_map(
            lambda a: jnp.array(a, copy=True), t)
        if stacked:
            return (stack_stage_params(plain_params[0]),
                    copy(plain_params[1]), copy(plain_params[2]))
        return copy(plain_params)

    def timed(step_fn, stacked, args):
        def run():
            p = fresh(stacked)
            return time_steps(step_fn, p, tx.init(p), args)
        return with_retries(run)

    n_params = model.num_params(plain_params)
    policy = None
    if REMAT_POLICY not in ("none", "") and CHECKPOINT != "never" \
            and n_stages == 1:
        policy = getattr(jax.checkpoint_policies, REMAT_POLICY)
    sched = ScheduledPipeline(mesh, model.stage_fn, pre_fn=model.pre_fn,
                              post_fn=model.loss_post_fn,
                              checkpoint=CHECKPOINT, schedule="1f1b",
                              remat_policy=policy)
    # Adam first-moment dtype: the composed bf16 probe measured ~4%
    # within one session, and the full bench with bf16-mu only measured
    # +2.4% over r3's committed f32-mu number (cross-session; see
    # MFU_SWEEP_r04.jsonl). Applied to the pipelined step AND the
    # single-device baselines alike, so vs_baseline stays like-for-like.
    # Override with BENCH_MU_DTYPE=float32.
    mu_dtype = jnp.dtype(os.environ.get("BENCH_MU_DTYPE", "bfloat16"))
    tx = optax.chain(optax.clip_by_global_norm(0.5),
                     optax.adam(1e-4, mu_dtype=mu_dtype))

    tokens = jax.random.randint(jax.random.key(1), (BATCH, cfg.seq_len),
                                0, cfg.vocab, jnp.int32)
    targets = jnp.roll(tokens, -1, axis=-1)
    x, n_rows = mb.stack_scatter({"tokens": tokens, "targets": targets},
                                 CHUNKS)
    w = mb.valid_row_mask(x, n_rows)
    # Backend-tuned key impl (rbg on TPU): threefry mask generation alone
    # cost 56 ms of a 216 ms step on v5e — see utils/rng.py.
    key = make_key(2)

    step = make_step(model, sched, tx)
    sec_per_step, loss = timed(step, True, (x, w, key))
    tokens_per_step = BATCH * cfg.seq_len
    pipe_tps_chip = tokens_per_step / sec_per_step / n_stages

    # Measured bubble. On a real device plane: trace-based — capture a short
    # profiler trace and report 1 - device_busy/span, the honest per-device
    # idle fraction (the reference author's TensorBoard-trace method,
    # README.md:559-567). The timing-slope alternative is biased high here:
    # per-step costs that do not scale with m (optimizer update, tunnel
    # dispatch) violate its affine premise. On platforms with no device
    # plane (virtual CPU) fall back to the downward slope probe (m/2 vs m —
    # downward because the d=1 unrolled program's temps grow with m).
    from pipe_tpu.obs.meters import (measured_bubble_two_point, profile_trace,
                                     stage_busy_from_trace)
    measured_bubble = None
    bubble_method = None
    try:
        import itertools
        import tempfile
        with tempfile.TemporaryDirectory() as td:
            # Each attempt traces into a FRESH subdir: a transient-error
            # retry would otherwise leave a partial trace session in the
            # dir, and the span (first event of session 1 .. last event of
            # session 2) would include the retry wait — reporting a bogus
            # near-1.0 bubble.
            attempt = itertools.count()

            def traced():
                sub = os.path.join(td, f"attempt{next(attempt)}")
                p = fresh(True)
                opt = tx.init(p)
                with profile_trace(sub):
                    loss_ = None
                    for _ in range(3):
                        p, opt, loss_ = step(p, opt, x, w, key)
                    float(loss_)
                return sub
            trace_dir = with_retries(traced)
            busy = stage_busy_from_trace(trace_dir)
            span = busy.pop("_span", 0.0)
            dev = [v for k, v in busy.items() if k.startswith("/device:")]
            if dev and span > 0:
                measured_bubble = max(0.0, 1.0 - sum(dev) / (span * len(dev)))
                bubble_method = "trace_busy"
    except Exception as e:
        print(f"trace-based bubble failed: {e}", file=sys.stderr)
    if measured_bubble is None and CHUNKS >= 2 and BATCH % CHUNKS == 0:
        try:
            mh = CHUNKS // 2
            tokens_h = tokens[:(BATCH // CHUNKS) * mh]
            targets_h = jnp.roll(tokens_h, -1, axis=-1)
            xh, n_rows_h = mb.stack_scatter({"tokens": tokens_h,
                                             "targets": targets_h}, mh)
            wh = mb.valid_row_mask(xh, n_rows_h)

            sec_h, _ = timed(step, True, (xh, wh, key))
            measured_bubble = measured_bubble_two_point(
                sec_per_step, CHUNKS, sec_h, mh)
            bubble_method = "timing_slope"
        except Exception as e:
            print(f"bubble slope timing failed: {e}", file=sys.stderr)

    # Multi-stage measured bubble: the one real chip cannot host a ppermute
    # ring, so probe a 4-stage pipeline on the virtual 8-CPU mesh — the
    # quick mode of the multistage probe, which also records serialized vs
    # packed-overlapped boundary transport side by side every round.
    here = os.path.dirname(os.path.abspath(__file__))
    bubble_multistage = None
    try:
        import subprocess
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=here)
        out = subprocess.run(
            [sys.executable, os.path.join(here, "tools",
                                          "multistage_probe.py"),
             "--quick", "4", "8"],
            capture_output=True, text=True, timeout=900, env=env)
        if out.returncode == 0:
            bubble_multistage = json.loads(out.stdout.strip().splitlines()[-1])
        else:
            print(f"multi-stage bubble probe rc={out.returncode}: "
                  f"{out.stderr[-2000:]}", file=sys.stderr)
    except Exception as e:
        print(f"multi-stage bubble probe failed: {e}", file=sys.stderr)

    # Zero-bubble split probe: 1f1b vs the structural B/W split rows
    # (hand-rolled TP triple + the auto-derived split) on the cpu8 mesh —
    # the per-round record behind the zb-h1 cost story
    # (ZB_SPLIT_PROBE_r{N}.json is the full-size committed artifact).
    zb_split_summary = None
    try:
        import subprocess
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=here)
        out = subprocess.run(
            [sys.executable, os.path.join(here, "tools",
                                          "zb_split_probe.py"), "--quick"],
            capture_output=True, text=True, timeout=900, env=env)
        if out.returncode == 0:
            zb_split_summary = json.loads(
                out.stdout.strip().splitlines()[-1])
        else:
            print(f"zb split probe rc={out.returncode}: "
                  f"{out.stderr[-2000:]}", file=sys.stderr)
    except Exception as e:
        print(f"zb split probe failed: {e}", file=sys.stderr)

    # Front-door adapter tax (Pipe(mesh=) vs raw executor), tracked every
    # round: the probe's last stdout line is its summary with the
    # tax_*_vs_raw ratios (cpu8 — the TPU chip is busy being the headline).
    front_door_tax = None
    try:
        import subprocess
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=here)
        out = subprocess.run(
            [sys.executable, os.path.join(here, "tools",
                                          "front_door_probe.py")],
            capture_output=True, text=True, timeout=900, env=env)
        if out.returncode == 0:
            summary = json.loads(out.stdout.strip().splitlines()[-1])
            front_door_tax = {
                "tax_uniform_vs_raw": summary["tax_uniform_vs_raw"],
                "tax_phase_vs_raw_phase":
                    summary.get("tax_phase_vs_raw_phase"),
                "tax_switch_vs_raw": summary["tax_switch_vs_raw"],
                "raw_sec_per_step":
                    summary["results"]["raw"]["sec_per_step"],
            }
        else:
            print(f"front-door probe rc={out.returncode}: "
                  f"{out.stderr[-2000:]}", file=sys.stderr)
    except Exception as e:
        print(f"front-door probe failed: {e}", file=sys.stderr)

    # Serving probe: the continuous-batching engine's steady-state
    # tokens/s vs the fixed-batch Generator at equal live-slot count,
    # plus TTFT p50/p99 under 0.7x-capacity Poisson load (cpu8, quick
    # mode of tools/serve_bench.py; SERVE_r{N}.json is the full record).
    serve_summary = None
    try:
        import subprocess
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=here)
        out = subprocess.run(
            [sys.executable, os.path.join(here, "tools",
                                          "serve_bench.py"), "--quick"],
            capture_output=True, text=True, timeout=900, env=env)
        if out.returncode == 0:
            serve_summary = json.loads(out.stdout.strip().splitlines()[-1])
        else:
            print(f"serve probe rc={out.returncode}: "
                  f"{out.stderr[-2000:]}", file=sys.stderr)
    except Exception as e:
        print(f"serve probe failed: {e}", file=sys.stderr)
    if serve_summary is not None:
        # Paged KV must not lose to the slab at equal live slots on the
        # shared-prefix workload (its 2x-slots-same-memory win is on
        # top of, not instead of, per-slot throughput).
        assert serve_summary["kv_paged_vs_slab_equal_slots"] >= 1.0, (
            "paged KV slower than slab at equal live slots: "
            f"{serve_summary['kv_paged_vs_slab_equal_slots']}x")
        # The radix tree's reason to exist: on the multi-tenant
        # workload (divergent full-block tails) it must reuse strictly
        # more blocks than the gen-1 whole-prefix counterfactual — and
        # the offload round trip must never change a token.
        assert (serve_summary["kv_radix_hit_block_fraction"]
                > serve_summary["kv_whole_prefix_hit_fraction"]), (
            "radix prefix reuse no better than a whole-prefix cache: "
            f"{serve_summary['kv_radix_hit_block_fraction']} vs "
            f"{serve_summary['kv_whole_prefix_hit_fraction']}")
        assert serve_summary["kv_offload_bitwise"], (
            "KV offload drill produced different tokens than the "
            "unpressured run")
        # The resident while_loop exists to remove per-chunk host
        # round-trips; it must not LOSE tokens/s at equal live slots.
        assert serve_summary["resident_vs_nonresident_tokens_s"] >= 1.0, (
            "resident serve loop slower than single-chunk ticks at "
            "equal live slots: "
            f"{serve_summary['resident_vs_nonresident_tokens_s']}x")
        # Gen-2 speculative lane: every draft source must stay bitwise
        # the Generator; the truncated-pipeline draft must clear the
        # n-gram baseline decisively on aperiodic prompts (the reason
        # model-based drafts exist); and whenever measured acceptance
        # clears the breakeven the planner computes from this host's
        # OWN measured chunk-cost ratio, spec must not lose tokens/s to
        # the non-spec resident loop at equal live slots.
        assert serve_summary["spec_bitwise"], (
            "a speculative draft source changed tokens vs the "
            "Generator")
        assert (serve_summary["spec_acceptance_truncated"] >= 0.3
                and serve_summary["spec_acceptance_truncated"]
                > serve_summary["spec_acceptance_ngram"]), (
            "truncated-pipeline draft acceptance "
            f"{serve_summary['spec_acceptance_truncated']} did not "
            "clear the n-gram baseline "
            f"{serve_summary['spec_acceptance_ngram']}")
        if (serve_summary["spec_acceptance_truncated"]
                > serve_summary["spec_breakeven_acceptance"]):
            assert serve_summary["spec_vs_nonspec_tokens_s"] >= 1.0, (
                "acceptance cleared the measured breakeven "
                f"({serve_summary['spec_acceptance_truncated']} > "
                f"{serve_summary['spec_breakeven_acceptance']}) but "
                "spec decode lost to the non-spec loop: "
                f"{serve_summary['spec_vs_nonspec_tokens_s']}x")
        assert serve_summary["spec_steady_new_traces"] == 0, (
            "the spec resident program retraced inside the measured "
            f"window ({serve_summary['spec_steady_new_traces']} new "
            "traces) — steady state must not recompile")

    # Chaos probe: one injected fault per layer (train NaN, transport
    # drop, serve backend raise, data raise) through the recovery
    # machinery — all_recovered must stay true every round (cpu8, quick
    # mode of tools/chaos_bench.py; CHAOS_r{N}.json is the full record).
    chaos_summary = None
    try:
        import subprocess
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=here)
        out = subprocess.run(
            [sys.executable, os.path.join(here, "tools",
                                          "chaos_bench.py"), "--quick"],
            capture_output=True, text=True, timeout=900, env=env)
        if out.returncode == 0:
            chaos_summary = json.loads(out.stdout.strip().splitlines()[-1])
        else:
            print(f"chaos probe rc={out.returncode}: "
                  f"{out.stderr[-2000:]}", file=sys.stderr)
    except Exception as e:
        print(f"chaos probe failed: {e}", file=sys.stderr)

    # Fleet probe: replica-count goodput scaling plus the
    # kill-one-of-3 failover proof over REAL child processes (SIGKILL
    # a replica process mid-stream: recovery + exactly-once ledger),
    # the async-tick straggler win, the session-remap KV handoff
    # TTFT, the disagg-vs-mixed SLO goodput drill plus the
    # phase-specialized SIGKILL drills (kill one prefill child, then
    # one decode child — exactly-once across the KV handoff), the
    # saturation sweep, and the observability plane over the SIGKILL
    # drill (delivered-token reconciliation + trace stitching) —
    # fleet_ok must stay true every round (quick mode of
    # tools/fleet_bench.py --fleet proc; FLEET_r{N}.json is the full
    # record).
    fleet_summary = None
    try:
        import subprocess
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=here)
        out = subprocess.run(
            [sys.executable, os.path.join(here, "tools",
                                          "fleet_bench.py"), "--quick",
             "--fleet", "proc",
             "--out", os.path.join(here, "FLEET_r20.json")],
            capture_output=True, text=True, timeout=900, env=env)
        if out.returncode == 0:
            fleet_summary = json.loads(out.stdout.strip().splitlines()[-1])
        else:
            print(f"fleet probe rc={out.returncode}: "
                  f"{out.stderr[-2000:]}", file=sys.stderr)
    except Exception as e:
        print(f"fleet probe failed: {e}", file=sys.stderr)
    if fleet_summary is not None:
        # Per-replica tick threads exist to confine a straggler's
        # stall to its own replica; at N=3 with one straggler the
        # async fleet must not LOSE steady-state goodput to the
        # serial tick loop.
        assert fleet_summary["async_beats_serial"], (
            "async-tick fleet goodput fell below the serial tick loop "
            f"at N=3: {fleet_summary['async_speedup']}x")
        # The stitched traces must reconstruct EVERY submitted id from
        # the SIGKILL drill exactly once — parent-side skeleton events
        # guarantee a timeline even when a child's events die with it,
        # and trace ids minted once at submit keep a failed-over id in
        # ONE trace (two placements, not two traces).
        assert fleet_summary["trace_stitch_frac"] == 1.0, (
            "trace stitching lost request ids in the proc kill drill: "
            f"frac={fleet_summary['trace_stitch_frac']}")
        assert fleet_summary["trace_stitch_exactly_once"], (
            "a request id appeared in more than one stitched trace")
        assert fleet_summary["tokens_reconciled"], (
            "per-replica delivered-token counters no longer sum to "
            "the parent ledger's delivered total")
        # Disaggregation's entry fee: shipping a cached prefix must
        # beat recomputing it, every round — otherwise the
        # prefill→decode handoff is pure overhead.
        assert fleet_summary["handoff_beats_reprefill"], (
            "KV handoff TTFT no longer beats re-prefill TTFT: "
            f"win={fleet_summary['ttft_win_s']}s")
        # And the split must pay at equal chips: the phase-specialized
        # pair's SLO goodput (decode cadence protected from prefill
        # burst interference) must not lose to 2 mixed replicas under
        # the prefill-heavy two-class workload.
        assert fleet_summary["disagg_beats_mixed"], (
            "disagg fleet lost SLO goodput to mixed at equal chips: "
            f"{fleet_summary['disagg_goodput_tokens_s']} < "
            f"{fleet_summary['mixed_goodput_tokens_s']} tokens/s")
        # Phase-specialized SIGKILL drills: killing a prefill child or
        # a decode child mid-handoff must still deliver every id
        # exactly once.
        assert fleet_summary["disagg_kill_prefill_exactly_once"], (
            "ids lost or duplicated after SIGKILL of a prefill replica")
        assert fleet_summary["disagg_kill_decode_exactly_once"], (
            "ids lost or duplicated after SIGKILL of a decode replica")
        # Round-20 wire hardening: a timed partition on one replica's
        # proc wire must heal losslessly (retained-frame replay, seq
        # dedup), and a corrupt-frame storm must be rejected whole at
        # the CRC — never half-parsed — with every id still answered
        # exactly once.
        assert fleet_summary["partition_heals_exactly_once"], (
            "ids lost or duplicated across a 2s wire partition")
        assert fleet_summary["corrupt_storm_ok"], (
            "wire corruption storm lost ids or never tripped the CRC: "
            f"rejects={fleet_summary['wire_crc_rejects']}")
        # Round-20 tentpole: SIGKILL the CONTROLLER mid-stream, rebuild
        # it from the fsync'd request journal, re-dial the orphaned
        # children in rejoin mode — exactly one terminal per id across
        # the two controller lives, mixed and disagg fleets both.
        assert fleet_summary["ctl_restart_exactly_once"], (
            "ids lost or duplicated across a controller SIGKILL+restart")
        assert fleet_summary["ctl_restart_disagg_exactly_once"], (
            "ids lost or duplicated across a disagg controller "
            "SIGKILL+restart")

    # Elastic probe: kill 1 of 4 stages mid-run -> heartbeat detection,
    # re-plan to 3, buddy restore, and the bitwise pin against the
    # from-snapshot reference — all_ok must stay true every round
    # (quick mode of tools/elastic_bench.py; ELASTIC_r{N}.json is the
    # full record).
    elastic_summary = None
    try:
        import subprocess
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=here)
        out = subprocess.run(
            [sys.executable, os.path.join(here, "tools",
                                          "elastic_bench.py"), "--quick"],
            capture_output=True, text=True, timeout=900, env=env)
        if out.returncode == 0:
            elastic_summary = json.loads(out.stdout.strip().splitlines()[-1])
        else:
            print(f"elastic probe rc={out.returncode}: "
                  f"{out.stderr[-2000:]}", file=sys.stderr)
    except Exception as e:
        print(f"elastic probe failed: {e}", file=sys.stderr)

    # Planner probe: calibrate -> search -> measure on the cpu8 probe
    # (quick mode of tools/plan_bench.py). plan_ok asserts the chosen
    # plan is no slower than the hand-tuned 1f1b m=8 baseline within
    # noise, and that every emitted plan's op table re-proved itself
    # (PLAN_r{N}.json is the full committed record).
    plan_summary = None
    try:
        import subprocess
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=here)
        out = subprocess.run(
            [sys.executable, os.path.join(here, "tools",
                                          "plan_bench.py"), "--quick"],
            capture_output=True, text=True, timeout=900, env=env)
        if out.returncode == 0:
            full = json.loads(out.stdout.strip().splitlines()[-1])
            plan_summary = {
                "plan_ok": full["plan_ok"],
                "all_plans_verified": full["all_plans_verified"],
                "top": {k: full["plan"][k] for k in
                        ("schedule", "m", "v", "split_stage")},
                "top_rel_err": full["top_measured"][0]["rel_err"],
                "top_vs_baseline_per_row":
                    full["top_vs_baseline_per_row"],
                "calibration_rel_residual":
                    full["calibration"]["rel_residual"],
            }
        else:
            print(f"plan probe rc={out.returncode}: "
                  f"{out.stderr[-2000:]}", file=sys.stderr)
    except Exception as e:
        print(f"plan probe failed: {e}", file=sys.stderr)

    # Chaos smoke lane: the pytest-marked elastic drill (kill stage 1/4,
    # resumed loss trajectory vs the unkilled run) plus one wire-chaos
    # drill (corrupt frame rejected whole at the framing layer) as the
    # repo's own test suite runs them — the bench proves the committed
    # tests pass, not just the bench-local drills.
    chaos_smoke = None
    try:
        import subprocess
        smoke_tests = [
            os.path.join("tests", "test_elastic.py")
            + "::test_elastic_drill_loss_trajectory",
            os.path.join("tests", "test_fleet_journal.py")
            + "::test_wire_corrupt_frame_is_rejected_whole_never_half_parsed",
        ]
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=here)
        t0 = time.time()
        out = subprocess.run(
            [sys.executable, "-m", "pytest", "-m", "chaos", "-q",
             "-p", "no:cacheprovider"] + smoke_tests,
            capture_output=True, text=True, timeout=900, env=env,
            cwd=here)
        chaos_smoke = {"ok": out.returncode == 0, "tests": smoke_tests,
                       "wall_s": round(time.time() - t0, 1)}
        if out.returncode != 0:
            print(f"chaos smoke rc={out.returncode}: "
                  f"{out.stdout[-2000:]}", file=sys.stderr)
    except Exception as e:
        print(f"chaos smoke failed: {e}", file=sys.stderr)

    trend_vs_prior = None
    try:
        trend_vs_prior = trend_vs_prior_round(here, bubble_multistage)
    except Exception as e:
        print(f"trend table failed: {e}", file=sys.stderr)

    # vs_baseline denominator = the FASTER of the two honest accumulation
    # programs (see make_plain_step), so the ratio never flatters the
    # pipeline by comparing against a strawman.
    vs_baseline = vs_fullbatch = 0.0
    baseline_styles = {}
    # at CHUNKS == 1 both styles collapse to the same single-step program
    for style in (("scan",) if CHUNKS == 1 else ("scan", "unrolled")):
        try:
            plain_acc = make_plain_step(model, tx, microbatches=CHUNKS,
                                        style=style)
            acc_sec, _ = timed(plain_acc, False, (tokens, targets, key))
            baseline_styles[style] = round(acc_sec, 5)
        except Exception as e:  # baseline OOM etc.
            print(f"plain baseline ({style}) failed: {e}", file=sys.stderr)
    if baseline_styles:
        best_sec = min(baseline_styles.values())
        vs_baseline = pipe_tps_chip / (tokens_per_step / best_sec)
    try:
        if CHUNKS > 1:
            plain = make_plain_step(model, tx)
            plain_sec, _ = timed(plain, False, (tokens, targets, key))
            vs_fullbatch = pipe_tps_chip / (tokens_per_step / plain_sec)
        else:
            vs_fullbatch = vs_baseline
    except Exception as e:  # full batch can OOM where micro-batching fits
        print(f"full-batch baseline failed: {e}", file=sys.stderr)

    # dots_saveable saves EVERY matmul output, so its recompute re-runs only
    # elementwise ops — zero extra MACs, hardware FLOPs = required. Other
    # policies re-run some matmuls; without a per-policy MAC model, keep the
    # mode's full-recompute count as the honest upper bound.
    hw_mode = ("never" if policy is not None
               and REMAT_POLICY == "dots_saveable" else CHECKPOINT)
    req_tok, hw_tok = train_flops_per_token(cfg, hw_mode, CHUNKS)
    model_flops = req_tok * tokens_per_step
    peak = peak_flops_per_chip()
    mfu = (req_tok * pipe_tps_chip) / peak
    hfu = (hw_tok * pipe_tps_chip) / peak

    # The same numbers as a StepReport, so BENCH_*.json rounds carry the
    # bubble/MFU/memory fields in the exact schema live training emits.
    report = StepReport.compute(
        step=0, wall_sec=sec_per_step, tokens=tokens_per_step,
        n_stages=n_stages, chunks=CHUNKS, checkpoint=hw_mode,
        schedule="1f1b", loss=loss, model_cfg=cfg,
        analytic_bubble=bubble_fraction(CHUNKS, n_stages),
        measured_bubble=measured_bubble,
        measured_bubble_method=bubble_method,
        memory=device_memory_peaks(), platform=platform,
        device_kind=jax.devices()[0].device_kind)

    print(json.dumps({
        "metric": "train_tokens_per_sec_per_chip",
        "value": round(pipe_tps_chip, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(vs_baseline, 4),
        "vs_fullbatch": round(vs_fullbatch, 4),
        "baseline_sec_per_step": baseline_styles,
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "n_stages": n_stages,
        "chunks": CHUNKS,
        "checkpoint": CHECKPOINT,
        "remat_policy": REMAT_POLICY if policy is not None else "none",
        "mu_dtype": str(mu_dtype),
        "params": n_params,
        "model_flops": model_flops,
        "mfu": round(mfu, 4),
        "hfu": round(hfu, 4),
        "analytic_bubble": round(bubble_fraction(CHUNKS, n_stages), 4),
        "measured_bubble": (round(measured_bubble, 4)
                            if measured_bubble is not None else None),
        "measured_bubble_method": bubble_method,
        "measured_bubble_multistage": bubble_multistage,
        "front_door_tax": front_door_tax,
        "zb_split": zb_split_summary,
        "serve": serve_summary,
        "chaos": chaos_summary,
        "fleet": fleet_summary,
        "elastic": elastic_summary,
        "plan": plan_summary,
        "chaos_smoke": chaos_smoke,
        "trend_vs_prior": trend_vs_prior,
        "final_loss": round(loss, 4),
        "step_report": report.to_json(),
        "config": dataclasses.asdict(
            dataclasses.replace(cfg, compute_dtype=str(cfg.compute_dtype))),
    }))


if __name__ == "__main__":
    main()
