// pipetpu_prefetch: native double-buffered batch assembly for the LM
// training loop.
//
// The reference stack's input path rides torch's native DataLoader workers
// (background threads assembling batches while the device computes); the
// tutorial driver itself assembles batches inline on the hot loop
// (reference main.py:102-113: get_batch slices + transposes per step).
// This library is the pipe_tpu equivalent: a producer thread walks the
// batchified id matrix and writes batch-first (data, target) pairs —
// get_batch's slice + transpose, fused into one pass — into a Python-owned
// ring of pre-allocated slots, so host batch assembly overlaps device
// compute and the hot loop only hands ready buffers to jax.device_put.
//
// Contract (enforced by the ctypes wrapper in pipe_tpu/data/native.py):
//   - source is row-major [nrows, bsz] int32; batch b covers rows
//     [b*bptt, (b+1)*bptt) with target rows shifted by one; only FULL
//     batches are produced ((nrows-1)/bptt of them) — the trainer's
//     tail-batch break, precomputed.
//   - slots live in caller-owned slabs [depth, bsz, bptt]; a slot returned
//     by ptpf_next stays valid until ptpf_release(slot); after release the
//     producer may overwrite it (classic double-buffer discipline).
//   - ptpf_next returns slots strictly in batch order; -1 when exhausted.
//
// Build: g++ -O3 -std=c++17 -shared -fPIC -pthread pipetpu_prefetch.cpp
//        -o libpipetpu_prefetch.so

#include <condition_variable>
#include <cstdint>
#include <mutex>
#include <thread>

namespace {

struct Prefetcher {
  const int32_t* src = nullptr;  // [nrows, bsz] row-major (caller-owned)
  int64_t nrows = 0, bsz = 0, bptt = 0;
  int64_t nb = 0;     // number of full batches
  int64_t depth = 0;  // ring slots
  int32_t* data_slab = nullptr;  // [depth, bsz, bptt] (caller-owned)
  int32_t* tgt_slab = nullptr;   // [depth, bsz, bptt] (caller-owned)

  std::mutex mu;
  std::condition_variable cv_producer, cv_consumer;
  int64_t produced = 0;  // batches written and published
  int64_t consumed = 0;  // batches handed to the consumer
  int64_t released = 0;  // batches the consumer has finished with
  bool stop = false;
  std::thread worker;

  void fill(int64_t b) {
    const int64_t slot = b % depth;
    int32_t* d = data_slab + slot * bsz * bptt;
    int32_t* t = tgt_slab + slot * bsz * bptt;
    const int32_t* base = src + b * bptt * bsz;
    // data[r, i] = source[b*bptt + i, r]; target shifts the row by one.
    for (int64_t i = 0; i < bptt; ++i) {
      const int32_t* row = base + i * bsz;
      const int32_t* row_next = row + bsz;
      for (int64_t r = 0; r < bsz; ++r) {
        d[r * bptt + i] = row[r];
        t[r * bptt + i] = row_next[r];
      }
    }
  }

  void run() {
    for (int64_t b = 0; b < nb; ++b) {
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_producer.wait(
            lock, [&] { return stop || produced - released < depth; });
        if (stop) return;
      }
      fill(b);  // slot is exclusively the producer's until published
      {
        std::lock_guard<std::mutex> lock(mu);
        ++produced;
      }
      cv_consumer.notify_one();
    }
  }
};

}  // namespace

extern "C" {

Prefetcher* ptpf_create(const int32_t* source, int64_t nrows, int64_t bsz,
                        int64_t bptt, int64_t depth, int32_t* data_slab,
                        int32_t* tgt_slab) {
  if (!source || !data_slab || !tgt_slab || nrows < 0 || bsz <= 0 ||
      bptt <= 0 || depth <= 0) {
    return nullptr;
  }
  try {
    auto* pf = new Prefetcher();
    pf->src = source;
    pf->nrows = nrows;
    pf->bsz = bsz;
    pf->bptt = bptt;
    pf->nb = nrows > 0 ? (nrows - 1) / bptt : 0;
    pf->depth = depth;
    pf->data_slab = data_slab;
    pf->tgt_slab = tgt_slab;
    pf->worker = std::thread([pf] { pf->run(); });
    return pf;
  } catch (...) {
    return nullptr;  // never let a C++ exception cross the C ABI
  }
}

int64_t ptpf_num_batches(const Prefetcher* pf) { return pf->nb; }

// Blocks until the next batch (in order) is ready; returns its slot index,
// or -1 when all nb batches have been consumed.
int64_t ptpf_next(Prefetcher* pf) {
  std::unique_lock<std::mutex> lock(pf->mu);
  if (pf->consumed >= pf->nb) return -1;
  pf->cv_consumer.wait(
      lock, [&] { return pf->stop || pf->produced > pf->consumed; });
  if (pf->stop) return -1;
  const int64_t slot = pf->consumed % pf->depth;
  ++pf->consumed;
  return slot;
}

// Marks the oldest outstanding slot reusable. Slots are released in the
// order they were consumed (the wrapper enforces this).
void ptpf_release(Prefetcher* pf) {
  {
    std::lock_guard<std::mutex> lock(pf->mu);
    if (pf->released < pf->consumed) ++pf->released;
  }
  pf->cv_producer.notify_one();
}

void ptpf_free(Prefetcher* pf) {
  if (!pf) return;
  {
    std::lock_guard<std::mutex> lock(pf->mu);
    pf->stop = true;
  }
  pf->cv_producer.notify_all();
  pf->cv_consumer.notify_all();
  if (pf->worker.joinable()) pf->worker.join();
  delete pf;
}

}  // extern "C"
