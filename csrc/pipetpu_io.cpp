// pipetpu_io: native corpus processing for the LM data pipeline.
//
// The reference framework's data loading rides torchtext's C++ kernels; this
// library is the pipe_tpu equivalent for the host-side input path: one-pass
// basic_english tokenization + first-appearance vocabulary + id stream over
// a text corpus, exposed through a C ABI consumed via ctypes
// (pipe_tpu/data/native.py). Semantics mirror pipe_tpu.data.lm_text exactly
// (ASCII lowercase; '";:' dropped; ".,!?()'" isolated; whitespace split;
// empty lines dropped; <unk>=0 then first-appearance order), which the
// parity tests in tests/test_native_io.py assert token-for-token.
//
// Build: g++ -O3 -std=c++17 -shared -fPIC pipetpu_io.cpp -o libpipetpu_io.so

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

struct Corpus {
  std::vector<int32_t> ids;
  std::vector<std::string> vocab;  // vocab[0] == "<unk>"
  std::unordered_map<std::string, int32_t> index;
};

inline bool is_drop(char c) { return c == '"' || c == ';' || c == ':'; }
inline bool is_isolate(char c) {
  return c == '.' || c == ',' || c == '!' || c == '?' || c == '(' ||
         c == ')' || c == '\'';
}

void process_line(Corpus& corpus, std::string_view line,
                  std::string& scratch) {
  scratch.clear();
  scratch.reserve(line.size() + 16);
  for (char c : line) {
    if (is_drop(c)) {
      scratch.push_back(' ');
    } else if (is_isolate(c)) {
      scratch.push_back(' ');
      scratch.push_back(c);
      scratch.push_back(' ');
    } else if (c >= 'A' && c <= 'Z') {
      scratch.push_back(static_cast<char>(c - 'A' + 'a'));
    } else if (c == '\t' || c == '\r' || c == '\n' || c == '\f' ||
               c == '\v') {
      scratch.push_back(' ');
    } else {
      scratch.push_back(c);
    }
  }
  size_t i = 0, n = scratch.size();
  while (i < n) {
    while (i < n && scratch[i] == ' ') ++i;
    size_t start = i;
    while (i < n && scratch[i] != ' ') ++i;
    if (i > start) {
      std::string tok = scratch.substr(start, i - start);
      auto it = corpus.index.find(tok);
      int32_t id;
      if (it == corpus.index.end()) {
        id = static_cast<int32_t>(corpus.vocab.size());
        corpus.index.emplace(tok, id);
        corpus.vocab.push_back(std::move(tok));
      } else {
        id = it->second;
      }
      corpus.ids.push_back(id);
    }
  }
}

Corpus* build(const char* data, size_t len) {
  auto* corpus = new Corpus();
  corpus->vocab.emplace_back("<unk>");
  corpus->index.emplace("<unk>", 0);
  std::string scratch;
  size_t pos = 0;
  while (pos < len) {
    const char* nl =
        static_cast<const char*>(memchr(data + pos, '\n', len - pos));
    size_t end = nl ? static_cast<size_t>(nl - data) : len;
    process_line(*corpus, std::string_view(data + pos, end - pos), scratch);
    pos = end + 1;
  }
  return corpus;
}

}  // namespace

extern "C" {

Corpus* ptio_from_bytes(const char* data, int64_t len) {
  if (len < 0) return nullptr;
  try {
    return build(data, static_cast<size_t>(len));
  } catch (...) {
    return nullptr;  // never let a C++ exception cross the C ABI
  }
}

Corpus* ptio_from_file(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  if (fseek(f, 0, SEEK_END) != 0) { fclose(f); return nullptr; }
  long size = ftell(f);
  if (size < 0 || fseek(f, 0, SEEK_SET) != 0) { fclose(f); return nullptr; }
  try {
    std::vector<char> buf(static_cast<size_t>(size));
    size_t got = fread(buf.data(), 1, buf.size(), f);
    fclose(f);
    return build(buf.data(), got);
  } catch (...) {
    fclose(f);
    return nullptr;
  }
}

int64_t ptio_num_tokens(const Corpus* c) {
  return static_cast<int64_t>(c->ids.size());
}

int32_t ptio_vocab_size(const Corpus* c) {
  return static_cast<int32_t>(c->vocab.size());
}

void ptio_copy_ids(const Corpus* c, int32_t* out) {
  memcpy(out, c->ids.data(), c->ids.size() * sizeof(int32_t));
}

const char* ptio_token(const Corpus* c, int32_t id) {
  if (id < 0 || id >= static_cast<int32_t>(c->vocab.size())) return nullptr;
  return c->vocab[static_cast<size_t>(id)].c_str();
}

int32_t ptio_lookup(const Corpus* c, const char* token) {
  auto it = c->index.find(token);
  return it == c->index.end() ? 0 : it->second;
}

void ptio_free(Corpus* c) { delete c; }

}  // extern "C"
