"""Per-stage busy/idle timeline from a ``profile_trace`` capture.

``obs.meters.profile_trace`` (the Trainer's ``profile_every`` hook, or any
manual ``with profile_trace(logdir):`` block) leaves ``*.xplane.pb`` files
behind; ``obs.meters.stage_timeline_from_trace`` buckets their events by
the ``chunk{i}-stage{j}`` named scopes the executors emit. This tool turns
that into the measured counterpart of ``tools/schedule_viz.py``: one ASCII
row per stage, busy buckets filled and idle visibly empty, with per-stage
busy seconds and the measured bubble (idle fraction over the trace span) —
rendered next to the analytic schedule table so the two can be eyeballed
for agreement.

Honest-fallback contract: device planes (``/device:*``) are preferred;
host planes with scope tags are labeled as such; a capture with no tagged
events at all (e.g. the virtual-CPU platform, whose python tracer records
host frames only) degrades to the analytic picture plus an explanation,
exit code 0 — a missing device plane is an expected environment, not an
error.

Usage:
    python tools/timeline_report.py LOGDIR [--schedule 1f1b] [-m M] [-n N]
        [--width 72] [--json out.json]

``-m``/``-n`` default to what the trace itself shows (max chunk/stage
tag + 1); pass them explicitly when the capture is partial.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from pipe_tpu.obs.meters import stage_timeline_from_trace

import schedule_viz


def _bucket_row(intervals: List[Tuple[float, float]], lo: float, hi: float,
                width: int) -> str:
    """Discretize merged busy intervals into ``width`` buckets over
    [lo, hi): '#' mostly busy (>=50%), '+' partially, '.' idle."""
    if hi <= lo:
        return "." * width
    step = (hi - lo) / width
    busy = [0.0] * width
    for s, e in intervals:
        b0 = max(0, int((s - lo) / step))
        b1 = min(width - 1, int((e - lo) / step))
        for b in range(b0, b1 + 1):
            cell_lo, cell_hi = lo + b * step, lo + (b + 1) * step
            busy[b] += max(0.0, min(e, cell_hi) - max(s, cell_lo))
    return "".join("#" if f >= 0.5 * step else "+" if f > 0 else "."
                   for f in busy)


def summarize(timeline: Dict[str, object], schedule: str,
              m: int, n: int) -> Dict[str, object]:
    """Machine-readable report: measured per-stage busy plus the analytic
    bubble for the same (schedule, m, n) geometry."""
    lo, hi = timeline["span"]
    span_sec = max(hi - lo, 0.0) / 1e9
    stages = timeline["stages"]
    measured = None
    if stages and span_sec > 0:
        busy = sum(s["busy_sec"] for s in stages.values())
        measured = 1.0 - busy / (span_sec * len(stages))
    analytic = schedule_viz.make_schedule(schedule).bubble(m, n)
    return {
        "source": timeline["source"],
        "span_sec": span_sec,
        "schedule": schedule, "chunks": m, "n_stages": n,
        "analytic_bubble": analytic,
        "measured_bubble": measured,
        "stages": {int(j): {"busy_sec": s["busy_sec"],
                            "chunks": {int(i): v
                                       for i, v in s["chunks"].items()}}
                   for j, s in stages.items()},
    }


def render(timeline: Dict[str, object], summary: Dict[str, object],
           width: int) -> str:
    lines = []
    src = timeline["source"]
    if src is None:
        lines.append("no chunk{i}-stage{j} tagged events in this capture.")
        lines.append("(Expected on CPU: jaxlib's python tracer records host")
        lines.append(" frames only — capture on a real accelerator to get")
        lines.append(" /device:* planes with XLA op names.)")
        return "\n".join(lines)
    lo, hi = timeline["span"]
    span_sec = summary["span_sec"]
    hdr = (f"measured timeline  source={src}  span={span_sec * 1e3:.2f}ms")
    if summary["measured_bubble"] is not None:
        hdr += (f"  measured_bubble={summary['measured_bubble']:.1%}"
                f"  analytic={summary['analytic_bubble']:.1%}")
    if src == "host":
        hdr += "  [host plane: wall-clock upper bound, not device busy]"
    lines.append(hdr)
    for j, s in sorted(timeline["stages"].items()):
        row = _bucket_row(s["intervals"], lo, hi, width)
        frac = s["busy_sec"] / span_sec if span_sec > 0 else 0.0
        lines.append(f"stage {j}|".rjust(9) + row
                     + f"| busy {s['busy_sec'] * 1e3:8.2f}ms ({frac:5.1%})")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("logdir", help="profile_trace output directory")
    p.add_argument("--schedule", default="1f1b",
                   choices=["gpipe", "1f1b", "zb-h1", "interleaved-1f1b"])
    p.add_argument("-m", type=int, default=None,
                   help="micro-batches (default: inferred from the trace)")
    p.add_argument("-n", type=int, default=None,
                   help="stages (default: inferred from the trace)")
    p.add_argument("--width", type=int, default=72,
                   help="timeline buckets per row")
    p.add_argument("--json", default=None,
                   help="also write the machine-readable summary here")
    args = p.parse_args(argv)

    timeline = stage_timeline_from_trace(args.logdir)
    stages = timeline["stages"]
    n = args.n or (max(stages) + 1 if stages else 1)
    m = args.m or (max((max(s["chunks"], default=0)
                        for s in stages.values()), default=0) + 1
                   if stages else 1)
    summary = summarize(timeline, args.schedule, m, n)

    print(render(timeline, summary, args.width))
    print()
    print("analytic schedule for the same geometry:")
    print(schedule_viz.ascii_timeline(args.schedule, m, n))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(summary, f, indent=2)
        print(f"\nwrote {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
