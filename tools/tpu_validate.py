"""On-chip validation of the Pallas flash-attention kernel — artifact writer.

``python tools/tpu_validate.py`` (on the real TPU) runs the checks CI cannot
(interpret mode has no PRNG, so in-kernel dropout is TPU-only — see
``ops/pallas_attention.py``) and writes ``TPU_VALIDATION.json`` at the repo
root so the validation leaves a reviewable artifact (VERDICT r1 weak #6):

1. forward parity vs the XLA reference attention (causal x non-causal);
2. gradient parity vs the XLA reference (no dropout);
3. in-kernel dropout determinism: same key -> bit-identical output and
   grads; different key -> different output;
4. in-kernel dropout unbiasedness: the mean over many keys of the dropped
   output approaches the undropped output (inverted-dropout scaling);
5. dropout backward self-consistency: the VJP regenerates the forward's
   masks bit-identically (grad of sum through same-key forwards agrees).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def xla_attention(q, k, v, causal, precision=jax.lax.Precision.HIGHEST):
    """Reference attention. On TPU a default-precision f32 einsum already
    runs as ONE bf16-input MXU pass (f32 accumulate), so the true-f32
    reference must force ``Precision.HIGHEST`` (bf16x3 passes); calling with
    ``Precision.DEFAULT`` instead yields exactly the single-pass hardware
    semantics — that is the accuracy yardstick the kernel is held to."""
    s = q.shape[1]
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, precision=precision,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        mask = np.tril(np.ones((s, s), bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w.astype(q.dtype), v,
                      precision=precision,
                      preferred_element_type=jnp.float32)


def max_err(a, b):
    return float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                 - b.astype(jnp.float32))))


def main() -> int:
    from pipe_tpu.ops.pallas_attention import flash_attention

    backend = jax.default_backend()
    results = {"platform": backend,
               "device_kind": jax.devices()[0].device_kind,
               "jax": jax.__version__, "checks": {}}
    ok = True

    b, s, h, d = 2, 256, 4, 64
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (b, s, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, s, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, s, h, d), jnp.float32)

    # 1) forward parity. Yardstick: the error the MXU's own single-pass
    # bf16-input semantics (Precision.DEFAULT) makes against the forced-f32
    # reference (Precision.HIGHEST, bf16x3). The kernel's matmuls use the
    # same single-pass hardware mode, so it must land within 1.5x of that.
    for causal in (True, False):
        err = max_err(jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=causal))(q, k, v),
            xla_attention(q, k, v, causal))
        hw_err = max_err(
            xla_attention(q, k, v, causal, jax.lax.Precision.DEFAULT),
            xla_attention(q, k, v, causal))
        tol = max(2e-3, 1.5 * hw_err)
        results["checks"][f"fwd_parity_causal={causal}"] = {
            "max_abs_err": err, "hardware_mode_err": hw_err,
            "tol": tol, "pass": err < tol}
        ok &= err < tol

    # 2) gradient parity
    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def loss_xla(q, k, v):
        return jnp.sum(xla_attention(q, k, v, True) ** 2)

    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    gx = jax.jit(jax.grad(loss_xla, argnums=(0, 1, 2)))(q, k, v)
    # per-tensor relative error (dq/dk/dv scales differ; normalizing the
    # joint max by one tensor's scale would give spurious verdicts)
    rels = [max_err(a, b) / max(float(jnp.max(jnp.abs(b))), 1e-9)
            for a, b in zip(gf, gx)]
    err = max(max_err(a, b) for a, b in zip(gf, gx))
    rel = max(rels)
    results["checks"]["grad_parity"] = {
        "max_abs_err": err, "rel_per_tensor": [round(r, 6) for r in rels],
        "rel": rel, "pass": rel < 2e-2}
    ok &= rel < 2e-2

    if backend != "tpu":
        results["checks"]["dropout"] = {
            "pass": None, "note": "skipped: in-kernel dropout is TPU-only"}
        results["pass"] = bool(ok)
        _write(results)
        return 0 if ok else 1

    # 3) dropout determinism
    rate = 0.3
    key = jax.random.key(7)
    f = jax.jit(lambda q, k, v, key: flash_attention(
        q, k, v, causal=True, dropout_rate=rate, dropout_key=key))
    o1, o2 = f(q, k, v, key), f(q, k, v, key)
    same = bool(jnp.array_equal(o1, o2))
    o3 = f(q, k, v, jax.random.key(8))
    diff = not bool(jnp.array_equal(o1, o3))
    results["checks"]["dropout_deterministic_same_key"] = {"pass": same}
    results["checks"]["dropout_differs_across_keys"] = {"pass": diff}
    ok &= same and diff

    # 4) dropout unbiasedness: E_key[dropped] ~ undropped
    K = 64
    acc = jnp.zeros_like(o1)
    for i in range(K):
        acc = acc + f(q, k, v, jax.random.key(100 + i))
    mean_out = acc / K
    base = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))(
        q, k, v)
    bias = max_err(mean_out, base) / max(float(jnp.max(jnp.abs(base))), 1e-9)
    # sampling noise at K=64, rate .3 over s=256 keys ~ few percent
    results["checks"]["dropout_unbiased"] = {
        "rel_bias_at_K64": bias, "pass": bias < 0.15}
    ok &= bias < 0.15

    # 5) dropout backward determinism (mask regeneration in bwd kernels)
    gdrop = jax.jit(jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=True, dropout_rate=rate,
            dropout_key=key) ** 2), argnums=(0, 1, 2)))
    g1 = gdrop(q, k, v)
    g2 = gdrop(q, k, v)
    gsame = all(bool(jnp.array_equal(a, b)) for a, b in zip(g1, g2))
    finite = all(bool(jnp.isfinite(a).all()) for a in g1)
    results["checks"]["dropout_grad_deterministic_and_finite"] = {
        "pass": gsame and finite}
    ok &= gsame and finite

    results["pass"] = bool(ok)
    _write(results)
    print(json.dumps(results, indent=2))
    return 0 if ok else 1


def _write(results):
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "TPU_VALIDATION.json")
    with open(out, "w") as f:
        json.dump(results, f, indent=2)
        f.write("\n")


if __name__ == "__main__":
    sys.exit(main())
