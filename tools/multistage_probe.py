"""Real-TPU multi-stage probe: interleaved v>1 table programs at d=1.

The headline bench (`bench.py`) runs n_stages=1 on the one available chip;
multi-stage wall-clock has otherwise only existed as cpu8 proxies. But
interleaved placements (v virtual stages per device) put a REAL multi-stage
table program on the single chip: the 16-layer tutorial model factors into
v virtual stage bodies, the `interleaved-1f1b` op tables sequence
FWD/BWD per (micro-batch, virtual stage) pairs, and the executor runs its
full stash/residual/cotangent machinery — the same math as the single-stage
program, so the measured delta IS the table machinery + stash traffic
(no ICI, granted: at d=1 the ring hop is a self-permute).

``python tools/multistage_probe.py --quick [n_stages chunks]`` instead runs
the cpu8 bubble probe with the schedule + transport (serialized vs packed
overlapped ppermute) comparison — no TPU needed; this is the subprocess
bench.py embeds as ``measured_bubble_multistage``.

``python tools/multistage_probe.py [v ...]`` (default: 1 2 4) — one JSON
line per variant:

* ``v=1``   — the headline 1f1b single-stage program (same-process anchor).
* ``v>=2``  — `InterleavedOneFOneBSchedule(interleave=v)` at d=1, both the
  dynamic per-cycle `lax.switch` scan and (where it fits) the trace-time
  static unroll, quantifying the switch tax on-chip at tutorial scale.

All variants: 520M tutorial config, chunks=4, checkpoint=except_last,
remat_policy=dots_saveable, bf16-mu Adam — the bench defaults — so numbers
land next to `BENCH_r{N}.json`'s headline row. Committed artifact:
`MULTISTAGE_TPU_r05.jsonl`.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if "--quick" in sys.argv:
    # --quick cpu8 mode (the bench.py multistage hook): no TPU required.
    # The platform MUST be forced before the jax import below binds a
    # backend — this is why the block sits mid-imports.
    from pipe_tpu.utils.platform import force_cpu_platform
    force_cpu_platform(8)

import jax
import jax.numpy as jnp
import optax

from bench import (BATCH, CHUNKS, make_step, peak_flops_per_chip,
                   time_steps, train_flops_per_token, tutorial_config,
                   with_retries)
from pipe_tpu.core import microbatch as mb
from pipe_tpu.core.schedule import InterleavedOneFOneBSchedule
from pipe_tpu.models.transformer_lm import PipelinedLM
from pipe_tpu.parallel.interleaved import stack_interleaved_params
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.scheduled import ScheduledPipeline
from pipe_tpu.utils.rng import make_key


def probe_variant(cfg, v: int, static_unroll, tx, tokens, targets):
    """Time one (v, static_unroll) variant; returns the result dict."""
    model = PipelinedLM(cfg, v)          # v virtual stage bodies at d=1
    params = model.init(jax.random.key(0))
    sp, prep, postp = params
    mesh = make_mesh(1, 1, devices=jax.devices()[:1])
    schedule = ("1f1b" if v == 1
                else InterleavedOneFOneBSchedule(interleave=v))
    sched = ScheduledPipeline(
        mesh, model.stage_fn, pre_fn=model.pre_fn,
        post_fn=model.loss_post_fn, checkpoint="except_last",
        schedule=schedule,
        remat_policy=jax.checkpoint_policies.dots_saveable,
        static_unroll=static_unroll)
    table = sched.schedule.op_tables(CHUNKS, 1)
    n_cycles = int(table[0].shape[0])

    x, n_rows = mb.stack_scatter({"tokens": tokens, "targets": targets},
                                 CHUNKS)
    w = mb.valid_row_mask(x, n_rows)
    key = make_key(2)
    step = make_step(model, sched, tx)

    def run():
        stacked = (stack_interleaved_params(sp, 1),
                   jax.tree_util.tree_map(lambda a: jnp.array(a, copy=True),
                                          prep),
                   jax.tree_util.tree_map(lambda a: jnp.array(a, copy=True),
                                          postp))
        return time_steps(step, stacked, tx.init(stacked), (x, w, key))

    sec, loss = with_retries(run)
    tokens_per_step = BATCH * cfg.seq_len
    tps = tokens_per_step / sec
    req_tok, _ = train_flops_per_token(cfg, "never", CHUNKS)
    mfu = (req_tok * tps) / peak_flops_per_chip()
    return {
        "v": v,
        "schedule": "1f1b" if v == 1 else "interleaved-1f1b",
        "program": ("static" if (static_unroll is True
                                 or (static_unroll is None and v == 1))
                    else "dynamic"),
        "n_cycles": n_cycles,
        "sec_per_step": round(sec, 5),
        "tokens_per_sec_per_chip": round(tps, 2),
        "mfu": round(mfu, 4),
        "final_loss": round(loss, 4),
    }


def main(vs):
    platform = jax.default_backend()
    cfg = tutorial_config(platform)
    header = {
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "chunks": CHUNKS, "batch": BATCH,
        "checkpoint": "except_last", "remat_policy": "dots_saveable",
        "d_model": cfg.d_model, "n_layers": cfg.n_layers,
        "seq_len": cfg.seq_len,
    }
    print(json.dumps({"header": header}), flush=True)

    tx = optax.chain(optax.clip_by_global_norm(0.5),
                     optax.adam(1e-4, mu_dtype=jnp.bfloat16))
    tokens = jax.random.randint(jax.random.key(1), (BATCH, cfg.seq_len),
                                0, cfg.vocab, jnp.int32)
    targets = jnp.roll(tokens, -1, axis=-1)

    anchor = None
    for v in vs:
        if cfg.n_layers % v:
            print(json.dumps({"v": v, "skipped":
                              f"{cfg.n_layers} layers not divisible"}),
                  flush=True)
            continue
        programs = [None] if v == 1 else [False, True]
        for static in programs:
            try:
                r = probe_variant(cfg, v, static, tx, tokens, targets)
            except Exception as e:       # static unroll can exceed HBM
                r = {"v": v,
                     "program": "static" if static else "dynamic",
                     "failed": str(e)[:200]}
                print(json.dumps(r), flush=True)
                continue
            if v == 1 and anchor is None:
                anchor = r["sec_per_step"]
            if anchor is not None and "sec_per_step" in r:
                r["overhead_vs_v1"] = round(r["sec_per_step"] / anchor, 4)
            print(json.dumps(r), flush=True)


def quick_main(n_stages: int = 4, chunks: int = 8):
    """cpu8 quick probe: the standing 4-stage/8-chunk bubble measurement
    plus the schedule AND transport (serialized vs packed-overlapped)
    comparison, one JSON line — what bench.py embeds as
    ``measured_bubble_multistage`` each round."""
    from pipe_tpu.obs.bubble_probe import main as bubble_main
    out = bubble_main(n_stages, chunks, compare_schedules=True,
                      compare_transport=True)
    out["mode"] = "quick-cpu8"
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    if "--quick" in sys.argv:
        pos = [int(a) for a in sys.argv[1:] if not a.startswith("--")]
        quick_main(*pos[:2])
    else:
        args = [int(a) for a in sys.argv[1:]] or [1, 2, 4]
        main(args)
