"""On-chip inference benchmark: KV-cached decode throughput for the 520M
tutorial LM (single chip, the hardware this environment has).

Measures, per configuration: prefill time (one batched causal pass over
the prompt) and steady-state decode tokens/s (the scan, amortized per
generated token per sequence, and aggregate across the batch). Greedy
sampling so the numbers are sampling-cost-free. The cached path's whole
point is turning O(t^2) re-forward into O(t) cache reads; the naive
re-forward equivalent at these lengths is too slow to be worth timing
per-run, so the comparison is architectural (see inference/generate.py).

Usage: python tools/gen_bench.py [batch ...]   (default: 1 8 32)
Prints one JSON line per batch size.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pipe_tpu.inference import GenerationConfig, Generator
from pipe_tpu.inference.quant import quantize_params
from pipe_tpu.models.transformer_lm import PipelinedLM

from bench import tutorial_config, with_retries

PROMPT = int(os.environ.get("GEN_BENCH_PROMPT", "128"))
MAX_NEW = int(os.environ.get("GEN_BENCH_NEW", "128"))


def main(batches, int8=False, unroll=False):
    platform = jax.default_backend()
    cfg = tutorial_config(platform)
    model = PipelinedLM(cfg, 1)
    sp, pre, post = model.init(jax.random.key(0))
    if int8:
        # Block weights only. Quantizing the vocab head was measured
        # COUNTERPRODUCTIVE (b=1: 33.5 ms/token vs 2.1 block-only): XLA
        # materializes the dequantized [d_model, vocab] f32 matrix every
        # step instead of fusing the dequant into the projection read.
        sp = quantize_params(sp)
    params = (sp, pre, post)
    gen = Generator(model, GenerationConfig(max_new_tokens=MAX_NEW,
                                            temperature=0.0),
                    layer_scan=not unroll)

    for b in batches:
        prompt = jax.random.randint(jax.random.key(1), (b, PROMPT),
                                    0, cfg.vocab, jnp.int32)

        def run():
            # compile + warm
            jax.block_until_ready(gen.generate(params, prompt))
            iters = 4
            t0 = time.perf_counter()
            for _ in range(iters):
                jax.block_until_ready(gen.generate(params, prompt))
            return (time.perf_counter() - t0) / iters

        try:
            sec = with_retries(run)
        except Exception as e:  # noqa: BLE001 — report per-config
            print(json.dumps({"batch": b, "error": str(e)[:200]}),
                  flush=True)
            continue
        print(json.dumps({
            "platform": platform, "weights": "int8" if int8 else "native",
            "layers": "unrolled" if unroll else "scan",
            "batch": b, "prompt": PROMPT,
            "max_new": MAX_NEW,
            "sec_per_generate": round(sec, 4),
            "ms_per_token_per_seq": round(1000 * sec / MAX_NEW, 3),
            "decode_tok_s_aggregate": round(b * MAX_NEW / sec, 1),
        }), flush=True)


if __name__ == "__main__":
    args = sys.argv[1:]
    int8 = "--int8" in args
    unroll = "--unroll" in args
    unknown = [a for a in args
               if a.startswith("--") and a not in ("--int8", "--unroll")]
    if unknown:
        sys.exit(f"unknown flags: {unknown} (valid: --int8 --unroll)")
    args = [a for a in args if not a.startswith("--")]
    main([int(a) for a in args] or [1, 8, 32], int8=int8, unroll=unroll)
