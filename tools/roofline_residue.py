"""Measure the MFU "item 3" residue: non-matmul op time in the train step.

``docs/mfu_roofline.md`` attributes the single-chip MFU gap to three
mechanisms; the third — elementwise/small-matmul residue — was originally
an "order 25-35 ms" estimate. This tool replaces the estimate with a
measurement: it captures a profiler trace of one compiled train step at
the bench layer shapes (``obs.meters.profile_trace``), parses the XSpace
with the repo's dependency-free reader (``obs.xplane``), classifies every
XLA op event as matmul vs everything-else, and prints the op-category
time split as one JSON line.

Scope honesty: this host has no TPU, so the ABSOLUTE times are CPU times.
What transfers is the op INVENTORY and the structure of the residue (which
non-dot ops exist in the compiled step and their relative weight among
themselves); the committed v5e milliseconds in the doc come from the
residue-by-subtraction arithmetic over the on-chip artifacts
(measured step - ideal matmul - optimizer streaming), which this trace
corroborates by showing the residue ops are really there and really
serialized between the dots.

Shapes: per-layer dims are the bench config exactly (d=2048, d_ff=2048,
nhead=32, s=128, V=28782); layer count and batch shrink (env
``RESIDUE_LAYERS`` / ``RESIDUE_BATCH``) so a CPU host traces in seconds —
per-layer op mix is what the doc cites, and that is layer-count-invariant.
"""

from __future__ import annotations

import collections
import json
import os
import re
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pipe_tpu.utils.platform import force_cpu_platform

force_cpu_platform(num_devices=1)

import jax
import jax.numpy as jnp

LAYERS = int(os.environ.get("RESIDUE_LAYERS", "2"))
BATCH = int(os.environ.get("RESIDUE_BATCH", "2"))
VOCAB = int(os.environ.get("RESIDUE_VOCAB", "28782"))

_MATMUL_MARKERS = ("dot", "matmul", "conv", "gemm")
_INFRA_PREFIXES = ("Tfrt", "Pjit", "Parse", "Thread")


_XLA_OP = re.compile(r"^[a-z][a-z0-9._\-]*$")


def _classify(name: str):
    """'matmul' / 'other' for XLA op events, None for runtime infra and
    the host tracer's Python-frame events ('$contextlib', 'PjitFunction',
    'ThreadpoolListener::...')."""
    if "::" in name or "$" in name or " " in name or name[:1].isupper():
        return None
    if not _XLA_OP.match(name):
        return None
    if any(name.startswith(p) for p in _INFRA_PREFIXES):
        return None
    base = name.split(".")[0]
    if any(m in base for m in _MATMUL_MARKERS):
        return "matmul"
    return "other"


def main() -> dict:
    from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
    from pipe_tpu.obs.meters import profile_trace
    from pipe_tpu.obs.xplane import load_trace_planes

    cfg = LMConfig(vocab=VOCAB, d_model=2048, nhead=32, d_ff=2048,
                   n_layers=LAYERS, seq_len=128, dropout=0.0)
    model = PipelinedLM(cfg, n_stages=1)
    sp, prep, postp = model.init(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (BATCH, cfg.seq_len),
                                0, cfg.vocab, jnp.int32)
    batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, -1)}

    from pipe_tpu.core.partition import StageCtx

    def loss_fn(sp):
        ctx = StageCtx(train=True)
        h = model.pre_fn(prep, batch, ctx)
        h = model.stage_fn(sp[0], h, ctx)
        return jnp.mean(model.loss_post_fn(postp, h, batch, ctx))

    step = jax.jit(jax.value_and_grad(loss_fn))
    jax.block_until_ready(step(sp))  # compile outside the trace

    logdir = tempfile.mkdtemp(prefix="roofline_residue_")
    with profile_trace(logdir):
        jax.block_until_ready(step(sp))

    cat_ns = collections.Counter()
    op_ns = collections.Counter()
    for plane in load_trace_planes(logdir):
        for line in plane.lines:
            for ev in line.events:
                cat = _classify(ev.name)
                if cat is None:
                    continue
                cat_ns[cat] += ev.duration_ns
                op_ns[(cat, ev.name.split(".")[0])] += ev.duration_ns

    total = sum(cat_ns.values())
    top_other = sorted(((n, t) for (c, n), t in op_ns.items()
                        if c == "other"), key=lambda kv: -kv[1])[:10]
    out = {
        "platform": jax.default_backend(),
        "layers": LAYERS, "batch": BATCH,
        "d_model": cfg.d_model, "d_ff": cfg.d_ff, "seq_len": cfg.seq_len,
        "vocab": VOCAB,
        "op_time_total_ms": round(total / 1e6, 3),
        "matmul_ms": round(cat_ns["matmul"] / 1e6, 3),
        "other_ms": round(cat_ns["other"] / 1e6, 3),
        "other_share": round(cat_ns["other"] / total, 4) if total else None,
        "top_other_ops_ms": {n: round(t / 1e6, 3) for n, t in top_other},
    }
    return out


if __name__ == "__main__":
    print(json.dumps(main()))
