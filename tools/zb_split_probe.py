"""Head-to-head: 1f1b vs zb-h1 (legacy stored-vjp) vs zb-h1 structural
split (hand-rolled and auto-derived), same TP-block model, cpu8 mesh.

The round-3 audit measured the legacy split at 1.70-1.83x 1f1b sec/step —
both B and W execute the full stored transpose. The structural split
(SplitBackwardStage) makes B params-constant and W contraction-only, so
total compute returns to one backward per micro-batch; on the serialized
single-core host the remaining gap vs 1f1b is extra cycles x machinery
only. The ``*-auto`` rows run the generalized jaxpr-surgery split
(``core/remat.py``, ``split_stage="auto"``) with its residual
passthrough dedup — weight leaves never ride the per-cycle slot store —
and a zb-h2 (deep-warmup) row rides along. ``--quick`` is the trimmed
variant ``bench.py`` embeds. Prints one JSON line; committed as the
honest zb-h1 cost record.
"""

from __future__ import annotations

import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main(n_stages=4, m=8, d_model=128, d_ff=512, seq_len=32, iters=3,
         quick=False):
    from pipe_tpu.utils.platform import force_cpu_platform
    force_cpu_platform(8)

    import dataclasses

    import jax
    import jax.numpy as jnp

    from pipe_tpu.core import microbatch as mb
    from pipe_tpu.models.tp_lm import (TPPipelinedLM,
                                       tp_split_backward_stage)
    from pipe_tpu.models.transformer_lm import LMConfig
    from pipe_tpu.parallel.mesh import make_mesh
    from pipe_tpu.parallel.scheduled import ScheduledPipeline
    from pipe_tpu.parallel.spmd import stack_stage_params

    cfg = dataclasses.replace(
        LMConfig().tiny(), d_model=d_model, nhead=4, d_ff=d_ff,
        seq_len=seq_len, n_layers=n_stages, dropout=0.0, vocab=512)
    model = TPPipelinedLM(cfg, n_stages, tp_axis=None)
    sp, prep, postp = model.init(jax.random.key(0))
    stacked = stack_stage_params(sp)
    mesh = make_mesh(n_stages, 1, devices=jax.devices()[:n_stages])
    tokens = jax.random.randint(jax.random.key(1), (4 * m, cfg.seq_len),
                                0, cfg.vocab, jnp.int32)
    x, n_rows = mb.stack_scatter(
        {"tokens": tokens, "targets": jnp.roll(tokens, -1, -1)}, m)
    w = mb.valid_row_mask(x, n_rows)

    variants = {
        "1f1b": dict(schedule="1f1b"),
        "zb-h1-legacy": dict(schedule="zb-h1"),
        "zb-h1-split": dict(schedule="zb-h1",
                            split_stage=tp_split_backward_stage(cfg)),
        # auto-derived structural split (core/remat.py jaxpr surgery) —
        # same table, no hand-rolled triple; the residual passthrough
        # dedup (weights never ride the slot store) applies to both
        "zb-h1-split-auto": dict(schedule="zb-h1", split_stage="auto"),
        "zb-h2-split-auto": dict(schedule="zb-h2", split_stage="auto"),
    }
    if quick:
        # bench.py embed: skip the legacy row (its 1.6x story is already
        # committed) and keep one hand-rolled + one auto split row
        variants.pop("zb-h1-legacy")
        iters = min(iters, 2)
    out = {"platform": "cpu8", "n_stages": n_stages, "chunks": m,
           "d_model": d_model, "variants": {}}
    if quick:
        out["mode"] = "quick-cpu8"
    for name, kw in variants.items():
        pipe = ScheduledPipeline(mesh, model.stage_fn, pre_fn=model.pre_fn,
                                 post_fn=model.loss_post_fn,
                                 checkpoint="never", **kw)
        lg = jax.jit(lambda s, pipe=pipe: pipe.loss_and_grad(
            s, prep, postp, x, w))
        jax.block_until_ready(lg(stacked))
        t0 = time.perf_counter()
        for _ in range(iters):
            r = lg(stacked)
        jax.block_until_ready(r)
        sec = (time.perf_counter() - t0) / iters
        out["variants"][name] = {"sec_per_step": round(sec, 5)}
    base = out["variants"]["1f1b"]["sec_per_step"]
    for v in out["variants"].values():
        v["vs_1f1b"] = round(v["sec_per_step"] / base, 4)
    return out


if __name__ == "__main__":
    kw = {}
    for a in sys.argv[1:]:
        if a == "--quick":
            kw["quick"] = True
            continue
        k, v = a.lstrip("-").split("=", 1)
        kw[k.replace("-", "_")] = int(v)
    print(json.dumps(main(**kw)))
