"""zb-h1 vs 1F1B: measure (cpu8, serialized), calibrate, predict (parallel).

``python tools/zb_crossover.py [--m 8] [--n 4] [--widths 128,256]`` times
one compiled step of both schedules at each width on the 8-virtual-device
CPU mesh, fits the cost model (per-width forward time ``f``, split overhead
``sigma``, per-cycle overhead ``o`` — see ``pipe_tpu/obs/zb_model.py``),
VALIDATES the serialized prediction against the very measurements it was
fitted on (relative residual), and prints one JSON line carrying:

* the calibration (incl. the measured ``sigma``),
* the serialized check (predicted vs measured zb/1f1b ratio),
* the PARALLEL-hardware prediction at the benchmarked (m, n) and a sweep
  over deeper/wider configs — each row reporting ``o_max``: the largest
  per-cycle overhead (in forward-time units) at which zb-h1 still wins.

This is the committed, falsifiable criterion the Trainer guidance gates on:
zb-h1 is recommended only for configs whose predicted parallel win survives
a plausible per-cycle overhead; the cpu8 wall-clock numbers travel alongside.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")


def measure(n_stages: int, chunks_list, widths, iters: int = 4,
            split: str = "auto"):
    """One (width, m) measurement row per combination — >= 2 distinct m
    values are what identify the per-cycle overhead in the fit (op counts
    scale with m; the fill/drain cycle surplus does not)."""
    from pipe_tpu.utils.platform import force_cpu_platform
    force_cpu_platform(8)

    import jax
    import jax.numpy as jnp

    from pipe_tpu.core import microbatch as mb
    from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
    from pipe_tpu.parallel.mesh import make_mesh
    from pipe_tpu.parallel.scheduled import ScheduledPipeline
    from pipe_tpu.parallel.spmd import stack_stage_params

    mesh = make_mesh(n_stages, 1, devices=jax.devices()[:n_stages])
    rows = []
    for width in widths:
        cfg = LMConfig(vocab=512, d_model=width, nhead=4, d_ff=2 * width,
                       n_layers=n_stages, seq_len=64, dropout=0.0)
        model = PipelinedLM(cfg, n_stages)
        sp, prep, postp = model.init(jax.random.key(0))
        sp = stack_stage_params(sp)
        for chunks in chunks_list:
            tokens = jax.random.randint(jax.random.key(1),
                                        (4 * chunks, cfg.seq_len), 0,
                                        cfg.vocab, jnp.int32)
            x, n_rows = mb.stack_scatter(
                {"tokens": tokens, "targets": jnp.roll(tokens, -1, -1)},
                chunks)
            w = mb.valid_row_mask(x, n_rows)
            row = {"width": width, "m": chunks}
            for name, key_out in (("1f1b", "t_1f1b"), ("zb-h1", "t_zb")):
                kw = {}
                if name == "zb-h1" and split != "none":
                    # the structural B/W split (params-constant B +
                    # contraction-only W) is the real zb-h1 cost since the
                    # auto split landed; --split none re-measures the
                    # legacy stored-vjp path for trend comparison
                    kw["split_stage"] = split
                pipe = ScheduledPipeline(
                    mesh, model.stage_fn, pre_fn=model.pre_fn,
                    post_fn=model.loss_post_fn, checkpoint="never",
                    schedule=name, **kw)
                lg = jax.jit(lambda s_, pipe=pipe: pipe.loss_and_grad(
                    s_, prep, postp, x, w))
                jax.block_until_ready(lg(sp))
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = lg(sp)
                jax.block_until_ready(out)
                row[key_out] = (time.perf_counter() - t0) / iters
            rows.append(row)
    return rows


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--m", type=int, default=8)
    p.add_argument("--n", type=int, default=4)
    # keep widths cache-resident on the single-core host: at d_model 256+
    # the m=2*m working set spills and step time grows superlinearly in m,
    # violating the linear cost model (the fit flags it with f <= 0)
    p.add_argument("--widths", default="64,128")
    p.add_argument("--iters", type=int, default=4)
    p.add_argument("--split", default="auto", choices=("auto", "none"),
                   help="zb-h1 backward: 'auto' = structural B/W split "
                        "(the shipping path), 'none' = legacy stored-vjp")
    args = p.parse_args(argv)
    widths = [int(w) for w in args.widths.split(",")]

    rows = measure(args.n, [args.m, 2 * args.m], widths, iters=args.iters,
                   split=args.split)

    from pipe_tpu.obs.zb_model import OpCosts, calibrate, crossover, predict

    cal = calibrate(rows, args.n)
    sigma = cal["sigma"]

    # serialized check: re-predict the measurements from the fit
    checks = []
    for row in rows:
        k = cal["widths"].index(row["width"])
        costs = OpCosts(f=cal["f_per_width"][k],
                        sigma=cal["sigma_per_width"][k],
                        o=cal["o_serialized_per_width"][k])
        pred = predict(row["m"], args.n, costs, "serialized")
        checks.append({
            "width": row["width"], "m": row["m"],
            "measured_ratio": row["t_zb"] / row["t_1f1b"],
            "predicted_ratio": pred["zb_over_1f1b"],
        })

    # parallel predictions: benchmarked config + a depth/width sweep.
    # f_ref: the largest width whose fit is physical (f > 0); a width with
    # f <= 0 violated the linear model (cache spill) and is excluded.
    good = [k for k, f in enumerate(cal["f_per_width"]) if f > 0]
    if not good:
        print(json.dumps({"error": "no width produced a physical fit"}))
        return 1
    f_ref = cal["f_per_width"][good[-1]]
    par = predict(args.m, args.n, OpCosts(f=f_ref, sigma=sigma, o=0.0),
                  "parallel")
    sweep = []
    for (mm, nn) in ((args.m, args.n), (8, 8), (16, 8), (32, 8),
                     (16, 16), (32, 16)):
        sweep.append(crossover(mm, nn, sigma))

    out = {
        "split": args.split,
        "measurements": rows,
        "calibration": cal,
        "serialized_check": checks,
        "parallel_prediction_at_bench_config": par,
        "crossover_sweep": sweep,
        "note": ("o_max_f_units: largest per-cycle overhead (units of one "
                 "stage-forward) at which zb-h1 still beats 1f1b on "
                 "parallel hardware; <= 0 means predicted loss outright. "
                 "sigma is the measured split-backward work overhead — "
                 "WIDTH-DEPENDENT on cpu8 (slot-store traffic), so the "
                 "committed gate is breakeven_sigma: zb-h1 wins at (m, n) "
                 "on parallel hardware iff its measured sigma there is "
                 "below it (at negligible per-cycle overhead)."),
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
