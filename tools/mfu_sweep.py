"""MFU sweep on the real chip: checkpoint x remat_policy on the bench
workload (520M tutorial config, chunks=4, d=1 static 1f1b program).

``python tools/mfu_sweep.py [policy ...]`` — times ONLY the pipelined
training step per configuration (no baselines/probes), printing one JSON
line per config. Used to pick bench.py's default policy (VERDICT r2 #6).
"""

from __future__ import annotations

import json
import os
import sys

import jax
import jax.numpy as jnp
import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from bench import (CHUNKS, BATCH, make_step, peak_flops_per_chip,
                   time_steps, train_flops_per_token, tutorial_config,
                   with_retries)
from pipe_tpu.core import microbatch as mb
from pipe_tpu.models.transformer_lm import PipelinedLM
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.scheduled import ScheduledPipeline
from pipe_tpu.parallel.spmd import stack_stage_params
from pipe_tpu.utils.rng import make_key


def main(configs):
    platform = jax.default_backend()
    cfg = tutorial_config(platform)
    mesh = make_mesh(1, 1, devices=jax.devices()[:1])
    model = PipelinedLM(cfg, 1)
    sp, prep, postp = model.init(jax.random.key(0))
    tx = optax.chain(optax.clip_by_global_norm(0.5), optax.adam(1e-4))
    tokens = jax.random.randint(jax.random.key(1), (BATCH, cfg.seq_len),
                                0, cfg.vocab, jnp.int32)
    x, n_rows = mb.stack_scatter(
        {"tokens": tokens, "targets": jnp.roll(tokens, -1, -1)}, CHUNKS)
    w = mb.valid_row_mask(x, n_rows)
    key = make_key(2)
    peak = peak_flops_per_chip()
    tokens_per_step = BATCH * cfg.seq_len

    for checkpoint, policy_name in configs:
        policy = (getattr(jax.checkpoint_policies, policy_name)
                  if policy_name != "none" else None)
        sched = ScheduledPipeline(
            mesh, model.stage_fn, pre_fn=model.pre_fn,
            post_fn=model.loss_post_fn, checkpoint=checkpoint,
            schedule="1f1b", remat_policy=policy)
        step = make_step(model, sched, tx)

        def run():
            p = (stack_stage_params(sp),
                 jax.tree_util.tree_map(lambda a: jnp.array(a, copy=True),
                                        prep),
                 jax.tree_util.tree_map(lambda a: jnp.array(a, copy=True),
                                        postp))
            return time_steps(step, p, tx.init(p), (x, w, key))

        try:
            sec, _ = with_retries(run)
        except Exception as e:
            print(json.dumps({"checkpoint": checkpoint,
                              "policy": policy_name,
                              "error": str(e)[:300]}))
            continue
        tps = tokens_per_step / sec
        # MFU's numerator is the required (no-recompute) FLOPs — checkpoint
        # mode and policy never change it
        req, _ = train_flops_per_token(cfg, "never", CHUNKS)
        print(json.dumps({
            "checkpoint": checkpoint, "policy": policy_name,
            "sec_per_step": round(sec, 5),
            "tok_s_chip": round(tps, 1),
            "mfu": round(req * tps / peak, 4),
        }), flush=True)


if __name__ == "__main__":
    if len(sys.argv) > 1:
        # "policy" (checkpoint defaults to except_last) or "checkpoint:policy"
        configs = [tuple(a.split(":", 1)) if ":" in a
                   else ("except_last", a) for a in sys.argv[1:]]
    else:
        configs = [("except_last", "dots_saveable"),
                   ("except_last", "dots_with_no_batch_dims_saveable"),
                   ("except_last", "none"),
                   ("never", "none")]
    main(configs)
