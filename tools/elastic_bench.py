"""Elastic bench: prove degraded-mode training survives losing a stage.

The drill (the paper's fault model, scaled to cpu8): a 4-stage GPipe
run with buddy replication armed takes a ``kill_stage`` fault mid-run —
stage 1 goes permanently silent. The run must

1. **detect** the loss from the per-stage gradient heartbeat (the
   killed stage's zeroed output annihilates the backward signal for
   every stage at or upstream of the cut; the largest persistently
   silent index localizes it) with no host sync on the healthy path;
2. **re-plan** over the 3 survivors — re-cut the layer balance, re-emit
   the op table for the new width and push it through the same
   verifier + phase compiler every table must pass
   (:func:`~pipe_tpu.core.schedule.replan_stage_loss`);
3. **restore** stage state from the buddy ring (each stage's shard was
   replicated one ppermute hop away on a cadence, sha256-pinned) and
   resume mid-epoch at the snapshot step.

The acceptance pin is *bitwise*: after the recovered run finishes, its
params AND Adam moments must equal — every leaf, every byte — a
reference that trains the unkilled 4-stage model to the snapshot step,
restacks it over 3 stages on the host, and finishes on a born-3-stage
trainer over the same global batches. Recovery is a re-coordinatization
plus verified replay, not an approximation.

The second pin is absence: with ``TrainerConfig.elastic=None`` the
train step's lowered HLO is byte-identical whether or not the elastic
machinery was ever constructed in the process.

Usage:
  python tools/elastic_bench.py                  # -> ELASTIC_r11.json
  python tools/elastic_bench.py --quick          # one JSON line
Progress goes to stderr; the last stdout line is always the summary
object, so ``bench.py`` embeds the --quick summary.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# 4-stage drill + 3-stage recovery need virtual CPU devices before jax
# binds a backend (same pattern as chaos_bench).
from pipe_tpu.utils.platform import force_cpu_platform  # noqa: E402

force_cpu_platform(8)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from pipe_tpu.data import lm_text  # noqa: E402
from pipe_tpu.models.transformer_lm import LMConfig  # noqa: E402
from pipe_tpu.obs.telemetry import (MetricsRegistry,  # noqa: E402
                                    set_registry)
from pipe_tpu.resilience import (ChaosPlan, ElasticConfig,  # noqa: E402
                                 Fault, ResilienceConfig)
from pipe_tpu.resilience.elastic import (restack_state,  # noqa: E402
                                         train_elastic)
from pipe_tpu.train.loop import Trainer, TrainerConfig  # noqa: E402

# 12 layers: divisible by 4 (healthy) and 3 (degraded) — uniform stage
# bodies on both sides of the re-plan.
CFG = LMConfig(vocab=67, d_model=16, nhead=2, d_ff=32, n_layers=12,
               seq_len=32, dropout=0.0)
STEPS = 10
KILL_STEP = 6


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _source():
    ids = np.random.RandomState(0).randint(0, CFG.vocab, size=20000)
    return lm_text.batchify(ids, 8)


def _tc(n_stages, **kw):
    rc = ResilienceConfig(warmup_steps=100, rewind_after=3,
                          snapshot_every=3, rewind_backoff_s=0.0)
    ec = ElasticConfig(snapshot_every=3, dead_after=2)
    base = dict(batch_size=8, bptt=16, chunks=4, n_stages=n_stages,
                schedule="gpipe", checkpoint="never", lr=0.01,
                resilience=rc, elastic=ec)
    base.update(kw)
    return TrainerConfig(**base)


def _leaves_equal(a_tree, b_tree):
    al = jax.tree_util.tree_leaves(a_tree)
    bl = jax.tree_util.tree_leaves(b_tree)
    if len(al) != len(bl):
        return False, len(al)
    bad = sum(0 if np.array_equal(np.asarray(a), np.asarray(b)) else 1
              for a, b in zip(al, bl))
    return bad == 0, len(al)


def drill_trial():
    """Kill stage 1 of 4 mid-run; assert detection, re-plan, buddy
    restore, and the bitwise pin against the from-snapshot reference."""
    reg = set_registry(MetricsRegistry())
    try:
        t0 = time.perf_counter()
        plan = ChaosPlan([Fault("kill_stage", step=KILL_STEP, stage=1)])
        tr = Trainer(CFG, _tc(4), chaos=plan)
        tr2, state_e, info = train_elastic(tr, _source(), max_steps=STEPS,
                                           log_fn=log)
        rec = info["recoveries"][0] if info["recoveries"] else {}
        drill_snaps = tr.registry.scalars().get(
            "resilience.elastic.snapshots", 0)

        # reference: unkilled to the snapshot step, restack on the
        # host, finish born-3-stage over the same global batch indices
        ref4 = Trainer(CFG, _tc(4), chaos=ChaosPlan([]))
        s4, _ = ref4.train_epoch(_source(), 0, ref4.init_state(),
                                 max_steps=rec.get("resume_step", 6),
                                 log_every=0, log_fn=log)
        host = jax.tree_util.tree_map(
            lambda a: np.asarray(a) if isinstance(a, jax.Array) else a, s4)
        host3 = restack_state(host, 4, 3)
        surv = np.delete(np.asarray(ref4.mesh.devices),
                         rec.get("stage", 1), axis=0).reshape(-1).tolist()
        ref3 = Trainer(CFG, _tc(3), devices=surv, chaos=ChaosPlan([]))
        tpl = ref3.init_state()
        s3 = jax.tree_util.tree_map(
            lambda h, t: (jax.device_put(np.asarray(h), t.sharding)
                          if isinstance(t, jax.Array) else h), host3, tpl)
        s3f, _ = ref3.train_epoch(_source(), 0, s3, max_steps=STEPS,
                                  log_every=0, log_fn=log,
                                  start_step=rec.get("resume_step", 6))

        params_eq, n_p = _leaves_equal(state_e.params, s3f.params)
        opt_eq, n_o = _leaves_equal(state_e.opt_state, s3f.opt_state)
        finite = all(bool(jnp.isfinite(l).all())
                     for l in jax.tree_util.tree_leaves(state_e.params)
                     if jnp.issubdtype(l.dtype, jnp.inexact))
        recovered = (info["replans"] == 1 and tr2.cfg.n_stages == 3
                     and rec.get("stage") == 1 and finite
                     and params_eq and opt_eq)
        return {
            "recovered": bool(recovered),
            "killed_stage": 1, "kill_step": KILL_STEP,
            "detected_step": rec.get("detected_step"),
            "snapshot_step": rec.get("snapshot_step"),
            "resume_step": rec.get("resume_step"),
            "lost_steps": rec.get("lost_steps"),
            "stages_after": int(tr2.cfg.n_stages),
            "buddy_snapshots": int(drill_snaps),
            "params_bitwise_vs_reference": bool(params_eq),
            "opt_state_bitwise_vs_reference": bool(opt_eq),
            "param_leaves": int(n_p), "opt_leaves": int(n_o),
            "params_finite": bool(finite),
            "recovery_s": round(float(rec.get("recovery_s", 0.0)), 3),
            "wall_s": round(time.perf_counter() - t0, 1),
        }
    finally:
        set_registry(reg)


def hlo_trial():
    """Elastic absent => the train step lowers byte-identical before and
    after the elastic machinery exists in the process."""
    reg = set_registry(MetricsRegistry())
    try:
        small = LMConfig(vocab=67, d_model=16, nhead=2, d_ff=32,
                         n_layers=4, seq_len=32, dropout=0.0)
        tc = TrainerConfig(batch_size=8, bptt=16, chunks=2, n_stages=2,
                           checkpoint="never", lr=0.01)
        tr = Trainer(small, tc)
        state = tr.init_state()
        data, target = next(tr._batches(_source(), 1))
        x, w = tr._make_x(data, target)
        args = (state, x, w, jax.random.key(0), jnp.float32(0.01))
        base = tr._step_fn.lower(*args).as_text()

        etr = Trainer(small, _tc(2, chunks=2), chaos=ChaosPlan([]))
        es = etr.init_state()
        aux = (jnp.float32(0.0), jnp.int32(0), jnp.int32(0),
               jnp.zeros((2,), jnp.int32))
        etr._step_fn.lower(es, aux, x, w, jax.random.key(0),
                           jnp.float32(0.01), jnp.int32(-1),
                           jnp.float32(0.0), jnp.int32(-1)).as_text()
        etr.elastic_store().capture(es, 0)       # the full machinery ran

        again = tr._step_fn.lower(*args).as_text()
        return {"ok": bool(again == base), "hlo_bytes": len(base)}
    finally:
        set_registry(reg)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="same drill, one JSON line (no artifact)")
    ap.add_argument("--out", default=None,
                    help="also write the summary JSON here")
    args = ap.parse_args()

    t0 = time.perf_counter()
    log("== elastic drill: kill stage 1/4 at step "
        f"{KILL_STEP}, re-plan to 3, bitwise pin")
    drill = drill_trial()
    log(f"   {drill}")
    log("== HLO pin: elastic absent -> byte-identical step")
    hlo = hlo_trial()
    log(f"   {hlo}")

    summary = {
        "bench": "elastic", "rev": "r11",
        "quick": bool(args.quick),
        "platform": jax.default_backend(),
        "all_ok": bool(drill["recovered"] and hlo["ok"]),
        "drill": drill,
        "hlo_unchanged_without_elastic": hlo,
        "wall_s": round(time.perf_counter() - t0, 1),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=2)
        log(f"wrote {args.out}")
    print(json.dumps(summary, indent=None if args.quick else 2))
    return 0 if summary["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
