"""Plan bench: calibrate → search → measure, end to end on the cpu8 probe.

The auto-planner (``core/planner.py``) claims it can pick schedule ×
micro-batch count × interleave from a few measured steps. This tool makes
it prove that on the repo's standing cpu8 probe (the ``bubble_probe`` /
``zb_split_probe`` harness — 4 ppermute-ring stages on 8 virtual CPU
devices, tiny transformer LM):

1. **Calibrate**: measure real 1f1b and zb-h1(split=auto) steps at two
   micro-batch counts, fit ``(f, sigma, o)`` with
   ``obs/zb_model.calibrate`` (its residual gates the whole run —
   ``CostProfile`` refuses untrustworthy fits), and fold the fit plus the
   model's real per-layer parameter/activation sizes into a
   ``CostProfile`` via ``planner.profile_from_calibration``.
2. **Search**: rank (schedule family × m × v × split_stage) with
   ``planner.search``; every emitted plan's op table is re-PROVEN here
   (``verify_op_tables`` / the interleaved verifier + ``compile_phases``)
   so the committed artifact carries the proof, not just the search's
   word for it.
3. **Measure**: run the top-3 plans for real (``ScheduledPipeline``,
   jitted ``loss_and_grad``) and record predicted-vs-measured error,
   plus the hand-tuned 1f1b m=8 baseline (the standing probe config).
   ``plan_ok`` asserts the chosen plan's measured per-row time is no
   slower than that baseline within the noise band.

``--quick`` is the trimmed variant ``bench.py`` embeds (smaller model,
top-1 measured). Prints one JSON line; the full run is committed as
``PLAN_r{N}.json``.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

# The standing probe config every perf tool in this repo hand-tunes to:
# 1f1b, m=8, checkpoint='never' (bubble_probe / zb_split_probe rows).
BASELINE = {"schedule": "1f1b", "m": 8, "v": 1, "split": False}


def main(quick=False, iters=3, noise=0.12, out_path=None):
    from pipe_tpu.utils.platform import force_cpu_platform
    force_cpu_platform(8)

    import dataclasses

    import jax
    import jax.numpy as jnp

    from pipe_tpu.core import microbatch as mb
    from pipe_tpu.core.planner import profile_from_calibration, search
    from pipe_tpu.core.schedule import (InterleavedOneFOneBSchedule,
                                        compile_phases, get_schedule,
                                        verify_interleaved_op_tables,
                                        verify_op_tables)
    from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
    from pipe_tpu.obs.zb_model import calibrate
    from pipe_tpu.parallel.interleaved import stack_interleaved_params
    from pipe_tpu.parallel.mesh import make_mesh
    from pipe_tpu.parallel.scheduled import ScheduledPipeline
    from pipe_tpu.parallel.spmd import stack_stage_params

    d = 4                       # pipeline stages on the cpu8 mesh
    n_layers = 8                # divides both v=1 (4 stages) and v=2 (8)
    # d_model stays 128 even in quick mode: below that, per-op compute
    # sinks under the per-cycle machinery overhead and the calibration
    # fit loses f (measured: d_model=64 drives f < 0 — unphysical).
    d_model = 128
    d_ff = 256 if quick else 512
    seq_len = 32 if quick else 64
    mb_rows = 8                 # rows per micro-batch, held constant:
    #                             batch scales with m (probe semantics),
    #                             which is also calibrate()'s assumption
    iters = min(iters, 2) if quick else iters

    cfg = dataclasses.replace(
        LMConfig().tiny(), d_model=d_model, nhead=4, d_ff=d_ff,
        seq_len=seq_len, n_layers=n_layers, dropout=0.0,
        vocab=256 if quick else 512)
    del seq_len  # use cfg.seq_len below
    mesh = make_mesh(d, 1, devices=jax.devices()[:d])

    models = {}             # n_virtual -> (model, stage_params, prep, postp)

    def model_for(n_virtual):
        if n_virtual not in models:
            model = PipelinedLM(cfg, n_virtual)
            sp, prep, postp = model.init(jax.random.key(0))
            models[n_virtual] = (model, sp, prep, postp)
        return models[n_virtual]

    def make_batch(m):
        tokens = jax.random.randint(jax.random.key(1),
                                    (mb_rows * m, cfg.seq_len),
                                    0, cfg.vocab, jnp.int32)
        x, n_rows = mb.stack_scatter(
            {"tokens": tokens, "targets": jnp.roll(tokens, -1, -1)}, m)
        return x, mb.valid_row_mask(x, n_rows)

    def measure(schedule, m, v=1, split=False):
        model, sp, prep, postp = model_for(v * d)
        stacked = (stack_interleaved_params(sp, d) if v > 1
                   else stack_stage_params(sp))
        sched = (InterleavedOneFOneBSchedule(interleave=v)
                 if schedule == "interleaved-1f1b" else schedule)
        kw = {"split_stage": "auto"} if split else {}
        pipe = ScheduledPipeline(
            mesh, model.stage_fn, pre_fn=model.pre_fn,
            post_fn=model.loss_post_fn, checkpoint="never",
            schedule=sched, **kw)
        x, w = make_batch(m)
        lg = jax.jit(lambda s: pipe.loss_and_grad(s, prep, postp, x, w))
        jax.block_until_ready(lg(stacked))      # compile
        t0 = time.perf_counter()
        for _ in range(iters):
            r = lg(stacked)
        jax.block_until_ready(r)
        return (time.perf_counter() - t0) / iters

    # -- 1. calibrate: two m points x {1f1b, zb-h1 split} ------------------
    cal_ms = (4, 8)
    rows = []
    for m in cal_ms:
        rows.append({"width": d_model, "m": m,
                     "t_1f1b": measure("1f1b", m),
                     "t_zb": measure("zb-h1", m, split=True)})
    calib = calibrate(rows, n=d)

    _, sp1, _, _ = model_for(d)
    total_param_bytes = int(sum(
        a.size * a.dtype.itemsize
        for a in jax.tree_util.tree_leaves(sp1) if hasattr(a, "dtype")))
    act_layer = mb_rows * cfg.seq_len * cfg.d_model * 4
    profile = profile_from_calibration(
        calib, n_layers=n_layers, rows=mb_rows,
        layer_param_bytes=total_param_bytes // n_layers,
        layer_act_bytes=act_layer, mode="serialized")

    # -- 2. search ---------------------------------------------------------
    plans = search(profile, n_devices=d, m_candidates=(2, 4, 8),
                   schedules=("gpipe", "1f1b", "interleaved-1f1b",
                              "zb-h1", "zb-h2"),
                   interleave_candidates=(2,), checkpoint="never",
                   uniform_only=True, max_plans=8)
    if not plans:
        raise SystemExit("planner emitted no plans on the cpu8 probe")

    # Re-prove every emitted plan's table here, so the committed artifact
    # carries the proof (acceptance: verify_op_tables + compile_phases).
    all_verified = True
    for p in plans:
        sched = (InterleavedOneFOneBSchedule(interleave=p.v) if p.v > 1
                 else get_schedule(p.schedule))
        tables = sched.op_tables(p.m, d if p.v > 1 else p.v * d)
        op, mbi = tables[0], tables[1]
        grp = tables[2] if len(tables) > 2 else None
        if p.v > 1:
            verify_interleaved_op_tables(op, mbi, grp, p.m, d, p.v)
        else:
            verify_op_tables(
                op, mbi, p.m, d, stash_slots=sched.stash_slots(p.m, d),
                wstash_slots=(sched.wstash_slots(p.m, d)
                              if sched.splits_backward else None))
        verdict = compile_phases(op, mbi, grp, m=p.m, d=d, v=p.v)
        all_verified = all_verified and bool(verdict.accepted)

    # -- 3. measure top-k + the hand-tuned baseline ------------------------
    topk = plans[:1 if quick else 3]
    measured = []
    for p in topk:
        t = measure(p.schedule, p.m, v=p.v, split=p.split_stage)
        measured.append({
            **{k: p.summary()[k] for k in
               ("schedule", "m", "v", "split_stage", "predicted_step_s")},
            "measured_step_s": round(t, 5),
            "measured_s_per_row": round(t / (p.m * mb_rows), 6),
            "rel_err": round(p.predicted_step_s / t - 1.0, 4)})

    b = BASELINE
    reuse = next((r for r in measured
                  if (r["schedule"], r["m"], r["v"], r["split_stage"])
                  == (b["schedule"], b["m"], b["v"], b["split"])), None)
    t_base = (reuse["measured_step_s"] if reuse
              else measure(b["schedule"], b["m"], v=b["v"],
                           split=b["split"]))
    base_per_row = t_base / (b["m"] * mb_rows)

    top = measured[0]
    top_vs_base = top["measured_s_per_row"] / base_per_row
    out = {
        "platform": "cpu8", "n_devices": d, "n_layers": n_layers,
        "d_model": d_model, "seq_len": cfg.seq_len, "mb_rows": mb_rows,
        "iters": iters,
        "calibration": {
            "sigma": round(calib["sigma"], 4),
            "f": [round(f, 6) for f in calib["f_per_width"]],
            "o": [round(o, 6) for o in calib["o_serialized_per_width"]],
            "rel_residual": round(calib["rel_residual"], 4),
            "measurements": [
                {k: (round(v, 5) if isinstance(v, float) else v)
                 for k, v in r.items()} for r in rows]},
        "plans_considered": len(plans),
        "all_plans_verified": all_verified,
        "plan": json.loads(plans[0].to_json()),
        "top_measured": measured,
        "baseline_1f1b": {"m": b["m"],
                          "measured_step_s": round(t_base, 5),
                          "measured_s_per_row": round(base_per_row, 6)},
        "top_vs_baseline_per_row": round(top_vs_base, 4),
        "noise_band": noise,
        "plan_ok": bool(all_verified and top_vs_base <= 1.0 + noise),
    }
    if quick:
        out["mode"] = "quick-cpu8"
    if out_path:
        with open(out_path, "w") as fh:
            json.dump(out, fh, indent=2, sort_keys=True)
            fh.write("\n")
    return out


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="trimmed bench.py embed: smaller model, top-1")
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--noise", type=float, default=0.12,
                    help="baseline tolerance band for plan_ok")
    ap.add_argument("--out", default=None,
                    help="also write the full JSON report here")
    a = ap.parse_args()
    print(json.dumps(main(quick=a.quick, iters=a.iters, noise=a.noise,
                          out_path=a.out)))
