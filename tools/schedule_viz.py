"""Render pipeline schedule tables as timelines (ASCII and SVG).

The ``(cycle, stage) -> (op, microbatch)`` tables in ``core/schedule.py``
ARE the executor — this tool makes them inspectable: a per-stage timeline
with one column per cycle, forward/backward/weight-grad slots colored and
labeled with their micro-batch, idle slots visibly empty (the bubble), and
the analytic bubble fraction in the title. The reference debugs its
schedule with print statements and a pptx; here the schedule is data, so
the picture is generated from the same arrays the compiled program runs.

Usage:
    python tools/schedule_viz.py [gpipe|1f1b|zb-h1|interleaved-1f1b]
        [-m MICROBATCHES] [-n STAGES] [-v INTERLEAVE] [--svg out.svg]

With no schedule argument, prints all of them at the default geometry.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional, Tuple

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pipe_tpu.core.schedule import (BWD, FWD, IDLE, WGRAD, GPipeSchedule,
                                    InterleavedOneFOneBSchedule,
                                    OneFOneBSchedule, ZeroBubbleSchedule)

_GLYPH = {IDLE: " . ", FWD: "F%d", BWD: "B%d", WGRAD: "W%d"}
_COLOR = {FWD: "#4c78a8", BWD: "#e45756", WGRAD: "#f2a900"}
_NAME = {FWD: "F", BWD: "B", WGRAD: "W"}


def make_schedule(name: str, interleave: int = 2):
    if name == "gpipe":
        return GPipeSchedule()
    if name == "1f1b":
        return OneFOneBSchedule()
    if name == "zb-h1":
        return ZeroBubbleSchedule()
    if name == "interleaved-1f1b":
        return InterleavedOneFOneBSchedule(interleave=interleave)
    raise ValueError(f"unknown schedule {name!r}")


def tables(name: str, m: int, n: int, interleave: int = 2):
    """(op, mb, grp_or_None, bubble). Interleaved tables are over DEVICES
    and carry a third array: which interleave group the slot serves."""
    sched = make_schedule(name, interleave)
    out = sched.op_tables(m, n)
    if len(out) == 3:
        op, mb, grp = out
    else:
        (op, mb), grp = out, None
    return op, mb, grp, sched.bubble(m, n)


def _trim(op: np.ndarray) -> int:
    """Last cycle with any work + 1 (tables may carry trailing idle)."""
    busy = np.nonzero((op != IDLE).any(axis=1))[0]
    return int(busy[-1]) + 1 if busy.size else 0


def _label(op, mb, grp, t, j) -> str:
    o = int(op[t, j])
    if o == IDLE:
        return "."
    if grp is None:
        return f"{_NAME[o]}{int(mb[t, j])}"
    return f"{_NAME[o]}{int(grp[t, j])}.{int(mb[t, j])}"


def ascii_timeline(name: str, m: int, n: int, interleave: int = 2) -> str:
    op, mb, grp, bubble = tables(name, m, n, interleave)
    T = _trim(op)
    width = max(3, len(str(m - 1)) + (5 if grp is not None else 2))
    row_kind = "device" if grp is not None else "stage"
    head = f"{name}  m={m} n={n}  cycles={T}  bubble={bubble:.1%}"
    if grp is not None:
        head += f"  (cells: op<group>.<microbatch>, v={interleave})"
    lines = [head,
             " " * 9 + "".join(f"{t:^{width}}" for t in range(T))]
    for j in range(op.shape[1]):
        cells = [f"{_label(op, mb, grp, t, j):^{width}}" for t in range(T)]
        lines.append(f"{row_kind} {j}|".rjust(9) + "".join(cells))
    return "\n".join(lines)


def svg_timeline(name: str, m: int, n: int, interleave: int = 2,
                 cell: int = 26) -> str:
    op, mb, grp, bubble = tables(name, m, n, interleave)
    if grp is not None:
        cell = max(cell, 40)  # wider cells for group.microbatch labels
    T = _trim(op)
    n_stages = op.shape[1]
    pad_l, pad_t = 64, 40
    w = pad_l + T * cell + 10
    h = pad_t + n_stages * cell + 10
    parts = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{w}" height="{h}" '
        f'font-family="monospace" font-size="11">',
        f'<text x="8" y="18">{name}  m={m} n={n}  cycles={T}  '
        f'bubble={bubble:.1%}</text>',
    ]
    for j in range(n_stages):
        y = pad_t + j * cell
        parts.append(f'<text x="8" y="{y + cell * 0.65:.0f}">s{j}</text>')
        for t in range(T):
            x = pad_l + t * cell
            o = int(op[t, j])
            if o == IDLE:
                parts.append(
                    f'<rect x="{x}" y="{y}" width="{cell - 1}" '
                    f'height="{cell - 1}" fill="#eeeeee"/>')
            else:
                parts.append(
                    f'<rect x="{x}" y="{y}" width="{cell - 1}" '
                    f'height="{cell - 1}" fill="{_COLOR[o]}"/>'
                    f'<text x="{x + cell // 2}" y="{y + cell * 0.65:.0f}" '
                    f'text-anchor="middle" fill="white">'
                    f'{_label(op, mb, grp, t, j)}</text>')
    parts.append("</svg>")
    return "".join(parts)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("schedule", nargs="?", default=None,
                   choices=["gpipe", "1f1b", "zb-h1", "interleaved-1f1b"])
    p.add_argument("-m", type=int, default=8, help="micro-batches")
    p.add_argument("-n", type=int, default=4, help="stages/devices")
    p.add_argument("-v", "--interleave", type=int, default=2)
    p.add_argument("--svg", default=None, help="write an SVG here instead")
    args = p.parse_args(argv)

    names = ([args.schedule] if args.schedule
             else ["gpipe", "1f1b", "zb-h1", "interleaved-1f1b"])
    if args.svg:
        if len(names) != 1:
            print("--svg needs an explicit schedule", file=sys.stderr)
            return 2
        with open(args.svg, "w") as f:
            f.write(svg_timeline(names[0], args.m, args.n, args.interleave))
        print(f"wrote {args.svg}")
        return 0
    for name in names:
        print(ascii_timeline(name, args.m, args.n, args.interleave))
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
