"""fleet_top: live terminal view of the fleet observability plane.

Polls the ``/fleet`` and ``/slo`` endpoints that
``pipe_tpu.apps.serve --metrics-port`` serves (docs/observability.md,
"Fleet observability") and renders a top(1)-style screen: one row per
replica — health state, queue depth, live slots, the
delivery-synchronized ``tokens_out``/``responses_out`` counters, obs
frame seq, metric staleness and the durable-journal lag (``jlag``:
seconds since the controller's last fsync'd lifecycle record, "-" for
journal-less fleets) — plus the fleet SLO verdict line with any
violations called out.

The screen is produced by the pure :func:`render` (fleet dict + slo
verdict in, string out) so tests exercise the layout without a server
or a terminal; the CLI is just fetch → clear → print in a loop.

Usage:
  python -m pipe_tpu.apps.serve ... --replicas 3 --metrics-port 9100 &
  python tools/fleet_top.py --url http://127.0.0.1:9100
  python tools/fleet_top.py --url http://127.0.0.1:9100 --once  # one frame
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request

__all__ = ["render", "fetch"]

_COLS = ("replica", "role", "state", "depth", "live", "tokens_out",
         "responses", "obs_seq", "stale", "jlag")


def fetch(base_url: str, timeout_s: float = 2.0):
    """(fleet dict, slo verdict dict) from a serve --metrics-port
    endpoint. Raises urllib errors on an unreachable server."""
    out = []
    for path in ("/fleet", "/slo"):
        with urllib.request.urlopen(base_url.rstrip("/") + path,
                                    timeout=timeout_s) as resp:
            out.append(json.loads(resp.read().decode()))
    return out[0], out[1]


def _fmt_stale(v) -> str:
    if v is None:
        return "-"
    return f"{float(v):.2f}s"


def render(fleet, slo, title: str = "fleet_top") -> str:
    """One screen: per-replica table + SLO verdict. ``fleet`` is the
    ``/fleet`` JSON ({replica index -> view dict}); ``slo`` the
    ``/slo`` verdict. Pure — no I/O, no clock."""
    rows = []
    tok_sum = resp_sum = 0
    by_role = {}
    for idx in sorted(fleet, key=lambda k: int(k)):
        v = fleet[idx]
        tok_sum += int(v.get("tokens_out") or 0)
        resp_sum += int(v.get("responses_out") or 0)
        role = str(v.get("role", "mixed"))
        agg = by_role.setdefault(role, {"n": 0, "healthy": 0, "tokens": 0,
                                        "responses": 0})
        agg["n"] += 1
        agg["healthy"] += int(v.get("state") == "healthy")
        agg["tokens"] += int(v.get("tokens_out") or 0)
        agg["responses"] += int(v.get("responses_out") or 0)
        rows.append((str(idx), role, str(v.get("state", "?")),
                     str(v.get("queue_depth", "-")),
                     str(v.get("live_slots", "-")),
                     str(v.get("tokens_out", 0)),
                     str(v.get("responses_out", 0)),
                     "-" if v.get("obs_seq") is None
                     else str(v["obs_seq"]),
                     _fmt_stale(v.get("staleness_s")),
                     # durable-journal lag: seconds since the last
                     # fsync'd lifecycle record; "-" = journal-less
                     _fmt_stale(v.get("journal_lag_s"))))
    widths = [max(len(c), *(len(r[i]) for r in rows)) if rows else len(c)
              for i, c in enumerate(_COLS)]
    ok = bool(slo.get("ok", True))
    lines = [f"{title} — {len(rows)} replica(s)   "
             f"SLO: {'OK' if ok else 'VIOLATED'}",
             "  ".join(c.ljust(w) for c, w in zip(_COLS, widths))]
    for r in rows:
        lines.append("  ".join(x.ljust(w) for x, w in zip(r, widths)))
    lines.append(f"fleet: tokens_out={tok_sum} responses={resp_sum}")
    # role rollup lines only when the fleet is actually disaggregated —
    # an all-mixed fleet would just repeat the totals
    if len(by_role) > 1:
        for role in sorted(by_role):
            agg = by_role[role]
            lines.append(
                f"role {role}: {agg['healthy']}/{agg['n']} healthy  "
                f"tokens_out={agg['tokens']} responses={agg['responses']}")
    obs = slo.get("observed", {})
    if obs:
        lines.append(
            "observed: "
            f"ttft p50 {obs.get('ttft_p50_s', 0):.4f}s "
            f"p99 {obs.get('ttft_p99_s', 0):.4f}s | "
            f"e2e p99 {obs.get('e2e_p99_s', 0):.4f}s | "
            f"goodput {obs.get('goodput', 0):.3f} | "
            f"miss {obs.get('deadline_miss_rate', 0):.3f} | "
            f"shed {obs.get('shed_rate', 0):.3f} | "
            f"delivered {obs.get('delivered', 0)}")
    for v in slo.get("violations", []):
        lines.append(f"VIOLATION {v['slo']}: observed "
                     f"{v['observed']:.4f} vs target {v['target']:.4f}")
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--url", default="http://127.0.0.1:9100",
                    help="base URL of a serve --metrics-port endpoint")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="poll period in seconds")
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit (no screen clear)")
    ap.add_argument("--no-clear", action="store_true",
                    help="append frames instead of redrawing the screen")
    args = ap.parse_args()

    while True:
        try:
            fleet, slo = fetch(args.url)
        except (urllib.error.URLError, OSError, json.JSONDecodeError) as e:
            print(f"fleet_top: {args.url} unreachable: {e}",
                  file=sys.stderr)
            if args.once:
                return 1
            time.sleep(args.interval)
            continue
        frame = render(fleet, slo)
        if args.once:
            print(frame)
            return 0
        if not args.no_clear:
            sys.stdout.write("\x1b[2J\x1b[H")      # clear + home
        print(frame, flush=True)
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
