"""HLO audit of the schedule-table executor (VERDICT r2 #5 / #3).

``python tools/hlo_audit.py [--d=4] [--m=8] [--schedules=1f1b,zb-h1]
[--checkpoint=never] [--d-model=256]``

Compiles one ``ScheduledPipeline.loss_and_grad`` step per schedule on the
virtual cpu8 mesh, then reports per-program:

* ``flops`` — XLA's own cost model (``compiled.cost_analysis()``), the
  decisive number for "does the B/W split execute extra matmul work";
* ``bytes accessed`` — HBM-traffic proxy;
* optimized-HLO op censuses: ``copy`` (conditional-copy tax), ``dot``
  (matmul count), ``while``/``conditional`` structure;
* cycles in the schedule table, so overhead can be attributed per cycle.

Prints one JSON line; also used by docs/architecture.md's overhead table.
"""

from __future__ import annotations

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def audit(n_stages: int = 4, chunks: int = 8, checkpoint: str = "never",
          schedules=("1f1b", "zb-h1"), d_model: int = 256,
          d_ff: int = 512, seq_len: int = 64) -> dict:
    from pipe_tpu.utils.platform import force_cpu_platform
    force_cpu_platform(8)

    import jax
    import jax.numpy as jnp

    from pipe_tpu.core import microbatch as mb
    from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
    from pipe_tpu.parallel.mesh import make_mesh
    from pipe_tpu.parallel.scheduled import ScheduledPipeline
    from pipe_tpu.parallel.spmd import stack_stage_params

    cfg = LMConfig(vocab=512, d_model=d_model, nhead=4, d_ff=d_ff,
                   n_layers=n_stages, seq_len=seq_len, dropout=0.0)
    mesh = make_mesh(n_stages, 1, devices=jax.devices()[:n_stages])
    model = PipelinedLM(cfg, n_stages)
    sp, prep, postp = model.init(jax.random.key(0))
    sp = stack_stage_params(sp)

    m = chunks
    tokens = jax.random.randint(jax.random.key(1), (4 * m, cfg.seq_len),
                                0, cfg.vocab, jnp.int32)
    x, n_rows = mb.stack_scatter(
        {"tokens": tokens, "targets": jnp.roll(tokens, -1, -1)}, m)
    w = mb.valid_row_mask(x, n_rows)

    out = {"platform": "cpu8", "n_stages": n_stages, "chunks": m,
           "checkpoint": checkpoint, "d_model": d_model, "programs": {}}
    for name in schedules:
        pipe = ScheduledPipeline(
            mesh, model.stage_fn, pre_fn=model.pre_fn,
            post_fn=model.loss_post_fn, checkpoint=checkpoint,
            schedule=name)
        lowered = jax.jit(
            lambda s, pipe=pipe: pipe.loss_and_grad(s, prep, postp, x, w)
        ).lower(sp)
        compiled = lowered.compile()
        try:
            ca = compiled.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        except Exception:  # cost model absent on some backends
            ca = {}
        hlo = compiled.as_text()
        census = {}
        for op in ("copy", "dot", "while", "conditional", "fusion",
                   "dynamic-update-slice", "dynamic-slice",
                   "collective-permute", "all-reduce"):
            # op names appear as `%foo.N = <type> op(`; the type may
            # contain spaces/parens (tuples), so anchor on ` op(` instead.
            census[op] = len(re.findall(rf" {op}\(", hlo)) + \
                len(re.findall(rf" {op}-start\(", hlo))
        out["programs"][name] = {
            "cycles": pipe._cycles(m),
            "flops": ca.get("flops"),
            "bytes_accessed": ca.get("bytes accessed"),
            "optimized_hlo_ops": census,
            "hlo_lines": hlo.count("\n"),
        }
    progs = out["programs"]
    if len(progs) == 2:
        a, b = list(progs)
        fa, fb = progs[a].get("flops"), progs[b].get("flops")
        if fa and fb:
            out["flops_ratio"] = round(fb / fa, 4)
    return out


def percycle(checkpoint: str = "except_last", d_model: int = 256,
             d_ff: int = 512, seq_len: int = 64, iters: int = 4) -> dict:
    """Per-cycle cost of each executor variant at IDENTICAL per-op work
    (one transformer layer per virtual stage, same shapes everywhere).

    For each variant, times one compiled step at m=4 and m=8 micro-batches;
    the slope over the known cycle-count delta is the marginal cost of one
    table cycle (op compute + scan/switch/slot machinery + ring hop), and
    comparing variants at the same per-op compute isolates the machinery:

    * ``d1_static``  — trace-time unrolled straight-line program (the
      branch-free baseline: pure op compute);
    * ``d1_dynamic`` — the same table through the dynamic scan (adds
      lax.switch + masked slot writes + carry copies);
    * ``d2``/``d4``  — the dynamic scan on a real stage ring. NOTE: the
      virtual cpu8 mesh serializes all devices onto this host's single
      core, so a cycle's cost is the SUM of active devices' op compute,
      not the max — d>1 slopes carry that serialization and upper-bound
      the real per-cycle machinery.
    """
    from pipe_tpu.utils.platform import force_cpu_platform
    force_cpu_platform(8)

    import time as _time

    import jax
    import jax.numpy as jnp

    from pipe_tpu.core import microbatch as mb
    from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
    from pipe_tpu.parallel.mesh import make_mesh
    from pipe_tpu.parallel.scheduled import ScheduledPipeline
    from pipe_tpu.parallel.spmd import stack_stage_params

    def step_time(pipe, model, sp, prep, postp, cfg, m):
        tokens = jax.random.randint(jax.random.key(1), (4 * m, cfg.seq_len),
                                    0, cfg.vocab, jnp.int32)
        x, n_rows = mb.stack_scatter(
            {"tokens": tokens, "targets": jnp.roll(tokens, -1, -1)}, m)
        w = mb.valid_row_mask(x, n_rows)
        lg = jax.jit(lambda s: pipe.loss_and_grad(s, prep, postp, x, w))
        jax.block_until_ready(lg(sp))
        t0 = _time.perf_counter()
        for _ in range(iters):
            r = lg(sp)
        jax.block_until_ready(r)
        return (_time.perf_counter() - t0) / iters

    out = {"platform": "cpu8", "checkpoint": checkpoint, "d_model": d_model,
           "per_op_work": "1 transformer layer", "variants": {}}
    variants = [("d1_static", 1, True), ("d1_dynamic", 1, False),
                ("d2", 2, None), ("d4", 4, None)]
    for name, d, unroll in variants:
        cfg = LMConfig(vocab=512, d_model=d_model, nhead=4, d_ff=d_ff,
                       n_layers=d, seq_len=seq_len, dropout=0.0)
        mesh = make_mesh(d, 1, devices=jax.devices()[:d])
        model = PipelinedLM(cfg, d)
        sp, prep, postp = model.init(jax.random.key(0))
        sp = stack_stage_params(sp)
        pipe = ScheduledPipeline(
            mesh, model.stage_fn, pre_fn=model.pre_fn,
            post_fn=model.loss_post_fn, checkpoint=checkpoint,
            schedule="1f1b", static_unroll=unroll)
        times, cycles = {}, {}
        for m in (4, 8):
            times[m] = step_time(pipe, model, sp, prep, postp, cfg, m)
            cycles[m] = pipe._cycles(m)
        slope = (times[8] - times[4]) / (cycles[8] - cycles[4])
        out["variants"][name] = {
            "t_m4_sec": round(times[4], 5), "t_m8_sec": round(times[8], 5),
            "cycles_m4": cycles[4], "cycles_m8": cycles[8],
            "per_cycle_ms": round(slope * 1e3, 3),
        }
    base = out["variants"]["d1_static"]["per_cycle_ms"]
    for v in out["variants"].values():
        v["machinery_tax_vs_static"] = round(v["per_cycle_ms"] / base, 3) \
            if base else None
    return out


def _hlo_computations(hlo: str):
    """Split optimized-HLO text into {computation_name: body_text}."""
    comps = {}
    name = None
    depth = 0
    buf: list = []
    for line in hlo.splitlines():
        if depth == 0:
            m = re.match(r"(?:ENTRY\s+)?%?([\w.\-]+)\s*(?:\([^)]*\))?.*\{",
                         line)
            if m:
                name = m.group(1)
                buf = [line]
                depth = line.count("{") - line.count("}")
                if depth == 0:
                    comps[name] = line
                    name = None
            continue
        buf.append(line)
        depth += line.count("{") - line.count("}")
        if depth <= 0 and name is not None:
            comps[name] = "\n".join(buf)
            name = None
            depth = 0
    return comps


def _called(body: str):
    """Computation names a body references (calls, control-flow regions)."""
    out = set()
    for key in ("to_apply", "body", "condition", "true_computation",
                "false_computation", "branch_computations", "calls"):
        for m in re.finditer(rf"{key}=\{{?([^,)\}}]+(?:,\s*[^,)\}}]+)*)\}}?",
                             body):
            for nm in m.group(1).split(","):
                out.add(nm.strip().lstrip("%"))
    return out


def _conditional_census(text: str):
    """Count HLO conditionals by arity. XLA canonicalizes pred-form
    conditionals (``lax.cond``) to 2-branch ``branch_computations={a, b}``,
    so the text key alone cannot separate op DISPATCH (``lax.switch`` —
    one branch per op code, ≥3 for any real table) from the executor's
    2-branch edge-ROLE conds (pre_fn at s==0, loss-seed at is_last,
    except_last's i==m-1). Arity can."""
    dispatch = role = 0
    for m in re.finditer(r"branch_computations=\{([^}]*)\}", text):
        arity = len([b for b in m.group(1).split(",") if b.strip()])
        if arity >= 3:
            dispatch += 1
        else:
            role += 1
    for _ in re.finditer(r"true_computation=", text):
        role += 1
    return dispatch, role


def _region_census(hlo: str, roots):
    """Op census over ``roots`` computations plus everything they call."""
    comps = _hlo_computations(hlo)
    seen = set()
    frontier = [r for r in roots if r in comps]
    while frontier:
        nm = frontier.pop()
        if nm in seen:
            continue
        seen.add(nm)
        frontier.extend(c for c in _called(comps[nm])
                        if c in comps and c not in seen)
    text = "\n".join(comps[nm] for nm in seen)
    dispatch, role = _conditional_census(text)
    return {
        # indexed (≥3-branch) HLO conditional — what lax.switch lowers to:
        # the op-dispatch construct the phase compiler exists to remove
        "dispatch_conditionals": dispatch,
        # 2-branch conditionals: the executor's edge-role conds, reported
        # transparently; they select a role, not an op
        "role_conditionals": role,
        "selects": len(re.findall(r" select\(", text)),
        "whiles": len(re.findall(r" while\(", text)),
    }


def phases(n_stages: int = 4, chunks: int = 8, checkpoint: str = "never",
           schedules=("1f1b", "zb-h1", "zb-h1-split", "gpipe"),
           d_model: int = 64, d_ff: int = 128, seq_len: int = 32) -> dict:
    """Census of the PHASE-COMPILED program vs the interpreted executor.

    For each schedule, compiles one ``loss_and_grad`` step with
    ``phase_compile=True`` and one with ``phase_compile=False`` and reports

    * whole-program dispatch-conditional counts (``branch_computations=``
      in optimized HLO — the indexed conditional ``lax.switch`` lowers
      to). The phased program must have ZERO anywhere;
    * per-while (= per steady-state scan segment) censuses of the phased
      program: zero dispatch conditionals and zero pred conditionals other
      than the executor's edge-role conds, which are listed so the claim
      stays honest ("switch-free" means no op dispatch, not no HLO
      conditional at all);
    * the phase program's segmentation (unrolled vs scan cycles).

    ASSERTS the acceptance invariant (steady-state scan bodies free of
    conditional dispatch) and exits non-zero on violation.
    """
    from pipe_tpu.utils.platform import force_cpu_platform
    force_cpu_platform(8)

    import jax
    import jax.numpy as jnp

    from pipe_tpu.core import microbatch as mb
    from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
    from pipe_tpu.parallel.mesh import make_mesh
    from pipe_tpu.parallel.scheduled import ScheduledPipeline
    from pipe_tpu.parallel.spmd import stack_stage_params

    cfg = LMConfig(vocab=128, d_model=d_model, nhead=4, d_ff=d_ff,
                   n_layers=n_stages, seq_len=seq_len, dropout=0.0)
    mesh = make_mesh(n_stages, 1, devices=jax.devices()[:n_stages])
    model = PipelinedLM(cfg, n_stages)
    sp, prep, postp = model.init(jax.random.key(0))
    sp = stack_stage_params(sp)

    m = chunks
    tokens = jax.random.randint(jax.random.key(1), (4 * m, cfg.seq_len),
                                0, cfg.vocab, jnp.int32)
    x, n_rows = mb.stack_scatter(
        {"tokens": tokens, "targets": jnp.roll(tokens, -1, -1)}, m)
    w = mb.valid_row_mask(x, n_rows)

    out = {"platform": "cpu8", "n_stages": n_stages, "chunks": m,
           "checkpoint": checkpoint, "d_model": d_model, "programs": {}}
    violations = []
    for name in schedules:
        # pseudo-schedule: "<name>-split" = the real schedule with the
        # auto-derived structural B/W split (W ops dispatch through the
        # same phased ramps/steady-state machinery)
        sched_kw = {"schedule": name}
        if name.endswith("-split"):
            sched_kw = {"schedule": name[:-len("-split")],
                        "split_stage": "auto"}
        row = {}
        for mode, phase in (("phased", True), ("interpreted", False)):
            pipe = ScheduledPipeline(
                mesh, model.stage_fn, pre_fn=model.pre_fn,
                post_fn=model.loss_post_fn, checkpoint=checkpoint,
                phase_compile=phase, **sched_kw)
            hlo = jax.jit(
                lambda s, pipe=pipe: pipe.loss_and_grad(s, prep, postp,
                                                        x, w)
            ).lower(sp).compile().as_text()
            comps = _hlo_computations(hlo)
            dispatch, role = _conditional_census(hlo)
            whole = {
                "dispatch_conditionals": dispatch,
                "role_conditionals": role,
                "whiles": len(re.findall(r" while\(", hlo)),
            }
            entry = {"whole_program": whole}
            if phase:
                prog = pipe._phase_program(m)
                entry["segments"] = [
                    (s_.kind, s_.t0, s_.t1, s_.period)
                    for s_ in prog.segments] if prog else None
                entry["scan_cycles"] = prog.scan_cycles if prog else 0
                entry["unrolled_cycles"] = (prog.unrolled_cycles
                                            if prog else 0)
                # every while body in the phased program is a steady-state
                # scan segment (ramps are straight-line)
                bodies = {}
                for comp_name, body in comps.items():
                    for mt in re.finditer(r"body=%?([\w.\-]+)", body):
                        bodies[mt.group(1)] = None
                per_while = {b: _region_census(hlo, [b]) for b in bodies}
                entry["steady_bodies"] = per_while
                bad = [b for b, c in per_while.items()
                       if c["dispatch_conditionals"]]
                if whole["dispatch_conditionals"] or bad:
                    violations.append(
                        f"{name}: dispatch conditional in phased program "
                        f"(whole={whole['dispatch_conditionals']}, "
                        f"bodies={bad})")
                if prog is None:
                    violations.append(
                        f"{name}: phase compiler rejected the table "
                        "(no phased program to audit)")
            row[mode] = entry
        out["programs"][name] = row
    out["violations"] = violations
    out["ok"] = not violations
    return out


def resident(num_slots: int = 2, max_len: int = 16,
             resident_chunks: int = 4, spec_tokens: int = 3,
             d_model: int = 32, d_ff: int = 64, n_layers: int = 4) -> dict:
    """Census of the RESIDENT serve whole-program (PR 11 acceptance pin).

    Lowers every resident decode program — single-device slab/paged,
    each with and without the speculative lane, plus the ring's
    slab/paged revolutions — and censuses its ``while`` bodies (the
    steady-state loop and everything it calls) with the same
    arity-based conditional classifier the phase audit uses.

    The pin: ZERO dispatch conditionals (indexed, >=3-branch — what
    ``lax.switch`` lowers to) anywhere in a steady-state body. The
    paged carry's regather fold is a 2-branch ``lax.cond`` — a role
    conditional, reported transparently; done-masking is pure masked
    arithmetic (selects). ASSERTS the invariant and exits non-zero on
    violation.
    """
    from pipe_tpu.utils.platform import force_cpu_platform
    force_cpu_platform(8)

    import jax
    import jax.numpy as jnp

    from pipe_tpu.inference import GenerationConfig
    from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
    from pipe_tpu.parallel.mesh import make_mesh
    from pipe_tpu.parallel.spmd import stack_stage_params
    from pipe_tpu.serve import BucketSpec, SingleDeviceSlotBackend
    from pipe_tpu.serve.ring import RingSlotBackend

    cfg = LMConfig(vocab=128, d_model=d_model, nhead=4, d_ff=d_ff,
                   n_layers=n_layers, seq_len=2 * max_len, dropout=0.0)
    model = PipelinedLM(cfg, n_stages=2)
    params = model.init(jax.random.key(0))
    gen = GenerationConfig(max_new_tokens=max_len // 2, temperature=0.0,
                           eos_token_id=1)

    def single(layout, spec):
        kw = dict(resident=True, resident_chunks=resident_chunks)
        if spec:
            kw["spec_tokens"] = spec_tokens
        if layout == "paged":
            kw.update(kv_block_size=4, prefill_chunk=4)
        else:
            kw["buckets"] = BucketSpec.of(max_len // 2)
        b = SingleDeviceSlotBackend(model, params, num_slots=num_slots,
                                    max_len=max_len, gen=gen, **kw)
        live = jnp.zeros((num_slots,), bool)
        budget = jnp.full((num_slots,), gen.max_new_tokens, jnp.int32)
        if b.paged:
            args = [b._block_stack, b._pre, b._post, b._pool_kv,
                    jnp.asarray(b.pool.table), b._tok, b._pos,
                    b._key_data, b._views, b._regather]
        else:
            args = [b._block_stack, b._pre, b._post, b._caches, b._tok,
                    b._pos, b._key_data]
        if spec:
            args.append(b._hist)
        args += [live, budget, jnp.int32(resident_chunks)]
        return b._resident_jit.lower(*args).compile().as_text()

    def ring(layout):
        sp, pre, post = params
        mesh = make_mesh(2, 1)
        kw = dict(resident=True, resident_revolutions=resident_chunks)
        if layout == "paged":
            kw.update(kv_block_size=4, prefill_chunk=4)
        else:
            kw["buckets"] = BucketSpec.of(max_len // 2)
        b = RingSlotBackend(mesh, model, stack_stage_params(sp), pre,
                            post, max_len=max_len, gen=gen, **kw)
        kind = "resident_paged" if b.paged else "resident"
        n = b.n
        args = [b._stage_params, b._pre, b._post, b._caches, b._h,
                b._tok_ring, b._pos_local, jnp.int32(0),
                jnp.asarray(b._admit), jnp.zeros((n,), jnp.int32),
                jnp.asarray(b._tok_inject), jnp.asarray(b._plen),
                jnp.asarray(b._key_data)]
        if b.paged:
            args.append(jnp.asarray(b.pool.table))
        args += [jnp.full((n,), gen.max_new_tokens, jnp.int32),
                 jnp.int32(resident_chunks)]
        return b._build(kind).lower(*args).compile().as_text()

    out = {"platform": "cpu8", "num_slots": num_slots,
           "max_len": max_len, "resident_chunks": resident_chunks,
           "spec_tokens": spec_tokens, "programs": {}}
    violations = []
    cases = [("single-slab", lambda: single("slab", False)),
             ("single-paged", lambda: single("paged", False)),
             ("single-slab-spec", lambda: single("slab", True)),
             ("single-paged-spec", lambda: single("paged", True)),
             ("ring-slab", lambda: ring("slab")),
             ("ring-paged", lambda: ring("paged"))]
    for name, build in cases:
        hlo = build()
        comps = _hlo_computations(hlo)
        dispatch, role = _conditional_census(hlo)
        bodies = {}
        for body in comps.values():
            for mt in re.finditer(r"body=%?([\w.\-]+)", body):
                bodies[mt.group(1)] = None
        per_body = {b_: _region_census(hlo, [b_]) for b_ in bodies}
        bad = [b_ for b_, c in per_body.items()
               if c["dispatch_conditionals"]]
        if dispatch or bad:
            violations.append(
                f"{name}: dispatch conditional in resident program "
                f"(whole={dispatch}, bodies={bad})")
        if not bodies:
            violations.append(
                f"{name}: no while body found — resident loop missing?")
        out["programs"][name] = {
            "whole_program": {"dispatch_conditionals": dispatch,
                              "role_conditionals": role,
                              "whiles": len(re.findall(r" while\(",
                                                       hlo))},
            "steady_bodies": per_body,
        }
    out["violations"] = violations
    out["ok"] = not violations
    return out


if __name__ == "__main__":
    kw = {}
    mode = audit
    for a in sys.argv[1:]:
        if a == "--percycle":
            mode = percycle
            continue
        if a == "--phases":
            mode = phases
            continue
        if a == "--resident":
            mode = resident
            continue
        k, v = a.lstrip("-").split("=", 1)
        k = k.replace("-", "_")
        kw[k] = tuple(v.split(",")) if k == "schedules" else (
            v if k == "checkpoint" else int(v))
    res = mode(**kw)
    print(json.dumps(res))
    if mode in (phases, resident) and not res["ok"]:
        sys.exit(1)
