"""Fleet bench: goodput vs replica count + kill-one-of-N failover proof.

Two questions, answered with the tiny LM on whatever backend is
available (the numbers of record are the committed ``FLEET_r10.json``):

1. **Scaling** — saturated fleet goodput (ok tokens/s through the
   Router's exactly-once ledger) at N = 1, 2, 3 replicas. On a real pod
   each replica is its own device and the curve is ~linear; on the CPU
   host the replicas share one processor, so the artifact records the
   honest (flat-ish) curve plus per-N slot counts for context.
2. **Kill one of N** — N = 3 replicas, a ``kill_replica`` chaos fault
   fires mid-stream. The per-delivery timeline is split into
   before/failover/after windows around the kill: goodput must drop by
   <= ~1/N (plus the retried work's lost progress), NOT to zero, and
   recover in the tail as the router re-places the dead replica's
   backlog onto the survivors. The ledger check rides along: every
   submitted request id yields exactly one terminal response.

Usage:
  python tools/fleet_bench.py                 # full run -> FLEET_r10.json
  python tools/fleet_bench.py --quick         # small run, one JSON line
Progress goes to stderr; the last stdout line is always the summary
object, so ``bench.py`` embeds the --quick summary.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

from pipe_tpu.inference import GenerationConfig  # noqa: E402
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM  # noqa: E402
from pipe_tpu.resilience import ChaosPlan, Fault, TickWatchdog  # noqa: E402
from pipe_tpu.serve import (BucketSpec, RequestQueue, Router,  # noqa: E402
                            RouterPolicy, ServeEngine,
                            SingleDeviceSlotBackend)

CFG = LMConfig(vocab=67, d_model=16, nhead=2, d_ff=32, n_layers=4,
               seq_len=64, dropout=0.0)
BUCKETS = BucketSpec.of(8, 16)
MAX_NEW = 32                 # engine cap; per-request budgets vary below
MAX_LEN = BUCKETS.max_len + MAX_NEW
SLOTS = 2
CHUNK = 4


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def make_workload(n, rng):
    """(prompt, max_new) pairs with varied generation lengths, so
    retirements/admissions stagger across ticks and deliveries form a
    continuous stream instead of synchronized waves — the kill trial's
    windowing needs a nonzero pre-kill baseline."""
    lens = rng.choice((6, 8, 12, 16), size=n)
    news = rng.choice((8, 12, 16, 24, 32), size=n)
    return [(rng.randint(1, CFG.vocab, size=int(p)).tolist(), int(m))
            for p, m in zip(lens, news)]


def make_fleet(model, params, n_replicas, *, chaos=None, capacity=256):
    gen_cfg = GenerationConfig(max_new_tokens=MAX_NEW, temperature=0.0)
    engines = []
    for _ in range(n_replicas):
        backend = SingleDeviceSlotBackend(
            model, params, num_slots=SLOTS, max_len=MAX_LEN, gen=gen_cfg,
            buckets=BUCKETS, decode_chunk=CHUNK)
        engines.append(ServeEngine(
            backend, RequestQueue(capacity=capacity),
            watchdog=TickWatchdog(stuck_slack_ticks=None)))
    return Router(engines, RequestQueue(capacity=capacity),
                  policy=RouterPolicy(backoff_base_s=0.0), chaos=chaos)


def warm(router, n_replicas):
    """Compile both prefill buckets + decode on every replica before
    the clock matters (least-loaded placement round-robins equal-load
    replicas, so 2N warm requests touch all of them)."""
    for _ in range(n_replicas):
        router.submit([1] * 8, max_new_tokens=1)
        router.submit([1] * 16, max_new_tokens=1)
    router.run_until_idle()


def timed_run(router, workload):
    """Submit everything, tick to idle, stamp each delivery with the
    router tick index it arrived on. Returns (records, elapsed_s,
    total_ticks) where records are (tick, status, n_tokens). Also runs
    the exactly-once ledger check: every submitted id, one terminal
    response."""
    submitted = [router.submit(p, max_new_tokens=m, seed=i).id
                 for i, (p, m) in enumerate(workload)]
    t0 = time.monotonic()
    records = []
    ticks = 0
    while not router.idle:
        tick = ticks
        ticks += 1
        for r in router.tick():
            records.append((tick, r.status, len(r.tokens)))
    elapsed = time.monotonic() - t0
    missing = [i for i in submitted if router.response(i) is None]
    assert not missing, f"requests with no terminal response: {missing}"
    return records, elapsed, ticks


def tokens_per_tick(records, lo, hi):
    """ok tokens delivered per tick over tick window [lo, hi)."""
    toks = sum(n for t, status, n in records
               if status == "ok" and lo <= t < hi)
    return toks / max(hi - lo, 1)


def scaling_trial(model, params, n_replicas, n_requests, seed):
    rng = np.random.RandomState(seed)
    router = make_fleet(model, params, n_replicas)
    warm(router, n_replicas)
    records, elapsed, ticks = timed_run(router,
                                        make_workload(n_requests, rng))
    ok = sum(1 for _, s, _ in records if s == "ok")
    ok_tokens = sum(n for _, s, n in records if s == "ok")
    return {
        "replicas": n_replicas,
        "slots_total": n_replicas * SLOTS,
        "requests": n_requests,
        "ok": ok,
        "ticks": ticks,
        "elapsed_s": round(elapsed, 3),
        "goodput_tokens_s": round(ok_tokens / max(elapsed, 1e-9), 1),
        "goodput_tokens_per_tick": round(ok_tokens / max(ticks, 1), 2),
    }


def kill_trial(model, params, n_replicas, n_requests, seed, kill_tick,
               window):
    """N replicas, kill one mid-stream; window the delivery timeline
    (in router ticks — tick wall time is roughly constant, and tick
    indexing keeps the windows deterministic) around the kill to show
    degrade-and-recover."""
    rng = np.random.RandomState(seed)
    chaos = ChaosPlan([Fault("kill_replica", step=kill_tick,
                             stage=n_replicas - 1)])
    router = make_fleet(model, params, n_replicas, chaos=chaos)
    warm(router, n_replicas)
    records, elapsed, ticks = timed_run(router,
                                        make_workload(n_requests, rng))
    assert ticks > kill_tick + window, (
        f"run finished in {ticks} ticks; needs > "
        f"{kill_tick + window} — raise the load")
    before = tokens_per_tick(records, max(kill_tick - window, 0),
                             kill_tick)
    during = tokens_per_tick(records, kill_tick, kill_tick + window)
    after = tokens_per_tick(records, kill_tick + window, ticks)
    by_status = {}
    for _, s, _ in records:
        by_status[s] = by_status.get(s, 0) + 1
    return {
        "replicas": n_replicas,
        "killed_replica": n_replicas - 1,
        "kill_tick": kill_tick,
        "window_ticks": window,
        "requests": n_requests,
        "ticks": ticks,
        "elapsed_s": round(elapsed, 3),
        "tokens_per_tick_before": round(before, 2),
        "tokens_per_tick_failover": round(during, 2),
        "tokens_per_tick_after": round(after, 2),
        "drop_frac": round(1.0 - during / max(before, 1e-9), 3),
        "recovered_frac": round(after / max(before, 1e-9), 3),
        "survived_failover": during > 0.0,
        "responses_by_status": by_status,
        "exactly_once": len(records) == n_requests,
        "replica_states": router.counts(),
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="small run; single-line JSON summary")
    ap.add_argument("--out", default=None,
                    help="also write the summary JSON here")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    t0 = time.perf_counter()
    model = PipelinedLM(CFG, 1)
    params = model.init(jax.random.key(0))

    n_requests = 24 if args.quick else 48
    replica_counts = (1, 3) if args.quick else (1, 2, 3)

    scaling = []
    for n in replica_counts:
        log(f"== scaling: {n} replica(s), {n_requests} requests")
        r = scaling_trial(model, params, n, n_requests, args.seed)
        scaling.append(r)
        log(f"   {r}")

    log("== kill one of 3 mid-stream")
    kill = kill_trial(model, params, 3, n_requests * 2, args.seed + 1,
                      kill_tick=6, window=4)
    log(f"   {kill}")

    ok = bool(kill["exactly_once"] and kill["survived_failover"]
              and kill["recovered_frac"] > 0.3)
    summary = {
        "bench": "fleet", "rev": "r10",
        "quick": bool(args.quick),
        "platform": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "slots_per_replica": SLOTS,
        "decode_chunk": CHUNK,
        "max_new_tokens": MAX_NEW,
        "scaling": scaling,
        "kill_one_of_n": kill,
        "fleet_ok": ok,
        "wall_s": round(time.perf_counter() - t0, 1),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=2)
        log(f"wrote {args.out}")
    if args.quick:
        print(json.dumps({
            "goodput_1_replica_tokens_s":
                scaling[0]["goodput_tokens_s"],
            "goodput_3_replicas_tokens_s":
                scaling[-1]["goodput_tokens_s"],
            "kill_drop_frac": kill["drop_frac"],
            "kill_recovered_frac": kill["recovered_frac"],
            "exactly_once": kill["exactly_once"],
            "fleet_ok": ok,
        }))
    else:
        print(json.dumps(summary, indent=2))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
