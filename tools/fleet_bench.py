"""Fleet bench: goodput, failover, async ticks, KV handoff, disagg.

Nine questions, answered with the tiny LM on whatever backend is
available (the numbers of record are the committed ``FLEET_r20.json``):

1. **Scaling** — saturated fleet goodput (ok tokens/s through the
   controller's exactly-once ledger) at N = 1, 2, 3 replicas, over the
   transport picked by ``--fleet``: same-process engines ticked
   serially (``inproc``), same-process engines each under a tick
   thread (``thread``), or one OS process per replica (``proc``, the
   :mod:`pipe_tpu.fleet.proc` socket transport). On a real pod each
   replica is its own device and the curve is ~linear; on the CPU host
   the replicas share one processor, so the artifact records the
   honest (flat-ish) curve plus per-N slot counts for context.
2. **Kill one of N** — N = 3 replicas, one dies mid-stream. In-process
   fleets inject a ``kill_replica`` chaos fault; the ``proc`` fleet
   kills the actual OS process (SIGKILL, no goodbye) and recovery runs
   through heartbeat loss + TransportError reclaim. Either way the
   delivery timeline is windowed before/failover/after: goodput must
   drop, NOT to zero, and recover as the controller re-places the dead
   replica's backlog onto the survivors — and every submitted id still
   yields exactly one terminal response.
3. **Async ticks vs serial** — N = 3 in-process replicas, one of them
   a deliberate straggler (decode sleeps). Serial router ticks pay the
   straggler's stall on EVERY fleet tick; per-replica tick threads
   confine it to its own replica. The bench asserts threaded goodput
   >= serial goodput — the claim ``async_tick`` exists to make.
4. **KV handoff TTFT** — a session remapped off its home replica
   either ships its cached prefix blocks to the new home
   (:meth:`FleetController._kv_handoff`) or re-prefills from scratch
   (export disabled). Measures TTFT of the first post-remap request
   both ways; the win is the prefill work the shipped blocks saved.
   The summary's ``handoff_beats_reprefill`` flag IS the disagg
   pipeline's entry fee: shipping a prefix must be cheaper than
   recomputing it, every round.
5. **Disagg vs mixed at equal chips** — 2 phase-specialized replicas
   (one prefill-only, one decode-only, KV shipped between them by
   :class:`~pipe_tpu.fleet.disagg.DisaggController`) against 2 mixed
   replicas, same slots, under a prefill-heavy deadlined workload.
   The metric is deadline goodput: ok tokens/s where ok means the
   request finished inside its ``timeout_s``. A mixed replica's tick
   interleaves multi-chunk host-blocking prefills with its decode
   chunks, so decode latency inherits the prefill burst variance and
   deadlines blow; the disagg decode replica's ticks hold only cheap
   cached-prefix resumes and decode chunks. Both arms run per-replica
   tick threads (the isolation async_tick exists to provide).
6. **Disagg SIGKILL drills** — 4 real child processes (2 prefill +
   2 decode), kill one PREFILL replica mid-stream, then (fresh fleet)
   one DECODE replica. Either death lands mid-handoff for some
   requests; the surviving role sibling absorbs the stream through
   the one park-or-finish reclaim gate and every submitted id still
   yields exactly one terminal — the exactly-once ledger, across the
   phase boundary.
7. **Wire chaos drills** — adversarial faults at the proc framing
   layer (:func:`pipe_tpu.fleet.proc.apply_wire_chaos`). A 2 s
   ``wire_partition`` on one replica's wire must heal losslessly: the
   child's re-dial lands in the listener's kernel backlog, retained
   response frames replay, the parent's sequence dedup swallows the
   duplicates — every id exactly one terminal. A ``wire_corrupt``
   storm (15 consecutive parent->child frames) must never half-parse:
   each bad frame is a CRC reject + connection drop + re-dial +
   replay, and the drill asserts the reject counters actually fired.
8. **Controller SIGKILL + restart** — the round-20 tentpole. The
   controller runs in a SEPARATE process (hidden ``--_ctl-worker``
   mode of this script) journaling every lifecycle transition to a
   :class:`~pipe_tpu.fleet.journal.RequestJournal`; the bench SIGKILLs
   it mid-stream (no goodbye, fsync'd WAL is all that survives), then
   replays the journal, re-dials the orphaned children in rejoin mode
   and rebuilds the controller with
   :meth:`~pipe_tpu.fleet.control.FleetController.from_journal`.
   Run twice — a mixed 3-replica fleet and a 2 prefill + 2 decode
   disagg fleet — and both times every submitted id must end with
   exactly one terminal response across the two controller lives.
9. **Saturation sweep** — steady-state goodput at N = 1..K replicas
   over the chosen transport; reports the front-queue bottleneck N
   (the smallest fleet within 10% of the sweep's best goodput) —
   past it, added replicas buy nothing because the shared host / the
   single front queue is the limit, not replica count.

The kill trials also exercise the fleet observability plane
(docs/observability.md, "Fleet observability"): the controller runs
under a :class:`~pipe_tpu.obs.fleet_obs.TraceBuffer` event log and a
:class:`~pipe_tpu.obs.fleet_obs.FleetObserver`, and the summary stamps
the delivered-token reconciliation (per-replica delivery-synchronized
token counters must sum to the parent ledger's delivered total — across
the SIGKILL), per-replica metric staleness, the SLO verdict over the
merged rollup, and trace-stitch stats: every submitted id must
reconstruct into exactly one stitched timeline, failed-over ids showing
both placements in one trace. ``bench.py --quick`` asserts those.

Every summary stamps host contention (1-min load average vs CPU count):
on a contended host the absolute numbers are noise — the flag says so
instead of letting the artifact lie.

Usage:
  python tools/fleet_bench.py                 # full run -> FLEET_r20.json
  python tools/fleet_bench.py --quick --fleet proc   # bench.py embed
Progress goes to stderr; the last stdout line is always the summary
object, so ``bench.py`` embeds the --quick summary.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import queue as queue_mod
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

from pipe_tpu.fleet import (DisaggController, FleetController,  # noqa: E402
                            InProcessTransport, ProcessReplicaTransport,
                            ReplicaSpec, RequestJournal)
from pipe_tpu.inference import GenerationConfig  # noqa: E402
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM  # noqa: E402
from pipe_tpu.obs.fleet_obs import (FleetObserver, SloMonitor,  # noqa: E402
                                    SloTargets, TraceBuffer)
from pipe_tpu.obs.telemetry import get_registry  # noqa: E402
from pipe_tpu.resilience import ChaosPlan, Fault, TickWatchdog  # noqa: E402
from pipe_tpu.serve import (BucketSpec, RequestQueue, Router,  # noqa: E402
                            RouterPolicy, ServeEngine,
                            SingleDeviceSlotBackend)

CFG = LMConfig(vocab=67, d_model=16, nhead=2, d_ff=32, n_layers=4,
               seq_len=64, dropout=0.0)
BUCKETS = BucketSpec.of(8, 16)
MAX_NEW = 32                 # engine cap; per-request budgets vary below
MAX_LEN = BUCKETS.max_len + MAX_NEW
SLOTS = 2
CHUNK = 4


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def host_contention():
    """1-min load average vs CPU count: above ~75% the host is fighting
    itself and wall-clock goodput numbers are noise."""
    try:
        load1 = os.getloadavg()[0]
    except OSError:                               # pragma: no cover
        return {"host_load1": None, "cpu_count": os.cpu_count() or 1,
                "contended": False}
    cpus = os.cpu_count() or 1
    return {"host_load1": round(load1, 2), "cpu_count": cpus,
            "contended": bool(load1 > 0.75 * cpus)}


def make_workload(n, rng):
    """(prompt, max_new) pairs with varied generation lengths, so
    retirements/admissions stagger across ticks and deliveries form a
    continuous stream instead of synchronized waves — the kill trial's
    windowing needs a nonzero pre-kill baseline."""
    lens = rng.choice((6, 8, 12, 16), size=n)
    news = rng.choice((8, 12, 16, 24, 32), size=n)
    return [(rng.randint(1, CFG.vocab, size=int(p)).tolist(), int(m))
            for p, m in zip(lens, news)]


def proc_spec():
    return ReplicaSpec(
        lm_cfg=dict(vocab=CFG.vocab, d_model=CFG.d_model, nhead=CFG.nhead,
                    d_ff=CFG.d_ff, n_layers=CFG.n_layers,
                    seq_len=CFG.seq_len, dropout=0.0),
        n_stages=1, init_seed=0, num_slots=SLOTS, max_len=MAX_LEN,
        gen=dict(max_new_tokens=MAX_NEW, temperature=0.0),
        buckets=list(BUCKETS.lengths), decode_chunk=CHUNK,
        heartbeat_interval_s=0.05)


def make_fleet(model, params, n_replicas, *, fleet="inproc", chaos=None,
               capacity=256, event_log=None):
    if fleet == "proc":
        transports = [ProcessReplicaTransport(proc_spec())
                      for _ in range(n_replicas)]
        return FleetController(
            transports, RequestQueue(capacity=capacity),
            policy=RouterPolicy(backoff_base_s=0.0,
                                heartbeat_timeout_s=5.0),
            event_log=event_log)
    gen_cfg = GenerationConfig(max_new_tokens=MAX_NEW, temperature=0.0)
    engines = []
    for _ in range(n_replicas):
        backend = SingleDeviceSlotBackend(
            model, params, num_slots=SLOTS, max_len=MAX_LEN, gen=gen_cfg,
            buckets=BUCKETS, decode_chunk=CHUNK)
        engines.append(ServeEngine(
            backend, RequestQueue(capacity=capacity),
            watchdog=TickWatchdog(stuck_slack_ticks=None)))
    return Router(engines, RequestQueue(capacity=capacity),
                  policy=RouterPolicy(backoff_base_s=0.0), chaos=chaos,
                  async_tick=(fleet == "thread"), event_log=event_log)


def warm(router, n_replicas):
    """Compile both prefill buckets + CHUNKED decode on every replica
    before the clock matters (least-loaded placement round-robins
    equal-load replicas, so 2N warm requests touch all of them;
    max_new > decode_chunk so the chunked decode graph compiles here,
    not inside a measured window)."""
    for _ in range(n_replicas):
        router.submit([1] * 8, max_new_tokens=2 * CHUNK)
        router.submit([1] * 16, max_new_tokens=2 * CHUNK)
    run_to_idle(router)


def run_to_idle(router, pace_s=0.01, timeout_s=600.0):
    deadline = time.monotonic() + timeout_s
    while not router.idle:
        router.tick()
        if pace_s:
            time.sleep(pace_s)
        assert time.monotonic() < deadline, "fleet never went idle"


def timed_run(router, workload, pace_s=0.0, on_tick=None):
    """Submit everything, tick to idle, stamp each delivery with the
    router tick index AND wall offset it arrived at. Returns (records,
    elapsed_s, total_ticks) where records are (tick, status, n_tokens,
    t_s). Also runs the exactly-once ledger check: every submitted id,
    one terminal response. ``pace_s`` throttles the sweep loop for
    self-ticking (thread/proc) replicas; ``on_tick(tick, router)`` is
    the chaos hook for trials that act mid-stream (e.g. kill a child
    process)."""
    submitted = [router.submit(p, max_new_tokens=m, seed=i).id
                 for i, (p, m) in enumerate(workload)]
    t0 = time.monotonic()
    records = []
    ticks = 0
    while not router.idle:
        tick = ticks
        ticks += 1
        if on_tick is not None:
            on_tick(tick, router, records)
        for r in router.tick():
            records.append((tick, r.status, len(r.tokens),
                            time.monotonic() - t0))
        if pace_s:
            time.sleep(pace_s)
        assert time.monotonic() - t0 < 600.0, "trial never went idle"
    elapsed = time.monotonic() - t0
    missing = [i for i in submitted if router.response(i) is None]
    assert not missing, f"requests with no terminal response: {missing}"
    return records, elapsed, ticks, submitted


def tokens_per_tick(records, lo, hi):
    """ok tokens delivered per tick over tick window [lo, hi)."""
    toks = sum(n for t, status, n, _ in records
               if status == "ok" and lo <= t < hi)
    return toks / max(hi - lo, 1)


def tokens_per_sec(records, lo_s, hi_s):
    """ok tokens delivered per second over wall window [lo_s, hi_s)."""
    toks = sum(n for _, status, n, t in records
               if status == "ok" and lo_s <= t < hi_s)
    return toks / max(hi_s - lo_s, 1e-9)


def ok_tokens(records):
    return sum(n for _, s, n, _ in records if s == "ok")


def obs_report(observer, submitted):
    """Observability-plane stamp for a kill trial: the delivered-token
    reconciliation, per-replica metric staleness, the SLO verdict over
    the merged fleet rollup, and trace-stitch stats — every submitted
    id must reconstruct into EXACTLY one stitched timeline (trace ids
    are minted once and survive failover), and failed-over ids must
    show both placements in one trace. Call AFTER router.close(): the
    proc children ship their final obs deltas on the shutdown RPC, and
    everything read here is parent-side state that survives them."""
    reconcile = observer.reconcile()
    per = observer.per_replica()
    stitched = observer.stitch_by_request()
    owners = {}
    for key, recs in observer.stitch().items():
        for r in recs:
            if r.get("request") is not None:
                owners.setdefault(int(r["request"]), set()).add(key)
    have = [i for i in submitted if i in stitched]
    exactly_once = all(len(owners.get(i, ())) == 1 for i in submitted)
    failed_over = sum(
        1 for i in submitted
        if len({r.get("attempts") for r in stitched.get(i, [])
                if r.get("stage") == "placed"}) >= 2)
    verdict = SloMonitor(SloTargets(goodput_min=0.5)).verdict(
        observer.rollup())
    return {
        "reconcile": reconcile,
        "staleness_s": {str(i): (None if v["staleness_s"] is None
                                 else round(v["staleness_s"], 3))
                        for i, v in per.items()},
        "trace_stitch": {
            "submitted": len(submitted),
            "stitched": len(have),
            "frac": round(len(have) / max(len(submitted), 1), 4),
            "exactly_once": bool(exactly_once),
            "failed_over_with_both_placements": failed_over,
        },
        "slo": verdict,
    }


def scaling_trial(model, params, n_replicas, n_requests, seed, fleet):
    rng = np.random.RandomState(seed)
    router = make_fleet(model, params, n_replicas, fleet=fleet)
    try:
        warm(router, n_replicas)
        records, elapsed, ticks, _ = timed_run(
            router, make_workload(n_requests, rng),
            pace_s=0.01 if fleet != "inproc" else 0.0)
    finally:
        router.close()
    ok = sum(1 for _, s, _, _ in records if s == "ok")
    return {
        "replicas": n_replicas,
        "transport": fleet,
        "slots_total": n_replicas * SLOTS,
        "requests": n_requests,
        "ok": ok,
        "ticks": ticks,
        "elapsed_s": round(elapsed, 3),
        "goodput_tokens_s": round(ok_tokens(records) / max(elapsed, 1e-9),
                                  1),
        "goodput_tokens_per_tick": round(
            ok_tokens(records) / max(ticks, 1), 2),
    }


def kill_trial(model, params, n_replicas, n_requests, seed, kill_tick,
               window, fleet):
    """N replicas, kill one mid-stream; window the delivery timeline
    around the kill to show degrade-and-recover. In-process fleets
    kill via the chaos plan at a router tick (tick wall time is
    roughly constant, so tick windows are deterministic). The proc
    fleet SIGKILLs the real child process and windows on SECONDS
    under a trickle-fed steady-state load: submitting the whole
    stream up front would make the first parent tick one giant
    placement-RPC burst and cluster every delivery at the end, so the
    feed keeps a bounded number of requests outstanding and the
    delivery timeline stays continuous through the kill."""
    rng = np.random.RandomState(seed)
    if fleet == "proc":
        return _kill_trial_proc(n_replicas, rng)
    chaos = ChaosPlan([Fault("kill_replica", step=kill_tick,
                             stage=n_replicas - 1)])
    trace_buf = TraceBuffer(maxlen=200_000)
    router = make_fleet(model, params, n_replicas, fleet=fleet,
                        chaos=chaos, event_log=trace_buf)
    try:
        warm(router, n_replicas)
        records, elapsed, ticks, submitted = timed_run(
            router, make_workload(n_requests, rng),
            pace_s=0.01 if fleet != "inproc" else 0.0)
        states = router.counts()
    finally:
        router.close()
    obs = obs_report(FleetObserver(router,
                                   parent_events=trace_buf.drain()),
                     submitted)
    assert ticks > kill_tick + window, (
        f"run finished in {ticks} ticks; needs > "
        f"{kill_tick + window} — raise the load")
    before = tokens_per_tick(records, max(kill_tick - window, 0),
                             kill_tick)
    during = tokens_per_tick(records, kill_tick, kill_tick + window)
    after = tokens_per_tick(records, kill_tick + window, ticks)
    by_status = {}
    for _, s, _, _ in records:
        by_status[s] = by_status.get(s, 0) + 1
    return {
        "replicas": n_replicas,
        "transport": fleet,
        "killed_replica": n_replicas - 1,
        "kill_mode": "chaos_fault",
        "kill_at": kill_tick,
        "window": window,
        "rate_unit": "tokens/tick",
        "requests": n_requests,
        "ticks": ticks,
        "elapsed_s": round(elapsed, 3),
        "rate_before": round(before, 2),
        "rate_failover": round(during, 2),
        "rate_after": round(after, 2),
        "drop_frac": round(1.0 - during / max(before, 1e-9), 3),
        "recovered_frac": round(after / max(before, 1e-9), 3),
        "survived_failover": during > 0.0 or after > 0.0,
        "responses_by_status": by_status,
        "exactly_once": len(records) == n_requests,
        "replica_states": states,
        "obs": obs,
    }


def _kill_trial_proc(n_replicas, rng, kill_after_s=2.0, duration_s=6.0,
                     max_outstanding=9):
    """SIGKILL one of N real child processes mid-stream. Steady-state
    feed: keep ``max_outstanding`` requests in flight, kill the last
    replica at ``kill_after_s``, keep feeding, then drain. Goodput in
    1 s windows before/during/after the kill shows the degrade (one
    replica's work vanishes and its in-flight set pays a retry) and
    the recovery (survivors absorb the stream)."""
    trace_buf = TraceBuffer(maxlen=200_000)
    router = make_fleet(None, None, n_replicas, fleet="proc",
                        event_log=trace_buf)
    # oversized pool: the feed must NOT run dry inside the measured
    # windows (a drained feed deflates the post-kill rate and reads as
    # a failed recovery)
    work = make_workload(4096, rng)
    submitted, records = [], []
    kill_t = None
    try:
        warm(router, n_replicas)
        t0 = time.monotonic()
        i = 0
        while time.monotonic() - t0 < duration_s:
            now = time.monotonic() - t0
            while len(submitted) - len(records) < max_outstanding \
                    and i < len(work):
                p, m = work[i]
                submitted.append(router.submit(
                    p, max_new_tokens=m, seed=i).id)
                i += 1
            if kill_t is None and now >= kill_after_s:
                router.replicas[n_replicas - 1].transport._proc.kill()
                kill_t = now
            for r in router.tick():
                records.append((0, r.status, len(r.tokens),
                                time.monotonic() - t0))
            time.sleep(0.005)
        deadline = time.monotonic() + 120.0
        while not router.idle:
            for r in router.tick():
                records.append((0, r.status, len(r.tokens),
                                time.monotonic() - t0))
            time.sleep(0.005)
            assert time.monotonic() < deadline, "drain never finished"
        elapsed = time.monotonic() - t0
        states = router.counts()
        missing = [x for x in submitted if router.response(x) is None]
        assert not missing, f"requests with no terminal: {missing}"
    finally:
        router.close()
    obs = obs_report(FleetObserver(router,
                                   parent_events=trace_buf.drain()),
                     submitted)
    assert kill_t is not None, "run too short to reach the kill point"
    w = min(1.0, kill_t, (elapsed - kill_t) / 2)
    before = tokens_per_sec(records, kill_t - w, kill_t)
    during = tokens_per_sec(records, kill_t, kill_t + w)
    after = tokens_per_sec(records, kill_t + w, elapsed)
    by_status = {}
    for _, s, _, _ in records:
        by_status[s] = by_status.get(s, 0) + 1
    return {
        "replicas": n_replicas,
        "transport": "proc",
        "killed_replica": n_replicas - 1,
        "kill_mode": "sigkill_process",
        "kill_at": round(kill_t, 3),
        "window": round(w, 3),
        "rate_unit": "tokens/s",
        "requests": len(submitted),
        "ticks": 0,
        "elapsed_s": round(elapsed, 3),
        "rate_before": round(before, 2),
        "rate_failover": round(during, 2),
        "rate_after": round(after, 2),
        "drop_frac": round(1.0 - during / max(before, 1e-9), 3),
        "recovered_frac": round(after / max(before, 1e-9), 3),
        "survived_failover": during > 0.0 or after > 0.0,
        "responses_by_status": by_status,
        "exactly_once": len(records) == len(submitted),
        "replica_states": states,
        "obs": obs,
    }


def straggler_trial(model, params, n_requests, seed, sleep_s=0.05,
                    duration_s=4.0):
    """N=3, replica 2 a straggler (decode sleeps ``sleep_s``): serial
    router ticks pay the sleep inline on EVERY fleet tick — nothing
    else decodes while the straggler naps; per-replica tick threads
    confine it to its own replica. Measured as steady-state goodput
    over a fixed wall-clock window with the front queue kept fed (a
    fixed-size workload would let the straggler's own tail dominate
    both arms and hide the siblings' win). Asserts threaded goodput
    >= serial goodput — the claim ``async_tick`` exists to make."""
    out = {}
    for mode in ("serial", "thread"):
        rng = np.random.RandomState(seed)
        router = make_fleet(model, params, 3,
                            fleet="thread" if mode == "thread"
                            else "inproc")
        try:
            warm(router, 3)
            backend = router.replicas[2].engine.backend
            orig = backend.decode

            def slow_decode(live, _orig=orig):
                time.sleep(sleep_s)
                return _orig(live)

            backend.decode = slow_decode
            pace = 0.01 if mode == "thread" else 0.0
            feed = iter(range(10_000))
            t0 = time.monotonic()
            deadline = t0 + duration_s
            tokens = finished = 0
            while time.monotonic() < deadline:
                while self_depth(router) < 6:     # keep the fleet fed
                    i = next(feed)
                    p, m = make_workload(1, rng)[0]
                    router.submit(p, max_new_tokens=m, seed=i)
                for r in router.tick():
                    if r.status == "ok":
                        tokens += len(r.tokens)
                        finished += 1
                if pace:
                    time.sleep(pace)
            elapsed = time.monotonic() - t0
            run_to_idle(router)                   # flush the remainder
        finally:
            router.close()
        out[mode] = {
            "window_s": round(elapsed, 3),
            "ok": finished,
            "ok_tokens": tokens,
            "goodput_tokens_s": round(tokens / max(elapsed, 1e-9), 1),
        }
    serial = out["serial"]["goodput_tokens_s"]
    threaded = out["thread"]["goodput_tokens_s"]
    out["straggler_sleep_s"] = sleep_s
    out["speedup"] = round(threaded / max(serial, 1e-9), 2)
    out["async_beats_serial"] = bool(threaded >= serial)
    assert threaded >= serial, (
        f"async ticks lost to serial under a straggler: "
        f"{threaded} < {serial} tokens/s")
    return out


def self_depth(router):
    """Outstanding work visible to the feeder: front depth plus every
    replica's queued+live share."""
    return router.queue.depth + sum(
        rep.transport.queue_depth + rep.transport.live_slots
        for rep in router.replicas if rep.state != "retired")


def handoff_trial(repeats=3):
    """Session remap TTFT, handoff vs re-prefill. Two paged replicas;
    a session decodes on its home (caching its prefix blocks), the
    home is marked suspect, and the next session request remaps. With
    KV handoff the destination imports the cached blocks and prefill
    skips them; with export disabled it re-prefills the whole prompt.

    Uses its own model config (wider + longer context than the fleet
    CFG): the win IS the prefill work saved, so the prompt has to be
    long enough that prefill costs more than shipping its blocks —
    48 tokens of a 16-wide model re-prefill in ~7ms, which any
    handoff overhead eats. Repeats each arm with a fresh fleet and
    takes the min TTFT (min is robust against scheduler noise on a
    shared host)."""
    hcfg = LMConfig(vocab=67, d_model=32, nhead=2, d_ff=64,
                    n_layers=4, seq_len=160, dropout=0.0)
    model = PipelinedLM(hcfg, 1)
    params = model.init(jax.random.key(5))
    gen_cfg = GenerationConfig(max_new_tokens=4, temperature=0.0)
    rng = np.random.RandomState(3)
    prompt = list(rng.randint(1, hcfg.vocab, size=144))  # 18 blocks

    def fleet():
        def engine():
            be = SingleDeviceSlotBackend(
                model, params, num_slots=SLOTS, max_len=160,
                gen=gen_cfg, kv_block_size=8, kv_pool_blocks=60,
                prefill_chunk=8)
            eng = ServeEngine(be, RequestQueue())
            # compile EVERY prefill path this replica will run before
            # anything is timed — including the resume-from-cached-
            # prefix program (a different trace than full prefill: the
            # remapped request must measure prefill work saved, not a
            # cold jit cache on the destination). A throwaway prompt,
            # served twice: full prefill, then the cached-prefix resume.
            warm_p = list(rng.randint(1, hcfg.vocab, size=144))
            for _ in range(2):
                eng.submit(warm_p, max_new_tokens=4, seed=9)
                eng.run_until_idle()
            return eng
        return Router([engine(), engine()], RequestQueue(),
                      policy=RouterPolicy(placement="session"))

    def serve_one(router):
        rid = router.submit(prompt, max_new_tokens=4, seed=0,
                            session="alice").id
        for _ in range(10000):
            router.tick()
            resp = router.response(rid)
            if resp is not None:
                assert resp.status == "ok", resp
                return resp
        raise AssertionError("request never finished")

    reg = get_registry()
    ttfts = {"handoff": [], "reprefill": []}
    shipped0 = reg.counter("serve.fleet.kv_handoff_shipped").value
    bytes0 = reg.counter("serve.fleet.kv_handoff_bytes").value
    for arm in ("handoff", "reprefill"):
        for _ in range(repeats):
            router = fleet()
            serve_one(router)                      # warm the home + jit
            serve_one(router)                      # steady-state TTFT
            if arm == "reprefill":
                for rep in router.replicas:        # sever the handoff
                    rep.transport.export_prefix = lambda prompt: None
            home = router._session_map["alice"]
            router.replicas[home].state = "suspect"
            resp = serve_one(router)               # remapped request
            ttfts[arm].append(resp.ttft)
            router.close()
    shipped = reg.counter("serve.fleet.kv_handoff_shipped").value \
        - shipped0
    nbytes = reg.counter("serve.fleet.kv_handoff_bytes").value - bytes0
    t_hand = min(ttfts["handoff"])
    t_cold = min(ttfts["reprefill"])
    return {
        "prompt_len": len(prompt),
        "kv_block_size": 8,
        "repeats": repeats,
        "blocks_shipped": int(shipped),
        "handoff_bytes": int(nbytes),
        "ttft_handoff_s": round(t_hand, 4),
        "ttft_reprefill_s": round(t_cold, 4),
        "ttft_win_s": round(t_cold - t_hand, 4),
        "ttft_all_handoff_s": [round(t, 4) for t in ttfts["handoff"]],
        "ttft_all_reprefill_s": [round(t, 4)
                                 for t in ttfts["reprefill"]],
        "handoff_moved_blocks": bool(shipped > 0),
    }


def prefix_placement_trial(repeats=3):
    """Gen-2 KV-aware placement: one replica holds a session's prefix
    blocks; ``placement="prefix"`` scores candidates by matched depth x
    occupancy headroom and lands the request there, vs least-loaded
    which (ties by index) sends it to the COLD replica. The TTFT gap is
    the prefill work the directory lookup saved. Plus the proactive
    arm: two concurrent sessions push a chain's refcount to the
    ``kv_hot_refs`` threshold and the controller replicates it to the
    idle sibling ahead of any remap."""
    hcfg = LMConfig(vocab=67, d_model=32, nhead=2, d_ff=64,
                    n_layers=4, seq_len=160, dropout=0.0)
    model = PipelinedLM(hcfg, 1)
    params = model.init(jax.random.key(6))
    gen_cfg = GenerationConfig(max_new_tokens=4, temperature=0.0)
    rng = np.random.RandomState(7)
    shared = list(rng.randint(1, hcfg.vocab, size=136))  # 17 blocks

    def engine():
        be = SingleDeviceSlotBackend(
            model, params, num_slots=SLOTS, max_len=160,
            gen=gen_cfg, kv_block_size=8, kv_pool_blocks=60,
            prefill_chunk=8)
        eng = ServeEngine(be, RequestQueue())
        warm_p = list(rng.randint(1, hcfg.vocab, size=144))
        for _ in range(2):                  # jit full + resume prefill
            eng.submit(warm_p, max_new_tokens=4, seed=9)
            eng.run_until_idle()
        return eng

    def fleet(policy):
        engines = [engine(), engine()]
        # replica 1 is the warm home: its pool already holds the
        # shared chain (least-loaded ties break toward replica 0)
        engines[1].submit(shared + [7], max_new_tokens=4, seed=0)
        engines[1].run_until_idle()
        return Router(engines, RequestQueue(), policy=policy)

    def serve_one(router, prompt):
        rid = router.submit(prompt, max_new_tokens=4, seed=0).id
        for _ in range(10000):
            router.tick()
            resp = router.response(rid)
            if resp is not None:
                assert resp.status == "ok", resp
                return resp
        raise AssertionError("request never finished")

    reg = get_registry()
    p0 = reg.counter("serve.fleet.prefix_placements").value
    ttfts = {"prefix": [], "least_loaded": []}
    for arm in ttfts:
        for i in range(repeats):
            router = fleet(RouterPolicy(placement=arm))
            resp = serve_one(router, shared + [11, 13 + i])
            ttfts[arm].append(resp.ttft)
            router.close()
    placements = reg.counter("serve.fleet.prefix_placements").value - p0

    # proactive replication: both sessions live on replica 0 push the
    # shared chain to refs=2; the controller ships it to replica 1
    rep0 = reg.counter("serve.fleet.kv_replicated").value
    router = Router(
        [engine(), engine()], RequestQueue(),
        policy=RouterPolicy(placement="prefix", kv_hot_refs=2))
    hot = list(rng.randint(1, hcfg.vocab, size=64))      # 8 blocks
    ra = router.submit(hot + [3], max_new_tokens=4, seed=0).id
    router.tick()
    rb = router.submit(hot + [5], max_new_tokens=4, seed=0).id
    for _ in range(10000):
        router.tick()
        if all(router.response(r) is not None for r in (ra, rb)):
            break
    replicated = reg.counter("serve.fleet.kv_replicated").value - rep0
    sibling_warm = router.replicas[1].transport.engine.backend.pool \
        .cached_prefix_blocks(hot)
    router.close()

    t_pre = min(ttfts["prefix"])
    t_ll = min(ttfts["least_loaded"])
    return {
        "prompt_len": len(shared) + 2,
        "kv_block_size": 8,
        "repeats": repeats,
        "prefix_placements": int(placements),
        "ttft_prefix_s": round(t_pre, 4),
        "ttft_least_loaded_s": round(t_ll, 4),
        "ttft_win_s": round(t_ll - t_pre, 4),
        "replicated_blocks": int(replicated),
        "sibling_warm_blocks": int(sibling_warm),
        "placement_found_prefix": bool(placements == repeats),
        "hot_chain_replicated": bool(replicated > 0
                                     and sibling_warm > 0),
    }


def _steady_state(router, make_req, duration_s, max_outstanding,
                  pace_s=0.005, on_tick=None):
    """Feed → measure → drain, with per-request wall latency. Keeps
    ``max_outstanding`` requests in flight for ``duration_s``, then
    drains to idle. ``make_req(i) -> (prompt, submit_kwargs)`` so a
    workload can vary max_new/priority/timeout_s per class;
    ``on_tick(now_s, router)`` is the chaos hook. Returns (records,
    submitted, elapsed_s) where records are (request_id, status,
    n_tokens, latency_s, t_deliver_s) — latency is wall
    submit→delivery as the CLIENT sees it, which for a disagg fleet
    spans prefill + KV handoff + decode."""
    sub_t = {}
    submitted, records = [], []  # (rid, status, ntok, wall_latency,
    t0 = time.monotonic()        #  t_deliver, ttft, engine_latency)

    def pump():
        for r in router.tick():
            now = time.monotonic() - t0
            records.append((r.request_id, r.status, len(r.tokens),
                            now - sub_t[r.request_id], now, r.ttft,
                            r.latency))

    i = 0
    while time.monotonic() - t0 < duration_s:
        while len(submitted) - len(records) < max_outstanding:
            p, kw = make_req(i)
            req = router.submit(p, seed=i, **kw)
            sub_t[req.id] = time.monotonic() - t0
            submitted.append(req.id)
            i += 1
        if on_tick is not None:
            on_tick(time.monotonic() - t0, router)
        pump()
        time.sleep(pace_s)
    deadline = time.monotonic() + 120.0
    while not router.idle:
        pump()
        time.sleep(pace_s)
        assert time.monotonic() < deadline, "drain never finished"
    elapsed = time.monotonic() - t0
    missing = [x for x in submitted if router.response(x) is None]
    assert not missing, f"requests with no terminal response: {missing}"
    return records, submitted, elapsed


DOC_LEN, DOC_NEW = 128, 4        # prefill load: 16 chunks in, 4 tokens out
CHAT_LEN, CHAT_NEW = 16, 32      # decode load: 2 chunks in, 32 tokens out
DISAGG_SLOTS = 3                 # per-replica slots in the disagg trial


def disagg_trial(seed=11, duration_s=4.0, deadline_s=0.28,
                 max_outstanding=8):
    """Disagg vs mixed at equal chips, scored as SLO goodput.

    Both arms: 2 replicas x DISAGG_SLOTS slots, per-replica tick
    threads, paged KV, a priority front queue with tiny engine queues
    (waiting happens where priority exists), and the same
    prefill-heavy two-class workload — "doc" requests (128-token
    prompt, 4 new tokens: pure chunked-prefill load, no SLO)
    interleaved 2:1 with "chat" requests (16-token prompt, 32 new
    tokens: decode load, priority, a decode-phase SLO). A chat scores
    its tokens only if its decode duration — ``Response.latency -
    Response.ttft``, the time its 16 decode chunks actually took —
    lands inside ``deadline_s``; chats carry 8x a doc's tokens, so
    the arm that protects decode cadence wins goodput. This is the
    DistServe framing: disaggregation trades first-token latency
    (the handoff hop; docs/fleet.md says so openly) for
    decode-latency SLO attainment, and the SLO is what this trial
    scores.

    The structural difference under measurement: a mixed engine's
    tick is run-to-completion — admissions first, each doc's full
    16-chunk prefill host-blocking, then ONE decode chunk for the
    live set — so every one of a chat's decode chunks queues behind
    whatever doc prefill bursts land that tick, and the chat's decode
    duration inflates at the MEDIAN, not just the tail. The disagg
    arm pins doc prefills to the prefill-only replica; the
    decode-only replica's tick thread issues the chat's chunks
    (resuming from the shipped prefix blocks) with nothing heavier
    than another chat in front. Same chips, same work — the decode
    interference is what the split removes."""
    hcfg = LMConfig(vocab=67, d_model=64, nhead=2, d_ff=128,
                    n_layers=4, seq_len=160, dropout=0.0)
    model = PipelinedLM(hcfg, 1)
    params = model.init(jax.random.key(8))
    gen_cfg = GenerationConfig(max_new_tokens=CHAT_NEW, temperature=0.0)

    def engine(phase):
        be = SingleDeviceSlotBackend(
            model, params, num_slots=DISAGG_SLOTS, max_len=160,
            gen=gen_cfg, kv_block_size=8, kv_pool_blocks=256,
            prefill_chunk=8, decode_chunk=2)
        # tiny engine queue: waiting happens at the PRIORITY front
        # queue (chats jump docs) instead of fifo behind a replica —
        # placement backpressure is what makes priority mean anything
        return ServeEngine(be, RequestQueue(capacity=2), phase=phase)

    def fleet(roles):
        trs = [InProcessTransport(engine(r), async_tick=True)
               for r in roles]
        cls = DisaggController if set(roles) != {"mixed"} \
            else FleetController
        return cls(trs, RequestQueue(capacity=256, policy="priority"),
                   policy=RouterPolicy(backoff_base_s=0.0))

    out = {}
    for arm, roles in (("mixed", ("mixed", "mixed")),
                       ("disagg", ("prefill", "decode"))):
        rng = np.random.RandomState(seed)
        docs = [rng.randint(1, hcfg.vocab, size=DOC_LEN).tolist()
                for _ in range(64)]
        chats = [rng.randint(1, hcfg.vocab, size=CHAT_LEN).tolist()
                 for _ in range(64)]
        kind_of = {}

        def make_req(i, _k=kind_of, _d=docs, _c=chats):
            # 2 docs : 1 chat — the prefill-heavy skew
            if i % 3 == 2:
                _k[i] = "chat"
                return _c[i // 3 % len(_c)], dict(
                    max_new_tokens=CHAT_NEW, priority=1)
            _k[i] = "doc"
            return _d[i % len(_d)], dict(max_new_tokens=DOC_NEW)

        ctl = fleet(roles)
        try:
            # warm through the CONTROLLER so each arm compiles exactly
            # the programs it will run: the mixed engines both classes'
            # full prefills + decode chunks, the disagg pair the
            # clamped prefill AND the destination's cached-prefix
            # resume. Each class served twice per round so the
            # resume-from-cache trace compiles too.
            for wp, mn in ((docs[0], DOC_NEW), (chats[0], CHAT_NEW)):
                for _ in range(2):
                    for _ in range(2):
                        ctl.submit(wp, max_new_tokens=mn, seed=7)
                    run_to_idle(ctl, pace_s=0.005)
            records, submitted, elapsed = _steady_state(
                ctl, make_req, duration_s, max_outstanding)
        finally:
            ctl.close()
        idx_of = {rid: i for i, rid in enumerate(submitted)}

        def decode_s(r):
            return None if r[5] is None else max(r[6] - r[5], 0.0)

        view = {}
        ok_toks = 0
        for kind in ("doc", "chat"):
            recs = [r for r in records
                    if kind_of[idx_of[r[0]]] == kind]
            ok = [r for r in recs if r[1] == "ok"]
            if kind == "chat":      # SLO-scored: decode cadence held
                good = [r for r in ok if decode_s(r) is not None
                        and decode_s(r) <= deadline_s]
            else:                   # docs carry no SLO
                good = ok
            ok_toks += sum(r[2] for r in good)
            e2e = sorted(r[3] for r in ok)
            dec = sorted(d for d in (decode_s(r) for r in ok)
                         if d is not None)
            view[kind] = {
                "requests": len(recs),
                "ok": len(ok),
                "slo_ok": len(good),
                "slo_ok_frac": round(len(good) / max(len(recs), 1),
                                     4),
                "e2e_p50_s": round(e2e[len(e2e) // 2], 4)
                if e2e else None,
                "decode_p50_s": round(dec[len(dec) // 2], 4)
                if dec else None,
                "decode_max_s": round(dec[-1], 4) if dec else None,
            }
        by_status = {}
        for r in records:
            by_status[r[1]] = by_status.get(r[1], 0) + 1
        out[arm] = {
            "replicas": len(roles),
            "roles": list(roles),
            "slots_total": len(roles) * DISAGG_SLOTS,
            "requests": len(submitted),
            "responses_by_status": by_status,
            "elapsed_s": round(elapsed, 3),
            "goodput_tokens_s": round(ok_toks / max(elapsed, 1e-9),
                                      1),
            "doc": view["doc"],
            "chat": view["chat"],
        }
    out["workload"] = {
        "doc": {"prompt_len": DOC_LEN, "max_new": DOC_NEW},
        "chat": {"prompt_len": CHAT_LEN, "max_new": CHAT_NEW,
                 "decode_slo_s": deadline_s, "priority": 1},
        "mix": "2 docs : 1 chat", "max_outstanding": max_outstanding,
        "duration_s": duration_s}
    out["disagg_beats_mixed"] = bool(
        out["disagg"]["goodput_tokens_s"]
        >= out["mixed"]["goodput_tokens_s"])
    return out


def disagg_kill_trial_proc(kill_role, seed, kill_after_s=2.0,
                           duration_s=6.0, max_outstanding=8):
    """SIGKILL one phase-specialized child mid-stream. 4 real
    processes — 2 prefill + 2 decode — under a DisaggController; every
    request crosses the prefill→handoff→decode boundary, and the kill
    lands while some are mid-crossing (shadow delivered but decode not
    yet placed, or decode in flight). The surviving role sibling must
    absorb the stream through the reclaim gate: all ids delivered
    exactly once, goodput recovers after the kill, and the
    shadow-aware token reconciliation still balances."""
    roles = ("prefill", "prefill", "decode", "decode")
    kill_idx = 1 if kill_role == "prefill" else 3
    trace_buf = TraceBuffer(maxlen=200_000)
    ctl = DisaggController(
        [ProcessReplicaTransport(dataclasses.replace(proc_spec(),
                                                     role=r))
         for r in roles],
        RequestQueue(capacity=256),
        policy=RouterPolicy(backoff_base_s=0.0,
                            heartbeat_timeout_s=5.0),
        event_log=trace_buf)
    rng = np.random.RandomState(seed)
    work = make_workload(4096, rng)
    kill_t = [None]

    def on_tick(now, router):
        if kill_t[0] is None and now >= kill_after_s:
            router.replicas[kill_idx].transport._proc.kill()
            kill_t[0] = now

    try:
        warm(ctl, len(roles))
        records, submitted, elapsed = _steady_state(
            ctl, lambda i: (work[i % len(work)][0],
                            {"max_new_tokens": work[i % len(work)][1]}),
            duration_s, max_outstanding, on_tick=on_tick)
        states = ctl.counts()
    finally:
        ctl.close()
    obs = obs_report(FleetObserver(ctl, parent_events=trace_buf.drain()),
                     submitted)
    assert kill_t[0] is not None, "run too short to reach the kill"
    kt = kill_t[0]

    def rate(lo, hi):
        return sum(r[2] for r in records
                   if r[1] == "ok" and lo <= r[4] < hi) \
            / max(hi - lo, 1e-9)

    w = min(1.0, kt, (elapsed - kt) / 2)
    before, during, after = (rate(kt - w, kt), rate(kt, kt + w),
                             rate(kt + w, elapsed))
    by_status = {}
    for r in records:
        by_status[r[1]] = by_status.get(r[1], 0) + 1
    return {
        "roles": list(roles),
        "killed_replica": kill_idx,
        "killed_role": kill_role,
        "kill_mode": "sigkill_process",
        "kill_at": round(kt, 3),
        "window": round(w, 3),
        "rate_unit": "tokens/s",
        "requests": len(submitted),
        "elapsed_s": round(elapsed, 3),
        "rate_before": round(before, 2),
        "rate_failover": round(during, 2),
        "rate_after": round(after, 2),
        "recovered_frac": round(after / max(before, 1e-9), 3),
        "survived_failover": during > 0.0 or after > 0.0,
        "responses_by_status": by_status,
        "exactly_once": len(records) == len(submitted),
        "replica_states": states,
        "obs": obs,
    }


def wire_chaos_trial(kind, seed, step=4, count=1, magnitude=2.0,
                     n_requests=16):
    """Adversarial faults on ONE replica's proc wire, full workload
    through the exactly-once ledger. ``wire_partition``: the covered
    outgoing frame is dropped and the wire goes dark for ``magnitude``
    seconds — the heal must lose nothing (retained-frame replay,
    sequence dedup) and duplicate nothing (a dup would trip the
    ledger's exactly-once raise and fail the drill loudly).
    ``wire_corrupt``: ``count`` consecutive frames are bit-flipped
    post-checksum — every one must be rejected WHOLE (CRC mismatch ->
    drop connection -> re-dial -> replay), never half-parsed into the
    dispatcher."""
    plan = ChaosPlan([Fault(kind, step=step, count=count, stage=1,
                            magnitude=magnitude)])
    transports = []
    for i in range(2):
        kw = dict(reconnect_timeout_s=15.0)
        if i == 1:
            kw.update(chaos=plan, chaos_replica=1)
        transports.append(ProcessReplicaTransport(proc_spec(), **kw))
    # heartbeat timeout ABOVE the partition hold: the drill is about
    # the wire healing under the health machine's nose, not failover
    ctl = FleetController(transports, RequestQueue(capacity=256),
                          policy=RouterPolicy(backoff_base_s=0.0,
                                              heartbeat_timeout_s=10.0))
    rng = np.random.RandomState(seed)
    work = make_workload(n_requests, rng)
    responses = {}
    try:
        warm(ctl, 2)
        t0 = time.monotonic()
        ids = [ctl.submit(p, max_new_tokens=m, seed=i).id
               for i, (p, m) in enumerate(work)]
        deadline = time.monotonic() + 120.0
        while not ctl.idle:
            for r in ctl.tick():
                assert r.request_id not in responses, \
                    f"duplicate terminal for {r.request_id}"
                responses[r.request_id] = r
            time.sleep(0.005)
            assert time.monotonic() < deadline, \
                f"{kind} drill never drained"
        elapsed = time.monotonic() - t0
        # one more heartbeat interval so the child's final counter
        # ship (crc rejects ride the hb frame) lands before we read it
        time.sleep(0.2)
        tr = transports[1]
        wire = {
            "resends": tr.wire_resends,
            "dup_suppressed": tr.wire_dup_suppressed,
            "crc_rejects_total": tr.crc_rejects_total,
        }
        fired = (tr._partition_until > 0.0 if kind == "wire_partition"
                 else wire["crc_rejects_total"] > 0)
        missing = [x for x in ids if x not in responses]
    finally:
        ctl.close()
    assert not missing, f"{kind}: requests with no terminal: {missing}"
    return {
        "kind": kind,
        "fault": {"step": step, "count": count, "magnitude": magnitude,
                  "replica": 1},
        "requests": len(ids),
        "elapsed_s": round(elapsed, 3),
        "fired": bool(fired),
        "wire": wire,
        "exactly_once": len(responses) == len(ids),
    }


def _ctl_worker_main(journal_dir, mode, seed, n_requests=40):
    """The controller half of the SIGKILL-restart drill, run as a
    child process of the bench. Builds a proc fleet journaling every
    lifecycle transition to ``journal_dir``, submits a workload,
    prints the submitted ids and a mid-flight marker on stdout, then
    ticks forever — the bench SIGKILLs this process and recovers from
    nothing but the journal plus the orphaned children."""
    journal = RequestJournal(journal_dir)
    policy = RouterPolicy(backoff_base_s=0.0, heartbeat_timeout_s=10.0)
    if mode == "disagg":
        roles = ("prefill", "prefill", "decode", "decode")
        ctl = DisaggController(
            [ProcessReplicaTransport(dataclasses.replace(proc_spec(),
                                                         role=r))
             for r in roles],
            RequestQueue(capacity=256), policy=policy, journal=journal)
    else:
        ctl = FleetController(
            [ProcessReplicaTransport(proc_spec()) for _ in range(3)],
            RequestQueue(capacity=256), policy=policy, journal=journal)
    for rep in ctl.replicas:
        journal.record_replica(rep.index, **rep.transport.rejoin_info())
    warm(ctl, len(ctl.replicas))
    rng = np.random.RandomState(seed)
    work = make_workload(n_requests, rng)
    ids = [ctl.submit(p, max_new_tokens=m, seed=i).id
           for i, (p, m) in enumerate(work)]
    print(json.dumps({"event": "submitted", "ids": ids}), flush=True)
    delivered = 0
    announced = False
    while True:
        delivered += len(ctl.tick())
        if not announced and delivered >= 2:
            # some terminals journaled, plenty still in flight: tell
            # the bench this is the adversarial moment to pull the plug
            print(json.dumps({"event": "midflight",
                              "delivered": delivered}), flush=True)
            announced = True
        time.sleep(0.002)


def ctl_restart_trial(mode, seed):
    """SIGKILL the CONTROLLER mid-stream, rebuild it from the journal.
    The controller (plus its journal WAL) lives in a separate process;
    its replica children survive the kill as orphans re-dialing the
    dead listener. The bench replays the WAL, re-binds the recorded
    ports in rejoin mode (re-registering the RUNNING children instead
    of spawning), reconciles placements against what each child still
    holds, and drains. Exactly-once across the two controller lives:
    pre-crash terminals (journaled) and post-recovery deliveries must
    partition the submitted id set — no id lost, none answered
    twice."""
    tmpdir = tempfile.mkdtemp(prefix="fleet-ctl-journal-")
    worker = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--_ctl-worker", tmpdir, "--_ctl-mode", mode,
         "--seed", str(seed)],
        stdout=subprocess.PIPE, text=True)
    lines: "queue_mod.Queue[str]" = queue_mod.Queue()
    threading.Thread(target=lambda: [lines.put(ln) for ln in worker.stdout],
                     daemon=True).start()

    def next_event(timeout_s):
        line = lines.get(timeout=timeout_s)
        return json.loads(line)

    state = None
    ctl2 = None
    recovered = []
    try:
        sub = next_event(300.0)
        assert sub["event"] == "submitted", sub
        mid = next_event(120.0)
        assert mid["event"] == "midflight", mid
        os.kill(worker.pid, signal.SIGKILL)      # no goodbye
        worker.wait(timeout=30)
        t0 = time.monotonic()
        state = RequestJournal.recover(tmpdir)
        assert not state.clean, "a SIGKILL cannot leave a clean log"
        assert state.orphans, \
            "kill landed after the drain — nothing was in flight"
        assert sorted(state.replicas) == list(range(len(state.replicas)))
        transports = [
            ProcessReplicaTransport(
                ReplicaSpec(**state.replicas[i]["spec"]),
                rejoin=state.replicas[i])
            for i in sorted(state.replicas)]
        journal2 = RequestJournal(tmpdir)        # the WAL keeps growing
        cls = DisaggController if mode == "disagg" else FleetController
        ctl2 = cls.from_journal(
            state, transports, RequestQueue(capacity=256),
            journal=journal2,
            policy=RouterPolicy(backoff_base_s=0.0,
                                heartbeat_timeout_s=10.0))
        deadline = time.monotonic() + 180.0
        while not ctl2.idle:
            recovered.extend(ctl2.tick())
            time.sleep(0.005)
            assert time.monotonic() < deadline, \
                "recovered fleet never drained"
        elapsed = time.monotonic() - t0
        ctl2.close()
        ctl2 = None                              # closed cleanly
        journal2.close(clean=True)
    finally:
        if worker.poll() is None:
            worker.kill()
        if ctl2 is not None:
            try:
                ctl2.close()
            except Exception:
                pass
        # belt and braces: no orphaned replica child outlives the drill
        if state is not None:
            for rec in state.replicas.values():
                pid = rec.get("pid")
                if pid:
                    try:
                        os.kill(int(pid), signal.SIGKILL)
                    except (OSError, ProcessLookupError):
                        pass
        shutil.rmtree(tmpdir, ignore_errors=True)
    all_ids = sorted(state.requests)
    pre = set(state.terminal)
    post = [r.request_id for r in recovered]
    exactly_once = (sorted(pre | set(post)) == all_ids
                    and len(post) == len(set(post))
                    and not (pre & set(post)))
    return {
        "mode": mode,
        "kill_mode": "sigkill_controller",
        "requests": len(all_ids),
        "pre_crash_terminal": len(pre),
        "orphans_at_crash": len(state.orphans),
        "recovered_delivered": len(post),
        "journal_records": state.records,
        "recover_s": round(elapsed, 3),
        "exactly_once": bool(exactly_once),
    }


def saturation_trial(model, params, fleet, counts, seed,
                     duration_s=3.0, max_outstanding=12):
    """Steady-state goodput at N = counts[0]..counts[-1] replicas over
    the chosen transport, all replicas fed from the ONE front queue.
    Reports the front-queue bottleneck N: the smallest fleet within
    10% of the sweep's best goodput — past it, added replicas buy
    nothing (on this shared-core host the engines contend for the
    same processor, so the knee lands early; on a pod each replica
    owns its chips and the knee is where the front queue's
    single-threaded placement loop saturates)."""
    rng = np.random.RandomState(seed)
    work = make_workload(4096, rng)
    sweep = []
    for n in counts:
        router = make_fleet(model, params, n, fleet=fleet)
        try:
            warm(router, n)
            records, submitted, elapsed = _steady_state(
                router,
                lambda i: (work[i % len(work)][0],
                           {"max_new_tokens": work[i % len(work)][1]}),
                duration_s, max_outstanding)
        finally:
            router.close()
        ok = [r for r in records if r[1] == "ok"]
        sweep.append({
            "replicas": n,
            "slots_total": n * SLOTS,
            "requests": len(submitted),
            "ok": len(ok),
            "elapsed_s": round(elapsed, 3),
            "goodput_tokens_s": round(
                sum(r[2] for r in ok) / max(elapsed, 1e-9), 1),
        })
    best = max(s["goodput_tokens_s"] for s in sweep)
    sat = next(s["replicas"] for s in sweep
               if s["goodput_tokens_s"] >= 0.9 * best)
    return {"transport": fleet, "rate_unit": "tokens/s",
            "duration_s_per_point": duration_s,
            "max_outstanding": max_outstanding, "sweep": sweep,
            "best_goodput_tokens_s": best, "saturation_n": sat}


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="small run; single-line JSON summary")
    ap.add_argument("--fleet", choices=["inproc", "thread", "proc"],
                    default="inproc",
                    help="replica transport for the scaling + kill "
                         "trials (straggler/handoff trials are always "
                         "in-process)")
    ap.add_argument("--out", default=None,
                    help="also write the summary JSON here")
    ap.add_argument("--seed", type=int, default=0)
    # hidden: the controller half of the SIGKILL-restart drill
    ap.add_argument("--_ctl-worker", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--_ctl-mode", default="mixed", help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args._ctl_worker:
        return _ctl_worker_main(args._ctl_worker, args._ctl_mode,
                                args.seed)

    t0 = time.perf_counter()
    model = PipelinedLM(CFG, 1)
    params = model.init(jax.random.key(0))

    n_requests = 24 if args.quick else 48
    replica_counts = (1, 3) if args.quick else (1, 2, 3)

    scaling = []
    for n in replica_counts:
        log(f"== scaling[{args.fleet}]: {n} replica(s), "
            f"{n_requests} requests")
        r = scaling_trial(model, params, n, n_requests, args.seed,
                          args.fleet)
        scaling.append(r)
        log(f"   {r}")

    log(f"== kill one of 3 mid-stream [{args.fleet}]")
    kill = kill_trial(model, params, 3, n_requests * 2, args.seed + 1,
                      kill_tick=6, window=4, fleet=args.fleet)
    log(f"   {kill}")

    log("== straggler: async ticks vs serial (N=3, in-process)")
    straggler = straggler_trial(model, params, n_requests, args.seed + 2)
    log(f"   {straggler}")

    log("== session-remap KV handoff TTFT (2 paged replicas)")
    handoff = handoff_trial(repeats=2 if args.quick else 3)
    log(f"   {handoff}")

    log("== prefix-aware placement + hot replication (2 paged replicas)")
    placement = prefix_placement_trial(repeats=2 if args.quick else 3)
    log(f"   {placement}")

    log("== disagg vs mixed at equal chips (prefill-heavy, deadlined)")
    disagg = disagg_trial(seed=args.seed + 3,
                          duration_s=3.0 if args.quick else 6.0)
    log(f"   {disagg}")

    log("== disagg SIGKILL drills: one prefill, then one decode (proc)")
    disagg_kills = {}
    for role in ("prefill", "decode"):
        disagg_kills[role] = disagg_kill_trial_proc(role, args.seed + 4)
        log(f"   kill {role}: {disagg_kills[role]}")

    log("== wire chaos drills: 2s partition, corruption storm (proc)")
    partition = wire_chaos_trial("wire_partition", args.seed + 6,
                                 magnitude=2.0)
    log(f"   partition: {partition}")
    corrupt = wire_chaos_trial("wire_corrupt", args.seed + 7, step=3,
                               count=15)
    log(f"   corrupt storm: {corrupt}")

    log("== controller SIGKILL + journal restart drills (proc)")
    ctl_restart = {}
    for mode in ("mixed", "disagg"):
        ctl_restart[mode] = ctl_restart_trial(mode, args.seed + 8)
        log(f"   {mode}: {ctl_restart[mode]}")

    log(f"== saturation sweep [{args.fleet}]: front-queue bottleneck")
    saturation = saturation_trial(
        model, params, args.fleet, (1, 2, 3) if args.quick
        else (1, 2, 3, 4), args.seed + 5,
        duration_s=2.5 if args.quick else 4.0)
    log(f"   {saturation}")

    stitch = kill["obs"]["trace_stitch"]
    handoff_beats_reprefill = bool(
        handoff["ttft_handoff_s"] < handoff["ttft_reprefill_s"])
    disagg_kills_ok = all(
        k["exactly_once"] and k["survived_failover"]
        and k["obs"]["reconcile"]["reconciled"]
        for k in disagg_kills.values())
    wire_ok = bool(partition["exactly_once"] and partition["fired"]
                   and corrupt["exactly_once"] and corrupt["fired"]
                   and corrupt["wire"]["crc_rejects_total"] > 0)
    restart_ok = all(r["exactly_once"] for r in ctl_restart.values())
    ok = bool(kill["exactly_once"] and kill["survived_failover"]
              and kill["recovered_frac"] > 0.3
              and straggler["async_beats_serial"]
              and handoff["handoff_moved_blocks"]
              and handoff_beats_reprefill
              and placement["placement_found_prefix"]
              and placement["hot_chain_replicated"]
              and disagg["disagg_beats_mixed"]
              and disagg_kills_ok
              and wire_ok and restart_ok
              and kill["obs"]["reconcile"]["reconciled"]
              and stitch["frac"] == 1.0
              and stitch["exactly_once"])
    summary = {
        "bench": "fleet", "rev": "r20",
        "quick": bool(args.quick),
        "fleet": args.fleet,
        "platform": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "slots_per_replica": SLOTS,
        "decode_chunk": CHUNK,
        "max_new_tokens": MAX_NEW,
        "contention": host_contention(),
        "scaling": scaling,
        "kill_one_of_n": kill,
        "async_vs_serial": straggler,
        "kv_handoff": handoff,
        "kv_prefix_placement": placement,
        "disagg_vs_mixed": disagg,
        "disagg_kill_drills": disagg_kills,
        "wire_chaos": {"partition": partition, "corrupt_storm": corrupt},
        "ctl_restart": ctl_restart,
        "saturation": saturation,
        "fleet_ok": ok,
        "wall_s": round(time.perf_counter() - t0, 1),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=2)
        log(f"wrote {args.out}")
    if args.quick:
        print(json.dumps({
            "transport": args.fleet,
            "goodput_1_replica_tokens_s":
                scaling[0]["goodput_tokens_s"],
            "goodput_3_replicas_tokens_s":
                scaling[-1]["goodput_tokens_s"],
            "kill_drop_frac": kill["drop_frac"],
            "kill_recovered_frac": kill["recovered_frac"],
            "exactly_once": kill["exactly_once"],
            "async_speedup": straggler["speedup"],
            "async_beats_serial": straggler["async_beats_serial"],
            "ttft_win_s": handoff["ttft_win_s"],
            "handoff_moved_blocks": handoff["handoff_moved_blocks"],
            "handoff_beats_reprefill": handoff_beats_reprefill,
            "disagg_goodput_tokens_s":
                disagg["disagg"]["goodput_tokens_s"],
            "mixed_goodput_tokens_s":
                disagg["mixed"]["goodput_tokens_s"],
            "disagg_beats_mixed": disagg["disagg_beats_mixed"],
            "disagg_kill_prefill_exactly_once":
                disagg_kills["prefill"]["exactly_once"],
            "disagg_kill_decode_exactly_once":
                disagg_kills["decode"]["exactly_once"],
            "partition_heals_exactly_once":
                partition["exactly_once"] and partition["fired"],
            "partition_dup_suppressed":
                partition["wire"]["dup_suppressed"],
            "corrupt_storm_ok":
                corrupt["exactly_once"] and corrupt["fired"],
            "wire_crc_rejects": corrupt["wire"]["crc_rejects_total"],
            "ctl_restart_exactly_once":
                ctl_restart["mixed"]["exactly_once"],
            "ctl_restart_disagg_exactly_once":
                ctl_restart["disagg"]["exactly_once"],
            "saturation_n": saturation["saturation_n"],
            "placement_ttft_win_s": placement["ttft_win_s"],
            "placement_found_prefix":
                placement["placement_found_prefix"],
            "hot_chain_replicated": placement["hot_chain_replicated"],
            "contended": summary["contention"]["contended"],
            "tokens_reconciled": kill["obs"]["reconcile"]["reconciled"],
            "trace_stitch_frac": stitch["frac"],
            "trace_stitch_exactly_once": stitch["exactly_once"],
            "slo_ok": kill["obs"]["slo"]["ok"],
            "fleet_ok": ok,
        }))
    else:
        print(json.dumps(summary, indent=2))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
