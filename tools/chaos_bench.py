"""Chaos bench: prove the resilience layer recovers under every fault class.

For each fault kind in the :class:`~pipe_tpu.resilience.ChaosPlan`
taxonomy, run a small job with the fault injected deterministically and
record whether it **recovered** and how many steps the fault cost
(``steps_to_recover``):

* **Train faults** (``nan_grads`` / ``inf_grads`` / ``nan_loss`` /
  ``loss_spike`` / ``nan_activations``) — the guarded step must skip the
  poisoned update(s), finish the run with finite params, and count
  exactly the injected anomalies. ``steps_to_recover`` = skipped steps.
  A separate ``rewind`` trial injects ``rewind_after`` consecutive
  faults to force a snapshot rollback.
* **Data faults** (``data_raise``) — the retrying iterator must rebuild
  the source and deliver every batch; zero training steps lost.
* **Transport faults** (``transport_drop`` / ``transport_corrupt``) —
  the emulator executor's hop fault must (a) fire deterministically
  (faulted output != clean output), and (b) a retry without the fault
  must reproduce the clean output bitwise — the transient-loss recovery
  story. ``steps_to_recover`` = 1 retried execution.
* **Persistent transport** (``persistent_hop_drop``) — the hop drops
  EVERY micro-batch from the fault on; the
  :class:`~pipe_tpu.resilience.HopHealth` streak counter must cross its
  ``dead_after`` threshold (the signal the elastic rung consumes) and a
  rerun without the fault must be bitwise clean.
* **Stage loss** (``kill_stage``) — a pipeline stage dies mid-run; the
  elastic rung (``resilience.elastic``) must detect it from the
  gradient heartbeat, re-plan over the survivors, restore from the
  buddy ring, and finish with finite params.
  ``steps_to_recover`` = steps lost to the rewind (detected - snapshot).
* **Serve faults** (``stall_tick`` / ``queue_flood`` /
  ``backend_raise``) — the engine must keep serving: stalls are counted
  by the watchdog, floods cannot starve real (higher-priority) traffic,
  and a raising backend errors only the request it hit.

Usage:
  python tools/chaos_bench.py                 # full run -> CHAOS_r11.json
  python tools/chaos_bench.py --quick         # subset, one JSON line
Progress goes to stderr; the last stdout line is always the summary
object, so ``bench.py`` embeds the --quick summary.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# The train trials need a 2-stage mesh; force virtual CPU devices before
# jax binds a backend (same pattern as multistage_probe).
from pipe_tpu.utils.platform import force_cpu_platform  # noqa: E402

force_cpu_platform(8)

import jax
import jax.numpy as jnp
import numpy as np

from pipe_tpu.data import lm_text
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
from pipe_tpu.obs.telemetry import MetricsRegistry, set_registry
from pipe_tpu.resilience import (ChaosPlan, Fault, ResilienceConfig,
                                 TickWatchdog)
from pipe_tpu.train.loop import Trainer, TrainerConfig

CFG = LMConfig(vocab=67, d_model=16, nhead=2, d_ff=32, n_layers=4,
               seq_len=32, dropout=0.0)
STEPS = 8
FAULT_STEP = 3


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _source():
    ids = np.random.RandomState(0).randint(0, CFG.vocab, size=20000)
    return lm_text.batchify(ids, 8)


def _resilience(**kw):
    base = dict(warmup_steps=100, rewind_after=2, snapshot_every=2,
                data_backoff_s=0.0, rewind_backoff_s=0.0)
    base.update(kw)
    return ResilienceConfig(**base)


def _trainer(rc, plan):
    tc = TrainerConfig(batch_size=8, bptt=16, chunks=2, n_stages=2,
                       checkpoint="never", lr=0.01, resilience=rc)
    return Trainer(CFG, tc, chaos=plan)


def _finite(state):
    return all(bool(jnp.isfinite(l).all())
               for l in jax.tree_util.tree_leaves(state.params)
               if jnp.issubdtype(l.dtype, jnp.inexact))


def train_trial(kind, count=1, magnitude=1e3, **rc_kw):
    """Inject `count` consecutive `kind` faults at FAULT_STEP; recovery =
    the run finishes all STEPS with finite params and the guard caught
    exactly the injected steps."""
    reg = set_registry(MetricsRegistry())
    try:
        plan = ChaosPlan([Fault(kind, step=FAULT_STEP, count=count,
                                magnitude=magnitude)])
        tr = _trainer(_resilience(**rc_kw), plan)
        t0 = time.perf_counter()
        state, info = tr.train_epoch(_source(), 0, tr.init_state(),
                                     max_steps=STEPS, log_every=0,
                                     log_fn=log)
        finite = _finite(state)
        recovered = (finite and info["steps"] == STEPS
                     and info["anomalies"] >= count
                     and np.isfinite(info["loss_ewma"]))
        return {"recovered": bool(recovered),
                "steps_to_recover": int(info["anomalies"]),
                "anomalies": int(info["anomalies"]),
                "rewinds": int(info["rewinds"]),
                "params_finite": bool(finite),
                "loss_ewma": round(float(info["loss_ewma"]), 4),
                "wall_s": round(time.perf_counter() - t0, 2)}
    finally:
        set_registry(reg)


def data_trial():
    """data_raise at one batch index: the retrying iterator rebuilds the
    source; every batch still arrives, zero steps lost."""
    reg = set_registry(MetricsRegistry())
    try:
        plan = ChaosPlan([Fault("data_raise", step=FAULT_STEP)])
        tr = _trainer(_resilience(), plan)
        state, info = tr.train_epoch(_source(), 0, tr.init_state(),
                                     max_steps=STEPS, log_every=0,
                                     log_fn=log)
        from pipe_tpu.obs.telemetry import get_registry
        retries = get_registry().scalars().get("resilience.data_retries", 0)
        recovered = (info["steps"] == STEPS and info["anomalies"] == 0
                     and retries >= 1)
        return {"recovered": bool(recovered), "steps_to_recover": 0,
                "data_retries": int(retries),
                "steps_completed": int(info["steps"])}
    finally:
        set_registry(reg)


def kill_stage_trial():
    """kill_stage: stage 1 of 3 dies mid-run. The elastic rung must
    localize it from the gradient heartbeat, re-plan to 2 stages,
    restore from the buddy ring, and finish every step with finite
    params (the full bitwise pin lives in tools/elastic_bench.py)."""
    reg = set_registry(MetricsRegistry())
    try:
        from pipe_tpu.resilience import ElasticConfig
        from pipe_tpu.resilience.elastic import train_elastic
        # 6 layers: divisible by 3 (healthy) and 2 (degraded)
        ecfg = LMConfig(vocab=67, d_model=16, nhead=2, d_ff=32,
                        n_layers=6, seq_len=32, dropout=0.0)
        tc = TrainerConfig(batch_size=8, bptt=16, chunks=2, n_stages=3,
                           schedule="gpipe", checkpoint="never", lr=0.01,
                           resilience=_resilience(),
                           elastic=ElasticConfig(snapshot_every=2,
                                                 dead_after=2))
        plan = ChaosPlan([Fault("kill_stage", step=4, stage=1)])
        tr = Trainer(ecfg, tc, devices=jax.devices()[:3], chaos=plan)
        t0 = time.perf_counter()
        tr2, state, info = train_elastic(tr, _source(), max_steps=STEPS,
                                         log_fn=log)
        rec = info["recoveries"][0] if info["recoveries"] else {}
        finite = _finite(state)
        recovered = (info["replans"] == 1 and tr2.cfg.n_stages == 2
                     and rec.get("stage") == 1 and finite)
        return {"recovered": bool(recovered),
                "steps_to_recover": int(rec.get("lost_steps", -1)),
                "killed_stage": rec.get("stage"),
                "detected_step": rec.get("detected_step"),
                "snapshot_step": rec.get("snapshot_step"),
                "stages_after": int(tr2.cfg.n_stages),
                "params_finite": bool(finite),
                "wall_s": round(time.perf_counter() - t0, 2)}
    finally:
        set_registry(reg)


def persistent_hop_trial():
    """persistent_hop_drop: the stage-0 hop drops EVERY micro-batch
    from the fault on. The HopHealth streak must cross ``dead_after``
    (the detection signal, where a transient drop's streak resets) and
    a rerun without the fault must be bitwise clean."""
    from pipe_tpu.core import microbatch as mb
    from pipe_tpu.parallel import emulator
    from pipe_tpu.resilience import HopHealth

    def stage(p, x, ctx):
        return jnp.tanh(x @ p)

    key = jax.random.key(7)
    params = [jax.random.normal(jax.random.fold_in(key, s), (8, 8))
              for s in range(2)]
    stages = [stage, stage]
    xs = [mb.Batch(jax.random.normal(jax.random.fold_in(key, 10 + i),
                                     (4, 8)), atomic=True)
          for i in range(3)]

    def run(chaos, hh=None):
        out = emulator.run(stages, params, list(xs), chaos=chaos,
                           hop_health=hh)
        return [np.asarray(b.values[0]) for b in out]

    clean = run(None)
    plan = ChaosPlan([Fault("persistent_hop_drop", step=0, stage=0)])
    hh = HopHealth(dead_after=2)
    faulted = run(plan, hh)
    all_dropped = all(not np.array_equal(a, b)
                      for a, b in zip(faulted, clean))
    streak = hh.streak(0)
    dead = hh.dead_hops
    retry = run(None)
    restored = all(np.array_equal(a, b) for a, b in zip(retry, clean))
    return {"recovered": bool(dead == [0] and streak >= 2
                              and all_dropped and restored),
            "steps_to_recover": 1, "hop_streak": int(streak),
            "dead_hops": list(dead),
            "every_microbatch_dropped": bool(all_dropped),
            "retry_bitwise_clean": bool(restored)}


def transport_trial(kind):
    """Emulator hop fault: faulted run differs from clean, retry without
    the fault reproduces the clean output bitwise."""
    from pipe_tpu.core import microbatch as mb
    from pipe_tpu.core.partition import StageCtx
    from pipe_tpu.parallel import emulator

    def stage(p, x, ctx: StageCtx):
        return jnp.tanh(x @ p)

    key = jax.random.key(7)
    params = [jax.random.normal(jax.random.fold_in(key, s), (8, 8))
              for s in range(2)]
    stages = [stage, stage]
    xs = [mb.Batch(jax.random.normal(jax.random.fold_in(key, 10 + i),
                                     (4, 8)), atomic=True)
          for i in range(2)]

    def run(chaos):
        out = emulator.run(stages, params, list(xs), chaos=chaos)
        return [np.asarray(b.values[0]) for b in out]

    clean = run(None)
    plan = ChaosPlan([Fault(kind, step=0, stage=0, microbatch=1)])
    faulted = run(plan)
    hit = not np.array_equal(faulted[1], clean[1])
    spared = np.array_equal(faulted[0], clean[0])
    retry = run(None)
    restored = all(np.array_equal(a, b) for a, b in zip(retry, clean))
    return {"recovered": bool(hit and spared and restored),
            "steps_to_recover": 1, "fault_detected": bool(hit),
            "other_microbatch_untouched": bool(spared),
            "retry_bitwise_clean": bool(restored)}


def _serve_engine(plan, watchdog=None, capacity=8, num_slots=2):
    from pipe_tpu.inference.generate import GenerationConfig
    from pipe_tpu.serve import (RequestQueue, ServeEngine,
                                SingleDeviceSlotBackend)
    model = PipelinedLM(CFG, 2)
    params = model.init(jax.random.key(0))
    backend = SingleDeviceSlotBackend(
        model, params, num_slots=num_slots, max_len=32,
        gen=GenerationConfig(max_new_tokens=8, temperature=1.0))
    queue = RequestQueue(capacity=capacity, policy="priority")
    return ServeEngine(backend, queue, chaos=plan, watchdog=watchdog)


def serve_trial(kind):
    reg = set_registry(MetricsRegistry())
    try:
        from pipe_tpu.obs.telemetry import get_registry
        if kind == "stall_tick":
            plan = ChaosPlan([Fault("stall_tick", step=1, magnitude=0.15)])
            eng = _serve_engine(plan, TickWatchdog(tick_budget_s=0.05))
        elif kind == "queue_flood":
            plan = ChaosPlan([Fault("queue_flood", step=0)])
            eng = _serve_engine(plan)
        else:
            plan = ChaosPlan([Fault("backend_raise", step=0)])
            eng = _serve_engine(plan)
            # tick 0 is the faulted tick: whatever it admits dies with
            # status="error"; traffic submitted afterwards must serve fine
            bad = eng.submit([9, 2, 3], max_new_tokens=4, seed=0)
            eng.tick()
            reqs = [eng.submit([1 + i, 2, 3], max_new_tokens=4, seed=i)
                    for i in range(2)]
            eng.run_until_idle(max_ticks=200)
            stats = [eng.response(r.id).status for r in reqs]
            errs = int(get_registry().scalars().get(
                "resilience.slot_errors", 0))
            return {"request_statuses": stats,
                    "faulted_status": eng.response(bad.id).status,
                    "recovered": bool(
                        eng.response(bad.id).status == "error"
                        and all(s == "ok" for s in stats) and errs == 1),
                    "steps_to_recover": errs, "slot_errors": errs}
        reqs = [eng.submit([1 + i, 2, 3], max_new_tokens=4, seed=i)
                for i in range(3)]
        eng.run_until_idle(max_ticks=200)
        stats = [eng.response(r.id).status for r in reqs]
        scalars = get_registry().scalars()
        out = {"request_statuses": stats}
        if kind == "stall_tick":
            slow = scalars.get("resilience.watchdog_slow_ticks", 0)
            out.update(recovered=bool(all(s == "ok" for s in stats)
                                      and slow >= 1),
                       steps_to_recover=0, slow_ticks=int(slow))
        else:
            # flood junk rides at the lowest priority: real traffic all
            # finishes despite the queue being force-filled
            out.update(recovered=bool(all(s == "ok" for s in stats)),
                       steps_to_recover=0,
                       floods=int(scalars.get("resilience.chaos_floods", 0)))
        return out
    finally:
        set_registry(reg)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--quick", action="store_true",
                    help="one fault per layer, one JSON line")
    ap.add_argument("--out", default=None,
                    help="also write the summary JSON here")
    args = ap.parse_args()

    t0 = time.perf_counter()
    results = {}
    if args.quick:
        train_kinds = ["nan_grads"]
        transport_kinds = ["transport_drop"]
        serve_kinds = ["backend_raise"]
        rewind = False
    else:
        train_kinds = ["nan_grads", "inf_grads", "nan_loss", "loss_spike",
                       "nan_activations"]
        transport_kinds = ["transport_drop", "transport_corrupt"]
        serve_kinds = ["stall_tick", "queue_flood", "backend_raise"]
        rewind = True

    for kind in train_kinds:
        log(f"== train fault: {kind}")
        # the spike check is disarmed during warmup, so the loss_spike
        # trial must warm up BEFORE the fault step to see it fire
        kw = {"warmup_steps": 2} if kind == "loss_spike" else {}
        results[kind] = train_trial(kind, **kw)
        log(f"   {results[kind]}")
    if rewind:
        log("== train fault: rewind (consecutive nan_grads)")
        r = train_trial("nan_grads", count=2)   # == rewind_after
        r["recovered"] = bool(r["recovered"] and r["rewinds"] >= 1)
        results["rewind"] = r
        log(f"   {r}")
    log("== data fault: data_raise")
    results["data_raise"] = data_trial()
    log(f"   {results['data_raise']}")
    for kind in transport_kinds:
        log(f"== transport fault: {kind}")
        results[kind] = transport_trial(kind)
        log(f"   {results[kind]}")
    if not args.quick:
        log("== transport fault: persistent_hop_drop")
        results["persistent_hop_drop"] = persistent_hop_trial()
        log(f"   {results['persistent_hop_drop']}")
        log("== stage fault: kill_stage (elastic re-plan 3->2)")
        results["kill_stage"] = kill_stage_trial()
        log(f"   {results['kill_stage']}")
    for kind in serve_kinds:
        log(f"== serve fault: {kind}")
        results[kind] = serve_trial(kind)
        log(f"   {results[kind]}")

    summary = {
        "bench": "chaos", "rev": "r11",
        "quick": bool(args.quick),
        "platform": jax.default_backend(),
        "all_recovered": all(v.get("recovered") for v in results.values()),
        "faults": results,
        "wall_s": round(time.perf_counter() - t0, 1),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(summary, f, indent=2)
        log(f"wrote {args.out}")
    print(json.dumps(summary if args.quick else summary, indent=None
                     if args.quick else 2))
    return 0 if summary["all_recovered"] else 1


if __name__ == "__main__":
    sys.exit(main())
