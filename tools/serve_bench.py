"""Serving benchmark: the continuous-batching engine under synthetic load.

Three questions, answered on whatever backend is available (the numbers
of record are the committed ``SERVE_r08.json``):

1. **Slot tax** — steady-state decode tokens/s with every slot
   continuously full, vs the fixed-batch ``Generator`` at the same live
   count (batch = num_slots). The engine's decode step is the batched
   per-slot program (vmapped positions, per-slot key chains) plus one
   host round-trip per ``decode_chunk`` tokens; the acceptance bar is
   >= 0.9x the one-shot batch program.
2. **Latency under load** — seeded Poisson arrivals at a fraction of
   measured capacity; per-request TTFT p50/p99
   (:func:`pipe_tpu.obs.telemetry.percentile_exact` — the streaming
   histogram's bucketed quantiles are too coarse for a bench artifact).
3. **Goodput under 2x overload, backpressure on vs off** — "on" bounds
   the queue (excess rejected at submit, cheap), "off" admits everything
   (requests rot in the queue past their deadline and are reaped, or
   time out mid-decode after burning slot-steps). Goodput counts only
   tokens of requests that finished ``ok`` within their deadline.
4. **Resident loop A/B** (``SERVE_r14.json``; ``--resident`` adds the
   speculative section to the full run) — host-overhead-per-token and
   tokens/s at equal live slots, single-chunk ticks vs the fused
   ``lax.while_loop``, plus draft/verify acceptance on repetitive
   prompts with the bitwise-Generator-parity bit reported.
5. **KV gen-2** (``SERVE_r17.json``) — multi-tenant radix reuse
   (fleet-common base, per-tenant divergence, full-block random tails:
   the shape where a gen-1 whole-prefix cache scores zero) with the
   counterfactual hit fraction and the TTFT the skipped prefill buys,
   plus the offload drill: spill cold blocks to host under pool
   pressure, restore on re-reference, tokens bitwise the unpressured
   run.
6. **Speculative gen-2** (``SERVE_r18.json``) — real draft sources on
   the tied-head bench weights (see :func:`_spec_bench_params`):
   n-gram vs truncated-pipeline vs tree acceptance on APERIODIC
   prompts (the history lookup's worst case, the model drafts' home
   turf), every source bitwise the Generator; then the
   measured-breakeven closed loop — spec vs non-spec resident tokens/s
   at equal live slots, with the verify-chunk cost ratio measured from
   the two engines' own step/round rates and fed back through the
   planner's :func:`~pipe_tpu.core.planner.spec_breakeven_acceptance`
   so the artifact records predicted AND measured speedup.

Usage:
  python tools/serve_bench.py            # full run, pretty JSON to stdout
  python tools/serve_bench.py --quick    # small run, one JSON line
Progress goes to stderr; stdout is machine-readable (the last line is
always the summary object), so ``bench.py`` embeds the --quick summary.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pipe_tpu.inference import GenerationConfig, Generator
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
from pipe_tpu.obs.telemetry import percentile_exact
from pipe_tpu.serve import (BucketSpec, QueueFull, RequestQueue,
                            ServeEngine, SingleDeviceSlotBackend)

CFG = LMConfig(vocab=1024, d_model=128, nhead=8, d_ff=512, n_layers=4,
               seq_len=256, dropout=0.0)
BUCKETS = BucketSpec.of(32, 64)
MAX_NEW = 64
# Size the slot cache to the workload, exactly as Generator sizes its
# cache to prompt+max_new: attention cost scales with cache ROWS, not
# live tokens, so an oversized max_len taxes every decode step (measured
# ~0.6x the fixed-batch baseline at 2x the needed rows vs ~1.3x when
# sized to fit).
MAX_LEN = BUCKETS.max_len + MAX_NEW
# KV A/B workload: a long shared system prompt (112 tokens = 14 full
# blocks at block=8) with short per-request tails — the shape
# prefix caching exists for. Demand per request is 19 blocks but only 5
# are private once the prefix is cached, so a pool holding the slab's
# row budget for S slots carries 2S live requests; and the paged
# prefill recomputes ONE 16-token chunk where the slab runs the full
# 128-wide bucket program.
KV_BLOCK = 8
SHARED_LEN = 112
AB_TAILS = (4, 8)
AB_MAX_NEW = 32
AB_BUCKETS = BucketSpec.of(128)
AB_MAX_LEN = SHARED_LEN + max(AB_TAILS) + AB_MAX_NEW    # 152
# Multi-tenant radix workload (SERVE_r17): every tenant's preamble
# starts with one fleet-common base (8 full blocks) then diverges into
# a per-tenant segment (4 blocks); request tails are >= 1 block so the
# full prompt-block chain is NEVER entirely cached — a gen-1
# whole-prefix cache (exact full-chain match) scores zero here, while
# the radix tree still reuses the base + tenant blocks of every
# admission after the first per tenant.
MT_BASE_LEN = 64
MT_TENANT_LEN = 32
MT_TENANTS = 3
MT_TAILS = (8, 16)


def host_contention():
    """1-min load average vs CPU count: above ~75% the host is fighting
    itself and wall-clock goodput numbers are noise."""
    try:
        load1 = os.getloadavg()[0]
    except OSError:                               # pragma: no cover
        return {"host_load1": None, "cpu_count": os.cpu_count() or 1,
                "contended": False}
    cpus = os.cpu_count() or 1
    return {"host_load1": round(load1, 2), "cpu_count": cpus,
            "contended": bool(load1 > 0.75 * cpus)}


def _backend_kv_kwargs(kv, pool_blocks=None):
    if kv == "slab":
        return {}
    return {"kv_block_size": KV_BLOCK, "kv_pool_blocks": pool_blocks}


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def make_prompts(n, rng):
    lens = rng.choice((20, 32, 48, 64), size=n)
    return [rng.randint(1, CFG.vocab, size=int(p)).tolist() for p in lens]


def baseline_tokens_per_sec(model, params, slots, rng):
    """Fixed-batch Generator decode tokens/s at batch == num_slots.
    Two generation lengths at the largest bucket's prompt width (the
    Generator cache spans 80..144 rows vs the engine's fixed 128 — the
    closest apples-to-apples the shape-specialized cache allows); the
    slope isolates the decode scan from prefill + sampling setup, and
    min-of-3 rejects scheduler noise."""
    prompt = jnp.asarray(
        rng.randint(1, CFG.vocab, size=(slots, BUCKETS.max_len)),
        jnp.int32)
    times = {}
    for max_new in (16, 80):
        g = Generator(model, GenerationConfig(max_new_tokens=max_new,
                                              temperature=0.0))
        g.generate(params, prompt).block_until_ready()   # compile
        reps = []
        for _ in range(3):
            t0 = time.monotonic()
            g.generate(params, prompt).block_until_ready()
            reps.append(time.monotonic() - t0)
        times[max_new] = min(reps)
    per_tok = (times[80] - times[16]) / (80 - 16)
    return slots / per_tok


def steady_state_tokens_per_sec(model, params, slots, chunk, rng,
                                ticks=20, kv="slab"):
    """Saturated continuous batching: a deep queue keeps every slot
    full across retirements (requests finish, replacements prefill in
    the same tick). Token count from the engine's own emitted-token
    counter, so prefill/retire churn is charged to the number honestly."""
    from pipe_tpu.obs.telemetry import get_registry
    gen_cfg = GenerationConfig(max_new_tokens=MAX_NEW, temperature=0.0)
    backend = SingleDeviceSlotBackend(
        model, params, num_slots=slots, max_len=MAX_LEN, gen=gen_cfg,
        buckets=BUCKETS, decode_chunk=chunk, **_backend_kv_kwargs(kv))
    n_requests = slots * (2 + chunk * ticks // MAX_NEW)
    eng = ServeEngine(backend, RequestQueue(capacity=n_requests + slots))
    for p in make_prompts(n_requests, rng):
        eng.submit(p)
    for _ in range(3):
        eng.tick()              # compile both prefill buckets + decode
    assert eng.live_slots == slots
    counter = get_registry().counter("serve.engine.tokens")
    n0 = counter.value
    t0 = time.monotonic()
    for _ in range(ticks):
        eng.tick()
    dt = time.monotonic() - t0
    assert eng.live_slots == slots      # the queue never ran dry
    return (counter.value - n0) / dt


def make_shared_prefix_prompts(n, rng, shared):
    tails = rng.choice(AB_TAILS, size=n)
    return [shared + rng.randint(1, CFG.vocab, size=int(t)).tolist()
            for t in tails]


def kv_ab_steady_state(model, params, slots, chunk, seed, *, ticks=8,
                       reps=3):
    """Steady-state decode tokens/s on the shared-prefix workload at a
    fixed row budget (``slots * MAX_LEN`` — the slab's footprint at S
    slots): slab at S slots, paged at S slots, paged at 2S slots on the
    SAME memory. The paged pool resumes prefill past the cached prefix
    (one chunk instead of a full bucket) and reserves actual block
    demand instead of max_len rows per slot, so the row budget that
    gives the slab S slots carries 2S live requests. All three engines
    are warmed through their first retirement wave, then measurement
    windows are INTERLEAVED config-by-config with best-of-reps per
    config — scheduler noise on this shared box is bursty over seconds,
    so back-to-back windows of one config would eat a burst whole."""
    from pipe_tpu.obs.telemetry import get_registry
    reg = get_registry()
    counter = reg.counter("serve.engine.tokens")
    pool_blocks = slots * (-(-AB_MAX_LEN // KV_BLOCK)) + 1
    warm = 3 + AB_MAX_NEW // chunk
    cfgs = [("slab", "slab", slots, None),
            ("paged_equal_slots", "paged", slots, pool_blocks),
            ("paged_2x_slots_same_memory", "paged", 2 * slots,
             pool_blocks)]
    hits0 = reg.counter("serve.kv.prefix_hits").value
    miss0 = reg.counter("serve.kv.prefix_misses").value
    engines = {}
    for name, kv, s, pb in cfgs:
        rng = np.random.RandomState(seed)
        gen_cfg = GenerationConfig(max_new_tokens=AB_MAX_NEW,
                                   temperature=0.0)
        backend = SingleDeviceSlotBackend(
            model, params, num_slots=s, max_len=AB_MAX_LEN, gen=gen_cfg,
            buckets=AB_BUCKETS, decode_chunk=chunk,
            **_backend_kv_kwargs(kv, pb))
        n_req = s * (3 + chunk * (reps * ticks + warm) // AB_MAX_NEW)
        eng = ServeEngine(backend, RequestQueue(capacity=n_req + s))
        shared = rng.randint(1, CFG.vocab, size=SHARED_LEN).tolist()
        for p in make_shared_prefix_prompts(n_req, rng, shared):
            eng.submit(p)
        for _ in range(warm):
            eng.tick()
        assert eng.live_slots == s, (name, eng.live_slots, s)
        engines[name] = (eng, s)
    best = {name: 0.0 for name, *_ in cfgs}
    for _ in range(reps):
        for name, kv, s, pb in cfgs:
            eng, _ = engines[name]
            n0 = counter.value
            t0 = time.monotonic()
            for _ in range(ticks):
                eng.tick()
            dt = time.monotonic() - t0
            assert eng.live_slots == s  # the queue never ran dry
            best[name] = max(best[name], (counter.value - n0) / dt)
    hits = reg.counter("serve.kv.prefix_hits").value - hits0
    miss = reg.counter("serve.kv.prefix_misses").value - miss0
    out = {}
    for name, kv, s, pb in cfgs:
        out[name] = {"kv": kv, "live_slots": s,
                     "tokens_s": round(best[name], 1)}
        if kv == "paged":
            out[name]["pool_blocks"] = pb
    out["prefix_hit_rate"] = round(hits / max(hits + miss, 1), 4)
    return out, pool_blocks


def make_multi_tenant_prompts(n, rng, base, tenant_segs):
    out = []
    for i in range(n):
        seg = tenant_segs[i % len(tenant_segs)]
        tail = rng.randint(1, CFG.vocab,
                           size=int(rng.choice(MT_TAILS))).tolist()
        out.append(base + seg + tail)
    return out


def multi_tenant_radix(model, params, slots, chunk, seed, *, n_requests):
    """Gen-2 headline: block-level radix reuse on a multi-tenant
    workload vs the gen-1 whole-prefix counterfactual, plus the TTFT it
    buys. Every request shares the fleet base; tenants diverge after it;
    tails are full random blocks — so no request's full block chain is
    ever cached and a whole-prefix cache reuses NOTHING, while the radix
    tree reuses 12 of ~13 blocks per warm admission. The same prompts
    run again with the prefix cache off to price the reuse in TTFT
    (prefill past cached blocks is skipped, so first tokens come back
    from one chunk instead of a 128-wide bucket sweep)."""
    from pipe_tpu.obs.telemetry import get_registry
    reg = get_registry()
    rng = np.random.RandomState(seed)
    base = rng.randint(1, CFG.vocab, size=MT_BASE_LEN).tolist()
    segs = [rng.randint(1, CFG.vocab, size=MT_TENANT_LEN).tolist()
            for _ in range(MT_TENANTS)]
    prompts = make_multi_tenant_prompts(n_requests, rng, base, segs)
    gen_cfg = GenerationConfig(max_new_tokens=AB_MAX_NEW, temperature=0.0)

    keys = ("prefix_hits", "prefix_misses", "prefix_whole_hits")

    def run(prefix_cache):
        cfg = (gen_cfg if prefix_cache
               else GenerationConfig(max_new_tokens=AB_MAX_NEW,
                                     temperature=0.0, prefix_cache=False))
        backend = SingleDeviceSlotBackend(
            model, params, num_slots=slots, max_len=AB_MAX_LEN, gen=cfg,
            buckets=AB_BUCKETS, decode_chunk=chunk,
            **_backend_kv_kwargs("paged"))
        eng = ServeEngine(backend,
                          RequestQueue(capacity=n_requests + 2 * slots))
        # compile every program (prefill chunks, decode, COW fork)
        # outside the TTFT window; the warm chain is invalidated so the
        # measured run starts from a cold cache either way
        warm = rng.randint(1, CFG.vocab, size=MT_BASE_LEN).tolist()
        eng.serve([warm + [5], warm], seeds=[seed, seed])
        pool = eng.backend.pool
        pool.invalidate(pool.prefix_hashes(warm))
        c0 = {k: reg.counter(f"serve.kv.{k}").value for k in keys}
        resps = eng.serve(prompts, seeds=[seed] * len(prompts))
        return resps, {k: reg.counter(f"serve.kv.{k}").value - c0[k]
                       for k in keys}

    radix_resps, d = run(True)
    radix_ttfts = sorted(r.ttft for r in radix_resps)
    off_ttfts = sorted(r.ttft for r in run(False)[0])
    looked_up = max(d["prefix_hits"] + d["prefix_misses"], 1)
    return {
        "workload": {"base_blocks": MT_BASE_LEN // KV_BLOCK,
                     "tenant_blocks": MT_TENANT_LEN // KV_BLOCK,
                     "tenants": MT_TENANTS, "tails": list(MT_TAILS),
                     "requests": n_requests},
        "radix_hit_block_fraction": round(d["prefix_hits"] / looked_up, 4),
        "whole_prefix_hit_fraction": round(
            d["prefix_whole_hits"] / looked_up, 4),
        "radix_ttft_p50_s": round(
            percentile_exact(radix_ttfts, 0.50), 4),
        "prefix_off_ttft_p50_s": round(
            percentile_exact(off_ttfts, 0.50), 4),
        "ttft_speedup_radix_vs_off": round(
            percentile_exact(off_ttfts, 0.50)
            / max(percentile_exact(radix_ttfts, 0.50), 1e-9), 3),
    }


def kv_offload_drill(model, params, seed):
    """Pressure drill: a pool too small for the working set spills cold
    blocks to host and restores them on re-reference — and the tokens
    must be BITWISE what a roomy pool produces (offload payloads are raw
    storage bytes, never requantized). Serial submissions force the
    evict-then-restore sequence deterministically."""
    from pipe_tpu.obs.telemetry import get_registry
    reg = get_registry()
    rng = np.random.RandomState(seed)
    shared = rng.randint(1, CFG.vocab, size=4 * KV_BLOCK).tolist()
    fillers = [rng.randint(1, CFG.vocab, size=6 * KV_BLOCK).tolist()
               for _ in range(2)]
    prompts = [shared + [3, 5], fillers[0], shared + [7, 9],
               fillers[1], shared + [11]]
    gen_cfg = GenerationConfig(max_new_tokens=16, temperature=0.0)

    def run(pool_blocks, offload):
        backend = SingleDeviceSlotBackend(
            model, params, num_slots=2, max_len=80, gen=gen_cfg,
            kv_block_size=KV_BLOCK, kv_pool_blocks=pool_blocks,
            prefill_chunk=16, kv_offload=offload)
        eng = ServeEngine(backend)
        toks = []
        for p in prompts:
            rid = eng.submit(p, seed=seed).id
            eng.run_until_idle()
            toks.append(np.asarray(eng.response(rid).tokens))
        return toks

    want = run(64, False)                 # roomy: nothing ever spills
    keys = ("offload_out", "offload_restores", "offload_bytes",
            "evictions")
    c0 = {k: reg.counter(f"serve.kv.{k}").value for k in keys}
    got = run(11, True)                   # tight: spill + restore
    d = {k: reg.counter(f"serve.kv.{k}").value - c0[k] for k in keys}
    bitwise = all(np.array_equal(a, b) for a, b in zip(got, want))
    return {"pool_blocks": 11, "requests": len(prompts),
            "blocks_offloaded": d["offload_out"],
            "blocks_restored": d["offload_restores"],
            "offload_bytes": d["offload_bytes"],
            "evictions": d["evictions"],
            "bitwise_equal_to_unpressured": bool(bitwise)}


RES_HORIZON = 8


def resident_steady_state(model, params, slots, seed, *, resident,
                          rounds, reps=2):
    """Steady-state tokens/s + host-overhead-per-token for one engine
    at ``slots`` live slots, decode_chunk=1. ``rounds`` counts resident
    launches; the non-resident engine runs ``rounds * RES_HORIZON``
    single-chunk ticks so both cover the same token volume. Best of
    ``reps`` measurement windows (tokens/s max, overhead min — both
    reject scheduler noise in the same direction)."""
    from pipe_tpu.obs.telemetry import get_registry
    reg = get_registry()
    tok_c = reg.counter("serve.engine.tokens")
    host_t = reg.timer("serve.engine.host_sec")
    sync_c = reg.counter("serve.engine.host_syncs")
    gen_cfg = GenerationConfig(max_new_tokens=MAX_NEW, temperature=0.0)
    kw = (dict(resident=True, resident_chunks=RES_HORIZON)
          if resident else dict(resident=False))
    backend = SingleDeviceSlotBackend(
        model, params, num_slots=slots, max_len=MAX_LEN, gen=gen_cfg,
        buckets=BUCKETS, decode_chunk=1, **kw)
    ticks = rounds if resident else rounds * RES_HORIZON
    warm = 3 if resident else 3 * RES_HORIZON
    per_slot = (warm + reps * ticks) * (RES_HORIZON if resident else 1)
    n_req = slots * (4 + 2 * per_slot // MAX_NEW)
    rng = np.random.RandomState(seed)
    eng = ServeEngine(backend, RequestQueue(capacity=n_req + slots))
    for p in make_prompts(n_req, rng):
        eng.submit(p)
    for _ in range(warm):
        eng.tick()
    assert eng.live_slots == slots
    best_tps, best_oh, syncs_per_tok = 0.0, float("inf"), 0.0
    for _ in range(reps):
        n0, h0, s0 = tok_c.value, host_t.total, sync_c.value
        t0 = time.monotonic()
        for _ in range(ticks):
            eng.tick()
        dt = time.monotonic() - t0
        assert eng.live_slots == slots      # the queue never ran dry
        n = tok_c.value - n0
        best_tps = max(best_tps, n / dt)
        best_oh = min(best_oh, (host_t.total - h0) / max(n, 1))
        syncs_per_tok = (sync_c.value - s0) / max(n, 1)
    return {"tokens_s": round(best_tps, 1),
            "host_overhead_per_token_us": round(best_oh * 1e6, 2),
            "host_syncs_per_token": round(syncs_per_tok, 4),
            "live_slots": slots}


def resident_ab(model, params, slots, seed, *, rounds, reps=2):
    """The PR 11 A/B: non-resident single-chunk ticks vs the resident
    ``lax.while_loop`` at EQUAL live slots and equal token volume. The
    resident loop's job is the host-overhead-per-token column; the
    tokens/s column is the no-regression bar."""
    non = resident_steady_state(model, params, slots, seed,
                                resident=False, rounds=rounds, reps=reps)
    res = resident_steady_state(model, params, slots, seed,
                                resident=True, rounds=rounds, reps=reps)
    return {
        "horizon_chunks": RES_HORIZON,
        "decode_chunk": 1,
        "nonresident": non,
        "resident": res,
        "resident_vs_nonresident_tokens_s": round(
            res["tokens_s"] / max(non["tokens_s"], 1e-9), 4),
        "host_overhead_reduction": round(
            non["host_overhead_per_token_us"]
            / max(res["host_overhead_per_token_us"], 1e-9), 2),
    }


def spec_acceptance(model, params, seed, *, n_prompts=4, max_new=32,
                    spec_tokens=3):
    """Speculative lane on draftable (repetitive) prompts: bitwise
    parity vs the per-prompt Generator, acceptance rate from the
    engine's own round/emission counters."""
    from pipe_tpu.obs.telemetry import get_registry
    reg = get_registry()
    rng = np.random.RandomState(seed)
    prompts = []
    for _ in range(n_prompts):
        pair = rng.randint(1, CFG.vocab, size=2).tolist()
        prompts.append(pair * 4)
    gen_cfg = GenerationConfig(max_new_tokens=max_new, temperature=0.0)
    g = Generator(model, gen_cfg)
    refs = [np.asarray(g.generate(
        params, jnp.asarray(p, jnp.int32)[None],
        jax.random.key(seed + i)))[0] for i, p in enumerate(prompts)]
    backend = SingleDeviceSlotBackend(
        model, params, num_slots=2, max_len=MAX_LEN, gen=gen_cfg,
        buckets=BUCKETS, resident=True, resident_chunks=RES_HORIZON,
        spec_tokens=spec_tokens)
    rounds0 = reg.counter("serve.engine.spec_rounds").value
    emitted0 = reg.counter("serve.engine.spec_emitted").value
    eng = ServeEngine(backend)
    resps = eng.serve(prompts,
                      seeds=[seed + i for i in range(n_prompts)])
    equal = all(
        np.array_equal(np.asarray(r.tokens), ref)
        for r, ref in zip(resps, refs))
    rounds = reg.counter("serve.engine.spec_rounds").value - rounds0
    emitted = reg.counter("serve.engine.spec_emitted").value - emitted0
    return {
        "spec_tokens": spec_tokens,
        "prompts": n_prompts,
        "max_new_tokens": max_new,
        "bitwise_equal_to_generator": bool(equal),
        "verify_rounds": int(rounds),
        "tokens_emitted": int(emitted),
        "tokens_per_round": round(emitted / max(rounds, 1), 3),
        # accepted drafts per offered draft (K-1 offered per round)
        "acceptance_rate": round(
            (emitted - rounds) / max(rounds * (spec_tokens - 1), 1), 4),
    }


SPEC_K = 4          # draft depth: 1 committed + K-1 offered per round
SPEC_STAGES = 4     # logical stages of the spec bench model (1-layer draft prefix)
SPEC_MAX_NEW = 32
_SPEC_EPS = 0.01


def _spec_bench_params(params, eps=_SPEC_EPS):
    """Derived weights for the gen-2 spec section. Two surgeries, both
    argmax-preserving for the FULL model:

    * the decoder is tied to the embedding table (``w = table.T``,
      ``b = 0``) — the same matrix the truncated/tree draft head
      scores tokens with;
    * every block's residual branch (attention out-projection, ffn
      second matmul) is scaled by ``eps`` — each post-LN block then
      nearly rescales its (already layer-normed) input instead of
      rotating it, so the hidden the stage-0 draft head reads already
      points at the argmax the full-depth verify head picks.

    Acceptance becomes a property of the DRAFT SOURCE rather than of
    prompt repetition: the model-based drafts track verify
    near-perfectly, while the next-token map stays position-driven
    (embedding + positional code) — an n-gram history lookup only
    scores where the emitted stream happens to revisit old contexts,
    a fraction of what the model drafts accept.
    """
    stages, pre, post = params
    out_stages = []
    for stage in stages:
        out_stage = []
        for bp in stage:
            bp = {k: dict(v) for k, v in bp.items()}
            bp["attn"]["wo"] = bp["attn"]["wo"] * eps
            bp["attn"]["bo"] = bp["attn"]["bo"] * eps
            bp["ff2"]["w"] = bp["ff2"]["w"] * eps
            bp["ff2"]["b"] = bp["ff2"]["b"] * eps
            out_stage.append(bp)
        out_stages.append(out_stage)
    table = pre["embed"]["table"]
    post = {"decoder": {
        "w": table.T.astype(post["decoder"]["w"].dtype),
        "b": jnp.zeros_like(post["decoder"]["b"])}}
    return out_stages, pre, post


def _spec_drive(model, params, prompts, seed, *, draft, branches=None):
    """Serve ``prompts`` through one draft source; acceptance from the
    engine's round/emission counters, parity vs the per-prompt
    Generator."""
    from pipe_tpu.obs.telemetry import get_registry
    reg = get_registry()
    gen_cfg = GenerationConfig(max_new_tokens=SPEC_MAX_NEW,
                               temperature=0.0)
    g = Generator(model, gen_cfg)
    refs = [np.asarray(g.generate(
        params, jnp.asarray(p, jnp.int32)[None],
        jax.random.key(seed + i)))[0] for i, p in enumerate(prompts)]
    pad = (branches or 1) * (SPEC_K - 1)    # rollback overwrite room
    backend = SingleDeviceSlotBackend(
        model, params, num_slots=2, max_len=MAX_LEN + pad, gen=gen_cfg,
        buckets=BUCKETS, resident=True, resident_chunks=RES_HORIZON,
        spec_tokens=SPEC_K, draft=draft, spec_branches=branches)
    r0 = reg.counter("serve.engine.spec_rounds").value
    e0 = reg.counter("serve.engine.spec_emitted").value
    eng = ServeEngine(backend)
    resps = eng.serve(prompts,
                      seeds=[seed + i for i in range(len(prompts))])
    equal = all(np.array_equal(np.asarray(r.tokens), ref)
                for r, ref in zip(resps, refs))
    rounds = reg.counter("serve.engine.spec_rounds").value - r0
    emitted = reg.counter("serve.engine.spec_emitted").value - e0
    out = {"bitwise_equal_to_generator": bool(equal),
           "verify_rounds": int(rounds),
           "tokens_per_round": round(emitted / max(rounds, 1), 3),
           "acceptance_rate": round(
               (emitted - rounds) / max(rounds * (SPEC_K - 1), 1), 4),
           "draft_cost_frac": round(float(
               reg.gauge("serve.spec.draft_cost_frac").value), 4)}
    if branches:
        out["branches"] = branches
    return out


def _spec_steady(model, params, slots, seed, *, spec_kw, max_len,
                 ticks, reps):
    """Steady-state (tokens/s, spec-rounds/s) for one resident engine —
    ``resident_steady_state``'s measurement loop with the spec lane's
    knobs threaded through and the round counter sampled alongside the
    token counter (the round rate is what prices the verify chunk)."""
    from pipe_tpu.obs.telemetry import get_registry
    reg = get_registry()
    tok_c = reg.counter("serve.engine.tokens")
    rnd_c = reg.counter("serve.engine.spec_rounds")
    gen_cfg = GenerationConfig(max_new_tokens=MAX_NEW, temperature=0.0)
    backend = SingleDeviceSlotBackend(
        model, params, num_slots=slots, max_len=max_len, gen=gen_cfg,
        buckets=BUCKETS, decode_chunk=1, resident=True,
        resident_chunks=RES_HORIZON, **spec_kw)
    k = spec_kw.get("spec_tokens") or 1
    per_slot = (3 + reps * ticks) * RES_HORIZON * k
    n_req = slots * (4 + 2 * per_slot // MAX_NEW)
    rng = np.random.RandomState(seed)
    eng = ServeEngine(backend, RequestQueue(capacity=n_req + slots))
    for p in make_prompts(n_req, rng):
        eng.submit(p)
    for _ in range(3):
        eng.tick()
    trc_c = reg.counter("serve.engine.resident_traces")
    trc0 = trc_c.value                      # warm compiled everything
    best_tps, best_rps = 0.0, 0.0
    for _ in range(reps):
        n0, r0 = tok_c.value, rnd_c.value
        t0 = time.monotonic()
        for _ in range(ticks):
            eng.tick()
        dt = time.monotonic() - t0
        # With acceptance ~1 a request retires every SECOND launch, so
        # unlike the nonspec sections a window end can land on the
        # retire tick itself (live drops until the next tick's
        # admission). The occupancy invariant that matters for the A/B
        # is that admission always had work waiting: the queue never
        # ran dry inside the window.
        assert len(eng.queue) > 0
        best_tps = max(best_tps, (tok_c.value - n0) / dt)
        best_rps = max(best_rps, (rnd_c.value - r0) / dt)
    return best_tps, best_rps, trc_c.value - trc0


# The ring needs >= 2 devices and this process already initialized the
# single-device backend, so the drill re-inits jax on the 2-virtual-chip
# CPU platform in a child interpreter (the conftest trick).
_RING_DRILL_SRC = r"""
import json, os, sys

sys.path.insert(0, os.environ["PIPE_TPU_ROOT"])
sys.path.insert(0, os.path.join(os.environ["PIPE_TPU_ROOT"], "tools"))
from pipe_tpu.utils.platform import force_cpu_platform
force_cpu_platform(num_devices=2)   # before backend init

import jax
import jax.numpy as jnp
import numpy as np

import serve_bench as sb
from pipe_tpu.inference import GenerationConfig, Generator
from pipe_tpu.obs.telemetry import get_registry
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.spmd import stack_stage_params
from pipe_tpu.serve import RingSlotBackend, ServeEngine

seed = int(sys.argv[1])
model = sb.PipelinedLM(sb.CFG, 2)          # one stage per ring chip
sp, pre, post = sb._spec_bench_params(model.init(jax.random.key(1)))
stacked = stack_stage_params(sp)
rng = np.random.RandomState(seed)
prompts = sb.make_prompts(3, rng)
reg = get_registry()


def drive(backend):
    # staggered arrivals: slot churn exercises relaunches + the
    # stale-round discard, not one clean batch
    eng = ServeEngine(backend)
    ids = [eng.submit(prompts[0], seed=seed).id]
    eng.tick()
    ids += [eng.submit(p, seed=seed).id for p in prompts[1:]]
    eng.run_until_idle()
    return [list(eng.response(i).tokens) for i in ids]


out = {"spec_tokens": sb.SPEC_K, "draft": "truncated",
       "prompts": len(prompts)}
for name, temp in (("greedy", 0.0), ("sampled", 0.8)):
    gen_cfg = GenerationConfig(max_new_tokens=16, temperature=temp,
                               top_k=12 if temp else None)
    g = Generator(model, gen_cfg)
    refs = [np.asarray(g.generate((sp, pre, post),
                                  jnp.asarray(p, jnp.int32)[None],
                                  jax.random.key(seed)))[0]
            for p in prompts]
    backend = RingSlotBackend(
        make_mesh(2, 1), model, stacked, pre, post,
        max_len=96 + sb.SPEC_K, gen=gen_cfg, buckets=sb.BUCKETS,
        resident=True, resident_revolutions=4,
        spec_tokens=sb.SPEC_K, draft="truncated")
    t0 = reg.counter("serve.ring.resident_traces").value
    r0 = reg.counter("serve.engine.spec_rounds").value
    e0 = reg.counter("serve.engine.spec_emitted").value
    got = drive(backend)
    warm = reg.counter("serve.ring.resident_traces").value - t0
    rounds = reg.counter("serve.engine.spec_rounds").value - r0
    emitted = reg.counter("serve.engine.spec_emitted").value - e0
    got2 = drive(backend)      # warm steady state: same traffic again
    out[name] = {
        "bitwise_equal_to_generator": bool(
            all(np.array_equal(np.asarray(a), r)
                for a, r in zip(got, refs)) and got2 == got),
        "verify_rounds": int(rounds),
        "acceptance_rate": round(
            (emitted - rounds) / max(rounds * (sb.SPEC_K - 1), 1), 4),
        "warm_traces": int(warm),
        "steady_state_new_traces": int(
            reg.counter("serve.ring.resident_traces").value - t0 - warm),
    }
print("RING_DRILL " + json.dumps(out))
"""


def _ring_spec_drill(seed):
    """Ring-backend spec on the same tied-head weights: truncated
    drafts ride the split-key chain through the revolutions, greedy AND
    sampled output stays bitwise the Generator, and re-serving the same
    traffic shape traces zero new ring programs."""
    import subprocess
    env = dict(os.environ,
               PIPE_TPU_ROOT=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))),
               JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", _RING_DRILL_SRC,
                           str(seed)], capture_output=True, text=True,
                          timeout=1800, env=env)
    for line in proc.stdout.splitlines():
        if line.startswith("RING_DRILL "):
            return json.loads(line[len("RING_DRILL "):])
    raise RuntimeError(f"ring spec drill produced no result "
                       f"(rc={proc.returncode}):\n"
                       f"{proc.stdout[-2000:]}{proc.stderr[-2000:]}")


def spec_gen2(slots, seed, *, quick):
    """Gen-2 speculative section: draft-source acceptance shoot-out +
    the measured-breakeven closed loop, on the tied-head bench weights
    (aperiodic prompts, greedy — every number is also a parity pin)."""
    from pipe_tpu.core.planner import (spec_breakeven_acceptance,
                                       spec_speedup)
    model = PipelinedLM(CFG, SPEC_STAGES)
    params = _spec_bench_params(model.init(jax.random.key(1)))
    rng = np.random.RandomState(seed)
    prompts = make_prompts(3 if quick else 4, rng)
    sources = [("ngram", None), ("truncated", None)]
    if not quick:
        sources.append(("tree", 3))
    per_source = {}
    for draft, branches in sources:
        log(f"  draft={draft}...")
        per_source[draft] = _spec_drive(model, params, prompts,
                                        seed, draft=draft,
                                        branches=branches)

    # Spec vs non-spec resident loop at EQUAL live slots, same
    # weights, same prompt mix. The verify-chunk cost ratio is
    # MEASURED, not assumed: non-spec emits one token per chunk step
    # (R1 = tokens/s), spec runs one K-row verify chunk per round
    # (R2 = rounds/s), and the round buys its draft on top — so
    # r = (R1/R2) * (1 - f). Feeding r back through the planner
    # closes the loop: the artifact records the breakeven acceptance
    # this host actually imposes next to the acceptance and speedup
    # it actually measured.
    ticks = 3 if quick else 8
    reps = 2 if quick else 3
    non_tps, _, _ = _spec_steady(model, params, slots, seed + 1,
                                 spec_kw={}, max_len=MAX_LEN,
                                 ticks=ticks, reps=reps)
    spec_tps, spec_rps, spec_traces = _spec_steady(
        model, params, slots, seed + 1,
        spec_kw=dict(spec_tokens=SPEC_K, draft="truncated"),
        max_len=MAX_LEN + SPEC_K - 1, ticks=ticks, reps=reps)
    f = per_source["truncated"]["draft_cost_frac"]
    a = per_source["truncated"]["acceptance_rate"]
    r = (non_tps / max(spec_rps, 1e-9)) * (1.0 - f)
    out_ring = None
    if not quick:
        log("  ring spec drill (subprocess, 2 virtual chips)...")
        out_ring = _ring_spec_drill(seed + 2)
    return {
        "spec_tokens": SPEC_K,
        "model_stages": SPEC_STAGES,
        "draft_stages": 1,
        "max_new_tokens": SPEC_MAX_NEW,
        "prompts": len(prompts),
        "draft_sources": per_source,
        "throughput": {
            "live_slots": slots,
            "nonspec_tokens_s": round(non_tps, 1),
            "spec_tokens_s": round(spec_tps, 1),
            "spec_vs_nonspec_tokens_s": round(
                spec_tps / max(non_tps, 1e-9), 4),
            "spec_rounds_s": round(spec_rps, 1),
            "acceptance": a,
            "draft_cost_frac": f,
            "chunk_cost_ratio_measured": round(r, 4),
            "breakeven_acceptance": round(
                spec_breakeven_acceptance(f, SPEC_K, r), 4),
            "predicted_speedup": round(
                spec_speedup(a, f, SPEC_K, r), 4),
            # measured-window recompiles of the spec resident program
            # (fixed K, no adaptive ladder in play -> must be zero)
            "steady_state_new_traces": int(spec_traces),
        },
        **({"ring": out_ring} if out_ring else {}),
    }


def drive_poisson(eng, prompts, arrivals, *, max_new, deadline_s):
    """Feed the engine a precomputed arrival schedule against the wall
    clock; tick until drained. Returns (responses, elapsed, rejected)."""
    t0 = time.monotonic()
    i, rejected, finished, peak_live = 0, 0, [], 0
    while i < len(arrivals) or not eng.idle:
        now = time.monotonic() - t0
        while i < len(arrivals) and arrivals[i] <= now:
            try:
                eng.submit(prompts[i], seed=i, max_new_tokens=max_new,
                           timeout_s=deadline_s)
            except QueueFull:
                rejected += 1
            i += 1
        if eng.idle and i < len(arrivals):
            time.sleep(min(arrivals[i] - now, 0.002))
            continue
        finished.extend(eng.tick())
        peak_live = max(peak_live, eng.live_slots)
    return finished, time.monotonic() - t0, rejected, peak_live


def load_run(model, params, slots, chunk, rng, *, n_requests, rate,
             max_new, deadline_s, capacity, kv="slab", pool_blocks=None,
             prompts=None, max_len=MAX_LEN, buckets=BUCKETS):
    gen_cfg = GenerationConfig(max_new_tokens=max_new, temperature=0.0)
    backend = SingleDeviceSlotBackend(
        model, params, num_slots=slots, max_len=max_len, gen=gen_cfg,
        buckets=buckets, decode_chunk=chunk,
        **_backend_kv_kwargs(kv, pool_blocks))
    eng = ServeEngine(backend, RequestQueue(capacity=capacity))
    # warm every program before the clock matters
    for p in ([1] * 20, [1] * 40):
        eng.submit(p, max_new_tokens=1)
    eng.run_until_idle()

    if prompts is None:
        prompts = make_prompts(n_requests, rng)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_requests))
    from pipe_tpu.obs.telemetry import get_registry
    reg = get_registry()
    hits0 = reg.counter("serve.kv.prefix_hits").value
    miss0 = reg.counter("serve.kv.prefix_misses").value
    blocked0 = reg.counter("serve.kv.admission_blocked").value
    finished, elapsed, rejected, peak_live = drive_poisson(
        eng, prompts, arrivals, max_new=max_new, deadline_s=deadline_s)
    ok = [r for r in finished if r.status == "ok"]
    ttfts = sorted(r.ttft for r in ok)
    kv_stats = {}
    if kv == "paged":
        hits = reg.counter("serve.kv.prefix_hits").value - hits0
        miss = reg.counter("serve.kv.prefix_misses").value - miss0
        kv_stats = {
            "prefix_hit_rate": round(hits / max(hits + miss, 1), 4),
            "admission_blocked":
                reg.counter("serve.kv.admission_blocked").value - blocked0,
        }
    return {
        "requests": n_requests,
        "offered_rate_req_s": round(rate, 3),
        "elapsed_s": round(elapsed, 3),
        "ok": len(ok),
        "timeout": sum(r.status == "timeout" for r in finished),
        "cancelled": sum(r.status == "cancelled" for r in finished),
        "rejected": rejected,
        "goodput_tokens_s": round(
            sum(len(r.tokens) for r in ok) / elapsed, 1),
        "ttft_p50_s": round(percentile_exact(ttfts, 0.50), 4),
        "ttft_p99_s": round(percentile_exact(ttfts, 0.99), 4),
        "peak_live_slots": peak_live,
        **kv_stats,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small run; single-line JSON summary")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--chunk", type=int, default=8,
                    help="decode_chunk: tokens per host round-trip")
    ap.add_argument("--kv", choices=("slab", "paged"), default="slab",
                    help="KV memory for the steady-state/latency "
                         "sections (the kv A/B section always runs both)")
    ap.add_argument("--resident", action="store_true",
                    help="full-size resident A/B + speculative-decode "
                         "section (quick mode always runs a small "
                         "resident A/B for the CI embed)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    rng = np.random.RandomState(args.seed)
    model = PipelinedLM(CFG, 1)
    params = model.init(jax.random.key(0))
    slots, chunk = args.slots, args.chunk

    log("baseline: fixed-batch Generator decode slope...")
    base_tps = baseline_tokens_per_sec(model, params, slots, rng)
    log(f"  {base_tps:.1f} tokens/s at batch={slots}")

    log(f"steady state: engine with every slot full (kv={args.kv})...")
    ticks = 8 if args.quick else 24
    serve_tps = steady_state_tokens_per_sec(model, params, slots, chunk,
                                            rng, ticks=ticks, kv=args.kv)
    ratio = serve_tps / base_tps
    log(f"  {serve_tps:.1f} tokens/s ({ratio:.3f}x fixed-batch)")

    # KV A/B on the shared-prefix workload at a FIXED row budget
    # (slots * AB_MAX_LEN rows == the slab's footprint at S slots): slab
    # at S slots, paged at S slots (the parity bar: paged must not lose
    # at equal concurrency), paged at 2S slots on the SAME memory — the
    # headline the pool buys. 2S only fits because the prefix blocks are
    # shared: 8 live requests need 14 shared + 8x5 private = 54 blocks
    # of the 76 allocatable, where private slabs would need 152.
    log("kv A/B: shared-prefix workload, slab vs paged...")
    ab, pool_blocks = kv_ab_steady_state(
        model, params, slots, chunk, args.seed + 2,
        ticks=8 if args.quick else 12, reps=3 if args.quick else 5)
    kv_slab = ab["slab"]
    kv_paged = ab["paged_equal_slots"]
    kv_paged_2x = ab["paged_2x_slots_same_memory"]
    kv_ab = {
        "workload": {"shared_prefix": SHARED_LEN,
                     "tails": list(AB_TAILS),
                     "max_new_tokens": AB_MAX_NEW,
                     "max_len": AB_MAX_LEN},
        "kv_memory_rows": slots * AB_MAX_LEN,
        "slab": kv_slab,
        "paged_equal_slots": kv_paged,
        "paged_2x_slots_same_memory": kv_paged_2x,
        "prefix_hit_rate": ab["prefix_hit_rate"],
        "paged_vs_slab_equal_slots": round(
            kv_paged["tokens_s"] / kv_slab["tokens_s"], 4),
        "paged_2x_vs_slab": round(
            kv_paged_2x["tokens_s"] / kv_slab["tokens_s"], 4),
        "live_slot_gain_same_memory": round(
            kv_paged_2x["live_slots"] / kv_slab["live_slots"], 2),
    }
    log(f"  slab {kv_slab['tokens_s']:.1f} tok/s @ {slots} slots; paged "
        f"{kv_paged['tokens_s']:.1f} tok/s @ {slots} slots "
        f"({kv_ab['paged_vs_slab_equal_slots']:.3f}x); paged "
        f"{kv_paged_2x['tokens_s']:.1f} tok/s @ {2 * slots} slots on the "
        f"same memory (hit rate {ab['prefix_hit_rate']:.3f})")

    # Gen-2 radix headline: multi-tenant reuse a whole-prefix cache
    # can't see, and the TTFT the skipped prefill buys.
    log("kv radix: multi-tenant workload vs whole-prefix "
        "counterfactual...")
    radix = multi_tenant_radix(model, params, slots, chunk,
                               args.seed + 6,
                               n_requests=12 if args.quick else 36)
    log(f"  radix hit fraction {radix['radix_hit_block_fraction']:.3f} "
        f"vs whole-prefix {radix['whole_prefix_hit_fraction']:.3f}; "
        f"ttft p50 {radix['radix_ttft_p50_s']:.4f}s vs "
        f"{radix['prefix_off_ttft_p50_s']:.4f}s cache-off "
        f"({radix['ttft_speedup_radix_vs_off']:.2f}x)")

    log("kv offload: evict-to-host + restore drill...")
    offload = kv_offload_drill(model, params, args.seed + 7)
    log(f"  spilled {offload['blocks_offloaded']} restored "
        f"{offload['blocks_restored']} blocks, bitwise="
        f"{offload['bitwise_equal_to_unpressured']}")

    # Resident loop A/B at equal live slots and equal token volume:
    # host-overhead-per-token is the number the fused loop exists to
    # shrink; tokens/s is the no-regression bar. Forced on explicitly —
    # "auto" keeps cpu on the single-chunk path, so this measures the
    # mechanism the accelerator default gets.
    log("resident A/B: single-chunk ticks vs the fused device loop...")
    res_ab = resident_ab(model, params, slots, args.seed + 4,
                         rounds=4 if args.quick else 10,
                         reps=2 if args.quick else 3)
    log(f"  non-resident {res_ab['nonresident']['tokens_s']:.1f} tok/s @ "
        f"{res_ab['nonresident']['host_overhead_per_token_us']:.1f} "
        f"us/tok host; resident {res_ab['resident']['tokens_s']:.1f} "
        f"tok/s @ {res_ab['resident']['host_overhead_per_token_us']:.1f} "
        f"us/tok ({res_ab['host_overhead_reduction']:.1f}x less host, "
        f"{res_ab['resident_vs_nonresident_tokens_s']:.3f}x tokens/s)")

    # Gen-2 speculative: draft-source shoot-out + measured breakeven
    # on the tied-head weights (both modes — bench.py gates the quick
    # fields; the full run is the SERVE_r18 record).
    log("spec gen-2: draft sources on tied-head weights...")
    spec2 = spec_gen2(slots, args.seed + 8, quick=args.quick)
    sp_src, sp_thr = spec2["draft_sources"], spec2["throughput"]
    log(f"  acceptance ngram {sp_src['ngram']['acceptance_rate']:.3f} "
        f"vs truncated {sp_src['truncated']['acceptance_rate']:.3f}"
        + (f" vs tree {sp_src['tree']['acceptance_rate']:.3f}"
           if "tree" in sp_src else "")
        + f"; spec {sp_thr['spec_vs_nonspec_tokens_s']:.3f}x non-spec "
        f"(breakeven a*={sp_thr['breakeven_acceptance']:.3f}, "
        f"predicted {sp_thr['predicted_speedup']:.3f}x)")

    # capacity in requests/s at the bench's request size
    max_new = MAX_NEW
    cap_req_s = serve_tps / max_new

    log("poisson @ 0.7x capacity...")
    n = 12 if args.quick else 48
    moderate = load_run(model, params, slots, chunk, rng,
                        n_requests=n, rate=0.7 * cap_req_s,
                        max_new=max_new, deadline_s=30.0,
                        capacity=4 * slots, kv=args.kv)

    host = host_contention()
    summary = {
        "bench": "serve_bench",
        "platform": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        **host,
        "slots": slots,
        "decode_chunk": chunk,
        "kv": args.kv,
        "buckets": list(BUCKETS.lengths),
        "max_new_tokens": max_new,
        "baseline_fixed_batch_tokens_s": round(base_tps, 1),
        "steady_state_tokens_s": round(serve_tps, 1),
        "serve_vs_fixed_batch": round(ratio, 4),
        "kv_ab": kv_ab,
        "kv_radix_multi_tenant": radix,
        "kv_offload_drill": offload,
        "resident_ab": res_ab,
        "speculative_gen2": spec2,
        "poisson_0p7": moderate,
    }
    if args.quick:
        print(json.dumps({
            "steady_state_tokens_s": summary["steady_state_tokens_s"],
            "serve_vs_fixed_batch": summary["serve_vs_fixed_batch"],
            "ttft_p50_s": moderate["ttft_p50_s"],
            "ttft_p99_s": moderate["ttft_p99_s"],
            "goodput_tokens_s": moderate["goodput_tokens_s"],
            "kv_paged_vs_slab_equal_slots":
                kv_ab["paged_vs_slab_equal_slots"],
            "kv_paged_2x_vs_slab": kv_ab["paged_2x_vs_slab"],
            "kv_live_slot_gain": kv_ab["live_slot_gain_same_memory"],
            "kv_prefix_hit_rate": kv_ab["prefix_hit_rate"],
            "kv_radix_hit_block_fraction":
                radix["radix_hit_block_fraction"],
            "kv_whole_prefix_hit_fraction":
                radix["whole_prefix_hit_fraction"],
            "kv_ttft_speedup_radix_vs_off":
                radix["ttft_speedup_radix_vs_off"],
            "kv_offload_bitwise":
                offload["bitwise_equal_to_unpressured"],
            "kv_offload_restores": offload["blocks_restored"],
            "resident_vs_nonresident_tokens_s":
                res_ab["resident_vs_nonresident_tokens_s"],
            "host_overhead_reduction":
                res_ab["host_overhead_reduction"],
            "spec_bitwise": all(
                s["bitwise_equal_to_generator"]
                for s in sp_src.values()),
            "spec_acceptance_ngram": sp_src["ngram"]["acceptance_rate"],
            "spec_acceptance_truncated":
                sp_src["truncated"]["acceptance_rate"],
            "spec_vs_nonspec_tokens_s":
                sp_thr["spec_vs_nonspec_tokens_s"],
            "spec_breakeven_acceptance":
                sp_thr["breakeven_acceptance"],
            "spec_steady_new_traces":
                sp_thr["steady_state_new_traces"],
            "contended": host["contended"],
        }))
        return

    if args.resident:
        log("speculative decode: draft/verify on repetitive prompts...")
        spec = spec_acceptance(model, params, args.seed + 5)
        summary["speculative"] = spec
        log(f"  bitwise={spec['bitwise_equal_to_generator']} "
            f"acceptance={spec['acceptance_rate']:.3f} "
            f"({spec['tokens_per_round']:.2f} tokens/verify-round)")

    # 2x overload: backpressure bounds the queue so the engine only
    # accepts what it can finish inside the deadline; without it the
    # queue absorbs everything and requests expire waiting (reaped before
    # prefill) or mid-decode (slot-steps burnt for zero goodput).
    # Deadline sized so a bounded queue's wait (<= capacity/service
    # rate) fits comfortably but an unbounded queue's does not — the
    # regime where shedding at the door beats accepting work that will
    # die waiting or burn slot-steps before timing out mid-decode.
    log("overload 2x, backpressure ON (bounded queue)...")
    n_over = 96
    deadline = 1.0
    on = load_run(model, params, slots, chunk,
                  np.random.RandomState(args.seed + 1),
                  n_requests=n_over, rate=2.0 * cap_req_s,
                  max_new=max_new, deadline_s=deadline,
                  capacity=2 * slots)
    log("overload 2x, backpressure OFF (unbounded queue)...")
    off = load_run(model, params, slots, chunk,
                   np.random.RandomState(args.seed + 1),
                   n_requests=n_over, rate=2.0 * cap_req_s,
                   max_new=max_new, deadline_s=deadline,
                   capacity=100000)
    summary["overload_2x"] = {
        "deadline_s": deadline,
        "backpressure_on": on,
        "backpressure_off": off,
        "goodput_ratio_on_vs_off": round(
            on["goodput_tokens_s"] / max(off["goodput_tokens_s"], 1e-9),
            3),
    }

    # Shared-prefix Poisson A/B: identical prompts and arrival schedule
    # (common 112-token system prompt, Poisson arrivals at 0.55x the
    # paged-2S engine's measured steady-state capacity) against slab-S
    # and paged-2S engines on the SAME KV row budget. The admission gain
    # is structural and shows up directly: the paged run carries up to
    # 2S concurrent requests (peak_live_slots) on memory that caps the
    # slab at S, with every admission past the first a prefix-cache hit
    # and zero pool-admission blocks — at goodput parity. (On this
    # host-bound micro-model the extra concurrency buys headroom, not
    # extra tokens/s; the steady-state A/B above prices the throughput.)
    log("kv poisson: shared-prefix load, slab S vs paged 2S...")
    sh_rng = np.random.RandomState(args.seed + 3)
    shared = sh_rng.randint(1, CFG.vocab, size=SHARED_LEN).tolist()
    n_kv = 96
    kv_prompts = make_shared_prefix_prompts(n_kv, sh_rng, shared)
    kv_rate = 0.55 * kv_paged_2x["tokens_s"] / AB_MAX_NEW
    kv_slab_load = load_run(model, params, slots, chunk,
                            np.random.RandomState(args.seed + 3),
                            n_requests=n_kv, rate=kv_rate,
                            max_new=AB_MAX_NEW, deadline_s=30.0,
                            capacity=12 * slots, prompts=kv_prompts,
                            max_len=AB_MAX_LEN, buckets=AB_BUCKETS)
    kv_paged_load = load_run(model, params, 2 * slots, chunk,
                             np.random.RandomState(args.seed + 3),
                             n_requests=n_kv, rate=kv_rate,
                             max_new=AB_MAX_NEW, deadline_s=30.0,
                             capacity=12 * slots, kv="paged",
                             pool_blocks=pool_blocks, prompts=kv_prompts,
                             max_len=AB_MAX_LEN, buckets=AB_BUCKETS)
    summary["kv_poisson_shared_prefix"] = {
        "offered_rate_req_s": round(kv_rate, 3),
        "kv_memory_rows": slots * AB_MAX_LEN,
        "slab": kv_slab_load,
        "paged_2x_slots_same_memory": kv_paged_load,
        "goodput_ratio_paged_vs_slab": round(
            kv_paged_load["goodput_tokens_s"]
            / max(kv_slab_load["goodput_tokens_s"], 1e-9), 3),
        "live_slot_gain_same_memory": round(
            kv_paged_load["peak_live_slots"]
            / max(kv_slab_load["peak_live_slots"], 1), 2),
    }
    print(json.dumps(summary, indent=2))


if __name__ == "__main__":
    main()
