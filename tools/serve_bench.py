"""Serving benchmark: the continuous-batching engine under synthetic load.

Three questions, answered on whatever backend is available (the numbers
of record are the committed ``SERVE_r08.json``):

1. **Slot tax** — steady-state decode tokens/s with every slot
   continuously full, vs the fixed-batch ``Generator`` at the same live
   count (batch = num_slots). The engine's decode step is the batched
   per-slot program (vmapped positions, per-slot key chains) plus one
   host round-trip per ``decode_chunk`` tokens; the acceptance bar is
   >= 0.9x the one-shot batch program.
2. **Latency under load** — seeded Poisson arrivals at a fraction of
   measured capacity; per-request TTFT p50/p99
   (:func:`pipe_tpu.obs.telemetry.percentile_exact` — the streaming
   histogram's bucketed quantiles are too coarse for a bench artifact).
3. **Goodput under 2x overload, backpressure on vs off** — "on" bounds
   the queue (excess rejected at submit, cheap), "off" admits everything
   (requests rot in the queue past their deadline and are reaped, or
   time out mid-decode after burning slot-steps). Goodput counts only
   tokens of requests that finished ``ok`` within their deadline.

Usage:
  python tools/serve_bench.py            # full run, pretty JSON to stdout
  python tools/serve_bench.py --quick    # small run, one JSON line
Progress goes to stderr; stdout is machine-readable (the last line is
always the summary object), so ``bench.py`` embeds the --quick summary.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pipe_tpu.inference import GenerationConfig, Generator
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
from pipe_tpu.obs.telemetry import percentile_exact
from pipe_tpu.serve import (BucketSpec, QueueFull, RequestQueue,
                            ServeEngine, SingleDeviceSlotBackend)

CFG = LMConfig(vocab=1024, d_model=128, nhead=8, d_ff=512, n_layers=4,
               seq_len=256, dropout=0.0)
BUCKETS = BucketSpec.of(32, 64)
MAX_NEW = 64
# Size the slot cache to the workload, exactly as Generator sizes its
# cache to prompt+max_new: attention cost scales with cache ROWS, not
# live tokens, so an oversized max_len taxes every decode step (measured
# ~0.6x the fixed-batch baseline at 2x the needed rows vs ~1.3x when
# sized to fit).
MAX_LEN = BUCKETS.max_len + MAX_NEW


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def make_prompts(n, rng):
    lens = rng.choice((20, 32, 48, 64), size=n)
    return [rng.randint(1, CFG.vocab, size=int(p)).tolist() for p in lens]


def baseline_tokens_per_sec(model, params, slots, rng):
    """Fixed-batch Generator decode tokens/s at batch == num_slots.
    Two generation lengths at the largest bucket's prompt width (the
    Generator cache spans 80..144 rows vs the engine's fixed 128 — the
    closest apples-to-apples the shape-specialized cache allows); the
    slope isolates the decode scan from prefill + sampling setup, and
    min-of-3 rejects scheduler noise."""
    prompt = jnp.asarray(
        rng.randint(1, CFG.vocab, size=(slots, BUCKETS.max_len)),
        jnp.int32)
    times = {}
    for max_new in (16, 80):
        g = Generator(model, GenerationConfig(max_new_tokens=max_new,
                                              temperature=0.0))
        g.generate(params, prompt).block_until_ready()   # compile
        reps = []
        for _ in range(3):
            t0 = time.monotonic()
            g.generate(params, prompt).block_until_ready()
            reps.append(time.monotonic() - t0)
        times[max_new] = min(reps)
    per_tok = (times[80] - times[16]) / (80 - 16)
    return slots / per_tok


def steady_state_tokens_per_sec(model, params, slots, chunk, rng,
                                ticks=20):
    """Saturated continuous batching: a deep queue keeps every slot
    full across retirements (requests finish, replacements prefill in
    the same tick). Token count from the engine's own emitted-token
    counter, so prefill/retire churn is charged to the number honestly."""
    from pipe_tpu.obs.telemetry import get_registry
    gen_cfg = GenerationConfig(max_new_tokens=MAX_NEW, temperature=0.0)
    backend = SingleDeviceSlotBackend(
        model, params, num_slots=slots, max_len=MAX_LEN, gen=gen_cfg,
        buckets=BUCKETS, decode_chunk=chunk)
    n_requests = slots * (2 + chunk * ticks // MAX_NEW)
    eng = ServeEngine(backend, RequestQueue(capacity=n_requests + slots))
    for p in make_prompts(n_requests, rng):
        eng.submit(p)
    for _ in range(3):
        eng.tick()              # compile both prefill buckets + decode
    assert eng.live_slots == slots
    counter = get_registry().counter("serve.engine.tokens")
    n0 = counter.value
    t0 = time.monotonic()
    for _ in range(ticks):
        eng.tick()
    dt = time.monotonic() - t0
    assert eng.live_slots == slots      # the queue never ran dry
    return (counter.value - n0) / dt


def drive_poisson(eng, prompts, arrivals, *, max_new, deadline_s):
    """Feed the engine a precomputed arrival schedule against the wall
    clock; tick until drained. Returns (responses, elapsed, rejected)."""
    t0 = time.monotonic()
    i, rejected, finished = 0, 0, []
    while i < len(arrivals) or not eng.idle:
        now = time.monotonic() - t0
        while i < len(arrivals) and arrivals[i] <= now:
            try:
                eng.submit(prompts[i], seed=i, max_new_tokens=max_new,
                           timeout_s=deadline_s)
            except QueueFull:
                rejected += 1
            i += 1
        if eng.idle and i < len(arrivals):
            time.sleep(min(arrivals[i] - now, 0.002))
            continue
        finished.extend(eng.tick())
    return finished, time.monotonic() - t0, rejected


def load_run(model, params, slots, chunk, rng, *, n_requests, rate,
             max_new, deadline_s, capacity):
    gen_cfg = GenerationConfig(max_new_tokens=max_new, temperature=0.0)
    backend = SingleDeviceSlotBackend(
        model, params, num_slots=slots, max_len=MAX_LEN, gen=gen_cfg,
        buckets=BUCKETS, decode_chunk=chunk)
    eng = ServeEngine(backend, RequestQueue(capacity=capacity))
    # warm every program before the clock matters
    for p in ([1] * 20, [1] * 40):
        eng.submit(p, max_new_tokens=1)
    eng.run_until_idle()

    prompts = make_prompts(n_requests, rng)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, size=n_requests))
    finished, elapsed, rejected = drive_poisson(
        eng, prompts, arrivals, max_new=max_new, deadline_s=deadline_s)
    ok = [r for r in finished if r.status == "ok"]
    ttfts = sorted(r.ttft for r in ok)
    return {
        "requests": n_requests,
        "offered_rate_req_s": round(rate, 3),
        "elapsed_s": round(elapsed, 3),
        "ok": len(ok),
        "timeout": sum(r.status == "timeout" for r in finished),
        "cancelled": sum(r.status == "cancelled" for r in finished),
        "rejected": rejected,
        "goodput_tokens_s": round(
            sum(len(r.tokens) for r in ok) / elapsed, 1),
        "ttft_p50_s": round(percentile_exact(ttfts, 0.50), 4),
        "ttft_p99_s": round(percentile_exact(ttfts, 0.99), 4),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small run; single-line JSON summary")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--chunk", type=int, default=8,
                    help="decode_chunk: tokens per host round-trip")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    rng = np.random.RandomState(args.seed)
    model = PipelinedLM(CFG, 1)
    params = model.init(jax.random.key(0))
    slots, chunk = args.slots, args.chunk

    log("baseline: fixed-batch Generator decode slope...")
    base_tps = baseline_tokens_per_sec(model, params, slots, rng)
    log(f"  {base_tps:.1f} tokens/s at batch={slots}")

    log("steady state: engine with every slot full...")
    ticks = 8 if args.quick else 24
    serve_tps = steady_state_tokens_per_sec(model, params, slots, chunk,
                                            rng, ticks=ticks)
    ratio = serve_tps / base_tps
    log(f"  {serve_tps:.1f} tokens/s ({ratio:.3f}x fixed-batch)")

    # capacity in requests/s at the bench's request size
    max_new = MAX_NEW
    cap_req_s = serve_tps / max_new

    log("poisson @ 0.7x capacity...")
    n = 12 if args.quick else 48
    moderate = load_run(model, params, slots, chunk, rng,
                        n_requests=n, rate=0.7 * cap_req_s,
                        max_new=max_new, deadline_s=30.0,
                        capacity=4 * slots)

    summary = {
        "bench": "serve_bench",
        "platform": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "slots": slots,
        "decode_chunk": chunk,
        "buckets": list(BUCKETS.lengths),
        "max_new_tokens": max_new,
        "baseline_fixed_batch_tokens_s": round(base_tps, 1),
        "steady_state_tokens_s": round(serve_tps, 1),
        "serve_vs_fixed_batch": round(ratio, 4),
        "poisson_0p7": moderate,
    }
    if args.quick:
        print(json.dumps({
            "steady_state_tokens_s": summary["steady_state_tokens_s"],
            "serve_vs_fixed_batch": summary["serve_vs_fixed_batch"],
            "ttft_p50_s": moderate["ttft_p50_s"],
            "ttft_p99_s": moderate["ttft_p99_s"],
            "goodput_tokens_s": moderate["goodput_tokens_s"],
        }))
        return

    # 2x overload: backpressure bounds the queue so the engine only
    # accepts what it can finish inside the deadline; without it the
    # queue absorbs everything and requests expire waiting (reaped before
    # prefill) or mid-decode (slot-steps burnt for zero goodput).
    # Deadline sized so a bounded queue's wait (<= capacity/service
    # rate) fits comfortably but an unbounded queue's does not — the
    # regime where shedding at the door beats accepting work that will
    # die waiting or burn slot-steps before timing out mid-decode.
    log("overload 2x, backpressure ON (bounded queue)...")
    n_over = 96
    deadline = 1.0
    on = load_run(model, params, slots, chunk,
                  np.random.RandomState(args.seed + 1),
                  n_requests=n_over, rate=2.0 * cap_req_s,
                  max_new=max_new, deadline_s=deadline,
                  capacity=2 * slots)
    log("overload 2x, backpressure OFF (unbounded queue)...")
    off = load_run(model, params, slots, chunk,
                   np.random.RandomState(args.seed + 1),
                   n_requests=n_over, rate=2.0 * cap_req_s,
                   max_new=max_new, deadline_s=deadline,
                   capacity=100000)
    summary["overload_2x"] = {
        "deadline_s": deadline,
        "backpressure_on": on,
        "backpressure_off": off,
        "goodput_ratio_on_vs_off": round(
            on["goodput_tokens_s"] / max(off["goodput_tokens_s"], 1e-9),
            3),
    }
    print(json.dumps(summary, indent=2))


if __name__ == "__main__":
    main()
