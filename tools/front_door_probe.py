"""Front-door adapter tax probe: Pipe(mesh=, schedule=) vs raw executor.

Round 4 measured the flagship `Pipe(mesh=, schedule='1f1b').loss_and_grad`
at ~2x the raw homogeneous `ScheduledPipeline` on identical math (cpu8,
4 stages, m=8, d_model 256) — the per-cycle `lax.switch` over stage
branches. Round 5 adds the uniform-partition fast path
(`HeteroScheduledPipeline._branches_uniform`): when every partition traces
to the same jaxpr over the same boundary/param layout, ONE shared branch
replaces the switch and the front door emits the raw executor's program.

Round 6 rebuilt the fast path as an IDENTITY lowering (native boundary
tuple carrier, natural stage-stacked params — no per-cycle PackPlan
flatten/pad/slice, no per-cycle ``unpack_stage``) and added the phase
compiler (``phase_compile=True``: unrolled ramps + switch-free
steady-state scan) on both sides of the ratio.

``python tools/front_door_probe.py`` (boots its own virtual 8-device CPU
platform) times five programs on the same uniform 4-stage stack:

* ``raw``            — `ScheduledPipeline` driven directly (the floor);
* ``raw-phase``      — the floor with phase compilation on;
* ``pipe-uniform``   — the front door with the fast path (round 5/6);
* ``pipe-phase``     — the front door, fast path + phase compilation
  (the acceptance configuration: tax ≤ 1.05x vs ``raw-phase``);
* ``pipe-switch``    — the front door with the fast path disabled
  (round 4's program, kept honest via monkeypatch).

Each program runs in its OWN subprocess (``PROBE_ONLY=<tag>`` re-invokes
this script for one timing): with five compiled programs resident in one
process, the later ones measured up to ~1.8x slower from allocator/cache
pressure alone — per-process isolation is the honest apples-to-apples.
One JSON line per program + a summary line with the tax ratios
(stdout only; redirect to keep a record).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from pipe_tpu.utils.platform import force_cpu_platform

force_cpu_platform(num_devices=8)

import jax
import jax.numpy as jnp
import numpy as np

from pipe_tpu import Lambda, Linear, Pipe, Sequential
from pipe_tpu.core import microbatch as mb
from pipe_tpu.core.partition import StageCtx
from pipe_tpu.parallel.hetero_scheduled import HeteroScheduledPipeline
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.scheduled import ScheduledPipeline
from pipe_tpu.parallel.spmd import stack_stage_params

D_MODEL = int(os.environ.get("PROBE_D_MODEL", "256"))
N_STAGES = int(os.environ.get("PROBE_STAGES", "4"))
LAYERS_PER_STAGE = int(os.environ.get("PROBE_LAYERS_PER_STAGE", "2"))
M = int(os.environ.get("PROBE_CHUNKS", "8"))
ROWS = int(os.environ.get("PROBE_ROWS", "64"))
ITERS = int(os.environ.get("PROBE_ITERS", "5"))


def block_layers():
    return [Linear(D_MODEL), Lambda(jax.nn.gelu)]


def time_fn(fn, *args):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(*args)
        jax.block_until_ready(out)
    return (time.perf_counter() - t0) / ITERS, out


TAGS = ("raw", "raw-phase", "pipe-uniform", "pipe-phase", "pipe-switch")


def run_one(tag: str) -> dict:
    mesh = make_mesh(N_STAGES, 1, devices=jax.devices()[:N_STAGES])
    n_layers = N_STAGES * LAYERS_PER_STAGE
    model = Sequential([l for _ in range(n_layers) for l in block_layers()])
    x = jax.random.normal(jax.random.key(1), (ROWS, D_MODEL))
    y = jax.random.normal(jax.random.key(2), (ROWS, D_MODEL))

    def loss_fn(out, tgt):
        return jnp.mean((out - tgt) ** 2, axis=-1)

    phase = True if tag.endswith("-phase") else None
    if tag.startswith("raw"):
        # --- raw homogeneous executor (the floor) -----------------------
        pipe0 = Pipe(model, chunks=M, checkpoint="except_last",
                     n_stages=N_STAGES)
        params_per_stage = pipe0.init(jax.random.key(0), x)
        # the raw executor needs a homogeneous stage body: apply the
        # stage's layer stack from the stacked param rows
        params_per_stage_layers = list(pipe0.partitions[0])

        def stage_fn(params_g, h, ctx):
            for j, layer in enumerate(params_per_stage_layers):
                h = layer.apply(params_g[j], h, ctx=ctx.fold(j))
            return h

        stacked = stack_stage_params(params_per_stage)
        xs, n_rows = mb.stack_scatter({"x": x, "tgt": y}, M)
        w = mb.valid_row_mask(xs, n_rows)
        raw = ScheduledPipeline(mesh, stage_fn,
                                pre_fn=lambda prep, x_mb, ctx: x_mb["x"],
                                post_fn=lambda postp, h, x_mb, ctx:
                                loss_fn(h, x_mb["tgt"]),
                                checkpoint="except_last", schedule="1f1b",
                                phase_compile=phase)
        raw_step = jax.jit(lambda sp, xx, ww: raw.loss_and_grad(
            sp, {}, {}, xx, ww, key=jax.random.key(9)))
        sec, (loss_raw, _) = time_fn(raw_step, stacked, xs, w)
        return {"sec_per_step": round(sec, 5),
                "loss": round(float(loss_raw), 6)}

    # --- front door: fast path on / phased / off ------------------------
    orig = HeteroScheduledPipeline._branches_uniform
    if tag == "pipe-switch":
        HeteroScheduledPipeline._branches_uniform = (
            lambda self, low, *, train: False)
    try:
        pipe = Pipe(model, chunks=M, checkpoint="except_last",
                    mesh=mesh, schedule="1f1b", phase_compile=phase)
        packed = pipe.shard_params(pipe.init(jax.random.key(0), x))
        step = jax.jit(lambda p, xx, yy: pipe.loss_and_grad(
            p, xx, targets=yy, loss_fn=loss_fn, key=jax.random.key(9)))
        sec, (loss, _) = time_fn(step, packed, x, y)
    finally:
        HeteroScheduledPipeline._branches_uniform = orig
    uni = getattr(pipe._train_executor, "uniform_fastpath", None)
    return {"sec_per_step": round(sec, 5), "loss": round(float(loss), 6),
            "uniform_fastpath": uni}


def main():
    import subprocess

    results = {}
    for tag in TAGS:
        env = dict(os.environ, PROBE_ONLY=tag)
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                              env=env, capture_output=True, text=True)
        if proc.returncode != 0:
            sys.stderr.write(proc.stderr)
            raise SystemExit(f"probe subprocess {tag!r} failed")
        line = proc.stdout.strip().splitlines()[-1]
        results[tag] = json.loads(line)
        results[tag].pop("program", None)
        print(json.dumps({"program": tag, **results[tag]}), flush=True)

    summary = {
        "config": {"d_model": D_MODEL, "n_stages": N_STAGES,
                   "layers_per_stage": LAYERS_PER_STAGE, "chunks": M,
                   "rows": ROWS, "platform": jax.default_backend(),
                   "n_devices": jax.device_count(),
                   "isolation": "one subprocess per program"},
        "tax_uniform_vs_raw": round(
            results["pipe-uniform"]["sec_per_step"]
            / results["raw"]["sec_per_step"], 4),
        "tax_phase_vs_raw_phase": round(
            results["pipe-phase"]["sec_per_step"]
            / results["raw-phase"]["sec_per_step"], 4),
        "tax_switch_vs_raw": round(
            results["pipe-switch"]["sec_per_step"]
            / results["raw"]["sec_per_step"], 4),
        "results": results,
    }
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    only = os.environ.get("PROBE_ONLY")
    if only:
        print(json.dumps({"program": only, **run_one(only)}), flush=True)
    else:
        main()
