"""ZeRO-1 optimizer-state sharding (train/zero.py).

Contracts: (1) layout-only — training with zero=True matches the
replicated optimizer up to float reduction order (Adam is elementwise; the
only non-elementwise op in the chain is grad-clip's global norm, whose
partitioned reduction can differ by ~1 ulp, which Adam's rsqrt then
amplifies over steps — so losses match tightly, params to a looser tol);
(2) the memory claim is real — each device holds
~1/(n_stages*n_data) of the moment bytes instead of 1/n_stages; (3) the
layout survives the jitted step (constraints hold, no silent
re-replication after step 1).
"""

import dataclasses

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding

from pipe_tpu.data import lm_text
from pipe_tpu.models.transformer_lm import LMConfig
from pipe_tpu.train.loop import Trainer, TrainerConfig
from pipe_tpu.train import zero

MODEL = LMConfig(vocab=96, d_model=32, nhead=4, d_ff=64, n_layers=4,
                 seq_len=16, dropout=0.0)
CFG = TrainerConfig(batch_size=8, bptt=16, chunks=2, n_stages=2, n_data=2,
                    lr=0.1, schedule="1f1b", checkpoint="never")


def _source(cfg, n_tokens=4096, seed=3):
    ids = np.random.default_rng(seed).integers(
        0, MODEL.vocab, size=n_tokens).astype(np.int32)
    return lm_text.batchify(ids, cfg.batch_size)


def _run_steps(cfg, n_steps=3):
    tr = Trainer(MODEL, cfg)
    state = tr.init_state()
    state, stats = tr.train_epoch(_source(cfg), state=state,
                                  max_steps=n_steps, log_every=0)
    return tr, state, stats


def test_zero_losses_match_replicated():
    _, s_base, stats_base = _run_steps(CFG)
    _, s_zero, stats_zero = _run_steps(dataclasses.replace(CFG, zero=True))
    assert np.isfinite(stats_zero["loss"])
    np.testing.assert_allclose(stats_zero["loss"], stats_base["loss"],
                               rtol=1e-4)
    # params after 3 steps agree leafwise to the reduction-order tolerance
    # (see module docstring; lr=0.1 Adam amplifies ulp-level norm diffs)
    for a, b in zip(jax.tree_util.tree_leaves(s_base.params),
                    jax.tree_util.tree_leaves(s_zero.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-3)


def test_zero_moments_are_data_sharded():
    tr, state, _ = _run_steps(dataclasses.replace(CFG, zero=True), n_steps=2)
    n_data = tr.mesh.shape["data"]
    assert n_data > 1
    report = zero.zero_report(state.opt_state, tr._zero_shardings)
    # the bulk of the moment bytes actually shard (only biases/scalars may
    # stay replicated)
    assert report["data_sharded_bytes"] > 0.8 * report["total_bytes"]
    # per-device accounting: a data-sharded leaf's addressable shard holds
    # 1/n_data of the rows it would hold replicated — and the layout
    # survived the jitted step (state here is post-step, not post-init)
    checked = 0
    for leaf, sh in zip(
            jax.tree_util.tree_leaves(state.opt_state),
            jax.tree_util.tree_leaves(
                tr._zero_shardings,
                is_leaf=lambda x: isinstance(x, NamedSharding))):
        axes = [a for e in sh.spec
                for a in (e if isinstance(e, tuple) else (e,)) if e]
        if "data" not in axes:
            continue
        shard = leaf.addressable_shards[0]
        denom = 1
        for ax in axes:
            denom *= tr.mesh.shape[ax]
        assert (int(np.prod(shard.data.shape))
                == int(np.prod(leaf.shape)) // denom), (
            leaf.shape, shard.data.shape, sh.spec)
        checked += 1
    assert checked >= 4


def test_zero_requires_init_state():
    tr = Trainer(MODEL, dataclasses.replace(CFG, zero=True))
    # build a state without init_state's layout derivation
    other = Trainer(MODEL, CFG)
    state = other.init_state()
    with pytest.raises(Exception, match="init_state"):
        tr.train_epoch(_source(CFG, 1024, seed=0), state=state,
                       max_steps=1, log_every=0)


def test_moment_sharding_fallback_replicates_indivisible():
    tr = Trainer(MODEL, dataclasses.replace(CFG, zero=True))
    state = tr.init_state()
    # every sharding in the tree is a NamedSharding (checkpointable layout)
    for sh in jax.tree_util.tree_leaves(
            tr._zero_shardings,
            is_leaf=lambda x: isinstance(x, NamedSharding)):
        assert isinstance(sh, NamedSharding)
    # scalars (adam count) stay replicated
    report = zero.zero_report(state.opt_state, tr._zero_shardings)
    assert report["replicated_bytes"] >= 0


def test_mu_dtype_bf16_composes_with_zero():
    """TrainerConfig(mu_dtype='bfloat16'): the Adam first-moment leaves
    are actually stored bf16, the step runs, and it composes with ZeRO-1
    moment sharding (MFU_SWEEP_r04 knob)."""
    import dataclasses
    import math

    from pipe_tpu.data import lm_text
    from pipe_tpu.models.transformer_lm import LMConfig
    from pipe_tpu.train.loop import Trainer, TrainerConfig

    lines = lm_text.synthetic_corpus(20_000, 99, seed=3)
    vocab = lm_text.Vocab(map(lm_text.basic_english_tokenize, lines))
    source = lm_text.batchify(lm_text.data_process(lines, vocab), 8)
    mcfg = dataclasses.replace(LMConfig().tiny(), n_layers=2)
    tr = Trainer(mcfg, TrainerConfig(
        schedule="1f1b", n_stages=2, n_data=2, chunks=2, batch_size=8,
        bptt=mcfg.seq_len, lr=1e-2, mu_dtype="bfloat16", zero=True))
    state, m = tr.train_epoch(source, max_steps=6, log_every=0)
    import jax.numpy as jnp
    assert m["loss"] < math.log(mcfg.vocab)
    bf16_leaves = [l for l in jax.tree_util.tree_leaves(state.opt_state)
                   if hasattr(l, "dtype") and l.dtype == jnp.bfloat16]
    assert bf16_leaves, "mu_dtype='bfloat16' produced no bf16 moments"
