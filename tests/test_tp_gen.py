"""Tensor-parallel decoding (inference/tp.py + ops/tp_layers.tp_block_decode).

Gold contract: greedy decode with heads/FFN sharded over the model axis
(head-sharded KV caches, two psums per block) matches the unsharded
(tp_axis=None) model token-for-token on the same weights.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core.partition import StageCtx
from pipe_tpu.inference import GenerationConfig, Generator
from pipe_tpu.inference.tp import TPShardedGenerator
from pipe_tpu.models.tp_lm import TPPipelinedLM
from pipe_tpu.models.transformer_lm import LMConfig
from pipe_tpu.parallel.mesh import make_mesh

CFG = LMConfig(vocab=73, d_model=32, nhead=4, d_ff=64, n_layers=4,
               seq_len=32, dropout=0.0)


def test_tp_block_decode_matches_apply():
    """Prefill via tp_block_decode (tp_axis=None) == tp_block_apply."""
    from pipe_tpu.ops.tp_layers import (tp_block_apply, tp_block_decode,
                                        tp_block_init)

    p = tp_block_init(jax.random.key(0), 32, 4, 64)
    h = jax.random.normal(jax.random.key(1), (2, 12, 32))
    ref = tp_block_apply(p, h, StageCtx(train=False), tp_axis=None)
    cache = {"k": jnp.zeros((2, 16, 4, 8)), "v": jnp.zeros((2, 16, 4, 8))}
    out, cache = tp_block_decode(p, h, cache, 0, tp_axis=None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(cache["k"][:, 12:]), 0.0)


@pytest.mark.parametrize("tp,b,p,max_new", [(2, 2, 8, 6), (4, 2, 8, 4)])
def test_tp_sharded_greedy_matches_unsharded(tp, b, p, max_new):
    model_tp = TPPipelinedLM(CFG, 2)              # tp_axis=MODEL_AXIS
    model_1 = TPPipelinedLM(CFG, 2, tp_axis=None)
    params = model_1.init(jax.random.key(0))      # same trees either way
    prompt = jax.random.randint(jax.random.key(1), (b, p), 0, CFG.vocab,
                                jnp.int32)
    gen_cfg = GenerationConfig(max_new_tokens=max_new, temperature=0.0)
    ref = np.asarray(Generator(model_1, gen_cfg).generate(params, prompt))
    mesh = make_mesh(1, 1, n_model=tp)
    got = np.asarray(TPShardedGenerator(mesh, model_tp, gen_cfg).generate(
        params, prompt))
    np.testing.assert_array_equal(got, ref)


@pytest.mark.parametrize("tp", [2, 4])
def test_tp_sharded_beam_matches_unsharded(tp):
    """Beam search over TP-sharded weights (VERDICT r3 #10): tokens AND
    scores equal the single-device Generator's — the beam machinery is
    layout-agnostic (replicated post-psum log-probs; batch-axis cache
    reorder), so sharding must be invisible to it."""
    model_tp = TPPipelinedLM(CFG, 2)
    model_1 = TPPipelinedLM(CFG, 2, tp_axis=None)
    params = model_1.init(jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(1), (2, 8), 0, CFG.vocab,
                                jnp.int32)
    gen_cfg = GenerationConfig(max_new_tokens=5, num_beams=3)
    ref_toks, ref_scores = Generator(model_1, gen_cfg).generate_with_scores(
        params, prompt)
    mesh = make_mesh(1, 1, n_model=tp)
    g = TPShardedGenerator(mesh, model_tp, gen_cfg)
    got_toks, got_scores = g.generate_with_scores(params, prompt)
    np.testing.assert_array_equal(np.asarray(got_toks),
                                  np.asarray(ref_toks))
    np.testing.assert_allclose(np.asarray(got_scores),
                               np.asarray(ref_scores), rtol=1e-5)
    # generate() routes num_beams > 1 through beam search
    np.testing.assert_array_equal(
        np.asarray(g.generate(params, prompt)), np.asarray(ref_toks))


def test_tp_generator_validations():
    model_tp = TPPipelinedLM(CFG, 2)
    model_1 = TPPipelinedLM(CFG, 2, tp_axis=None)
    mesh = make_mesh(1, 1, n_model=2)
    with pytest.raises(ValueError, match="tp_axis"):
        TPShardedGenerator(mesh, model_1)
    with pytest.raises(ValueError, match="model"):
        TPShardedGenerator(make_mesh(2, 1), model_tp)
    g = TPShardedGenerator(mesh, model_tp,
                           GenerationConfig(max_new_tokens=2))
    with pytest.raises(ValueError, match="num_beams"):
        g.generate_with_scores(None, jnp.zeros((2, 4), jnp.int32))
