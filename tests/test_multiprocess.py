"""Two-process multi-host dryrun (VERDICT r2 #8): the distributed runtime
(`runtime/distributed.py`) exercised end-to-end with real separate
processes — coordinator wiring, a global (stage x data) mesh whose data
axis crosses the process boundary, host-local batch assembly, and one
cross-process 1F1B pipeline step whose loss must equal the single-process
run bit-for-tolerance.

Heavy (spawns 2 JAX processes, each compiling the step), so gated behind
``PIPE_TPU_MULTIPROC=1``; ``__graft_entry__.dryrun_multichip`` also runs
the same check (shared launcher: ``launch_two_process_check``).
"""

import os

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("PIPE_TPU_MULTIPROC") != "1",
    reason="2-process dryrun is heavy; set PIPE_TPU_MULTIPROC=1 to run")


def test_two_process_pipeline_step_matches_single_process(tmp_path):
    from pipe_tpu.runtime._multiproc_check import (launch_two_process_check,
                                                   single_process_loss)

    m_loss, m_ck, m_loss_sx = launch_two_process_check(
        str(tmp_path / "loss.txt"))
    s_loss, s_ck = single_process_loss()
    assert m_loss == pytest.approx(s_loss, rel=1e-6), (m_loss, s_loss)
    # ZeRO-1 moments sharded over the process-spanning data axis: the
    # partitioned update + re-gather must be a pure layout choice
    assert m_ck == pytest.approx(s_ck, rel=1e-5), (m_ck, s_ck)
    # stage-across topology: inter-stage ppermute crosses the process
    # boundary (1 stage per process) — still a pure layout choice
    assert m_loss_sx == pytest.approx(s_loss, rel=1e-6), (m_loss_sx, s_loss)
