"""Two-process multi-host dryrun (VERDICT r2 #8): the distributed runtime
(`runtime/distributed.py`) exercised end-to-end with real separate
processes — coordinator wiring, a global (stage x data) mesh whose data
axis crosses the process boundary, host-local batch assembly, and one
cross-process 1F1B pipeline step whose loss must equal the single-process
run bit-for-tolerance.

Heavy (spawns 2 JAX processes, each compiling the step), so gated behind
``PIPE_TPU_MULTIPROC=1``; ``tools/multiproc_dryrun.py`` runs it standalone
and the round dryrun invokes it.
"""

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("PIPE_TPU_MULTIPROC") != "1",
    reason="2-process dryrun is heavy; set PIPE_TPU_MULTIPROC=1 to run")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_pipeline_step_matches_single_process(tmp_path):
    port = _free_port()
    out = tmp_path / "loss.txt"
    env = dict(os.environ)
    # Fresh interpreters must not boot the axon TPU plugin (it would hang
    # CPU selection) and must not inherit the test process's 8-device
    # forcing: the worker sets its own 2-device CPU platform.
    env["PYTHONPATH"] = REPO
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "pipe_tpu.runtime._multiproc_check",
             str(i), "2", str(port), str(out)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for i in range(2)
    ]
    try:
        outputs = []
        for p in procs:
            stdout, _ = p.communicate(timeout=600)
            outputs.append(stdout.decode(errors="replace"))
    finally:
        for p in procs:           # never leave orphaned JAX processes
            if p.poll() is None:
                p.kill()
                p.wait()
    for p, text in zip(procs, outputs):
        assert p.returncode == 0, f"worker failed:\n{text[-3000:]}"
    multi = float(out.read_text())

    from pipe_tpu.runtime._multiproc_check import single_process_loss
    single = single_process_loss()
    assert multi == pytest.approx(single, rel=1e-6), (multi, single)
