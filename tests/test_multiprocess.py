"""Two-process multi-host dryrun (VERDICT r2 #8): the distributed runtime
(`runtime/distributed.py`) exercised end-to-end with real separate
processes — coordinator wiring, a global (stage x data) mesh whose data
axis crosses the process boundary, host-local batch assembly, and one
cross-process 1F1B pipeline step whose loss must equal the single-process
run bit-for-tolerance.

Heavy (spawns 2 JAX processes, each compiling the step), so gated behind
``PIPE_TPU_MULTIPROC=1``; ``__graft_entry__.dryrun_multichip`` also runs
the same check (shared launcher: ``launch_two_process_check``).
"""

import os

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("PIPE_TPU_MULTIPROC") != "1",
    reason="2-process dryrun is heavy; set PIPE_TPU_MULTIPROC=1 to run")


def test_two_process_pipeline_step_matches_single_process(tmp_path):
    from pipe_tpu.runtime._multiproc_check import (launch_two_process_check,
                                                   single_process_loss)

    multi = launch_two_process_check(str(tmp_path / "loss.txt"))
    single = single_process_loss()
    assert multi == pytest.approx(single, rel=1e-6), (multi, single)
