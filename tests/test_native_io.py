"""Native (C++) corpus processor: parity with the Python pipeline.

The shared library must produce token-for-token identical ids and vocabulary
to ``data.lm_text`` on ASCII corpora — then the trainer can use either path
interchangeably.
"""

import numpy as np
import pytest

from pipe_tpu.data import lm_text
from pipe_tpu.data.native import (NativeCorpus, native_available,
                                  process_corpus)

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="no C++ toolchain")

SAMPLE = """The quick brown Fox jumps over the lazy dog.
Pack my box with five dozen liquor jugs!
(Hello, world); "quotes" and it's colons: done?

Repeated words repeated WORDS repeated.
"""


def python_reference(text):
    lines = text.splitlines()
    vocab = lm_text.Vocab(map(lm_text.basic_english_tokenize, lines))
    ids = lm_text.data_process(lines, vocab)
    return ids, [vocab.lookup_token(i) for i in range(len(vocab))]


def test_ids_and_vocab_parity():
    c = NativeCorpus.from_text(SAMPLE)
    exp_ids, exp_vocab = python_reference(SAMPLE)
    np.testing.assert_array_equal(c.ids(), exp_ids)
    assert c.vocab_list() == exp_vocab


def test_file_roundtrip(tmp_path):
    p = tmp_path / "c.txt"
    p.write_text(SAMPLE)
    ids, vocab = process_corpus(path=str(p))
    exp_ids, exp_vocab = python_reference(SAMPLE)
    np.testing.assert_array_equal(ids, exp_ids)
    assert vocab == exp_vocab


def test_lookup_and_unk():
    c = NativeCorpus.from_text("alpha beta gamma alpha")
    assert c.lookup("alpha") == 1  # 0 is <unk>
    assert c.lookup("never-seen") == 0
    assert c.token(0) == "<unk>"
    assert c.vocab_size == 4


def test_large_corpus_matches_and_is_fast():
    lines = lm_text.synthetic_corpus(120_000, 500, seed=9)
    text = "\n".join(lines)
    import time
    t0 = time.perf_counter()
    c = NativeCorpus.from_text(text)
    native_ids = c.ids()
    native_t = time.perf_counter() - t0

    t0 = time.perf_counter()
    exp_ids, _ = python_reference(text)
    python_t = time.perf_counter() - t0

    np.testing.assert_array_equal(native_ids, exp_ids)
    # not a hard perf gate, but native should never be slower
    assert native_t <= python_t, (native_t, python_t)


def test_empty_and_whitespace_only():
    c = NativeCorpus.from_text("\n   \n\t\n")
    assert c.num_tokens == 0
    assert c.vocab_size == 1  # just <unk>
