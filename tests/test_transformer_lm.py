"""Transformer-LM tests: tutorial model through both executors.

Mirrors the reference's verification strategy (SURVEY §4): the runnable
tutorial as integration test, plus transparency between the pipelined and
plain forms of the same model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu import Pipe
from pipe_tpu.core import microbatch as mb
from pipe_tpu.core.partition import StageCtx
from pipe_tpu.models.transformer_lm import (LMConfig, PipelinedLM,
                                            build_sequential, cross_entropy)
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.spmd import SpmdPipeline, stack_stage_params

CFG = LMConfig().tiny()


def make_tokens(key, batch, seq):
    return jax.random.randint(key, (batch, seq), 0, CFG.vocab)


def test_sequential_lm_shapes():
    seq_model = build_sequential(CFG)
    # Encoder(2 modules) + n_layers + Decoder
    assert len(seq_model) == CFG.n_layers + 3
    params = seq_model.init(jax.random.key(0),
                            jax.ShapeDtypeStruct((2, CFG.seq_len), jnp.int32))
    toks = make_tokens(jax.random.key(1), 2, CFG.seq_len)
    logits = seq_model.apply(params, toks)
    assert logits.shape == (2, CFG.seq_len, CFG.vocab)


def test_pipe_lm_transparency():
    """Pipe-wrapped LM == plain LM (the 2-stage tutorial topology)."""
    seq_model = build_sequential(CFG)
    # balance like the tutorial: encoder+posenc+half blocks | rest+decoder
    pipe = Pipe(seq_model, chunks=4, checkpoint="never",
                balance=[2 + CFG.n_layers // 2, CFG.n_layers // 2 + 1])
    sp = pipe.init(jax.random.key(0),
                   jax.ShapeDtypeStruct((2, CFG.seq_len), jnp.int32))
    flat = [p for stage in sp for p in stage]
    toks = make_tokens(jax.random.key(1), 8, CFG.seq_len)
    got = pipe(sp, toks)
    expected = seq_model.apply(flat, toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_spmd_lm_matches_plain():
    n_stages = 4
    lm = PipelinedLM(CFG, n_stages)
    stage_params, pre_p, post_p = lm.init(jax.random.key(0))
    mesh = make_mesh(n_stages, 1)
    pipe = SpmdPipeline(mesh, lm.stage_fn, pre_fn=lm.pre_fn,
                        post_fn=lm.post_fn)
    stacked = stack_stage_params(stage_params)

    toks = make_tokens(jax.random.key(1), 8, CFG.seq_len)
    xs, bs = mb.stack_scatter(toks, 4)
    logits = mb.stack_gather(pipe(stacked, pre_p, post_p, xs), bs)

    # plain single-device forward of the identical params
    h = lm.pre_fn(pre_p, toks, StageCtx())
    for blocks in stage_params:
        h = lm.stage_fn(blocks, h, StageCtx())
    expected = lm.post_fn(post_p, h, StageCtx())
    np.testing.assert_allclose(np.asarray(logits), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_spmd_lm_loss_mode_and_grads():
    """In-pipeline loss (post_with_batch): value and grads match plain CE."""
    n_stages = 2
    lm = PipelinedLM(CFG, n_stages)
    stage_params, pre_p, post_p = lm.init(jax.random.key(0))
    mesh = make_mesh(n_stages, 1)
    pipe = SpmdPipeline(mesh, lm.stage_fn, pre_fn=lm.pre_fn,
                        post_fn=lm.loss_post_fn, post_with_batch=True)
    stacked = stack_stage_params(stage_params)

    toks = make_tokens(jax.random.key(1), 8, CFG.seq_len)
    targets = jnp.roll(toks, -1, axis=-1)
    x = {"tokens": toks, "targets": targets}
    xs, bs = mb.stack_scatter(x, 4)

    def pipe_loss(sp, pre_p, post_p):
        per_row = pipe(sp, pre_p, post_p, xs, train=False)
        return jnp.mean(per_row)

    def plain_loss(splist, pre_p, post_p):
        h = lm.pre_fn(pre_p, toks, StageCtx())
        for blocks in splist:
            h = lm.stage_fn(blocks, h, StageCtx())
        logits = lm.post_fn(post_p, h, StageCtx())
        return cross_entropy(logits, targets)

    lv = pipe_loss(stacked, pre_p, post_p)
    le = plain_loss(stage_params, pre_p, post_p)
    np.testing.assert_allclose(float(lv), float(le), rtol=1e-5)

    g_pipe = jax.grad(pipe_loss, argnums=(0, 1, 2))(stacked, pre_p, post_p)
    g_plain = jax.grad(plain_loss, argnums=(0, 1, 2))(
        list(stage_params), pre_p, post_p)
    g_plain = (stack_stage_params(g_plain[0]), g_plain[1], g_plain[2])
    for g, e in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_plain)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                   rtol=5e-4, atol=1e-6)


def test_spmd_lm_train_step_converges():
    n_stages = 2
    lm = PipelinedLM(CFG, n_stages)
    stage_params, pre_p, post_p = lm.init(jax.random.key(0))
    mesh = make_mesh(n_stages, 2)  # pipeline x data
    pipe = SpmdPipeline(mesh, lm.stage_fn, pre_fn=lm.pre_fn,
                        post_fn=lm.loss_post_fn, post_with_batch=True,
                        checkpoint="except_last")
    params = (stack_stage_params(stage_params), pre_p, post_p)

    toks = make_tokens(jax.random.key(1), 16, CFG.seq_len)
    targets = jnp.roll(toks, -1, axis=-1)
    xs, _ = mb.stack_scatter({"tokens": toks, "targets": targets}, 4)

    @jax.jit
    def step(params, k):
        def loss_fn(params):
            sp, pre_p, post_p = params
            return jnp.mean(pipe(sp, pre_p, post_p, xs, key=k, train=True))
        l, g = jax.value_and_grad(loss_fn)(params)
        return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, params, g), l

    losses = []
    for i in range(30):
        params, l = step(params, jax.random.key(i))
        losses.append(float(l))
    assert losses[-1] < losses[0] - 0.3, losses[::10]


def test_uneven_layers_rejected_for_spmd():
    with pytest.raises(ValueError):
        PipelinedLM(CFG, 3)  # 4 layers % 3 != 0


def test_cross_entropy_reference():
    logits = jnp.array([[[2.0, 0.0, 0.0], [0.0, 2.0, 0.0]]])
    targets = jnp.array([[0, 1]])
    l = cross_entropy(logits, targets)
    expected = -np.log(np.exp(2) / (np.exp(2) + 2))
    np.testing.assert_allclose(float(l), expected, rtol=1e-6)
