"""Pipe(mesh=...) — the flagship API driving the compiled SPMD executor.

VERDICT r1 #1: the reference's ``Pipe(module, chunks, checkpoint)`` IS the
multi-device product (``pipe.py:344-356`` builds the multi-device Pipeline,
``pipe.py:431-494`` runs it). These tests push the same transparency matrix
as ``test_pipe.py`` through ``Pipe(..., mesh=make_mesh(n, 1))`` on the
virtual CPU mesh, plus the capabilities round 1 left emulator-only:

* uneven stage balance (reference ``pipe.py:191-218`` accepts arbitrary
  splits) — VERDICT r1 #9;
* ``@skippable`` stash/pop across non-adjacent stages, forward AND gradients
  (reference portal machinery, ``pipeline.py:136-138``) — VERDICT r1 #7;
* multi-value stage boundaries, ``NoChunk`` side inputs, dropout keying,
  data-axis composition.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu import (Dropout, Lambda, Linear, NoChunk, Pipe, Sequential,
                      StageCtx)
from pipe_tpu.extras.skip import Namespace, skippable, stash, pop
from pipe_tpu.ops.layers import Module
from pipe_tpu.parallel.mesh import make_mesh

WIDTH = 8


def make_mlp(key, depth=4, width=WIDTH):
    seq = Sequential([Linear(width) for _ in range(depth)])
    params = seq.init(key, jnp.zeros((2, width)))
    return seq, params


def _regroup(flat_params, balance):
    out, off = [], 0
    for w in balance:
        out.append(flat_params[off:off + w])
        off += w
    return out


def stage_mesh(n_stages, n_data=1):
    return make_mesh(n_stages, n_data,
                     devices=jax.devices()[:n_stages * n_data])


# ---------- transparency matrix through the mesh ----------

@pytest.mark.parametrize("chunks", [1, 2, 4, 3])  # 3: non-divisible (8 % 3)
@pytest.mark.parametrize("n_stages", [1, 2, 4])
def test_forward_transparency_mesh(chunks, n_stages):
    seq, params = make_mlp(jax.random.key(0))
    pipe = Pipe(seq, chunks=chunks, checkpoint="never",
                mesh=stage_mesh(n_stages))
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    expected = seq.apply(params, x)
    got = pipe(_regroup(params, pipe.balance), x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("checkpoint", ["never", "except_last", "always"])
def test_gradient_transparency_mesh(checkpoint):
    seq, params = make_mlp(jax.random.key(0))
    pipe = Pipe(seq, chunks=4, checkpoint=checkpoint, mesh=stage_mesh(2))
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    stage_params = _regroup(params, pipe.balance)

    def plain_loss(p):
        return jnp.mean(seq.apply(p, x) ** 2)

    def pipe_loss(sp):
        return jnp.mean(pipe(sp, x, train=True) ** 2)

    expected = jax.grad(plain_loss)(params)
    got = jax.grad(pipe_loss)(stage_params)
    flat_e = jax.tree_util.tree_leaves(expected)
    flat_g = jax.tree_util.tree_leaves(got)
    assert len(flat_e) == len(flat_g)
    for e, g in zip(flat_e, flat_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                   rtol=1e-4, atol=1e-5)


# ---------- uneven balance (VERDICT r1 #9) ----------

@pytest.mark.parametrize("balance", [[3, 1], [1, 3], [1, 2, 1]])
def test_uneven_balance_mesh_matches_plain(balance):
    """Arbitrary splits on the compiled path (reference pipe.py:191-218)."""
    seq = Sequential([Linear(WIDTH), Linear(16), Linear(WIDTH), Linear(WIDTH)])
    params = seq.init(jax.random.key(0), jnp.zeros((2, WIDTH)))
    pipe = Pipe(seq, chunks=4, checkpoint="except_last",
                mesh=stage_mesh(len(balance)), balance=balance)
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    expected = seq.apply(params, x)
    got = pipe(_regroup(params, balance), x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)


def test_uneven_balance_mesh_gradients_match_emulator():
    seq = Sequential([Linear(WIDTH), Linear(16), Linear(WIDTH), Linear(WIDTH)])
    params = seq.init(jax.random.key(0), jnp.zeros((2, WIDTH)))
    balance = [3, 1]
    sp = _regroup(params, balance)
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    mesh_pipe = Pipe(seq, chunks=2, checkpoint="never",
                     mesh=stage_mesh(2), balance=balance)
    emu_pipe = Pipe(seq, chunks=2, checkpoint="never", balance=balance)

    gm = jax.grad(lambda p: jnp.mean(mesh_pipe(p, x, train=True) ** 2))(sp)
    ge = jax.grad(lambda p: jnp.mean(emu_pipe(p, x, train=True) ** 2))(sp)
    for a, b in zip(jax.tree_util.tree_leaves(gm),
                    jax.tree_util.tree_leaves(ge)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


# ---------- @skippable on the compiled path (VERDICT r1 #7) ----------

@skippable(stash=["long"])
class StashLong(Module):
    def init(self, key, *a):
        return {}

    def apply(self, p, x, ctx=StageCtx()):
        stash("long", x)
        return x


@skippable(pop=["long"])
class PopLong(Module):
    def init(self, key, *a):
        return {}

    def apply(self, p, x, ctx=StageCtx()):
        return x + pop("long")


@pytest.mark.parametrize("n_stages,balance", [(4, None), (2, [1, 3]),
                                              (3, [1, 1, 2])])
def test_skip_through_mesh_matches_emulator(n_stages, balance):
    """stash at stage 0, pop hops to the last stage — the compiled lowering
    of the reference's portals (pipeline.py:136-138)."""
    seq = Sequential([StashLong(), Linear(WIDTH), Linear(WIDTH), PopLong()])
    mesh_pipe = Pipe(seq, chunks=2, checkpoint="never",
                     mesh=stage_mesh(n_stages), balance=balance)
    emu_pipe = Pipe(seq, chunks=2, checkpoint="never",
                    n_stages=n_stages, balance=balance)
    sp = mesh_pipe.init(jax.random.key(2), jnp.zeros((2, WIDTH)))
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    np.testing.assert_allclose(np.asarray(mesh_pipe(sp, x)),
                               np.asarray(emu_pipe(sp, x)),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("checkpoint", ["never", "always"])
def test_skip_gradients_through_mesh(checkpoint):
    seq = Sequential([StashLong(), Linear(WIDTH), Linear(WIDTH), PopLong()])
    mesh_pipe = Pipe(seq, chunks=2, checkpoint=checkpoint, mesh=stage_mesh(4))
    emu_pipe = Pipe(seq, chunks=2, checkpoint=checkpoint, n_stages=4)
    sp = mesh_pipe.init(jax.random.key(2), jnp.zeros((2, WIDTH)))
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))

    gm = jax.grad(lambda p: jnp.mean(mesh_pipe(p, x, train=True) ** 2))(sp)
    ge = jax.grad(lambda p: jnp.mean(emu_pipe(p, x, train=True) ** 2))(sp)
    for a, b in zip(jax.tree_util.tree_leaves(gm),
                    jax.tree_util.tree_leaves(ge)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_skip_pop_at_middle_stage_through_mesh():
    """Pop at an interior stage (1 of 4): the lane leaves the ring mid-
    pipeline while later stages keep computing."""
    seq = Sequential([StashLong(), Linear(WIDTH), PopLong(), Linear(WIDTH),
                      Linear(WIDTH), Linear(WIDTH)])
    mesh_pipe = Pipe(seq, chunks=2, checkpoint="never", mesh=stage_mesh(4),
                     balance=[1, 2, 2, 1])
    emu_pipe = Pipe(seq, chunks=2, checkpoint="never", balance=[1, 2, 2, 1])
    sp = mesh_pipe.init(jax.random.key(2), jnp.zeros((2, WIDTH)))
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    np.testing.assert_allclose(np.asarray(mesh_pipe(sp, x)),
                               np.asarray(emu_pipe(sp, x)),
                               rtol=1e-5, atol=1e-5)


def test_two_namespaced_skips_through_mesh():
    """Two instances of the same skippable pair, isolated by Namespace —
    two independent lanes on the ring."""
    ns1, ns2 = Namespace(), Namespace()
    seq = Sequential([
        StashLong().isolate(ns1), Linear(WIDTH),
        StashLong().isolate(ns2), Linear(WIDTH),
        PopLong().isolate(ns2), PopLong().isolate(ns1),
    ])
    mesh_pipe = Pipe(seq, chunks=2, checkpoint="never", mesh=stage_mesh(3),
                     balance=[2, 2, 2])
    emu_pipe = Pipe(seq, chunks=2, checkpoint="never", balance=[2, 2, 2])
    sp = mesh_pipe.init(jax.random.key(2), jnp.zeros((2, WIDTH)))
    x = jax.random.normal(jax.random.key(1), (4, WIDTH))
    np.testing.assert_allclose(np.asarray(mesh_pipe(sp, x)),
                               np.asarray(emu_pipe(sp, x)),
                               rtol=1e-5, atol=1e-5)


# ---------- boundary shapes, NoChunk, dropout, data axis ----------

def test_multi_value_boundary_through_mesh():
    """A stage boundary carrying a tuple of different shapes/dtypes rides the
    packed per-dtype carrier."""
    split = Lambda(lambda x: (x, jnp.sum(x, axis=-1, keepdims=True)),
                   name="split")
    merge = Lambda(lambda x, s: x * s, name="merge")
    seq = Sequential([Linear(WIDTH), split, merge, Linear(WIDTH)])
    mesh_pipe = Pipe(seq, chunks=2, checkpoint="never", mesh=stage_mesh(2),
                     balance=[2, 2])
    sp = mesh_pipe.init(jax.random.key(0), jnp.zeros((2, WIDTH)))
    emu_pipe = Pipe(seq, chunks=2, checkpoint="never", balance=[2, 2])
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    np.testing.assert_allclose(np.asarray(mesh_pipe(sp, x)),
                               np.asarray(emu_pipe(sp, x)),
                               rtol=1e-5, atol=1e-5)


def test_nochunk_through_mesh():
    scale_layer = Lambda(lambda x, s: (x * s, s), name="scale")
    sum_layer = Lambda(lambda x, s: x + s, name="add")
    seq = Sequential([scale_layer, sum_layer])
    pipe = Pipe(seq, chunks=2, checkpoint="never", mesh=stage_mesh(2))
    x = jnp.ones((4, 3))
    out = pipe([[{}], [{}]], x, NoChunk(jnp.full((1,), 2.0)))
    np.testing.assert_allclose(np.asarray(out), np.ones((4, 3)) * 2 + 2.0)


def test_dropout_deterministic_given_key_mesh():
    seq = Sequential([Linear(WIDTH), Dropout(0.5), Linear(WIDTH)])
    pipe = Pipe(seq, chunks=2, checkpoint="never", mesh=stage_mesh(2),
                balance=[2, 1])
    sp = pipe.init(jax.random.key(0), jnp.zeros((2, WIDTH)))
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    k = jax.random.key(42)
    a = pipe(sp, x, key=k, train=True)
    b = pipe(sp, x, key=k, train=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = pipe(sp, x, key=jax.random.key(43), train=True)
    assert not np.allclose(np.asarray(a), np.asarray(c))


def test_data_axis_composition():
    """PP x DP: same mesh API, rows sharded over the data axis."""
    seq, params = make_mlp(jax.random.key(0))
    pipe = Pipe(seq, chunks=2, checkpoint="except_last",
                mesh=stage_mesh(2, n_data=2))
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    expected = seq.apply(params, x)
    got = pipe(_regroup(params, pipe.balance), x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("batch", [7, 2])
def test_small_batch_with_data_axis(batch):
    """batch < chunks*n_data: rows are zero-padded to divide the data axis
    and sliced back off — output matches the emulator exactly."""
    seq, params = make_mlp(jax.random.key(0))
    sp = _regroup(params, [2, 2])
    mesh_pipe = Pipe(seq, chunks=4, checkpoint="never",
                     mesh=stage_mesh(2, n_data=2))
    emu_pipe = Pipe(seq, chunks=4, checkpoint="never", n_stages=2)
    x = jax.random.normal(jax.random.key(1), (batch, WIDTH))
    np.testing.assert_allclose(np.asarray(mesh_pipe(sp, x)),
                               np.asarray(emu_pipe(sp, x)),
                               rtol=1e-5, atol=1e-6)


def test_eval_mode_matches_never_mesh():
    seq, params = make_mlp(jax.random.key(0))
    sp = _regroup(params, [2, 2])
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    p_always = Pipe(seq, chunks=2, checkpoint="always", mesh=stage_mesh(2))
    p_never = Pipe(seq, chunks=2, checkpoint="never", mesh=stage_mesh(2))
    np.testing.assert_array_equal(
        np.asarray(p_always(sp, x, train=False)),
        np.asarray(p_never(sp, x, train=False)))


def test_jit_whole_pipe_mesh():
    seq, params = make_mlp(jax.random.key(0))
    pipe = Pipe(seq, chunks=4, checkpoint="except_last", mesh=stage_mesh(2))
    sp = _regroup(params, pipe.balance)
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))

    @jax.jit
    def step(p, x, k):
        return pipe(p, x, key=k, train=True)

    out = step(sp, x, jax.random.key(0))
    assert out.shape == (8, WIDTH)


# ---------- validation ----------

def test_mesh_without_stage_axis_rejected():
    from jax.sharding import Mesh
    seq, _ = make_mlp(jax.random.key(0))
    bad = Mesh(np.asarray(jax.devices()[:2]).reshape(2), ("replica",))
    with pytest.raises(ValueError):
        Pipe(seq, chunks=2, mesh=bad)


def test_mesh_stage_count_mismatch_rejected():
    seq, _ = make_mlp(jax.random.key(0))
    with pytest.raises(ValueError):
        Pipe(seq, chunks=2, mesh=stage_mesh(2), n_stages=4)


def test_mesh_deferred_batch_norm_gpipe_only():
    """BN through mesh= rides the wavefront executor's stat lanes; the
    table schedules reject it (stats are not routed there)."""
    seq, _ = make_mlp(jax.random.key(0))
    Pipe(seq, chunks=2, mesh=stage_mesh(2), deferred_batch_norm=True)
    with pytest.raises(NotImplementedError):
        Pipe(seq, chunks=2, mesh=stage_mesh(2), deferred_batch_norm=True,
             schedule="zb-h1")


# ---------- the reference's headline use: the tutorial LM through Pipe ----

def test_tutorial_lm_through_pipe_mesh():
    """Encoder + blocks + Decoder (reference main.py:139-157) driven by
    Pipe(mesh=...) — heterogeneous partitions (embed / blocks / decode) on
    the compiled executor, matching the plain Sequential."""
    import dataclasses

    from pipe_tpu.models.transformer_lm import LMConfig, build_sequential

    cfg = dataclasses.replace(LMConfig().tiny(), n_layers=2, dropout=0.0)
    seq = build_sequential(cfg)
    # 5 layers (embed, posenc, 2 blocks, decoder) over 2 uneven stages
    pipe = Pipe(seq, chunks=2, checkpoint="except_last",
                mesh=stage_mesh(2), balance=[3, 2])
    tokens = jnp.zeros((2, cfg.seq_len), jnp.int32)
    sp = pipe.init(jax.random.key(0), tokens)

    x = jax.random.randint(jax.random.key(1), (4, cfg.seq_len),
                           0, cfg.vocab, jnp.int32)
    flat = [p for stage in sp for p in stage]
    expected = seq.apply(flat, x)
    got = pipe(sp, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)

    emu = Pipe(seq, chunks=2, checkpoint="except_last", balance=[3, 2])

    def loss_mesh(p):
        logits = pipe(p, x, key=jax.random.key(3), train=True)
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    def loss_emu(p):
        logits = emu(p, x, key=jax.random.key(3), train=True)
        return jnp.mean(logits.astype(jnp.float32) ** 2)

    gm = jax.grad(loss_mesh)(sp)
    ge = jax.grad(loss_emu)(sp)
    for a, b in zip(jax.tree_util.tree_leaves(gm),
                    jax.tree_util.tree_leaves(ge)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-5)
