"""Backend-tuned PRNG keys (utils/rng): impl selection + executor plumbing.

The rbg impl is selected on TPU for throughput (threefry mask bits cost 26%
of a tutorial-LM step, measured — see ``pipe_tpu/utils/rng.py``); these tests
pin the properties the framework relies on regardless of impl: fold_in/split
derivation, bit-identical replay (remat parity), and that a non-default-impl
key flows through the compiled pipeline executor end to end.
"""

import jax
import jax.numpy as jnp
import pytest

from pipe_tpu.utils.rng import default_prng_impl, make_key


def test_default_impl_off_tpu_is_none():
    assert jax.default_backend() != "tpu"  # suite runs on the CPU platform
    assert default_prng_impl() is None


def test_default_impl_on_tpu_is_rbg(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert default_prng_impl() == "rbg"


def test_make_key_explicit_impl_overrides(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    k = make_key(0, impl="threefry2x32")
    assert "threefry" in str(jax.random.key_impl(k))


@pytest.mark.parametrize("impl", [None, "rbg"])
def test_key_properties_hold_per_impl(impl):
    k = make_key(7, impl=impl)
    # same key -> same bits (remat replay relies on this)
    a = jax.random.bernoulli(k, 0.5, (64,))
    b = jax.random.bernoulli(k, 0.5, (64,))
    assert jnp.array_equal(a, b)
    # fold_in derives a different stream
    c = jax.random.bernoulli(jax.random.fold_in(k, 1), 0.5, (64,))
    assert not jnp.array_equal(a, c)
    # split works
    k1, k2 = jax.random.split(k)
    assert not jnp.array_equal(jax.random.key_data(k1),
                               jax.random.key_data(k2))


def test_rbg_key_through_compiled_pipeline():
    """A non-default-impl key must survive the executor's fold_in plumbing
    (scans, shard_map) — same dropout-under-remat replay contract."""
    from pipe_tpu.core import microbatch as mb
    from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
    from pipe_tpu.parallel.mesh import make_mesh
    from pipe_tpu.parallel.spmd import SpmdPipeline, stack_stage_params

    cfg = LMConfig(vocab=64, d_model=16, nhead=2, d_ff=32, n_layers=2,
                   seq_len=8, dropout=0.2)
    model = PipelinedLM(cfg, 2)
    sp, prep, postp = model.init(make_key(0))
    mesh = make_mesh(2, 1, devices=jax.devices()[:2])
    pipe = SpmdPipeline(mesh, model.stage_fn, pre_fn=model.pre_fn,
                        post_fn=model.loss_post_fn, post_with_batch=True,
                        checkpoint="except_last")
    tokens = jax.random.randint(make_key(1), (4, cfg.seq_len), 0, cfg.vocab,
                                jnp.int32)
    x, _ = mb.stack_scatter({"tokens": tokens,
                             "targets": jnp.roll(tokens, -1, -1)}, 2)
    stacked = stack_stage_params(sp)
    key = make_key(2, impl="rbg")
    rows1 = pipe(stacked, prep, postp, x, key=key, train=True)
    rows2 = pipe(stacked, prep, postp, x, key=key, train=True)
    assert jnp.all(jnp.isfinite(rows1))
    # deterministic under the same rbg key (dropout replay)
    assert jnp.array_equal(rows1, rows2)
    # and the grad path composes
    g = jax.grad(lambda p: jnp.mean(pipe(p, prep, postp, x, key=key,
                                         train=True)))(stacked)
    assert all(jnp.all(jnp.isfinite(l)) for l in jax.tree_util.tree_leaves(g))
