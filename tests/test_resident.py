"""The resident serve loop (PR 11): fused multi-chunk decode + spec lane.

Gold contract, layered on the serve suite's pins:

* **Parity.** A resident backend — the `lax.while_loop` that runs up to
  ``resident_chunks`` decode chunks back-to-back on device — emits
  bitwise the tokens of the single-chunk tick path, on both backends,
  slab and paged, greedy and sampled. The single-chunk path itself is
  pinned to the one-shot ``Generator`` by tests/test_serve.py, so the
  resident loop inherits the gold contract transitively (and we
  re-assert it directly for greedy).
* **Zero steady-state recompiles.** The resident program traces exactly
  once across staggered arrivals and mixed prompt lengths
  (``serve.engine.resident_traces`` / ``serve.ring.resident_traces``).
* **The regather decision lives on device.** A steady-state resident
  tick (no prefill) makes ZERO host-driven gather decisions
  (``serve.kv.regather_host_decisions``); the non-resident paged path
  makes one per tick.
* **Early exit.** The device loop exits before ``r_max`` when any live
  slot finishes (``serve.engine.device_exits``), so a freed slot waits
  at most one chunk, not a full horizon.
* **Speculative decode.** Every draft source — prompt-history n-gram,
  truncated-pipeline (first stage(s) + tied embedding head), and the
  multi-branch tree — emits bitwise the per-prompt ``Generator``
  tokens (draft rejection rolls back to exact greedy/sampled
  behaviour) while emitting MORE than one token per verify round on
  draftable text (``serve.engine.spec_emitted`` >
  ``serve.engine.spec_rounds``). The ring backend speaks the same
  contract: the Generator split key chain threads through the
  revolutions, so ring spec output is Generator-bitwise too, greedy
  AND sampled. Adaptive-K rung switches, one-token prompts (no draft
  history) and EOS landing mid-accepted-run all preserve the pin.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.inference import GenerationConfig, Generator
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
from pipe_tpu.obs.telemetry import get_registry
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.spmd import stack_stage_params
from pipe_tpu.serve import (BucketSpec, RingSlotBackend, ServeEngine,
                            SingleDeviceSlotBackend)

CFG = LMConfig(vocab=89, d_model=32, nhead=4, d_ff=64, n_layers=4,
               seq_len=32, dropout=0.0)


@pytest.fixture(scope="module")
def model_and_params():
    model = PipelinedLM(CFG, n_stages=2)
    return model, model.init(jax.random.key(0))


def _one_shot_refs(model, params, prompts, gen_cfg, seed):
    g = Generator(model, gen_cfg)
    return [np.asarray(g.generate(params,
                                  jnp.asarray(p, jnp.int32)[None],
                                  jax.random.key(seed)))[0]
            for p in prompts]


def _mixed_prompts(lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [list(rng.randint(1, CFG.vocab, size=n)) for n in lengths]


def _make_backend(kind, model, params, gen_cfg, layout="slab",
                  max_len=16, **kw):
    """A backend with the resident knobs threaded per kind: the ring
    speaks ``resident_revolutions``, the single device
    ``resident_chunks``."""
    if layout == "paged":
        kw.setdefault("kv_block_size", 4)
        kw.setdefault("prefill_chunk", 4)
    else:
        kw.setdefault("buckets", BucketSpec.of(4, 8))
    if kind == "single":
        kw.setdefault("num_slots", 2)
        return SingleDeviceSlotBackend(model, params, max_len=max_len,
                                       gen=gen_cfg, **kw)
    if "resident_chunks" in kw:
        kw["resident_revolutions"] = kw.pop("resident_chunks")
    kw.pop("num_slots", None)
    sp, pre, post = params
    mesh = make_mesh(2, 1)
    return RingSlotBackend(mesh, model, stack_stage_params(sp), pre, post,
                           max_len=max_len, gen=gen_cfg, **kw)


def _drive_staggered(backend, prompts, seed):
    """Mid-flight arrivals: slot churn exercises relaunches with mixed
    budgets, not one clean batch."""
    eng = ServeEngine(backend)
    ids = [eng.submit(prompts[0], seed=seed).id]
    eng.tick()
    ids += [eng.submit(p, seed=seed).id for p in prompts[1:]]
    eng.run_until_idle()
    return [list(eng.response(r).tokens) for r in ids]


# ---------------------------------------------------------------------------
# parity: resident loop vs the single-chunk tick path


PARITY_CASES = [
    ("single", "slab", 0.0), ("single", "slab", 0.8),
    ("single", "paged", 0.0), ("single", "paged", 0.8),
    ("ring", "slab", 0.0), ("ring", "slab", 0.8),
    ("ring", "paged", 0.0), ("ring", "paged", 0.8),
]
PARITY_IDS = [f"{k}-{l}-{'greedy' if t == 0.0 else 'sampled'}"
              for k, l, t in PARITY_CASES]


@pytest.mark.parametrize("kind,layout,temp", PARITY_CASES, ids=PARITY_IDS)
def test_resident_matches_single_chunk_tick(kind, layout, temp,
                                            model_and_params):
    """resident=True with a small horizon (forcing several launches)
    emits bitwise the non-resident tick path; greedy additionally
    re-pins the one-shot Generator directly."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=6, temperature=temp,
                               top_k=12 if temp else None)
    prompts = _mixed_prompts((3, 5, 4))

    base = _make_backend(kind, model, params, gen_cfg, layout,
                         resident=False)
    ref = _drive_staggered(base, prompts, seed=7)
    res = _make_backend(kind, model, params, gen_cfg, layout,
                        resident=True, resident_chunks=3)
    got = _drive_staggered(res, prompts, seed=7)
    assert got == ref
    if temp == 0.0:
        refs = _one_shot_refs(model, params, prompts, gen_cfg, seed=7)
        for g, r in zip(got, refs):
            np.testing.assert_array_equal(np.asarray(g), r)


def test_resident_eos_retires_early(model_and_params):
    """Device-side eos done-masking: the resident loop retires at the
    EOS token with the same truncated output as the tick path."""
    model, params = model_and_params
    probe = GenerationConfig(max_new_tokens=8, temperature=0.0)
    prompts = _mixed_prompts((4, 6))
    free = _one_shot_refs(model, params, prompts, probe, seed=7)
    eos = int(free[0][2])   # a token greedy decoding actually emits

    gen_cfg = GenerationConfig(max_new_tokens=8, temperature=0.0,
                               eos_token_id=eos)
    base = _make_backend("single", model, params, gen_cfg,
                         resident=False)
    ref = _drive_staggered(base, prompts, seed=7)
    res = _make_backend("single", model, params, gen_cfg,
                        resident=True, resident_chunks=8)
    got = _drive_staggered(res, prompts, seed=7)
    assert got == ref
    assert any(t and t[-1] == eos for t in got)


# ---------------------------------------------------------------------------
# the trace pin + host-sync accounting


@pytest.mark.parametrize("kind", ["single", "ring"])
def test_resident_traces_once_and_counts_host_syncs(kind,
                                                    model_and_params):
    """The resident whole-program traces exactly once across staggered
    traffic and mixed prompt lengths, and every launch is one counted
    host sync feeding the host-overhead-per-token gauge."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=6, temperature=0.0)
    prompts = _mixed_prompts((3, 5, 4, 7, 5))
    refs = _one_shot_refs(model, params, prompts, gen_cfg, seed=7)

    backend = _make_backend(kind, model, params, gen_cfg,
                            resident=True, resident_chunks=4)
    reg = get_registry()
    counter = ("serve.engine.resident_traces" if kind == "single"
               else "serve.ring.resident_traces")
    traces0 = reg.counter(counter).value
    syncs0 = reg.counter("serve.engine.host_syncs").value

    got = _drive_staggered(backend, prompts, seed=7)
    for g, r in zip(got, refs):
        np.testing.assert_array_equal(np.asarray(g), r)
    assert reg.counter(counter).value - traces0 == 1
    assert reg.counter("serve.engine.host_syncs").value - syncs0 >= 1
    assert reg.gauge("serve.engine.host_overhead_per_token").value >= 0.0


def test_regather_decision_stays_on_device(model_and_params):
    """Paged resident: prefill arms the device regather flag (one host
    decision per admission); steady-state resident ticks make ZERO.
    The non-resident path decides once per tick — the host tax the
    carry fold removes."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=8, temperature=0.0)
    reg = get_registry()

    res = _make_backend("single", model, params, gen_cfg, "paged",
                        resident=True, resident_chunks=1)
    eng = ServeEngine(res)
    eng.submit(_mixed_prompts((4,))[0], seed=7)
    eng.submit(_mixed_prompts((5,))[0], seed=7)
    eng.tick()          # prefills (arm the flag) + first launch
    d0 = reg.counter("serve.kv.regather_host_decisions").value
    eng.tick()
    eng.tick()          # two steady-state ticks: no prefill
    assert reg.counter("serve.kv.regather_host_decisions").value - d0 == 0
    eng.run_until_idle()

    base = _make_backend("single", model, params, gen_cfg, "paged",
                         resident=False)
    eng = ServeEngine(base)
    eng.submit(_mixed_prompts((4,))[0], seed=7)
    eng.submit(_mixed_prompts((5,))[0], seed=7)
    eng.tick()
    d0 = reg.counter("serve.kv.regather_host_decisions").value
    eng.tick()
    eng.tick()
    assert reg.counter("serve.kv.regather_host_decisions").value - d0 == 2
    eng.run_until_idle()


def test_resident_early_exit_on_slot_free(model_and_params):
    """Backend unit: with budgets [2, many] and an 8-chunk horizon the
    device exits after chunk 2 (slot 0 done) — the readout is 2 chunks
    wide and the early-exit counter ticks."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=8, temperature=0.0)
    backend = _make_backend("single", model, params, gen_cfg,
                            resident=True, resident_chunks=8)
    backend.prefill(0, _mixed_prompts((4,))[0], seed=7)
    backend.prefill(1, _mixed_prompts((5,))[0], seed=7)
    reg = get_registry()
    exits0 = reg.counter("serve.engine.device_exits").value
    toks, valid = backend.decode(np.array([True, True]),
                                 budgets=np.array([2, 100], np.int32))
    assert toks.shape == (2, 2)
    assert valid.all()
    assert reg.counter("serve.engine.device_exits").value - exits0 == 1


# ---------------------------------------------------------------------------
# speculative decode: acceptance parity + rollback


SPEC_CASES = [("slab", 0.0), ("slab", 0.8), ("paged", 0.0),
              ("paged", 0.8)]
SPEC_IDS = [f"{l}-{'greedy' if t == 0.0 else 'sampled'}"
            for l, t in SPEC_CASES]


@pytest.mark.parametrize("layout,temp", SPEC_CASES, ids=SPEC_IDS)
def test_speculative_decode_matches_generator(layout, temp,
                                              model_and_params):
    """K-token draft/verify: responses are bitwise the per-prompt
    Generator output (rejections roll back exactly), and on draftable
    (repetitive) text the lane emits more than one token per verify
    round — the speedup the lane exists for."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=8, temperature=temp,
                               top_k=12 if temp else None)
    prompts = [[5, 6, 5, 6, 5, 6], [3, 3, 3, 3]]
    refs = _one_shot_refs(model, params, prompts, gen_cfg, seed=11)

    backend = _make_backend("single", model, params, gen_cfg, layout,
                            max_len=24, resident=True,
                            resident_chunks=4, spec_tokens=3)
    reg = get_registry()
    rounds0 = reg.counter("serve.engine.spec_rounds").value
    emitted0 = reg.counter("serve.engine.spec_emitted").value

    got = _drive_staggered(backend, prompts, seed=11)
    for g, r in zip(got, refs):
        np.testing.assert_array_equal(np.asarray(g), r)
    rounds = reg.counter("serve.engine.spec_rounds").value - rounds0
    emitted = reg.counter("serve.engine.spec_emitted").value - emitted0
    # each response's first token comes from prefill, not the spec lane
    assert emitted >= sum(len(g) for g in got) - len(prompts)
    assert rounds > 0 and emitted > rounds   # acceptance rate > 0


DRAFT_CASES = [
    ("truncated", None, "slab", 0.0), ("truncated", None, "paged", 0.8),
    ("tree", 2, "slab", 0.8), ("tree", 3, "paged", 0.0),
]
DRAFT_IDS = [f"{d}{b or ''}-{l}-{'greedy' if t == 0.0 else 'sampled'}"
             for d, b, l, t in DRAFT_CASES]


@pytest.mark.parametrize("draft,branches,layout,temp", DRAFT_CASES,
                         ids=DRAFT_IDS)
def test_draft_sources_match_generator(draft, branches, layout, temp,
                                       model_and_params):
    """Model-based drafts: the truncated pipeline (stage 0 + tied
    embedding head) and the B-branch tree verified in ONE fixed-shape
    chunk under the causal tree mask both stay bitwise the Generator —
    acceptance changes throughput, never tokens."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=8, temperature=temp,
                               top_k=12 if temp else None)
    prompts = [[5, 6, 5, 6, 5, 6], [3, 3, 3, 3]]
    refs = _one_shot_refs(model, params, prompts, gen_cfg, seed=11)

    backend = _make_backend("single", model, params, gen_cfg, layout,
                            max_len=24, resident=True,
                            resident_chunks=4, spec_tokens=3,
                            draft=draft, spec_branches=branches)
    reg = get_registry()
    rounds0 = reg.counter("serve.engine.spec_rounds").value
    emitted0 = reg.counter("serve.engine.spec_emitted").value
    got = _drive_staggered(backend, prompts, seed=11)
    for g, r in zip(got, refs):
        np.testing.assert_array_equal(np.asarray(g), r)
    rounds = reg.counter("serve.engine.spec_rounds").value - rounds0
    emitted = reg.counter("serve.engine.spec_emitted").value - emitted0
    assert rounds > 0 and emitted >= rounds
    if temp == 0.0:
        # greedy verify matches greedy draft often enough to accept; a
        # sampled verify on random weights legitimately accepts ~nothing
        assert emitted > rounds
    assert reg.gauge("serve.spec.draft_cost_frac").value > 0.0


RING_SPEC_CASES = [
    ("ngram", "slab", 0.0), ("ngram", "paged", 0.8),
    ("truncated", "slab", 0.8), ("truncated", "paged", 0.0),
]
RING_SPEC_IDS = [f"{d}-{l}-{'greedy' if t == 0.0 else 'sampled'}"
                 for d, l, t in RING_SPEC_CASES]


@pytest.mark.parametrize("draft,layout,temp", RING_SPEC_CASES,
                         ids=RING_SPEC_IDS)
def test_ring_speculative_matches_generator(draft, layout, temp,
                                            model_and_params):
    """Ring spec: the K-row wavefront chunk rides the ppermute message
    ring while stage n-1 verifies against the Generator split key chain
    — staggered arrivals (stale in-flight rounds discarded by the
    admission inequalities) still emit bitwise Generator tokens, greedy
    AND sampled."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=8, temperature=temp,
                               top_k=12 if temp else None)
    prompts = [[5, 6, 5, 6, 5, 6], [3, 3, 3, 3], [7, 8, 7, 8, 7]]
    refs = _one_shot_refs(model, params, prompts, gen_cfg, seed=11)

    backend = _make_backend("ring", model, params, gen_cfg, layout,
                            max_len=24, resident=True,
                            resident_chunks=4, spec_tokens=3,
                            draft=draft)
    reg = get_registry()
    rounds0 = reg.counter("serve.engine.spec_rounds").value
    emitted0 = reg.counter("serve.engine.spec_emitted").value
    got = _drive_staggered(backend, prompts, seed=11)
    for g, r in zip(got, refs):
        np.testing.assert_array_equal(np.asarray(g), r)
    rounds = reg.counter("serve.engine.spec_rounds").value - rounds0
    emitted = reg.counter("serve.engine.spec_emitted").value - emitted0
    assert rounds > 0 and emitted >= rounds
    if temp == 0.0:
        assert emitted > rounds   # acceptance rate > 0 under greedy


def test_adaptive_k_shrink_grow_parity(model_and_params):
    """Per-slot acceptance-EWMA adaptive K: a draftable slot next to an
    adversarial one forces rung switches mid-stream; the rollback
    overwrite under a shrunk-then-regrown K stays bitwise the
    Generator, every rung comes from the pre-traced ladder (traces <=
    ladder rungs), and a second identical drive retraces NOTHING."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=10, temperature=0.8,
                               top_k=12)
    prompts = [[5, 6, 5, 6, 5, 6, 5, 6],        # draftable
               _mixed_prompts((7,), seed=3)[0]]  # adversarial
    refs = _one_shot_refs(model, params, prompts, gen_cfg, seed=13)

    backend = _make_backend("single", model, params, gen_cfg,
                            max_len=32, resident=True,
                            resident_chunks=4, spec_tokens=4,
                            spec_adaptive=True)
    reg = get_registry()
    traces0 = reg.counter("serve.engine.resident_traces").value
    got = _drive_staggered(backend, prompts, seed=13)
    for g, r in zip(got, refs):
        np.testing.assert_array_equal(np.asarray(g), r)
    traced = reg.counter("serve.engine.resident_traces").value - traces0
    assert 1 <= traced <= len(backend._spec_ladder)
    # misses on the adversarial slot shrank its EWMA below the optimism
    # every request starts at
    assert backend._spec_ewma.min() < float(backend.spec_tokens)
    # warm steady state: the same traffic again traces zero new programs
    got2 = _drive_staggered(backend, prompts, seed=13)
    assert got2 == got
    assert reg.counter("serve.engine.resident_traces").value \
        - traces0 == traced


@pytest.mark.parametrize("kind", ["single", "ring"])
def test_spec_empty_history_slots(kind, model_and_params):
    """One-token prompts: the n-gram drafter has NO history to match
    and the truncated drafter extends a length-1 prefix — junk drafts
    must be rejected back to exact Generator output, never crash or
    corrupt the rollback."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=6, temperature=0.0)
    prompts = [[7], [3]]
    refs = _one_shot_refs(model, params, prompts, gen_cfg, seed=5)
    for draft in ("ngram", "truncated"):
        backend = _make_backend(kind, model, params, gen_cfg,
                                max_len=24, resident=True,
                                resident_chunks=4, spec_tokens=3,
                                draft=draft)
        got = _drive_staggered(backend, prompts, seed=5)
        for g, r in zip(got, refs):
            np.testing.assert_array_equal(np.asarray(g), r)


@pytest.mark.parametrize("kind", ["single", "ring"])
def test_spec_eos_mid_accepted_run(kind, model_and_params):
    """EOS emitted in the MIDDLE of an accepted draft run: the response
    truncates exactly at EOS (tokens past it in the same round are
    dropped) and retires early, matching the Generator's own EOS
    masking."""
    model, params = model_and_params
    probe = GenerationConfig(max_new_tokens=8, temperature=0.0)
    prompts = [[5, 6, 5, 6, 5, 6], [3, 3, 3, 3]]
    free = _one_shot_refs(model, params, prompts, probe, seed=11)
    eos = int(free[0][3])   # a token greedy decoding actually emits

    gen_cfg = GenerationConfig(max_new_tokens=8, temperature=0.0,
                               eos_token_id=eos)
    refs = [r.tolist() for r in
            _one_shot_refs(model, params, prompts, gen_cfg, seed=11)]
    # the Generator pads past EOS; responses stop AT it
    refs = [r[:r.index(eos) + 1] if eos in r else r for r in refs]
    backend = _make_backend(kind, model, params, gen_cfg,
                            max_len=24, resident=True,
                            resident_chunks=4, spec_tokens=3)
    got = _drive_staggered(backend, prompts, seed=11)
    assert got == refs
    assert any(t and t[-1] == eos and len(t) < 8 for t in got)


# ---------------------------------------------------------------------------
# knob validation: loud rejections, not silent fallbacks


def test_resident_knob_validation(model_and_params):
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=4, temperature=0.0)
    with pytest.raises(ValueError, match="resident"):
        _make_backend("single", model, params, gen_cfg,
                      resident="yes")
    with pytest.raises(ValueError, match="resident_chunks"):
        _make_backend("single", model, params, gen_cfg,
                      resident=True, resident_chunks=0)
    with pytest.raises(ValueError, match="spec_tokens"):
        _make_backend("single", model, params, gen_cfg,
                      resident=True, spec_tokens=1)
    with pytest.raises(ValueError, match="resident"):
        _make_backend("single", model, params, gen_cfg,
                      resident=False, spec_tokens=3)
    # draft knobs configure the spec lane — meaningless without it
    with pytest.raises(ValueError, match="speculative lane"):
        _make_backend("single", model, params, gen_cfg,
                      resident=True, draft="truncated")
    # the tree draft needs branches to fan out
    with pytest.raises(ValueError, match="spec_branches"):
        _make_backend("single", model, params, gen_cfg,
                      resident=True, spec_tokens=3, draft="tree")
    # the ring wavefront carries ONE linear K-row chunk per slot, so
    # the tree's branch fan-out and the adaptive ladder's shape switch
    # stay single-device; a ring draft deeper than stage 0 would need
    # layers that are not resident where the draft runs
    with pytest.raises(ValueError, match="single-device"):
        _make_backend("ring", model, params, gen_cfg, resident=True,
                      spec_tokens=3, draft="tree", spec_branches=2)
    with pytest.raises(ValueError, match="single-device"):
        _make_backend("ring", model, params, gen_cfg, resident=True,
                      spec_tokens=3, spec_adaptive=True)
    with pytest.raises(ValueError, match="STRICT prefix"):
        _make_backend("ring", model, params, gen_cfg, resident=True,
                      spec_tokens=3, draft="truncated", draft_stages=2)
    # ring spec decode is resident-only: budgets must ride the launch
    spec_ring = _make_backend("ring", model, params, gen_cfg,
                              resident=True, spec_tokens=3)
    with pytest.raises(ValueError, match="resident-only"):
        spec_ring.decode(np.array([True, False]))


def test_spec_headroom_tightens_validate(model_and_params):
    """spec_tokens=K writes K rows per verify round — K-1 rows of slack
    must stay below max_len or the fixed-shape write would clamp.
    validate() rejects at submit with the headroom named."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=8, temperature=0.0)
    backend = _make_backend("single", model, params, gen_cfg,
                            max_len=24, resident=True, spec_tokens=3,
                            buckets=BucketSpec.of(16))
    eng = ServeEngine(backend)
    with pytest.raises(ValueError, match="speculative headroom"):
        eng.submit(list(range(1, 16)), max_new_tokens=8)
    # the same request without the spec lane is servable
    plain = _make_backend("single", model, params, gen_cfg,
                          max_len=24, resident=True,
                          buckets=BucketSpec.of(16))
    ServeEngine(plain).submit(list(range(1, 16)), max_new_tokens=8)
