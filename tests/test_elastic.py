"""pipe_tpu.resilience.elastic: survive stage loss, re-plan, resume.

The pins that frame the elastic rung:

* **Bitwise opt-out** — ``TrainerConfig.elastic=None`` lowers the train
  step byte-identical before and after the elastic machinery exists in
  the process (``test_train_step_hlo_unchanged_by_elastic``).
* **Bitwise replication** — every buddy capture re-hashes the copies
  against the source shards, and restore reassembles the exact state.
* **Bitwise regrouping** — restacking an n-stage state over n-1 stages
  equals a born-(n-1)-stage initialization (global-layer init keys),
  and a resumed segment equals the uninterrupted run on the same
  global batch indices.
* **Verified re-planning** — the degraded op table passes the same
  emission proofs (verify_op_tables + compile_phases) every table must.
* **The drill** — kill a stage mid-run: heartbeat detection, re-plan,
  buddy restore, resumed loss trajectory tracking the unkilled run
  (this is the ``pytest -m chaos`` smoke lane bench.py executes).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pipe_tpu.core.balance import BalanceError, rebalance_stage_loss
from pipe_tpu.core.schedule import replan_stage_loss
from pipe_tpu.data import lm_text
from pipe_tpu.models.transformer_lm import LMConfig
from pipe_tpu.resilience import (KILL_NONE, ChaosPlan, ElasticConfig,
                                 Fault, HopHealth, ResilienceConfig,
                                 restack_state, stage_heartbeat)
from pipe_tpu.resilience.chaos import INJECT_NONE, inject_scope, kill_scope
from pipe_tpu.resilience.elastic import train_elastic
from pipe_tpu.train.loop import Trainer, TrainerConfig
from pipe_tpu.utils.rng import make_key

pytestmark = pytest.mark.chaos

CFG = LMConfig(vocab=67, d_model=16, nhead=2, d_ff=32, n_layers=4,
               seq_len=32, dropout=0.0)
# 12 layers regroup uniformly over 4 AND 3 stages — the drill geometry
DRILL_CFG = LMConfig(vocab=67, d_model=16, nhead=2, d_ff=32, n_layers=12,
                     seq_len=32, dropout=0.0)
RC = ResilienceConfig(warmup_steps=100, rewind_after=3, snapshot_every=3,
                      data_backoff_s=0.0, rewind_backoff_s=0.0)


def _tc(n_stages=2, elastic="default", **kw):
    base = dict(batch_size=8, bptt=16, chunks=2, n_stages=n_stages,
                schedule="gpipe", checkpoint="never", lr=0.01,
                resilience=RC)
    if elastic == "default":
        base["elastic"] = ElasticConfig(snapshot_every=3, dead_after=2)
    elif elastic is not None:
        base["elastic"] = elastic
    base.update(kw)
    return TrainerConfig(**base)


@pytest.fixture(scope="module")
def source():
    ids = np.random.RandomState(0).randint(0, CFG.vocab, size=20000)
    return lm_text.batchify(ids, 8)


def _host(tree):
    return jax.tree_util.tree_map(
        lambda a: np.asarray(a) if isinstance(a, jax.Array) else a, tree)


def _trees_equal(a, b):
    al, bl = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(al) == len(bl) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(al, bl))


# ---------------------------------------------------------------------------
# plan / balance units


def test_rebalance_stage_loss():
    assert rebalance_stage_loss([3, 3, 3, 3]) == [4, 4, 4]
    # cost-weighted: the expensive layer ends up alone-ish
    assert rebalance_stage_loss([2, 2, 2],
                                costs=[1, 1, 5, 1, 1, 1]) == [3, 3]
    with pytest.raises(BalanceError):
        rebalance_stage_loss([4])                   # nothing to shrink to
    with pytest.raises(BalanceError):
        rebalance_stage_loss([2, 2], costs=[1.0])   # costs/layers mismatch


def test_replan_stage_loss_emits_verified_tables():
    for schedule in ("gpipe", "1f1b", "zb-h1"):
        plan = replan_stage_loss(8, 4, 1, schedule=schedule,
                                 balance=[3, 3, 3, 3])
        assert plan.n_stages == 3
        assert plan.balance == (4, 4, 4)
        assert plan.op is not None      # table emitted for the new width
        assert plan.phase.accepted, plan.phase.reason


def test_replan_stage_loss_rejects_bad_topologies():
    with pytest.raises(ValueError):
        replan_stage_loss(8, 1, 0)                  # n_stages < 2
    with pytest.raises(ValueError):
        replan_stage_loss(8, 4, 7)                  # lost stage out of range
    with pytest.raises(ValueError):
        replan_stage_loss(8, 4, 1, schedule="interleaved-1f1b")


def test_chaos_kill_plan_units():
    plan = ChaosPlan([Fault("kill_stage", step=6, stage=1),
                      Fault("nan_grads", step=2)])
    assert plan.train_kill(5) == KILL_NONE
    assert plan.train_kill(6) == 1
    assert plan.train_kill(99) == 1                 # permanent
    survivor = plan.without("kill_stage")
    assert survivor.train_kill(99) == KILL_NONE
    assert any(f.kind == "nan_grads" for f in survivor.faults)
    with pytest.raises(ValueError):
        plan.without("not_a_kind")


def test_persistent_hop_drop_and_hop_health():
    from pipe_tpu.core import microbatch as mb
    from pipe_tpu.parallel import emulator

    def stage(p, x, ctx):
        return jnp.tanh(x @ p)

    key = jax.random.key(7)
    params = [jax.random.normal(jax.random.fold_in(key, s), (8, 8))
              for s in range(2)]
    xs = [mb.Batch(jax.random.normal(jax.random.fold_in(key, 10 + i),
                                     (4, 8)), atomic=True)
          for i in range(3)]

    def run(chaos, hh=None):
        out = emulator.run([stage, stage], params, list(xs), chaos=chaos,
                           hop_health=hh)
        return [np.asarray(b.values[0]) for b in out]

    clean = run(None)
    hh = HopHealth(dead_after=2)
    faulted = run(ChaosPlan([Fault("persistent_hop_drop", step=0,
                                   stage=0)]), hh)
    # EVERY micro-batch dropped (a transient drop hits exactly one)
    assert all(not np.array_equal(a, b) for a, b in zip(faulted, clean))
    assert hh.streak(0) == 3
    assert hh.dead_hops == [0]
    # transient drop by contrast: streak resets, hop never declared dead
    hh2 = HopHealth(dead_after=2)
    run(ChaosPlan([Fault("transport_drop", step=0, stage=0,
                         microbatch=1)]), hh2)
    assert hh2.streak(0) == 0 and hh2.dead_hops == []
    assert all(np.array_equal(a, b) for a, b in zip(run(None), clean))


# ---------------------------------------------------------------------------
# detection physics


def test_kill_heartbeat_localizes_stage(source):
    """Killing stage j zeroes grads for every stage <= j and none
    above — the largest silent index IS the dead stage."""
    tr = Trainer(CFG, _tc(), chaos=ChaosPlan([]))
    state = tr.init_state()
    data, target = next(tr._batches(source, 2))
    x, w = tr._make_x(data, target)

    def beat(kill):
        with inject_scope(jnp.int32(INJECT_NONE)), \
                kill_scope(jnp.int32(kill)):
            _, _, _, grads = tr._compute_update(
                state, x, w, make_key(0), jnp.float32(0.01),
                inject=jnp.int32(INJECT_NONE), magnitude=jnp.float32(0.0))
        return np.asarray(stage_heartbeat(grads[0], 2))

    clean = beat(KILL_NONE)
    assert (clean > 0).all()
    k0 = beat(0)
    assert k0[0] == 0.0 and k0[1] > 0.0
    k1 = beat(1)
    assert (k1 == 0.0).all()        # last stage kill silences everything


# ---------------------------------------------------------------------------
# buddy replication


def test_buddy_capture_restore_bitwise(source):
    tr = Trainer(CFG, _tc(), chaos=ChaosPlan([]))
    state = tr.init_state()
    store = tr.elastic_store()
    store.capture(state, 3)          # verify=True re-hashes vs source
    assert store.has_snapshot and store.step == 3
    restored = store.restore_state()
    assert _trees_equal(_host(state), restored)


def test_buddy_restore_detects_corruption(source):
    tr = Trainer(CFG, _tc(), chaos=ChaosPlan([]))
    store = tr.elastic_store()
    store.capture(tr.init_state(), 0)
    store._buddy[0] = np.array(store._buddy[0], copy=True)
    store._buddy[0].reshape(-1)[0] += 1.0
    with pytest.raises(RuntimeError, match="manifest"):
        store.restore_state()


# ---------------------------------------------------------------------------
# restacking


def test_restack_matches_born_narrow_init():
    """4-stage init regrouped over 2 stages == born-2-stage init,
    bitwise — PipelinedLM keys every block by GLOBAL layer index."""
    tr4 = Trainer(CFG, _tc(4, chunks=4),
                  devices=jax.devices()[:4])
    tr2 = Trainer(CFG, _tc(2))
    s4, s2 = tr4.init_state(), tr2.init_state()
    restacked = restack_state(_host(s4), 4, 2)
    assert _trees_equal(restacked.params, _host(s2.params))
    with pytest.raises(ValueError):
        restack_state(_host(s4), 4, 3)   # 4 layers don't regroup over 3


def test_restack_roundtrip_identity():
    tr2 = Trainer(CFG, _tc(2))
    s2 = _host(tr2.init_state())
    again = restack_state(restack_state(s2, 2, 4), 4, 2)
    assert _trees_equal(again, s2)


# ---------------------------------------------------------------------------
# the HLO pin (acceptance criterion)


def test_train_step_hlo_unchanged_by_elastic(source):
    """elastic=None => the train step lowers byte-identical before and
    after the elastic machinery exists in the process."""
    tr = Trainer(CFG, _tc(elastic=None, resilience=None))
    state = tr.init_state()
    data, target = next(tr._batches(source, 1))
    x, w = tr._make_x(data, target)
    args = (state, x, w, jax.random.key(0), jnp.float32(0.01))
    base = tr._step_fn.lower(*args).as_text()

    etr = Trainer(CFG, _tc(), chaos=ChaosPlan([Fault("kill_stage",
                                                     step=0, stage=0)]))
    es = etr.init_state()
    aux = (jnp.float32(0.0), jnp.int32(0), jnp.int32(0),
           jnp.zeros((2,), jnp.int32))
    etr._step_fn.lower(es, aux, x, w, jax.random.key(0),
                       jnp.float32(0.01), jnp.int32(-1),
                       jnp.float32(0.0), jnp.int32(0)).as_text()
    etr.elastic_store().capture(es, 0)

    assert tr._step_fn.lower(*args).as_text() == base


def test_elastic_requires_resilience_and_flat_schedules():
    with pytest.raises(ValueError, match="resilience"):
        Trainer(CFG, _tc(resilience=None))
    with pytest.raises(ValueError, match="interleave"):
        Trainer(CFG, _tc(schedule="interleaved-1f1b", chunks=4))


# ---------------------------------------------------------------------------
# mid-epoch resumption


@pytest.mark.slow
def test_resume_start_step_bitwise(source):
    """Splitting an epoch at a step boundary (the elastic resume path)
    reproduces the uninterrupted run bitwise: batches, PRNG folds, and
    chaos indices all key on the GLOBAL batch index."""
    tr = Trainer(CFG, _tc(), chaos=ChaosPlan([]))
    straight, _ = tr.train_epoch(source, 0, tr.init_state(), max_steps=6,
                                 log_every=0)
    tr2 = Trainer(CFG, _tc(), chaos=ChaosPlan([]))
    part, _ = tr2.train_epoch(source, 0, tr2.init_state(), max_steps=4,
                              log_every=0)
    resumed, _ = tr2.train_epoch(source, 0, part, max_steps=6,
                                 log_every=0, start_step=4)
    assert _trees_equal(_host(straight.params), _host(resumed.params))
    assert _trees_equal(_host(straight.opt_state),
                        _host(resumed.opt_state))


# ---------------------------------------------------------------------------
# the drill (bench.py's ``pytest -m chaos`` smoke lane)


@pytest.mark.slow
def test_elastic_drill_loss_trajectory():
    """Kill stage 1 of 4 mid-run: detection + re-plan + buddy restore,
    and the resumed loss trajectory tracks the unkilled 4-stage run
    step-for-step after the rewind point."""
    ids = np.random.RandomState(0).randint(0, DRILL_CFG.vocab, size=20000)
    src = lm_text.batchify(ids, 8)

    def cfg(n):
        return _tc(n, chunks=4,
                   elastic=ElasticConfig(snapshot_every=3, dead_after=2))

    plan = ChaosPlan([Fault("kill_stage", step=6, stage=1)])
    tr = Trainer(DRILL_CFG, cfg(4), chaos=plan)
    tr2, state, info = train_elastic(tr, src, max_steps=10,
                                     log_fn=lambda m: None)
    assert info["replans"] == 1
    rec = info["recoveries"][0]
    assert rec["stage"] == 1
    assert rec["snapshot_step"] == 5 and rec["detected_step"] == 7
    assert tr2.cfg.n_stages == 3
    assert all(bool(jnp.isfinite(l).all())
               for l in jax.tree_util.tree_leaves(state.params)
               if jnp.issubdtype(l.dtype, jnp.inexact))

    # unkilled reference on the same global batches
    ref = Trainer(DRILL_CFG, cfg(4), chaos=ChaosPlan([]))
    _, ref_info = ref.train_epoch(src, 0, ref.init_state(), max_steps=10,
                                  log_every=0)
    got = info["loss_by_step"]
    want = ref_info["loss_by_step"]
    resumed_steps = sorted(got)
    assert resumed_steps == [6, 7, 8, 9]   # resumed from snapshot 5 + 1
    for b in resumed_steps:
        assert got[b] == pytest.approx(want[b], rel=1e-4), (
            f"step {b}: resumed loss {got[b]} vs unkilled {want[b]}")
