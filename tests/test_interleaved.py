"""Interleaved virtual-stage pipeline tests.

Transparency: the interleaved executor must match the plain (unpipelined)
model forward and gradients exactly, for every (devices d, interleave v,
micro-batches m >= d) combination — plus the bubble model and the
device-major parameter permutation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core import microbatch as mb
from pipe_tpu.core.partition import StageCtx
from pipe_tpu.core.schedule import InterleavedSchedule
from pipe_tpu.ops.layers import Linear
from pipe_tpu.parallel.interleaved import (InterleavedSpmdPipeline,
                                           stack_interleaved_params)
from pipe_tpu.parallel.mesh import make_mesh

WIDTH = 8


def make_stages(S, key):
    layer = Linear(WIDTH)
    params = [layer.init(jax.random.fold_in(key, s), jnp.zeros((1, WIDTH)))
              for s in range(S)]

    def stage_fn(p, h, ctx):
        return jnp.tanh(layer.apply(p, h))

    return stage_fn, params


def reference(stage_fn, params, x):
    h = x
    for p in params:
        h = stage_fn(p, h, StageCtx())
    return h


def test_stack_interleaved_layout():
    d, v = 2, 2
    params = [{"w": jnp.full((1,), float(s))} for s in range(d * v)]
    stacked = stack_interleaved_params(params, d)
    # device-major rows: device 0 -> stages (0, 2); device 1 -> (1, 3)
    np.testing.assert_array_equal(
        np.asarray(stacked["w"]).ravel(), [0.0, 2.0, 1.0, 3.0])


@pytest.mark.parametrize("d,v,chunks", [(2, 2, 4), (2, 3, 2), (4, 2, 4),
                                        (1, 4, 2), (8, 1, 8)])
def test_forward_transparency(d, v, chunks):
    S = d * v
    stage_fn, params = make_stages(S, jax.random.key(0))
    mesh = make_mesh(d, 1)
    pipe = InterleavedSpmdPipeline(mesh, stage_fn, v=v)
    stacked = stack_interleaved_params(params, d)

    x = jax.random.normal(jax.random.key(1), (chunks * 2, WIDTH))
    xs, bs = mb.stack_scatter(x, chunks)
    got = mb.stack_gather(pipe(stacked, {}, {}, xs), bs)
    exp = reference(stage_fn, params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("checkpoint", ["never", "always"])
def test_gradient_transparency(checkpoint):
    d, v = 2, 2
    S = d * v
    stage_fn, params = make_stages(S, jax.random.key(0))
    mesh = make_mesh(d, 1)
    pipe = InterleavedSpmdPipeline(mesh, stage_fn, v=v,
                                   checkpoint=checkpoint)
    stacked = stack_interleaved_params(params, d)

    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    xs, bs = mb.stack_scatter(x, 4)

    def pipe_loss(sp):
        return jnp.mean(mb.stack_gather(
            pipe(sp, {}, {}, xs, train=True), bs) ** 2)

    def plain_loss(ps):
        return jnp.mean(reference(stage_fn, ps, x) ** 2)

    got = jax.grad(pipe_loss)(stacked)
    exp = stack_interleaved_params(jax.grad(plain_loss)(list(params)), d)
    for a, b in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(exp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_pre_post_and_data_axis():
    d, v = 2, 2
    stage_fn, params = make_stages(d * v, jax.random.key(0))
    emb, dec = Linear(WIDTH), Linear(3)
    pre_p = emb.init(jax.random.key(10), jnp.zeros((1, 5)))
    post_p = dec.init(jax.random.key(11), jnp.zeros((1, WIDTH)))
    mesh = make_mesh(d, 2)
    pipe = InterleavedSpmdPipeline(
        mesh, stage_fn, v=v,
        pre_fn=lambda p, x, ctx: emb.apply(p, x),
        post_fn=lambda p, h, ctx: dec.apply(p, h))
    stacked = stack_interleaved_params(params, d)

    x = jax.random.normal(jax.random.key(1), (8, 5))
    xs, bs = mb.stack_scatter(x, 4)
    got = mb.stack_gather(pipe(stacked, pre_p, post_p, xs), bs)
    exp = dec.apply(post_p, reference(stage_fn, params, emb.apply(pre_p, x)))
    np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                               rtol=1e-5, atol=1e-6)


def test_m_less_than_d_rejected():
    d, v = 4, 2
    stage_fn, params = make_stages(d * v, jax.random.key(0))
    mesh = make_mesh(d, 1)
    pipe = InterleavedSpmdPipeline(mesh, stage_fn, v=v)
    stacked = stack_interleaved_params(params, d)
    x = jax.random.normal(jax.random.key(1), (4, WIDTH))
    xs, _ = mb.stack_scatter(x, 2)  # m=2 < d=4
    with pytest.raises(ValueError, match="micro-batches >= devices"):
        pipe(stacked, {}, {}, xs)


def test_bubble_improves_with_v():
    sched = InterleavedSchedule(v=2)
    m, d = 8, 4
    gpipe_bubble = (d - 1) / (m + d - 1)
    inter_bubble = sched.device_bubble(m, d)
    assert inter_bubble < gpipe_bubble


# ------ interleaved (v>1) forward/eval executor (VERDICT r3 #6) ------

def test_interleaved_pipe_forward_matches_emulator():
    """Pipe(mesh=, schedule='interleaved-1f1b') forward: the op tables run
    with BWD rows masked to IDLE (the reference's eval-mode pipeline with
    checkpointing off, pipeline.py:153-155) — outputs equal the serial
    emulator, with and without a data axis."""
    from pipe_tpu import Lambda, Linear, Pipe, Sequential
    from pipe_tpu.parallel.mesh import make_mesh

    def build():
        return Sequential([Linear(8), Lambda(jnp.tanh), Linear(8),
                           Lambda(jnp.tanh), Linear(8), Lambda(jnp.tanh),
                           Linear(8), Linear(4)])

    x = jax.random.normal(jax.random.key(1), (8, 8))
    emu = Pipe(build(), chunks=4, n_stages=4, balance=[2, 2, 2, 2])
    params = emu.init(jax.random.key(0), x)
    exp = emu(params, x, train=False)
    for n_data in (1, 2):
        pipe = Pipe(build(), chunks=4,
                    mesh=make_mesh(2, n_data,
                                   devices=jax.devices()[:2 * n_data]),
                    schedule="interleaved-1f1b", balance=[2, 2, 2, 2])
        packed = pipe.shard_params(pipe.init(jax.random.key(0), x))
        out = jax.jit(lambda p, pipe=pipe: pipe(p, x))(packed)
        np.testing.assert_allclose(np.asarray(out), np.asarray(exp),
                                   rtol=1e-5, atol=1e-6)


def test_interleaved_trained_model_eval_on_mesh():
    """Train with interleaved-1f1b loss_and_grad, then compute eval loss
    ON the mesh (no emulator regroup) — it must equal the emulator's eval
    of the same trained weights."""
    import optax

    from pipe_tpu import Lambda, Linear, Pipe, Sequential
    from pipe_tpu.parallel.mesh import make_mesh

    def build():
        return Sequential([Linear(8), Lambda(jnp.tanh), Linear(8),
                           Lambda(jnp.tanh), Linear(8), Lambda(jnp.tanh),
                           Linear(8), Linear(4)])

    x = jax.random.normal(jax.random.key(1), (8, 8))
    y = jax.random.normal(jax.random.key(2), (8, 4))

    def loss_fn(o, t):
        return jnp.sum((o - t) ** 2, axis=-1)

    pipe = Pipe(build(), chunks=4,
                mesh=make_mesh(2, 1, devices=jax.devices()[:2]),
                schedule="interleaved-1f1b", balance=[2, 2, 2, 2])
    packed = pipe.shard_params(pipe.init(jax.random.key(0), x))
    tx = optax.sgd(0.05)
    opt = tx.init(packed)

    @jax.jit
    def step(pk, opt):
        loss, g = pipe.loss_and_grad(pk, x, targets=y, loss_fn=loss_fn)
        upd, opt = tx.update(g, opt, pk)
        return optax.apply_updates(pk, upd), opt, loss

    for _ in range(8):
        packed, opt, loss = step(packed, opt)
        jax.block_until_ready(loss)
    out_mesh = jax.jit(lambda p: pipe(p, x))(packed)
    eval_mesh = float(jnp.mean(loss_fn(out_mesh, y)))
    emu = Pipe(build(), chunks=4, n_stages=4, balance=[2, 2, 2, 2])
    out_emu = emu(pipe.unshard_params(packed), x, train=False)
    eval_emu = float(jnp.mean(loss_fn(out_emu, y)))
    assert eval_mesh == pytest.approx(eval_emu, rel=1e-5)
    assert eval_mesh < float(loss)  # eval (no further step) is consistent
