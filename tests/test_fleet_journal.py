"""Crash-tolerant control plane: the durable request journal, the
controller restart/rejoin recovery path, and the adversarial wire
chaos injection (ISSUE 20).

Tier-1 throughout: the journal writes to tmp_path, recovery runs over
in-process transports (fresh FakeBackend engines standing in for
surviving children), and the wire-chaos tests drive the framing layer
over socketpairs — no child interpreters. The real SIGKILL-the-parent
drills live in ``tools/fleet_bench.py`` (rev r20).
"""

import json
import socket
import struct

import pytest

from pipe_tpu.fleet import (DisaggController, FleetController,
                            InProcessTransport, JournalState,
                            RequestJournal, RouterPolicy)
from pipe_tpu.fleet.proc import (FrameCorrupt, _pack, apply_wire_chaos,
                                 recv_frame, send_frame)
from pipe_tpu.resilience import ChaosPlan, Fault, TickWatchdog
from pipe_tpu.serve import RequestQueue, ServeEngine
from test_router import FakeBackend

# ---------------------------------------------------------------------------
# the journal: append, replay, torn lines


def _journal(tmp_path, **kw):
    kw.setdefault("fsync", False)          # tmpfs tests skip the fsync
    return RequestJournal(str(tmp_path / "j"), **kw)


def test_journal_replays_lifecycle_into_state(tmp_path):
    j = _journal(tmp_path)
    j.append("submit", request=0, prompt=[1, 2], max_new_tokens=8, seed=0)
    j.append("submit", request=1, prompt=[3], max_new_tokens=4, seed=0)
    j.append("place", request=0, replica=1, attempts=1)
    j.append("place", request=1, replica=0, attempts=1)
    j.append("park", request=1, attempts=1, delay_s=0.1)
    j.append("place", request=1, replica=1, attempts=2)
    j.append("deliver", request=0, status="ok", finish_reason="eos",
             tokens=8)
    j.close()
    st = RequestJournal.recover(j.path)
    assert sorted(st.requests) == [0, 1]
    assert st.terminal.keys() == {0}
    assert st.orphans == [1]               # submitted, never delivered
    assert st.placed_on == {1: 1}          # the LAST un-consumed placement
    assert st.attempts == {0: 1, 1: 2}     # parks don't refund attempts
    assert st.max_request_id == 1
    assert not st.clean


def test_journal_clean_shutdown_is_only_clean_at_the_end(tmp_path):
    j = _journal(tmp_path)
    j.append("submit", request=0, prompt=[1], max_new_tokens=2, seed=0)
    j.append("deliver", request=0, status="ok", finish_reason="eos",
             tokens=2)
    j.close(clean=True)
    assert RequestJournal.recover(j.path).clean
    # restart appends more work: the log no longer ENDS clean
    j2 = RequestJournal(j.path, fsync=False)
    j2.append("submit", request=1, prompt=[2], max_new_tokens=2, seed=0)
    j2.close()
    st = RequestJournal.recover(j.path)
    assert not st.clean and st.orphans == [1]


def test_journal_recover_tolerates_torn_final_line(tmp_path):
    # mirror of the EventLog pin in test_fleet_obs.py: a crash can tear
    # only the FINAL line, and recovery must replay everything before it
    j = _journal(tmp_path)
    j.append("submit", request=0, prompt=[1], max_new_tokens=2, seed=0)
    j.append("place", request=0, replica=0, attempts=1)
    j.close()
    raw = open(j.path, "rb").read()
    with open(j.path, "wb") as fh:
        fh.write(raw[:-9])                 # tear the last record mid-JSON
    st = RequestJournal.recover(j.path)
    assert st.orphans == [0]
    assert st.placed_on == {}              # the torn "place" never happened


def test_journal_recover_refuses_torn_middle_line(tmp_path):
    j = _journal(tmp_path)
    j.append("submit", request=0, prompt=[1], max_new_tokens=2, seed=0)
    j.close()
    lines = open(j.path, "rb").read().splitlines()
    lines.insert(1, b'{"kind": "place", "request')   # torn MIDDLE line
    lines.append(json.dumps({"kind": "deliver", "request": 0,
                             "status": "ok", "finish_reason": "eos",
                             "tokens": 2}).encode())
    with open(j.path, "wb") as fh:
        fh.write(b"\n".join(lines) + b"\n")
    with pytest.raises(json.JSONDecodeError):
        RequestJournal.recover(j.path)     # corruption, not a crash: loud


def test_journal_rejects_unknown_record_kind(tmp_path):
    j = _journal(tmp_path)
    with pytest.raises(ValueError, match="unknown journal record kind"):
        j.append("frobnicate", request=0)
    j.close()


def test_journal_missing_file_recovers_empty(tmp_path):
    st = RequestJournal.recover(str(tmp_path / "never-written"))
    assert st.records == 0 and st.orphans == [] and not st.clean


def test_record_replica_writes_rejoin_snapshot(tmp_path):
    j = _journal(tmp_path)
    j.record_replica(0, port=5001, token="t0", pid=123, role="mixed")
    j.record_replica(1, port=5002, token="t1", pid=124, role="mixed")
    j.record_replica(0, port=5003, token="t2", pid=125, role="mixed")
    j.close()
    st = RequestJournal.recover(j.path)
    assert st.replicas[0]["port"] == 5003  # latest record wins
    meta = json.load(open(str(tmp_path / "j" / "fleet.json")))
    assert meta["replicas"]["1"]["token"] == "t1"


def test_shadow_record_pops_placement_and_tags_state():
    st = JournalState()
    for rec in [
        {"kind": "submit", "request": 3, "prompt": [1], "max_new_tokens": 9},
        {"kind": "place", "request": 3, "replica": 0, "attempts": 1},
        {"kind": "shadow", "request": 3, "src": 0, "max_new_tokens": 9},
    ]:
        st.apply(rec)
    assert 3 in st.shadow and st.placed_on == {}
    assert st.orphans == [3]


# ---------------------------------------------------------------------------
# RouterPolicy backoff: the doubling sequence and the cap, fake-clocked


def _controller(n, journal=None, **policy_kw):
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    transports = [
        InProcessTransport(
            ServeEngine(FakeBackend(2),
                        RequestQueue(capacity=32, clock=clock),
                        watchdog=TickWatchdog(stuck_slack_ticks=None)))
        for _ in range(n)]
    ctl = FleetController(transports,
                          RequestQueue(capacity=32, clock=clock),
                          policy=RouterPolicy(**policy_kw),
                          journal=journal)
    return ctl, t


def _run(ctl, t, max_ticks=300):
    out = []
    for _ in range(max_ticks):
        if ctl.idle:
            return out
        t[0] += 0.01
        out.extend(ctl.tick())
    raise AssertionError(f"fleet not idle: {ctl.counts()}")


def test_park_backoff_doubles_from_policy_base_and_caps(tmp_path):
    # the parked delay is min(base * 2^(attempts-1), cap) — pin the
    # whole sequence through the journal's park records, fake-clocked
    j = _journal(tmp_path)
    ctl, t = _controller(1, journal=j,
                         backoff_base_s=0.05, backoff_max_s=0.2,
                         retry_budget=8)
    req = ctl.submit([1, 2], max_new_tokens=2)
    delays = []
    for attempts in (1, 2, 3, 4, 5):
        req.attempts = attempts
        ctl._park(req, t[0])
        delays.append(ctl._parked.pop()[0] - t[0])
    assert delays == [0.05, 0.1, 0.2, 0.2, 0.2]
    j.close()
    # journal carries the same delays (the WAL is written BEFORE the park)
    journaled = [rec["delay_s"] for rec in map(json.loads,
                 open(j.path)) if rec["kind"] == "park"]
    assert journaled == [0.05, 0.1, 0.2, 0.2, 0.2]


# ---------------------------------------------------------------------------
# restart from the journal (in-process stand-ins for surviving children)


def test_restart_from_journal_delivers_orphans_exactly_once(tmp_path):
    j = _journal(tmp_path)
    ctl, t = _controller(2, journal=j, backoff_base_s=0.0)
    ids = [ctl.submit([3, 4, 5], max_new_tokens=4).id for _ in range(6)]
    # run just far enough that SOME ids deliver, then "SIGKILL": drop
    # the controller on the floor, journal un-closed (no clean record)
    delivered_pre = []
    for _ in range(200):
        t[0] += 0.01
        delivered_pre.extend(r.request_id for r in ctl.tick())
        if len(delivered_pre) >= 2:
            break
    assert delivered_pre, "drill needs at least one pre-crash terminal"
    in_flight = [i for i in ids if i not in delivered_pre]
    assert in_flight, "drill needs work in flight at the crash"

    st = RequestJournal.recover(j.path)
    assert sorted(st.orphans) == sorted(in_flight)
    # fresh life: new engines (the in-process "children" died with the
    # parent — process children would be re-dialed instead)
    ctl2, t2 = _controller(2, backoff_base_s=0.0)
    ctl2 = FleetController.from_journal(
        st, [r.transport for r in ctl2.replicas],
        RequestQueue(capacity=32, clock=lambda: t2[0]),
        policy=RouterPolicy(backoff_base_s=0.0))
    out = _run(ctl2, t2)
    assert sorted(r.request_id for r in out) == sorted(in_flight)
    # the exactly-once ledger came back: pre-crash terminals are
    # stubbed, and a replica replaying one must trip the raise
    from pipe_tpu.serve.queue import Response
    with pytest.raises(RuntimeError, match="exactly-once"):
        ctl2._deliver(Response(request_id=delivered_pre[0], tokens=[],
                               status="ok", finish_reason="eos",
                               prompt_len=0, ttft=None, latency=0.0))
    # new submissions never reuse a journaled id
    assert ctl2.submit([1], max_new_tokens=1).id > max(ids)


def test_restart_on_clean_log_skips_reconciliation(tmp_path):
    j = _journal(tmp_path)
    ctl, t = _controller(1, journal=j, backoff_base_s=0.0)
    rid = ctl.submit([1, 2], max_new_tokens=2).id
    _run(ctl, t)
    j.close(clean=True)
    st = RequestJournal.recover(j.path)
    assert st.clean and st.orphans == []
    ctl2, t2 = _controller(1, backoff_base_s=0.0)
    ctl2 = FleetController.from_journal(
        st, [r.transport for r in ctl2.replicas],
        RequestQueue(capacity=32, clock=lambda: t2[0]))
    assert ctl2.idle                       # nothing parked, nothing tracked
    assert rid in ctl2._responses          # but the ledger stub is there


def test_disagg_restore_rebuilds_phase_tags(tmp_path):
    st = JournalState()
    for rec in [
        # id 0 crossed the prefill->decode hinge (shadow journaled)
        {"kind": "submit", "request": 0, "prompt": [1, 2],
         "max_new_tokens": 9, "seed": 0},
        {"kind": "place", "request": 0, "replica": 0, "attempts": 1},
        {"kind": "shadow", "request": 0, "src": 0, "max_new_tokens": 9},
        # id 1 never finished its prefill
        {"kind": "submit", "request": 1, "prompt": [3],
         "max_new_tokens": 7, "seed": 0},
        {"kind": "place", "request": 1, "replica": 1, "attempts": 1},
    ]:
        st.apply(rec)
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    transports = [
        InProcessTransport(
            ServeEngine(FakeBackend(2),
                        RequestQueue(capacity=32, clock=clock),
                        watchdog=TickWatchdog(stuck_slack_ticks=None)))
        for _ in range(2)]
    ctl = DisaggController.from_journal(
        st, transports, RequestQueue(capacity=32, clock=clock),
        policy=RouterPolicy(backoff_base_s=0.0), clock=clock)
    req0 = ctl._tracked[0]
    assert req0.phase == "decode"
    assert req0.max_new_tokens == 9        # full budget restored
    assert ctl._prefill_on[0] == 0         # prefix source remembered
    req1 = ctl._tracked[1]
    assert req1.phase == "prefill"
    assert req1.max_new_tokens == 1        # re-clamped for the replay
    assert ctl._orig_max_new[1] == 7       # the real budget is stashed


# ---------------------------------------------------------------------------
# adversarial wire chaos at the framing layer


def test_frames_carry_crc_and_seq_in_the_header():
    a, b = socket.socketpair()
    try:
        frame = send_frame(a, {"op": "response", "id": 1}, seq=7)
        (n,) = struct.unpack(">I", frame[:4])
        assert n == len(frame) - 4         # length still covers the body
        import zlib
        (crc,) = struct.unpack(">I", frame[4:8])
        assert crc == zlib.crc32(frame[8:]) & 0xFFFFFFFF
        assert struct.unpack(">I", frame[8:12]) == (7,)
        msg = recv_frame(b)
        assert msg["_seq"] == 7 and msg["id"] == 1
        # unsequenced frames surface no _seq key at all
        send_frame(a, {"op": "hb"})
        assert "_seq" not in recv_frame(b)
    finally:
        a.close()
        b.close()


@pytest.mark.chaos
def test_wire_corrupt_frame_is_rejected_whole_never_half_parsed():
    plan = ChaosPlan([Fault("wire_corrupt", step=0, count=1)])
    a, b = socket.socketpair()
    try:
        from pipe_tpu.fleet.proc import _frame
        frame = _frame(_pack({"op": "place", "id": 9}), 1)
        frames, hold = apply_wire_chaos(plan, 0, frame)
        assert hold == 0.0 and len(frames) == 1 and frames[0] != frame
        a.sendall(frames[0])
        with pytest.raises(FrameCorrupt):
            recv_frame(b)                  # rejected whole, not half-parsed
        # the NEXT frame (index 1, uncovered) passes untouched
        frames2, _ = apply_wire_chaos(plan, 1, frame)
        assert frames2 == [frame]
        a.sendall(frames2[0])
        assert recv_frame(b)["id"] == 9
    finally:
        a.close()
        b.close()


@pytest.mark.chaos
def test_wire_dup_frames_collapse_under_seq_dedup():
    plan = ChaosPlan([Fault("wire_dup", step=0, count=1)])
    a, b = socket.socketpair()
    try:
        from pipe_tpu.fleet.proc import _frame
        frame = _frame(_pack({"op": "response", "id": 4, "tokens": [1]}), 3)
        frames, _ = apply_wire_chaos(plan, 0, frame)
        assert frames == [frame, frame]    # duplicated on the wire
        for f in frames:
            a.sendall(f)
        recv_max, taken = 0, []
        for _ in frames:                   # the receiver's dedup discipline
            msg = recv_frame(b)
            seq = msg.pop("_seq")
            if seq <= recv_max:
                continue
            recv_max = seq
            taken.append(msg["id"])
        assert taken == [4]                # exactly once
    finally:
        a.close()
        b.close()


@pytest.mark.chaos
def test_wire_partition_drops_frame_and_returns_hold():
    plan = ChaosPlan([Fault("wire_partition", step=2, count=1,
                            magnitude=2.0)])
    frame = b"\x00\x00\x00\x08" + b"x" * 8
    assert apply_wire_chaos(plan, 0, frame) == ([frame], 0.0)
    frames, hold = apply_wire_chaos(plan, 2, frame)
    assert frames == [] and hold == 2.0    # lost with the connection
    # magnitude is capped so a typo can't hold the wire forever
    big = ChaosPlan([Fault("wire_partition", step=0, count=1,
                           magnitude=1e9)])
    assert apply_wire_chaos(big, 0, frame)[1] == 30.0


@pytest.mark.chaos
def test_wire_faults_address_replicas_via_stage():
    plan = ChaosPlan([Fault("wire_corrupt", step=0, count=5, stage=1)])
    frame = b"\x00\x00\x00\x08" + b"y" * 8
    # replica 0's wire is untouched; replica 1's frame is corrupted
    assert apply_wire_chaos(plan, 0, frame, replica=0) == ([frame], 0.0)
    corrupted, _ = apply_wire_chaos(plan, 0, frame, replica=1)
    assert corrupted[0] != frame


def test_wire_fault_accessor_rejects_non_wire_kinds():
    plan = ChaosPlan([Fault("wire_delay", step=0, count=1, magnitude=0.01)])
    with pytest.raises(ValueError, match="not a wire fault kind"):
        plan.wire_fault("stall_tick", 0)
    assert plan.wire_fault("wire_delay", 0) is not None
    assert plan.wire_fault("wire_delay", 1) is None
