"""Ring attention / context parallelism tests.

Core property: attention over a device-sharded sequence is numerically
identical (forward AND gradient) to single-device attention — the long-
context analogue of the pipeline transparency tests (SURVEY §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.ops.ring_attention import (blockwise_attention_reference,
                                         ring_attention)
from pipe_tpu.parallel.context import (context_parallel_attention,
                                       make_context_mesh)


def qkv(key, b=2, s=32, h=4, d=8, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("n_ctx", [1, 2, 4, 8])
@pytest.mark.parametrize("causal", [False, True])
def test_forward_parity(n_ctx, causal):
    q, k, v = qkv(jax.random.key(0))
    mesh = make_context_mesh(n_ctx)
    got = context_parallel_attention(mesh, q, k, v, causal=causal)
    exp = blockwise_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_gradient_parity(causal):
    q, k, v = qkv(jax.random.key(1), s=16)
    mesh = make_context_mesh(4)

    def ring_loss(q, k, v):
        return jnp.sum(
            context_parallel_attention(mesh, q, k, v, causal=causal) ** 2)

    def plain_loss(q, k, v):
        return jnp.sum(
            blockwise_attention_reference(q, k, v, causal=causal) ** 2)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_plain = jax.grad(plain_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_plain):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_oracle_matches_naive_softmax():
    q, k, v = qkv(jax.random.key(2), s=8)
    exp = blockwise_attention_reference(q, k, v, causal=True)
    # naive: full mask + softmax
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(8.0)
    mask = jnp.tril(jnp.ones((8, 8), bool))
    w = jax.nn.softmax(jnp.where(mask, logits, -jnp.inf), axis=-1)
    naive = jnp.einsum("bhqk,bkhd->bqhd", w, v)
    np.testing.assert_allclose(np.asarray(exp), np.asarray(naive),
                               rtol=1e-5, atol=1e-6)


def test_jit_and_bf16():
    q, k, v = qkv(jax.random.key(3), dtype=jnp.bfloat16)
    mesh = make_context_mesh(4)
    got = jax.jit(lambda q, k, v: context_parallel_attention(
        mesh, q, k, v, causal=True))(q, k, v)
    exp = blockwise_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got, dtype=np.float32),
                               np.asarray(exp, dtype=np.float32),
                               rtol=5e-2, atol=5e-2)
    assert got.dtype == jnp.bfloat16


def test_long_sequence_streams():
    """Sequence 8x longer than one device's block attends correctly."""
    q, k, v = qkv(jax.random.key(4), b=1, s=128, h=2, d=4)
    mesh = make_context_mesh(8)
    got = context_parallel_attention(mesh, q, k, v, causal=True)
    exp = blockwise_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                               rtol=2e-5, atol=2e-6)
