"""Micro-batch layer tests — model: upstream ``test_microbatch.py`` strategy
(SURVEY §4): scatter/gather identity, torch.chunk sizing, NoChunk, Batch ops."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core import microbatch as mb


def test_scatter_gather_identity():
    x = jnp.arange(32.0).reshape(8, 4)
    batches = mb.scatter((x,), 4)
    assert len(batches) == 4
    assert all(b.atomic for b in batches)
    out = mb.gather(batches)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_scatter_torch_chunk_semantics_non_divisible():
    # torch.chunk(10, 4) -> sizes [3, 3, 3, 1]
    x = jnp.arange(10.0)[:, None]
    batches = mb.scatter((x,), 4)
    assert [b.tensor.shape[0] for b in batches] == [3, 3, 3, 1]
    np.testing.assert_array_equal(np.asarray(mb.gather(batches)), np.asarray(x))


def test_scatter_fewer_chunks_than_requested():
    # torch.chunk(6, 4) -> ceil size 2 -> only 3 chunks
    x = jnp.arange(6.0)[:, None]
    batches = mb.scatter((x,), 4)
    assert len(batches) == 3
    assert [b.tensor.shape[0] for b in batches] == [2, 2, 2]


def test_scatter_multiple_inputs_and_nonarray():
    x = jnp.ones((8, 2))
    y = jnp.zeros((8, 3))
    batches = mb.scatter((x, "tag", y), 2)
    assert len(batches) == 2
    assert not batches[0].atomic
    assert batches[0][1] == "tag"
    out = mb.gather(batches)
    assert out[1] == "tag"
    assert out[0].shape == (8, 2) and out[2].shape == (8, 3)


def test_nochunk_replicated():
    x = jnp.ones((8, 2))
    mask = jnp.arange(5)
    batches = mb.scatter((x, mb.NoChunk(mask)), 4)
    for b in batches:
        np.testing.assert_array_equal(np.asarray(b[1]), np.asarray(mask))
    out = mb.gather(batches)
    assert out[1].shape == (5,)


def test_nochunk_rejects_nonarray():
    with pytest.raises(TypeError):
        mb.NoChunk("not an array")


def test_check_requires_array():
    with pytest.raises(TypeError):
        mb.check("just a string")
    with pytest.raises(TypeError):
        mb.check()
    mb.check(jnp.ones(3))  # no raise


def test_inconsistent_batch_sizes():
    with pytest.raises(ValueError):
        mb.scatter((jnp.ones((8, 2)), jnp.ones((4, 2))), 2)


def test_batch_call_and_atomicity():
    b = mb.Batch(jnp.ones((2, 3)), atomic=True)
    out = b.call(lambda t: t * 2)
    assert out.atomic
    out2 = b.call(lambda t: (t, t + 1))
    assert not out2.atomic and len(out2) == 2


def test_batch_find_tensor_idx():
    b = mb.Batch(("meta", jnp.ones(3)), atomic=False)
    assert b.find_tensor_idx() == 1
    with pytest.raises(ValueError):
        mb.Batch(("a", "b"), atomic=False).find_tensor_idx()


def test_stack_scatter_gather_roundtrip():
    x = jnp.arange(24.0).reshape(8, 3)
    stacked, bs = mb.stack_scatter(x, 4)
    assert stacked.shape == (4, 2, 3) and bs == 8
    out = mb.stack_gather(stacked, bs)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_stack_scatter_pads_non_divisible():
    x = jnp.arange(10.0)[:, None]
    stacked, bs = mb.stack_scatter(x, 4)
    assert stacked.shape == (4, 3, 1) and bs == 10
    out = mb.stack_gather(stacked, bs)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_stack_scatter_tree_with_nochunk():
    tree = {"x": jnp.ones((8, 2)), "m": mb.NoChunk(jnp.arange(3))}
    stacked, bs = mb.stack_scatter(tree, 2)
    assert stacked["x"].shape == (2, 4, 2)
    assert stacked["m"].shape == (2, 3)


def test_scatter_under_jit():
    @jax.jit
    def f(x):
        batches = mb.scatter((x,), 4)
        batches = [b.call(lambda t: t * 2) for b in batches]
        return mb.gather(batches)

    x = jnp.arange(8.0)[:, None]
    np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x) * 2)
