"""MoE decoding (ops/moe.moe_block_decode + the generalized sharded
generator).

Capacity note: GShard capacity is computed from the current call's token
count, so cached decode (T=b per step) and a full-sequence forward
(T=b*seq) can drop different tokens at tight capacity factors. The tests
use capacity_factor=n_experts (nothing ever drops in either path) so
parity is exact; the production default keeps the standard 1.25.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core.partition import StageCtx
from pipe_tpu.inference import GenerationConfig, Generator
from pipe_tpu.inference.tp import TPShardedGenerator
from pipe_tpu.models.moe_lm import MoELMConfig, MoEPipelinedLM
from pipe_tpu.parallel.mesh import make_mesh

CFG = MoELMConfig(vocab=67, d_model=32, nhead=4, d_ff=64, n_layers=2,
                  seq_len=32, dropout=0.0, n_experts=4, top_k=2,
                  capacity_factor=4.0)   # = n_experts: drop-free


def test_moe_block_decode_matches_apply():
    """Prefill via moe_block_decode (ep=None) == moe_block_apply (same
    token count, so same capacity — exact)."""
    from pipe_tpu.ops.moe import moe_block_apply, moe_block_decode
    from pipe_tpu.ops.moe import moe_block_init

    p = moe_block_init(jax.random.key(0), 32, 4, 64, 4)
    h = jax.random.normal(jax.random.key(1), (2, 12, 32))
    ref, _aux = moe_block_apply(p, h, StageCtx(train=False), n_experts=4,
                                k=2, capacity_factor=4.0, ep_axis=None)
    cache = {"k": jnp.zeros((2, 16, 4, 8)), "v": jnp.zeros((2, 16, 4, 8))}
    out, cache = moe_block_decode(p, h, cache, 0, n_experts=4, k=2,
                                  capacity_factor=4.0, ep_axis=None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_moe_greedy_generation_matches_naive_reforward():
    model = MoEPipelinedLM(CFG, 2, ep_axis=None)
    params = model.init(jax.random.key(2))
    prompt = jax.random.randint(jax.random.key(3), (2, 6), 0, CFG.vocab,
                                jnp.int32)
    max_new = 5
    gen = Generator(model, GenerationConfig(max_new_tokens=max_new,
                                            temperature=0.0))
    fast = np.asarray(gen.generate(params, prompt))

    def full_logits(tokens):
        sp, pre, post = params
        ctx = StageCtx(train=False)
        h = model.pre_fn(pre, tokens, ctx)
        for blocks in sp:
            h = model.stage_fn(blocks, h, ctx)
        return model.post_fn(post, h, ctx)

    seq = np.asarray(prompt)
    naive = []
    for _ in range(max_new):
        logits = full_logits(jnp.asarray(seq))
        nxt = np.asarray(jnp.argmax(logits[:, -1, :], axis=-1),
                         dtype=np.int32)
        naive.append(nxt)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(fast, np.stack(naive, axis=1))


def test_moe_sharded_greedy_matches_unsharded():
    model_ep = MoEPipelinedLM(CFG, 2)              # ep_axis=MODEL_AXIS
    model_1 = MoEPipelinedLM(CFG, 2, ep_axis=None)
    params = model_1.init(jax.random.key(4))
    prompt = jax.random.randint(jax.random.key(5), (2, 8), 0, CFG.vocab,
                                jnp.int32)
    gen_cfg = GenerationConfig(max_new_tokens=5, temperature=0.0)
    ref = np.asarray(Generator(model_1, gen_cfg).generate(params, prompt))
    got = np.asarray(TPShardedGenerator(
        make_mesh(1, 1, n_model=2), model_ep, gen_cfg).generate(params,
                                                                prompt))
    np.testing.assert_array_equal(got, ref)


def test_ep_sharded_beam_matches_unsharded():
    """Beam search over EP-sharded (experts + heads) weights: tokens AND
    scores equal the single-device Generator's — pins the serving.md/
    PARITY claim for the MoE family (the dense dispatch must hold under
    the b*k beam batch)."""
    model_ep = MoEPipelinedLM(CFG, 2)
    model_1 = MoEPipelinedLM(CFG, 2, ep_axis=None)
    params = model_1.init(jax.random.key(0))
    prompt = jax.random.randint(jax.random.key(5), (2, 8), 0, CFG.vocab,
                                jnp.int32)
    gen_cfg = GenerationConfig(max_new_tokens=5, num_beams=2)
    ref_t, ref_s = Generator(model_1, gen_cfg).generate_with_scores(
        params, prompt)
    got_t, got_s = TPShardedGenerator(
        make_mesh(1, 1, n_model=2), model_ep,
        gen_cfg).generate_with_scores(params, prompt)
    np.testing.assert_array_equal(np.asarray(got_t), np.asarray(ref_t))
    np.testing.assert_allclose(np.asarray(got_s), np.asarray(ref_s),
                               rtol=1e-5)
