"""Ring-pipelined decoding (inference/pipelined.py).

Gold contract: greedy pipelined generation over stage-sharded params is
token-for-token identical to the single-device Generator — the ring, the
group interleave, the sacrificial-slot masking, and the prefill handoff
are all layout/schedule choices, never math choices.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.inference import GenerationConfig, Generator
from pipe_tpu.inference.pipelined import PipelinedGenerator
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.spmd import stack_stage_params

CFG = LMConfig(vocab=79, d_model=32, nhead=4, d_ff=64, n_layers=4,
               seq_len=32, dropout=0.0)


def _setup(n_stages, seed=0):
    model = PipelinedLM(CFG, n_stages)
    sp, pre, post = model.init(jax.random.key(seed))
    mesh = make_mesh(n_stages, 1)
    return model, mesh, (sp, pre, post)


@pytest.mark.parametrize("n_stages,batch,p,max_new", [
    (2, 4, 8, 6),
    (4, 4, 5, 5),
    (2, 2, 8, 1),   # max_new=1: prefill-only output
])
def test_pipelined_greedy_matches_single_device(n_stages, batch, p, max_new):
    model, mesh, (sp, pre, post) = _setup(n_stages)
    prompt = jax.random.randint(jax.random.key(1), (batch, p), 0, CFG.vocab,
                                jnp.int32)
    gen_cfg = GenerationConfig(max_new_tokens=max_new, temperature=0.0)

    ref = np.asarray(Generator(model, gen_cfg).generate((sp, pre, post),
                                                        prompt))
    pg = PipelinedGenerator(mesh, model, gen_cfg)
    got = np.asarray(pg.generate(stack_stage_params(sp), pre, post, prompt))
    np.testing.assert_array_equal(got, ref)


def test_pipelined_sampling_reproducible():
    model, mesh, (sp, pre, post) = _setup(2)
    prompt = jnp.zeros((4, 6), jnp.int32)
    pg = PipelinedGenerator(mesh, model,
                            GenerationConfig(max_new_tokens=8,
                                             temperature=0.9, top_k=12))
    a = np.asarray(pg.generate(stack_stage_params(sp), pre, post, prompt,
                               key=jax.random.key(3)))
    b = np.asarray(pg.generate(stack_stage_params(sp), pre, post, prompt,
                               key=jax.random.key(3)))
    c = np.asarray(pg.generate(stack_stage_params(sp), pre, post, prompt,
                               key=jax.random.key(4)))
    np.testing.assert_array_equal(a, b)
    assert (a != c).any()
    assert a.shape == (4, 8)
    assert (a >= 0).all() and (a < CFG.vocab).all()


def test_pipelined_batch_must_divide_into_groups():
    model, mesh, (sp, pre, post) = _setup(2)
    pg = PipelinedGenerator(mesh, model, GenerationConfig(max_new_tokens=2))
    with pytest.raises(ValueError, match="ring groups"):
        pg.generate(stack_stage_params(sp), pre, post,
                    jnp.zeros((3, 4), jnp.int32))


@pytest.mark.parametrize("n_stages,batch,p,max_new,k", [
    (2, 4, 8, 6, 3),
    (4, 4, 5, 4, 2),
    (2, 2, 8, 1, 2),   # max_new=1: beams seeded by prefill only
])
def test_pipelined_beam_matches_single_device(n_stages, batch, p, max_new,
                                              k):
    """Ring-pipelined beam search == the single-device beam, tokens AND
    scores: the parent indices riding the ring and the per-stage slab
    reorders are a layout choice, never a math choice."""
    model, mesh, (sp, pre, post) = _setup(n_stages)
    prompt = jax.random.randint(jax.random.key(1), (batch, p), 0, CFG.vocab,
                                jnp.int32)
    gen_cfg = GenerationConfig(max_new_tokens=max_new, num_beams=k)

    ref_toks, ref_sc = Generator(model, gen_cfg).generate_with_scores(
        (sp, pre, post), prompt)
    pg = PipelinedGenerator(mesh, model, gen_cfg)
    got_toks, got_sc = pg.generate_with_scores(stack_stage_params(sp), pre,
                                               post, prompt)
    np.testing.assert_array_equal(np.asarray(got_toks),
                                  np.asarray(ref_toks))
    np.testing.assert_allclose(np.asarray(got_sc), np.asarray(ref_sc),
                               rtol=1e-5, atol=1e-5)


def test_pipelined_beam_generate_routes_to_beam():
    model, mesh, (sp, pre, post) = _setup(2)
    gen_cfg = GenerationConfig(max_new_tokens=4, num_beams=2)
    prompt = jnp.zeros((2, 6), jnp.int32)
    pg = PipelinedGenerator(mesh, model, gen_cfg)
    toks = pg.generate(stack_stage_params(sp), pre, post, prompt)
    ref = Generator(model, gen_cfg).generate((sp, pre, post), prompt)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(ref))
