"""Schedules-as-data demo test: a user-AUTHORED op table drives `Pipe`.

The executor contract is a pair of numpy tables, not a class hierarchy:
anything whose ``op_tables(m, n)`` passes ``verify_op_tables`` runs on
``ScheduledPipeline`` and therefore through the ``Pipe(mesh=,
schedule=...)`` front door. This file is the documented walkthrough
(``docs/schedules.md``, "Bring your own schedule") as an executable test:

1. write the (op, microbatch) tables BY HAND as array literals;
2. prove them with ``verify_op_tables`` (and show what it rejects);
3. wrap them in a minimal ``Schedule`` subclass;
4. train through ``Pipe(mesh=, schedule=<custom>)`` and match the plain
   composition;
5. ask the phase compiler for its verdict on the hand-written table —
   the same table that interprets also phase-compiles (dense steady
   state, switch-free scan).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core import microbatch as mb
from pipe_tpu.core.schedule import (BWD, FWD, IDLE, Schedule,
                                    compile_phases, verify_op_tables)
from pipe_tpu.parallel.mesh import make_mesh

WIDTH = 8

# The hand-authored tables: 1F1B geometry at (m=4, n=2), written out as
# data. Row = cycle, column = stage. F/B/. are just ints (FWD/BWD/IDLE);
# MBI says which micro-batch each op touches (0 where idle).
F, B, _ = FWD, BWD, IDLE
OP = np.array([
    [F, _],   # c0: stage0 F0
    [F, F],   # c1: stage0 F1, stage1 F0
    [_, B],   # c2:            stage1 B0
    [B, F],   # c3: stage0 B0, stage1 F1   (B0 exactly 1 cycle after c2)
    [F, B],   # c4: stage0 F2, stage1 B1
    [B, F],   # c5: stage0 B1, stage1 F2
    [F, B],   # c6: stage0 F3, stage1 B2
    [B, F],   # c7: stage0 B2, stage1 F3
    [_, B],   # c8:            stage1 B3
    [B, _],   # c9: stage0 B3
], dtype=np.int32)
MBI = np.array([
    [0, 0], [1, 0], [0, 0], [0, 1], [2, 1],
    [1, 2], [3, 2], [2, 3], [0, 3], [3, 0],
], dtype=np.int32)
M, N = 4, 2


@dataclasses.dataclass(frozen=True)
class HandAuthoredSchedule(Schedule):
    """Step 3 of the demo: the thinnest wrapper the executor accepts —
    tables plus the stash capacity the tables imply."""
    name: str = "hand-authored-1f1b"

    def op_tables(self, m, n):
        assert (m, n) == (M, N), "this table was authored for m=4, n=2"
        return OP.copy(), MBI.copy()

    def stash_slots(self, m, n):
        return 2  # max live FWD-to-BWD activations per stage, by eye

    def bubble(self, m, n):
        op, _ = self.op_tables(m, n)
        return float((op == IDLE).mean())


def test_hand_written_table_verifies():
    verify_op_tables(OP, MBI, M, N, stash_slots=2)


def test_verifier_rejects_a_broken_edit():
    """Step 2's negative half: delay stage0's B0 by one cycle (break the
    rigid reverse ring) and the proof fails — authoring mistakes are
    caught before anything executes."""
    op, mbi = OP.copy(), MBI.copy()
    op[3, 0], op[4, 0] = IDLE, BWD      # B0 slides c3 -> c4, clobbering F2
    mbi[3, 0], mbi[4, 0] = 0, 0
    with pytest.raises(AssertionError):
        verify_op_tables(op, mbi, M, N, stash_slots=2)


def test_custom_table_through_pipe_front_door():
    """Steps 3-4: Pipe(mesh=, schedule=<custom>) trains on the authored
    table, and — since the table IS 1F1B geometry — reproduces the
    shipped '1f1b' schedule's loss and grads exactly."""
    from pipe_tpu import Linear, Pipe, Sequential

    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    y = jax.random.normal(jax.random.key(2), (8, WIDTH))

    def loss_fn(out, tgt):
        return jnp.mean((out - tgt) ** 2, axis=-1)

    out = []
    for sched in (HandAuthoredSchedule(), "1f1b"):
        seq = Sequential([Linear(WIDTH) for _ in range(4)])
        mesh = make_mesh(N, 1, devices=jax.devices()[:N])
        pipe = Pipe(seq, chunks=M, checkpoint="never", mesh=mesh,
                    schedule=sched)
        packed = pipe.shard_params(pipe.init(jax.random.key(0), x))
        out.append(pipe.loss_and_grad(packed, x, targets=y,
                                      loss_fn=loss_fn))
    (l_c, g_c), (l_ref, g_ref) = out
    np.testing.assert_array_equal(np.asarray(l_c), np.asarray(l_ref))
    for a, b in zip(jax.tree_util.tree_leaves(g_c),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_custom_table_phase_compiler_verdict():
    """Step 5: the SAME hand-written table phase-compiles — the compiler
    finds the dense F/B steady state and the scheduled executor's phased
    lowering matches the interpreted one bitwise."""
    verdict = compile_phases(OP, MBI, None, m=M, d=N, v=1)
    assert verdict.accepted, verdict.reason
    assert verdict.program.scan_cycles > 0

    from pipe_tpu.parallel.scheduled import ScheduledPipeline
    from pipe_tpu.parallel.spmd import stack_stage_params
    from pipe_tpu.ops.layers import Linear

    layer = Linear(WIDTH)
    params = [layer.init(jax.random.fold_in(jax.random.key(0), j),
                         jnp.zeros((1, WIDTH))) for j in range(N)]

    def stage_fn(p, h, ctx):
        return jnp.tanh(layer.apply(p, h))

    mesh = make_mesh(N, 1, devices=jax.devices()[:N])
    x = jax.random.normal(jax.random.key(1), (2 * M, WIDTH))
    xs, _ = mb.stack_scatter(x, M)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    out = []
    for phase in (True, False):
        pipe = ScheduledPipeline(
            mesh, stage_fn,
            pre_fn=lambda p, x_mb, ctx: x_mb,
            post_fn=lambda p, h, x_mb, ctx: jnp.sum((h - 1.0) ** 2, -1),
            checkpoint="never", schedule=HandAuthoredSchedule(),
            phase_compile=phase)
        out.append(jax.jit(pipe.loss_and_grad)(
            stack_stage_params(params), {}, {}, xs, w))
    (l_p, g_p), (l_i, g_i) = out
    np.testing.assert_array_equal(np.asarray(l_p), np.asarray(l_i))
    for a, b in zip(jax.tree_util.tree_leaves(g_p),
                    jax.tree_util.tree_leaves(g_i)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# The same walkthrough with a SPLIT backward: a hand-authored table that
# carries W (deferred weight-grad) ops. Each micro-batch runs F, then B
# (input-grad, rigid reverse ring), then W in a bubble of the author's
# choosing. This is the zero-bubble IR as data.
# ---------------------------------------------------------------------------

from pipe_tpu.core.schedule import WGRAD

W = WGRAD
OP_ZB = np.array([
    [F, _],   # c0: stage0 F0
    [F, F],   # c1: stage0 F1, stage1 F0
    [_, B],   # c2:            stage1 B0
    [B, W],   # c3: stage0 B0, stage1 W0  (W fills stage1's wait on B0's ring)
    [W, F],   # c4: stage0 W0, stage1 F1
    [_, B],   # c5:            stage1 B1
    [B, W],   # c6: stage0 B1, stage1 W1
    [W, _],   # c7: stage0 W1
], dtype=np.int32)
MBI_ZB = np.array([
    [0, 0], [1, 0], [0, 0], [0, 0],
    [0, 1], [0, 1], [1, 1], [1, 0],
], dtype=np.int32)
M_ZB = 2


@dataclasses.dataclass(frozen=True)
class HandAuthoredZBSchedule(Schedule):
    """The split-backward wrapper: same shape as above plus the two
    declarations the W ops need — ``splits_backward`` (executors shape
    the tap/cotangent carries off it) and the park capacity."""
    name: str = "hand-authored-zb"

    def op_tables(self, m, n):
        assert (m, n) == (M_ZB, N), "this table was authored for m=2, n=2"
        return OP_ZB.copy(), MBI_ZB.copy()

    def stash_slots(self, m, n):
        return 2  # both micro-batches' activations live until their W

    def wstash_slots(self, m, n):
        return 1  # at most one parked B cotangent awaits its W

    @property
    def splits_backward(self):
        return True

    def bubble(self, m, n):
        op, _ = self.op_tables(m, n)
        return float((op == IDLE).mean())


def test_hand_written_w_table_verifies():
    """The W-aware verifier accepts the authored table with exactly the
    declared stash + park capacities (the joint peak here is 3: two live
    stashes plus one parked cotangent at c3)."""
    verify_op_tables(OP_ZB, MBI_ZB, M_ZB, N, stash_slots=2,
                     wstash_slots=1)


def test_verifier_rejects_w_slid_before_its_b():
    """Negative half: pull stage0's W0 up into c2 (before its B0 at c3)
    and the dependence proof fails — W consumes B's parked cotangent."""
    op, mbi = OP_ZB.copy(), MBI_ZB.copy()
    op[2, 0], op[4, 0] = W, IDLE        # W0 slides c4 -> c2
    mbi[2, 0], mbi[4, 0] = 0, 0
    with pytest.raises(AssertionError):
        verify_op_tables(op, mbi, M_ZB, N, stash_slots=2, wstash_slots=1)


def test_custom_w_table_runs_split_executor():
    """The authored W table drives ScheduledPipeline with an auto-derived
    structural split and reproduces the fused-backward 1f1b run of the
    same params — schedules-as-data extends to the B/W split."""
    from pipe_tpu.parallel.scheduled import ScheduledPipeline
    from pipe_tpu.parallel.spmd import stack_stage_params
    from pipe_tpu.ops.layers import Linear

    layer = Linear(WIDTH)
    params = [layer.init(jax.random.fold_in(jax.random.key(0), j),
                         jnp.zeros((1, WIDTH))) for j in range(N)]

    def stage_fn(p, h, ctx):
        return jnp.tanh(layer.apply(p, h))

    mesh = make_mesh(N, 1, devices=jax.devices()[:N])
    x = jax.random.normal(jax.random.key(1), (2 * M_ZB, WIDTH))
    xs, _ = mb.stack_scatter(x, M_ZB)
    w_rows = jnp.ones(xs.shape[:2], jnp.float32)
    out = []
    for sched, split in ((HandAuthoredZBSchedule(), "auto"),
                         ("1f1b", None)):
        pipe = ScheduledPipeline(
            mesh, stage_fn,
            pre_fn=lambda p, x_mb, ctx: x_mb,
            post_fn=lambda p, h, x_mb, ctx: jnp.sum((h - 1.0) ** 2, -1),
            checkpoint="never", schedule=sched, split_stage=split)
        out.append(jax.jit(pipe.loss_and_grad)(
            stack_stage_params(params), {}, {}, xs, w_rows))
    (l_zb, g_zb), (l_ref, g_ref) = out
    np.testing.assert_allclose(np.asarray(l_zb), np.asarray(l_ref),
                               rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g_zb),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)
