"""Skip-connection subsystem tests (reference skip/ suite, SURVEY §2,4).

Covers: @skippable declaration, stash/pop through Pipe across stages,
verify_skippables failure modes, inspect_skip_layout wiring, Namespace
isolation, gradient flow through a skip, and remat compatibility (skips must
cross jax.checkpoint boundaries as explicit residuals).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core.partition import StageCtx
from pipe_tpu.extras.skip import (Namespace, SkipTracker, inspect_skip_layout,
                                  pop, skippable, stash, verify_skippables)
from pipe_tpu.ops.layers import Lambda, Linear, Module, Sequential
from pipe_tpu.pipe import Pipe


@skippable(stash=["skip"])
class StashX(Module):
    def init(self, key, *inputs):
        return {}

    def apply(self, params, x, ctx: StageCtx = StageCtx()):
        stash("skip", x)  # bare call: namespace resolves via the instance
        return x


@skippable(pop=["skip"])
class PopX(Module):
    def init(self, key, *inputs):
        return {}

    def apply(self, params, x, ctx: StageCtx = StageCtx()):
        return x + pop("skip")


def double(x):
    return x * 2.0


def build_pipe(n_stages, chunks=2, checkpoint="never"):
    """[stash, double, double, pop] split across stages: skip jumps stages."""
    module = Sequential([
        StashX(),
        Lambda(double),
        Lambda(double),
        PopX(),
    ])
    return Pipe(module, chunks=chunks, checkpoint=checkpoint,
                n_stages=n_stages)


@pytest.mark.parametrize("n_stages", [1, 2, 4])
@pytest.mark.parametrize("checkpoint", ["never", "except_last", "always"])
def test_stash_pop_through_pipe(n_stages, checkpoint):
    pipe = build_pipe(n_stages, chunks=2, checkpoint=checkpoint)
    x = jnp.arange(8.0).reshape(4, 2)
    params = pipe.init(jax.random.key(0), x)
    out = pipe(params, x, train=True, key=jax.random.key(1))
    np.testing.assert_allclose(np.asarray(out), np.asarray(x * 4 + x))


def test_gradient_through_skip():
    pipe = build_pipe(2, chunks=2)
    x = jnp.ones((4, 2))
    params = pipe.init(jax.random.key(0), x)

    g = jax.grad(lambda x: jnp.sum(pipe(params, x)))(x)
    # d/dx (4x + x) = 5
    np.testing.assert_allclose(np.asarray(g), 5.0 * np.ones((4, 2)))


def test_jit_through_skip():
    pipe = build_pipe(2, chunks=2, checkpoint="always")
    x = jnp.ones((4, 2))
    params = pipe.init(jax.random.key(0), x)

    out = jax.jit(lambda p, x: pipe(p, x, train=True,
                                    key=jax.random.key(0)))(params, x)
    np.testing.assert_allclose(np.asarray(out), 5.0 * np.ones((4, 2)))


def test_verify_pop_before_stash():
    with pytest.raises(TypeError, match="popped before"):
        verify_skippables(Sequential([PopX(), StashX()]))


def test_verify_unpopped_stash():
    with pytest.raises(TypeError, match="never popped"):
        verify_skippables(Sequential([StashX(), Lambda(double)]))


def test_verify_double_stash():
    with pytest.raises(TypeError, match="stashed twice"):
        verify_skippables(Sequential([StashX(), StashX(), PopX(), PopX()]))


def test_namespace_isolation():
    ns1, ns2 = Namespace(), Namespace()
    module = Sequential([
        StashX().isolate(ns1),
        StashX().isolate(ns2),
        PopX().isolate(ns1),
        PopX().isolate(ns2),
    ])
    verify_skippables(module)  # no mis-wiring: namespaces disambiguate
    pipe = Pipe(module, chunks=2, n_stages=2)
    x = jnp.ones((4, 2))
    params = pipe.init(jax.random.key(0), x)
    out = pipe(params, x)
    # x -> stash(ns1) -> stash(ns2) -> +pop(ns1) -> +pop(ns2)
    np.testing.assert_allclose(np.asarray(out), 3.0 * np.ones((4, 2)))


def test_inspect_skip_layout():
    pipe = build_pipe(4, chunks=1)
    layout = pipe.skip_layout
    assert layout.num_skips == 1
    # stash in stage 0 (layer 0), pop in stage 3 (layer 3)
    assert layout.requires_copy(0, 3)
    assert list(layout.copy_policy(3))[0][0] == 0
    assert layout.stashes_of(0) and layout.pops_of(3)
    assert layout.max_hop() == 3


def test_same_stage_skip_stays_local():
    pipe = build_pipe(1, chunks=2)
    assert pipe.skip_layout.num_skips == 1
    assert pipe.skip_layout.stashes_of(0) == ()  # same-stage: no export
    x = jnp.ones((4, 2))
    params = pipe.init(jax.random.key(0), x)
    np.testing.assert_allclose(np.asarray(pipe(params, x)),
                               5.0 * np.ones((4, 2)))


def test_isolate_only_keeps_other_names():
    """isolate(ns, only=[...]) moves only the listed names into ns."""
    ns = Namespace()

    @skippable(stash=["a", "b"])
    class S2(Module):
        def init(self, key, *inputs):
            return {}

        def apply(self, params, x, ctx: StageCtx = StageCtx()):
            stash("a", x)
            stash("b", 2 * x)
            return x

    iso = S2().isolate(ns, only=["a"])
    assert iso._stash_names == ("a", "b")  # names survive
    assert iso.ns_of("a") is ns
    assert iso.ns_of("b") is not ns
    assert {(n is ns, name) for n, name in iso.stashes} == {
        (True, "a"), (False, "b")}


def test_two_isolated_instances_of_one_class():
    """Namespace isolation works with bare stash/pop calls (no manual ns)."""
    ns1, ns2 = Namespace(), Namespace()
    module = Sequential([
        StashX().isolate(ns1),
        StashX().isolate(ns2),
        PopX().isolate(ns2),
        PopX().isolate(ns1),
    ])
    verify_skippables(module)
    pipe = Pipe(module, chunks=2, n_stages=4)
    x = jnp.ones((4, 2))
    params = pipe.init(jax.random.key(0), x)
    out = pipe(params, x, train=True, key=jax.random.key(1))
    np.testing.assert_allclose(np.asarray(out), 3.0 * np.ones((4, 2)))


def test_tracker_double_stash_raises():
    t = SkipTracker()
    with t.scope(0, 0):
        stash("a", jnp.ones(2))
        with pytest.raises(RuntimeError, match="stashed twice"):
            stash("a", jnp.ones(2))


def test_pop_without_stash_raises():
    t = SkipTracker()
    with t.scope(0, 0):
        with pytest.raises(RuntimeError, match="popped before stash"):
            pop("nothing")


def test_stash_outside_run_raises():
    with pytest.raises(RuntimeError, match="outside a pipeline run"):
        stash("a", jnp.ones(2))
