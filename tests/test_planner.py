"""Auto-planner proofs: every emitted plan verifies, the search is
deterministic and cap-respecting, the wall model pins to the committed
cost model, bad calibrations are refused, and ``Pipe(plan=...)``
reproduces the hand-specified config bitwise.
"""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core.balance import balance_cost, profile_times, stage_costs
from pipe_tpu.core.memplan import (MemoryPlanInputs, activation_slot_plan,
                                   estimate_memory)
from pipe_tpu.core.planner import (CalibrationError, CostProfile, Plan,
                                   predict_wall, search, uniform_profile)
from pipe_tpu.core.schedule import (InterleavedOneFOneBSchedule,
                                    compile_phases, get_schedule,
                                    verify_interleaved_op_tables,
                                    verify_op_tables)
from pipe_tpu.obs.zb_model import OpCosts, schedule_wall
from pipe_tpu.ops.layers import Linear, Sequential
from pipe_tpu.parallel.mesh import make_mesh

WIDTH = 8

ALL_SCHEDULES = ("gpipe", "1f1b", "interleaved-1f1b", "zb-h1", "zb-h2")


def _search_8x4(**kw):
    """The canonical search fixture: 8 uniform layers on 4 devices."""
    profile = uniform_profile(8, rows=4, f=1.0, layer_param_bytes=1000,
                              layer_act_bytes=500)
    kw.setdefault("schedules", ALL_SCHEDULES)
    return search(profile, n_devices=4, m_candidates=(2, 4, 8), **kw)


# ---------------------------------------------------------------------------
# the wall model pins to obs/zb_model.schedule_wall
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", ["gpipe", "1f1b", "zb-h1", "zb-h2"])
@pytest.mark.parametrize("mode", ["serialized", "parallel"])
def test_predict_wall_matches_schedule_wall(name, mode):
    """With uniform cost columns and b = 2f, the heterogeneous wall model
    IS schedule_wall — same table, same price."""
    m, n = 8, 4
    costs = OpCosts(f=0.7, sigma=1.3, o=0.05)
    op = get_schedule(name).op_tables(m, n)[0]
    want = schedule_wall(op, costs, mode)
    got = predict_wall(op, None, [0.7] * n, [1.4] * n, d=n,
                       sigma=1.3, o=0.05, mode=mode)
    assert got == pytest.approx(want, rel=1e-12)


def test_predict_wall_heterogeneous_bottleneck():
    """A stage 2x the cost doubles the per-cycle max it participates in —
    the parallel wall must strictly exceed the uniform one."""
    op = get_schedule("1f1b").op_tables(8, 4)[0]
    uni = predict_wall(op, None, [1.0] * 4, [2.0] * 4, d=4,
                       sigma=1.0, o=0.0, mode="parallel")
    het = predict_wall(op, None, [1.0, 2.0, 1.0, 1.0],
                       [2.0, 4.0, 2.0, 2.0], d=4,
                       sigma=1.0, o=0.0, mode="parallel")
    assert het > uni


# ---------------------------------------------------------------------------
# every emitted plan carries a valid, phase-compilable table
# ---------------------------------------------------------------------------


def test_every_emitted_plan_verifies():
    plans = _search_8x4()
    assert plans, "search emitted no plans"
    for p in plans:
        d = p.n_devices
        sched = (InterleavedOneFOneBSchedule(interleave=p.v) if p.v > 1
                 else get_schedule(p.schedule))
        tables = sched.op_tables(p.m, d if p.v > 1 else p.v * d)
        op, mbi = tables[0], tables[1]
        grp = tables[2] if len(tables) > 2 else None
        if p.v > 1:
            verify_interleaved_op_tables(op, mbi, grp, p.m, d, p.v)
        else:
            verify_op_tables(
                op, mbi, p.m, d, stash_slots=sched.stash_slots(p.m, d),
                wstash_slots=(sched.wstash_slots(p.m, d)
                              if sched.splits_backward else None))
        verdict = compile_phases(op, mbi, grp, m=p.m, d=d, v=p.v)
        assert verdict.accepted, (p.schedule, p.m, p.v, verdict.reason)
        assert p.phase_ok


def test_search_deterministic():
    """No RNG, no clock: a fixed profile yields the same ranked list."""
    a = [p.summary() for p in _search_8x4()]
    b = [p.summary() for p in _search_8x4()]
    assert a == b


def test_memory_cap_excludes_over_cap_candidates():
    free = _search_8x4(max_plans=32)
    peaks = sorted(p.predicted_peak_bytes for p in free)
    cap = peaks[len(peaks) // 2]        # median: some in, some out
    assert peaks[-1] > cap              # the cap actually bites
    capped = _search_8x4(max_plans=32, memory_cap_bytes=cap)
    assert capped
    assert all(p.predicted_peak_bytes <= cap for p in capped)
    over = {(p.schedule, p.m, p.v, p.split_stage) for p in free
            if p.predicted_peak_bytes > cap}
    kept = {(p.schedule, p.m, p.v, p.split_stage) for p in capped}
    assert not (over & kept)


def test_ranking_is_per_row():
    """Per-row time, not per-step: with batch scaling alongside m, a
    bigger m amortizes fill/drain and must not lose on raw step time."""
    plans = _search_8x4()
    for p in plans:
        assert p.predicted_s_per_row == pytest.approx(
            p.predicted_step_s / (p.m * 4))


# ---------------------------------------------------------------------------
# calibration refusal: residual over threshold -> loud no
# ---------------------------------------------------------------------------


def test_calibration_refused_over_residual_threshold():
    with pytest.warns(UserWarning, match="REFUSING"):
        with pytest.raises(CalibrationError):
            CostProfile(layer_fwd_s=(1.0,) * 4, layer_bwd_s=(2.0,) * 4,
                        layer_param_bytes=(0,) * 4,
                        layer_act_bytes=(0,) * 4,
                        rel_residual=0.30)


def test_calibration_accepted_under_threshold():
    p = CostProfile(layer_fwd_s=(1.0,) * 4, layer_bwd_s=(2.0,) * 4,
                    layer_param_bytes=(0,) * 4, layer_act_bytes=(0,) * 4,
                    rel_residual=0.06)
    assert p.n_layers == 4


# ---------------------------------------------------------------------------
# the Plan artifact round-trips
# ---------------------------------------------------------------------------


def test_plan_json_roundtrip(tmp_path):
    top = _search_8x4()[0]
    again = Plan.from_json(top.to_json())
    assert again == top
    path = tmp_path / "plan.json"
    top.save(str(path))
    assert Plan.load(str(path)) == top
    d = json.loads(top.to_json())
    assert d["version"] == 1
    assert d["runners_up"]          # the winner records what it beat


# ---------------------------------------------------------------------------
# Pipe(plan=...) == the hand-specified config, bitwise
# ---------------------------------------------------------------------------


def test_pipe_plan_reproduces_hand_config_bitwise():
    from pipe_tpu.pipe import Pipe

    profile = uniform_profile(4, rows=4, f=1.0, layer_param_bytes=256,
                              layer_act_bytes=32)
    plans = search(profile, n_devices=2, m_candidates=(4,),
                   schedules=("1f1b",))
    top = plans[0]
    assert (top.schedule, top.m) == ("1f1b", 4)

    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    y = jax.random.normal(jax.random.key(2), (8, WIDTH))

    def loss_fn(out, tgt):
        return jnp.mean((out - tgt) ** 2, axis=-1)

    out = []
    for kw in ({"plan": top},
               {"chunks": top.m, "checkpoint": top.checkpoint,
                "schedule": top.schedule_obj(),
                "balance": list(top.balance)}):
        seq = Sequential([Linear(WIDTH) for _ in range(4)])
        mesh = make_mesh(2, 1, devices=jax.devices()[:2])
        pipe = Pipe(seq, mesh=mesh, **kw)
        packed = pipe.shard_params(pipe.init(jax.random.key(0), x))
        out.append((packed, pipe.loss_and_grad(packed, x, targets=y,
                                               loss_fn=loss_fn)))
    (p_plan, (l_plan, g_plan)), (p_hand, (l_hand, g_hand)) = out
    for a, b in zip(jax.tree_util.tree_leaves(p_plan),
                    jax.tree_util.tree_leaves(p_hand)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(l_plan), np.asarray(l_hand))
    for a, b in zip(jax.tree_util.tree_leaves(g_plan),
                    jax.tree_util.tree_leaves(g_hand)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pipe_plan_conflicts_rejected():
    from pipe_tpu.pipe import Pipe
    top = _search_8x4()[0]
    seq = Sequential([Linear(WIDTH) for _ in range(4)])
    mesh = make_mesh(2, 1, devices=jax.devices()[:2])
    with pytest.raises(ValueError, match="plan"):
        Pipe(seq, mesh=mesh, plan=top, chunks=2)


# ---------------------------------------------------------------------------
# shared memory arithmetic: planner and executor price the same slots
# ---------------------------------------------------------------------------


def test_memplan_matches_scheduled_executor():
    from pipe_tpu.parallel.scheduled import ScheduledPipeline
    mesh = make_mesh(2, 1, devices=jax.devices()[:2])
    for schedule, split in (("1f1b", None), ("zb-h1", "auto")):
        kw = {"split_stage": split} if split else {}
        pipe = ScheduledPipeline(
            mesh, lambda p, h, ctx: h,
            pre_fn=lambda p, x_mb, ctx: x_mb,
            post_fn=lambda p, h, x_mb, ctx: jnp.sum(h, -1),
            checkpoint="never", schedule=schedule, **kw)
        got = pipe.memory_plan(4)
        sched = get_schedule(schedule)
        want = activation_slot_plan(MemoryPlanInputs(
            v=1, stash_slots=sched.stash_slots(4, 2),
            wstash_slots=(sched.wstash_slots(4, 2)
                          if sched.splits_backward else 0),
            checkpoint="never", split_stage=bool(split)))
        for k, v in want.items():
            assert got[k] == v, (schedule, k, got[k], v)
        # the dict the executor reports prices directly
        assert estimate_memory(got, act_bytes=100, param_bytes=1000) > 0


def test_estimate_memory_monotone_in_checkpoint():
    """'never' stashes every residual; 'always' stashes none — the
    estimate must order accordingly (what a pruning cap relies on)."""
    bytes_for = {
        ck: estimate_memory(
            MemoryPlanInputs(v=1, stash_slots=4, checkpoint=ck),
            act_bytes=100)
        for ck in ("never", "except_last", "always")}
    assert bytes_for["never"] > bytes_for["except_last"] \
        > bytes_for["always"]


# ---------------------------------------------------------------------------
# satellite: balance cost vector + noise-robust profiling
# ---------------------------------------------------------------------------


def test_balance_cost_vector_and_max():
    costs = [1.0, 2.0, 3.0, 4.0]
    vec = balance_cost([1, 3], costs, per_stage=True)
    assert vec == [1.0, 9.0]
    assert vec == stage_costs([1, 3], costs)
    assert balance_cost([1, 3], costs) == 9.0


def test_profile_times_median_of_k():
    seq = Sequential([Linear(WIDTH) for _ in range(3)])
    x = jnp.ones((4, WIDTH))
    params = seq.init(jax.random.key(0), x)
    times = profile_times(seq, params, x, repeat=3, warmup=1)
    assert len(times) == 3
    assert all(t > 0 for t in times)


def test_trainer_plan_auto_resolves():
    """Trainer(plan='auto'): the planner overrides schedule/chunks with a
    feasible ranked winner, and the trainer builds + initializes."""
    from pipe_tpu.models.transformer_lm import LMConfig
    from pipe_tpu.train.loop import Trainer, TrainerConfig

    model_cfg = dataclasses.replace(LMConfig().tiny(), n_layers=4)
    cfg = TrainerConfig(n_stages=2, chunks=4, checkpoint="never",
                       batch_size=8, eval_batch_size=8,
                       bptt=model_cfg.seq_len, plan="auto")
    trainer = Trainer(model_cfg, cfg)
    rc = trainer.cfg
    assert rc.plan is not None
    assert rc.schedule == rc.plan.schedule
    assert rc.chunks == rc.plan.m
    assert rc.batch_size % rc.chunks == 0
    state = trainer.init_state()
    assert state is not None
