"""Numerical parity against torch itself (CPU): the strongest checkable
evidence for the tutorial-parity claim.

The reference model is ``nn.TransformerEncoderLayer`` stacked between an
embedding Encoder and a linear Decoder (``/root/reference/main.py:139-157``).
These tests load ONE set of weights into both torch's module and this
package's :class:`~pipe_tpu.ops.layers.TransformerEncoderLayer` and assert
the outputs match to float32 tolerance — layer math, LN placement/eps,
activation, causal masking, and the full Encoder->blocks->Decoder
composition all pinned against the actual reference substrate rather than
a reimplementation of it.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax
import jax.numpy as jnp

from pipe_tpu.core.partition import StageCtx
from pipe_tpu.ops.layers import TransformerEncoderLayer

D_MODEL, NHEAD, D_FF, SEQ, BATCH = 16, 2, 32, 12, 3


def causal_mask(seq=SEQ):
    return torch.triu(torch.full((seq, seq), float("-inf")), diagonal=1)


def torch_sinusoid(seq, d):
    """The tutorial's PositionalEncoding table, built INDEPENDENTLY on the
    torch side (reference main.py's formula) so the composition test
    actually validates this package's table rather than injecting it."""
    import math
    position = torch.arange(seq).unsqueeze(1)
    div = torch.exp(torch.arange(0, d, 2) * (-math.log(10000.0) / d))
    pe = torch.zeros(seq, d)
    pe[:, 0::2] = torch.sin(position * div)
    pe[:, 1::2] = torch.cos(position * div)
    return pe


def torch_layer(seed=0, activation="relu"):
    torch.manual_seed(seed)
    return torch.nn.TransformerEncoderLayer(
        d_model=D_MODEL, nhead=NHEAD, dim_feedforward=D_FF, dropout=0.0,
        activation=activation, batch_first=True)


def params_from_torch(tl) -> dict:
    """Map torch's TransformerEncoderLayer weights onto our param pytree.

    torch packs QKV as in_proj_weight [3d, d] (y = x @ W.T + b); ours are
    separate [d, d] right-multiplied (y = x @ W + b) — so each torch block
    transposes. torch Linear weight [out, in] -> ours [in, out].
    """
    sd = {k: v.detach().numpy() for k, v in tl.state_dict().items()}
    d = D_MODEL
    wq, wk, wv = (sd["self_attn.in_proj_weight"][i * d:(i + 1) * d].T
                  for i in range(3))
    bq, bk, bv = (sd["self_attn.in_proj_bias"][i * d:(i + 1) * d]
                  for i in range(3))
    return jax.tree_util.tree_map(jnp.asarray, {
        "attn": {"wq": wq, "wk": wk, "wv": wv,
                 "wo": sd["self_attn.out_proj.weight"].T,
                 "bq": bq, "bk": bk, "bv": bv,
                 "bo": sd["self_attn.out_proj.bias"]},
        "ff1": {"w": sd["linear1.weight"].T, "b": sd["linear1.bias"]},
        "ff2": {"w": sd["linear2.weight"].T, "b": sd["linear2.bias"]},
        "ln1": {"g": sd["norm1.weight"], "b": sd["norm1.bias"]},
        "ln2": {"g": sd["norm2.weight"], "b": sd["norm2.bias"]},
    })


@pytest.mark.parametrize("activation", ["relu", "gelu"])
@pytest.mark.parametrize("causal", [False, True])
def test_encoder_layer_matches_torch(causal, activation):
    # torch's activation="gelu" is the EXACT erf form — pinned against this
    # package's "gelu" (the BERT/ViT variant; GPT-2 uses "gelu_tanh")
    tl = torch_layer(activation=activation).eval()
    params = params_from_torch(tl)
    ours = TransformerEncoderLayer(D_MODEL, NHEAD, D_FF, dropout=0.0,
                                   causal=causal, activation=activation)

    x = np.random.default_rng(1).standard_normal(
        (BATCH, SEQ, D_MODEL)).astype(np.float32)
    with torch.no_grad():
        if causal:
            exp = tl(torch.from_numpy(x), src_mask=causal_mask())
        else:
            exp = tl(torch.from_numpy(x))
    got = ours.apply(params, jnp.asarray(x), ctx=StageCtx())
    np.testing.assert_allclose(np.asarray(got), exp.numpy(),
                               rtol=2e-5, atol=2e-5)


def test_full_tutorial_composition_matches_torch():
    """Embedding*sqrt(d) -> posenc -> N layers (causal) -> decoder, both
    frameworks, one weight set (the main.py model shape at toy scale)."""
    import math

    from pipe_tpu.ops.layers import (Decoder, Embedding, PositionalEncoding)

    VOCAB, NLAYERS = 50, 2
    tls = [torch_layer(seed=i).eval() for i in range(NLAYERS)]
    layer_params = [params_from_torch(tl) for tl in tls]

    rng = np.random.default_rng(2)
    emb_w = rng.standard_normal((VOCAB, D_MODEL)).astype(np.float32)
    dec_w = rng.standard_normal((D_MODEL, VOCAB)).astype(np.float32) * 0.1
    dec_b = rng.standard_normal((VOCAB,)).astype(np.float32) * 0.1
    tokens = rng.integers(0, VOCAB, size=(BATCH, SEQ))

    # --- torch side (the reference composition, main.py:139-157; the
    # sinusoid table built independently — see torch_sinusoid) ---
    with torch.no_grad():
        h = torch.from_numpy(emb_w[tokens]) * math.sqrt(D_MODEL)
        h = h + torch_sinusoid(SEQ, D_MODEL)
        for tl in tls:
            h = tl(h, src_mask=causal_mask())
        exp = h @ torch.from_numpy(dec_w) + torch.from_numpy(dec_b)

    # --- pipe_tpu side (its OWN PositionalEncoding table) ---
    pe = PositionalEncoding(D_MODEL, 0.0)
    emb = Embedding(VOCAB, D_MODEL, scale=True)
    dec = Decoder(VOCAB)
    ours = TransformerEncoderLayer(D_MODEL, NHEAD, D_FF, dropout=0.0,
                                   causal=True)
    h = emb.apply({"table": jnp.asarray(emb_w)}, jnp.asarray(tokens))
    h = pe.apply({}, h, ctx=StageCtx())
    for p in layer_params:
        h = ours.apply(p, h, ctx=StageCtx())
    got = dec.apply({"w": jnp.asarray(dec_w), "b": jnp.asarray(dec_b)}, h)
    np.testing.assert_allclose(np.asarray(got), exp.numpy(),
                               rtol=3e-5, atol=3e-5)


def test_encoder_layer_grads_match_torch():
    """d(loss)/d(input) parity: the backward math (through softmax, LN,
    residuals) agrees with torch's autograd on the same weights."""
    tl = torch_layer().eval()
    params = params_from_torch(tl)
    ours = TransformerEncoderLayer(D_MODEL, NHEAD, D_FF, dropout=0.0,
                                   causal=True)
    x = np.random.default_rng(3).standard_normal(
        (BATCH, SEQ, D_MODEL)).astype(np.float32)

    xt = torch.from_numpy(x.copy()).requires_grad_(True)
    tl(xt, src_mask=causal_mask()).pow(2).sum().backward()
    exp = xt.grad.numpy()

    got = jax.grad(lambda a: jnp.sum(
        ours.apply(params, a, ctx=StageCtx()) ** 2))(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), exp, rtol=3e-4, atol=3e-4)
