"""Ulysses (all-to-all) sequence parallelism: exactness + PP x CP wiring.

The second SP strategy next to the K/V ring (``ops.ulysses_attention``):
head<->sequence all-to-all resharding around an unsharded attention. Bars:
bit-level-close parity with plain attention (forward AND grads) on the
virtual CPU mesh, and the context-parallel LM matching its ring variant and
the plain single-device oracle.
"""

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pipe_tpu.ops.layers import dot_product_attention
from pipe_tpu.ops.ulysses_attention import ulysses_attention
from pipe_tpu.parallel.context import (context_parallel_attention,
                                       make_context_mesh)


def qkv(key, b=2, s=32, h=4, d=8):
    kq, kk, kv = jax.random.split(key, 3)
    return (jax.random.normal(kq, (b, s, h, d)),
            jax.random.normal(kk, (b, s, h, d)),
            jax.random.normal(kv, (b, s, h, d)))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("n_context", [2, 4])
def test_ulysses_forward_parity(causal, n_context):
    q, k, v = qkv(jax.random.key(0))
    mesh = make_context_mesh(n_context)
    got = context_parallel_attention(mesh, q, k, v, causal=causal,
                                     impl="ulysses")
    exp = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                               rtol=2e-5, atol=2e-6)


def test_ulysses_matches_ring():
    q, k, v = qkv(jax.random.key(1))
    mesh = make_context_mesh(4)
    u = context_parallel_attention(mesh, q, k, v, causal=True,
                                   impl="ulysses")
    r = context_parallel_attention(mesh, q, k, v, causal=True, impl="ring")
    np.testing.assert_allclose(np.asarray(u), np.asarray(r),
                               rtol=2e-5, atol=2e-6)


def test_ulysses_gradient_parity():
    q, k, v = qkv(jax.random.key(2))
    mesh = make_context_mesh(2)

    def loss_u(q, k, v):
        return jnp.sum(context_parallel_attention(
            mesh, q, k, v, causal=True, impl="ulysses") ** 2)

    def loss_p(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    gu = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(loss_p, argnums=(0, 1, 2))(q, k, v)
    for a, e in zip(gu, gp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=2e-4, atol=2e-5)


def test_ulysses_rejects_indivisible_heads():
    # heads=3 over axis of 2 cannot split
    q = k = v = jnp.zeros((1, 8, 3, 4))
    mesh = make_context_mesh(2)
    with pytest.raises(ValueError, match="heads % axis_size"):
        context_parallel_attention(mesh, q, k, v, impl="ulysses")


def test_ulysses_bad_impl_rejected():
    q = k = v = jnp.zeros((1, 8, 2, 4))
    mesh = make_context_mesh(2)
    with pytest.raises(ValueError, match="ring|ulysses"):
        context_parallel_attention(mesh, q, k, v, impl="alltoall")


def test_pp_cp_ulysses_matches_ring_model():
    """ContextParallelLM(sp_impl='ulysses') == its ring twin AND the plain
    single-device oracle, through the full pipelined executor."""
    from test_long_context import plain_reference_loss, tiny_cfg

    from pipe_tpu.core import microbatch as mb
    from pipe_tpu.models.long_context_lm import ContextParallelLM
    from pipe_tpu.parallel.mesh import CONTEXT_AXIS, make_mesh
    from pipe_tpu.parallel.spmd import SpmdPipeline, stack_stage_params

    n_stages, n_context, chunks, seq, rows = 2, 2, 2, 32, 4
    cfg = dataclasses.replace(tiny_cfg(seq), n_layers=2)
    results = {}
    for impl in ("ring", "ulysses"):
        model = ContextParallelLM(cfg, n_stages, sp_impl=impl)
        sp, prep, postp = model.init(jax.random.key(0))
        stacked = stack_stage_params(sp)
        mesh = make_mesh(n_stages, 1, n_context=n_context)
        pipe = SpmdPipeline(mesh, model.stage_fn, pre_fn=model.pre_fn,
                            post_fn=model.loss_post_fn, post_with_batch=True,
                            context_axis=CONTEXT_AXIS)
        tokens = jax.random.randint(jax.random.key(1), (rows * chunks, seq),
                                    0, cfg.vocab, jnp.int32)
        targets = jnp.roll(tokens, -1, axis=-1)
        x, _ = mb.stack_scatter({"tokens": tokens, "targets": targets},
                                chunks)
        results[impl] = np.asarray(
            pipe(stacked, prep, postp, x)).reshape(-1)
        if impl == "ulysses":
            exp = plain_reference_loss(model, (sp, prep, postp), tokens,
                                       targets)
            np.testing.assert_allclose(results[impl], np.asarray(exp),
                                       rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(results["ulysses"], results["ring"],
                               rtol=2e-5, atol=2e-6)
