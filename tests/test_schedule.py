"""Schedule tests — the (i, j) wavefront contract of reference
``pipeline.py:63-79`` and the bubble cost model."""

import pytest

from pipe_tpu.core.schedule import (GPipeSchedule, InterleavedSchedule,
                                    OneFOneBSchedule, bubble_fraction,
                                    clock_cycles, get_schedule)


def test_clock_cycles_matches_reference():
    # m=3, n=2: [(0,0)], [(1,0),(0,1)], [(2,0),(1,1)], [(2,1)]
    got = [sorted(c) for c in clock_cycles(3, 2)]
    assert got == [[(0, 0)], [(0, 1), (1, 0)], [(1, 1), (2, 0)], [(2, 1)]]


def test_clock_cycles_counts():
    for m in (1, 2, 5, 8):
        for n in (1, 2, 4):
            cycles = list(clock_cycles(m, n))
            assert len(cycles) == m + n - 1
            tasks = [t for c in cycles for t in c]
            assert sorted(tasks) == [(i, j) for i in range(m) for j in range(n)]


def test_every_task_exactly_once_no_conflicts():
    cycles = list(clock_cycles(8, 4))
    for c in cycles:
        # within a cycle, every stage appears at most once (parallel-safe)
        stages = [j for (_, j) in c]
        assert len(stages) == len(set(stages))
        # wavefront invariant
        assert all(i + j == c[0][0] + c[0][1] for (i, j) in c)


def test_dependency_order():
    # (i, j) must run after (i, j-1) and after (i-1, j) was *dispatchable*
    seen = set()
    for c in clock_cycles(6, 3):
        for (i, j) in c:
            if j > 0:
                assert (i, j - 1) in seen
        seen.update(c)


def test_bubble_fraction():
    assert bubble_fraction(4, 2) == pytest.approx(1 / 5)
    assert bubble_fraction(8, 1) == 0.0
    s = GPipeSchedule()
    assert s.bubble(4, 2) == pytest.approx((5 * 2 - 8) / 10)


def test_get_schedule():
    assert isinstance(get_schedule("gpipe"), GPipeSchedule)
    assert isinstance(get_schedule("1f1b"), OneFOneBSchedule)
    inter = get_schedule("interleaved", v=2)
    assert isinstance(inter, InterleavedSchedule)
    assert inter.virtual_stages(4) == 8
    assert inter.device_of(5, 4) == 1
    with pytest.raises(ValueError):
        get_schedule("nope")


def test_interleaved_covers_virtual_stages():
    s = InterleavedSchedule(v=2)
    # n passed to cycles is already the TOTAL (virtual) stage count.
    cycles = s.cycles(4, 4)
    tasks = [t for c in cycles for t in c]
    assert sorted(tasks) == [(i, j) for i in range(4) for j in range(4)]
    # interleaving shrinks the per-device fill bubble ~v-fold
    assert s.device_bubble(8, 4) < GPipeSchedule().bubble(8, 4)


def test_fair_split_non_divisible():
    from pipe_tpu.core.partition import split_balance
    assert split_balance(4, 3) == [2, 1, 1]
    assert split_balance(7, 5) == [2, 2, 1, 1, 1]
    assert split_balance(9, 6) == [2, 2, 2, 1, 1, 1]


# ---------- zero-bubble (zb-h1) tables ----------

def test_zb_tables_verify_and_beat_1f1b_bubble():
    from pipe_tpu.core.schedule import (OneFOneBSchedule, ZeroBubbleSchedule,
                                        verify_zb_op_tables)
    s = ZeroBubbleSchedule()
    for m, n in [(4, 2), (8, 4), (4, 4), (16, 4), (2, 4), (8, 8), (4, 1)]:
        op, mb = s.op_tables(m, n)
        verify_zb_op_tables(op, mb, m, n, s.stash_slots(m, n),
                            s.wstash_slots(m, n))
        # 1F1B in F=B=W unit time: m*3 busy units + 3(n-1) fill/drain units
        unit_1f1b = 3 * (n - 1) / (3 * m + 3 * (n - 1))
        if n > 1:
            assert s.bubble(m, n) < unit_1f1b, (m, n)
        # memory stays 1F1B-bounded (the H1 property): stashed inputs and
        # deferred cotangents within a small constant of min(m, n)
        assert s.stash_slots(m, n) <= min(m, n + 2), (m, n)
        assert s.wstash_slots(m, n) <= min(m, n + 2), (m, n)


def test_zb_registered():
    from pipe_tpu.core.schedule import ZeroBubbleSchedule, get_schedule
    assert isinstance(get_schedule("zb-h1"), ZeroBubbleSchedule)
