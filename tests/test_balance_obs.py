"""Auto-balance (balance_by_time/size) and observability tests.

The reference only *advertises* balance_by_time (``pipe.py:42-58``); these
tests pin down the shipped implementation: profiles produce sane costs, the
bottleneck splitter is optimal on known cases, and the profiler/memory
helpers produce usable artifacts.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core.balance import (balance_by_size, balance_by_time,
                                   balance_cost, profile_sizes, profile_times,
                                   _bottleneck_split)
from pipe_tpu.core.partition import BalanceError
from pipe_tpu.obs import BubbleMeter, device_memory_report, profile_trace
from pipe_tpu.ops.layers import Lambda, Linear, Sequential
from pipe_tpu.pipe import Pipe


def test_bottleneck_split_known_optimum():
    # costs [1,1,8,1,1] into 2 stages: best bottleneck is 10 vs naive 2/3
    assert _bottleneck_split([1, 1, 8, 1, 1], 2) in ([3, 2], [2, 3])
    # uniform costs: even split
    assert _bottleneck_split([1] * 8, 4) == [2, 2, 2, 2]
    # huge first layer: it gets its own stage
    b = _bottleneck_split([100, 1, 1, 1], 2)
    assert b == [1, 3]


def test_bottleneck_split_is_optimal_vs_bruteforce():
    rng = np.random.default_rng(0)
    for _ in range(20):
        costs = rng.uniform(0.1, 10, size=7).tolist()
        got = _bottleneck_split(costs, 3)
        assert len(got) == 3 and sum(got) == 7
        best = min(
            balance_cost([i, j, 7 - i - j], costs)
            for i in range(1, 6) for j in range(1, 7 - i))
        assert balance_cost(got, costs) == pytest.approx(best, rel=1e-9)


def test_split_infeasible_raises():
    with pytest.raises(BalanceError):
        _bottleneck_split([1.0], 2)


def big_small_module():
    return Sequential([
        Linear(256), Lambda(jax.nn.relu), Linear(8), Lambda(jax.nn.relu),
        Linear(8),
    ])


def test_profile_times_orders_layers():
    module = big_small_module()
    x = jnp.zeros((16, 256))
    params = module.init(jax.random.key(0), x)
    t = profile_times(module, params, x, backward=False, repeat=2)
    assert len(t) == 5 and all(ti > 0 for ti in t)


def test_profile_sizes_reflects_params():
    module = big_small_module()
    x = jnp.zeros((16, 256))
    params = module.init(jax.random.key(0), x)
    s = profile_sizes(module, params, x)
    assert s[0] > s[2]  # 256x256 linear dwarfs 8-wide ones
    assert all(si > 0 for si in s)


def test_balance_by_size_end_to_end_with_pipe():
    module = big_small_module()
    x = jnp.zeros((16, 256))
    params = module.init(jax.random.key(0), x)
    bal = balance_by_size(2, module, params, x)
    assert sum(bal) == len(module) and len(bal) == 2
    pipe = Pipe(module, chunks=2, n_stages=2, balance=bal)
    p = pipe.init(jax.random.key(0), x)
    out = pipe(p, x)
    assert out.shape == (16, 8)


def test_balance_by_time_end_to_end():
    module = big_small_module()
    x = jnp.zeros((16, 256))
    params = module.init(jax.random.key(0), x)
    bal = balance_by_time(2, module, params, x, backward=False, repeat=1)
    assert sum(bal) == len(module) and all(b > 0 for b in bal)


def test_bubble_meter():
    m = BubbleMeter(chunks=4, n_stages=2)
    assert m.analytic == pytest.approx(1 / 5)
    assert m.measured([1.0, 1.0], 1.0) == pytest.approx(0.0)
    assert m.measured([0.5, 0.5], 1.0) == pytest.approx(0.5)
    assert "analytic=20.00%" in m.report()


def test_profile_trace_writes(tmp_path):
    logdir = str(tmp_path / "trace")
    with profile_trace(logdir):
        jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    found = []
    for root, _, files in os.walk(logdir):
        found.extend(files)
    assert found, "no trace files written"


def test_device_memory_report():
    r = device_memory_report()
    assert "device memory profile" in r


def test_measured_bubble_slope():
    from pipe_tpu.obs.meters import measured_bubble_slope

    # ideal pipeline: t(m) = a*(m+n-1) -> slope recovers analytic bubble
    a, m, n = 0.01, 8, 4
    t_m, t_2m = a * (m + n - 1), a * (2 * m + n - 1)
    assert measured_bubble_slope(t_m, t_2m, m) == pytest.approx(
        (n - 1) / (m + n - 1))
    # pure constant overhead, no per-cycle cost -> bubble 1
    assert measured_bubble_slope(1.0, 1.0, m) == pytest.approx(1.0)
    # degenerate inputs
    assert measured_bubble_slope(0.0, 1.0, m) == 0.0
    # n=1, zero overhead: t scales linearly with m -> bubble 0
    assert measured_bubble_slope(0.08, 0.16, 8) == pytest.approx(0.0)


def test_merge_busy_ns_unions_overlaps():
    from pipe_tpu.obs.meters import _merge_busy_ns

    assert _merge_busy_ns([]) == 0.0
    assert _merge_busy_ns([(0.0, 10.0), (5.0, 15.0)]) == pytest.approx(15.0)
    assert _merge_busy_ns([(20.0, 30.0), (0.0, 10.0)]) == pytest.approx(20.0)
    assert _merge_busy_ns([(0.0, 5.0), (1.0, 2.0)]) == pytest.approx(5.0)


def test_stage_busy_from_trace_cpu(tmp_path):
    """On the virtual CPU platform there are no /device: planes; the parser
    must return cleanly with just the span key (slope method is the CPU
    fallback)."""
    from pipe_tpu.obs.meters import stage_busy_from_trace

    logdir = str(tmp_path / "trace")
    with profile_trace(logdir):
        jax.block_until_ready(jnp.ones((64, 64)) @ jnp.ones((64, 64)))
    busy = stage_busy_from_trace(logdir)
    assert "_span" in busy
    for k, v in busy.items():
        assert v >= 0.0


def test_balance_by_size_drives_pipe_mesh():
    """Measured auto-balance feeding the compiled mesh executor — the
    composition the reference only advertises (pipe.py:42-58) and never
    shipped, here end-to-end on the multi-device path."""
    from pipe_tpu.parallel.mesh import make_mesh

    module = big_small_module()
    x = jnp.zeros((16, 256))
    params = module.init(jax.random.key(0), x)
    bal = balance_by_size(2, module, params, x)
    mesh = make_mesh(2, 1, devices=jax.devices()[:2])
    pipe = Pipe(module, chunks=2, mesh=mesh, balance=bal)
    emu = Pipe(module, chunks=2, n_stages=2, balance=bal)
    p = pipe.init(jax.random.key(0), x)
    xr = jax.random.normal(jax.random.key(1), (16, 256))
    np.testing.assert_allclose(np.asarray(pipe(p, xr)),
                               np.asarray(emu(p, xr)),
                               rtol=1e-5, atol=1e-5)
