"""KV cache generation 2: the radix tree, block eviction/offload, and
fleet-wide KV-aware placement.

Three layers under test, mirroring the pool's own split:

* **host allocator laws** (no device programs): the radix trie's
  insert/match/split-on-divergence structure, the refcount lifecycle
  across COW fork + retire, the eviction law (a node with resident
  descendants is never freed — leaf-first, oldest-first), and the host
  offload round trip (spill under pressure, restore on re-reference,
  payloads bitwise).
* **engine drills**: offload→restore through real prefill/decode stays
  bitwise the one-shot Generator (fp32 greedy AND sampled) and
  run-identical for int8; the admission loop skips a blocked head for a
  smaller admissible request without reordering priorities.
* **fleet**: placement scores replicas by matched prefix depth ×
  occupancy headroom, and hot prefixes replicate to a sibling ahead of
  demand through the PR 13 export/import path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.inference import GenerationConfig, Generator
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
from pipe_tpu.obs.telemetry import get_registry
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.spmd import stack_stage_params
from pipe_tpu.serve import (KvPool, RequestQueue, Router, RouterPolicy,
                            ServeEngine, SingleDeviceSlotBackend)
from pipe_tpu.serve.kvpool import (HostKvStore, prefix_hashes,
                                   prefix_match_depth)
from pipe_tpu.serve.ring import RingSlotBackend

CFG = LMConfig(vocab=89, d_model=32, nhead=4, d_ff=64, n_layers=4,
               seq_len=32, dropout=0.0)


@pytest.fixture(scope="module")
def model_and_params():
    model = PipelinedLM(CFG, n_stages=2)
    return model, model.init(jax.random.key(0))


def _one_shot_refs(model, params, prompts, gen_cfg, seed):
    g = Generator(model, gen_cfg)
    return [np.asarray(g.generate(params,
                                  jnp.asarray(p, jnp.int32)[None],
                                  jax.random.key(seed)))[0]
            for p in prompts]


def _mixed_prompts(lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [list(rng.randint(1, CFG.vocab, size=n)) for n in lengths]


def _pool(**kw):
    kw.setdefault("num_blocks", 9)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 16)
    return KvPool(**kw)


def _conserved(pool):
    s = pool.stats()
    return (s["blocks_free"] + s["blocks_in_use"] + s["blocks_evictable"]
            == s["blocks_total"])


def _fake_payload(bid):
    # deterministic per-physical-block content, two dtypes so the
    # bitwise round-trip check covers fp32 and int8 storage at once
    rng = np.random.RandomState(bid)
    return {"k": rng.randn(2, 4, 8).astype(np.float32),
            "scale": np.full((2, 4), float(bid), np.float32),
            "codes": rng.randint(-128, 128, (2, 4, 8)).astype(np.int8)}


# ---------------------------------------------------------------------------
# radix laws (host only)


def test_prefix_hash_chain_and_match_depth():
    # rolling chain: digest i commits to blocks 0..i
    h = prefix_hashes(list(range(1, 13)), 4)
    assert len(h) == 3 and len(set(h)) == 3
    bent = list(range(1, 13))
    bent[0] = 77                       # perturb block 0 -> every digest
    assert all(a != b for a, b in zip(h, prefix_hashes(bent, 4)))
    bent2 = list(range(1, 13))
    bent2[5] = 77                      # perturb block 1 -> digests 1, 2
    h2 = prefix_hashes(bent2, 4)
    assert h2[0] == h[0] and h2[1] != h[1] and h2[2] != h[2]
    # match depth stops at the first non-resident digest
    assert prefix_match_depth(h, set(h)) == 3
    assert prefix_match_depth(h, {h[0], h[2]}) == 1
    assert prefix_match_depth(h, set()) == 0


def test_radix_insert_match_and_split_on_divergence():
    pool = _pool(num_blocks=17, max_len=32, num_slots=3)
    a = _mixed_prompts((16,), seed=1)[0]          # 4 full blocks
    pool.admit(0, a, 1, chunk=4)
    pool.release(0)
    # one path-compressed run holds the whole chain
    assert pool._radix_node_count() == 1
    assert pool.stats()["radix_nodes"] == 1
    b = a[:8] + _mixed_prompts((8,), seed=2)[0]   # diverge after block 2
    adm = pool.admit(0, b, 1, chunk=4)
    assert adm.prefix_hits == 2                   # radix partial match
    pool.release(0)
    # split on divergence: [a0,a1] -> {[a2,a3], [b2,b3]}
    assert pool._radix_node_count() == 3
    ha, hb = pool.prefix_hashes(a), pool.prefix_hashes(b)
    assert ha[:2] == hb[:2] and ha[2] != hb[2]
    node, pos = pool._node_of[ha[1]]
    assert pos == len(node.run) - 1 and len(node.children) == 2
    # the directory advertises every digest on both arms
    d = pool.prefix_digest_summary()
    assert set(d["digests"]) == set(ha) | set(hb)
    assert d["block_size"] == 4
    assert _conserved(pool)


def test_refcount_lifecycle_across_fork_and_retire():
    pool = _pool(num_blocks=17, max_len=32, num_slots=3)
    shared = _mixed_prompts((8,), seed=3)[0]      # 2 full blocks
    ha = pool.prefix_hashes(shared)
    pool.admit(0, shared + [7, 9], 2, chunk=4)
    assert [pool._cached[h].refs for h in ha] == [1, 1]
    pool.admit(1, shared + [11], 2, chunk=4)      # read-only share
    assert [pool._cached[h].refs for h in ha] == [2, 2]
    pool.release(0)
    assert [pool._cached[h].refs for h in ha] == [1, 1]
    pool.release(1)
    assert [pool._cached[h].refs for h in ha] == [0, 0]
    assert pool.evictable_blocks >= 2             # refs-0 -> LRU
    # full-hit fork: the source entry is NOT re-referenced (the fork is
    # a private copy) and survives the fork's retirement untouched
    adm = pool.admit(2, shared, 2, chunk=4)
    assert len(adm.cow_forks) == 1
    assert pool._cached[ha[0]].refs == 1          # block 1 shared again
    assert pool._cached[ha[1]].refs == 0          # block 2 fork source
    pool.release(2)
    assert [pool._cached[h].refs for h in ha] == [0, 0]
    assert _conserved(pool)


def test_eviction_never_frees_a_node_with_resident_descendants():
    reg = get_registry()
    pool = _pool(num_blocks=6, num_slots=2)       # 5 allocatable
    p1 = _mixed_prompts((12,), seed=4)[0]         # 3 cached blocks
    pool.admit(0, p1, 1, chunk=4)
    pool.release(0)
    assert pool.evictable_blocks == 3 and pool.free_blocks == 2
    # 3-block demand against 2 free: ONE eviction — and although the
    # chain head is the OLDEST entry on the clock, the leaf goes first
    # (evicting h0 would strand h1/h2: their digests chain through it)
    e0 = reg.counter("serve.kv.evictions").value
    pool.admit(1, _mixed_prompts((9,), seed=5)[0], 4, chunk=4)
    assert reg.counter("serve.kv.evictions").value - e0 == 1
    assert pool.cached_prefix_blocks(p1) == 2     # h0, h1 intact
    pool.release(1, )
    assert _conserved(pool)


# ---------------------------------------------------------------------------
# host offload (pool level, fake device reader)


def test_offload_spill_and_restore_roundtrip_bitwise():
    reg = get_registry()
    pool = _pool(num_blocks=6, num_slots=2)       # 5 allocatable
    store = HostKvStore()
    pool.attach_offload(store, _fake_payload)
    p1 = _mixed_prompts((12,), seed=6)[0]
    pool.admit(0, p1, 1, chunk=4)
    leaf_block = pool._cached[pool.prefix_hashes(p1)[2]].block
    pool.release(0)
    o0 = reg.counter("serve.kv.offload_out").value
    r0 = reg.counter("serve.kv.offload_restores").value
    pool.admit(1, _mixed_prompts((9,), seed=7)[0], 4, chunk=4)
    # pressure spilled the leaf to host instead of dropping it
    assert reg.counter("serve.kv.offload_out").value - o0 == 1
    assert pool.offloaded_blocks == 1
    assert pool.cached_prefix_blocks(p1) == 3     # offloaded still hits
    assert pool.stats()["blocks_offloaded"] == 1
    assert pool.stats()["host_kv_bytes"] == store.nbytes
    pool.release(1)
    pool.invalidate(pool.prefix_hashes(
        _mixed_prompts((9,), seed=7)[0]))         # make room
    # a LONGER re-admission reuses the offloaded leaf read-only:
    # restored onto a fresh device block with the EXACT bytes that were
    # spilled (fp32 and int8 alike) — an identical-length prompt would
    # instead fork it (recompute tail) and leave the original on host
    adm = pool.admit(0, p1 + _mixed_prompts((4,), seed=10)[0], 1,
                     chunk=4)
    assert reg.counter("serve.kv.offload_restores").value - r0 == 1
    assert len(adm.restores) >= 1
    want = _fake_payload(leaf_block)
    _, payload = adm.restores[0]
    for name in want:
        np.testing.assert_array_equal(payload[name], want[name])
        assert payload[name].dtype == want[name].dtype
    assert pool.offloaded_blocks == 0             # resident again
    pool.release(0)
    assert _conserved(pool)


def test_host_store_caps_age_out_oldest():
    store = HostKvStore(max_blocks=2)
    pay = _fake_payload(1)
    assert store.put("a", pay) == []
    assert store.put("b", pay) == []
    assert store.put("c", pay) == ["a"]           # oldest ages out
    assert "a" not in store and "b" in store and len(store) == 2
    # a byte cap smaller than one payload rejects the put itself
    tiny = HostKvStore(max_bytes=8)
    assert "x" in tiny.put("x", pay)
    assert "x" not in tiny
    # pop removes (restore-for-reuse), get keeps (fork of offloaded)
    assert store.get("b") is pay and "b" in store
    assert store.pop("b") is pay and "b" not in store


def test_pool_survives_store_dropping_its_own_put():
    reg = get_registry()
    pool = _pool(num_blocks=6, num_slots=2)
    pool.attach_offload(HostKvStore(max_bytes=8), _fake_payload)
    p1 = _mixed_prompts((12,), seed=8)[0]
    pool.admit(0, p1, 1, chunk=4)
    pool.release(0)
    d0 = reg.counter("serve.kv.offload_dropped").value
    pool.admit(1, _mixed_prompts((9,), seed=9)[0], 4, chunk=4)
    # the payload never fit: hard eviction, counted, no phantom entry
    assert reg.counter("serve.kv.offload_dropped").value - d0 == 1
    assert pool.offloaded_blocks == 0
    assert pool.cached_prefix_blocks(p1) == 2
    pool.release(1)
    assert _conserved(pool)


# ---------------------------------------------------------------------------
# engine drills


def _offload_workload():
    shared = _mixed_prompts((8,), seed=21)[0]     # 2 cacheable blocks
    filler = _mixed_prompts((12,), seed=22)[0]    # evicts them
    return [shared + [3, 5], filler, shared + [7, 9]]


@pytest.mark.parametrize("gen_kw", [
    dict(temperature=0.0),
    dict(temperature=0.8, top_k=12),
], ids=["greedy", "sampled"])
def test_engine_offload_restore_bitwise_fp32(gen_kw, model_and_params):
    """Spill mid-run, restore on re-reference: tokens stay bitwise the
    one-shot Generator, greedy and sampled."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=4, **gen_kw)
    prompts = _offload_workload()
    refs = _one_shot_refs(model, params, prompts, gen_cfg, seed=6)
    backend = SingleDeviceSlotBackend(
        model, params, num_slots=2, max_len=16, gen=gen_cfg,
        kv_block_size=4, prefill_chunk=4, kv_pool_blocks=6,
        kv_offload=True)
    reg = get_registry()
    o0 = reg.counter("serve.kv.offload_out").value
    r0 = reg.counter("serve.kv.offload_restores").value
    eng = ServeEngine(backend)
    resps = []
    for p in prompts:                 # serial: force evict-then-restore
        rid = eng.submit(p, seed=6).id
        eng.run_until_idle()
        resps.append(eng.response(rid))
    assert reg.counter("serve.kv.offload_out").value - o0 > 0
    assert reg.counter("serve.kv.offload_restores").value - r0 > 0
    for resp, ref in zip(resps, refs):
        assert resp.status == "ok"
        np.testing.assert_array_equal(np.asarray(resp.tokens), ref)


def test_engine_offload_restore_run_identical_int8(model_and_params):
    """int8 KV payloads spill as raw codes+scales, so an offloaded run
    is token-identical to an unpressured one — the round trip never
    requantizes."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=4, temperature=0.0)
    prompts = _offload_workload()

    def run(pool_blocks, offload):
        be = SingleDeviceSlotBackend(
            model, params, num_slots=2, max_len=16, gen=gen_cfg,
            kv_block_size=4, prefill_chunk=4, kv_pool_blocks=pool_blocks,
            kv_dtype="int8", kv_offload=offload)
        eng = ServeEngine(be)
        out = []
        for p in prompts:
            rid = eng.submit(p, seed=0).id
            eng.run_until_idle()
            out.append(np.asarray(eng.response(rid).tokens))
        return out

    reg = get_registry()
    o0 = reg.counter("serve.kv.offload_out").value
    want = run(32, False)             # roomy: nothing evicts
    assert reg.counter("serve.kv.offload_out").value == o0
    got = run(6, True)                # tight: spill + restore
    assert reg.counter("serve.kv.offload_out").value - o0 > 0
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)


def test_offload_requires_paged_and_single_device(model_and_params):
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=4)
    with pytest.raises(ValueError, match="paged"):
        SingleDeviceSlotBackend(model, params, num_slots=2, max_len=16,
                                gen=gen_cfg, kv_offload=True)
    sp, pre, post = params
    with pytest.raises(NotImplementedError, match="single-device"):
        RingSlotBackend(make_mesh(2, 1), model, stack_stage_params(sp),
                        pre, post, max_len=16, gen=gen_cfg,
                        kv_block_size=4, kv_offload=True)


def test_kv_headroom_validation_names_the_waste(model_and_params):
    gen = GenerationConfig(max_new_tokens=6)
    gen.check_kv_headroom(18, 8)      # 24 rows: divides, fine
    with pytest.raises(ValueError) as ei:
        gen.check_kv_headroom(16, 8)  # 22 rows: 2 wasted of 8
    msg = str(ei.value)
    assert "does not divide" in msg and "waste 2 of 8 rows" in msg
    # the backend runs the same check against its bucket ceiling
    from pipe_tpu.serve import BucketSpec
    model, params = model_and_params
    with pytest.raises(ValueError, match="does not divide"):
        SingleDeviceSlotBackend(
            model, params, num_slots=2, max_len=24, gen=gen,
            buckets=BucketSpec.of(16), kv_block_size=8, prefill_chunk=4)


def test_admission_skips_blocked_head_for_smaller_request(
        model_and_params):
    """Head-of-line fix: a head too big for the current pool parks (the
    PR 11 containment pin) but a smaller admissible request behind it
    is admitted past it, counted by serve.engine.admission_skipped —
    and everyone's tokens stay bitwise."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=6, temperature=0.0)
    big_a, big_b = _mixed_prompts((5, 6), seed=31)      # 3 blocks each
    small = _mixed_prompts((4,), seed=32)[0]            # 4+6-1 rows? no:
    refs = _one_shot_refs(model, params, [big_a, big_b, small],
                          gen_cfg, seed=2)
    # 5 allocatable: big_a (3 blocks) leaves 2 free — big_b blocks at
    # the head, small (plen 4 + 2 new - 1 -> 2 blocks) fits
    backend = SingleDeviceSlotBackend(
        model, params, num_slots=2, max_len=16, gen=gen_cfg,
        kv_block_size=4, prefill_chunk=4, kv_pool_blocks=6)
    reg = get_registry()
    s0 = reg.counter("serve.engine.admission_skipped").value
    b0 = reg.counter("serve.kv.admission_blocked").value
    eng = ServeEngine(backend)
    ra = eng.submit(big_a, seed=2).id
    eng.tick()                                          # big_a live
    rb = eng.submit(big_b, seed=2).id
    rs = eng.submit(small, max_new_tokens=2, seed=2).id
    eng.tick()
    assert reg.counter("serve.kv.admission_blocked").value - b0 >= 1
    assert reg.counter("serve.engine.admission_skipped").value - s0 == 1
    # small got past the parked head; big_b still waits at it
    assert eng.response(rb) is None and eng.queue.depth == 1
    eng.run_until_idle()
    for rid, ref, n in ((ra, refs[0], None), (rb, refs[1], None),
                        (rs, refs[2], 2)):
        resp = eng.response(rid)
        assert resp.status == "ok"
        want = ref if n is None else ref[:len(big_a) - 1 + n]
        got = np.asarray(resp.tokens)
        np.testing.assert_array_equal(got, want[:len(got)])


def test_admission_skip_respects_priority_order(model_and_params):
    """With a priority queue the skip scan walks candidates in pop
    order: a blocked high-priority head is bypassed by the HIGHEST
    priority admissible request, never an arbitrary one."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=6, temperature=0.0)
    big = _mixed_prompts((6,), seed=33)[0]
    lo, hi = _mixed_prompts((4, 4), seed=34)
    backend = SingleDeviceSlotBackend(
        model, params, num_slots=2, max_len=16, gen=gen_cfg,
        kv_block_size=4, prefill_chunk=4, kv_pool_blocks=6)
    eng = ServeEngine(backend, RequestQueue(policy="priority"))
    filler = _mixed_prompts((5,), seed=35)[0]
    eng.submit(filler, seed=0, priority=9)
    eng.tick()                                          # 3 blocks live
    eng.submit(big, seed=0, priority=8)                 # head: blocked
    rl = eng.submit(lo, max_new_tokens=2, seed=0, priority=1).id
    rh = eng.submit(hi, max_new_tokens=2, seed=0, priority=5).id
    eng.tick()
    # the priority-5 bypasser got the slot; priority-1 still waits
    # behind the parked head
    assert eng.response(rl) is None
    assert eng.queue.depth == 2
    eng.run_until_idle()
    assert eng.response(rh).status == "ok"
    assert eng.response(rl).status == "ok"


# ---------------------------------------------------------------------------
# fleet: prefix-aware placement + proactive replication


def _fleet(model, params, gen_cfg, policy, n=2):
    engines = [ServeEngine(SingleDeviceSlotBackend(
        model, params, num_slots=2, max_len=16, gen=gen_cfg,
        kv_block_size=4, prefill_chunk=4))
        for _ in range(n)]
    return engines, Router(engines, RequestQueue(), policy=policy)


def test_prefix_placement_lands_where_the_prefix_lives(model_and_params):
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=4, temperature=0.0)
    shared = _mixed_prompts((8,), seed=41)[0]
    engines, router = _fleet(model, params, gen_cfg,
                             RouterPolicy(placement="prefix"))
    # warm replica 1 out of band — least-loaded would now pick 0
    engines[1].submit(shared + [3], seed=0)
    engines[1].run_until_idle()
    reg = get_registry()
    p0 = reg.counter("serve.fleet.prefix_placements").value
    rid = router.submit(shared + [5, 6], max_new_tokens=4, seed=0).id
    for _ in range(50):
        router.tick()
        if router.response(rid) is not None:
            break
    assert router.response(rid).status == "ok"
    assert reg.counter("serve.fleet.prefix_placements").value - p0 == 1
    # replica 0 never saw it: its pool cached nothing
    assert not engines[0].backend.pool._cached
    assert engines[1].backend.pool.cached_prefix_blocks(shared) == 2


def test_hot_prefix_replicates_to_sibling(model_and_params):
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=6, temperature=0.0)
    shared = _mixed_prompts((8,), seed=43)[0]
    engines, router = _fleet(
        model, params, gen_cfg,
        RouterPolicy(placement="prefix", kv_hot_refs=2))
    reg = get_registry()
    k0 = reg.counter("serve.fleet.kv_replicated").value
    ra = router.submit(shared + [3], max_new_tokens=6, seed=0).id
    router.tick()                       # lands replica 0, publishes
    rb = router.submit(shared + [5], max_new_tokens=6, seed=0).id
    done = []
    for _ in range(60):
        router.tick()
        done = [router.response(r) for r in (ra, rb)]
        if all(d is not None for d in done):
            break
    assert all(d.status == "ok" for d in done)
    # both rode replica 0 (prefix score beat least-loaded), refs hit 2,
    # and the controller shipped the hot chain to the idle sibling
    assert reg.counter("serve.fleet.kv_replicated").value - k0 == 2
    assert engines[1].backend.pool.cached_prefix_blocks(shared) == 2
    assert not router._parked


def test_router_policy_validates_gen2_knobs():
    RouterPolicy(placement="prefix", kv_hot_refs=2)
    with pytest.raises(ValueError, match="least_loaded|session|prefix"):
        RouterPolicy(placement="hash")
    with pytest.raises(ValueError, match="not hot"):
        RouterPolicy(kv_hot_refs=1)
    with pytest.raises(ValueError):
        RouterPolicy(kv_replicate_max_per_tick=0)
