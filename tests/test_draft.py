"""Draft-source unit contracts (PR 18): the interface the spec lane
trusts without tracing a model.

* ``tree_layout`` — the static flattened-tree geometry the causal tree
  mask is built from: depths, disjoint branches, ancestor-or-self
  closure.
* ``resolve_draft`` — flag-level construction rejects impossible combos
  loudly (never a silent fallback); ngram short-circuits the prefix
  constraint entirely (it has no model half).
* ``draft_cost_frac`` — the (rows x layers) cost model feeding the
  planner's breakeven: zero for ngram, the strict-prefix ratio for
  truncated/tree, always in [0, 1).
* planner ``spec_speedup`` / ``spec_breakeven_acceptance`` — the
  analytic model is self-consistent: speedup AT the breakeven
  acceptance is exactly 1.0, and the knobs move it the right way.
"""

import numpy as np
import pytest

from pipe_tpu.core.planner import spec_breakeven_acceptance, spec_speedup
from pipe_tpu.inference.draft import (NgramDraft, TreeDraft,
                                      TruncatedDraft, resolve_draft,
                                      tree_layout)


def test_tree_layout_geometry():
    K, B = 4, 3
    depths, anc = tree_layout(K, B)
    Q = 1 + B * (K - 1)
    assert depths.shape == (Q,) and anc.shape == (Q, Q)
    assert depths[0] == 0
    # branch b occupies rows [1 + b*(K-1), 1 + (b+1)*(K-1)) at depths
    # 1..K-1; every row sees the root and its own prefix, nothing else
    for b in range(B):
        base = 1 + b * (K - 1)
        np.testing.assert_array_equal(depths[base:base + K - 1],
                                      np.arange(1, K))
        for i in range(K - 1):
            r = base + i
            expect = {0, *range(base, base + i + 1)}
            assert set(np.nonzero(anc[r])[0]) == expect
    # ancestor-or-self is reflexive and respects depth ordering
    assert all(anc[j, j] for j in range(Q))
    assert all(depths[r] <= depths[j]
               for j in range(Q) for r in np.nonzero(anc[j])[0])


def test_resolve_draft_combos():
    # ngram has no model half: the prefix constraint never applies
    assert isinstance(
        resolve_draft("ngram", n_stages=1, layers_per_stage=4,
                      draft_stages=99), NgramDraft)
    d = resolve_draft("truncated", n_stages=4, layers_per_stage=2,
                      draft_stages=3)
    assert isinstance(d, TruncatedDraft) and d.draft_layers == 6
    t = resolve_draft("tree", n_stages=2, layers_per_stage=2,
                      spec_branches=3)
    assert isinstance(t, TreeDraft)
    assert t.branches == 3 and t.draft_layers == 2

    with pytest.raises(ValueError, match="STRICT prefix"):
        resolve_draft("truncated", n_stages=2, layers_per_stage=2,
                      draft_stages=2)
    with pytest.raises(ValueError, match="STRICT prefix"):
        resolve_draft("truncated", n_stages=1, layers_per_stage=4)
    with pytest.raises(ValueError, match="STRICT prefix"):
        resolve_draft("tree", n_stages=2, layers_per_stage=2,
                      draft_stages=0, spec_branches=2)
    with pytest.raises(ValueError, match="spec_branches"):
        resolve_draft("tree", n_stages=2, layers_per_stage=2)
    with pytest.raises(ValueError, match="spec_branches"):
        resolve_draft("tree", n_stages=2, layers_per_stage=2,
                      spec_branches=1)
    with pytest.raises(ValueError, match="unknown draft source"):
        resolve_draft("medusa", n_stages=2, layers_per_stage=2)
    with pytest.raises(ValueError, match="branches"):
        TreeDraft(1, 2)
    with pytest.raises(ValueError, match="draft layer"):
        TruncatedDraft(0)


def test_draft_cost_model():
    assert NgramDraft().draft_cost_frac(4, 16) == 0.0
    # truncated: (K-1)*Ld draft row-layers vs K*L verify row-layers
    K, L = 3, 4
    assert TruncatedDraft(1).draft_cost_frac(K, L) == \
        pytest.approx(2 / (2 + 12))
    # deeper prefix costs more, never reaching 1
    fracs = [TruncatedDraft(ld).draft_cost_frac(4, 16)
             for ld in (1, 4, 8, 15)]
    assert fracs == sorted(fracs) and all(0 < f < 1 for f in fracs)
    # tree: 1 shared root step + B*(K-2) branch steps of Ld layers,
    # verified in a Q-row chunk
    B, Ld = 2, 2
    steps, Q = 1 + B * (K - 2), 1 + B * (K - 1)
    assert TreeDraft(B, Ld).draft_cost_frac(K, L) == \
        pytest.approx(steps * Ld / (steps * Ld + Q * L))
    # K=2 tree: the shared root step is the whole draft
    assert TreeDraft(3, 2).draft_cost_frac(2, 4) == \
        pytest.approx(2 / (2 + 4 * 4))


def test_spec_model_self_consistent():
    for f, K, r in [(0.0, 2, 1.0), (0.25, 3, 1.0), (0.25, 4, 1.6),
                    (0.6, 8, 2.5)]:
        a_star = spec_breakeven_acceptance(f, K, r)
        if 0.0 < a_star < 1.0:
            assert spec_speedup(a_star, f, K, r) == pytest.approx(1.0)
        # speedup is monotone in acceptance
        assert spec_speedup(1.0, f, K, r) >= spec_speedup(0.0, f, K, r)
    # free draft, memory-bound chunk (ratio 1): any acceptance >= 0 wins
    assert spec_breakeven_acceptance(0.0, 4, 1.0) == 0.0
    assert spec_speedup(0.0, 0.0, 4, 1.0) == pytest.approx(1.0)
    # an expensive draft under a FLOP-bound chunk can never pay off
    assert spec_breakeven_acceptance(0.9, 2, 2.0) == 1.0
    # knob directions: deeper K needs less acceptance per token won;
    # a costlier draft needs more
    assert spec_breakeven_acceptance(0.25, 8) < \
        spec_breakeven_acceptance(0.25, 2)
    assert spec_breakeven_acceptance(0.5, 4) > \
        spec_breakeven_acceptance(0.1, 4)
    with pytest.raises(ValueError, match="K >= 2"):
        spec_speedup(0.5, 0.1, 1)
    with pytest.raises(ValueError, match="acceptance"):
        spec_speedup(1.5, 0.1, 4)
    with pytest.raises(ValueError, match="draft_cost_frac"):
        spec_breakeven_acceptance(1.0, 4)
    with pytest.raises(ValueError, match="chunk_cost_ratio"):
        spec_breakeven_acceptance(0.1, 4, 0.0)
