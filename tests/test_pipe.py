"""Pipe API + transparency tests.

Transparency is THE correctness property of the whole design (upstream
``test_transparency`` per SURVEY §4): micro-batching + pipeline scheduling +
activation checkpointing must produce the identical result (and gradients) as
the plain unpipelined model, up to dtype tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import pipe_tpu
from pipe_tpu import (Dropout, Lambda, Linear, NoChunk, Pipe, Sequential,
                      StageCtx)


def make_mlp(key, depth=4, width=8):
    layers = [Linear(width) for _ in range(depth)]
    seq = Sequential(layers)
    params = seq.init(key, jnp.zeros((2, width)))
    return seq, params


# ---------- validation parity (reference pipe.py:324-345) ----------

def test_chunks_less_than_1():
    seq, _ = make_mlp(jax.random.key(0))
    with pytest.raises(ValueError):
        Pipe(seq, chunks=0)
    with pytest.raises(ValueError):
        Pipe(seq, chunks=-1)


def test_chunks_not_int():
    seq, _ = make_mlp(jax.random.key(0))
    with pytest.raises(TypeError):
        Pipe(seq, chunks=2.5)


def test_bad_checkpoint_mode():
    seq, _ = make_mlp(jax.random.key(0))
    with pytest.raises(ValueError):
        Pipe(seq, chunks=2, checkpoint="sometimes")


def test_module_must_be_sequential():
    with pytest.raises(TypeError):
        Pipe([Linear(4)], chunks=1)


def test_duplicate_children_rejected():
    layer = Linear(8)
    with pytest.raises(ValueError):
        Pipe(Sequential([layer, layer]), chunks=1)


def test_balance_errors():
    seq, _ = make_mlp(jax.random.key(0), depth=4)
    with pytest.raises(pipe_tpu.BalanceError):
        Pipe(seq, chunks=1, balance=[1, 1])  # doesn't sum to 4
    with pytest.raises(pipe_tpu.BalanceError):
        Pipe(seq, chunks=1, n_stages=5)  # more stages than layers


# ---------- container protocol (reference pipe.py:358-386) ----------

def test_container_protocol():
    seq, _ = make_mlp(jax.random.key(0), depth=4)
    pipe = Pipe(seq, chunks=2, n_stages=2)
    assert len(pipe) == 4
    assert pipe[0] is seq[0]
    assert list(iter(pipe)) == list(seq)
    assert pipe.balance == [2, 2]


# ---------- transparency ----------

@pytest.mark.parametrize("chunks", [1, 2, 4, 3])  # 3: non-divisible (8 % 3 != 0)
@pytest.mark.parametrize("n_stages", [1, 2, 4])
def test_forward_transparency(chunks, n_stages):
    key = jax.random.key(0)
    seq, params = make_mlp(key)
    pipe = Pipe(seq, chunks=chunks, checkpoint="never", n_stages=n_stages)
    x = jax.random.normal(jax.random.key(1), (8, 8))

    expected = seq.apply(params, x)
    # regroup flat per-layer params into per-stage lists
    stage_params = _regroup(params, pipe.balance)
    got = pipe(stage_params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)


def _regroup(flat_params, balance):
    out, off = [], 0
    for w in balance:
        out.append(flat_params[off:off + w])
        off += w
    return out


@pytest.mark.parametrize("checkpoint", ["never", "except_last", "always"])
def test_gradient_transparency(checkpoint):
    key = jax.random.key(0)
    seq, params = make_mlp(key)
    pipe = Pipe(seq, chunks=4, checkpoint=checkpoint, n_stages=2)
    x = jax.random.normal(jax.random.key(1), (8, 8))
    stage_params = _regroup(params, pipe.balance)

    def plain_loss(p):
        return jnp.mean(seq.apply(p, x) ** 2)

    def pipe_loss(sp):
        return jnp.mean(pipe(sp, x, train=True) ** 2)

    expected = jax.grad(plain_loss)(params)
    got = jax.grad(pipe_loss)(stage_params)
    flat_e = jax.tree_util.tree_leaves(expected)
    flat_g = jax.tree_util.tree_leaves(got)
    assert len(flat_e) == len(flat_g)
    for e, g in zip(flat_e, flat_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                   rtol=1e-4, atol=1e-5)


def test_pipe_init_matches_sequential_shapes():
    seq = Sequential([Linear(16), Linear(8), Linear(4)])
    pipe = Pipe(seq, chunks=2, n_stages=3, checkpoint="never")
    sp = pipe.init(jax.random.key(0), jnp.zeros((2, 16)))
    assert len(sp) == 3
    assert sp[0][0]["w"].shape == (16, 16)
    assert sp[1][0]["w"].shape == (16, 8)
    assert sp[2][0]["w"].shape == (8, 4)
    out = pipe(sp, jnp.ones((4, 16)))
    assert out.shape == (4, 4)


def test_dropout_deterministic_given_key():
    seq = Sequential([Linear(8), Dropout(0.5), Linear(8)])
    pipe = Pipe(seq, chunks=2, n_stages=2, balance=[2, 1], checkpoint="never")
    sp = pipe.init(jax.random.key(0), jnp.zeros((2, 8)))
    x = jax.random.normal(jax.random.key(1), (8, 8))
    k = jax.random.key(42)
    a = pipe(sp, x, key=k, train=True)
    b = pipe(sp, x, key=k, train=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = pipe(sp, x, key=jax.random.key(43), train=True)
    assert not np.allclose(np.asarray(a), np.asarray(c))


def test_remat_matches_no_remat_with_dropout():
    """The RNG-replay property: remat'd forward must be bit-identical, so
    gradients under 'always' equal gradients under 'never' even with dropout
    (what the reference buys with save/restore_rng_states, README.md:528-537)."""
    seq = Sequential([Linear(8), Dropout(0.5), Linear(8)])
    x = jax.random.normal(jax.random.key(1), (8, 8))
    k = jax.random.key(7)

    grads = {}
    for mode in ("never", "always"):
        pipe = Pipe(seq, chunks=2, n_stages=1, checkpoint=mode)
        sp = pipe.init(jax.random.key(0), jnp.zeros((2, 8)))

        def loss(p):
            return jnp.mean(pipe(p, x, key=k, train=True) ** 2)

        grads[mode] = jax.grad(loss)(sp)
    for a, b in zip(jax.tree_util.tree_leaves(grads["never"]),
                    jax.tree_util.tree_leaves(grads["always"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)


def test_eval_mode_disables_checkpoint():
    # train=False => checkpoint_stop == 0 (reference pipeline.py:153-155);
    # observable as: no error, identical output to never-mode.
    seq, params = make_mlp(jax.random.key(0))
    sp = _regroup(params, [2, 2])
    x = jax.random.normal(jax.random.key(1), (8, 8))
    p_always = Pipe(seq, chunks=2, n_stages=2, checkpoint="always")
    p_never = Pipe(seq, chunks=2, n_stages=2, checkpoint="never")
    np.testing.assert_array_equal(
        np.asarray(p_always(sp, x, train=False)),
        np.asarray(p_never(sp, x, train=False)))


def test_multi_input_stage_with_nochunk():
    """Non-batch side input rides NoChunk through the pipeline."""
    scale_layer = Lambda(lambda x, s: (x * s, s), name="scale")
    sum_layer = Lambda(lambda x, s: x + s, name="add")
    seq = Sequential([scale_layer, sum_layer])
    pipe = Pipe(seq, chunks=2, n_stages=2, checkpoint="never")
    x = jnp.ones((4, 3))
    out = pipe([[{}], [{}]], x, NoChunk(jnp.full((1,), 2.0)))
    np.testing.assert_allclose(np.asarray(out), np.ones((4, 3)) * 2 + 2.0)


def test_jit_whole_pipe():
    seq, params = make_mlp(jax.random.key(0))
    pipe = Pipe(seq, chunks=4, n_stages=2, checkpoint="except_last")
    sp = _regroup(params, pipe.balance)
    x = jax.random.normal(jax.random.key(1), (8, 8))

    @jax.jit
    def step(p, x, k):
        return pipe(p, x, key=k, train=True)

    out = step(sp, x, jax.random.key(0))
    assert out.shape == (8, 8)
