"""pipe_tpu.resilience: fault injection, detection, recovery (train + serve).

The two pins that frame everything here:

* **Bitwise opt-out** — with no ResilienceConfig and no ChaosPlan, the
  train step and the serve decode program lower to byte-identical HLO
  before and after the resilience machinery is constructed/used
  (``test_*_hlo_unchanged*``), and a guarded-but-fault-free run produces
  bitwise the params of the unguarded trainer.
* **Loud, contained failure** — every injected fault class recovers
  (skip-step, rewind, data retry, slot-error containment) or aborts
  explicitly (TrainingAborted), never silently corrupts.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.data import lm_text
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
from pipe_tpu.obs.events import NULL_EVENT_LOG
from pipe_tpu.obs.telemetry import MetricsRegistry, get_registry, set_registry
from pipe_tpu.resilience import (ChaosError, ChaosPlan, DataIteratorFailed,
                                 Fault, ResilienceConfig,
                                 ResilienceController, RetryingIterator,
                                 TickWatchdog, TrainingAborted, step_guard)
from pipe_tpu.train.loop import Trainer, TrainerConfig

pytestmark = pytest.mark.chaos

CFG = LMConfig(vocab=67, d_model=16, nhead=2, d_ff=32, n_layers=4,
               seq_len=32, dropout=0.0)
RC = ResilienceConfig(warmup_steps=100, rewind_after=2, snapshot_every=2,
                      data_backoff_s=0.0, rewind_backoff_s=0.0)


def _tc(**kw):
    base = dict(batch_size=8, bptt=16, chunks=2, n_stages=2,
                checkpoint="never", lr=0.01)
    base.update(kw)
    return TrainerConfig(**base)


@pytest.fixture(scope="module")
def source():
    ids = np.random.RandomState(0).randint(0, CFG.vocab, size=20000)
    return lm_text.batchify(ids, 8)


@pytest.fixture(scope="module")
def chaos_trainer():
    """One compiled chaos-armed trainer shared by the fault tests: the
    inject code is a *traced* argument, so swapping ``tr.chaos`` between
    tests exercises different fault classes with zero recompiles."""
    return Trainer(CFG, _tc(resilience=RC), chaos=ChaosPlan([]))


def _params_equal(a, b):
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree_util.tree_leaves(a),
                               jax.tree_util.tree_leaves(b)))


def _params_finite(state):
    return all(bool(jnp.isfinite(l).all())
               for l in jax.tree_util.tree_leaves(state.params)
               if jnp.issubdtype(l.dtype, jnp.inexact))


# ---------------------------------------------------------------------------
# detection unit tests


def test_step_guard_verdicts():
    grads = {"w": jnp.ones((3,), jnp.float32)}
    kw = dict(spike_factor=4.0, warmup_steps=2, ewma_alpha=0.5)

    ok, ewma = step_guard(jnp.float32(2.0), grads, jnp.float32(0.0),
                          jnp.int32(0), **kw)
    assert bool(ok) and float(ewma) == 2.0          # seeds on first accept

    ok, _ = step_guard(jnp.float32(jnp.nan), grads, jnp.float32(2.0),
                       jnp.int32(5), **kw)
    assert not bool(ok)                              # non-finite loss

    bad = {"w": jnp.array([1.0, jnp.inf, 0.0], jnp.float32)}
    ok, ewma = step_guard(jnp.float32(2.0), bad, jnp.float32(2.0),
                          jnp.int32(5), **kw)
    assert not bool(ok) and float(ewma) == 2.0       # EWMA holds on reject

    ok, _ = step_guard(jnp.float32(100.0), grads, jnp.float32(2.0),
                       jnp.int32(5), **kw)
    assert not bool(ok)                              # spike past warmup

    ok, _ = step_guard(jnp.float32(100.0), grads, jnp.float32(2.0),
                       jnp.int32(1), **kw)
    assert bool(ok)                                  # warmup disarms spike


def test_tick_watchdog_validation_and_stuck_budget():
    wd = TickWatchdog(stuck_slack_ticks=3)
    assert wd.stuck_after(max_new_tokens=8, decode_chunk=4) == 2 + 3
    assert TickWatchdog(stuck_slack_ticks=None).stuck_after(8, 1) is None
    with pytest.raises(ValueError):
        TickWatchdog(tick_budget_s=0.0)
    with pytest.raises(ValueError):
        TickWatchdog(shed_ewma_threshold=1.5)


# ---------------------------------------------------------------------------
# recovery unit tests (controller + iterator; no jit)


def _aux(consec, total, ewma=1.0):
    return (jnp.float32(ewma), jnp.int32(consec), jnp.int32(total))


def test_controller_rewinds_then_aborts():
    cfg = ResilienceConfig(rewind_after=1, max_rewinds=1, snapshot_every=1,
                           warmup_steps=100)
    slept = []
    ctl = ResilienceController(cfg, get_registry(), NULL_EVENT_LOG,
                               log_fn=lambda s: None, sleep=slept.append)
    good = {"w": jnp.arange(3.0)}
    state, aux = ctl.after_step(0, good, _aux(0, 0))     # snapshots
    assert ctl.anomalies == 0
    state, aux = ctl.after_step(1, {"w": jnp.full((3,), jnp.nan)},
                                _aux(1, 1))
    assert ctl.rewinds == 1 and ctl.anomalies == 1
    assert np.array_equal(np.asarray(state["w"]), np.arange(3.0))
    assert int(aux[1]) == 0                              # consec reset
    with pytest.raises(TrainingAborted):
        ctl.after_step(2, {"w": jnp.full((3,), jnp.nan)}, _aux(1, 2))


def test_controller_aborts_without_snapshot():
    cfg = ResilienceConfig(rewind_after=1, warmup_steps=100)
    ctl = ResilienceController(cfg, get_registry(), NULL_EVENT_LOG,
                               log_fn=lambda s: None)
    with pytest.raises(TrainingAborted, match="no known-good snapshot"):
        ctl.after_step(0, {"w": jnp.zeros(2)}, _aux(1, 1))


def test_retrying_iterator_resumes_at_position():
    fails = {2: 1}      # item 2 fails once

    def factory(pos):
        def gen():
            for i in range(pos, 5):
                if fails.get(i, 0) > 0:
                    fails[i] -= 1
                    raise ChaosError(f"boom at {i}")
                yield i
        return gen()

    it = RetryingIterator(factory, retries=2, backoff_s=0.0, sleep=lambda s: None)
    assert list(it) == [0, 1, 2, 3, 4]


def test_retrying_iterator_exhausts_budget():
    def factory(pos):
        def gen():
            raise ChaosError("always")
            yield  # pragma: no cover
        return gen()

    it = RetryingIterator(factory, retries=2, backoff_s=0.0,
                          sleep=lambda s: None)
    with pytest.raises(DataIteratorFailed, match="failed 3 times"):
        next(it)


def _flaky_factory(fails):
    """Factory whose source raises ``fails['left']`` times, then yields
    0..2 from the requested position."""
    def factory(pos):
        def gen():
            if fails["left"] > 0:
                fails["left"] -= 1
                raise ChaosError("flaky source")
            for i in range(pos, 3):
                yield i
        return gen()
    return factory


def test_retrying_iterator_delivers_at_exact_retry_cap():
    # the source fails exactly `retries` times: the last permitted
    # rebuild must deliver, not abort one attempt early
    fails = {"left": 2}
    it = RetryingIterator(_flaky_factory(fails), retries=2, backoff_s=0.0,
                          sleep=lambda s: None)
    assert list(it) == [0, 1, 2]
    assert fails["left"] == 0


def test_retrying_iterator_one_past_cap_aborts():
    # one more failure than the budget allows — even though the next
    # rebuild would have succeeded, the cap is the cap
    fails = {"left": 3}
    it = RetryingIterator(_flaky_factory(fails), retries=2, backoff_s=0.0,
                          sleep=lambda s: None)
    with pytest.raises(DataIteratorFailed, match="failed 3 times"):
        next(it)


def test_retrying_iterator_backoffs_double_under_fake_clock():
    def factory(pos):
        def gen():
            raise ChaosError("always")
            yield  # pragma: no cover
        return gen()

    slept = []
    it = RetryingIterator(factory, retries=3, backoff_s=0.25,
                          sleep=slept.append)
    with pytest.raises(DataIteratorFailed):
        next(it)
    # one sleep per burnt retry (none after the final failure), each
    # exactly double the last — strictly monotone, no wall clock read
    assert slept == [0.25 * 2 ** k for k in range(3)]
    assert all(b > a for a, b in zip(slept, slept[1:]))


def test_controller_rewind_backoffs_double_and_pin_oldest_snapshot():
    cfg = ResilienceConfig(rewind_after=1, max_rewinds=3,
                           snapshot_every=100, warmup_steps=100,
                           rewind_backoff_s=0.5)
    slept, lines = [], []
    ctl = ResilienceController(cfg, get_registry(), NULL_EVENT_LOG,
                               log_fn=lines.append, sleep=slept.append)
    good = {"w": jnp.arange(3.0)}
    ctl.after_step(0, good, _aux(0, 0))          # the ONLY snapshot: step 0
    bad = {"w": jnp.full((3,), jnp.nan)}
    for k in range(3):
        state, aux = ctl.after_step(k + 1, bad, _aux(1, k + 1))
        assert np.array_equal(np.asarray(state["w"]), np.arange(3.0))
    # every rewind targeted the oldest (and only) in-memory snapshot —
    # there is nothing older to reach — and each backoff doubled
    assert ctl.rewinds == 3
    assert all("snapshot of step 0" in l for l in lines)
    assert slept == [0.5 * 2 ** k for k in range(3)]
    assert all(b > a for a, b in zip(slept, slept[1:]))
    with pytest.raises(TrainingAborted, match="after 3 rewinds"):
        ctl.after_step(4, bad, _aux(1, 4))


# ---------------------------------------------------------------------------
# guarded trainer: parity, skip-step, data retry


def test_guarded_no_fault_matches_unguarded_bitwise(source):
    """The headline parity claim: resilience ON but fault-free produces
    bitwise the params of the unguarded trainer."""
    tr_g = Trainer(CFG, _tc(resilience=RC))
    tr_d = Trainer(CFG, _tc())
    sg, ig = tr_g.train_epoch(source, 0, tr_g.init_state(), max_steps=3,
                              log_every=0)
    sd, _ = tr_d.train_epoch(source, 0, tr_d.init_state(), max_steps=3,
                             log_every=0)
    assert ig["anomalies"] == 0 and ig["rewinds"] == 0
    assert _params_equal(sg.params, sd.params)
    assert int(sg.step) == int(sd.step) == 3


def test_skip_step_on_injected_nan(chaos_trainer, source):
    tr = chaos_trainer
    tr.chaos = ChaosPlan([Fault("nan_grads", step=2)])
    state, info = tr.train_epoch(source, 0, tr.init_state(), max_steps=5,
                                 log_every=0)
    assert info["anomalies"] == 1 and info["rewinds"] == 0
    assert _params_finite(state)
    assert np.isfinite(info["loss_ewma"])
    assert int(state.step) == 5          # skipped step still advances step


def test_nan_activations_caught_by_guard(chaos_trainer, source):
    tr = chaos_trainer
    tr.chaos = ChaosPlan([Fault("nan_activations", step=1)])
    state, info = tr.train_epoch(source, 0, tr.init_state(), max_steps=3,
                                 log_every=0)
    assert info["anomalies"] == 1
    assert _params_finite(state)


def test_persistent_faults_rewind(chaos_trainer, source):
    tr = chaos_trainer
    tr.chaos = ChaosPlan([Fault("nan_grads", step=2, count=2)])
    lines = []
    state, info = tr.train_epoch(source, 0, tr.init_state(), max_steps=6,
                                 log_every=0, log_fn=lines.append)
    assert info["rewinds"] >= 1
    assert _params_finite(state)
    assert any("rewind" in l for l in lines)


def test_data_fault_retried_no_steps_lost(chaos_trainer, source):
    tr = chaos_trainer
    tr.chaos = ChaosPlan([Fault("data_raise", step=1)])
    # the trainer binds its registry at construction — count the delta
    before = tr.registry.scalars().get("resilience.data_retries", 0)
    state, info = tr.train_epoch(source, 0, tr.init_state(),
                                 max_steps=4, log_every=0)
    after = tr.registry.scalars().get("resilience.data_retries", 0)
    assert info["steps"] == 4 and info["anomalies"] == 0
    assert after - before == 1


# ---------------------------------------------------------------------------
# the HLO byte-equality pins (acceptance criterion)


def test_train_step_hlo_unchanged_by_resilience(source):
    """The default train step's lowered HLO is byte-identical before and
    after resilience machinery exists in the process — opt-in means
    *absent from the program*, not merely disabled."""
    tr = Trainer(CFG, _tc())
    state = tr.init_state()
    data, target = next(tr._batches(source, 1))
    x, w = tr._make_x(data, target)
    args = (state, x, w, jax.random.key(0), jnp.float32(0.01))
    base = tr._step_fn.lower(*args).as_text()

    chaos_tr = Trainer(CFG, _tc(resilience=RC),
                       chaos=ChaosPlan([Fault("nan_grads", step=0)]))
    aux = (jnp.float32(0.0), jnp.int32(0), jnp.int32(0))
    cs = chaos_tr.init_state()
    chaos_tr._step_fn.lower(cs, aux, x, w, jax.random.key(0),
                            jnp.float32(0.01), jnp.int32(1),
                            jnp.float32(1e3)).as_text()

    assert tr._step_fn.lower(*args).as_text() == base


def test_decode_hlo_unchanged_by_watchdog_and_chaos():
    from pipe_tpu.serve import ServeEngine, SingleDeviceSlotBackend
    from pipe_tpu.inference.generate import GenerationConfig

    model = PipelinedLM(CFG, 2)
    params = model.init(jax.random.key(0))

    def lowered():
        be = SingleDeviceSlotBackend(
            model, params, num_slots=2, max_len=16,
            gen=GenerationConfig(max_new_tokens=4, temperature=1.0))
        return be._decode_jit.lower(
            be._block_stack, be._pre, be._post, be._caches, be._tok,
            be._pos, be._key_data).as_text(), be

    base, _ = lowered()
    text, be = lowered()
    ServeEngine(be, watchdog=TickWatchdog(tick_budget_s=0.1,
                                          shed_ewma_threshold=0.5),
                chaos=ChaosPlan([Fault("stall_tick", step=0)]))
    text2, _ = lowered()
    assert base == text == text2


# ---------------------------------------------------------------------------
# emulator transport faults


def test_emulator_transport_fault_targets_one_hop():
    from pipe_tpu.core import microbatch as mb
    from pipe_tpu.parallel import emulator

    def stage(p, x, ctx):
        return jnp.tanh(x @ p)

    key = jax.random.key(7)
    params = [jax.random.normal(jax.random.fold_in(key, s), (8, 8))
              for s in range(2)]
    xs = [mb.Batch(jax.random.normal(jax.random.fold_in(key, 10 + i),
                                     (4, 8)), atomic=True)
          for i in range(2)]

    def run(chaos):
        out = emulator.run([stage, stage], params, list(xs), chaos=chaos)
        return [np.asarray(b.values[0]) for b in out]

    clean = run(None)
    drop = run(ChaosPlan([Fault("transport_drop", step=0, stage=0,
                                microbatch=1)]))
    assert np.array_equal(drop[0], clean[0])       # other microbatch spared
    assert not np.array_equal(drop[1], clean[1])
    corrupt = run(ChaosPlan([Fault("transport_corrupt", step=0, stage=0,
                                   microbatch=0)]))
    assert np.isnan(corrupt[0]).all()              # NaN-poisoned hop
    assert np.array_equal(corrupt[1], clean[1])
    # a retry without the plan reproduces the clean run bitwise
    assert all(np.array_equal(a, b) for a, b in zip(run(None), clean))


# ---------------------------------------------------------------------------
# serve engine: containment, watchdog, shedding, drain


@pytest.fixture(scope="module")
def serve_backend():
    from pipe_tpu.inference.generate import GenerationConfig
    from pipe_tpu.serve import SingleDeviceSlotBackend

    model = PipelinedLM(CFG, 2)
    params = model.init(jax.random.key(0))
    return SingleDeviceSlotBackend(
        model, params, num_slots=2, max_len=32,
        gen=GenerationConfig(max_new_tokens=8, temperature=1.0))


def test_prefill_error_contained_to_one_request(serve_backend):
    from pipe_tpu.serve import ServeEngine

    be = serve_backend
    eng = ServeEngine(be)
    orig, calls = be.prefill, {"n": 0}

    def bad_prefill(slot, prompt, seed):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("boom")
        return orig(slot, prompt, seed)

    reg = set_registry(MetricsRegistry())
    be.prefill = bad_prefill
    try:
        r1 = eng.submit([1, 2, 3], max_new_tokens=4)
        r2 = eng.submit([4, 5, 6], max_new_tokens=4)
        eng.run_until_idle()
        errs = get_registry().scalars().get("resilience.slot_errors", 0)
    finally:
        be.prefill = orig
        set_registry(reg)
    assert eng.response(r1.id).status == "error"
    assert eng.response(r1.id).finish_reason == "backend_error"
    assert eng.response(r2.id).status == "ok"      # others keep serving
    assert errs == 1
    assert eng.live_slots == 0 and len(eng._free) == be.num_slots


def test_decode_errors_tolerated_then_retire_all(serve_backend):
    from pipe_tpu.serve import ServeEngine

    be = serve_backend
    orig = be.decode
    # below the limit: tick skipped, slot state intact, request finishes
    flaky = {"n": 0}

    def flaky_decode(live):
        flaky["n"] += 1
        if flaky["n"] <= 2:
            raise RuntimeError("transient")
        return orig(live)

    eng = ServeEngine(be, decode_error_limit=3)
    be.decode = flaky_decode
    try:
        r = eng.submit([1, 2, 3], max_new_tokens=4)
        eng.run_until_idle()
    finally:
        be.decode = orig
    assert eng.response(r.id).status == "ok"

    # at the limit: live slots retired as errors, engine stays usable
    def dead_decode(live):
        raise RuntimeError("dead backend")

    eng2 = ServeEngine(be, decode_error_limit=2)
    be.decode = dead_decode
    try:
        r = eng2.submit([1, 2, 3], max_new_tokens=4)
        eng2.tick()
        assert eng2.response(r.id) is None         # first error tolerated
        eng2.tick()
        resp = eng2.response(r.id)
    finally:
        be.decode = orig
    assert resp.status == "error" and resp.finish_reason == "backend_error"
    r2 = eng2.submit([4, 5], max_new_tokens=4)     # engine still serves
    eng2.run_until_idle()
    assert eng2.response(r2.id).status == "ok"


def test_stuck_slot_retired_as_error(serve_backend):
    from pipe_tpu.serve import ServeEngine

    be = serve_backend
    orig = be.decode

    def no_progress(live):
        toks, valid = orig(live)
        return toks, np.zeros_like(valid)          # tokens never valid

    eng = ServeEngine(be, watchdog=TickWatchdog(stuck_slack_ticks=2))
    be.decode = no_progress
    try:
        r = eng.submit([1, 2, 3], max_new_tokens=4)
        for _ in range(12):
            eng.tick()
            if eng.response(r.id) is not None:
                break
    finally:
        be.decode = orig
    resp = eng.response(r.id)
    assert resp is not None and resp.status == "error"
    assert resp.finish_reason == "stuck"


def test_degraded_mode_sheds_lowest_priority(serve_backend):
    from pipe_tpu.serve import RequestQueue, ServeEngine

    t = {"now": 0.0}
    q = RequestQueue(capacity=16, policy="priority",
                     clock=lambda: t["now"])
    eng = ServeEngine(serve_backend, q, watchdog=TickWatchdog(
        shed_ewma_threshold=0.5, shed_ewma_alpha=1.0,
        stuck_slack_ticks=None))
    # a queued request missing its deadline drives the miss EWMA to 1.0
    eng.submit([1, 2], max_new_tokens=2, timeout_s=0.1)
    t["now"] = 1.0
    eng.tick()
    assert eng._miss_ewma == 1.0
    lo = eng.submit([3, 4], max_new_tokens=2, priority=-5)
    hi = eng.submit([5, 6], max_new_tokens=2, priority=5)
    eng.tick()
    assert eng.response(lo.id) is not None
    assert eng.response(lo.id).status == "shed"
    assert eng.response(lo.id).finish_reason == "shed"
    resp_hi = eng.response(hi.id)
    assert resp_hi is None or resp_hi.status != "shed"
    eng.run_until_idle()


def test_drain_finishes_live_sheds_queued(serve_backend):
    from pipe_tpu.serve import EngineDraining, ServeEngine

    eng = ServeEngine(serve_backend)
    ra = eng.submit([1, 2, 3], max_new_tokens=4)
    rb = eng.submit([4, 5], max_new_tokens=4)
    rc = eng.submit([6, 7], max_new_tokens=4)      # queued (2 slots)
    eng.tick()
    eng.drain()
    with pytest.raises(EngineDraining):
        eng.submit([8], max_new_tokens=2)
    ticks = 0
    while not eng.drained:
        eng.tick()
        ticks += 1
        assert ticks < 50
    assert eng.response(ra.id).status == "ok"
    assert eng.response(rb.id).status == "ok"
    assert eng.response(rc.id).status == "shed"
    assert eng.response(rc.id).finish_reason == "drain"


def test_queue_full_reports_depth_capacity_age():
    from pipe_tpu.serve import QueueFull, RequestQueue

    t = {"now": 100.0}
    q = RequestQueue(capacity=2, clock=lambda: t["now"])
    q.submit([1], max_new_tokens=1)
    t["now"] = 103.0
    q.submit([2], max_new_tokens=1)
    with pytest.raises(QueueFull) as ei:
        q.submit([3], max_new_tokens=1)
    e = ei.value
    assert e.depth == 2 and e.capacity == 2
    assert e.oldest_age_s == pytest.approx(3.0)
    assert "depth 2/2" in str(e) and "3.000s" in str(e)


def test_shed_lowest_orders_by_priority_then_youngest():
    from pipe_tpu.serve import RequestQueue

    q = RequestQueue(capacity=8, policy="priority")
    a = q.submit([1], max_new_tokens=1, priority=0)   # oldest at prio 0
    b = q.submit([2], max_new_tokens=1, priority=5)
    c = q.submit([3], max_new_tokens=1, priority=0)   # youngest at prio 0
    shed = q.shed_lowest(2)
    assert [r.id for r in shed] == [a.id, c.id]       # prio 5 survives...
    assert q.depth == 1 and q.pop().id == b.id
    # ...and within a priority level the youngest sheds first
    q2 = RequestQueue(capacity=8)
    x = q2.submit([1], max_new_tokens=1)
    y = q2.submit([2], max_new_tokens=1)
    assert [r.id for r in q2.shed_lowest(1)] == [y.id]
    assert q2.pop().id == x.id


# ---------------------------------------------------------------------------
# checkpoint manifest (atomic + verifiable save)


def test_checkpoint_manifest_verifies_and_names_corrupt_leaf(tmp_path,
                                                             source):
    from pipe_tpu.train.state import (CheckpointCorrupt, restore_checkpoint,
                                      save_checkpoint)

    tr = Trainer(CFG, _tc())
    state = tr.init_state()
    ckpt = str(tmp_path / "ck")
    save_checkpoint(ckpt, state, 0)
    manifest = tmp_path / "ck" / "manifest_step0.json"
    assert manifest.is_file()

    restored = restore_checkpoint(ckpt, tr.init_state())   # verify=True
    assert _params_equal(restored.params, state.params)

    # tamper one leaf's recorded hash: restore must refuse, naming it
    doc = json.loads(manifest.read_text())
    leaf = sorted(doc["leaves"])[0]
    doc["leaves"][leaf] = "0" * 64
    manifest.write_text(json.dumps(doc))
    with pytest.raises(CheckpointCorrupt) as ei:
        restore_checkpoint(ckpt, tr.init_state())
    assert leaf in str(ei.value)

    restore_checkpoint(ckpt, tr.init_state(), verify=False)  # opt-out


def test_torn_manifest_tmp_files_ignored_on_restore(tmp_path):
    """A crash between tmp-write and rename leaves ``.*.tmp`` droppings;
    only a completed rename may ever be read back."""
    from pipe_tpu.train.state import (read_buddy_manifest,
                                      restore_checkpoint, save_checkpoint,
                                      write_buddy_manifest)

    shards = {"stage0": "a" * 64, "stage1": "b" * 64}
    write_buddy_manifest(str(tmp_path), 5, shards, 2)
    # torn writes: a truncated tmp NEXT TO the good step-5 record, and
    # a step-7 write that died before its rename
    (tmp_path / ".buddy_step5.json.tmp").write_text('{"step": 5, "n_st')
    (tmp_path / ".buddy_step7.json.tmp").write_text('{"step": 7')
    doc = read_buddy_manifest(str(tmp_path), 5)
    assert doc == {"step": 5, "n_stages": 2, "stage_shards": shards}
    assert read_buddy_manifest(str(tmp_path), 7) is None

    # checkpoint side: a leftover torn manifest tmp must neither block
    # nor pollute verification of the completed manifest
    tr = Trainer(CFG, _tc())
    state = tr.init_state()
    ckpt = tmp_path / "ck"
    save_checkpoint(str(ckpt), state, 0)
    (ckpt / ".manifest_step0.json.tmp").write_text('{"step": 0, "leav')
    restored = restore_checkpoint(str(ckpt), tr.init_state())
    assert _params_equal(restored.params, state.params)


# ---------------------------------------------------------------------------
# SIGTERM autosave: signal mid-epoch -> checkpoint -> bitwise resume


def test_sigterm_autosave_resumes_next_step_bitwise(tmp_path, source):
    """The preemption flow end to end on the REAL signal: SIGTERM lands
    mid-epoch, the in-flight step finishes, the checkpoint is written,
    the epoch loop exits cleanly — and re-running the next step from the
    restored state reproduces the uninterrupted run bitwise."""
    import os
    import signal

    from pipe_tpu.train.state import latest_step, restore_checkpoint

    # uninterrupted reference: two steps
    tr_ref = Trainer(CFG, _tc())
    ref, _ = tr_ref.train_epoch(source, 0, tr_ref.init_state(),
                                max_steps=2, log_every=0)

    tr = Trainer(CFG, _tc())
    ckpt = str(tmp_path / "auto")
    prev_handler = signal.getsignal(signal.SIGTERM)
    try:
        tr.install_autosave(ckpt)                  # default: SIGTERM
        fired = {"done": False}
        orig_step = tr._step_fn

        def step_and_signal(*a, **kw):
            out = orig_step(*a, **kw)
            if not fired["done"]:
                fired["done"] = True
                os.kill(os.getpid(), signal.SIGTERM)
            return out

        tr._step_fn = step_and_signal
        lines = []
        _, stats = tr.train_epoch(source, state=tr.init_state(),
                                  max_steps=4, log_every=0,
                                  log_fn=lines.append)
        tr._step_fn = orig_step
    finally:
        signal.signal(signal.SIGTERM, prev_handler)
    assert stats["steps"] == 1                     # clean early exit
    assert any("autosave" in l for l in lines)
    assert latest_step(ckpt) == 1

    restored = restore_checkpoint(ckpt, tr.init_state())
    assert int(restored.step) == 1
    # replay step b=1 exactly as train_epoch would have (epoch-0 key
    # chain, epoch-0 StepLR)
    from pipe_tpu.utils.rng import make_key

    data, target = list(tr._batches(source, 2, start=1))[0]
    x, w = tr._make_x(data, target)
    key = jax.random.fold_in(make_key(tr.cfg.seed), 0)
    state2, _ = tr._step_fn(restored, x, w, jax.random.fold_in(key, 1),
                            jnp.float32(tr.cfg.lr))
    assert int(state2.step) == 2
    assert _params_equal(state2.params, ref.params)
