"""Schedule visualization (tools/schedule_viz.py) — rendered from the SAME
op tables the executor runs, so the picture can't drift from the program."""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))

import schedule_viz  # noqa: E402


@pytest.mark.parametrize("name", ["gpipe", "1f1b", "zb-h1",
                                  "interleaved-1f1b"])
def test_ascii_timeline_renders(name):
    out = schedule_viz.ascii_timeline(name, m=6, n=3)
    lines = out.splitlines()
    assert "bubble=" in lines[0]
    # one row per stage/device + title + cycle header
    assert len(lines) == 3 + 2
    if name == "gpipe":
        # the canonical fill-drain shape: stage 0 starts F0, stage 2 two in
        assert "F0" in lines[2] and lines[4].count(".") >= 2


@pytest.mark.parametrize("name", ["gpipe", "1f1b", "zb-h1",
                                  "interleaved-1f1b"])
def test_svg_timeline_wellformed(name):
    import xml.etree.ElementTree as ET

    svg = schedule_viz.svg_timeline(name, m=4, n=2)
    root = ET.fromstring(svg)
    assert root.tag.endswith("svg")
    rects = [e for e in root.iter() if e.tag.endswith("rect")]
    assert len(rects) > 4


def test_zb_table_shows_wgrad():
    out = schedule_viz.ascii_timeline("zb-h1", m=4, n=2)
    assert "W0" in out and "B0" in out and "F0" in out


def test_cli_all_and_svg(tmp_path, capsys):
    assert schedule_viz.main(["-m", "4", "-n", "2"]) == 0
    out = capsys.readouterr().out
    assert out.count("bubble=") == 4
    svg = tmp_path / "s.svg"
    assert schedule_viz.main(["1f1b", "--svg", str(svg)]) == 0
    assert svg.exists()
