"""W-op (split-backward) table IR: verifier dependence edges, joint
stash+park capacity, the zb-h2 deep-warmup variant, and the analytic
bubble report.

These pin the zero-bubble IR contract added with the structural split:
``verify_op_tables`` treats W-bearing tables as first-class (W strictly
after its own B; activations freed at W, not B; the B->W cotangent park
bounded by ``wstash_slots``), the joint stash+park peak never exceeds
the capacity the schedule declares, and the comm-shift (overlapped
transport) contract threads ``splits_backward`` through."""

import numpy as np
import pytest

from pipe_tpu.core.schedule import (BWD, FWD, IDLE, WGRAD,
                                    ZeroBubbleDeepSchedule,
                                    align_phase_tables, compile_phases,
                                    get_schedule, verify_op_tables,
                                    zb_joint_capacity)
from pipe_tpu.obs.zb_model import analytic_bubbles

GEOMS = [(8, 4), (16, 4), (12, 6)]


@pytest.mark.parametrize("name", ["zb-h1", "zb-h2"])
@pytest.mark.parametrize("m,n", GEOMS)
def test_w_tables_verify(name, m, n):
    """The shipped split tables pass the W-aware verifier with exactly
    the capacities the schedule declares."""
    sched = get_schedule(name)
    op, mbi = sched.op_tables(m, n)
    assert (op == WGRAD).sum() == m * n, "one W per (microbatch, stage)"
    verify_op_tables(op, mbi, m, n,
                     stash_slots=sched.stash_slots(m, n),
                     wstash_slots=sched.wstash_slots(m, n))


def test_verifier_rejects_w_before_its_b():
    """Dependence edge: W consumes B's parked cotangent, so a table
    where some (i, j)'s W precedes its B must fail the proof."""
    sched = get_schedule("zb-h1")
    m, n = 8, 4
    op, mbi = sched.op_tables(m, n)
    # swap the first (B, W) pair of stage 0's microbatch 0
    t_b = min(t for t in range(op.shape[0])
              if op[t, 0] == BWD and mbi[t, 0] == 0)
    t_w = min(t for t in range(op.shape[0])
              if op[t, 0] == WGRAD and mbi[t, 0] == 0)
    assert t_b < t_w
    broken = op.copy()
    broken[t_b, 0], broken[t_w, 0] = WGRAD, BWD
    with pytest.raises(AssertionError):
        verify_op_tables(broken, mbi, m, n,
                         stash_slots=sched.stash_slots(m, n),
                         wstash_slots=sched.wstash_slots(m, n))


def test_verifier_accounts_stash_freed_at_w_not_b():
    """Capacity edge: activations stay live through W (B alone does not
    release the taps), so claiming a 1F1B-style stash freed at B —
    stash_slots shrunk below the F->W window — must fail, and the
    declared capacity must pass."""
    sched = get_schedule("zb-h1")
    m, n = 8, 4
    op, mbi = sched.op_tables(m, n)
    S = sched.stash_slots(m, n)
    verify_op_tables(op, mbi, m, n, stash_slots=S,
                     wstash_slots=sched.wstash_slots(m, n))
    with pytest.raises(AssertionError):
        verify_op_tables(op, mbi, m, n, stash_slots=1,
                         wstash_slots=sched.wstash_slots(m, n))
    with pytest.raises(AssertionError):
        verify_op_tables(op, mbi, m, n, stash_slots=S, wstash_slots=0)


@pytest.mark.parametrize("name", ["zb-h1", "zb-h2"])
@pytest.mark.parametrize("m,n", GEOMS)
def test_joint_capacity_within_declared_slots(name, m, n):
    """The joint peak (live stashes [arrive, W] + live parks [B, W)) is
    the number the W op shrinks; it must fit the schedule's declared
    stash + wstash budget, and parks must actually exist (joint > peak
    stash alone would miss them)."""
    sched = get_schedule(name)
    op, mbi = sched.op_tables(m, n)
    joint = zb_joint_capacity(op, mbi, m, n)
    assert joint <= sched.stash_slots(m, n) + sched.wstash_slots(m, n)
    assert joint > 0


def test_comm_shift_interaction():
    """comm_shift >= 2 proves the overlapped-transport contract with
    ``splits_backward`` threaded through: the serialized zb-h1 table
    violates the hop-2 receive deadline (rigid B ring steps 1 cycle),
    while the phase-aligned table passes."""
    m, n = 8, 4
    sched = get_schedule("zb-h1")
    op, mbi = sched.op_tables(m, n)
    with pytest.raises(AssertionError):
        verify_op_tables(op, mbi, m, n, comm_shift=2)
    op2, mb2, _ = align_phase_tables(op, mbi, None, m=m, d=n, v=1, hop=2)
    verify_op_tables(op2, mb2, m, n, comm_shift=2)


def test_zb_h2_deeper_warmup_strictly_helps_where_ramp_dominates():
    """zb-h2 admits up to 2n-1 in-flight microbatches; its bubble is
    never worse than zb-h1's and strictly better at (12, 6), where
    zb-h1's shallow warmup leaves ramp idles W cannot reach. Both stay
    strictly below 1F1B everywhere tested."""
    for m, n in GEOMS:
        b1 = get_schedule("1f1b").bubble(m, n)
        bh1 = get_schedule("zb-h1").bubble(m, n)
        bh2 = get_schedule("zb-h2").bubble(m, n)
        assert bh2 <= bh1 < b1, (m, n, b1, bh1, bh2)
    assert (get_schedule("zb-h2").bubble(12, 6)
            < get_schedule("zb-h1").bubble(12, 6))


def test_zb_h2_registered_and_caps():
    sched = get_schedule("zb-h2")
    assert isinstance(sched, ZeroBubbleDeepSchedule)
    assert sched.splits_backward
    # memory trade: the deep warmup admits up to 2n-1 in-flight
    assert sched._in_flight_cap(16, 4) == 7
    assert get_schedule("zb-h1")._in_flight_cap(16, 4) == 5


@pytest.mark.parametrize("name", ["zb-h1", "zb-h2"])
def test_w_tables_phase_compile(name, m=8, n=4):
    """The phase compiler accepts W-bearing tables (period-3 F/B/W
    steady state) — the switch-free lowering is not a fused-backward
    privilege."""
    op, mbi = get_schedule(name).op_tables(m, n)
    verdict = compile_phases(op, mbi, None, m=m, d=n, v=1)
    assert verdict.accepted, verdict.reason
    assert verdict.program.scan_cycles > 0
    assert any(seg.period == 3 for seg in verdict.program.segments
               if seg.kind == "scan")


def test_analytic_bubbles_report():
    """obs.zb_model.analytic_bubbles: same accounting as
    Schedule.bubble, split schedules strictly below 1f1b."""
    for m, n in GEOMS:
        ab = analytic_bubbles(m, n)
        assert set(ab) == {"1f1b", "zb-h1", "zb-h2"}
        assert ab["zb-h1"] < ab["1f1b"]
        assert ab["zb-h2"] < ab["1f1b"]
        assert ab[name_min := min(ab, key=ab.get)] >= 0 and \
            name_min in ("zb-h1", "zb-h2")
