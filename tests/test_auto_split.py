"""Auto-derived structural B/W split (``core.remat.split_backward_stage``).

PR-3 hand-rolled the split for the TP block (``tp_split_backward_stage``);
this generalization traces ANY ``stage_fn(params, h, ctx)`` and derives
the same triple by jaxpr surgery: a tapped forward (bitwise equal to the
plain one), a params-CONSTANT B vjp, and a contraction-only W. These
tests pin the contract on the main model-zoo stage (``PipelinedLM`` —
attention + MLP + dropout, nothing hand-annotated), the failure guards,
and the phased whole-program HLO census for ``split_stage="auto"``."""

import dataclasses
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core import microbatch as mb
from pipe_tpu.core.partition import StageCtx
from pipe_tpu.core.remat import SplitUnsupported, split_backward_stage
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.scheduled import ScheduledPipeline
from pipe_tpu.parallel.spmd import stack_stage_params


def _cfg(n_layers, dropout=0.1):
    return dataclasses.replace(
        LMConfig().tiny(), n_layers=n_layers, dropout=dropout)


def _grad_trees_close(got, exp):
    for (ka, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(got),
                               jax.tree_util.tree_leaves_with_path(exp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5, err_msg=str(ka))


def test_auto_split_unit_parity_and_censuses():
    """On the untouched PipelinedLM stage: tapped forward == plain
    forward bitwise; B's gh and W's param grads match the fused vjp; the
    COMPILED B contains zero weight-shaped dot outputs; the COMPILED W
    contains no token-dimension dot outputs (contraction-only)."""
    cfg = _cfg(2)
    model = PipelinedLM(cfg, 2)
    sp, _, _ = model.init(jax.random.key(0))
    p = sp[0]
    # batch=3: tokens = 3*seq_len = 48 collides with no weight dim, so a
    # weight-SHAPED dot output can only be a weight-grad contraction
    h = jax.random.normal(jax.random.key(1), (3, cfg.seq_len, cfg.d_model))
    ctx = StageCtx(key=jax.random.key(7))
    seed = jax.random.normal(jax.random.key(2), h.shape)

    ref_out, ref_vjp = jax.vjp(
        lambda pp, hh: model.stage_fn(pp, hh, ctx), p, h)
    gp_ref, gh_ref = ref_vjp(seed)

    split = split_backward_stage(model.stage_fn)
    zs = split.zs_fn(p, h)
    out, vjp_fn, taps = jax.vjp(
        lambda hh, zz: split.tapped_fn(p, hh, ctx, zz), h, zs,
        has_aux=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref_out))
    gh, gzs = vjp_fn(seed)
    np.testing.assert_allclose(np.asarray(gh), np.asarray(gh_ref),
                               rtol=1e-5, atol=1e-6)
    gp = split.wgrad_fn(taps, gzs)
    _grad_trees_close(gp, gp_ref)

    weight_shapes = {tuple(l.shape)
                     for _, l in jax.tree_util.tree_leaves_with_path(p)
                     if l.ndim >= 2}
    tokens = 3 * cfg.seq_len

    hlo_b = jax.jit(lambda s: vjp_fn(s)).lower(seed).compile().as_text()
    dots_b = re.findall(r"= f32\[([\d,]+)\][^ ]* dot\(", hlo_b)
    assert dots_b, "census regex matched no dots at all — HLO drifted?"
    bad_b = [d for d in dots_b
             if tuple(int(x) for x in d.split(",")) in weight_shapes]
    assert not bad_b, f"B compiled weight-grad-shaped matmuls: {bad_b}"

    hlo_w = jax.jit(split.wgrad_fn).lower(taps, gzs).compile().as_text()
    dots_w = re.findall(r"= f32\[([\d,]+)\][^ ]* dot\(", hlo_w)
    assert dots_w, "W pass compiled no dots — not a contraction pass?"
    bad_w = [d for d in dots_w
             if tokens in tuple(int(x) for x in d.split(","))]
    assert not bad_w, f"W compiled token-dim (activation) matmuls: {bad_w}"


@pytest.mark.parametrize("schedule,n_stages,m",
                         [("zb-h1", 1, 4), ("zb-h1", 2, 8),
                          ("zb-h1", 4, 4), ("zb-h2", 4, 8)])
def test_auto_split_transparency(schedule, n_stages, m):
    """zb-h1/zb-h2 + split_stage="auto" on PipelinedLM: loss and every
    grad leaf equal the fused-backward 1f1b run of the same params."""
    cfg = _cfg(n_stages)
    model = PipelinedLM(cfg, n_stages)
    sp, prep, postp = model.init(jax.random.key(0))
    stacked = stack_stage_params(sp)
    tokens = jax.random.randint(jax.random.key(1), (2 * m, cfg.seq_len),
                                0, cfg.vocab, jnp.int32)
    x, n_rows = mb.stack_scatter(
        {"tokens": tokens, "targets": jnp.roll(tokens, -1, -1)}, m)
    w = mb.valid_row_mask(x, n_rows)
    mesh = make_mesh(n_stages, 1, devices=jax.devices()[:n_stages])

    ref = ScheduledPipeline(mesh, model.stage_fn, pre_fn=model.pre_fn,
                            post_fn=model.loss_post_fn, checkpoint="never",
                            schedule="1f1b")
    l_ref, g_ref = jax.jit(ref.loss_and_grad)(
        stacked, prep, postp, x, w, key=jax.random.key(9))

    zb = ScheduledPipeline(mesh, model.stage_fn, pre_fn=model.pre_fn,
                           post_fn=model.loss_post_fn, checkpoint="never",
                           schedule=schedule, split_stage="auto")
    l_zb, g_zb = jax.jit(zb.loss_and_grad)(
        stacked, prep, postp, x, w, key=jax.random.key(9))

    np.testing.assert_allclose(float(l_zb), float(l_ref), rtol=1e-5)
    for got, exp in zip(g_zb, g_ref):
        _grad_trees_close(got, exp)


def test_auto_split_unused_param_leaf_gets_zero_grad():
    """A param leaf the stage never touches still appears in W's output
    tree, as zeros — same contract as the fused vjp."""
    def stage(p, h, ctx):
        return jnp.tanh(h @ p["w"])

    p = {"w": jax.random.normal(jax.random.key(0), (8, 8)),
         "dead": jnp.ones((5,))}
    h = jax.random.normal(jax.random.key(1), (3, 8))
    ctx = StageCtx(key=jax.random.key(2))
    seed = jnp.ones_like(h)

    split = split_backward_stage(stage)
    zs = split.zs_fn(p, h)
    _, vjp_fn, taps = jax.vjp(
        lambda hh, zz: split.tapped_fn(p, hh, ctx, zz), h, zs,
        has_aux=True)
    _, gzs = vjp_fn(seed)
    gp = split.wgrad_fn(taps, gzs)
    gp_ref, _ = jax.vjp(lambda pp, hh: stage(pp, hh, ctx), p, h)[1](seed)
    np.testing.assert_array_equal(np.asarray(gp["dead"]),
                                  np.zeros((5,)))
    _grad_trees_close(gp, gp_ref)


def test_auto_split_chain_free_fallback_for_cascaded_contractions():
    """A region output whose only consumer is ANOTHER param contraction
    ((h @ w1) @ w2 — the shape of the TP block's attention internals)
    cannot chain through the W replay: the replayed product would be
    param-dependent x param-dependent, which has no linear transpose.
    The plan must detect this at build time and fall back to injecting
    every region output, staying gradient-exact."""
    def stage(p, h, ctx):
        return (h @ p["w1"]) @ p["w2"]

    p = {"w1": jax.random.normal(jax.random.key(0), (8, 8)),
         "w2": jax.random.normal(jax.random.key(1), (8, 8))}
    h = jax.random.normal(jax.random.key(2), (3, 8))
    ctx = StageCtx(key=jax.random.key(3))
    seed = jnp.ones_like(h)

    split = split_backward_stage(stage)
    zs = split.zs_fn(p, h)
    assert len(zs) == 2, "both contractions must inject — nothing chained"
    _, vjp_fn, taps = jax.vjp(
        lambda hh, zz: split.tapped_fn(p, hh, ctx, zz), h, zs,
        has_aux=True)
    gh, gzs = vjp_fn(seed)
    gp = split.wgrad_fn(taps, gzs)
    gp_ref, gh_ref = jax.vjp(
        lambda pp, hh: stage(pp, hh, ctx), p, h)[1](seed)
    np.testing.assert_allclose(np.asarray(gh), np.asarray(gh_ref),
                               rtol=1e-5, atol=1e-6)
    _grad_trees_close(gp, gp_ref)


def test_auto_split_rejects_param_only_output():
    """A stage returning a params-derived value (no data dependence)
    cannot split — B would need the params it is constant in."""
    def bad(p, h, ctx):
        return p["w"] * 2.0

    p = {"w": jnp.ones((3, 4))}
    h = jnp.ones((3, 4))
    split = split_backward_stage(bad)
    with pytest.raises(SplitUnsupported):
        split.zs_fn(p, h)


def test_auto_split_rejects_nonlinear_param_entry():
    """Params must enter linearly up to the first param*data contraction
    — ``h @ exp(w)`` has no linear-transpose W region."""
    def bad(p, h, ctx):
        return h @ jnp.exp(p["w"])

    p = {"w": jnp.ones((4, 4))}
    h = jnp.ones((3, 4))
    split = split_backward_stage(bad)
    with pytest.raises(SplitUnsupported):
        split.zs_fn(p, h)


def test_phased_auto_split_whole_program_census():
    """Acceptance: zb-h1 + split_stage="auto" + phase_compile=True — the
    phase program is accepted (fbw3 steady state) and the compiled
    whole-program HLO contains ZERO dispatch conditionals (arity >= 3
    ``conditional``s from lax.switch); role conditionals (arity 2) from
    masking may remain."""
    n_stages, m = 2, 4
    cfg = _cfg(n_stages, dropout=0.0)
    model = PipelinedLM(cfg, n_stages)
    sp, prep, postp = model.init(jax.random.key(0))
    stacked = stack_stage_params(sp)
    tokens = jax.random.randint(jax.random.key(1), (2 * m, cfg.seq_len),
                                0, cfg.vocab, jnp.int32)
    x, n_rows = mb.stack_scatter(
        {"tokens": tokens, "targets": jnp.roll(tokens, -1, -1)}, m)
    w = mb.valid_row_mask(x, n_rows)
    mesh = make_mesh(n_stages, 1, devices=jax.devices()[:n_stages])
    pipe = ScheduledPipeline(mesh, model.stage_fn, pre_fn=model.pre_fn,
                             post_fn=model.loss_post_fn,
                             checkpoint="never", schedule="zb-h1",
                             split_stage="auto", phase_compile=True)
    assert pipe._phase_program(m) is not None

    hlo = jax.jit(pipe.loss_and_grad).lower(
        stacked, prep, postp, x, w, key=jax.random.key(9)
    ).compile().as_text()
    dispatch = [g for g in re.findall(r"branch_computations=\{([^}]*)\}",
                                      hlo)
                if g.count(",") + 1 >= 3]
    assert not dispatch, (
        f"phased zb-h1 split program kept {len(dispatch)} dispatch "
        f"conditionals")
