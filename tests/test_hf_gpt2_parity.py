"""Numerical parity of the GPT-2 family against huggingface transformers.

Loads ONE weight set into ``transformers.GPT2Model`` (CPU torch — the
de-facto reference implementation of the architecture) and this package's
``GPT2Embed``/``PreLNBlock``/final-LN stack, asserting the hidden states
match to float32 tolerance. Pins: Conv1D weight orientation (HF's [in, out]
equals this package's right-multiply convention), gelu_new (the "gelu_tanh" activation variant), pre-LN residual placement, causal masking, and
learned token+position embeddings.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax
import jax.numpy as jnp

from pipe_tpu.core.partition import StageCtx
from pipe_tpu.models.gpt2 import GPT2Config, GPT2Embed
from pipe_tpu.ops.layers import LayerNorm, PreLNBlock

D, H, L, FF, SEQ, VOCAB, BATCH = 16, 2, 2, 64, 12, 50, 3


def hf_model():
    cfg = transformers.GPT2Config(
        vocab_size=VOCAB, n_positions=64, n_embd=D, n_layer=L, n_head=H,
        n_inner=FF, activation_function="gelu_new", resid_pdrop=0.0,
        embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    return transformers.GPT2Model(cfg).eval()


def params_from_hf(model):
    """(embed_params, [block_params...], ln_f_params) from HF's state dict.

    HF GPT-2 uses Conv1D (weight [in, out], y = x @ W + b) — the SAME
    orientation as this package's Linear, so no transposes anywhere.
    """
    sd = {k: jnp.asarray(v.detach().numpy())
          for k, v in model.state_dict().items()}
    embed = {"wte": sd["wte.weight"], "wpe": sd["wpe.weight"]}
    blocks = []
    for i in range(L):
        p = f"h.{i}."
        ca_w, ca_b = sd[p + "attn.c_attn.weight"], sd[p + "attn.c_attn.bias"]
        blocks.append({
            "attn": {"wq": ca_w[:, :D], "wk": ca_w[:, D:2 * D],
                     "wv": ca_w[:, 2 * D:],
                     "bq": ca_b[:D], "bk": ca_b[D:2 * D], "bv": ca_b[2 * D:],
                     "wo": sd[p + "attn.c_proj.weight"],
                     "bo": sd[p + "attn.c_proj.bias"]},
            "ff1": {"w": sd[p + "mlp.c_fc.weight"],
                    "b": sd[p + "mlp.c_fc.bias"]},
            "ff2": {"w": sd[p + "mlp.c_proj.weight"],
                    "b": sd[p + "mlp.c_proj.bias"]},
            "ln1": {"g": sd[p + "ln_1.weight"], "b": sd[p + "ln_1.bias"]},
            "ln2": {"g": sd[p + "ln_2.weight"], "b": sd[p + "ln_2.bias"]},
        })
    ln_f = {"g": sd["ln_f.weight"], "b": sd["ln_f.bias"]}
    return embed, blocks, ln_f


def jax_forward(embed_p, block_ps, ln_f_p, tokens, wpe=None):
    """The ONE embed -> blocks -> final-LN stack both tests validate."""
    cfg = GPT2Config(vocab=VOCAB, d_model=D, nhead=H, d_ff=FF, n_layers=L,
                     seq_len=64, dropout=0.0)
    if wpe is not None:
        embed_p = {**embed_p, "wpe": wpe}
    h = GPT2Embed(cfg).apply(embed_p, jnp.asarray(tokens))
    block = PreLNBlock(D, H, FF, dropout=0.0, causal=True,
                       activation="gelu_tanh")
    for p in block_ps:
        h = block.apply(p, h, ctx=StageCtx())
    return LayerNorm().apply(ln_f_p, h)


def test_gpt2_hidden_states_match_hf():
    model = hf_model()
    embed_p, block_ps, ln_f_p = params_from_hf(model)

    tokens = np.random.default_rng(1).integers(0, VOCAB, size=(BATCH, SEQ))
    with torch.no_grad():
        exp = model(torch.from_numpy(tokens)).last_hidden_state.numpy()

    got = jax_forward(embed_p, block_ps, ln_f_p, tokens)
    np.testing.assert_allclose(np.asarray(got), exp, rtol=3e-5, atol=3e-5)


def test_gpt2_grads_match_hf():
    """d(loss)/d(position-embedding) parity through the whole stack."""
    model = hf_model()
    embed_p, block_ps, ln_f_p = params_from_hf(model)
    tokens = np.random.default_rng(2).integers(0, VOCAB, size=(BATCH, SEQ))

    wpe = model.wpe.weight
    model.zero_grad()
    model(torch.from_numpy(tokens)).last_hidden_state.pow(2).sum().backward()
    exp = wpe.grad.numpy()

    got = jax.grad(lambda w: jnp.sum(
        jax_forward(embed_p, block_ps, ln_f_p, tokens, wpe=w) ** 2))(
        embed_p["wpe"])
    np.testing.assert_allclose(np.asarray(got), exp, rtol=2e-4, atol=2e-4)
