"""The serve subsystem (pipe_tpu/serve): continuous batching over slots.

Gold contract, same shape as the generator suites: serving a request
through the slot engine — staggered arrivals, mixed prompt lengths,
whatever the other slots are doing — yields bitwise the tokens of a
one-shot batch-1 ``Generator.generate`` on that prompt. On top of the
parity pin: the zero-recompile pin (the decode program's trace counter
stays at 1 across all traffic), and the queue semantics (backpressure,
deadlines, cancellation, priority).
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.inference import GenerationConfig, Generator
from pipe_tpu.inference.generate import sequence_lengths
from pipe_tpu.inference.pipelined import PipelinedGenerator
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
from pipe_tpu.obs.telemetry import get_registry, percentile_exact
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.spmd import stack_stage_params
from pipe_tpu.serve import (BucketSpec, QueueFull, RequestQueue,
                            RingSlotBackend, ServeEngine,
                            SingleDeviceSlotBackend)

CFG = LMConfig(vocab=89, d_model=32, nhead=4, d_ff=64, n_layers=4,
               seq_len=32, dropout=0.0)


@pytest.fixture(scope="module")
def model_and_params():
    model = PipelinedLM(CFG, n_stages=2)
    return model, model.init(jax.random.key(0))


def _one_shot_refs(model, params, prompts, gen_cfg, seed):
    g = Generator(model, gen_cfg)
    return [np.asarray(g.generate(params,
                                  jnp.asarray(p, jnp.int32)[None],
                                  jax.random.key(seed)))[0]
            for p in prompts]


def _mixed_prompts(lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [list(rng.randint(1, CFG.vocab, size=n)) for n in lengths]


def _make_backend(kind, model, params, gen_cfg, **kw):
    if kind == "single":
        return SingleDeviceSlotBackend(model, params, num_slots=2,
                                       max_len=16, gen=gen_cfg,
                                       buckets=BucketSpec.of(4, 8), **kw)
    sp, pre, post = params
    mesh = make_mesh(2, 1)
    return RingSlotBackend(mesh, model, stack_stage_params(sp), pre, post,
                           max_len=16, gen=gen_cfg,
                           buckets=BucketSpec.of(4, 8), **kw)


# ---------------------------------------------------------------------------
# the parity pin + the zero-recompile pin


@pytest.mark.parametrize("kind", ["single", "ring"])
def test_staggered_arrivals_match_one_shot_generator(kind,
                                                     model_and_params):
    """Mixed prompt lengths arriving mid-flight, greedy: every response
    is bitwise the one-shot batch-1 Generator output, and the decode
    program traced exactly once (zero steady-state recompiles)."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=6, temperature=0.0)
    prompts = _mixed_prompts((3, 5, 4, 7, 5))
    refs = _one_shot_refs(model, params, prompts, gen_cfg, seed=7)

    backend = _make_backend(kind, model, params, gen_cfg)
    trace_counter = ("serve.engine.decode_traces" if kind == "single"
                     else "serve.ring.decode_traces")
    traces0 = get_registry().counter(trace_counter).value

    eng = ServeEngine(backend)
    ids = [eng.submit(prompts[0], seed=7).id]
    eng.tick()
    ids += [eng.submit(p, seed=7).id for p in prompts[1:3]]
    eng.tick()
    ids += [eng.submit(p, seed=7).id for p in prompts[3:]]
    eng.run_until_idle()

    for i, rid in enumerate(ids):
        resp = eng.response(rid)
        assert resp.status == "ok" and resp.finish_reason == "length"
        np.testing.assert_array_equal(np.asarray(resp.tokens), refs[i])
        assert resp.ttft is not None and resp.latency >= resp.ttft
    assert get_registry().counter(trace_counter).value - traces0 == 1
    # two buckets touched -> exactly two prefill programs
    assert backend.program_stats()["prefill_programs"] == 2


def test_chunked_decode_parity(model_and_params):
    """decode_chunk=3 chops the same carry chain into K-step ticks —
    parity is unchanged."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=6, temperature=0.0)
    prompts = _mixed_prompts((3, 5, 4, 7, 5))
    refs = _one_shot_refs(model, params, prompts, gen_cfg, seed=7)
    backend = _make_backend("single", model, params, gen_cfg,
                            decode_chunk=3)
    resps = ServeEngine(backend).serve(prompts, seeds=[7] * len(prompts))
    for resp, ref in zip(resps, refs):
        np.testing.assert_array_equal(np.asarray(resp.tokens), ref)


def test_sampled_decode_parity(model_and_params):
    """temperature>0: the slot key chain replicates the batch-1
    Generator chain exactly, so even sampled tokens are bitwise equal."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=6, temperature=0.8,
                               top_k=12)
    prompts = _mixed_prompts((3, 5, 4))
    refs = _one_shot_refs(model, params, prompts, gen_cfg, seed=5)
    backend = _make_backend("single", model, params, gen_cfg)
    resps = ServeEngine(backend).serve(prompts, seeds=[5] * len(prompts))
    for resp, ref in zip(resps, refs):
        np.testing.assert_array_equal(np.asarray(resp.tokens), ref)


def test_serve_eos_retires_early(model_and_params):
    """With eos_token_id set, the engine retires the slot at the EOS
    token and the emitted tokens are the one-shot run truncated at its
    sequence length."""
    model, params = model_and_params
    probe = GenerationConfig(max_new_tokens=8, temperature=0.0)
    prompts = _mixed_prompts((4, 6))
    free = _one_shot_refs(model, params, prompts, probe, seed=7)
    eos = int(free[0][2])   # a token greedy decoding actually emits

    gen_cfg = GenerationConfig(max_new_tokens=8, temperature=0.0,
                               eos_token_id=eos)
    refs = _one_shot_refs(model, params, prompts, gen_cfg, seed=7)
    lens = [int(sequence_lengths(jnp.asarray(r)[None], eos)[0])
            for r in refs]
    backend = _make_backend("single", model, params, gen_cfg)
    resps = ServeEngine(backend).serve(prompts, seeds=[7, 7])
    for resp, ref, n in zip(resps, refs, lens):
        np.testing.assert_array_equal(np.asarray(resp.tokens), ref[:n])
        if resp.finish_reason == "eos":
            assert resp.tokens[-1] == eos
        assert len(resp.tokens) == n


def test_validate_rejects_unservable_requests(model_and_params):
    """Bad requests bounce at submit — they never cost a slot."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=6, temperature=0.0)
    eng = ServeEngine(_make_backend("single", model, params, gen_cfg))
    with pytest.raises(ValueError, match="bucket"):
        eng.submit(list(range(1, 10)))          # longest bucket is 8
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit([1, 2, 3], max_new_tokens=60)
    assert eng.queue.depth == 0


# ---------------------------------------------------------------------------
# queue semantics: backpressure, deadlines, cancellation, priority


def test_backpressure_rejects_when_full(model_and_params):
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=4, temperature=0.0)
    backend = _make_backend("single", model, params, gen_cfg)
    eng = ServeEngine(backend, RequestQueue(capacity=2))
    reg = get_registry()
    rejected0 = reg.counter("serve.engine.rejected").value
    eng.submit([1, 2, 3])
    eng.submit([4, 5])
    with pytest.raises(QueueFull):
        eng.submit([6, 7, 8])
    assert reg.counter("serve.engine.rejected").value - rejected0 == 1
    # draining frees capacity again
    eng.run_until_idle()
    eng.submit([6, 7, 8])
    eng.run_until_idle()


def test_deadline_timeout_retires_running_slot(model_and_params):
    """A running request whose deadline passes is retired mid-stream:
    status=timeout, partial tokens kept, slot freed for the next
    admission."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=50, temperature=0.0)
    backend = SingleDeviceSlotBackend(model, params, num_slots=1,
                                      max_len=64, gen=gen_cfg,
                                      buckets=BucketSpec.of(4))
    t = [0.0]
    eng = ServeEngine(backend, RequestQueue(clock=lambda: t[0]))
    doomed = eng.submit([1, 2, 3], timeout_s=5.0)
    eng.tick()  # admit + first decode
    assert eng.live_slots == 1
    t[0] = 6.0
    finished = eng.tick()
    assert [r.request_id for r in finished] == [doomed.id]
    resp = eng.response(doomed.id)
    assert resp.status == "timeout" and resp.finish_reason == "deadline"
    assert len(resp.tokens) >= 1           # partial output survives
    assert eng.live_slots == 0
    # the freed slot admits the next request
    ok = eng.submit([4, 5, 6], max_new_tokens=3)
    eng.run_until_idle()
    assert eng.response(ok.id).status == "ok"


def test_deadline_timeout_reaps_queued_request(model_and_params):
    """A request that dies WAITING is reaped before ever costing a
    prefill: no tokens, no ttft."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=4, temperature=0.0)
    backend = _make_backend("single", model, params, gen_cfg)
    t = [0.0]
    eng = ServeEngine(backend, RequestQueue(clock=lambda: t[0]))
    req = eng.submit([1, 2, 3], timeout_s=1.0)
    t[0] = 2.0
    eng.tick()
    resp = eng.response(req.id)
    assert resp.status == "timeout" and resp.tokens == []
    assert resp.ttft is None


def test_cancellation_frees_slot(model_and_params):
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=50, temperature=0.0)
    backend = SingleDeviceSlotBackend(model, params, num_slots=1,
                                      max_len=64, gen=gen_cfg,
                                      buckets=BucketSpec.of(4))
    eng = ServeEngine(backend)
    victim = eng.submit([1, 2, 3])
    queued = eng.submit([4, 5, 6], max_new_tokens=3)
    eng.tick()
    assert eng.live_slots == 1 and eng.queue.depth == 1
    assert eng.cancel(victim.id)
    eng.run_until_idle()
    v = eng.response(victim.id)
    assert v.status == "cancelled" and v.finish_reason == "cancelled"
    assert eng.response(queued.id).status == "ok"
    # cancelling a finished/unknown id is a no-op
    assert not eng.cancel(victim.id)


def test_cancel_while_queued_never_prefills(model_and_params):
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=4, temperature=0.0)
    backend = SingleDeviceSlotBackend(model, params, num_slots=1,
                                      max_len=16, gen=gen_cfg,
                                      buckets=BucketSpec.of(4))
    eng = ServeEngine(backend)
    running = eng.submit([1, 2], max_new_tokens=4)
    waiting = eng.submit([3, 4], max_new_tokens=4)
    eng.tick()
    eng.cancel(waiting.id)
    eng.run_until_idle()
    assert eng.response(waiting.id).status == "cancelled"
    assert eng.response(waiting.id).tokens == []
    assert eng.response(running.id).status == "ok"


def test_priority_queue_orders_admissions():
    q = RequestQueue(capacity=8, policy="priority", clock=lambda: 0.0)
    a = q.submit([1], max_new_tokens=1, seed=0, priority=0)
    b = q.submit([2], max_new_tokens=1, seed=0, priority=5)
    c = q.submit([3], max_new_tokens=1, seed=0, priority=5)
    d = q.submit([4], max_new_tokens=1, seed=0, priority=1)
    # highest priority first; FIFO among equals
    assert [q.pop().id for _ in range(4)] == [b.id, c.id, d.id, a.id]


def test_fifo_queue_is_fifo():
    q = RequestQueue(capacity=4, clock=lambda: 0.0)
    ids = [q.submit([i], max_new_tokens=1, seed=0).id for i in range(3)]
    assert [q.pop().id for _ in range(3)] == ids


# ---------------------------------------------------------------------------
# buckets + program-cache hygiene


def test_bucket_spec_selection_and_padding():
    spec = BucketSpec.of(4, 8, 16)
    assert spec.bucket_for(1) == 4
    assert spec.bucket_for(4) == 4
    assert spec.bucket_for(5) == 8
    assert spec.bucket_for(16) == 16
    with pytest.raises(ValueError):
        spec.bucket_for(17)
    padded, n = spec.pad([7, 7, 7, 7, 7], pad_token_id=9)
    assert padded == [7, 7, 7, 7, 7, 9, 9, 9] and n == 5
    assert spec.max_len == 16


def test_bucket_pow2_ladder():
    spec = BucketSpec.pow2(min_len=8, max_len=100)
    assert spec.lengths == (8, 16, 32, 64, 100)


def test_unbucketed_prefill_warns_past_threshold(model_and_params):
    """bucketing disabled + many distinct prompt lengths -> loud
    RuntimeWarning when the program cache blows past the threshold."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=2, temperature=0.0)
    backend = SingleDeviceSlotBackend(model, params, num_slots=1,
                                      max_len=16, gen=gen_cfg,
                                      buckets=None, shape_cache_warn=2)
    eng = ServeEngine(backend)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        for n in (2, 3, 4):
            eng.serve([_mixed_prompts((n,))[0]])
        hits = [x for x in w if issubclass(x.category, RuntimeWarning)
                and "bucketing DISABLED" in str(x.message)]
    assert len(hits) == 1
    assert backend.program_stats()["prefill_programs"] == 3


def test_generator_shape_cache_counters(model_and_params):
    """Satellite: the plain Generator now counts its per-shape jit cache
    and warns when it grows past the threshold."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=2, temperature=0.0)
    g = Generator(model, gen_cfg, shape_cache_warn=2)
    reg = get_registry()
    h0 = reg.counter("serve.program_cache_hits").value
    m0 = reg.counter("serve.program_cache_misses").value
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        for shape in ((1, 4), (1, 5), (1, 4), (1, 6)):
            g.generate(params, jnp.ones(shape, jnp.int32))
        hits = [x for x in w if issubclass(x.category, RuntimeWarning)]
    assert reg.counter("serve.program_cache_misses").value - m0 == 3
    assert reg.counter("serve.program_cache_hits").value - h0 == 1
    assert len(hits) == 1


# ---------------------------------------------------------------------------
# EOS done-masking in the underlying generators (satellite)


def test_generator_eos_masks_and_measures_lengths(model_and_params):
    """eos_token_id: tokens match the unmasked run up to (and
    including) the first EOS, pad after; sequence_lengths and
    generate_with_lengths agree."""
    model, params = model_and_params
    prompt = jnp.asarray(_mixed_prompts((5,), seed=3)[0],
                         jnp.int32)[None]
    free = np.asarray(Generator(
        model, GenerationConfig(max_new_tokens=8,
                                temperature=0.0)).generate(params, prompt))
    eos = int(free[0, 2])                      # output is new tokens only
    hit = int(np.flatnonzero(free[0] == eos)[0])

    gen_cfg = GenerationConfig(max_new_tokens=8, temperature=0.0,
                               eos_token_id=eos, pad_token_id=0)
    out, lens = Generator(model, gen_cfg).generate_with_lengths(params,
                                                                prompt)
    out = np.asarray(out)
    np.testing.assert_array_equal(out[0, :hit + 1], free[0, :hit + 1])
    assert (out[0, hit + 1:] == 0).all()
    assert int(lens[0]) == hit + 1
    np.testing.assert_array_equal(
        np.asarray(sequence_lengths(jnp.asarray(out), eos)),
        np.asarray(lens))


def test_generator_eos_none_is_unchanged(model_and_params):
    """eos_token_id=None must trace the exact pre-satellite program —
    same outputs, full-width lengths."""
    model, params = model_and_params
    prompt = jnp.ones((2, 4), jnp.int32)
    g = Generator(model, GenerationConfig(max_new_tokens=5,
                                          temperature=0.0))
    out, lens = g.generate_with_lengths(params, prompt)
    assert np.asarray(lens).tolist() == [5, 5]
    assert sequence_lengths(out, None).tolist() == [5, 5]


def test_pipelined_eos_matches_single_device(model_and_params):
    """EOS masking through the ring: bitwise vs the single-device
    Generator with the same eos, including the pad tail."""
    model, params = model_and_params
    sp, pre, post = params
    prompt = jax.random.randint(jax.random.key(2), (2, 6), 1, CFG.vocab,
                                jnp.int32)
    free = np.asarray(Generator(
        model, GenerationConfig(max_new_tokens=6,
                                temperature=0.0)).generate(params, prompt))
    eos = int(free[0, 3])
    gen_cfg = GenerationConfig(max_new_tokens=6, temperature=0.0,
                               eos_token_id=eos)
    ref = np.asarray(Generator(model, gen_cfg).generate(params, prompt))
    mesh = make_mesh(2, 1)
    pg = PipelinedGenerator(mesh, model, gen_cfg)
    got, lens = pg.generate_with_lengths(stack_stage_params(sp), pre,
                                         post, prompt)
    np.testing.assert_array_equal(np.asarray(got), ref)
    np.testing.assert_array_equal(
        np.asarray(lens), np.asarray(sequence_lengths(jnp.asarray(ref),
                                                      eos)))


def test_generation_config_validates_eos():
    with pytest.raises(ValueError, match="eos_token_id"):
        GenerationConfig(eos_token_id=-1)
    with pytest.raises(ValueError, match="pad_token_id"):
        GenerationConfig(pad_token_id=-2)
    with pytest.raises(ValueError, match="beam"):
        GenerationConfig(num_beams=2, eos_token_id=3)


def test_sequence_lengths_basics():
    toks = jnp.asarray([[5, 2, 7, 7], [1, 1, 1, 2], [3, 3, 3, 3]],
                       jnp.int32)
    assert sequence_lengths(toks, 2).tolist() == [2, 4, 4]
    assert sequence_lengths(toks, None).tolist() == [4, 4, 4]


def test_percentile_exact():
    vals = [5.0, 1.0, 3.0, 2.0, 4.0]
    assert percentile_exact(vals, 0.5) == 3.0
    assert percentile_exact(vals, 0.99) == 5.0
    assert percentile_exact(vals, 0.0) == 1.0
    assert percentile_exact([], 0.5) == 0.0
