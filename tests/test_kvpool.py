"""Paged KV pool (pipe_tpu/serve/kvpool.py): blocks, sharing, parity.

The contract under test, in order of importance:

* **Bitwise parity.** Paged decode — gather the slot's block view, run
  the UNCHANGED layer decode, scatter the new rows — matches the slab
  backends and the one-shot batch-1 Generator token-for-token, greedy
  AND sampled, on both backends, including through copy-on-write
  prefix forks (the tentpole acceptance pin).
* **One program, any shape.** Paged mode compiles ONE chunked prefill
  program and ONE decode program regardless of prompt-length mix —
  trace counters pin zero steady-state recompiles where the slab path
  keys a prefill program per bucket.
* **Allocator honesty.** Every admit/release/evict keeps
  ``free + in_use + evictable == total``; a released slot's table row
  is zeroed (sacrificial) before its blocks can be reallocated; failed
  prefills unpublish their half-written cache entries.
* **Admission control.** Block availability gates admission: requests
  park at the head of the queue (FIFO preserved) until blocks free,
  counted by ``serve.kv.admission_blocked``.
* **Opt-out is absent.** ``prefix_cache=False`` changes host policy
  only — the compiled decode HLO is byte-identical.

Pool-only tests are pure host allocator checks (no device programs);
the parity tests reuse the tiny-model fixture discipline of
``tests/test_serve.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.inference import GenerationConfig, Generator
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
from pipe_tpu.obs.telemetry import get_registry
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.spmd import stack_stage_params
from pipe_tpu.serve import (KvPool, PoolExhausted, RequestQueue, Router,
                            RouterPolicy, ServeEngine,
                            SingleDeviceSlotBackend, block_demand)
from pipe_tpu.serve.ring import RingSlotBackend

CFG = LMConfig(vocab=89, d_model=32, nhead=4, d_ff=64, n_layers=4,
               seq_len=32, dropout=0.0)


@pytest.fixture(scope="module")
def model_and_params():
    model = PipelinedLM(CFG, n_stages=2)
    return model, model.init(jax.random.key(0))


def _one_shot_refs(model, params, prompts, gen_cfg, seed):
    g = Generator(model, gen_cfg)
    return [np.asarray(g.generate(params,
                                  jnp.asarray(p, jnp.int32)[None],
                                  jax.random.key(seed)))[0]
            for p in prompts]


def _mixed_prompts(lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [list(rng.randint(1, CFG.vocab, size=n)) for n in lengths]


def _paged_backend(kind, model, params, gen_cfg, **kw):
    if kind == "single":
        kw.setdefault("num_slots", 2)
        return SingleDeviceSlotBackend(model, params, max_len=16,
                                       gen=gen_cfg, kv_block_size=4,
                                       prefill_chunk=4, **kw)
    sp, pre, post = params
    mesh = make_mesh(2, 1)
    return RingSlotBackend(mesh, model, stack_stage_params(sp), pre, post,
                           max_len=16, gen=gen_cfg, kv_block_size=4,
                           prefill_chunk=4, **kw)


def _pool(**kw):
    kw.setdefault("num_blocks", 9)
    kw.setdefault("block_size", 4)
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_len", 16)
    return KvPool(**kw)


def _conserved(pool):
    s = pool.stats()
    return (s["blocks_free"] + s["blocks_in_use"] + s["blocks_evictable"]
            == s["blocks_total"])


# ---------------------------------------------------------------------------
# host allocator (no device programs)


def test_block_demand_and_validation():
    # last sampled token's row is never written, hence the -1
    assert block_demand(5, 6, 4) == 3     # 10 rows
    assert block_demand(4, 1, 4) == 1     # 4 rows
    assert block_demand(1, 16, 4) == 4    # 16 rows
    with pytest.raises(ValueError, match="power of two"):
        _pool(block_size=3)
    with pytest.raises(ValueError, match="sacrificial"):
        _pool(num_blocks=1)


def test_admit_release_accounting_and_sacrificial_row():
    pool = _pool(prefix_cache=False)
    prompt = list(range(1, 6))
    adm = pool.admit(0, prompt, 6)        # 10 rows -> 3 blocks
    assert len(adm.blocks) == 3
    assert 0 not in adm.blocks            # block 0 never allocated
    assert pool.free_blocks == 5 and _conserved(pool)
    # reserved entries head the row; the unreserved tail is sacrificial
    assert list(pool.table[0][:3]) == adm.blocks
    assert not pool.table[0][3:].any()
    with pytest.raises(RuntimeError, match="admitted twice"):
        pool.admit(0, prompt, 6)
    pool.release(0)
    assert not pool.table[0].any()        # dead slot -> block 0 forever
    assert pool.free_blocks == 8 and _conserved(pool)
    pool.release(0)                       # idempotent


def test_prefix_reuse_and_cow_fork_plan():
    pool = _pool(num_blocks=17)
    shared = list(range(1, 9))            # exactly 2 full blocks
    a = pool.admit(0, shared + [20, 21], 4, chunk=4)
    assert a.prefix_hits == 0 and not a.cow_forks
    # same 8-token prefix, different tail: both full blocks reused
    # read-only, prefill resumes at the chunk boundary past them
    b = pool.admit(1, shared + [30], 4, chunk=4)
    assert b.prefix_hits == 2 and not b.cow_forks
    assert b.resume_from == 8
    assert b.blocks[:2] == a.blocks[:2]   # physically shared
    assert pool.stats()["shared_blocks"] == 2
    pool.release(0)
    pool.release(1)
    # identical FULL-hit prompt: the recompute tail (position plen-1)
    # falls inside the last shared block -> that block forks, the rest
    # stay read-only shares
    c = pool.admit(0, shared, 4, chunk=4)
    assert c.prefix_hits == 2 and len(c.cow_forks) == 1
    assert c.resume_from == 4
    assert c.blocks[0] == a.blocks[0]     # block 1 still shared
    assert c.blocks[1] != a.blocks[1]     # block 2 forked private
    assert _conserved(pool)


def test_release_failed_unpublishes_registered_entries():
    pool = _pool()
    prompt = list(range(1, 9))
    pool.admit(0, prompt, 4, chunk=4)
    assert pool.cached_prefix_blocks(prompt) == 2
    pool.release(0, failed=True)          # prefill died mid-write
    assert pool.cached_prefix_blocks(prompt) == 0
    assert pool.free_blocks == 8 and _conserved(pool)


def test_lru_eviction_and_invalidate():
    reg = get_registry()
    pool = _pool(num_blocks=7, num_slots=3, max_len=32)  # 6 allocatable
    p1, p2 = list(range(1, 9)), list(range(40, 48))
    pool.admit(0, p1, 1, chunk=4)             # 2 blocks, both cached
    pool.release(0)                           # refs 0 -> LRU, not free
    assert pool.free_blocks == 4 and pool.evictable_blocks == 2
    pool.admit(1, p2, 1, chunk=4)
    pool.release(1)
    assert pool.free_blocks == 2 and pool.evictable_blocks == 4
    # demand 6 > free 2: eviction reclaims the OLDEST entries (p1's)
    ev0 = reg.counter("serve.kv.evictions").value
    pool.admit(2, list(range(60, 82)), 2, chunk=4)   # 23 rows -> 6 blocks
    assert reg.counter("serve.kv.evictions").value - ev0 == 4
    assert pool.cached_prefix_blocks(p1) == 0
    pool.release(2)
    # invalidate: refcount-0 cached blocks go straight to the free list
    pool2 = _pool()
    pool2.admit(0, p1, 1, chunk=4)
    pool2.release(0)
    assert pool2.invalidate(pool2.prefix_hashes(p1)) == 2
    assert pool2.evictable_blocks == 0 and pool2.free_blocks == 8
    assert _conserved(pool2)


def test_pool_exhausted_detail_and_can_admit():
    pool = _pool(num_blocks=4)            # 3 allocatable
    assert pool.can_admit(5, 6) is True   # 10 rows -> 3 blocks, exact fit
    assert pool.can_admit(9, 8) is False  # 16 rows -> 4 blocks: never
    pool.admit(0, [1, 2, 3, 4, 5], 6)     # 3 blocks: pool now empty
    assert pool.can_admit(2, 2) is False
    with pytest.raises(PoolExhausted) as ei:
        pool.admit(1, [1, 2], 2)
    assert ei.value.free == 0 and ei.value.total == 3
    assert ei.value.demand == 1
    assert _conserved(pool)


def test_fragmentation_counts_unwritable_tail_rows():
    pool = _pool(prefix_cache=False)
    pool.admit(0, [1, 2, 3], 3)           # 5 rows over 2 blocks (8 rows)
    assert pool.stats()["fragmentation"] == pytest.approx(3 / 8)
    pool.release(0)
    assert pool.stats()["fragmentation"] == 0.0


def test_generation_config_kv_knobs():
    assert GenerationConfig().kv_block_size is None
    assert GenerationConfig().prefix_cache is True
    assert GenerationConfig(kv_block_size=8).kv_block_size == 8
    for bad in (0, 3, 6, -4):
        with pytest.raises(ValueError, match="power of two"):
            GenerationConfig(kv_block_size=bad)


# ---------------------------------------------------------------------------
# parity pins (the tentpole acceptance)


@pytest.mark.parametrize("kind", ["single", "ring"])
def test_paged_staggered_parity_and_one_program(kind, model_and_params):
    """Mixed prompt lengths arriving mid-flight through the PAGED
    backend: bitwise the one-shot Generator, with exactly ONE decode
    trace and ONE chunked-prefill trace across all five lengths (the
    slab path would have compiled one prefill per bucket)."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=6, temperature=0.0)
    prompts = _mixed_prompts((3, 5, 4, 7, 5))
    refs = _one_shot_refs(model, params, prompts, gen_cfg, seed=7)

    backend = _paged_backend(kind, model, params, gen_cfg)
    pre = "serve.engine" if kind == "single" else "serve.ring"
    reg = get_registry()
    d0 = reg.counter(f"{pre}.decode_traces").value
    c0 = reg.counter(f"{pre}.prefill_chunk_traces").value

    eng = ServeEngine(backend)
    ids = [eng.submit(prompts[0], seed=7).id]
    eng.tick()
    ids += [eng.submit(p, seed=7).id for p in prompts[1:3]]
    eng.tick()
    ids += [eng.submit(p, seed=7).id for p in prompts[3:]]
    eng.run_until_idle()

    for i, rid in enumerate(ids):
        resp = eng.response(rid)
        assert resp.status == "ok" and resp.finish_reason == "length"
        np.testing.assert_array_equal(np.asarray(resp.tokens), refs[i])
    assert reg.counter(f"{pre}.decode_traces").value - d0 == 1
    assert reg.counter(f"{pre}.prefill_chunk_traces").value - c0 == 1
    assert backend.program_stats() == {
        "prefill_programs": 1, "decode_chunk": 1, "kv": "paged"}
    # every slot released -> the pool drained back to empty
    assert backend.pool.stats()["blocks_in_use"] == 0


def test_paged_sampled_parity_single(model_and_params):
    """temperature>0 through the paged single-device backend: the chunk
    prefill + sample epilogue replicate the batch-1 Generator key chain,
    so sampled tokens stay bitwise equal."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=6, temperature=0.8,
                               top_k=12)
    prompts = _mixed_prompts((3, 5, 4))
    refs = _one_shot_refs(model, params, prompts, gen_cfg, seed=5)
    backend = _paged_backend("single", model, params, gen_cfg)
    resps = ServeEngine(backend).serve(prompts, seeds=[5] * len(prompts))
    for resp, ref in zip(resps, refs):
        np.testing.assert_array_equal(np.asarray(resp.tokens), ref)


def test_paged_sampled_parity_ring_matches_slab_ring(model_and_params):
    """The ring threads the Generator's split key chain through the
    revolutions (it used to speak its own fold_in chain), so the pin is
    three-way: paged-ring == slab-ring == the one-shot Generator,
    token-for-token."""
    model, params = model_and_params
    sp, pre, post = params
    gen_cfg = GenerationConfig(max_new_tokens=5, temperature=1.0,
                               top_k=8)
    prompts = _mixed_prompts((3, 6, 4), seed=3)
    refs = _one_shot_refs(model, params, prompts, gen_cfg, seed=3)
    mesh = make_mesh(2, 1)
    slab = RingSlotBackend(mesh, model, stack_stage_params(sp), pre,
                           post, max_len=16, gen=gen_cfg)
    want = ServeEngine(slab).serve(prompts, seeds=[3] * len(prompts))
    paged = _paged_backend("ring", model, params, gen_cfg)
    got = ServeEngine(paged).serve(prompts, seeds=[3] * len(prompts))
    for a, b, ref in zip(got, want, refs):
        assert a.status == "ok"
        np.testing.assert_array_equal(np.asarray(a.tokens),
                                      np.asarray(b.tokens))
        np.testing.assert_array_equal(np.asarray(a.tokens), ref)


@pytest.mark.parametrize("kind", ["single", "ring"])
def test_shared_prefix_cow_parity(kind, model_and_params):
    """Requests sharing a system prompt reuse its cached blocks
    (prefix_hits > 0); a repeat of the IDENTICAL prompt forks the block
    its recompute tail rewrites (cow_forks > 0). Both stay bitwise equal
    to cold one-shot references — sharing is invisible to tokens."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=5, temperature=0.0)
    sys_prompt = _mixed_prompts((8,), seed=11)[0]   # exactly 2 blocks
    prompts = [sys_prompt + [3], sys_prompt + [5, 6], sys_prompt,
               sys_prompt]                          # last: full-hit fork
    refs = _one_shot_refs(model, params, prompts, gen_cfg, seed=2)
    backend = _paged_backend(kind, model, params, gen_cfg,
                             kv_pool_blocks=17)
    reg = get_registry()
    h0 = reg.counter("serve.kv.prefix_hits").value
    f0 = reg.counter("serve.kv.cow_forks").value
    resps = ServeEngine(backend).serve(prompts,
                                       seeds=[2] * len(prompts))
    for resp, ref in zip(resps, refs):
        np.testing.assert_array_equal(np.asarray(resp.tokens), ref)
    assert reg.counter("serve.kv.prefix_hits").value - h0 > 0
    assert reg.counter("serve.kv.cow_forks").value - f0 > 0


def test_int8_kv_blocks_top1_agreement(model_and_params):
    """int8 KV blocks (quantize on scatter, dequantize in the gathered
    attention read): tolerance contract, not the bitwise pin — greedy
    tokens should overwhelmingly agree with the fp backend's."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=6, temperature=0.0)
    prompts = _mixed_prompts((5, 7), seed=4)
    refs = _one_shot_refs(model, params, prompts, gen_cfg, seed=0)
    backend = _paged_backend("single", model, params, gen_cfg,
                             kv_dtype="int8")
    resps = ServeEngine(backend).serve(prompts,
                                       seeds=[0] * len(prompts))
    agree = total = 0
    for resp, ref in zip(resps, refs):
        got = np.asarray(resp.tokens)
        agree += int((got == ref[:len(got)]).sum())
        total += len(got)
    assert agree / total >= 0.8, f"int8 agreement {agree}/{total}"


def test_int8_kv_requires_paged_and_single_device(model_and_params):
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=4)
    with pytest.raises(ValueError, match="paged"):
        SingleDeviceSlotBackend(model, params, num_slots=2, max_len=16,
                                gen=gen_cfg, kv_dtype="int8")
    sp, pre, post = params
    with pytest.raises(NotImplementedError, match="single-device"):
        RingSlotBackend(make_mesh(2, 1), model, stack_stage_params(sp),
                        pre, post, max_len=16, gen=gen_cfg,
                        kv_block_size=4, kv_dtype="int8")


def test_prefix_cache_off_decode_hlo_identical(model_and_params):
    """prefix_cache=False is host allocator policy ONLY: the compiled
    paged decode program lowers to byte-identical HLO either way."""
    model, params = model_and_params

    def lowered(prefix_cache):
        gen_cfg = GenerationConfig(max_new_tokens=4, temperature=0.0,
                                   prefix_cache=prefix_cache)
        be = _paged_backend("single", model, params, gen_cfg)
        return be._decode_jit.lower(
            be._block_stack, be._pre, be._post, be._pool_kv,
            jnp.asarray(be.pool.table), be._tok, be._pos,
            be._key_data, be._views, jnp.asarray(True)).as_text()

    assert lowered(True) == lowered(False)


# ---------------------------------------------------------------------------
# admission by block availability


def test_admission_parks_at_head_until_blocks_free(model_and_params):
    """A pool too small for two concurrent requests parks the second at
    the queue head (no slot is burned, FIFO order holds) and admits it
    when the first retires — counted by serve.kv.admission_blocked."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=6, temperature=0.0)
    prompts = _mixed_prompts((5, 4, 6), seed=9)
    refs = _one_shot_refs(model, params, prompts, gen_cfg, seed=1)
    # 5 allocatable blocks; each request needs 3 -> one at a time
    backend = _paged_backend("single", model, params, gen_cfg,
                             kv_pool_blocks=6)
    reg = get_registry()
    b0 = reg.counter("serve.kv.admission_blocked").value
    eng = ServeEngine(backend)
    ids = [eng.submit(p, seed=1).id for p in prompts]
    eng.run_until_idle()
    assert reg.counter("serve.kv.admission_blocked").value - b0 > 0
    for rid, ref in zip(ids, refs):
        resp = eng.response(rid)
        assert resp.status == "ok"
        np.testing.assert_array_equal(np.asarray(resp.tokens), ref)


# ---------------------------------------------------------------------------
# router KV handoff


def test_router_session_remap_invalidates_and_counts(model_and_params):
    """A session remapped off its home replica invalidates the prefix
    blocks it cached there (no stale reuse if it ever maps back) and
    the probe of the new home classifies the handoff warm/cold."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=4, temperature=0.0)
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731

    def engine():
        be = _paged_backend("single", model, params, gen_cfg)
        return ServeEngine(be, RequestQueue(clock=clock))

    engines = [engine(), engine()]
    router = Router(engines, RequestQueue(clock=clock),
                    policy=RouterPolicy(placement="session"))
    prompt = _mixed_prompts((8,), seed=13)[0]       # 2 cacheable blocks

    def serve_one():
        rid = router.submit(prompt, max_new_tokens=4, seed=0,
                            session="alice").id
        for _ in range(100):
            t[0] += 0.01
            router.tick()
            if router.response(rid) is not None:
                return router.response(rid)
        raise AssertionError("request never finished")

    reg = get_registry()
    k0 = {k: reg.counter(f"serve.fleet.kv_handoff_{k}").value
          for k in ("total", "cold", "invalidated")}
    assert serve_one().status == "ok"
    home = router._session_map["alice"]
    home_pool = router.replicas[home].engine.backend.pool
    assert home_pool.cached_prefix_blocks(prompt) == 2
    assert reg.counter("serve.fleet.kv_handoff_total").value == k0["total"]

    router.replicas[home].state = "suspect"         # stop placement home
    assert serve_one().status == "ok"
    assert router._session_map["alice"] != home     # remapped
    assert home_pool.cached_prefix_blocks(prompt) == 0   # invalidated
    assert reg.counter(
        "serve.fleet.kv_handoff_total").value - k0["total"] == 1
    assert reg.counter(
        "serve.fleet.kv_handoff_cold").value - k0["cold"] == 1
    assert reg.counter(
        "serve.fleet.kv_handoff_invalidated").value \
        - k0["invalidated"] == 2
