"""Numerical parity of the ViT block against huggingface transformers.

``transformers.ViTModel``'s encoder layer is pre-LN with EXACT-erf gelu —
this pins the ``"gelu"`` activation choice of :class:`PreLNBlock` (the ViT
family's block; GPT-2 uses ``"gelu_tanh"``) against the implementation that
defines the common ViT checkpoints.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax
import jax.numpy as jnp

from pipe_tpu.core.partition import StageCtx
from pipe_tpu.ops.layers import PreLNBlock

D, H, FF, SEQ, BATCH = 16, 2, 64, 10, 3


def hf_layer():
    cfg = transformers.ViTConfig(
        hidden_size=D, num_hidden_layers=1, num_attention_heads=H,
        intermediate_size=FF, hidden_act="gelu", hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    torch.manual_seed(0)
    # take the layer from a full ViTModel so the attn-implementation
    # dispatch is initialized (a bare ViTLayer(cfg) lacks it)
    return transformers.ViTModel(cfg).eval().encoder.layer[0]


def params_from_hf(layer) -> dict:
    """HF ViT uses torch Linear ([out, in] -> transpose for our
    right-multiply); attention Q/K/V are separate Linears."""
    sd = {k: v.detach().numpy() for k, v in layer.state_dict().items()}
    a = "attention.attention."
    o = "attention.output."
    return jax.tree_util.tree_map(jnp.asarray, {
        "attn": {"wq": sd[a + "query.weight"].T,
                 "wk": sd[a + "key.weight"].T,
                 "wv": sd[a + "value.weight"].T,
                 "bq": sd[a + "query.bias"], "bk": sd[a + "key.bias"],
                 "bv": sd[a + "value.bias"],
                 "wo": sd[o + "dense.weight"].T,
                 "bo": sd[o + "dense.bias"]},
        "ff1": {"w": sd["intermediate.dense.weight"].T,
                "b": sd["intermediate.dense.bias"]},
        "ff2": {"w": sd["output.dense.weight"].T,
                "b": sd["output.dense.bias"]},
        "ln1": {"g": sd["layernorm_before.weight"],
                "b": sd["layernorm_before.bias"]},
        "ln2": {"g": sd["layernorm_after.weight"],
                "b": sd["layernorm_after.bias"]},
    })


def test_vit_block_matches_hf():
    layer = hf_layer()
    params = params_from_hf(layer)
    ours = PreLNBlock(D, H, FF, dropout=0.0, causal=False)  # default "gelu"

    x = np.random.default_rng(1).standard_normal(
        (BATCH, SEQ, D)).astype(np.float32)
    with torch.no_grad():
        out = layer(torch.from_numpy(x))
        exp = (out[0] if isinstance(out, tuple) else out).numpy()
    got = ours.apply(params, jnp.asarray(x), ctx=StageCtx())
    np.testing.assert_allclose(np.asarray(got), exp, rtol=3e-5, atol=3e-5)
