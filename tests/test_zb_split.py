"""Structural B/W split for zero-bubble schedules (round 3).

The round-3 audit showed the stored-vjp "DCE split" executes the full
transpose at both B and W (1.7x 1f1b). These tests pin the structural
replacement: B applies a params-CONSTANT vjp (zero weight-grad
contractions in its compiled form), W runs nothing but tap x cotangent
contractions, and the whole thing is gradient-transparent."""

import dataclasses
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core import microbatch as mb
from pipe_tpu.core.partition import StageCtx
from pipe_tpu.models.tp_lm import TPPipelinedLM, tp_split_backward_stage
from pipe_tpu.models.transformer_lm import LMConfig
from pipe_tpu.ops.tp_layers import (tp_block_apply, tp_block_init,
                                    tp_block_tapped, tp_block_wgrad,
                                    tp_block_zs)
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.scheduled import ScheduledPipeline
from pipe_tpu.parallel.spmd import stack_stage_params

D, HEADS, FF, SEQ = 16, 4, 32, 8


def _cfg(n_layers):
    return dataclasses.replace(
        LMConfig().tiny(), d_model=D, nhead=HEADS, d_ff=FF, seq_len=SEQ,
        n_layers=n_layers, dropout=0.1)


def test_tapped_block_equals_plain_and_b_has_no_weight_matmuls():
    """Unit contract: tapped forward == plain forward bitwise; (h, zs)-vjp
    gh == full-vjp gh; wgrad(taps, gzs) == full-vjp param grads; and the
    COMPILED B pass contains zero param-shaped dot outputs."""
    # batch=3 so tokens = 3*SEQ = 24 collides with NO weight dim pair —
    # with batch=2, tokens == D and activation-grad dots are weight-SHAPED
    # false positives in the census below
    p = tp_block_init(jax.random.key(0), D, HEADS, FF)
    h = jax.random.normal(jax.random.key(1), (3, SEQ, D))
    ctx = StageCtx(key=jax.random.key(7))
    seed = jax.random.normal(jax.random.key(2), (3, SEQ, D))

    ref_out, ref_vjp = jax.vjp(
        lambda p, h: tp_block_apply(p, h, ctx, dropout=0.1, tp_axis=None),
        p, h)
    gp_ref, gh_ref = ref_vjp(seed)

    zs = tp_block_zs(h, p)
    out, vjp_fn, taps = jax.vjp(
        lambda hh, zz: tp_block_tapped(p, hh, ctx, zz, dropout=0.1),
        h, zs, has_aux=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref_out))
    gh, gzs = vjp_fn(seed)
    np.testing.assert_allclose(np.asarray(gh), np.asarray(gh_ref),
                               rtol=1e-5, atol=1e-6)
    gp = tp_block_wgrad(taps, gzs)
    for (ka, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(gp),
                               jax.tree_util.tree_leaves_with_path(gp_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5, err_msg=str(ka))

    hlo = jax.jit(lambda s: vjp_fn(s)).lower(seed).compile().as_text()
    # any dot whose OUTPUT shape equals a weight leaf's shape is a
    # weight-grad contraction (any rank: catches wqkv [D,3,H,hd] and
    # wo [H,hd,D] as well as the 2-D w1/w2)
    weight_shapes = {tuple(l.shape)
                     for path, l in jax.tree_util.tree_leaves_with_path(p)
                     if l.ndim >= 2}
    # (regex fixed: the previous spelling never matched compiled HLO's
    # ``%name = f32[dims]{layout} dot(...)`` lines, making the census
    # vacuous — the sanity check below guards against that recurring)
    all_dots = re.findall(r"= f32\[([\d,]+)\][^ ]* dot\(", hlo)
    assert all_dots, "census regex matched no dots at all — HLO drifted?"
    param_shaped = [
        dims for dims in all_dots
        if tuple(int(x) for x in dims.split(",")) in weight_shapes]
    assert not param_shaped, (
        f"B pass compiled weight-grad-shaped matmuls: {param_shaped}")


@pytest.mark.parametrize("n_stages,m", [(1, 4), (2, 8), (4, 4)])
def test_zb_split_transparency(n_stages, m):
    """zb-h1 + SplitBackwardStage: loss and all grads equal the plain
    1f1b/never run of the same params (static d=1 and dynamic d>1)."""
    cfg = _cfg(n_stages)
    model = TPPipelinedLM(cfg, n_stages, tp_axis=None)
    sp, prep, postp = model.init(jax.random.key(0))
    stacked = stack_stage_params(sp)
    tokens = jax.random.randint(jax.random.key(1), (2 * m, cfg.seq_len),
                                0, cfg.vocab, jnp.int32)
    x, n_rows = mb.stack_scatter(
        {"tokens": tokens, "targets": jnp.roll(tokens, -1, -1)}, m)
    w = mb.valid_row_mask(x, n_rows)
    mesh = make_mesh(n_stages, 1, devices=jax.devices()[:n_stages])

    ref = ScheduledPipeline(mesh, model.stage_fn, pre_fn=model.pre_fn,
                            post_fn=model.loss_post_fn, checkpoint="never",
                            schedule="1f1b")
    l_ref, g_ref = jax.jit(ref.loss_and_grad)(
        stacked, prep, postp, x, w, key=jax.random.key(9))

    zb = ScheduledPipeline(mesh, model.stage_fn, pre_fn=model.pre_fn,
                          post_fn=model.loss_post_fn, checkpoint="never",
                          schedule="zb-h1",
                          split_stage=tp_split_backward_stage(cfg))
    l_zb, g_zb = jax.jit(zb.loss_and_grad)(
        stacked, prep, postp, x, w, key=jax.random.key(9))

    np.testing.assert_allclose(float(l_zb), float(l_ref), rtol=1e-5)
    for got, exp in zip(g_zb, g_ref):
        for (ka, a), (_, b) in zip(
                jax.tree_util.tree_leaves_with_path(got),
                jax.tree_util.tree_leaves_with_path(exp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=1e-5,
                                       err_msg=str(ka))


def test_zb_split_guards():
    cfg = _cfg(2)
    model = TPPipelinedLM(cfg, 2, tp_axis=None)
    mesh = make_mesh(2, 1, devices=jax.devices()[:2])
    split = tp_split_backward_stage(cfg)
    with pytest.raises(ValueError, match="never"):
        ScheduledPipeline(mesh, model.stage_fn, pre_fn=model.pre_fn,
                          post_fn=model.loss_post_fn,
                          checkpoint="except_last", schedule="zb-h1",
                          split_stage=split)
    with pytest.raises(ValueError, match="splits_backward"):
        ScheduledPipeline(mesh, model.stage_fn, pre_fn=model.pre_fn,
                          post_fn=model.loss_post_fn, checkpoint="never",
                          schedule="1f1b", split_stage=split)