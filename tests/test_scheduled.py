"""ScheduledPipeline tests: the manual fwd+bwd executor.

The properties under test, per VERDICT r1 items #2 and #5:

* loss/grad transparency vs the plain model across schedules (gpipe, 1f1b),
  checkpoint modes (always/except_last/never), stage counts, and m < n;
* REAL 1F1B memory: the stashed-activation buffer is min(m, n) slots vs
  GPipe's m (structural), and compiled FLOPs show the remat policy is exact
  per micro-batch (always > except_last > never);
* bitwise agreement with the AD executor (same key-folding scheme), so the
  two compiled paths are interchangeable;
* data-parallel composition and padded-row masking.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core import microbatch as mb
from pipe_tpu.core.partition import StageCtx
from pipe_tpu.core.schedule import get_schedule, verify_op_tables
from pipe_tpu.ops.layers import Dropout, Linear
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.scheduled import ScheduledPipeline
from pipe_tpu.parallel.spmd import SpmdPipeline, stack_stage_params

WIDTH = 8


def make_stage(n_stages, key, dropout=0.0):
    layer = Linear(WIDTH)
    drop = Dropout(dropout) if dropout else None
    params = [layer.init(jax.random.fold_in(key, j), jnp.zeros((1, WIDTH)))
              for j in range(n_stages)]

    def stage_fn(p, h, ctx):
        h = jnp.tanh(layer.apply(p, h))
        if drop is not None:
            h = drop.apply({}, h, ctx=ctx)
        return h

    return stage_fn, params


def pre_fn(p, x, ctx):
    return x


def post_fn(p, h, x_mb, ctx):
    return jnp.sum((h - 1.0) ** 2, axis=-1)


def plain_loss_fn(stage_fn, params, x):
    h = x
    for p in params:
        h = stage_fn(p, h, StageCtx())
    return jnp.mean(jnp.sum((h - 1.0) ** 2, axis=-1))


# ---------- op tables ----------

@pytest.mark.parametrize("name", ["gpipe", "1f1b"])
@pytest.mark.parametrize("m,n", [(4, 2), (8, 4), (3, 3), (1, 2), (2, 4),
                                 (8, 1), (16, 8)])
def test_op_tables_valid(name, m, n):
    s = get_schedule(name)
    op, mbi = s.op_tables(m, n)
    verify_op_tables(op, mbi, m, n, stash_slots=s.stash_slots(m, n))


def test_verify_op_tables_catches_undersized_stash():
    """GPipe tables with a 1F1B-sized stash must be rejected (the capacity
    invariant is part of the executor contract, not just op placement)."""
    m, n = 8, 2
    op, mbi = get_schedule("gpipe").op_tables(m, n)
    with pytest.raises(AssertionError, match="stash slot clobber"):
        verify_op_tables(op, mbi, m, n, stash_slots=min(m, n))


def test_1f1b_stash_cap():
    """The schedule guarantee behind the min(m, n) buffer: BWD of i lands
    before FWD of i + min(m, n) at every stage."""
    s = get_schedule("1f1b")
    for m, n in [(8, 2), (8, 4), (16, 8)]:
        S = s.stash_slots(m, n)
        assert S == min(m, n)
        op, mbi = s.op_tables(m, n)
        t_of = {}
        for t in range(op.shape[0]):
            for j in range(n):
                if op[t, j]:
                    t_of[(op[t, j], mbi[t, j], j)] = t
        from pipe_tpu.core.schedule import BWD, FWD
        for j in range(n):
            for i in range(m - S):
                assert t_of[(BWD, i, j)] < t_of[(FWD, i + S, j)]


# ---------- transparency ----------

@pytest.mark.parametrize("schedule", ["gpipe", "1f1b", "zb-h1"])
@pytest.mark.parametrize("checkpoint", ["always", "except_last", "never"])
@pytest.mark.parametrize("n_stages,m", [(1, 4), (2, 8), (4, 8), (4, 2)])
def test_loss_and_grad_transparency(schedule, checkpoint, n_stages, m):
    # n_stages == 1 exercises the trace-time static specialization
    # (_device_program_static); >= 2 the dynamic table scan. zb-h1 covers
    # the split-backward (B/W) executor paths in both.
    stage_fn, params = make_stage(n_stages, jax.random.key(0))
    mesh = make_mesh(n_stages, 1)
    x = jax.random.normal(jax.random.key(1), (2 * m, WIDTH))
    xs, bs = mb.stack_scatter(x, m)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    stacked = stack_stage_params(params)

    l_ref, g_ref = jax.value_and_grad(
        lambda ps: plain_loss_fn(stage_fn, ps, x))(params)
    g_ref = stack_stage_params(g_ref)

    pipe = ScheduledPipeline(mesh, stage_fn, pre_fn=pre_fn, post_fn=post_fn,
                             checkpoint=checkpoint, schedule=schedule)
    loss, (gsp, _, _) = jax.jit(pipe.loss_and_grad)(
        stacked, {}, {}, xs, w, key=jax.random.key(9))
    np.testing.assert_allclose(float(loss), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(gsp),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pre_post_param_grads():
    """Grads reach pre (embed-like) and post (loss-head) params, matching
    the plain composition."""
    n_stages, m = 2, 4
    stage_fn, params = make_stage(n_stages, jax.random.key(0))
    emb = Linear(WIDTH)
    pre_p = emb.init(jax.random.key(10), jnp.zeros((1, 5)))
    head = Linear(1)
    post_p = head.init(jax.random.key(11), jnp.zeros((1, WIDTH)))

    def pre(p, x, ctx):
        return emb.apply(p, x)

    def post(p, h, x_mb, ctx):
        return jnp.squeeze(head.apply(p, h), -1) ** 2

    mesh = make_mesh(n_stages, 1)
    x = jax.random.normal(jax.random.key(1), (8, 5))
    xs, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    stacked = stack_stage_params(params)

    def plain(ps, pre_p, post_p):
        h = emb.apply(pre_p, x)
        for p in ps:
            h = stage_fn(p, h, StageCtx())
        return jnp.mean(jnp.squeeze(head.apply(post_p, h), -1) ** 2)

    l_ref, (g_ps, g_pre_ref, g_post_ref) = jax.value_and_grad(
        plain, argnums=(0, 1, 2))(params, pre_p, post_p)

    pipe = ScheduledPipeline(mesh, stage_fn, pre_fn=pre, post_fn=post,
                             checkpoint="except_last", schedule="1f1b")
    loss, (gsp, gpre, gpost) = jax.jit(pipe.loss_and_grad)(
        stacked, pre_p, post_p, xs, w)
    np.testing.assert_allclose(float(loss), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(gsp),
                    jax.tree_util.tree_leaves(stack_stage_params(g_ps))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
    for got, ref in ((gpre, g_pre_ref), (gpost, g_post_ref)):
        for a, b in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("checkpoint", ["always", "except_last", "never"])
def test_dropout_matches_ad_executor_bitwise(checkpoint):
    """Same key-folding scheme as SpmdPipeline → identical dropout masks →
    identical loss, across executors. (The remat'd recompute replays the same
    key — the reference's save/restore_rng_states, README.md:528-537.)"""
    n_stages, m = 2, 4
    stage_fn, params = make_stage(n_stages, jax.random.key(0), dropout=0.5)
    mesh = make_mesh(n_stages, 1)
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    xs, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    stacked = stack_stage_params(params)
    key = jax.random.key(42)

    sched = ScheduledPipeline(mesh, stage_fn, pre_fn=pre_fn, post_fn=post_fn,
                              checkpoint=checkpoint, schedule="1f1b")
    loss_s, _ = jax.jit(sched.loss_and_grad)(stacked, {}, {}, xs, w, key=key)

    ad = SpmdPipeline(mesh, stage_fn, pre_fn=pre_fn, post_fn=post_fn,
                      post_with_batch=True, checkpoint=checkpoint)
    per_row = ad(stacked, {}, {}, xs, key=key, train=True)
    loss_ad = jnp.sum(per_row * w) / jnp.sum(w)
    np.testing.assert_array_equal(np.asarray(loss_s), np.asarray(loss_ad))

    # determinism: same key → same loss; different key → different
    loss_s2, _ = jax.jit(sched.loss_and_grad)(stacked, {}, {}, xs, w, key=key)
    np.testing.assert_array_equal(np.asarray(loss_s), np.asarray(loss_s2))
    loss_s3, _ = jax.jit(sched.loss_and_grad)(
        stacked, {}, {}, xs, w, key=jax.random.key(7))
    assert not np.allclose(np.asarray(loss_s), np.asarray(loss_s3))


# ---------- the memory story ----------

def test_memory_plan_1f1b_caps_stash():
    mesh = make_mesh(2, 1)
    stage_fn, _ = make_stage(2, jax.random.key(0))
    kw = dict(pre_fn=pre_fn, post_fn=post_fn)
    m = 16
    g = ScheduledPipeline(mesh, stage_fn, checkpoint="always",
                          schedule="gpipe", **kw)
    f = ScheduledPipeline(mesh, stage_fn, checkpoint="always",
                          schedule="1f1b", **kw)
    assert g.memory_plan(m)["stash_slots"] == m
    assert f.memory_plan(m)["stash_slots"] == 2  # min(m, n): the 1F1B cap
    # residual slots follow the checkpoint mode exactly
    assert ScheduledPipeline(mesh, stage_fn, checkpoint="never",
                             schedule="1f1b", **kw).memory_plan(m)[
        "residual_slots"] == 2
    assert ScheduledPipeline(mesh, stage_fn, checkpoint="except_last",
                             schedule="1f1b", **kw).memory_plan(m)[
        "residual_slots"] == 1


def test_except_last_is_exact_per_microbatch():
    """Count actual stage-body executions via a debug callback: always
    recomputes every micro-batch at backward, except_last all but the last,
    never none — the reference mode map (pipe.py:354) realized EXACTLY on the
    compiled path, which the AD executor cannot do (static remat, spmd.py
    docstring). Total executions = m*n forward + recomputed*n backward."""
    n_stages, m = 2, 4
    base_fn, params = make_stage(n_stages, jax.random.key(0))
    mesh = make_mesh(n_stages, 1)
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    xs, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    stacked = stack_stage_params(params)

    calls = []

    def stage_fn(p, h, ctx):
        jax.debug.callback(lambda: calls.append(1))
        return base_fn(p, h, ctx)

    expected = {"always": m * n_stages + m * n_stages,
                "except_last": m * n_stages + (m - 1) * n_stages,
                "never": m * n_stages}
    for mode, want in expected.items():
        pipe = ScheduledPipeline(mesh, stage_fn, pre_fn=pre_fn,
                                 post_fn=post_fn, checkpoint=mode,
                                 schedule="1f1b")
        calls.clear()
        loss, grads = pipe.loss_and_grad(stacked, {}, {}, xs, w)
        jax.block_until_ready((loss, grads))
        jax.effects_barrier()
        assert len(calls) == want, (mode, len(calls), want)


# ---------- composition ----------

def test_data_parallel_grads():
    n_stages, n_data, m = 2, 2, 4
    stage_fn, params = make_stage(n_stages, jax.random.key(0))
    mesh = make_mesh(n_stages, n_data)
    x = jax.random.normal(jax.random.key(1), (16, WIDTH))
    xs, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    stacked = stack_stage_params(params)

    l_ref, g_ref = jax.value_and_grad(
        lambda ps: plain_loss_fn(stage_fn, ps, x))(params)
    g_ref = stack_stage_params(g_ref)

    pipe = ScheduledPipeline(mesh, stage_fn, pre_fn=pre_fn, post_fn=post_fn,
                             checkpoint="except_last", schedule="1f1b")
    loss, (gsp, _, _) = jax.jit(pipe.loss_and_grad)(stacked, {}, {}, xs, w)
    np.testing.assert_allclose(float(loss), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(gsp),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_padded_row_masking():
    """Zero-weighted (padding) rows contribute nothing: loss equals the
    plain model on the real rows only."""
    n_stages, m = 2, 4
    stage_fn, params = make_stage(n_stages, jax.random.key(0))
    mesh = make_mesh(n_stages, 1)
    x10 = jax.random.normal(jax.random.key(1), (10, WIDTH))
    xs, bs = mb.stack_scatter(x10, m)       # pads 10 -> 12 rows
    assert bs == 10 and xs.shape[:2] == (4, 3)
    idx = jnp.arange(12).reshape(4, 3)
    w = (idx < 10).astype(jnp.float32)
    stacked = stack_stage_params(params)

    pipe = ScheduledPipeline(mesh, stage_fn, pre_fn=pre_fn, post_fn=post_fn,
                             checkpoint="never", schedule="1f1b")
    loss, _ = jax.jit(pipe.loss_and_grad)(stacked, {}, {}, xs, w)
    l_ref = plain_loss_fn(stage_fn, params, x10)
    np.testing.assert_allclose(float(loss), float(l_ref), rtol=1e-5)


# ---------- interleaved 1F1B (BASELINE config #4's schedule) ----------

def _plain_loss_chain(stage_fn, params, x):
    h = x
    for p in params:
        h = stage_fn(p, h, StageCtx())
    return jnp.mean(jnp.sum((h - 1.0) ** 2, axis=-1))


@pytest.mark.parametrize("d,v,m", [(1, 2, 4), (2, 2, 4), (4, 2, 8),
                                   (2, 4, 8), (3, 2, 6)])
@pytest.mark.parametrize("mode", ["never", "except_last", "always"])
def test_interleaved_1f1b_matches_plain(d, v, m, mode):
    """Loss AND grads of the interleaved manual executor equal the plain
    chain over all v*d virtual stages."""
    from pipe_tpu.core.schedule import InterleavedOneFOneBSchedule
    from pipe_tpu.parallel.interleaved import stack_interleaved_params

    if (d, v, m) not in ((2, 2, 4), (1, 2, 4)) and mode != "except_last":
        pytest.skip("full mode matrix only at the smallest shapes; (1, 2, 4) "
                    "covers the static d == 1 specialization per mode")
    S = d * v
    stage_fn, params = make_stage(S, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (m * 2, WIDTH))
    xm, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xm.shape[:2], jnp.float32)
    sched = ScheduledPipeline(
        make_mesh(d, 1, devices=jax.devices()[:d]), stage_fn,
        pre_fn=lambda p, a, ctx: a,
        post_fn=lambda p, h, a, ctx: jnp.sum((h - 1.0) ** 2, axis=-1),
        checkpoint=mode,
        schedule=InterleavedOneFOneBSchedule(interleave=v))
    stacked = stack_interleaved_params(params, d)
    loss, (g_sp, _, _) = jax.jit(
        lambda a: sched.loss_and_grad(a, {}, {}, xm, w))(stacked)

    exp_loss, exp_g = jax.value_and_grad(
        lambda p: _plain_loss_chain(stage_fn, p, x))(params)
    np.testing.assert_allclose(float(loss), float(exp_loss), rtol=1e-5)
    exp_stacked = stack_interleaved_params(exp_g, d)
    for a, b in zip(jax.tree_util.tree_leaves(g_sp),
                    jax.tree_util.tree_leaves(exp_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_interleaved_1f1b_dropout_exact_except_last():
    """With dropout active, except_last must equal never bit-for-bit (same
    key folds; stored vs recomputed residuals replay identical masks)."""
    from pipe_tpu.core.schedule import InterleavedOneFOneBSchedule
    from pipe_tpu.parallel.interleaved import stack_interleaved_params

    d, v, m = 2, 2, 4
    stage_fn, params = make_stage(d * v, jax.random.key(0), dropout=0.3)
    x = jax.random.normal(jax.random.key(1), (m * 2, WIDTH))
    xm, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xm.shape[:2], jnp.float32)
    stacked = stack_interleaved_params(params, d)
    out = {}
    for mode in ("never", "except_last", "always"):
        sched = ScheduledPipeline(
            make_mesh(d, 1, devices=jax.devices()[:d]), stage_fn,
            pre_fn=lambda p, a, ctx: a,
            post_fn=lambda p, h, a, ctx: jnp.sum((h - 1.0) ** 2, axis=-1),
            checkpoint=mode,
            schedule=InterleavedOneFOneBSchedule(interleave=v))
        out[mode] = jax.jit(
            lambda a: sched.loss_and_grad(a, {}, {}, xm, w,
                                          key=jax.random.key(5)))(stacked)
    for mode in ("except_last", "always"):
        np.testing.assert_array_equal(np.asarray(out["never"][0]),
                                      np.asarray(out[mode][0]))
        for a, b in zip(jax.tree_util.tree_leaves(out["never"][1]),
                        jax.tree_util.tree_leaves(out[mode][1])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)


def test_interleaved_1f1b_tables_and_memory_plan():
    from pipe_tpu.core.schedule import (InterleavedOneFOneBSchedule,
                                        verify_interleaved_op_tables)

    s = InterleavedOneFOneBSchedule(interleave=2)
    for (m, d) in [(4, 2), (8, 4), (16, 4)]:
        op, mbt, grp = s.op_tables(m, d)
        verify_interleaved_op_tables(op, mbt, grp, m, d, 2)
        # the interleave shrinks the schedule vs plain 1F1B of depth v*d
        assert op.shape[0] < 2 * (m * 2 + 2 * d - 1)

    sched = ScheduledPipeline(
        make_mesh(2, 1, devices=jax.devices()[:2]),
        lambda p, h, ctx: h, pre_fn=lambda p, a, ctx: a,
        post_fn=lambda p, h, a, ctx: jnp.sum(h, axis=-1),
        checkpoint="except_last", schedule=s)
    plan = sched.memory_plan(8)
    assert plan["virtual_stages_per_device"] == 2
    assert plan["residual_slots"] == 2          # one per group (except_last)
    assert plan["stash_slots"] == 2 * plan["stash_slots_per_virtual_stage"]


def test_interleaved_1f1b_with_data_axis():
    from pipe_tpu.core.schedule import InterleavedOneFOneBSchedule
    from pipe_tpu.parallel.interleaved import stack_interleaved_params

    d, v, m = 2, 2, 4
    stage_fn, params = make_stage(d * v, jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (m * 4, WIDTH))
    xm, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xm.shape[:2], jnp.float32)
    sched = ScheduledPipeline(
        make_mesh(d, 2, devices=jax.devices()[:2 * d]), stage_fn,
        pre_fn=lambda p, a, ctx: a,
        post_fn=lambda p, h, a, ctx: jnp.sum((h - 1.0) ** 2, axis=-1),
        checkpoint="except_last",
        schedule=InterleavedOneFOneBSchedule(interleave=v))
    stacked = stack_interleaved_params(params, d)
    loss, _ = jax.jit(
        lambda a: sched.loss_and_grad(a, {}, {}, xm, w))(stacked)
    exp = _plain_loss_chain(stage_fn, params, x)
    np.testing.assert_allclose(float(loss), float(exp), rtol=1e-5)


@pytest.mark.parametrize("checkpoint", ["always", "except_last"])
def test_remat_policy_transparency(checkpoint):
    """Selective remat (jax.checkpoint_policies.dots_saveable) on the d=1
    static program: identical loss and grads to the full-recompute path —
    the policy changes what is stored, never the math."""
    m = 4
    stage_fn, params = make_stage(2, jax.random.key(0))
    mesh = make_mesh(1, 1, devices=jax.devices()[:1])
    x = jax.random.normal(jax.random.key(1), (2 * m, WIDTH))
    xs, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    stacked = stack_stage_params(params[:1])

    results = []
    for policy in (None, jax.checkpoint_policies.dots_saveable):
        pipe = ScheduledPipeline(mesh, stage_fn, pre_fn=pre_fn,
                                 post_fn=post_fn, checkpoint=checkpoint,
                                 schedule="1f1b", remat_policy=policy)
        loss, (gsp, _, _) = jax.jit(pipe.loss_and_grad)(
            stacked, {}, {}, xs, w, key=jax.random.key(9))
        results.append((float(loss), gsp))
    (l_full, g_full), (l_pol, g_pol) = results
    assert l_full == pytest.approx(l_pol, rel=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g_full),
                    jax.tree_util.tree_leaves(g_pol)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("checkpoint", ["always", "except_last"])
@pytest.mark.parametrize("d", [2, 4])
def test_remat_policy_transparency_dynamic(checkpoint, d):
    """Selective remat on the d>1 DYNAMIC scan (the multi-device stage
    axis): identical loss and grads to the full-recompute path. The
    recompute micro-batches park their policy-saved residual subset in a
    second, policy-shaped slot store; saved micro-batches still use the
    full store — the cond-gated selection must never change the math."""
    m = 4
    stage_fn, params = make_stage(d, jax.random.key(0))
    mesh = make_mesh(d, 1, devices=jax.devices()[:d])
    x = jax.random.normal(jax.random.key(1), (2 * m, WIDTH))
    xs, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    stacked = stack_stage_params(params)

    results = []
    for policy in (None, jax.checkpoint_policies.dots_saveable):
        pipe = ScheduledPipeline(mesh, stage_fn, pre_fn=pre_fn,
                                 post_fn=post_fn, checkpoint=checkpoint,
                                 schedule="1f1b", remat_policy=policy)
        loss, (gsp, _, _) = jax.jit(pipe.loss_and_grad)(
            stacked, {}, {}, xs, w, key=jax.random.key(9))
        results.append((float(loss), gsp))
    (l_full, g_full), (l_pol, g_pol) = results
    assert l_full == pytest.approx(l_pol, rel=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g_full),
                    jax.tree_util.tree_leaves(g_pol)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


def test_remat_policy_interleaved_dynamic():
    """Policy + interleaved-1f1b (v=2) + data axis on the dynamic scan."""
    from pipe_tpu.core.schedule import InterleavedOneFOneBSchedule
    from pipe_tpu.parallel.interleaved import stack_interleaved_params
    m, d, v = 4, 2, 2
    stage_fn, params = make_stage(v * d, jax.random.key(0))
    mesh = make_mesh(d, 2, devices=jax.devices()[:2 * d])
    x = jax.random.normal(jax.random.key(1), (4 * m, WIDTH))
    xs, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    stacked = stack_interleaved_params(params, d)

    results = []
    for policy in (None, jax.checkpoint_policies.dots_saveable):
        pipe = ScheduledPipeline(
            mesh, stage_fn, pre_fn=pre_fn, post_fn=post_fn,
            checkpoint="except_last",
            schedule=InterleavedOneFOneBSchedule(interleave=v),
            remat_policy=policy)
        loss, (gsp, _, _) = jax.jit(pipe.loss_and_grad)(
            stacked, {}, {}, xs, w, key=jax.random.key(9))
        results.append((float(loss), gsp))
    (l_full, g_full), (l_pol, g_pol) = results
    assert l_full == pytest.approx(l_pol, rel=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g_full),
                    jax.tree_util.tree_leaves(g_pol)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("checkpoint", ["never", "except_last"])
def test_skip_lanes_raw_executor(checkpoint):
    """SkipLanes on the raw table executor: a 0 -> 3 skip rides the
    forward lane ring + FIFO park, and its pop cotangent returns on the
    reverse ring — loss and grads equal the plain chained model."""
    from pipe_tpu.parallel.scheduled import SkipLanes
    d, m = 4, 4
    key = jax.random.key(0)
    params = [{"w": jax.random.normal(jax.random.fold_in(key, jj),
                                      (WIDTH, WIDTH)) * 0.3,
               "b": jnp.zeros((WIDTH,))} for jj in range(d)]
    lanes = SkipLanes(pairs=((0, 3),),
                      specs=(jax.ShapeDtypeStruct((2, WIDTH),
                                                  jnp.float32),))

    def stage_fn(p, h, ctx, pops):
        h1 = jnp.tanh(h @ p["w"] + p["b"])
        out = jnp.where(jnp.asarray(ctx.stage == 3), h1 + pops[0], h1)
        sk = jnp.where(jnp.asarray(ctx.stage == 0), h1,
                       jnp.zeros_like(h1))
        return out, (sk,)

    def plain(ps, x):
        h = x
        saved = None
        for jj, p in enumerate(ps):
            h1 = jnp.tanh(h @ p["w"] + p["b"])
            if jj == 0:
                saved = h1
            h = h1 + saved if jj == 3 else h1
        return jnp.mean(jnp.sum((h - 1.0) ** 2, axis=-1))

    mesh = make_mesh(d, 1, devices=jax.devices()[:d])
    x = jax.random.normal(jax.random.key(1), (2 * m, WIDTH))
    xs, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    pipe = ScheduledPipeline(mesh, stage_fn, pre_fn=pre_fn,
                             post_fn=post_fn, checkpoint=checkpoint,
                             schedule="1f1b", skip_lanes=lanes)
    loss, (gsp, _, _) = jax.jit(pipe.loss_and_grad)(
        stack_stage_params(params), {}, {}, xs, w)
    exp_loss = plain(params, x)
    exp_g = jax.grad(plain)(params, x)
    assert float(loss) == pytest.approx(float(exp_loss), rel=1e-5)
    for jj in range(d):
        got_j = jax.tree_util.tree_map(lambda a: a[jj], gsp)
        for a, b in zip(jax.tree_util.tree_leaves(got_j),
                        jax.tree_util.tree_leaves(exp_g[jj])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6)
    plan = pipe.memory_plan(m)
    assert plan["skip_lanes"] == 1
    assert plan["skip_fwd_park_slots"] >= 1
    assert plan["skip_bwd_park_slots"] >= 1


def test_remat_policy_inert_at_never_warns():
    stage_fn, _ = make_stage(2, jax.random.key(0))
    mesh = make_mesh(2, 1, devices=jax.devices()[:2])
    with pytest.warns(UserWarning, match="inert"):
        ScheduledPipeline(
            mesh, stage_fn, pre_fn=pre_fn, post_fn=post_fn,
            checkpoint="never", schedule="1f1b",
            remat_policy=jax.checkpoint_policies.dots_saveable)


@pytest.mark.parametrize("schedule", ["1f1b", "zb-h1"])
def test_static_unroll_matches_dynamic_at_d1(schedule):
    """static_unroll=True (trace-time straight-line) and =False (the
    dynamic table scan) must produce identical loss and grads at d == 1 —
    the two programs implement one schedule contract."""
    m = 4
    stage_fn, params = make_stage(1, jax.random.key(0))
    mesh = make_mesh(1, 1, devices=jax.devices()[:1])
    x = jax.random.normal(jax.random.key(1), (2 * m, WIDTH))
    xs, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    stacked = stack_stage_params(params)

    results = []
    for flag in (True, False):
        pipe = ScheduledPipeline(mesh, stage_fn, pre_fn=pre_fn,
                                 post_fn=post_fn, checkpoint="except_last",
                                 schedule=schedule, static_unroll=flag)
        loss, (gsp, _, _) = jax.jit(pipe.loss_and_grad)(
            stacked, {}, {}, xs, w, key=jax.random.key(9))
        results.append((float(loss), gsp))
    (l_s, g_s), (l_d, g_d) = results
    assert l_s == pytest.approx(l_d, rel=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g_s),
                    jax.tree_util.tree_leaves(g_d)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)
