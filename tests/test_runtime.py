"""Multi-host runtime wrappers (single-process behavior + API contract)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.parallel.mesh import DATA_AXIS, STAGE_AXIS
from pipe_tpu.runtime import (global_pipeline_mesh, host_local_batch,
                              initialize, is_initialized, process_summary)


def test_initialize_single_process_noop():
    initialize()
    assert is_initialized()
    initialize()  # idempotent


def test_global_pipeline_mesh_layout():
    mesh = global_pipeline_mesh(4)
    assert mesh.axis_names == (STAGE_AXIS, DATA_AXIS)
    assert mesh.shape[STAGE_AXIS] == 4
    assert mesh.shape[DATA_AXIS] == 2
    # stage-contiguous: first data column is devices 0..3
    col = mesh.devices[:, 0]
    assert [d.id for d in col] == [0, 1, 2, 3]


def test_global_pipeline_mesh_validation():
    with pytest.raises(ValueError, match="not divisible"):
        global_pipeline_mesh(3)
    with pytest.raises(ValueError, match="exceeds"):
        global_pipeline_mesh(4, 4)


def test_host_local_batch_single_process():
    mesh = global_pipeline_mesh(2)  # (stage=2, data=4)
    local = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
    arr = host_local_batch(mesh, local)
    assert arr.shape == (8, 3)
    np.testing.assert_array_equal(np.asarray(arr), local)
    # sharded over data on dim 0
    assert arr.sharding.spec[0] == DATA_AXIS


def test_process_summary():
    s = process_summary()
    assert "process 0/1" in s and "8" in s
