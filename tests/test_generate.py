"""KV-cached generation (inference/generate.py + the layer decode methods).

The load-bearing contract: cached incremental decode is the SAME math as
the training forward — teacher-forced cached logits match the full causal
forward at every position, greedy cached generation matches a naive
re-forward-per-token loop, and sampling is reproducible from its key.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core.partition import StageCtx
from pipe_tpu.inference import GenerationConfig, Generator
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
from pipe_tpu.ops.layers import (MultiHeadAttention, PreLNBlock,
                                 TransformerEncoderLayer)

CFG = LMConfig(vocab=89, d_model=32, nhead=4, d_ff=64, n_layers=4,
               seq_len=32, dropout=0.0)


def _model_and_params(n_stages=2, seed=0):
    model = PipelinedLM(CFG, n_stages)
    params = model.init(jax.random.key(seed))
    return model, params


def _full_logits(model, params, tokens):
    """Training-path forward: pre_fn -> every stage's blocks -> head."""
    sp, pre, post = params
    ctx = StageCtx(train=False)
    h = model.pre_fn(pre, tokens, ctx)
    for blocks in sp:
        h = model.stage_fn(blocks, h, ctx)
    return model.post_fn(post, h, ctx)


@pytest.mark.parametrize("block_cls", [TransformerEncoderLayer, PreLNBlock])
def test_block_decode_matches_apply(block_cls):
    """Prefill (q=seq, pos=0) through block.decode == the causal apply."""
    blk = block_cls(32, 4, 64, dropout=0.0, causal=True)
    x = jax.random.normal(jax.random.key(1), (2, 16, 32))
    params = blk.init(jax.random.key(2), x)
    ref = blk.apply(params, x, ctx=StageCtx(train=False))
    cache = blk.attn.make_cache(2, 24)
    out, cache = blk.decode(params, x, cache, 0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # the cache rows [0, 16) are written; [16, 24) untouched
    assert not np.allclose(np.asarray(cache["k"][:, :16]), 0.0)
    np.testing.assert_array_equal(np.asarray(cache["k"][:, 16:]), 0.0)


def test_incremental_decode_matches_prefill():
    """Feeding tokens one at a time == one prefill pass (same cache,
    same outputs) — the O(1)-per-step path is the same math."""
    blk = TransformerEncoderLayer(32, 4, 64, dropout=0.0, causal=True)
    x = jax.random.normal(jax.random.key(3), (2, 12, 32))
    params = blk.init(jax.random.key(4), x)
    full, full_cache = blk.decode(params, x,
                                  blk.attn.make_cache(2, 12), 0)
    cache = blk.attn.make_cache(2, 12)
    outs = []
    for t in range(12):
        o, cache = blk.decode(params, x[:, t:t + 1], cache, t)
        outs.append(o)
    inc = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(inc), np.asarray(full),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(cache["k"]),
                               np.asarray(full_cache["k"]),
                               rtol=1e-6, atol=1e-6)


def test_teacher_forced_cached_logits_match_forward():
    """Drive the generator's layer stack with a FIXED token sequence and
    compare each step's logits to the full training forward."""
    model, params = _model_and_params()
    sp, pre, post = params
    tokens = jax.random.randint(jax.random.key(5), (2, 20), 0, CFG.vocab,
                                jnp.int32)
    ref = _full_logits(model, params, tokens)  # [2, 20, V]

    gen = Generator(model, GenerationConfig(max_new_tokens=1))
    blocks = gen._blocks(sp)
    caches = [model.block.attn.make_cache(2, 20,
                                          dtype=CFG.compute_dtype)
              for _ in blocks]
    got = []
    for t in range(20):
        h = model.embed_at(pre, tokens[:, t:t + 1], t)
        for l, bp in enumerate(blocks):
            h, caches[l] = model.block.decode(bp, h, caches[l], t)
        got.append(gen._head(post, h)[:, 0, :])
    got = jnp.stack(got, axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=5e-5, atol=5e-5)


def test_greedy_generation_matches_naive_reforward():
    model, params = _model_and_params()
    prompt = jax.random.randint(jax.random.key(6), (2, 8), 0, CFG.vocab,
                                jnp.int32)
    max_new = 6
    gen = Generator(model, GenerationConfig(max_new_tokens=max_new,
                                            temperature=0.0))
    fast = np.asarray(gen.generate(params, prompt))

    # naive: re-run the full forward over the growing sequence each step
    seq = np.asarray(prompt)
    naive = []
    for _ in range(max_new):
        logits = _full_logits(model, params, jnp.asarray(seq))
        nxt = np.asarray(jnp.argmax(logits[:, -1, :], axis=-1),
                         dtype=np.int32)
        naive.append(nxt)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    naive = np.stack(naive, axis=1)
    np.testing.assert_array_equal(fast, naive)


def test_sampling_reproducible_and_temperature():
    model, params = _model_and_params()
    prompt = jnp.zeros((3, 4), jnp.int32)
    g = Generator(model, GenerationConfig(max_new_tokens=8, temperature=0.8,
                                          top_k=16))
    a = np.asarray(g.generate(params, prompt, key=jax.random.key(7)))
    b = np.asarray(g.generate(params, prompt, key=jax.random.key(7)))
    c = np.asarray(g.generate(params, prompt, key=jax.random.key(8)))
    np.testing.assert_array_equal(a, b)
    assert a.shape == (3, 8)
    assert (a != c).any()  # different key, different samples
    assert (a >= 0).all() and (a < CFG.vocab).all()


def test_generator_rejects_models_without_embed_at():
    class NoEmbed:
        pass

    with pytest.raises(TypeError, match="embed_at"):
        Generator(NoEmbed())


def test_max_new_tokens_one():
    model, params = _model_and_params()
    prompt = jnp.zeros((2, 5), jnp.int32)
    g = Generator(model, GenerationConfig(max_new_tokens=1, temperature=0.0))
    out = np.asarray(g.generate(params, prompt))
    assert out.shape == (2, 1)
    logits = _full_logits(model, params, prompt)
    np.testing.assert_array_equal(
        out[:, 0], np.asarray(jnp.argmax(logits[:, -1, :], axis=-1)))


# --- GPT-2 family (pre-LN blocks, learned positions) ---

def _gpt2_full_logits(model, params, tokens):
    from pipe_tpu.core.partition import StageCtx as Ctx
    sp, pre, post = params
    ctx = Ctx(train=False)
    h = model.pre_fn(pre, tokens, ctx)
    for blocks in sp:
        h = model.stage_fn(blocks, h, ctx)
    return model.head.apply(post["head"], h, ctx=ctx)


def test_gpt2_greedy_generation_matches_naive_reforward():
    from pipe_tpu.models.gpt2 import GPT2Config, PipelinedGPT2

    cfg = GPT2Config().tiny()
    model = PipelinedGPT2(cfg, 2)
    params = model.init(jax.random.key(9))
    prompt = jax.random.randint(jax.random.key(10), (2, 6), 0, cfg.vocab,
                                jnp.int32)
    max_new = 5
    gen = Generator(model, GenerationConfig(max_new_tokens=max_new,
                                            temperature=0.0))
    fast = np.asarray(gen.generate(params, prompt))

    seq = np.asarray(prompt)
    naive = []
    for _ in range(max_new):
        logits = _gpt2_full_logits(model, params, jnp.asarray(seq))
        nxt = np.asarray(jnp.argmax(logits[:, -1, :], axis=-1),
                         dtype=np.int32)
        naive.append(nxt)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(fast, np.stack(naive, axis=1))


def test_gpt2_pipelined_matches_single_device():
    from pipe_tpu.inference.pipelined import PipelinedGenerator
    from pipe_tpu.models.gpt2 import GPT2Config, PipelinedGPT2
    from pipe_tpu.parallel.mesh import make_mesh
    from pipe_tpu.parallel.spmd import stack_stage_params

    cfg = GPT2Config().tiny()
    model = PipelinedGPT2(cfg, 2)
    sp, pre, post = model.init(jax.random.key(11))
    prompt = jax.random.randint(jax.random.key(12), (4, 6), 0, cfg.vocab,
                                jnp.int32)
    gen_cfg = GenerationConfig(max_new_tokens=5, temperature=0.0)
    ref = np.asarray(Generator(model, gen_cfg).generate((sp, pre, post),
                                                        prompt))
    pg = PipelinedGenerator(make_mesh(2, 1), model, gen_cfg)
    got = np.asarray(pg.generate(stack_stage_params(sp), pre, post, prompt))
    np.testing.assert_array_equal(got, ref)


def test_gpt2_position_guard():
    from pipe_tpu.models.gpt2 import GPT2Config, PipelinedGPT2

    cfg = GPT2Config().tiny()   # seq_len 16 = wpe rows
    model = PipelinedGPT2(cfg, 1)
    g = Generator(model, GenerationConfig(max_new_tokens=14))
    with pytest.raises(ValueError, match="positional table"):
        g.generate(None, jnp.zeros((1, 4), jnp.int32))  # 4 + 14 > 16


# --- beam search ---

def _seq_logprob(model, params, prompt, cont):
    """Teacher-forced total log-prob of `cont` given `prompt` (independent
    scorer: the full training forward, no caches)."""
    full = jnp.concatenate([prompt, cont], axis=1)
    logits = _full_logits(model, params, full)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    p = prompt.shape[1]
    total = 0.0
    for t in range(cont.shape[1]):
        step_lp = logp[:, p - 1 + t, :]
        total = total + jnp.take_along_axis(
            step_lp, cont[:, t][:, None], axis=-1)[:, 0]
    return total


def test_beam_search_scores_are_consistent_and_beat_greedy():
    model, params = _model_and_params()
    prompt = jax.random.randint(jax.random.key(20), (3, 6), 0, CFG.vocab,
                                jnp.int32)
    max_new = 5
    greedy = Generator(model, GenerationConfig(max_new_tokens=max_new,
                                               temperature=0.0))
    beam = Generator(model, GenerationConfig(max_new_tokens=max_new,
                                             num_beams=4))
    g_toks = greedy.generate(params, prompt)
    b_toks, b_scores = beam.generate_with_scores(params, prompt)
    assert b_toks.shape == (3, max_new) and b_scores.shape == (3,)

    # internal beam scores == independently-computed sequence log-probs
    ext = _seq_logprob(model, params, prompt, b_toks)
    np.testing.assert_allclose(np.asarray(b_scores), np.asarray(ext),
                               rtol=1e-4, atol=1e-4)
    # the best of 4 beams scores at least as well as the greedy path
    g_scores = _seq_logprob(model, params, prompt, g_toks)
    assert (np.asarray(b_scores) >= np.asarray(g_scores) - 1e-4).all()


def test_beam_k1_path_and_generate_dispatch():
    model, params = _model_and_params()
    prompt = jnp.zeros((2, 4), jnp.int32)
    greedy = Generator(model, GenerationConfig(max_new_tokens=4,
                                               temperature=0.0))
    beamed = Generator(model, GenerationConfig(max_new_tokens=4,
                                               num_beams=3))
    out = np.asarray(beamed.generate(params, prompt))  # dispatches to beam
    assert out.shape == (2, 4)
    with pytest.raises(ValueError, match="num_beams"):
        greedy.generate_with_scores(params, prompt)
    with pytest.raises(ValueError):
        GenerationConfig(num_beams=0)


def test_beam_max_new_one_equals_greedy():
    model, params = _model_and_params()
    prompt = jax.random.randint(jax.random.key(21), (2, 5), 0, CFG.vocab,
                                jnp.int32)
    g = Generator(model, GenerationConfig(max_new_tokens=1, temperature=0.0)
                  ).generate(params, prompt)
    b, _ = Generator(model, GenerationConfig(max_new_tokens=1, num_beams=3)
                     ).generate_with_scores(params, prompt)
    np.testing.assert_array_equal(np.asarray(b), np.asarray(g))


def test_layer_scan_false_matches_default():
    """The unrolled-layer decode path (outer-carry caches, in-place row
    writes) is the same math as the inner-scan path. Exact token equality
    is safe HERE because conftest pins the whole suite to the CPU
    platform (deterministic fusion order) at f32 — on other backends the
    two program structures may resolve argmax near-ties differently
    (see the Generator docstring)."""
    model, params = _model_and_params()
    prompt = jax.random.randint(jax.random.key(30), (2, 8), 0, CFG.vocab,
                                jnp.int32)
    cfg = GenerationConfig(max_new_tokens=6, temperature=0.0)
    a = np.asarray(Generator(model, cfg).generate(params, prompt))
    b = np.asarray(Generator(model, cfg, layer_scan=False).generate(
        params, prompt))
    np.testing.assert_array_equal(a, b)
    with pytest.raises(ValueError, match="layer_scan"):
        Generator(model, GenerationConfig(max_new_tokens=2, num_beams=2),
                  layer_scan=False)


def test_data_parallel_generation_is_a_jit_sharding():
    """DP serving needs NO new machinery: the whole decode program is
    batch-parallel, so sharding the prompt's batch dim over a data axis
    (params replicated) partitions every cache and matmul batch-wise.
    Tokens match the unsharded run exactly."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pipe_tpu.parallel.mesh import make_mesh

    model, params = _model_and_params(n_stages=2)
    mesh = make_mesh(1, 4)   # 4-way data axis
    prompt = jax.random.randint(jax.random.key(40), (8, 6), 0, CFG.vocab,
                                jnp.int32)
    cfg = GenerationConfig(max_new_tokens=5, temperature=0.0)
    ref = np.asarray(Generator(model, cfg).generate(params, prompt))

    gen = Generator(model, cfg)
    sharded_prompt = jax.device_put(
        prompt, NamedSharding(mesh, P("data")))
    repl = NamedSharding(mesh, P())
    params_r = jax.tree_util.tree_map(
        lambda a: jax.device_put(jnp.asarray(a), repl), params)
    out = np.asarray(gen.generate(params_r, sharded_prompt))
    np.testing.assert_array_equal(out, ref)
