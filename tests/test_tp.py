"""Tensor parallelism (ops/tp_layers.py + ScheduledPipeline
stage_param_specs): sharding over the model axis is a layout choice, never
a math choice.

The yardstick is always the SAME parameters through the tp_axis=None
(unsharded) computation; tp=2 forward, loss, and every gradient leaf must
match to fp-reduction tolerance (VERDICT's transparency discipline applied
to the new strategy)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core import microbatch as mb
from pipe_tpu.core.partition import StageCtx
from pipe_tpu.models.tp_lm import TPPipelinedLM
from pipe_tpu.models.transformer_lm import LMConfig
from pipe_tpu.ops.tp_layers import (tp_block_apply, tp_block_init,
                                    tp_block_specs)
from pipe_tpu.parallel.mesh import MODEL_AXIS, make_mesh
from pipe_tpu.parallel.scheduled import ScheduledPipeline
from pipe_tpu.parallel.spmd import stack_stage_params
from pipe_tpu.utils.compat import shard_map

D, HEADS, FF, SEQ, ROWS = 16, 4, 32, 8, 2


def _tiny_cfg(n_layers=2):
    import dataclasses
    return dataclasses.replace(
        LMConfig().tiny(), d_model=D, nhead=HEADS, d_ff=FF, seq_len=SEQ,
        n_layers=n_layers, dropout=0.0)


def test_tp_block_matches_unsharded():
    """One block, tp=2, differentiated IN-PROGRAM (the executor contract:
    jax.vjp inside the shard_map body, grads never reduced over the model
    axis — sharded leaves local, replicated leaves model-identical via
    tp_enter) vs full params unsharded."""
    from jax.sharding import PartitionSpec as P

    params = tp_block_init(jax.random.key(0), D, HEADS, FF)
    h = jax.random.normal(jax.random.key(1), (ROWS, SEQ, D))
    mesh = make_mesh(1, 1, n_model=2, devices=jax.devices()[:2])

    def loss_unsharded(p, h):
        out = tp_block_apply(p, h, StageCtx(), tp_axis=None)
        return jnp.sum(out ** 2)

    l_ref, g_ref = jax.value_and_grad(loss_unsharded)(params, h)

    specs = tp_block_specs()
    grad_specs = jax.tree_util.tree_map(
        lambda s_: s_, specs, is_leaf=lambda v: isinstance(v, P))

    def device_program(p, h):
        def loss(p):
            out = tp_block_apply(p, h, StageCtx(), tp_axis=MODEL_AXIS)
            return jnp.sum(out ** 2)
        return jax.value_and_grad(loss)(p)

    run = shard_map(device_program, mesh=mesh,
                        in_specs=(specs, P()),
                        out_specs=(P(), grad_specs), check_vma=False)
    l_tp, g_tp = jax.jit(run)(params, h)
    np.testing.assert_allclose(float(l_tp), float(l_ref), rtol=1e-5)
    for (ka, a), (kb, b) in zip(
            jax.tree_util.tree_leaves_with_path(g_tp),
            jax.tree_util.tree_leaves_with_path(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5,
                                   err_msg=str(ka))


@pytest.mark.parametrize("n_stages,tp", [(1, 2), (2, 2)])
def test_pp_tp_loss_and_grad_transparency(n_stages, tp):
    """PP x TP through ScheduledPipeline(stage_param_specs=): loss and all
    grads match the unsharded (tp_axis=None) run of the same params."""
    cfg = _tiny_cfg(n_layers=n_stages)
    m = 4
    model_tp = TPPipelinedLM(cfg, n_stages)
    model_ref = TPPipelinedLM(cfg, n_stages, tp_axis=None)
    sp, prep, postp = model_ref.init(jax.random.key(0))
    stacked = stack_stage_params(sp)

    tokens = jax.random.randint(jax.random.key(1), (2 * m, cfg.seq_len),
                                0, cfg.vocab, jnp.int32)
    x, n_rows = mb.stack_scatter(
        {"tokens": tokens, "targets": jnp.roll(tokens, -1, -1)}, m)
    w = mb.valid_row_mask(x, n_rows)

    mesh_ref = make_mesh(n_stages, 1,
                         devices=jax.devices()[:n_stages])
    pipe_ref = ScheduledPipeline(
        mesh_ref, model_ref.stage_fn, pre_fn=model_ref.pre_fn,
        post_fn=model_ref.loss_post_fn, checkpoint="except_last",
        schedule="1f1b")
    l_ref, (g_ref, gpre_ref, gpost_ref) = jax.jit(pipe_ref.loss_and_grad)(
        stacked, prep, postp, x, w, key=jax.random.key(9))

    mesh_tp = make_mesh(n_stages, 1, n_model=tp,
                        devices=jax.devices()[:n_stages * tp])
    pipe_tp = ScheduledPipeline(
        mesh_tp, model_tp.stage_fn, pre_fn=model_tp.pre_fn,
        post_fn=model_tp.loss_post_fn, checkpoint="except_last",
        schedule="1f1b",
        stage_param_specs=model_tp.stage_param_specs())
    l_tp, (g_tp, gpre_tp, gpost_tp) = jax.jit(pipe_tp.loss_and_grad)(
        stacked, prep, postp, x, w, key=jax.random.key(9))

    np.testing.assert_allclose(float(l_tp), float(l_ref), rtol=1e-5)
    for name, got, exp in (("stage", g_tp, g_ref),
                           ("pre", gpre_tp, gpre_ref),
                           ("post", gpost_tp, gpost_ref)):
        for (ka, a), (kb, b) in zip(
                jax.tree_util.tree_leaves_with_path(got),
                jax.tree_util.tree_leaves_with_path(exp)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5,
                err_msg=f"{name}{ka}")


def test_pp_tp_dp_composition():
    """The full PP x DP x TP product on 8 virtual devices: finite loss,
    grads match the unsharded yardstick."""
    cfg = _tiny_cfg(n_layers=2)
    m = 2
    model_tp = TPPipelinedLM(cfg, 2)
    model_ref = TPPipelinedLM(cfg, 2, tp_axis=None)
    sp, prep, postp = model_ref.init(jax.random.key(0))
    stacked = stack_stage_params(sp)
    tokens = jax.random.randint(jax.random.key(1), (4 * m, cfg.seq_len),
                                0, cfg.vocab, jnp.int32)
    x, n_rows = mb.stack_scatter(
        {"tokens": tokens, "targets": jnp.roll(tokens, -1, -1)}, m)
    w = mb.valid_row_mask(x, n_rows)

    mesh_ref = make_mesh(2, 1, devices=jax.devices()[:2])
    pipe_ref = ScheduledPipeline(
        mesh_ref, model_ref.stage_fn, pre_fn=model_ref.pre_fn,
        post_fn=model_ref.loss_post_fn, checkpoint="never",
        schedule="1f1b")
    l_ref, (g_ref, _, _) = jax.jit(pipe_ref.loss_and_grad)(
        stacked, prep, postp, x, w, key=jax.random.key(9))

    mesh = make_mesh(2, 2, n_model=2, devices=jax.devices()[:8])
    pipe = ScheduledPipeline(
        mesh, model_tp.stage_fn, pre_fn=model_tp.pre_fn,
        post_fn=model_tp.loss_post_fn, checkpoint="never",
        schedule="1f1b",
        stage_param_specs=model_tp.stage_param_specs())
    l_tp, (g_tp, _, _) = jax.jit(pipe.loss_and_grad)(
        stacked, prep, postp, x, w, key=jax.random.key(9))

    np.testing.assert_allclose(float(l_tp), float(l_ref), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(g_tp),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)


def test_stage_param_specs_structure_mismatch_raises():
    cfg = _tiny_cfg(n_layers=1)
    model = TPPipelinedLM(cfg, 1)
    sp, prep, postp = model.init(jax.random.key(0))
    mesh = make_mesh(1, 1, n_model=2, devices=jax.devices()[:2])
    pipe = ScheduledPipeline(
        mesh, model.stage_fn, pre_fn=model.pre_fn,
        post_fn=model.loss_post_fn, checkpoint="never", schedule="1f1b",
        stage_param_specs={"wrong": "shape"})
    x, n_rows = mb.stack_scatter(
        {"tokens": jnp.zeros((2, cfg.seq_len), jnp.int32),
         "targets": jnp.zeros((2, cfg.seq_len), jnp.int32)}, 2)
    w = mb.valid_row_mask(x, n_rows)
    with pytest.raises((ValueError, TypeError)):
        pipe.loss_and_grad(stack_stage_params(sp), prep, postp, x, w)
