"""Overlapped boundary transport: transparency + shifted-table proofs.

The overlapped executor (``overlap_transport=True``) packs each direction's
boundary pytree into one uint32 carrier, issues exactly one ppermute per
direction per cycle, and runs the comm-shifted op tables from
``shift_comm_tables``. The contract under test:

* loss AND every grad leaf are BITWISE identical to the serialized path —
  across all four schedules, the three checkpoint modes, policy remat,
  skip lanes (multi-hop relay and 0-hop register), and PP x DP;
* ``verify_op_tables(comm_shift=2)`` proves the shifted timing and rejects
  a deliberately mis-shifted comm slot;
* ``pack_words``/``unpack_words`` round-trip bitwise for every dtype mix.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core import microbatch as mb
from pipe_tpu.core.schedule import (
    FWD, IDLE, get_schedule, shift_comm_tables, verify_op_tables,
    verify_shifted_op_tables, _times_by_code)
from pipe_tpu.parallel.buffers import pack_words, packed_words, unpack_words
from pipe_tpu.parallel.interleaved import stack_interleaved_params
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.scheduled import ScheduledPipeline, SkipLanes
from pipe_tpu.parallel.spmd import stack_stage_params

WIDTH = 8
ROWS = 4  # per-microbatch rows per data shard

lane_spec = jax.ShapeDtypeStruct((ROWS, WIDTH), jnp.float32)


def make_params(key, n_virtual):
    ks = jax.random.split(key, n_virtual)
    return [{"w": jax.random.normal(k, (WIDTH, WIDTH)) * 0.3,
             "b": jnp.zeros((WIDTH,))} for k in ks]


def pre_fn(prep, x_mb, ctx):
    return x_mb["x"]


def post_fn(postp, h, x_mb, ctx):
    return jnp.mean((h - x_mb["tgt"]) ** 2, axis=-1)


def plain_stage_fn(p, h, ctx):
    return jnp.tanh(h @ p["w"] + p["b"])


def lane_stage_fn(pairs):
    """Stage body that boards each lane at src and injects it at dst."""
    def stage_fn(p, h, ctx, pops):
        st = jnp.tanh(h @ p["w"] + p["b"])
        for (src, dst), pop in zip(pairs, pops):
            st = st + jnp.where(jnp.asarray(ctx.stage == dst), pop, 0.0)
        stashes = tuple(
            jnp.where(jnp.asarray(ctx.stage == src), st,
                      jnp.zeros((ROWS, WIDTH), jnp.float32))
            for (src, dst) in pairs)
        return st, stashes
    return stage_fn


def run_loss_and_grad(*, schedule, d, m, mode, overlap, pairs=(), v=1,
                      data=1, policy=None):
    mesh = make_mesh(d, data, devices=jax.devices()[:d * data])
    params = make_params(jax.random.key(0), v * d)
    stacked = (stack_stage_params(params) if v == 1
               else stack_interleaved_params(params, d))
    rows = ROWS * data
    x = jax.random.normal(jax.random.key(1), (m * rows, WIDTH))
    tgt = jax.random.normal(jax.random.key(2), (m * rows, WIDTH))
    xs, n_rows = mb.stack_scatter({"x": x, "tgt": tgt}, m)
    w = mb.valid_row_mask(xs, n_rows)
    lanes = SkipLanes(pairs=tuple(pairs),
                      specs=tuple(lane_spec for _ in pairs)) if pairs else None
    sf = lane_stage_fn(pairs) if pairs else plain_stage_fn
    pipe = ScheduledPipeline(mesh, sf, pre_fn=pre_fn, post_fn=post_fn,
                             checkpoint=mode, schedule=schedule,
                             skip_lanes=lanes, remat_policy=policy,
                             overlap_transport=overlap)
    loss, (gs, _, _) = jax.jit(
        lambda sp, xx, ww: pipe.loss_and_grad(
            sp, {}, {}, xx, ww, key=jax.random.key(9)))(stacked, xs, w)
    return loss, gs


def assert_bitwise(res0, res1):
    l0, g0 = res0
    l1, g1 = res1
    assert float(l0) == float(l1)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        assert float(jnp.max(jnp.abs(a - b))) == 0.0


# ---------------------------------------------------------------------------
# Transparency: overlap vs serialized, bitwise
# ---------------------------------------------------------------------------

# (id, schedule, d, m, mode, pairs, v, data, policy?)
TRANSPARENCY_CASES = [
    ("gpipe-always", "gpipe", 4, 8, "always", (), 1, 1, False),
    ("1f1b-never", "1f1b", 4, 8, "never", (), 1, 1, False),
    ("1f1b-except_last", "1f1b", 4, 8, "except_last", (), 1, 1, False),
    ("1f1b-policy-remat", "1f1b", 4, 8, "except_last", (), 1, 1, True),
    ("interleaved-v2", "interleaved-1f1b", 4, 8, "except_last",
     (), 2, 1, False),
    ("zb-h1-never", "zb-h1", 4, 8, "never", (), 1, 1, False),
    ("lane-3hop-1f1b", "1f1b", 4, 8, "except_last", ((0, 3),), 1, 1, False),
    ("lanes-dual-never", "1f1b", 4, 8, "never",
     ((0, 2), (1, 3)), 1, 1, False),
    ("lane-0hop-v2", "interleaved-1f1b", 4, 8, "except_last",
     ((0, 4),), 2, 1, False),
    ("ppxdp-4x2", "1f1b", 4, 8, "except_last", (), 1, 2, False),
]


@pytest.mark.parametrize(
    "schedule,d,m,mode,pairs,v,data,use_policy",
    [c[1:] for c in TRANSPARENCY_CASES],
    ids=[c[0] for c in TRANSPARENCY_CASES])
def test_overlap_transparency(schedule, d, m, mode, pairs, v, data,
                              use_policy):
    policy = jax.checkpoint_policies.dots_saveable if use_policy else None
    kw = dict(schedule=schedule, d=d, m=m, mode=mode, pairs=pairs, v=v,
              data=data, policy=policy)
    assert_bitwise(run_loss_and_grad(overlap=False, **kw),
                   run_loss_and_grad(overlap=True, **kw))


def test_memory_plan_reports_transport():
    mesh = make_mesh(4, 1, devices=jax.devices()[:4])
    mk = lambda ov: ScheduledPipeline(
        mesh, plain_stage_fn, pre_fn=pre_fn, post_fn=post_fn,
        checkpoint="except_last", schedule="1f1b", overlap_transport=ov)
    p0, p1 = mk(False).memory_plan(8), mk(True).memory_plan(8)
    assert p0["transport"] == "serialized"
    assert p1["transport"] == "overlapped"
    assert p1["grad_park_slots"] >= 1
    # comm shift stretches the clock: the schedule trades cycles for the
    # per-cycle collective being off the critical path
    assert p1["cycles"] > p0["cycles"]


def test_overlap_auto_off_on_cpu_and_single_stage():
    mesh = make_mesh(4, 1, devices=jax.devices()[:4])
    auto = ScheduledPipeline(mesh, plain_stage_fn, pre_fn=pre_fn,
                             post_fn=post_fn, schedule="1f1b")
    # cpu test platform: auto must resolve to serialized (perf + parity of
    # the existing cpu suites)
    assert auto._overlap_enabled() is False
    forced = ScheduledPipeline(mesh, plain_stage_fn, pre_fn=pre_fn,
                               post_fn=post_fn, schedule="1f1b",
                               overlap_transport=True)
    assert forced._overlap_enabled() is True
    single = ScheduledPipeline(
        make_mesh(1, 1, devices=jax.devices()[:1]), plain_stage_fn,
        pre_fn=pre_fn, post_fn=post_fn, schedule="1f1b",
        overlap_transport=True)
    # d == 1 has no transport at all
    assert single._overlap_enabled() is False


def test_disabled_telemetry_is_zero_cost_on_hot_path():
    """bench.py times its hot path under the null registry; this pins the
    claim that doing so changes NOTHING in the compiled program — the
    lowered HLO of a scheduled train step is byte-identical under the
    default (enabled) registry and the null registry, i.e. telemetry on
    this path is trace-time only."""
    from pipe_tpu.obs.telemetry import null_registry, set_registry

    def lowered():
        mesh = make_mesh(2, 1, devices=jax.devices()[:2])
        params = stack_stage_params(make_params(jax.random.key(0), 2))
        x = jax.random.normal(jax.random.key(1), (4 * ROWS, WIDTH))
        tgt = jax.random.normal(jax.random.key(2), (4 * ROWS, WIDTH))
        xs, n_rows = mb.stack_scatter({"x": x, "tgt": tgt}, 4)
        w = mb.valid_row_mask(xs, n_rows)
        pipe = ScheduledPipeline(mesh, plain_stage_fn, pre_fn=pre_fn,
                                 post_fn=post_fn, checkpoint="except_last",
                                 schedule="1f1b")
        return jax.jit(lambda sp, xx, ww: pipe.loss_and_grad(
            sp, {}, {}, xx, ww, key=jax.random.key(9))).lower(
            params, xs, w).as_text()

    base = lowered()
    prev = set_registry(null_registry())
    try:
        disabled = lowered()
    finally:
        set_registry(prev)
    assert base == disabled


def test_quick_probe_reports_transport_side_by_side():
    """The cpu8 quick probe (bench.py's measured_bubble_multistage source,
    `tools/multistage_probe.py --quick`) must report serialized and
    overlapped 1f1b side by side, each with a per-transport measured
    bubble."""
    from pipe_tpu.obs.bubble_probe import main as bubble_main
    out = bubble_main(2, 4, compare_schedules=True, compare_transport=True,
                      d_model=16, d_ff=32, seq_len=8, skip_slope=True,
                      iters=1)
    scheds = out["schedules"]
    assert {"1f1b", "1f1b-overlap", "1f1b+policy", "zb-h1"} <= set(scheds)
    for name in ("1f1b", "1f1b-overlap"):
        assert scheds[name]["sec_per_step"] > 0
        assert "measured_bubble" in scheds[name]


# ---------------------------------------------------------------------------
# Shifted-table proofs (host-only, no tracing)
# ---------------------------------------------------------------------------

def _tables(name, m, d, v=1):
    sched = (get_schedule(name, interleave=v) if name == "interleaved-1f1b"
             else get_schedule(name))
    tabs = sched.op_tables(m, d)
    return tabs if len(tabs) == 3 else (*tabs, None)


@pytest.mark.parametrize("name,v", [
    ("gpipe", 1), ("1f1b", 1), ("interleaved-1f1b", 2), ("zb-h1", 1)])
def test_shift_comm_tables_verify_all_schedules(name, v):
    m, d = 8, 4
    op0, mb0, grp0 = _tables(name, m, d, v)
    op, mbi, grp = shift_comm_tables(op0, mb0, grp0, m=m, d=d, v=v)
    verify_shifted_op_tables(op, mbi, grp if grp0 is not None else None,
                             m=m, d=d, v=v,
                             splits_backward=(name == "zb-h1"))
    # every forward hop respects the 2-cycle in-flight window
    t_f, t_b, _ = _times_by_code(op, mbi, grp, m, d, v)
    S = v * d
    assert (t_f[:, 1:] - t_f[:, :-1] >= 2).all()
    assert (t_b[:, :-1] - t_b[:, 1:] >= 2).all()


def test_verify_op_tables_rejects_misshifted_comm_slot():
    m, d = 8, 4
    op0, mb0, _ = _tables("1f1b", m, d)
    op, mbi, _ = shift_comm_tables(op0, mb0, None, m=m, d=d)
    # the shifted table passes the overlapped contract...
    verify_op_tables(op, mbi, m, d, comm_shift=2)
    # ...then sabotage one comm slot: pull a tight FWD one cycle earlier
    # (into an idle slot on its device) so it reads an in-flight value
    t_f, _, _ = _times_by_code(op, mbi, None, m, d, 1)
    moved = False
    for t in range(1, op.shape[0]):
        for p in range(1, d):
            if (op[t, p] == FWD and op[t - 1, p] == IDLE
                    and t == t_f[mbi[t, p], p - 1] + 2):
                op2, mb2 = op.copy(), mbi.copy()
                op2[t - 1, p], mb2[t - 1, p] = op2[t, p], mb2[t, p]
                op2[t, p], mb2[t, p] = IDLE, 0
                moved = True
                break
        if moved:
            break
    assert moved, "no tight FWD with an idle predecessor slot found"
    with pytest.raises(AssertionError,
                       match="shifted comm slot violation"):
        verify_op_tables(op2, mb2, m, d, comm_shift=2)


def test_shift_comm_tables_noop_below_two_stages():
    m = 4
    op0, mb0, _ = _tables("1f1b", m, 1)
    op, mbi, grp = shift_comm_tables(op0, mb0, None, m=m, d=1)
    assert (op == op0).all() and (mbi == mb0).all()


# ---------------------------------------------------------------------------
# Packed word carrier: bitwise round-trip
# ---------------------------------------------------------------------------

def test_pack_words_roundtrip_bitwise():
    key = jax.random.key(3)
    tree = {
        "f32": jax.random.normal(key, (3, 5)),
        "bf16": jax.random.normal(jax.random.fold_in(key, 1),
                                  (7,)).astype(jnp.bfloat16),
        "f16": jax.random.normal(jax.random.fold_in(key, 2),
                                 (2, 3)).astype(jnp.float16),
        "i32": jnp.arange(-4, 5, dtype=jnp.int32),
        "u8": jnp.arange(11, dtype=jnp.uint8),
        "scalar": jnp.float32(2.5),
    }
    spec = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), x.dtype), tree)
    vec = jax.jit(pack_words)(tree)
    assert vec.dtype == jnp.uint32
    assert vec.shape == (packed_words(spec),)
    out = jax.jit(lambda w: unpack_words(w, spec))(vec)
    for a, b in zip(jax.tree_util.tree_leaves(tree),
                    jax.tree_util.tree_leaves(out)):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_pack_words_empty_and_bool():
    assert pack_words({}).shape == (0,)
    assert packed_words({}) == 0
    with pytest.raises(TypeError, match="bool"):
        pack_words({"flag": jnp.zeros((2,), jnp.bool_)})
