"""Stage-sharded parameters on the flagship mesh path (VERDICT r2 #1).

The reference's ``_split_module`` moves each partition to its own device
(reference ``pipe.py:191-218``, wired at ``pipe.py:344-356``) — each GPU
holds ONLY its stage's weights. These tests pin the TPU-native equivalent:
``Pipe.shard_params`` packs per-stage trees into per-dtype ``[n, cap]`` rows
sharded over the mesh's stage axis, each device's addressable bytes scale as
~total/n, and forward + gradients stay transparent in the packed layout.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu import Dropout, Linear, Pipe, Sequential
from pipe_tpu.core.packing import StageParamPack
from pipe_tpu.parallel.mesh import make_mesh

WIDTH = 8


def make_mlp(key, depth=4, width=WIDTH):
    seq = Sequential([Linear(width) for _ in range(depth)])
    params = seq.init(key, jnp.zeros((2, width)))
    return seq, params


def _regroup(flat_params, balance):
    out, off = [], 0
    for w in balance:
        out.append(flat_params[off:off + w])
        off += w
    return out


def stage_mesh(n_stages, n_data=1):
    return make_mesh(n_stages, n_data,
                     devices=jax.devices()[:n_stages * n_data])


def test_shard_unshard_roundtrip():
    seq, params = make_mlp(jax.random.key(0))
    pipe = Pipe(seq, chunks=2, checkpoint="never", mesh=stage_mesh(2))
    sp = _regroup(params, pipe.balance)
    packed = pipe.shard_params(sp)
    back = pipe.unshard_params(packed)
    for a, b in zip(jax.tree_util.tree_leaves(sp),
                    jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("chunks", [1, 2, 3])
@pytest.mark.parametrize("n_stages", [1, 2, 4])
def test_sharded_forward_matches_plain(chunks, n_stages):
    seq, params = make_mlp(jax.random.key(0))
    pipe = Pipe(seq, chunks=chunks, checkpoint="never",
                mesh=stage_mesh(n_stages))
    packed = pipe.shard_params(_regroup(params, pipe.balance))
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    np.testing.assert_allclose(np.asarray(pipe(packed, x)),
                               np.asarray(seq.apply(params, x)),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("checkpoint", ["never", "except_last", "always"])
def test_sharded_gradient_transparency(checkpoint):
    """grad with respect to the PACKED layout == plain-model grads after
    unshard — stage grads come back sharded with no stage-axis collectives."""
    seq, params = make_mlp(jax.random.key(0))
    pipe = Pipe(seq, chunks=4, checkpoint=checkpoint, mesh=stage_mesh(2))
    sp = _regroup(params, pipe.balance)
    packed = pipe.shard_params(sp)
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))

    expected = jax.grad(lambda p: jnp.mean(seq.apply(p, x) ** 2))(params)
    gp = jax.grad(lambda pk: jnp.mean(pipe(pk, x, train=True) ** 2))(packed)
    got = pipe.unshard_grads(gp)
    flat_e = jax.tree_util.tree_leaves(_regroup(expected, pipe.balance))
    flat_g = jax.tree_util.tree_leaves(got)
    assert len(flat_e) == len(flat_g)
    for e, g in zip(flat_e, flat_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                   rtol=1e-4, atol=1e-5)


def test_sharded_grads_with_data_axis():
    """PP x DP in the packed layout: AD inserts the data-axis psum for the
    replicated rows; stage rows need no collective at all."""
    seq, params = make_mlp(jax.random.key(0))
    pipe = Pipe(seq, chunks=2, checkpoint="except_last",
                mesh=stage_mesh(2, n_data=2))
    packed = pipe.shard_params(_regroup(params, pipe.balance))
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))

    expected = jax.grad(lambda p: jnp.mean(seq.apply(p, x) ** 2))(params)
    gp = jax.grad(lambda pk: jnp.mean(pipe(pk, x, train=True) ** 2))(packed)
    got = pipe.unshard_grads(gp)
    for e, g in zip(jax.tree_util.tree_leaves(_regroup(expected,
                                                       pipe.balance)),
                    jax.tree_util.tree_leaves(got)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                   rtol=1e-4, atol=1e-5)


def test_sharded_dropout_and_jit():
    seq = Sequential([Linear(WIDTH), Dropout(0.5), Linear(WIDTH)])
    pipe = Pipe(seq, chunks=2, checkpoint="never", mesh=stage_mesh(2),
                balance=[2, 1])
    packed = pipe.init_sharded(jax.random.key(0), jnp.zeros((2, WIDTH)))
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))

    @jax.jit
    def fwd(pk, k):
        return pipe(pk, x, key=k, train=True)

    a = fwd(packed, jax.random.key(42))
    b = fwd(packed, jax.random.key(42))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.allclose(np.asarray(a),
                           np.asarray(fwd(packed, jax.random.key(43))))


def test_uneven_heterogeneous_sharded_matches_emulator():
    """Uneven balance + shape-varying boundaries in the packed layout."""
    seq = Sequential([Linear(WIDTH), Linear(16), Linear(WIDTH), Linear(WIDTH)])
    params = seq.init(jax.random.key(0), jnp.zeros((2, WIDTH)))
    balance = [3, 1]
    mesh_pipe = Pipe(seq, chunks=2, checkpoint="never", mesh=stage_mesh(2),
                     balance=balance)
    emu_pipe = Pipe(seq, chunks=2, checkpoint="never", balance=balance)
    sp = _regroup(params, balance)
    packed = mesh_pipe.shard_params(sp)
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    np.testing.assert_allclose(np.asarray(mesh_pipe(packed, x)),
                               np.asarray(emu_pipe(sp, x)),
                               rtol=1e-5, atol=1e-5)


def test_per_device_bytes_scale():
    """4 equal stages: each device's addressable param bytes == total/4 —
    the memory scaling that is pipeline parallelism's reason to exist."""
    seq, params = make_mlp(jax.random.key(0), depth=4)
    pipe = Pipe(seq, chunks=2, checkpoint="never", mesh=stage_mesh(4))
    packed = pipe.shard_params(_regroup(params, pipe.balance))

    total = sum(np.asarray(l).nbytes
                for l in jax.tree_util.tree_leaves(params))
    per_dev: dict = {}
    for arr in packed.values():
        for sh in arr.addressable_shards:
            per_dev[sh.device] = per_dev.get(sh.device, 0) + sh.data.nbytes
    assert len(per_dev) == 4
    for dev, nbytes in per_dev.items():
        assert nbytes == total // 4, (dev, nbytes, total)
    # the pack's own accounting agrees with the buffers
    assert pipe._executor.param_pack.per_device_bytes() == total // 4


def test_foreign_packed_layout_rejected():
    """A packed dict whose buffer layout does not match this Pipe's pack is
    rejected at call time ([3,1] vs [2,2] differ in capacity; NOTE mirror
    balances like [3,1]/[1,3] produce byte-identical layouts and cannot be
    told apart — that residual ambiguity is documented in check_packed)."""
    seq = Sequential([Linear(WIDTH) for _ in range(4)])
    params = seq.init(jax.random.key(0), jnp.zeros((2, WIDTH)))
    pa = Pipe(seq, chunks=2, checkpoint="never", mesh=stage_mesh(2),
              balance=[3, 1])
    pb = Pipe(seq, chunks=2, checkpoint="never", mesh=stage_mesh(2),
              balance=[2, 2])
    packed_a = pa.shard_params(_regroup(params, [3, 1]))
    pb.shard_params(_regroup(params, [2, 2]))  # pb has its own pack
    x = jnp.ones((4, WIDTH))
    with pytest.raises(ValueError):
        pb(packed_a, x)
    # and a wrong stage count at shard time fails fast
    with pytest.raises(ValueError):
        pa.shard_params(_regroup(params, [2, 1, 1]))


def test_packed_params_need_shard_params_first():
    seq, params = make_mlp(jax.random.key(0))
    p1 = Pipe(seq, chunks=2, checkpoint="never", mesh=stage_mesh(2))
    p2 = Pipe(seq, chunks=2, checkpoint="never", mesh=stage_mesh(2))
    packed = p1.shard_params(_regroup(params, p1.balance))
    x = jnp.ones((4, WIDTH))
    with pytest.raises(ValueError):
        p2(packed, x)
    emu = Pipe(seq, chunks=2, checkpoint="never", n_stages=2)
    with pytest.raises(TypeError):
        emu(packed, x)


def test_tutorial_520m_per_device_bytes():
    """The VERDICT r2 #1 'done' bar: the 520M tutorial config through
    Pipe(mesh=, n_stages=4) on the cpu8 mesh, each device holding ~total/4
    param bytes (reference model: 520,900,718 params, README.md:570)."""
    from pipe_tpu.models.transformer_lm import LMConfig, build_sequential
    import dataclasses

    cfg = dataclasses.replace(LMConfig(), seq_len=32, dropout=0.0)
    seq = build_sequential(cfg)
    # embed+posenc+3 blocks | 5 blocks | 5 blocks | 3 blocks+decoder:
    # ≈134M / 126M / 126M / 134M params — near-uniform cost split.
    balance = [5, 5, 5, 4]
    pipe = Pipe(seq, chunks=2, checkpoint="except_last",
                mesh=stage_mesh(4), balance=balance)
    tokens = jnp.zeros((1, cfg.seq_len), jnp.int32)
    sp = pipe.init(jax.random.key(0), tokens)
    n_params = sum(int(np.prod(np.shape(l)))
                   for l in jax.tree_util.tree_leaves(sp))
    assert n_params > 4e8, n_params  # the real tutorial scale
    packed = pipe.shard_params(sp)
    del sp

    total = sum(arr.nbytes for arr in packed.values())
    per_dev: dict = {}
    for arr in packed.values():
        for sh in arr.addressable_shards:
            per_dev[sh.device] = per_dev.get(sh.device, 0) + sh.data.nbytes
    assert len(per_dev) == 4
    for dev, nbytes in per_dev.items():
        # cap = largest stage -> per-device <= ~1.07x of total/4 here
        assert nbytes <= 1.1 * total / 4, (dev, nbytes, total)

    # and the model still runs end to end in the packed layout
    x = jax.random.randint(jax.random.key(1), (2, cfg.seq_len),
                           0, cfg.vocab, jnp.int32)
    out = pipe(packed, x)
    assert out.shape == (2, cfg.seq_len, cfg.vocab)
    assert bool(jnp.isfinite(out).all())
