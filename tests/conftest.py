"""Test configuration: run the suite on a virtual 8-device CPU platform.

This is the TPU-build analogue of the reference's CPU-sentinel-stream trick
(``AbstractStream`` admitting a CPU fallback, reference pipe.py:22,
pipeline.py:22): every layer — scheduler, SPMD pipeline, ppermute rings,
checkpointing — runs on plain CPU with a simulated 8-device mesh, so the full
multi-"device" suite needs no TPUs and no cluster. See
``pipe_tpu.utils.platform`` for why this is done via jax.config rather than
env vars on this machine.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pipe_tpu.utils.platform import force_cpu_platform

force_cpu_platform(num_devices=8)


# ---------------------------------------------------------------------------
# Smoke tier (`pytest -m smoke`, ~3 min): one transparency case per
# executor x schedule x checkpoint mode plus one per major subsystem —
# enough to catch a broken executor/schedule/mode quickly; the full matrix
# stays the CI bar. Selected by exact nodeid so the set is explicit and
# greppable; a listed id that stops collecting fails loudly below.
_SMOKE = {
    # emulator (flagship default path): forward + grads
    "test_pipe.py::test_forward_transparency[2-4]",
    "test_pipe.py::test_gradient_transparency[never]",
    "test_pipe.py::test_gradient_transparency[except_last]",
    "test_pipe.py::test_gradient_transparency[always]",
    # AD wavefront executor (gpipe) + mesh Pipe front door
    "test_spmd.py::test_forward_transparency[4]",
    "test_spmd.py::test_gradient_transparency[except_last]",
    "test_pipe_mesh.py::test_gradient_transparency_mesh[except_last]",
    "test_pipe_mesh.py::test_skip_through_mesh_matches_emulator[4-None]",
    # table executor: 1f1b/gpipe/zb tables x modes, policy, skips, BN
    "test_scheduled.py::test_loss_and_grad_transparency[2-8-never-1f1b]",
    "test_scheduled.py::"
    "test_loss_and_grad_transparency[2-8-except_last-1f1b]",
    "test_scheduled.py::test_loss_and_grad_transparency[2-8-always-1f1b]",
    "test_scheduled.py::"
    "test_loss_and_grad_transparency[2-8-except_last-gpipe]",
    "test_scheduled.py::test_remat_policy_transparency_dynamic"
    "[2-except_last]",
    "test_scheduled.py::test_skip_lanes_raw_executor[except_last]",
    "test_pipe_1f1b.py::test_loss_and_grad_transparency[except_last-1f1b]",
    "test_pipe_1f1b.py::test_skippable_through_table_executor"
    "[never-1f1b]",
    "test_norm.py::test_table_executor_bn_matches_emulator"
    "[except_last-1f1b]",
    # overlapped packed transport: one bitwise-parity case + the shifted-
    # table proof that backs every overlapped run
    "test_overlap_transport.py::test_overlap_transparency"
    "[1f1b-except_last]",
    "test_overlap_transport.py::"
    "test_verify_op_tables_rejects_misshifted_comm_slot",
    # the zero-cost-telemetry HLO pin behind the headline timing (the
    # quick cpu8 transport probe itself is a ~60s benchmark — slow tier)
    "test_overlap_transport.py::"
    "test_disabled_telemetry_is_zero_cost_on_hot_path",
    # interleaved (train + the forward/eval executor)
    "test_interleaved.py::test_interleaved_pipe_forward_matches_emulator",
    "test_pipe_1f1b.py::test_interleaved_1f1b_through_pipe",
    # zero-bubble split tables + the crossover model; W-op IR verifier
    # and the auto-derived structural split (round 6)
    "test_zb_split.py::test_zb_split_transparency[2-8]",
    "test_zb_model.py::test_breakeven_sigma_is_the_exact_boundary",
    "test_zb_tables.py::test_w_tables_verify[8-4-zb-h1]",
    "test_zb_tables.py::test_verifier_rejects_w_before_its_b",
    "test_auto_split.py::test_auto_split_transparency[zb-h1-2-8]",
    "test_custom_schedule.py::test_custom_w_table_runs_split_executor",
    # core data structures + parallelism composition + serving
    "test_microbatch.py::test_scatter_gather_identity",
    "test_schedule.py::test_clock_cycles_matches_reference",
    "test_tp.py::test_pp_tp_loss_and_grad_transparency[2-2]",
    "test_moe.py::test_pp_dp_ep_loss_and_grad_transparency",
    "test_zero.py::test_zero_losses_match_replicated",
    "test_losses.py::test_loss_block_through_pipelined_step",
    "test_generate.py::test_greedy_generation_matches_naive_reforward",
    "test_pipelined_gen.py::"
    "test_pipelined_greedy_matches_single_device[2-4-8-6]",
    # serve engine: the parity + zero-recompile pin on both backends,
    # and the queue's three liveness behaviours
    "test_serve.py::test_staggered_arrivals_match_one_shot_generator"
    "[single]",
    "test_serve.py::test_staggered_arrivals_match_one_shot_generator"
    "[ring]",
    "test_serve.py::test_backpressure_rejects_when_full",
    "test_serve.py::test_deadline_timeout_retires_running_slot",
    "test_serve.py::test_cancellation_frees_slot",
    # phase-compiled executor: one bitwise-parity case per lowering shape
    # (scan steady state, scan-free unroll), the loud rejection path, and
    # the front-door plumbing
    "test_phase_compile.py::test_phased_bitwise_parity[never-1f1b]",
    "test_phase_compile.py::test_phased_bitwise_parity[never-zb-h1]",
    "test_phase_compile.py::test_phased_bitwise_parity_interleaved",
    "test_phase_compile.py::test_rejected_table_falls_back_loudly",
    "test_phase_compile.py::test_front_door_phase_compile_plumbing",
    # schedules-as-data: a user-authored op table through the front door
    "test_custom_schedule.py::test_custom_table_through_pipe_front_door",
    # resident serve loop: the fused-loop parity pin and the speculative
    # lane's bitwise-acceptance pin (PR 11)
    "test_resident.py::test_resident_matches_single_chunk_tick"
    "[single-slab-greedy]",
    "test_resident.py::test_speculative_decode_matches_generator"
    "[slab-greedy]",
    # resilience: the byte-identical-opt-out pin, one recovery path per
    # layer (train skip-step, serve containment), and the verifiable save
    "test_resilience.py::test_train_step_hlo_unchanged_by_resilience",
    "test_resilience.py::test_skip_step_on_injected_nan",
    "test_resilience.py::test_prefill_error_contained_to_one_request",
    "test_resilience.py::"
    "test_checkpoint_manifest_verifies_and_names_corrupt_leaf",
}


# ---------------------------------------------------------------------------
# Slow tier: the heaviest parametrizations, excluded from the tier-1 gate
# (`-m 'not slow'`, 870 s budget — ROADMAP.md) so the default run finishes
# inside it; `-m slow` (or `-m ''`) runs the full matrix. Every entry here
# is a heavyweight duplicate of coverage a lighter kept test (often a smoke
# id) still exercises — nothing is the ONLY test of its feature. Selected
# by exact nodeid, same contract as _SMOKE; overlap with _SMOKE is a
# conftest bug and asserted against below.
_SLOW = {
    # 520M-config byte accounting: minutes of param init, no exec coverage
    "test_sharded_params.py::test_tutorial_520m_per_device_bytes",
    # model-zoo end-to-end trainers; test_model_zoo.py keeps per-family
    # gradient/training coverage at CI size
    "test_apps.py::test_zoo_families[gpt2-1f1b]",
    "test_apps.py::test_zoo_families[bert-interleaved-1f1b]",
    "test_apps.py::test_zoo_families[vit-gpipe]",
    # tutorial-driver e2e + heaviest CLI resume paths;
    # test_generate_cli_single_and_pipelined and the checkpoint roundtrip
    # tests keep the save/resume contract in tier 1
    "test_apps.py::test_lm_tutorial_tiny",
    "test_apps.py::test_generate_cli_resume_roundtrip",
    "test_apps.py::test_generate_cli_resume_interleaved_layout",
    "test_apps.py::test_generate_cli_context_shards",
    # heavyweight duplicates of kept transparency/parity coverage
    "test_spmd.py::test_remat_post_parity",
    "test_transformer_lm.py::test_spmd_lm_loss_mode_and_grads",
    "test_transformer_lm.py::test_spmd_lm_train_step_converges",
    "test_rng.py::test_rbg_key_through_compiled_pipeline",
    "test_long_context.py::test_pp_cp_gradient_flows_and_matches",
    "test_pipe_mesh.py::test_tutorial_lm_through_pipe_mesh",
    "test_pipe_1f1b.py::test_integer_inputs_through_table_executor",
    "test_pipe_1f1b.py::test_dropout_determinism_1f1b",
    "test_resilience.py::test_guarded_no_fault_matches_unguarded_bitwise",
    "test_model_zoo.py::test_vit_gradients_flow",
    "test_balance_obs.py::test_profile_trace_writes",
    # trainer e2e: interleaved + zb-h1 trainers stay, these two are the
    # slowest of the four near-identical bodies
    "test_data_train.py::test_1f1b_trainer",
    "test_data_train.py::test_autosave_on_stop_signal",
    "test_data_train.py::test_trainer_generate_from_state",
    # generation: the naive-reforward parity cases at family scale;
    # test_generate.py keeps the base-model parity + pipelined parity
    "test_generate.py::test_gpt2_greedy_generation_matches_naive_reforward",
    "test_generate.py::test_beam_search_scores_are_consistent_and_beat_greedy",
    "test_moe_gen.py::test_moe_greedy_generation_matches_naive_reforward",
    "test_quant.py::test_quantized_decode_faithful_on_trained_model",
    # zb split: the d=1 static-unroll duplicates (the [2-8] dynamic case
    # and the smoke ids keep the split contract in tier 1)
    "test_zb_split.py::test_zb_split_transparency[1-4]",
    "test_auto_split.py::test_auto_split_transparency[zb-h1-1-4]",
    # ------------------------------------------------------------------
    # Expansion sized from a clean single-core duration profile
    # (--durations=0, uncontended): the pre-expansion default run measured
    # 1256s vs the 870s budget; the entries below cut ~478s of measured
    # call time. Per entry, the coverage that stays in tier 1 is named.
    #
    # the ~60s cpu8 transport benchmark; test_overlap_transparency's
    # 12-case parity matrix + the telemetry HLO pin stay
    "test_overlap_transport.py::"
    "test_quick_probe_reports_transport_side_by_side",
    # mesh/interleaved BatchNorm: one case per axis layout stays
    # (skip_interleaved, table_executor_bn smoke + gpipe, running stats,
    # *_with_data_axis); these are the heavyweight grad-parity dupes
    "test_norm.py::test_mesh_bn_data_axis_grads_match_emulator",
    "test_norm.py::test_mesh_bn_training_grads_match_emulator[never]",
    "test_norm.py::test_mesh_bn_training_grads_match_emulator[always]",
    "test_norm.py::test_mesh_bn_interleaved_matches_emulator"
    "[except_last-pp]",
    "test_norm.py::test_mesh_bn_interleaved_matches_emulator"
    "[except_last-ppxdp]",
    "test_norm.py::test_mesh_bn_interleaved_matches_emulator[never-pp]",
    "test_norm.py::test_mesh_bn_interleaved_matches_emulator"
    "[never-ppxdp]",
    "test_norm.py::test_table_executor_bn_matches_emulator[never-1f1b]",
    # phased executor parity grid: smoke keeps [never-1f1b]/[never-zb-h1]/
    # interleaved/rejection/front-door; skip_lanes[never], policy_ulp and
    # pp_dp stay as the per-shape reps ([never-gpipe] moved below, PR 17)
    "test_phase_compile.py::test_phased_bitwise_parity[except_last-gpipe]",
    "test_phase_compile.py::test_phased_bitwise_parity[except_last-zb-h1]",
    "test_phase_compile.py::test_phased_bitwise_parity[except_last-1f1b]",
    "test_phase_compile.py::test_phased_bitwise_parity[always-gpipe]",
    "test_phase_compile.py::test_phased_bitwise_parity[always-zb-h1]",
    "test_phase_compile.py::test_phased_bitwise_parity[always-1f1b]",
    "test_phase_compile.py::test_phased_bitwise_parity_skip_lanes"
    "[except_last]",
    "test_phase_compile.py::test_accepted_table_counts_and_gauges",
    "test_phase_compile.py::test_uniform_probe_failure_warns_and_trains",
    # serve: the smoke set keeps both-backend parity + the three queue
    # liveness behaviours; generator_eos_masks / shape_cache_counters stay
    "test_serve.py::test_serve_eos_retires_early",
    "test_serve.py::test_chunked_decode_parity",
    "test_serve.py::test_sampled_decode_parity",
    "test_serve.py::test_pipelined_eos_matches_single_device",
    # fleet router: the stub-backend suite keeps exactly-once/failover/
    # health gating in tier 1; this is the real-model bitwise dupe
    "test_router.py::test_kill_failover_token_parity_real_model",
    # mesh Pipe grad parametrizations; smoke keeps [except_last] +
    # skip_through_mesh, and the forward/uneven-matches-plain grid stays
    "test_pipe_mesh.py::test_gradient_transparency_mesh[always]",
    "test_pipe_mesh.py::test_gradient_transparency_mesh[never]",
    "test_pipe_mesh.py::test_skip_gradients_through_mesh[never]",
    "test_pipe_mesh.py::test_skip_gradients_through_mesh[always]",
    "test_pipe_mesh.py::test_uneven_balance_mesh_gradients_match_emulator",
    # table-executor loss/grad grid dupes: smoke keeps [except_last-1f1b];
    # [never-gpipe]/[always-zb-h1]/[always-1f1b] + test_scheduled's own
    # 65-case matrix keep every schedule x mode pairing in tier 1
    "test_pipe_1f1b.py::test_loss_and_grad_transparency[always-gpipe]",
    "test_pipe_1f1b.py::test_loss_and_grad_transparency[except_last-gpipe]",
    "test_pipe_1f1b.py::test_loss_and_grad_transparency"
    "[except_last-zb-h1]",
    "test_pipe_1f1b.py::test_loss_and_grad_transparency[never-1f1b]",
    "test_pipe_1f1b.py::test_skippable_interleaved"
    "[except_last-same-device-lane]",
    "test_pipe_1f1b.py::test_skippable_interleaved"
    "[except_last-cross-device-lane]",
    "test_pipe_1f1b.py::test_loss_and_grad_transparency[never-zb-h1]",
    # heavyweight exactness dupe of the kept [2-8-except_last-1f1b]
    # transparency smoke id
    "test_scheduled.py::test_except_last_is_exact_per_microbatch",
    # zoo trainers at family scale; *_matches_sequential/_plain + the
    # embed-skip and loss-stat tests keep each family's math in tier 1
    "test_model_zoo.py::test_gpt2_trains_through_scheduled_1f1b",
    "test_model_zoo.py::test_bert_through_interleaved_1f1b",
    # auto-split: smoke [zb-h1-2-8] + unit_parity_and_censuses +
    # unused-param-leaf keep the structural-split contract; these are the
    # bigger-table dupes and the whole-program HLO census
    "test_auto_split.py::test_phased_auto_split_whole_program_census",
    "test_auto_split.py::test_auto_split_transparency[zb-h1-4-4]",
    "test_auto_split.py::test_auto_split_transparency[zb-h2-4-8]",
    # ZeRO: the smoke loss-parity case keeps the optimizer contract;
    # these assert sharding layout / dtype composition on top of it
    "test_zero.py::test_zero_moments_are_data_sharded",
    "test_zero.py::test_mu_dtype_bf16_composes_with_zero",
    # pp x cp: gradient_flows is already slow; debug_context_check and
    # the [2-2]/[2-4]/[4-2] forward params stay
    "test_long_context.py::test_pp_cp_trains",
    "test_long_context.py::test_pp_cp_forward_transparency[1-8]",
    # context-sharded generation: two greedy + two beam params and the
    # sampling-reproducibility case stay; beam dispatch is also covered
    # by test_generate.py::test_beam_k1_path_and_generate_dispatch
    "test_long_context_gen.py::"
    "test_context_sharded_beam_generate_routes_to_beam",
    "test_long_context_gen.py::"
    "test_context_sharded_greedy_matches_single_device[2-2-16-6]",
    "test_long_context_gen.py::"
    "test_context_sharded_beam_matches_single_device[4-2-16-4-2]",
    # parametrized dupes of kept siblings ([2-2] smoke tp case,
    # gradient_parity[True], beam[2], ffn[1], spmd [except_last] smoke)
    "test_tp.py::test_pp_tp_loss_and_grad_transparency[1-2]",
    "test_ring_attention.py::test_gradient_parity[False]",
    "test_tp_gen.py::test_tp_sharded_beam_matches_unsharded[4]",
    "test_moe.py::test_moe_ffn_matches_unsharded[2]",
    "test_spmd.py::test_gradient_transparency[never]",
    "test_spmd.py::test_gradient_transparency[always]",
    # quantized decode: beam_runs + the two unit tests keep int8 decode
    # in tier 1; the faithful-decode e2e above is already slow
    "test_quant.py::test_quantized_pipelined_decode_runs",
    # signal-handling e2e; test_data_train's autosave_on_stop_signal
    # (slow) is the same contract at trainer level, and the smoke
    # resilience ids keep recovery in tier 1
    "test_resilience.py::test_sigterm_autosave_resumes_next_step_bitwise",
    # one grad param stays ([always]); forward grid + smoke forward stay
    "test_interleaved.py::test_gradient_transparency[never]",
    # event-file plumbing dupes: telemetry's trainer_emits_events_and_
    # step_reports and tb's scalar_writer_roundtrip stay
    "test_tb.py::test_trainer_emits_event_files",
    "test_telemetry.py::test_uniform_fastpath_taken_and_gauged",
    # cross-model parity dupe; ulysses_matches_ring + gradient_parity stay
    "test_ulysses.py::test_pp_cp_ulysses_matches_ring_model",
    # jit-sharding assertion; all generation-parity cases stay
    "test_generate.py::test_data_parallel_generation_is_a_jit_sharding",
    # resident-loop duplicates: the kept cases (single-slab greedy +
    # sampled, single-paged greedy, ring-slab greedy, the single trace
    # pin, both spec greedy/sampled reps) pin every layout x backend x
    # sampling mode at least once in tier 1; these re-run the same
    # programs on the remaining crossings
    "test_resident.py::test_resident_matches_single_chunk_tick"
    "[single-paged-sampled]",
    "test_resident.py::test_resident_matches_single_chunk_tick"
    "[ring-slab-sampled]",
    "test_resident.py::test_resident_matches_single_chunk_tick"
    "[ring-paged-greedy]",
    "test_resident.py::test_resident_matches_single_chunk_tick"
    "[ring-paged-sampled]",
    "test_resident.py::test_resident_traces_once_and_counts_host_syncs"
    "[ring]",
    "test_resident.py::test_speculative_decode_matches_generator"
    "[slab-sampled]",
    "test_resident.py::test_speculative_decode_matches_generator"
    "[paged-greedy]",
    # paged-KV ring-backend duplicates: the [single] twins keep every
    # pool feature (staggered parity + one-program pin, COW prefix
    # parity, sampled parity) in tier 1; the ring backend's paged path
    # re-runs the same pins on the stage-sharded executor in the full
    # matrix
    "test_kvpool.py::test_paged_staggered_parity_and_one_program[ring]",
    "test_kvpool.py::test_shared_prefix_cow_parity[ring]",
    "test_kvpool.py::test_paged_sampled_parity_ring_matches_slab_ring",
    # ------------------------------------------------------------------
    # Second expansion (PR 17), sized from a fresh single-core profile
    # (--durations=0, uncontended): the default run had crept to 952s vs
    # the 870s budget, and this 1-core host shows ~±6% run-to-run
    # variance, so the target is ~790s measured. The entries below cut
    # ~160s of measured call time. Kept coverage per entry:
    #
    # ~67s, three full train-step compiles under different kill scopes —
    # the heaviest single tier-1 test; the (slow) elastic drill exercises
    # heartbeat kill-detection end-to-end and persistent_hop_drop_and_
    # hop_health keeps hop health in tier 1
    "test_elastic.py::test_kill_heartbeat_localizes_stage",
    # [except_last] stays as the dropout-key-folding rep (it covers both
    # remat'd and non-remat'd stages in one run); the 65-case loss/grad
    # matrix keeps every checkpoint mode on this executor
    "test_scheduled.py::test_dropout_matches_ad_executor_bitwise[always]",
    "test_scheduled.py::test_dropout_matches_ad_executor_bitwise[never]",
    # mirror of the spmd pattern above: [except_last] stays as the rep
    "test_sharded_params.py::test_sharded_gradient_transparency[never]",
    "test_sharded_params.py::test_sharded_gradient_transparency[always]",
    # interleaved trainer stays as the trainer-level e2e; zb-h1 schedule
    # math is pinned by the [never-zb-h1] phase smoke + zb_split/zb_tables
    "test_data_train.py::test_zb_h1_trainer",
    # [greedy] + the int8 run-identical drill keep the engine-level
    # offload/restore path in tier 1; sampled paged-decode parity is held
    # by test_kvpool's sampled parity twin
    "test_kv_radix.py::test_engine_offload_restore_bitwise_fp32[sampled]",
    # gen-1 head-parking drill superseded in tier 1 by test_kv_radix's
    # admission pins (blocked-head counter, priority-respecting skip);
    # the full matrix keeps the parking path
    "test_kvpool.py::test_admission_parks_at_head_until_blocks_free",
    # unit-level dupes of kept composition smokes: the [2-2] pp x tp
    # smoke + tp_gen/tp beam parity keep TP math; ffn[1] keeps MoE
    "test_tp.py::test_tp_block_matches_unsharded",
    # phased gpipe rides the same scan lowering as the kept [never-1f1b]
    # / [never-zb-h1] smokes; table-level gpipe parity stays via the
    # scheduled [2-8-except_last-gpipe] smoke
    "test_phase_compile.py::test_phased_bitwise_parity[never-gpipe]",
    # per-crossing parity dupes; the named sibling params stay in tier 1
    # ([4-2-16-5] greedy cp rep, [2-4]/[4-2] pp x cp forwards,
    # [2-4-8-6-3] beam, [2-4-8-6] greedy smoke + [2-2-8-1] one-token
    # edge, [4-2-8-4] tp_gen greedy)
    "test_long_context_gen.py::"
    "test_context_sharded_greedy_matches_single_device[4-1-32-4]",
    "test_long_context.py::test_pp_cp_forward_transparency[2-2]",
    "test_pipelined_gen.py::"
    "test_pipelined_beam_matches_single_device[4-4-5-4-2]",
    "test_pipelined_gen.py::"
    "test_pipelined_greedy_matches_single_device[4-4-5-5]",
    "test_tp_gen.py::test_tp_sharded_greedy_matches_unsharded[2-2-8-6]",
    # vit family-scale dupe: vit_pipelined_matches_sequential + the
    # pipe_1f1b uneven-balance test keep both contracts
    "test_model_zoo.py::test_vit_uneven_balance_through_pipe_mesh",
    # table-executor BN gpipe crossings: the [except_last-1f1b] smoke
    # keeps BN-through-table; gpipe tables stay via the scheduled smoke
    "test_norm.py::test_table_executor_bn_matches_emulator"
    "[except_last-gpipe]",
    "test_norm.py::test_table_executor_bn_matches_emulator[never-gpipe]",
    # ------------------------------------------------------------------
    # Gen-2 speculative decode (PR 18): the heavy runtime drills
    # (5-10s each, ~55s total) all ride the slow tier — a clean
    # tier-1 run already sits within ~40s of the 870s budget BEFORE
    # this family, so there is no room for even one rep.
    # tests/test_draft.py keeps the fast gen-2 unit pins (tree
    # geometry, draft resolution, cost model, planner
    # self-consistency) in tier 1; every parity/trace contract below
    # runs in the full suite.
    "test_resident.py::test_draft_sources_match_generator"
    "[truncated-slab-greedy]",
    "test_resident.py::test_draft_sources_match_generator"
    "[truncated-paged-sampled]",
    "test_resident.py::test_draft_sources_match_generator"
    "[tree2-slab-sampled]",
    "test_resident.py::test_draft_sources_match_generator"
    "[tree3-paged-greedy]",
    "test_resident.py::test_ring_speculative_matches_generator"
    "[ngram-slab-greedy]",
    "test_resident.py::test_ring_speculative_matches_generator"
    "[ngram-paged-sampled]",
    "test_resident.py::test_ring_speculative_matches_generator"
    "[truncated-slab-sampled]",
    "test_resident.py::test_ring_speculative_matches_generator"
    "[truncated-paged-greedy]",
    "test_resident.py::test_adaptive_k_shrink_grow_parity",
    "test_resident.py::test_spec_empty_history_slots[single]",
    "test_resident.py::test_spec_empty_history_slots[ring]",
    "test_resident.py::test_spec_eos_mid_accepted_run[single]",
    "test_resident.py::test_spec_eos_mid_accepted_run[ring]",
    # PR 11 ngram-spec crossing made redundant by the gen-2 family:
    # the slab-greedy twin stays tier-1, the paged resident program is
    # pinned by test_resident_matches_single_chunk_tick
    # [single-paged-greedy]
    "test_resident.py::test_speculative_decode_matches_generator"
    "[paged-sampled]",
}


def pytest_collection_modifyitems(config, items):
    overlap = _SLOW & _SMOKE
    assert not overlap, f"smoke ids must not be slow-marked: {overlap}"
    found = set()
    for item in items:
        nodeid = item.nodeid.split("tests/")[-1]
        if nodeid in _SMOKE:
            item.add_marker(pytest.mark.smoke)
            found.add(nodeid)
        if nodeid in _SLOW:
            item.add_marker(pytest.mark.slow)
    # Enforce completeness PER FILE: a smoke nodeid must exist whenever
    # its file collected at all — catches renames without tripping on
    # legitimate partial runs (single files, --ignore, -k filters leave
    # whole files out, not individual smoke ids). -k filters and explicit
    # `file.py::test` selections DO drop individual ids, so gate on both.
    if not config.option.keyword and \
            not any("::" in a for a in config.args):
        collected_files = {item.nodeid.split("tests/")[-1].split("::")[0]
                           for item in items}
        missing = {nid for nid in _SMOKE - found
                   if nid.split("::")[0] in collected_files}
        assert not missing, (
            f"smoke-tier nodeids no longer collect (renamed/removed "
            f"tests?): {sorted(missing)}")
