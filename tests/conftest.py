"""Test configuration: run the suite on a virtual 8-device CPU platform.

This is the TPU-build analogue of the reference's CPU-sentinel-stream trick
(``AbstractStream`` admitting a CPU fallback, reference pipe.py:22,
pipeline.py:22): every layer — scheduler, SPMD pipeline, ppermute rings,
checkpointing — runs on plain CPU with a simulated 8-device mesh, so the full
multi-"device" suite needs no TPUs and no cluster. See
``pipe_tpu.utils.platform`` for why this is done via jax.config rather than
env vars on this machine.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pipe_tpu.utils.platform import force_cpu_platform

force_cpu_platform(num_devices=8)
