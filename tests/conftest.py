"""Test configuration: run the suite on a virtual 8-device CPU platform.

This is the TPU-build analogue of the reference's CPU-sentinel-stream trick
(``AbstractStream`` admitting a CPU fallback, reference pipe.py:22,
pipeline.py:22): every layer — scheduler, SPMD pipeline, ppermute rings,
checkpointing — runs on plain CPU with a simulated 8-device mesh, so the full
multi-"device" suite needs no TPUs and no cluster. See
``pipe_tpu.utils.platform`` for why this is done via jax.config rather than
env vars on this machine.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pipe_tpu.utils.platform import force_cpu_platform

force_cpu_platform(num_devices=8)


# ---------------------------------------------------------------------------
# Smoke tier (`pytest -m smoke`, ~3 min): one transparency case per
# executor x schedule x checkpoint mode plus one per major subsystem —
# enough to catch a broken executor/schedule/mode quickly; the full matrix
# stays the CI bar. Selected by exact nodeid so the set is explicit and
# greppable; a listed id that stops collecting fails loudly below.
_SMOKE = {
    # emulator (flagship default path): forward + grads
    "test_pipe.py::test_forward_transparency[2-4]",
    "test_pipe.py::test_gradient_transparency[never]",
    "test_pipe.py::test_gradient_transparency[except_last]",
    "test_pipe.py::test_gradient_transparency[always]",
    # AD wavefront executor (gpipe) + mesh Pipe front door
    "test_spmd.py::test_forward_transparency[4]",
    "test_spmd.py::test_gradient_transparency[except_last]",
    "test_pipe_mesh.py::test_gradient_transparency_mesh[except_last]",
    "test_pipe_mesh.py::test_skip_through_mesh_matches_emulator[4-None]",
    # table executor: 1f1b/gpipe/zb tables x modes, policy, skips, BN
    "test_scheduled.py::test_loss_and_grad_transparency[2-8-never-1f1b]",
    "test_scheduled.py::"
    "test_loss_and_grad_transparency[2-8-except_last-1f1b]",
    "test_scheduled.py::test_loss_and_grad_transparency[2-8-always-1f1b]",
    "test_scheduled.py::"
    "test_loss_and_grad_transparency[2-8-except_last-gpipe]",
    "test_scheduled.py::test_remat_policy_transparency_dynamic"
    "[2-except_last]",
    "test_scheduled.py::test_skip_lanes_raw_executor[except_last]",
    "test_pipe_1f1b.py::test_loss_and_grad_transparency[except_last-1f1b]",
    "test_pipe_1f1b.py::test_skippable_through_table_executor"
    "[never-1f1b]",
    "test_norm.py::test_table_executor_bn_matches_emulator"
    "[except_last-1f1b]",
    # overlapped packed transport: one bitwise-parity case + the shifted-
    # table proof that backs every overlapped run
    "test_overlap_transport.py::test_overlap_transparency"
    "[1f1b-except_last]",
    "test_overlap_transport.py::"
    "test_verify_op_tables_rejects_misshifted_comm_slot",
    # the bench-side probes: quick cpu8 transport comparison + the
    # zero-cost-telemetry HLO pin behind the headline timing
    "test_overlap_transport.py::"
    "test_quick_probe_reports_transport_side_by_side",
    "test_overlap_transport.py::"
    "test_disabled_telemetry_is_zero_cost_on_hot_path",
    # interleaved (train + the forward/eval executor)
    "test_interleaved.py::test_interleaved_pipe_forward_matches_emulator",
    "test_pipe_1f1b.py::test_interleaved_1f1b_through_pipe",
    # zero-bubble split tables + the crossover model
    "test_zb_split.py::test_zb_split_transparency[2-8]",
    "test_zb_model.py::test_breakeven_sigma_is_the_exact_boundary",
    # core data structures + parallelism composition + serving
    "test_microbatch.py::test_scatter_gather_identity",
    "test_schedule.py::test_clock_cycles_matches_reference",
    "test_tp.py::test_pp_tp_loss_and_grad_transparency[2-2]",
    "test_moe.py::test_pp_dp_ep_loss_and_grad_transparency",
    "test_zero.py::test_zero_losses_match_replicated",
    "test_losses.py::test_loss_block_through_pipelined_step",
    "test_generate.py::test_greedy_generation_matches_naive_reforward",
    "test_pipelined_gen.py::"
    "test_pipelined_greedy_matches_single_device[2-4-8-6]",
    # serve engine: the parity + zero-recompile pin on both backends,
    # and the queue's three liveness behaviours
    "test_serve.py::test_staggered_arrivals_match_one_shot_generator"
    "[single]",
    "test_serve.py::test_staggered_arrivals_match_one_shot_generator"
    "[ring]",
    "test_serve.py::test_backpressure_rejects_when_full",
    "test_serve.py::test_deadline_timeout_retires_running_slot",
    "test_serve.py::test_cancellation_frees_slot",
    # phase-compiled executor: one bitwise-parity case per lowering shape
    # (scan steady state, scan-free unroll), the loud rejection path, and
    # the front-door plumbing
    "test_phase_compile.py::test_phased_bitwise_parity[never-1f1b]",
    "test_phase_compile.py::test_phased_bitwise_parity[never-zb-h1]",
    "test_phase_compile.py::test_phased_bitwise_parity_interleaved",
    "test_phase_compile.py::test_rejected_table_falls_back_loudly",
    "test_phase_compile.py::test_front_door_phase_compile_plumbing",
    # schedules-as-data: a user-authored op table through the front door
    "test_custom_schedule.py::test_custom_table_through_pipe_front_door",
    # resilience: the byte-identical-opt-out pin, one recovery path per
    # layer (train skip-step, serve containment), and the verifiable save
    "test_resilience.py::test_train_step_hlo_unchanged_by_resilience",
    "test_resilience.py::test_skip_step_on_injected_nan",
    "test_resilience.py::test_prefill_error_contained_to_one_request",
    "test_resilience.py::"
    "test_checkpoint_manifest_verifies_and_names_corrupt_leaf",
}


def pytest_collection_modifyitems(config, items):
    found = set()
    for item in items:
        nodeid = item.nodeid.split("tests/")[-1]
        if nodeid in _SMOKE:
            item.add_marker(pytest.mark.smoke)
            found.add(nodeid)
    # Enforce completeness PER FILE: a smoke nodeid must exist whenever
    # its file collected at all — catches renames without tripping on
    # legitimate partial runs (single files, --ignore, -k filters leave
    # whole files out, not individual smoke ids). -k filters and explicit
    # `file.py::test` selections DO drop individual ids, so gate on both.
    if not config.option.keyword and \
            not any("::" in a for a in config.args):
        collected_files = {item.nodeid.split("tests/")[-1].split("::")[0]
                           for item in items}
        missing = {nid for nid in _SMOKE - found
                   if nid.split("::")[0] in collected_files}
        assert not missing, (
            f"smoke-tier nodeids no longer collect (renamed/removed "
            f"tests?): {sorted(missing)}")
