"""Deferred BatchNorm tests (reference test_deferred_batch_norm, SURVEY §4).

The core property: running stats after one pipelined mini-batch (any chunks)
equal the stats of one whole-batch BN update — micro-batching must not change
BN semantics (reference batchnorm.py capability, README.md:549-554).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.extras.norm import (BatchNorm, DeferredBatchNorm,
                                  convert_deferred_batch_norm)
from pipe_tpu.ops.layers import Lambda, Linear, Sequential
from pipe_tpu.pipe import Pipe


def whole_batch_reference_stats(x, momentum=0.1):
    """One torch-style BN update from the full mini-batch."""
    n = x.shape[0] * (x.shape[1] if x.ndim == 3 else 1)
    axes = tuple(range(x.ndim - 1))
    mean = np.mean(np.asarray(x), axis=axes)
    var = np.var(np.asarray(x), axis=axes)
    unbiased = var * n / max(n - 1.0, 1.0)
    return momentum * mean, (1 - momentum) * 1.0 + momentum * unbiased


@pytest.mark.parametrize("chunks", [1, 2, 4])
@pytest.mark.parametrize("checkpoint", ["never", "always"])
def test_running_stats_match_whole_batch(chunks, checkpoint):
    module = Sequential([Linear(6), BatchNorm()])
    pipe = Pipe(module, chunks=chunks, checkpoint=checkpoint, n_stages=2,
                deferred_batch_norm=True)
    x = jax.random.normal(jax.random.key(1), (8, 6))
    params = pipe.init(jax.random.key(0), x)

    out, new_params = pipe(params, x, train=True, key=jax.random.key(2))

    # reference: stats of the *linear output* over the whole batch
    h = module[0].apply(params[0][0], x)
    exp_mean, exp_var = whole_batch_reference_stats(h)
    got = new_params[1][0]  # stage 1, layer 0 = the converted BN
    np.testing.assert_allclose(np.asarray(got["mean"]), exp_mean,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got["var"]), exp_var,
                               rtol=1e-5, atol=1e-6)


def test_chunks_invariance():
    """Stats identical whether the batch ran as 1, 2, or 4 micro-batches."""
    x = jax.random.normal(jax.random.key(1), (8, 6))
    results = []
    for chunks in (1, 2, 4):
        pipe = Pipe(Sequential([Linear(6), BatchNorm()]), chunks=chunks,
                    n_stages=2, deferred_batch_norm=True)
        params = pipe.init(jax.random.key(0), x)
        _, new_params = pipe(params, x, train=True)
        results.append(new_params[1][0])
    for r in results[1:]:
        np.testing.assert_allclose(np.asarray(r["mean"]),
                                   np.asarray(results[0]["mean"]),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(r["var"]),
                                   np.asarray(results[0]["var"]),
                                   rtol=1e-5, atol=1e-6)


def test_eval_uses_running_stats():
    pipe = Pipe(Sequential([BatchNorm()]), chunks=2, n_stages=1,
                deferred_batch_norm=True)
    x = jax.random.normal(jax.random.key(1), (8, 4)) * 3.0 + 1.0
    params = pipe.init(jax.random.key(0), x)
    out = pipe(params, x, train=False)  # eval: single return, no commit
    # init stats are mean=0, var=1 -> eval output equals input (scale=1,b=0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-4,
                               atol=1e-4)


def test_train_forward_normalizes_per_microbatch():
    pipe = Pipe(Sequential([BatchNorm()]), chunks=2, n_stages=1,
                deferred_batch_norm=True)
    x = jax.random.normal(jax.random.key(1), (8, 4)) * 5.0
    params = pipe.init(jax.random.key(0), x)
    out, _ = pipe(params, x, train=True)
    # each micro-batch normalized by its own stats: per-half mean ~0, var ~1
    for half in (np.asarray(out[:4]), np.asarray(out[4:])):
        np.testing.assert_allclose(half.mean(axis=0), 0.0, atol=1e-5)
        np.testing.assert_allclose(half.var(axis=0), 1.0, atol=1e-3)


def test_convert_replaces_only_plain_bn():
    module = Sequential([Linear(4), BatchNorm(), Lambda(lambda x: x * 2)])
    converted = convert_deferred_batch_norm(module, chunks=4)
    kinds = [type(l).__name__ for l in converted]
    assert kinds == ["Linear", "DeferredBatchNorm", "Lambda"]
    assert isinstance(converted[1], DeferredBatchNorm)


def test_momentum_accumulates_over_steps():
    """Two train steps move stats twice (one commit per mini-batch each)."""
    pipe = Pipe(Sequential([BatchNorm()]), chunks=2, n_stages=1,
                deferred_batch_norm=True)
    x = jnp.ones((8, 4)) * 2.0
    params = pipe.init(jax.random.key(0), x)
    _, p1 = pipe(params, x, train=True)
    _, p2 = pipe(p1, x, train=True)
    m1 = np.asarray(p1[0][0]["mean"])
    m2 = np.asarray(p2[0][0]["mean"])
    np.testing.assert_allclose(m1, 0.2, atol=1e-6)        # 0.9*0 + 0.1*2
    np.testing.assert_allclose(m2, 0.38, atol=1e-6)       # 0.9*0.2 + 0.1*2
