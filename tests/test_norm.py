"""Deferred BatchNorm tests (reference test_deferred_batch_norm, SURVEY §4).

The core property: running stats after one pipelined mini-batch (any chunks)
equal the stats of one whole-batch BN update — micro-batching must not change
BN semantics (reference batchnorm.py capability, README.md:549-554).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.extras.norm import (BatchNorm, DeferredBatchNorm,
                                  convert_deferred_batch_norm)
from pipe_tpu.ops.layers import Lambda, Linear, Sequential
from pipe_tpu.pipe import Pipe


def whole_batch_reference_stats(x, momentum=0.1):
    """One torch-style BN update from the full mini-batch."""
    n = x.shape[0] * (x.shape[1] if x.ndim == 3 else 1)
    axes = tuple(range(x.ndim - 1))
    mean = np.mean(np.asarray(x), axis=axes)
    var = np.var(np.asarray(x), axis=axes)
    unbiased = var * n / max(n - 1.0, 1.0)
    return momentum * mean, (1 - momentum) * 1.0 + momentum * unbiased


@pytest.mark.parametrize("chunks", [1, 2, 4])
@pytest.mark.parametrize("checkpoint", ["never", "always"])
def test_running_stats_match_whole_batch(chunks, checkpoint):
    module = Sequential([Linear(6), BatchNorm()])
    pipe = Pipe(module, chunks=chunks, checkpoint=checkpoint, n_stages=2,
                deferred_batch_norm=True)
    x = jax.random.normal(jax.random.key(1), (8, 6))
    params = pipe.init(jax.random.key(0), x)

    out, new_params = pipe(params, x, train=True, key=jax.random.key(2))

    # reference: stats of the *linear output* over the whole batch
    h = module[0].apply(params[0][0], x)
    exp_mean, exp_var = whole_batch_reference_stats(h)
    got = new_params[1][0]  # stage 1, layer 0 = the converted BN
    np.testing.assert_allclose(np.asarray(got["mean"]), exp_mean,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got["var"]), exp_var,
                               rtol=1e-5, atol=1e-6)


def test_chunks_invariance():
    """Stats identical whether the batch ran as 1, 2, or 4 micro-batches."""
    x = jax.random.normal(jax.random.key(1), (8, 6))
    results = []
    for chunks in (1, 2, 4):
        pipe = Pipe(Sequential([Linear(6), BatchNorm()]), chunks=chunks,
                    n_stages=2, deferred_batch_norm=True)
        params = pipe.init(jax.random.key(0), x)
        _, new_params = pipe(params, x, train=True)
        results.append(new_params[1][0])
    for r in results[1:]:
        np.testing.assert_allclose(np.asarray(r["mean"]),
                                   np.asarray(results[0]["mean"]),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(r["var"]),
                                   np.asarray(results[0]["var"]),
                                   rtol=1e-5, atol=1e-6)


def test_eval_uses_running_stats():
    pipe = Pipe(Sequential([BatchNorm()]), chunks=2, n_stages=1,
                deferred_batch_norm=True)
    x = jax.random.normal(jax.random.key(1), (8, 4)) * 3.0 + 1.0
    params = pipe.init(jax.random.key(0), x)
    out = pipe(params, x, train=False)  # eval: single return, no commit
    # init stats are mean=0, var=1 -> eval output equals input (scale=1,b=0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-4,
                               atol=1e-4)


def test_train_forward_normalizes_per_microbatch():
    pipe = Pipe(Sequential([BatchNorm()]), chunks=2, n_stages=1,
                deferred_batch_norm=True)
    x = jax.random.normal(jax.random.key(1), (8, 4)) * 5.0
    params = pipe.init(jax.random.key(0), x)
    out, _ = pipe(params, x, train=True)
    # each micro-batch normalized by its own stats: per-half mean ~0, var ~1
    for half in (np.asarray(out[:4]), np.asarray(out[4:])):
        np.testing.assert_allclose(half.mean(axis=0), 0.0, atol=1e-5)
        np.testing.assert_allclose(half.var(axis=0), 1.0, atol=1e-3)


def test_convert_replaces_only_plain_bn():
    module = Sequential([Linear(4), BatchNorm(), Lambda(lambda x: x * 2)])
    converted = convert_deferred_batch_norm(module, chunks=4)
    kinds = [type(l).__name__ for l in converted]
    assert kinds == ["Linear", "DeferredBatchNorm", "Lambda"]
    assert isinstance(converted[1], DeferredBatchNorm)


def test_momentum_accumulates_over_steps():
    """Two train steps move stats twice (one commit per mini-batch each)."""
    pipe = Pipe(Sequential([BatchNorm()]), chunks=2, n_stages=1,
                deferred_batch_norm=True)
    x = jnp.ones((8, 4)) * 2.0
    params = pipe.init(jax.random.key(0), x)
    _, p1 = pipe(params, x, train=True)
    _, p2 = pipe(p1, x, train=True)
    m1 = np.asarray(p1[0][0]["mean"])
    m2 = np.asarray(p2[0][0]["mean"])
    np.testing.assert_allclose(m1, 0.2, atol=1e-6)        # 0.9*0 + 0.1*2
    np.testing.assert_allclose(m2, 0.38, atol=1e-6)       # 0.9*0.2 + 0.1*2


# ---------- deferred BN through the compiled mesh path (VERDICT r2 #4) ----

def _stage_mesh(n_stages, n_data=1):
    from pipe_tpu.parallel.mesh import make_mesh
    return make_mesh(n_stages, n_data,
                     devices=jax.devices()[:n_stages * n_data])


@pytest.mark.parametrize("checkpoint", ["never", "always"])
def test_mesh_running_stats_match_emulator(checkpoint):
    """Pipelined BN stats through Pipe(mesh=) == the serial emulator's ==
    the whole-batch update (reference pipe.py:341-342 converts BN and runs
    it on the multi-device pipeline)."""
    module = Sequential([Linear(6), BatchNorm()])
    x = jax.random.normal(jax.random.key(1), (8, 6))
    mesh_pipe = Pipe(module, chunks=4, checkpoint=checkpoint,
                     mesh=_stage_mesh(2), deferred_batch_norm=True)
    emu_pipe = Pipe(module, chunks=4, checkpoint=checkpoint, n_stages=2,
                    deferred_batch_norm=True)
    params = mesh_pipe.init(jax.random.key(0), x)

    out_m, new_m = mesh_pipe(params, x, train=True, key=jax.random.key(2))
    out_e, new_e = emu_pipe(params, x, train=True, key=jax.random.key(2))
    np.testing.assert_allclose(np.asarray(out_m), np.asarray(out_e),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(new_m),
                    jax.tree_util.tree_leaves(new_e)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    h = module[0].apply(params[0][0], x)
    exp_mean, exp_var = whole_batch_reference_stats(h)
    got = new_m[1][0]
    np.testing.assert_allclose(np.asarray(got["mean"]), exp_mean,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got["var"]), exp_var,
                               rtol=1e-5, atol=1e-6)


def test_mesh_bn_packed_params_commit():
    """Stage-sharded packed params: the commit rebuilds only BN stages'
    rows; round-trip shows the updated running stats."""
    module = Sequential([Linear(6), BatchNorm()])
    x = jax.random.normal(jax.random.key(1), (8, 6))
    pipe = Pipe(module, chunks=2, checkpoint="never", mesh=_stage_mesh(2),
                deferred_batch_norm=True)
    params = pipe.init(jax.random.key(0), x)
    packed = pipe.shard_params(params)

    out, new_packed = pipe(packed, x, train=True)
    emu = Pipe(module, chunks=2, checkpoint="never", n_stages=2,
               deferred_batch_norm=True)
    _, new_e = emu(params, x, train=True)
    new_trees = pipe.unshard_params(new_packed)
    for a, b in zip(jax.tree_util.tree_leaves(new_trees),
                    jax.tree_util.tree_leaves(new_e)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_mesh_bn_with_data_axis():
    """PP x DP: per-shard partial sums reduce host-side; committed stats
    are the exact whole-mini-batch statistics — AND the train-mode forward
    itself matches the emulator (normalization stats psum over the data
    axis, so shard-local rows see whole-micro-batch statistics)."""
    module = Sequential([Linear(6), BatchNorm()])
    x = jax.random.normal(jax.random.key(1), (8, 6))
    mesh_pipe = Pipe(module, chunks=2, checkpoint="never",
                     mesh=_stage_mesh(2, n_data=2),
                     deferred_batch_norm=True)
    emu_pipe = Pipe(module, chunks=2, checkpoint="never", n_stages=2,
                    deferred_batch_norm=True)
    params = mesh_pipe.init(jax.random.key(0), x)
    out_m, new_m = mesh_pipe(params, x, train=True)
    out_e, new_e = emu_pipe(params, x, train=True)
    np.testing.assert_allclose(np.asarray(out_m), np.asarray(out_e),
                               rtol=1e-5, atol=1e-6)
    got, exp = new_m[1][0], new_e[1][0]
    np.testing.assert_allclose(np.asarray(got["mean"]),
                               np.asarray(exp["mean"]), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(got["var"]),
                               np.asarray(exp["var"]), rtol=1e-5, atol=1e-6)


def test_mesh_bn_data_axis_grads_match_emulator():
    """jax.grad of the data-sharded mesh BN forward matches the emulator:
    the data axis is purely a layout choice, never a math choice."""
    module = Sequential([Linear(6), BatchNorm(), Lambda(jax.nn.relu),
                         Linear(1)])
    x = jax.random.normal(jax.random.key(1), (8, 6))
    mesh_pipe = Pipe(module, chunks=2, checkpoint="never",
                     mesh=_stage_mesh(2, n_data=2),
                     deferred_batch_norm=True)
    emu_pipe = Pipe(module, chunks=2, checkpoint="never", n_stages=2,
                    deferred_batch_norm=True)
    params = mesh_pipe.init(jax.random.key(0), x)

    def loss(pipe):
        def f(p):
            out, _ = pipe(p, x, train=True)
            return jnp.sum(out ** 2)
        return f

    g_m = jax.grad(loss(mesh_pipe))(params)
    g_e = jax.grad(loss(emu_pipe))(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_m),
                    jax.tree_util.tree_leaves(g_e)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_mesh_bn_rejects_padded_rows():
    """Zero-padded rows would contaminate batch statistics: fail fast."""
    module = Sequential([Linear(6), BatchNorm()])
    pipe = Pipe(module, chunks=4, checkpoint="never", mesh=_stage_mesh(2),
                deferred_batch_norm=True)
    x = jax.random.normal(jax.random.key(1), (7, 6))  # 7 % 4 != 0
    params = pipe.init(jax.random.key(0), jnp.zeros((8, 6)))
    with pytest.raises(ValueError):
        pipe(params, x, train=True)


def test_mesh_plain_bn_rejects_padded_rows():
    """PLAIN BatchNorm (no deferred conversion) hits the same guard: its
    train-mode normalization statistics are just as contaminated by fake
    zero rows as the deferred accumulators are."""
    module = Sequential([Linear(6), BatchNorm()])
    pipe = Pipe(module, chunks=4, checkpoint="never", mesh=_stage_mesh(2))
    x = jax.random.normal(jax.random.key(1), (7, 6))  # 7 % 4 != 0
    params = pipe.init(jax.random.key(0), jnp.zeros((8, 6)))
    with pytest.raises(ValueError, match="BatchNorm"):
        pipe(params, x, train=True)


def test_mesh_bn_zbh1_rejected():
    """zb-h1 is out for BN (the W op's vjp seed has no stats slot) — it
    fails FAST at construction, not at the first loss_and_grad trace."""
    module = Sequential([Linear(6), BatchNorm(), Linear(6), BatchNorm()])
    with pytest.raises(NotImplementedError, match="zb-h1|split-backward"):
        Pipe(module, chunks=2, mesh=_stage_mesh(2),
             deferred_batch_norm=True, schedule="zb-h1")


@pytest.mark.parametrize("n_data", [1, 2], ids=["pp", "ppxdp"])
@pytest.mark.parametrize("checkpoint", ["never", "except_last"])
def test_mesh_bn_interleaved_matches_emulator(checkpoint, n_data):
    """Deferred BN composes with interleaved (v > 1) placements: training
    via loss_and_grad (stat lanes through the op tables) AND train-mode
    forward (stat lanes through the FWD-masked tables) both return the
    emulator's committed running stats; eval after the commit matches too
    (reference pipe.py:341-342 composes BN with the pipeline
    unconditionally). ``n_data=2`` adds a data axis, so the stat-lane
    psum reduces over (stage, data) and train-mode normalization psums
    micro-batch stats across the data shards."""
    module = Sequential([Linear(6), BatchNorm(), Linear(6), BatchNorm()])
    x = jax.random.normal(jax.random.key(1), (8, 6))
    y = jax.random.normal(jax.random.key(2), (8, 6))

    def loss_fn(out, tgt):
        return jnp.sum((out - tgt) ** 2, axis=-1)

    emu = Pipe(module, chunks=4, checkpoint="except_last", n_stages=4,
               deferred_batch_norm=True)
    params = emu.init(jax.random.key(0), x)

    def emu_loss(ps):
        out, _ = emu(ps, x, train=True)
        return jnp.mean(loss_fn(out, y))

    exp_loss = float(emu_loss(params))
    exp_grads = jax.grad(emu_loss)(params)
    out_e, exp_new = emu(params, x, train=True)

    pipe = Pipe(module, chunks=4, checkpoint=checkpoint,
                mesh=_stage_mesh(2, n_data), schedule="interleaved-1f1b",
                deferred_batch_norm=True)
    packed = pipe.shard_params(params)

    # training: loss, grads AND committed stats match the emulator
    loss, grads, new_packed = jax.jit(lambda p: pipe.loss_and_grad(
        p, x, targets=y, loss_fn=loss_fn))(packed)
    assert float(loss) == pytest.approx(exp_loss, rel=1e-5)
    # atol 1e-5: micro-batch BN (2 rows/chunk) amplifies f32
    # accumulation-order noise in the grads; the same comparison under
    # jax_enable_x64 agrees to 1e-15, so the difference is ordering, not
    # math
    for a, b in zip(jax.tree_util.tree_leaves(pipe.unshard_grads(grads)),
                    jax.tree_util.tree_leaves(exp_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
    for a, b in zip(
            jax.tree_util.tree_leaves(pipe.unshard_params(new_packed)),
            jax.tree_util.tree_leaves(exp_new)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    # train-mode forward: (out, new_params), both matching the emulator
    out_m, new_fwd = pipe(packed, x, train=True)
    np.testing.assert_allclose(np.asarray(out_m), np.asarray(out_e),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(
            jax.tree_util.tree_leaves(pipe.unshard_params(new_fwd)),
            jax.tree_util.tree_leaves(exp_new)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)

    # eval after the commit: running stats in use, no stats returned
    ev_m = pipe(new_fwd, x)
    ev_e, _new = emu(exp_new, x), None
    np.testing.assert_allclose(np.asarray(ev_m), np.asarray(ev_e),
                               rtol=1e-5, atol=1e-6)


def test_mesh_bn_skip_interleaved_matches_emulator():
    """@skippable + deferred BN through interleaved (v > 1) placements
    together: the skip lane's direct per-lane permute and the BN stat
    lanes ride the same op tables — loss, grads, and committed running
    stats all match the serial emulator."""
    from pipe_tpu.core.partition import StageCtx
    from pipe_tpu.extras.skip import pop, skippable, stash
    from pipe_tpu.ops.layers import Module

    @skippable(stash=["z"])
    class S(Module):
        def init(self, key, *a):
            return {}

        def apply(self, p, x, ctx=StageCtx()):
            stash("z", x)
            return x

    @skippable(pop=["z"])
    class Po(Module):
        def init(self, key, *a):
            return {}

        def apply(self, p, x, ctx=StageCtx()):
            return x + pop("z")

    # 6 layers over 4 virtual stages (balance [2,1,1,2]): the skip jumps
    # 0 -> 3 across devices at d=2, with a BN on each edge partition
    module = Sequential([Linear(6), S(), BatchNorm(), Linear(6),
                         Po(), BatchNorm()])
    balance = [2, 1, 1, 2]
    x = jax.random.normal(jax.random.key(1), (8, 6))
    y = jax.random.normal(jax.random.key(2), (8, 6))

    def loss_fn(out, tgt):
        return jnp.sum((out - tgt) ** 2, axis=-1)

    emu = Pipe(module, chunks=4, checkpoint="except_last", n_stages=4,
               balance=balance, deferred_batch_norm=True)
    params = emu.init(jax.random.key(0), x)

    def emu_loss(ps):
        out, _ = emu(ps, x, train=True)
        return jnp.mean(loss_fn(out, y))

    exp_loss = float(emu_loss(params))
    exp_grads = jax.grad(emu_loss)(params)
    _, exp_new = emu(params, x, train=True)

    pipe = Pipe(module, chunks=4, checkpoint="except_last",
                mesh=_stage_mesh(2), schedule="interleaved-1f1b",
                balance=balance, deferred_batch_norm=True)
    packed = pipe.shard_params(params)
    loss, grads, new_packed = jax.jit(lambda p: pipe.loss_and_grad(
        p, x, targets=y, loss_fn=loss_fn))(packed)
    assert float(loss) == pytest.approx(exp_loss, rel=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(pipe.unshard_grads(grads)),
                    jax.tree_util.tree_leaves(exp_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
    for a, b in zip(
            jax.tree_util.tree_leaves(pipe.unshard_params(new_packed)),
            jax.tree_util.tree_leaves(exp_new)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("checkpoint", ["never", "always"])
def test_mesh_bn_training_grads_match_emulator(checkpoint):
    """jax.grad through the mesh BN forward — the supported training route
    for deferred-BN models on a mesh — matches the emulator."""
    module = Sequential([Linear(6), BatchNorm(), Lambda(jax.nn.relu),
                         Linear(1)])
    x = jax.random.normal(jax.random.key(1), (8, 6))
    mesh_pipe = Pipe(module, chunks=4, checkpoint=checkpoint,
                     mesh=_stage_mesh(2), deferred_batch_norm=True)
    emu_pipe = Pipe(module, chunks=4, checkpoint=checkpoint, n_stages=2,
                    deferred_batch_norm=True)
    params = mesh_pipe.init(jax.random.key(0), x)

    def loss_mesh(p):
        out, _ = mesh_pipe(p, x, train=True)
        return jnp.mean(out ** 2)

    def loss_emu(p):
        out, _ = emu_pipe(p, x, train=True)
        return jnp.mean(out ** 2)

    gm = jax.grad(loss_mesh)(params)
    ge = jax.grad(loss_emu)(params)
    for a, b in zip(jax.tree_util.tree_leaves(gm),
                    jax.tree_util.tree_leaves(ge)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


# ------- deferred BN through the op-TABLE executor (VERDICT r3 #5) -------

@pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
@pytest.mark.parametrize("checkpoint", ["never", "except_last", "always"])
def test_table_executor_bn_matches_emulator(schedule, checkpoint):
    """Deferred BN trains through Pipe(mesh=, schedule='1f1b')
    .loss_and_grad: loss, grads AND committed running stats equal the
    serial emulator's (reference pipe.py:341-342 composes BN with the
    training pipeline unconditionally). Stats accumulate on FWD ops only
    — BWD recomputes re-compute and discard them, so recompute modes
    cannot double-count."""
    module = Sequential([Linear(6), BatchNorm(), Linear(6), BatchNorm(),
                         Linear(3)])
    x = jax.random.normal(jax.random.key(1), (8, 6))
    y = jax.random.normal(jax.random.key(2), (8, 3))

    def loss_fn(out, tgt):
        return jnp.sum((out - tgt) ** 2, axis=-1)

    emu = Pipe(module, chunks=4, checkpoint="except_last", n_stages=2,
               deferred_batch_norm=True)
    params = emu.init(jax.random.key(0), x)

    def emu_loss(ps):
        out, _ = emu(ps, x, train=True)
        return jnp.mean(loss_fn(out, y))

    exp_loss = float(emu_loss(params))
    exp_grads = jax.grad(emu_loss)(params)
    _, exp_new = emu(params, x, train=True)

    pipe = Pipe(module, chunks=4, checkpoint=checkpoint,
                mesh=_stage_mesh(2), schedule=schedule,
                deferred_batch_norm=True)
    packed = pipe.shard_params(pipe.init(jax.random.key(0), x))
    loss, grads, new_packed = jax.jit(lambda p: pipe.loss_and_grad(
        p, x, targets=y, loss_fn=loss_fn))(packed)
    assert float(loss) == pytest.approx(exp_loss, rel=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(pipe.unshard_grads(grads)),
                    jax.tree_util.tree_leaves(exp_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)
    for a, b in zip(
            jax.tree_util.tree_leaves(pipe.unshard_params(new_packed)),
            jax.tree_util.tree_leaves(exp_new)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_table_executor_bn_with_data_axis():
    """PP x DP through the table executor: per-shard stat partial sums
    psum over the data axis; committed stats equal the emulator's."""
    module = Sequential([Linear(6), BatchNorm(), Linear(3)])
    x = jax.random.normal(jax.random.key(1), (8, 6))
    y = jax.random.normal(jax.random.key(2), (8, 3))

    def loss_fn(out, tgt):
        return jnp.sum((out - tgt) ** 2, axis=-1)

    emu = Pipe(module, chunks=2, checkpoint="never", n_stages=2,
               deferred_batch_norm=True)
    params = emu.init(jax.random.key(0), x)

    def emu_loss(ps):
        out, _ = emu(ps, x, train=True)
        return jnp.mean(loss_fn(out, y))

    exp_loss = float(emu_loss(params))
    _, exp_new = emu(params, x, train=True)

    pipe = Pipe(module, chunks=2, checkpoint="never",
                mesh=_stage_mesh(2, n_data=2), schedule="1f1b",
                deferred_batch_norm=True)
    packed = pipe.shard_params(pipe.init(jax.random.key(0), x))
    loss, grads, new_packed = jax.jit(lambda p: pipe.loss_and_grad(
        p, x, targets=y, loss_fn=loss_fn))(packed)
    assert float(loss) == pytest.approx(exp_loss, rel=1e-5)
    for a, b in zip(
            jax.tree_util.tree_leaves(pipe.unshard_params(new_packed)),
            jax.tree_util.tree_leaves(exp_new)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_table_executor_bn_rejects_padded_rows():
    module = Sequential([Linear(6), BatchNorm(), Linear(3)])
    x = jax.random.normal(jax.random.key(1), (7, 6))  # 7 % (2*1) != 0... but
    # chunks=2 pads micro-batches: 7 % 2 != 0 -> padded rows would enter
    # the statistics; the executor must refuse
    y = jax.random.normal(jax.random.key(2), (7, 3))
    pipe = Pipe(module, chunks=2, checkpoint="never", mesh=_stage_mesh(2),
                schedule="1f1b", deferred_batch_norm=True)
    packed = pipe.shard_params(pipe.init(jax.random.key(0), x))
    with pytest.raises(ValueError, match="divide"):
        pipe.loss_and_grad(packed, x, targets=y,
                           loss_fn=lambda o, t: jnp.sum((o - t) ** 2, -1))
