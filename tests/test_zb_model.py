"""zb-h1 cost model (obs/zb_model.py): the falsifiable win criterion.

These tests pin the MODEL's math on synthetic costs — the committed
calibration artifact (ZB_CROSSOVER_r{N}.json) pins the fit on real cpu8
measurements. Together: the cpu8 wall-clock loss of zb-h1 and its predicted
parallel-hardware behavior come from one set of equations.
"""

import numpy as np
import pytest

from pipe_tpu.core.schedule import BWD, FWD, WGRAD, get_schedule
from pipe_tpu.obs.zb_model import (OpCosts, calibrate, crossover, predict,
                                   schedule_wall)


def test_ideal_split_wins_parallel_loses_nothing_serialized():
    """sigma=1, o=0: the zero-bubble promise — zb-h1 strictly beats 1F1B
    on PARALLEL hardware wherever 1F1B has a bubble, while total work
    (serialized wall) is identical."""
    for (m, n) in ((8, 4), (8, 8), (16, 8)):
        par = predict(m, n, OpCosts(f=1.0, sigma=1.0, o=0.0), "parallel")
        ser = predict(m, n, OpCosts(f=1.0, sigma=1.0, o=0.0), "serialized")
        assert par["zb_wins"], (m, n, par)
        assert ser["zb_over_1f1b"] == pytest.approx(1.0)


def test_measured_sigma_flips_the_parallel_prediction():
    """At the committed cpu8-measured overhead (sigma ~ 1.6+), the
    parallel prediction flips against zb-h1 at the shallow bench config —
    the model explains BOTH the idle-fraction win and the wall-clock loss."""
    lo = predict(8, 4, OpCosts(f=1.0, sigma=1.0, o=0.0), "parallel")
    hi = predict(8, 4, OpCosts(f=1.0, sigma=3.0, o=0.0), "parallel")
    assert lo["zb_wins"] and not hi["zb_wins"]


def test_per_cycle_overhead_taxes_zb_more():
    """zb tables have more cycles; o > 0 must widen 1F1B's absolute lead
    (the o_max crossover in `crossover()` is exactly this slope)."""
    a = predict(16, 8, OpCosts(f=1.0, sigma=2.0, o=0.0), "parallel")
    b = predict(16, 8, OpCosts(f=1.0, sigma=2.0, o=1.0), "parallel")
    gap_a = a["t_zb"] - a["t_1f1b"]
    gap_b = b["t_zb"] - b["t_1f1b"]
    row = crossover(16, 8, sigma=2.0)
    assert gap_b == pytest.approx(
        gap_a + (row["cycles_zb"] - row["cycles_1f1b"]) * 1.0)
    assert gap_b > gap_a


def test_breakeven_sigma_is_the_exact_boundary():
    """t_zb(sigma*) == t_1f1b at o=0, and sigma just below/above the
    breakeven flips the outcome."""
    for (m, n) in ((8, 8), (16, 8), (16, 16)):
        row = crossover(m, n, sigma=1.0)
        s_star = row["breakeven_sigma"]
        assert s_star > 1.0, (m, n, s_star)   # ideal split always wins
        at = predict(m, n, OpCosts(f=1.0, sigma=s_star, o=0.0), "parallel")
        assert at["zb_over_1f1b"] == pytest.approx(1.0, rel=1e-9)
        assert predict(m, n, OpCosts(f=1.0, sigma=s_star * 0.99, o=0.0),
                       "parallel")["zb_wins"]
        assert not predict(m, n, OpCosts(f=1.0, sigma=s_star * 1.01, o=0.0),
                           "parallel")["zb_wins"]


def test_calibrate_recovers_synthetic_truth():
    """Generate serialized measurements from known (f, sigma, o); the fit
    must recover them."""
    n = 4
    truth = {64: (0.002, 1.7, 0.004), 128: (0.009, 1.9, 0.012)}
    rows = []
    for width, (f, sg, o) in truth.items():
        for m in (8, 16):
            c = OpCosts(f=f, sigma=sg, o=o)
            rows.append({
                "width": width, "m": m,
                "t_1f1b": schedule_wall(
                    get_schedule("1f1b").op_tables(m, n)[0], c,
                    "serialized"),
                "t_zb": schedule_wall(
                    get_schedule("zb-h1").op_tables(m, n)[0], c,
                    "serialized"),
            })
    cal = calibrate(rows, n)
    for k, width in enumerate(cal["widths"]):
        f, sg, o = truth[width]
        assert cal["f_per_width"][k] == pytest.approx(f, rel=1e-6)
        assert cal["sigma_per_width"][k] == pytest.approx(sg, rel=1e-6)
        assert cal["o_serialized_per_width"][k] == pytest.approx(o,
                                                                 rel=1e-6)
        assert cal["rel_residual_per_width"][k] < 1e-9


def test_calibrate_rejects_single_m():
    with pytest.raises(ValueError, match="micro-batch"):
        calibrate([{"width": 64, "m": 8, "t_1f1b": 1.0, "t_zb": 1.5}], 4)


def test_schedule_wall_modes_agree_with_hand_count():
    """Hand-check on a tiny table: parallel sums per-cycle maxima,
    serialized sums everything."""
    op = np.array([[FWD, 0], [BWD, FWD], [WGRAD, BWD], [0, WGRAD]])
    c = OpCosts(f=1.0, sigma=1.5, o=0.25)
    # split table: B and W cost sigma * f = 1.5 each
    # parallel: max per cycle = [1, 1.5, 1.5, 1.5] + 4 * 0.25
    assert schedule_wall(op, c, "parallel") == pytest.approx(5.5 + 1.0)
    # serialized: 1 + (1.5 + 1) + (1.5 + 1.5) + 1.5 + 4 * 0.25
    assert schedule_wall(op, c, "serialized") == pytest.approx(8.0 + 1.0)
