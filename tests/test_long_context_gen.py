"""Context-sharded decoding (inference/long_context.py).

Gold contract: with the SAME parameter trees, greedy decode with the
prompt KV cache sharded over a context axis matches the single-device
Generator token-for-token — ring prefill, the distributed flash combine,
and the device-0-owned decode cache are layout choices, never math
choices.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.inference import GenerationConfig, Generator
from pipe_tpu.inference.long_context import ContextShardedGenerator
from pipe_tpu.models.long_context_lm import ContextParallelLM
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
from pipe_tpu.parallel.mesh import make_mesh

CFG = LMConfig(vocab=83, d_model=32, nhead=4, d_ff=64, n_layers=4,
               seq_len=64, dropout=0.0)


def _setup(n_ctx, seed=0):
    cp = ContextParallelLM(CFG, n_stages=2)
    params = cp.init(jax.random.key(seed))      # PipelinedLM-shaped trees
    ref_model = PipelinedLM(CFG, 2)
    mesh = make_mesh(1, 1, n_context=n_ctx)
    return cp, ref_model, mesh, params


@pytest.mark.parametrize("n_ctx,b,p,max_new", [
    (2, 2, 16, 6),
    (4, 2, 16, 5),
    (4, 1, 32, 4),
])
def test_context_sharded_greedy_matches_single_device(n_ctx, b, p, max_new):
    cp, ref_model, mesh, params = _setup(n_ctx)
    prompt = jax.random.randint(jax.random.key(1), (b, p), 0, CFG.vocab,
                                jnp.int32)
    gen_cfg = GenerationConfig(max_new_tokens=max_new, temperature=0.0)
    ref = np.asarray(Generator(ref_model, gen_cfg).generate(params, prompt))
    got = np.asarray(ContextShardedGenerator(mesh, cp, gen_cfg).generate(
        params, prompt))
    np.testing.assert_array_equal(got, ref)


def test_context_sharded_sampling_reproducible():
    cp, _, mesh, params = _setup(2)
    g = ContextShardedGenerator(
        mesh, cp, GenerationConfig(max_new_tokens=6, temperature=0.9,
                                   top_k=8))
    prompt = jnp.zeros((2, 8), jnp.int32)
    a = np.asarray(g.generate(params, prompt, key=jax.random.key(5)))
    b = np.asarray(g.generate(params, prompt, key=jax.random.key(5)))
    c = np.asarray(g.generate(params, prompt, key=jax.random.key(6)))
    np.testing.assert_array_equal(a, b)
    assert (a != c).any()
    assert (a >= 0).all() and (a < CFG.vocab).all()


def test_context_sharded_validations():
    cp, _, mesh, params = _setup(2)
    g = ContextShardedGenerator(mesh, cp,
                                GenerationConfig(max_new_tokens=2))
    with pytest.raises(ValueError, match="divide"):
        g.generate(params, jnp.zeros((1, 7), jnp.int32))
    with pytest.raises(ValueError, match="beam"):
        ContextShardedGenerator(mesh, cp,
                                GenerationConfig(max_new_tokens=2,
                                                 num_beams=2))
    with pytest.raises(ValueError, match="context"):
        ContextShardedGenerator(make_mesh(2, 1), cp,
                                GenerationConfig(max_new_tokens=2))
