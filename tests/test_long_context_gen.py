"""Context-sharded decoding (inference/long_context.py).

Gold contract: with the SAME parameter trees, greedy decode with the
prompt KV cache sharded over a context axis matches the single-device
Generator token-for-token — ring prefill, the distributed flash combine,
and the device-0-owned decode cache are layout choices, never math
choices.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.inference import GenerationConfig, Generator
from pipe_tpu.inference.long_context import ContextShardedGenerator
from pipe_tpu.models.long_context_lm import ContextParallelLM
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
from pipe_tpu.parallel.mesh import make_mesh

CFG = LMConfig(vocab=83, d_model=32, nhead=4, d_ff=64, n_layers=4,
               seq_len=64, dropout=0.0)


def _setup(n_ctx, seed=0):
    cp = ContextParallelLM(CFG, n_stages=2)
    params = cp.init(jax.random.key(seed))      # PipelinedLM-shaped trees
    ref_model = PipelinedLM(CFG, 2)
    mesh = make_mesh(1, 1, n_context=n_ctx)
    return cp, ref_model, mesh, params


@pytest.mark.parametrize("n_ctx,b,p,max_new", [
    (2, 2, 16, 6),
    (4, 2, 16, 5),
    (4, 1, 32, 4),
])
def test_context_sharded_greedy_matches_single_device(n_ctx, b, p, max_new):
    cp, ref_model, mesh, params = _setup(n_ctx)
    prompt = jax.random.randint(jax.random.key(1), (b, p), 0, CFG.vocab,
                                jnp.int32)
    gen_cfg = GenerationConfig(max_new_tokens=max_new, temperature=0.0)
    ref = np.asarray(Generator(ref_model, gen_cfg).generate(params, prompt))
    got = np.asarray(ContextShardedGenerator(mesh, cp, gen_cfg).generate(
        params, prompt))
    np.testing.assert_array_equal(got, ref)


def test_context_sharded_sampling_reproducible():
    cp, _, mesh, params = _setup(2)
    g = ContextShardedGenerator(
        mesh, cp, GenerationConfig(max_new_tokens=6, temperature=0.9,
                                   top_k=8))
    prompt = jnp.zeros((2, 8), jnp.int32)
    a = np.asarray(g.generate(params, prompt, key=jax.random.key(5)))
    b = np.asarray(g.generate(params, prompt, key=jax.random.key(5)))
    c = np.asarray(g.generate(params, prompt, key=jax.random.key(6)))
    np.testing.assert_array_equal(a, b)
    assert (a != c).any()
    assert (a >= 0).all() and (a < CFG.vocab).all()


def test_context_sharded_validations():
    cp, _, mesh, params = _setup(2)
    g = ContextShardedGenerator(mesh, cp,
                                GenerationConfig(max_new_tokens=2))
    with pytest.raises(ValueError, match="divide"):
        g.generate(params, jnp.zeros((1, 7), jnp.int32))
    # beam search IS supported context-sharded (round 5); only the
    # scores surface enforces its num_beams precondition
    with pytest.raises(ValueError, match="num_beams"):
        g.generate_with_scores(params, jnp.zeros((1, 8), jnp.int32))
    with pytest.raises(ValueError, match="context"):
        ContextShardedGenerator(make_mesh(2, 1), cp,
                                GenerationConfig(max_new_tokens=2))


@pytest.mark.parametrize("n_ctx,b,p,max_new,k", [
    (2, 2, 16, 6, 3),
    (4, 2, 16, 4, 2),
    (2, 1, 16, 1, 2),   # max_new=1: beams seeded by prefill only
])
def test_context_sharded_beam_matches_single_device(n_ctx, b, p, max_new,
                                                    k):
    """Context-sharded beam search == the single-device beam, tokens AND
    scores: beams ride _partial_attend's query axis over the SHARED
    prompt shard (no cache tiling, no prompt-cache reorder) — a layout
    choice, never a math choice."""
    cp, ref_model, mesh, params = _setup(n_ctx)
    prompt = jax.random.randint(jax.random.key(1), (b, p), 0, CFG.vocab,
                                jnp.int32)
    gen_cfg = GenerationConfig(max_new_tokens=max_new, num_beams=k)
    ref_toks, ref_sc = Generator(ref_model, gen_cfg).generate_with_scores(
        params, prompt)
    got_toks, got_sc = ContextShardedGenerator(
        mesh, cp, gen_cfg).generate_with_scores(params, prompt)
    np.testing.assert_array_equal(np.asarray(got_toks),
                                  np.asarray(ref_toks))
    np.testing.assert_allclose(np.asarray(got_sc), np.asarray(ref_sc),
                               rtol=1e-5, atol=1e-5)


def test_context_sharded_beam_generate_routes_to_beam():
    cp, ref_model, mesh, params = _setup(2)
    gen_cfg = GenerationConfig(max_new_tokens=4, num_beams=2)
    prompt = jnp.zeros((2, 8), jnp.int32)
    toks = ContextShardedGenerator(mesh, cp, gen_cfg).generate(params,
                                                               prompt)
    ref = Generator(ref_model, gen_cfg).generate(params, prompt)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(ref))
