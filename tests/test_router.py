"""The fleet router (pipe_tpu/serve/router.py): health-gated failover.

The contract under test, in order of importance:

* **Exactly-once delivery.** Every id submitted at the fleet front door
  yields exactly one terminal Response through the router — including
  under ``kill_replica`` chaos, where requests bounce through eviction,
  retry parking and re-placement (the PR's acceptance pin).
* **Health gating.** SUSPECT stops placement only; WEDGED evicts the
  backlog intact, re-places it under the retry budget, and walks the
  replica through DRAINING to RETIRED. A fleet with no recoverable
  replica and no spawn hook fails stranded work loudly (``no_replicas``)
  instead of spinning.
* **Request identity survives failover.** ``submitted_at``/``deadline``
  ride the same Request object through every re-queue — no deadline
  credit — and cancellation is one flag flip wherever the request sits.
* **Zero overhead when absent.** ``chaos=None`` leaves the replica
  backends untouched (no wrappers); the single-engine path never
  constructs a Router at all.

Fast tests drive a stub slot backend on a fake clock — deterministic,
no jax in the loop. The one real-model test (slow tier) pins bitwise
token parity through a mid-stream replica kill: seeds and prompts ride
the re-placement, so failed-over greedy output matches the one-shot
Generator exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.inference import GenerationConfig, Generator
from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
from pipe_tpu.obs.telemetry import get_registry, labelled
from pipe_tpu.resilience import ChaosPlan, Fault, TickWatchdog
from pipe_tpu.serve import (DRAINING, HEALTHY, RETIRED, SUSPECT, BucketSpec,
                            EngineDraining, QueueFull, RequestQueue, Router,
                            RouterPolicy, ServeEngine,
                            SingleDeviceSlotBackend)

# ---------------------------------------------------------------------------
# stub backend: the slot-backend contract without jax


class _FakeGen:
    eos_token_id = None
    max_new_tokens = 32
    pad_token_id = 0


class FakeBackend:
    """S slots, one deterministic token per decode step, no device in
    sight — what the router sees of a backend, nothing more."""

    def __init__(self, num_slots=2, poison=None):
        self.num_slots = num_slots
        self.gen = _FakeGen()
        self.buckets = None
        self.decode_chunk = 1
        self.poison = poison          # prompts starting with this fail

    def validate(self, prompt_len, max_new_tokens):
        if max_new_tokens > self.gen.max_new_tokens:
            raise ValueError("max_new_tokens above engine cap")

    def prefill(self, slot, prompt, seed):
        if self.poison is not None and prompt[0] == self.poison:
            raise RuntimeError("poisoned prompt")
        return 1

    def decode(self, live):
        toks = np.ones((self.num_slots, 1), np.int32)
        valid = np.broadcast_to(np.asarray(live, bool)[:, None],
                                toks.shape)
        return toks, valid


def make_fleet(n_replicas, *, slots=2, replica_capacity=32,
               front_capacity=32, chaos=None, poison=None, **policy_kw):
    """N stub replicas + front queue, all on one fake clock. Returns
    (router, t) where t is the mutable clock cell."""
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    policy_kw.setdefault("backoff_base_s", 0.0)
    engines = [
        ServeEngine(FakeBackend(slots, poison=poison),
                    RequestQueue(capacity=replica_capacity, clock=clock),
                    watchdog=TickWatchdog(stuck_slack_ticks=None))
        for _ in range(n_replicas)]
    router = Router(engines,
                    RequestQueue(capacity=front_capacity, clock=clock),
                    policy=RouterPolicy(**policy_kw), chaos=chaos)
    return router, t


def run(router, t, max_ticks=300):
    out = []
    for _ in range(max_ticks):
        if router.idle:
            return out
        t[0] += 0.01
        out.extend(router.tick())
    raise AssertionError(
        f"fleet not idle after {max_ticks} ticks: {router.counts()}")


# ---------------------------------------------------------------------------
# placement


def test_least_loaded_placement_spreads_work():
    router, t = make_fleet(3, slots=2)
    ids = [router.submit([1, 2, 3], max_new_tokens=4).id
           for _ in range(6)]
    t[0] += 0.01
    router.tick()
    loads = [rep.load for rep in router.replicas]
    assert loads == [2, 2, 2], loads
    run(router, t)
    for rid in ids:
        resp = router.response(rid)
        assert resp.status == "ok" and len(resp.tokens) == 4


def test_session_affinity_pins_then_remaps_off_unhealthy_home():
    router, t = make_fleet(3, placement="session")
    r1 = router.submit([1, 2], max_new_tokens=6, session="a")
    t[0] += 0.01
    router.tick()
    home = router._placed_on[r1.id]
    r2 = router.submit([1, 2], max_new_tokens=6, session="a")
    t[0] += 0.01
    router.tick()
    # pinned: same replica although it is now the MOST loaded
    assert router._placed_on[r2.id] == home
    # home goes unhealthy -> session falls back and REMAPS
    router.replicas[home].state = SUSPECT
    r3 = router.submit([1, 2], max_new_tokens=6, session="a")
    t[0] += 0.01
    router.tick()
    new_home = router._placed_on[r3.id]
    assert new_home != home
    assert router._session_map["a"] == new_home
    run(router, t)


# ---------------------------------------------------------------------------
# the acceptance pin: kill one of N, every id exactly once


@pytest.mark.chaos
def test_kill_replica_all_ids_resolve_exactly_once():
    """N=3, ``kill_replica`` fires mid-stream on replica 2: its queued
    backlog is evicted and re-placed, its live slots fail over, and
    every submitted id ends with exactly one terminal response — all
    ``ok``, because the kill is retryable and two replicas survive."""
    reg = get_registry()
    wedged0 = reg.counter("serve.fleet.wedged").value
    chaos = ChaosPlan([Fault("kill_replica", step=3, stage=2)])
    router, t = make_fleet(3, slots=2, chaos=chaos)
    ids = [router.submit([1, 2, 3], max_new_tokens=8, seed=i).id
           for i in range(12)]
    delivered = run(router, t)

    assert len(delivered) == len(ids)          # exactly once, in total
    assert sorted(r.request_id for r in delivered) == sorted(ids)
    for rid in ids:
        resp = router.response(rid)
        assert resp is not None and resp.status == "ok"
        assert len(resp.tokens) == 8
    # the killed replica walked WEDGED -> DRAINING -> RETIRED
    assert router.replicas[2].state == RETIRED
    assert router.counts()[HEALTHY] == 2
    assert reg.counter("serve.fleet.wedged").value == wedged0 + 1
    # work actually failed over (attempts > 1 somewhere)
    assert reg.counter("serve.fleet.failed_over").value > 0
    # per-replica labelled gauges reflect the terminal states
    assert reg.gauge(labelled("serve.fleet.replica.state",
                              replica=2)).value == 4.0  # RETIRED code


@pytest.mark.chaos
def test_wedged_backlog_is_evicted_intact_and_reserved():
    """Queued (never-admitted) requests on the killed replica come back
    INTACT and finish ok elsewhere with attempts == 2."""
    chaos = ChaosPlan([Fault("kill_replica", step=2, stage=1)])
    # slots=1 + deep replica queues so replica 1 holds a real backlog
    router, t = make_fleet(2, slots=1, chaos=chaos)
    reqs = [router.submit([1, 2], max_new_tokens=4, seed=i)
            for i in range(6)]
    run(router, t)
    assert all(router.response(r.id).status == "ok" for r in reqs)
    bounced = [r for r in reqs if r.attempts > 1]
    assert bounced, "no request ever touched the killed replica"
    assert all(r.attempts == 2 for r in bounced)


# ---------------------------------------------------------------------------
# retry budget / backoff


def test_retry_budget_exhausts_to_single_error_response():
    """A poison request that fails prefill on every replica burns its
    placements and ends as ONE ``retries_exhausted`` error, while
    healthy traffic keeps flowing."""
    router, t = make_fleet(2, poison=666, retry_budget=2,
                           wedge_error_ticks=100, wedge_decode_errors=100,
                           recover_healthy_ticks=1)
    bad = router.submit([666, 1], max_new_tokens=4)
    good = router.submit([1, 2], max_new_tokens=4)
    run(router, t)
    resp = router.response(bad.id)
    assert resp.status == "error"
    assert resp.finish_reason == "retries_exhausted"
    assert bad.attempts == 2
    assert router.response(good.id).status == "ok"


def test_backoff_parks_until_eligible():
    """With a real backoff base the bounced request sits parked until
    the clock passes ``base * 2^(attempts-1)``."""
    router, t = make_fleet(2, poison=666, retry_budget=3,
                           backoff_base_s=1.0, backoff_max_s=8.0,
                           wedge_error_ticks=100, wedge_decode_errors=100,
                           recover_healthy_ticks=1)
    bad = router.submit([666, 1], max_new_tokens=4)
    t[0] += 0.01
    router.tick()                  # placed (attempts=1), fails, parks
    assert bad.attempts == 1 and len(router._parked) == 1
    for _ in range(5):             # 0.05s << 1.0s backoff: stays parked
        t[0] += 0.01
        router.tick()
    assert bad.attempts == 1 and len(router._parked) == 1
    t[0] += 1.0                    # eligible now
    router.tick()
    assert bad.attempts == 2


def test_reclaim_park_or_finish_decisions_and_exactly_once():
    """``Router.reclaim`` is the ONE shared park-or-finish gate for
    requests knocked off a replica (wedge eviction and live-tick
    failures both route through it): cancelled/expired → parked for the
    terminal sweep, budget remaining → parked with backoff, budget
    spent → exactly one terminal error."""
    from pipe_tpu.serve.queue import Request

    router, t = make_fleet(1, retry_budget=2, backoff_base_s=1.0,
                           backoff_max_s=8.0)
    now = 5.0
    cancelled = Request(id=101, prompt=[1], max_new_tokens=4,
                        cancelled=True, attempts=1)
    expired = Request(id=102, prompt=[1], max_new_tokens=4,
                      deadline=4.0, attempts=1)
    retryable = Request(id=103, prompt=[1], max_new_tokens=4, attempts=1)
    spent = Request(id=104, prompt=[1], max_new_tokens=4, attempts=2,
                    submitted_at=1.0)

    finished = router.reclaim([cancelled, expired, retryable, spent], now)

    # only the spent request is terminal, and it is already ledgered
    assert [r.request_id for r in finished] == [104]
    assert finished[0].status == "error"
    assert finished[0].finish_reason == "retries_exhausted"
    assert router.response(104) is finished[0]
    # cancelled/expired park at `now` (no backoff credit); the
    # retryable one parks at now + base * 2^(attempts-1)
    parked = {req.id: at for at, req in router._parked}
    assert parked == {101: now, 102: now, 103: now + 1.0}
    # re-reclaiming the spent request would double-deliver: the ledger
    # refuses loudly instead of silently overwriting
    with pytest.raises(RuntimeError, match="exactly-once"):
        router.reclaim([spent], now)


# ---------------------------------------------------------------------------
# satellites: cancellation after failover, all-SUSPECT backpressure


def test_cancel_while_parked_after_failover():
    """Cancel a request sitting in the retry park (bounced off a failing
    replica, waiting out its backoff): one terminal ``cancelled``
    response, nothing delivered twice."""
    router, t = make_fleet(2, poison=666, backoff_base_s=100.0,
                           wedge_error_ticks=100, wedge_decode_errors=100)
    bad = router.submit([666, 1], max_new_tokens=4)
    t[0] += 0.01
    router.tick()                  # bounce -> parked for 100s
    assert len(router._parked) == 1
    assert router.cancel(bad.id)
    t[0] += 0.01
    delivered = router.tick()      # parked sweep emits the terminal
    assert [r.request_id for r in delivered] == [bad.id]
    resp = router.response(bad.id)
    assert resp.status == "cancelled" and resp.finish_reason == "cancelled"
    assert router.idle
    assert not router.cancel(bad.id)    # terminal ids are gone


def test_all_suspect_stops_placement_and_backpressures():
    """Every replica SUSPECT: placement halts (hysteresis — SUSPECT work
    just waits), the front queue fills, and the next submit feels
    QueueFull instead of silent loss."""
    reg = get_registry()
    rejected0 = reg.counter("serve.fleet.rejected").value
    router, t = make_fleet(2, front_capacity=4,
                           recover_healthy_ticks=1000)
    for rep in router.replicas:
        rep.state = SUSPECT
    for _ in range(4):
        router.submit([1, 2], max_new_tokens=4)
    for _ in range(3):
        t[0] += 0.01
        router.tick()
    assert router.queue.depth == 4          # nothing placed
    assert all(rep.load == 0 for rep in router.replicas)
    with pytest.raises(QueueFull):
        router.submit([1, 2], max_new_tokens=4)
    assert reg.counter("serve.fleet.rejected").value == rejected0 + 1


# ---------------------------------------------------------------------------
# deadlines survive failover


def test_no_deadline_credit_after_failover():
    """A request bounced by a failing replica keeps its ORIGINAL
    deadline through the retry park: once the clock passes it, the
    terminal record is ``timeout``/``deadline`` — not a fresh retry."""
    router, t = make_fleet(2, poison=666, backoff_base_s=0.0,
                           wedge_error_ticks=100, wedge_decode_errors=100,
                           retry_budget=100, recover_healthy_ticks=1)
    bad = router.submit([666, 1], max_new_tokens=4, timeout_s=0.5)
    deadline = bad.deadline
    t[0] += 0.01
    router.tick()                  # bounce #1
    assert bad.deadline == deadline        # identity preserved
    t[0] += 1.0                    # past the original deadline
    run(router, t)
    resp = router.response(bad.id)
    assert resp.status == "timeout" and resp.finish_reason == "deadline"
    assert resp.latency >= 0.5


# ---------------------------------------------------------------------------
# fleet drain, lifecycle, dead fleet


def test_fleet_drain_sheds_and_finishes_live():
    router, t = make_fleet(2, slots=1, replica_capacity=1,
                           front_capacity=16)
    reqs = [router.submit([1, 2], max_new_tokens=3) for _ in range(6)]
    t[0] += 0.01
    router.tick()                  # 2 live, 2 replica-queued, 2 at front
    router.drain()
    with pytest.raises(EngineDraining):
        router.submit([1, 2], max_new_tokens=3)
    run(router, t)
    assert router.drained
    statuses = {router.response(r.id).status for r in reqs}
    assert statuses <= {"ok", "shed"}
    shed = [r for r in reqs
            if router.response(r.id).finish_reason == "drain"]
    live_done = [r for r in reqs if router.response(r.id).status == "ok"]
    assert shed and live_done      # both paths exercised


def test_spawn_on_sustained_depth_and_retire_idle():
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731

    def spawn():
        return ServeEngine(FakeBackend(1),
                           RequestQueue(capacity=1, clock=clock),
                           watchdog=TickWatchdog(stuck_slack_ticks=None))

    engines = [spawn()]
    router = Router(engines, RequestQueue(capacity=32, clock=clock),
                    policy=RouterPolicy(backoff_base_s=0.0, spawn_depth=2,
                                        spawn_sustain_ticks=2,
                                        retire_idle_ticks=2,
                                        min_replicas=1),
                    spawn_fn=spawn)
    reqs = [router.submit([1, 2], max_new_tokens=4) for _ in range(6)]
    spawned0 = get_registry().counter("serve.fleet.spawned").value
    run(router, t)
    assert len(router.replicas) > 1        # depth sustained -> spawned
    assert get_registry().counter("serve.fleet.spawned").value > spawned0
    assert all(router.response(r.id).status == "ok" for r in reqs)
    for _ in range(8):                     # idle ticks -> retire back down
        t[0] += 0.01
        router.tick()
    counts = router.counts()
    assert counts[HEALTHY] == 1            # never below min_replicas
    assert counts[RETIRED] == len(router.replicas) - 1


@pytest.mark.chaos
def test_dead_fleet_fails_stranded_work_loudly():
    """Last replica wedges with work still parked/front-queued and no
    spawn hook: the stranded requests end ``no_replicas`` instead of
    parking forever — run_until_idle terminates."""
    chaos = ChaosPlan([Fault("kill_replica", step=0, stage=0)])
    router, t = make_fleet(1, chaos=chaos, wedge_error_ticks=1,
                           retry_budget=5)
    reqs = [router.submit([1, 2], max_new_tokens=4) for _ in range(3)]
    run(router, t, max_ticks=20)           # must terminate FAST
    for r in reqs:
        resp = router.response(r.id)
        assert resp.status == "error"
        assert resp.finish_reason == "no_replicas"
    for _ in range(2):                     # DRAINING -> RETIRED settles
        t[0] += 0.01
        router.tick()
    assert router.counts()[RETIRED] == 1


# ---------------------------------------------------------------------------
# zero overhead when absent


def test_chaos_none_leaves_backends_untouched():
    """No ChaosPlan -> the router installs NO wrappers: the replica
    backends' prefill/decode stay the class methods, never shadowed by
    instance attributes (the fleet layer adds zero overhead to the hot
    path)."""
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    engines = [ServeEngine(FakeBackend(2),
                           RequestQueue(capacity=8, clock=clock))
               for _ in range(2)]
    Router(engines, RequestQueue(capacity=8, clock=clock))
    for eng in engines:
        assert "decode" not in vars(eng.backend)
        assert "prefill" not in vars(eng.backend)
    # and WITH a plan, the wrappers are installed
    engines2 = [ServeEngine(FakeBackend(2),
                            RequestQueue(capacity=8, clock=clock))]
    Router(engines2, RequestQueue(capacity=8, clock=clock),
           chaos=ChaosPlan([Fault("kill_replica", step=0, stage=0)]))
    assert "decode" in vars(engines2[0].backend)
    assert "prefill" in vars(engines2[0].backend)


def test_router_rejects_shared_or_foreign_queues():
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    front = RequestQueue(capacity=8, clock=clock)
    shared = RequestQueue(capacity=8, clock=clock)
    with pytest.raises(ValueError):       # engine on the front queue
        Router([ServeEngine(FakeBackend(), front)], front)
    with pytest.raises(ValueError):       # two engines, one queue
        Router([ServeEngine(FakeBackend(), shared),
                ServeEngine(FakeBackend(), shared)],
               RequestQueue(capacity=8, clock=clock))
    with pytest.raises(ValueError):       # wrong clock domain
        Router([ServeEngine(FakeBackend(),
                            RequestQueue(capacity=8))], front)


# ---------------------------------------------------------------------------
# satellite units: queue re-queue identity, shed tie-break, watchdog surface


def test_requeue_preserves_identity_and_backpressures():
    t = [0.0]
    q = RequestQueue(capacity=2, clock=lambda: t[0])
    req = q.submit([1, 2], max_new_tokens=4, timeout_s=1.0)
    rid, sub, dl = req.id, req.submitted_at, req.deadline
    assert q.pop() is req
    t[0] = 5.0                     # clock moves; identity must not
    q.requeue(req)
    assert (req.id, req.submitted_at, req.deadline) == (rid, sub, dl)
    assert req.attempts == 0       # requeue never counts placements
    q.submit([3], max_new_tokens=1)
    with pytest.raises(QueueFull):
        q.requeue(req)


def test_shed_lowest_tiebreak_is_pure_request_identity():
    """Key is (priority, arrival, id): lowest priority first, youngest
    arrival within a level, highest id on exact-arrival ties — stable
    under the list reordering router re-queues cause."""
    t = [0.0]
    q = RequestQueue(capacity=8, clock=lambda: t[0])
    old = q.submit([1], max_new_tokens=1)            # t=0
    t[0] = 1.0
    y1 = q.submit([1], max_new_tokens=1)             # t=1
    y2 = q.submit([1], max_new_tokens=1)             # t=1, higher id
    hi = q.submit([1], max_new_tokens=1, priority=5)
    # reorder the backing list the way failover re-queues would
    q._waiting.reverse()
    assert [r.id for r in q.shed_lowest(2)] == [y2.id, y1.id]
    assert {r.id for r in q._waiting} == {old.id, hi.id}


def test_watchdog_read_only_health_surface():
    wd = TickWatchdog(tick_budget_s=0.1, stuck_slack_ticks=None)
    assert wd.record_tick(0.05) is False
    assert wd.slow_streak == 0 and wd.last_tick_s == 0.05
    assert wd.record_tick(0.2) is True
    assert wd.record_tick(0.3) is True
    assert (wd.slow_streak, wd.slow_ticks) == (2, 2)
    wd.record_tick(0.01)
    assert (wd.slow_streak, wd.slow_ticks) == (0, 2)
    assert wd.miss_ewma == 0.0
    assert wd.record_outcome(True) == pytest.approx(wd.shed_ewma_alpha)
    wd.record_stuck()
    assert wd.stuck_slots == 1


# ---------------------------------------------------------------------------
# real model, slow tier: bitwise parity through a mid-stream kill


CFG = LMConfig(vocab=89, d_model=32, nhead=4, d_ff=64, n_layers=4,
               seq_len=32, dropout=0.0)


@pytest.fixture(scope="module")
def model_and_params():
    model = PipelinedLM(CFG, n_stages=2)
    return model, model.init(jax.random.key(0))


@pytest.mark.chaos
def test_kill_failover_token_parity_real_model(model_and_params):
    """The gold contract survives failover: kill one of three real
    replicas mid-decode; every response is still bitwise the one-shot
    batch-1 Generator output, because the failed-over request re-enters
    a fresh slot with its original prompt AND seed."""
    model, params = model_and_params
    gen_cfg = GenerationConfig(max_new_tokens=6, temperature=0.0)
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(1, CFG.vocab, size=n))
               for n in (3, 5, 4, 7, 5, 6)]
    g = Generator(model, gen_cfg)
    refs = [np.asarray(g.generate(params, jnp.asarray(p, jnp.int32)[None],
                                  jax.random.key(7)))[0]
            for p in prompts]

    chaos = ChaosPlan([Fault("kill_replica", step=2, stage=2)])
    engines = [
        ServeEngine(SingleDeviceSlotBackend(
            model, params, num_slots=2, max_len=16, gen=gen_cfg,
            buckets=BucketSpec.of(4, 8)),
            RequestQueue(capacity=16),
            watchdog=TickWatchdog(stuck_slack_ticks=None))
        for _ in range(3)]
    router = Router(engines, RequestQueue(capacity=16),
                    policy=RouterPolicy(backoff_base_s=0.0), chaos=chaos)
    ids = [router.submit(p, max_new_tokens=6, seed=7).id for p in prompts]
    router.run_until_idle(max_ticks=200)

    assert router.replicas[2].state == RETIRED
    for i, rid in enumerate(ids):
        resp = router.response(rid)
        assert resp.status == "ok" and resp.finish_reason == "length"
        np.testing.assert_array_equal(np.asarray(resp.tokens), refs[i])


# ---------------------------------------------------------------------------
# appended with the fleet split (pipe_tpu/fleet): the exactly-once
# ledger across a TRANSPORT drop — the wire dies mid-flight while the
# replica behind it may be perfectly healthy


class _CutWire:
    """Wrap a replica's transport so the wire can be cut mid-flight:
    once ``severed``, every remote call raises TransportError while
    local state reads (queue depth, counters) stay ungated — exactly
    the failure surface of a dead socket under a live child process.
    Plain class on purpose: inheriting ReplicaTransport's default
    methods would shadow the ``__getattr__`` delegation."""

    _LOCAL = frozenset(["queue_depth", "queue_capacity", "live_slots",
                        "default_max_new_tokens", "rpc_inflight",
                        "rpc_retries", "close", "idle", "drained",
                        "engine"])

    def __init__(self, inner):
        self.inner = inner
        self.severed = False

    def __getattr__(self, name):
        from pipe_tpu.fleet import TransportError
        attr = getattr(object.__getattribute__(self, "inner"), name)
        if name in _CutWire._LOCAL:
            return attr
        if self.severed:
            raise TransportError("wire cut (test)")
        if callable(attr):
            def call(*a, **k):
                if self.severed:
                    raise TransportError("wire cut (test)")
                return attr(*a, **k)
            return call
        return attr


def test_transport_drop_mid_flight_delivers_every_id_exactly_once():
    """Cut one replica's wire (NOT the replica) with work in flight:
    the drop path reclaims the stranded in-flight set exactly once —
    every id resolves to one terminal through a sibling, the dropped
    replica walks to RETIRED, and the ledger still refuses a forged
    duplicate afterwards."""
    router, t = make_fleet(3, slots=2)
    ids = [router.submit([1, 2], max_new_tokens=8).id for _ in range(9)]
    t[0] += 0.01
    router.tick()                     # work in flight on every replica
    rep = router.replicas[0]
    wire = _CutWire(rep.transport)
    rep.transport = wire
    wire.severed = True
    out = run(router, t)
    assert sorted(r.request_id for r in out) == sorted(ids)
    assert all(r.status == "ok" for r in out)
    assert rep.state == RETIRED
    assert [r.state for r in router.replicas[1:]] == [HEALTHY, HEALTHY]
    with pytest.raises(RuntimeError, match="exactly-once"):
        router._deliver(out[0])


def test_transport_drop_of_whole_fleet_fails_each_id_once():
    """Every wire cut at once, nothing recoverable: stranded and queued
    work fails loudly (``no_replicas``) — but still exactly once per
    id, never silently dropped and never doubled."""
    router, t = make_fleet(2, slots=2)
    ids = [router.submit([3, 4], max_new_tokens=8).id for _ in range(6)]
    t[0] += 0.01
    router.tick()
    for rep in router.replicas:
        wire = _CutWire(rep.transport)
        rep.transport = wire
        wire.severed = True
    out = run(router, t)
    assert sorted(r.request_id for r in out) == sorted(ids)
    assert all(r.status == "error" for r in out)
    assert all(r.finish_reason == "no_replicas" for r in out)
    assert all(rep.state == RETIRED for rep in router.replicas)
