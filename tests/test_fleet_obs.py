"""Fleet observability plane (docs/observability.md, "Fleet
observability"): mergeable registry snapshots, label escaping, event-log
rotation, the TraceBuffer, cross-mode FleetObserver semantics, trace
stitching across failover, the SLO monitor, and the zero-overhead
pledge.

Tier-1 runs the unit pieces plus the shared observer matrix over the
in-process transports (``inproc``/``thread``) and the decode-HLO pin.
The ``slow`` tier runs the same matrix over REAL child processes plus
the acceptance drill: N=3 proc replicas, SIGKILL one mid-flight — the
per-replica delivery-synchronized token counters must sum to the
parent-observed delivered total, every delivered id must reconstruct
into exactly one stitched timeline, and a failed-over id must show BOTH
placements in one trace. Telemetry off must mean ZERO ``obs`` frames on
the wire (frame census) and byte-identical decode HLO.
"""

import json
import os
import time

import pytest

from pipe_tpu.fleet import (FleetController, ProcessReplicaTransport,
                            ReplicaSpec, RouterPolicy)
from pipe_tpu.obs.events import EventLog
from pipe_tpu.obs.fleet_obs import (STAGE_RANK, FleetObserver, SloMonitor,
                                    SloTargets, TraceBuffer,
                                    prometheus_text)
from pipe_tpu.obs.telemetry import (MetricsRegistry, get_registry, labelled,
                                    null_registry, set_registry)
from pipe_tpu.resilience import TickWatchdog
from pipe_tpu.serve import RequestQueue, Router, ServeEngine
from test_router import FakeBackend

CFG_KW = dict(vocab=61, d_model=16, nhead=2, d_ff=32, n_layers=2,
              seq_len=64, dropout=0.0)


@pytest.fixture
def registry():
    """Fresh registry installed as the process default; restored after."""
    prev = get_registry()
    reg = MetricsRegistry()
    set_registry(reg)
    yield reg
    set_registry(prev)


# ---------------------------------------------------------------------------
# labelled(): collision-safe escaping


def test_labelled_escapes_label_separators():
    # a replica id carrying the separator characters must not be able
    # to forge another series' name
    assert labelled("m", replica="a.b") == "m{replica=a\\.b}"
    forged = labelled("m", a="1,b=2")
    honest = labelled("m", a="1", b="2")
    assert forged != honest
    assert labelled("m", r="x{y}") == "m{r=x\\{y\\}}"


def test_labelled_plain_int_labels_unchanged():
    # every existing call site labels with int replica indices — their
    # series names must not churn
    assert labelled("serve.fleet.heartbeat_age_s", replica=0) == \
        "serve.fleet.heartbeat_age_s{replica=0}"


# ---------------------------------------------------------------------------
# mergeable snapshots


def test_mergeable_snapshot_roundtrips_all_instruments(registry):
    registry.counter("c").inc(7)
    registry.gauge("g").set(2.5)
    t = registry.timer("t")
    t.observe(1.0)
    t.observe(2.0)
    h = registry.histogram("h")
    for v in (0.001, 0.5, 4.0):
        h.observe(v)
    snap = registry.snapshot(mergeable=True, base={})
    out = MetricsRegistry()
    out.merge_snapshot(snap)
    assert out.counter("c").value == 7
    assert out.gauge("g").value == 2.5
    assert out.timer("t").count == 2 and out.timer("t").total == 3.0
    oh = out.histogram("h")
    assert oh.count == 3 and oh.sum == pytest.approx(4.501)
    assert oh.min == 0.001 and oh.max == 4.0


def test_mergeable_snapshot_is_delta_against_base(registry):
    base = {}
    registry.counter("c").inc(5)
    registry.histogram("h").observe(1.0)
    first = registry.snapshot(mergeable=True, base=base)
    assert first["c"]["d"] == 5
    # no movement -> zero-delta instruments are omitted entirely
    assert registry.snapshot(mergeable=True, base=base) == {}
    registry.counter("c").inc(2)
    second = registry.snapshot(mergeable=True, base=base)
    assert second["c"]["d"] == 2 and "h" not in second
    # a receiver that merges every delta reconstructs the totals
    out = MetricsRegistry()
    out.merge_snapshot(first)
    out.merge_snapshot(second)
    assert out.counter("c").value == 7
    assert out.histogram("h").count == 1


def test_merge_accumulates_histogram_buckets_across_sources(registry):
    a, b = MetricsRegistry(), MetricsRegistry()
    a.histogram("h").observe(0.01)
    a.histogram("h").observe(0.02)
    b.histogram("h").observe(8.0)
    merged = MetricsRegistry()
    merged.merge_snapshot(a.snapshot(mergeable=True, base={}))
    merged.merge_snapshot(b.snapshot(mergeable=True, base={}))
    h = merged.histogram("h")
    assert h.count == 3
    assert h.percentile(0.5) >= 0.02       # fleet median, not one source
    assert h.percentile(0.99) >= 8.0
    assert h.min == 0.01 and h.max == 8.0


def test_merge_into_disabled_registry_is_noop():
    reg = MetricsRegistry()
    reg.counter("c").inc(3)
    null_registry().merge_snapshot(reg.snapshot(mergeable=True, base={}))
    assert null_registry().counter("c").value == 0


# ---------------------------------------------------------------------------
# EventLog: size-bounded rotation + torn-final-line tolerance


def test_event_log_rotates_at_max_bytes(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    with EventLog(path, max_bytes=2048) as log:
        for i in range(200):
            log.event("request", request=i, stage="queued",
                      pad="x" * 64)
    assert os.path.exists(path + ".1"), "rollover file missing"
    assert os.path.getsize(path) <= 2048 + 4096  # one record of slack
    recs = EventLog.read(path)
    assert recs, "post-rotation log must be readable"
    header = recs[0]
    assert header["kind"] == "log_open" and header.get("rotated") is True
    # the rollover file holds the OLDER records
    old = EventLog.read(path + ".1")
    assert old[-1]["request"] < recs[-1]["request"]


def test_event_log_read_tolerates_torn_final_line(tmp_path):
    path = str(tmp_path / "torn.jsonl")
    with EventLog(path) as log:
        log.event("request", request=0, stage="queued")
        log.event("request", request=1, stage="queued")
    with open(path, "a") as f:
        f.write('{"kind": "request", "request": 2, "sta')   # crash here
    recs = EventLog.read(path)
    assert [r.get("request") for r in recs if r["kind"] == "request"] \
        == [0, 1]


def test_event_log_read_raises_on_torn_middle_line(tmp_path):
    # only a TRAILING torn line is a crash artifact; garbage in the
    # middle is corruption and must stay loud
    path = str(tmp_path / "bad.jsonl")
    with open(path, "w") as f:
        f.write('{"kind": "request", "request": 0}\n')
        f.write('{"kind": "requ\n')
        f.write('{"kind": "request", "request": 1}\n')
    with pytest.raises(json.JSONDecodeError):
        EventLog.read(path)


def test_event_log_rejects_tiny_max_bytes(tmp_path):
    with pytest.raises(ValueError):
        EventLog(str(tmp_path / "x.jsonl"), max_bytes=10)


# ---------------------------------------------------------------------------
# TraceBuffer


def test_trace_buffer_bounded_drops_oldest_and_counts():
    buf = TraceBuffer(maxlen=4)
    for i in range(7):
        buf.event("request", request=i)
    assert buf.dropped == 3
    got = [r["request"] for r in buf.drain()]
    assert got == [3, 4, 5, 6]
    assert buf.drain() == []                  # drain clears


def test_trace_buffer_spans_nest_like_event_log():
    buf = TraceBuffer()
    with buf.span("request", request=1) as outer:
        with buf.span("request", request=1) as inner:
            pass
    recs = buf.drain()
    assert recs[0]["id"] == inner and recs[0]["parent"] == outer
    assert recs[1]["id"] == outer and recs[1]["parent"] is None
    assert recs[0]["dur"] >= 0.0


# ---------------------------------------------------------------------------
# stitch ordering (synthetic streams: the SIGKILL failover shape)


class _StubTransport:
    def __init__(self, events=None):
        self._events = events
        self.obs_tokens_out = 0
        self.obs_responses_out = 0
        self.queue_depth = 0
        self.live_slots = 0

    def obs_view(self):
        if self._events is None:
            return None
        return (MetricsRegistry(), 0.1, 3, list(self._events))


class _StubReplica:
    def __init__(self, index, transport):
        self.index = index
        self.state = "healthy"
        self.transport = transport


class _StubController:
    def __init__(self, replicas, parent_records):
        self.replicas = replicas
        self._responses = {}
        self.events = type("E", (), {"path": None})()
        self.parent_records = parent_records


def test_stitch_orders_failover_as_one_trace_two_placements():
    # parent skeleton: queued -> placed(1) -> retry_parked(1) ->
    # placed(2) -> delivered; child streams contribute prefill/terminal
    # from two UNRELATED clocks (replica1's t is tiny — wall-clock
    # alone would sort it before replica0's records)
    tid = "abc123"
    parent = [
        {"kind": "request", "request": 7, "trace": tid, "stage": "queued",
         "t": 1.0},
        {"kind": "request", "request": 7, "trace": tid, "stage": "placed",
         "replica": 0, "attempts": 1, "t": 1.1},
        {"kind": "resilience", "request": 7, "trace": tid,
         "stage": "retry_parked", "attempts": 1, "t": 5.0},
        {"kind": "request", "request": 7, "trace": tid, "stage": "placed",
         "replica": 1, "attempts": 2, "t": 5.1},
        {"kind": "request", "request": 7, "trace": tid,
         "stage": "delivered", "attempts": 2, "t": 9.0},
    ]
    rep0 = [{"kind": "request", "request": 7, "trace": tid,
             "stage": "prefill", "attempts": 1, "t": 900.5}]
    rep1 = [{"kind": "request", "request": 7, "trace": tid,
             "stage": "prefill", "attempts": 2, "t": 0.002},
            {"kind": "request", "request": 7, "trace": tid,
             "stage": "terminal", "attempts": 2, "t": 0.9}]
    ctl = _StubController(
        [_StubReplica(0, _StubTransport(rep0)),
         _StubReplica(1, _StubTransport(rep1))], parent)
    obs = FleetObserver(ctl, parent_events=parent)
    traces = obs.stitch()
    assert list(traces) == [tid], "one trace across the failover"
    stages = [(r["stage"], r.get("attempts", 0), r["src"])
              for r in traces[tid]]
    assert stages == [
        ("queued", 0, "parent"),
        ("placed", 1, "parent"),
        ("prefill", 1, "replica0"),
        ("retry_parked", 1, "parent"),
        ("placed", 2, "parent"),
        ("prefill", 2, "replica1"),
        ("terminal", 2, "replica1"),
        ("delivered", 2, "parent"),
    ]
    by_req = obs.stitch_by_request()
    assert list(by_req) == [7] and len(by_req[7]) == 8


def test_stitch_groups_traceless_request_records_by_request_id():
    parent = [{"kind": "request", "request": 3, "stage": "queued",
               "t": 0.0},
              {"kind": "other", "t": 0.0}]           # no trace, no request
    ctl = _StubController([], parent)
    traces = FleetObserver(ctl, parent_events=parent).stitch()
    assert list(traces) == ["req:3"]
    assert STAGE_RANK["queued"] == 0                  # pinned vocabulary


def test_observer_peeks_live_trace_buffer_without_draining():
    """A live TraceBuffer passed as ``parent_events`` (the serve
    driver's --trace-out wiring) is read non-mutatingly: stitch twice,
    buffer still full."""
    buf = TraceBuffer()
    buf.event("request", request=1, trace="t1", stage="queued")
    obs = FleetObserver(_StubController([], []), parent_events=buf)
    assert list(obs.stitch()) == ["t1"]
    assert list(obs.stitch()) == ["t1"], "peek must not drain"
    assert buf.peek() and buf.drain(), "records still buffered"


# ---------------------------------------------------------------------------
# salvage: accepted-but-unpolled responses survive a transport drop


class _FrameAcceptTransport:
    """The surface a SIGKILL leaves behind on the process transport:
    terminal responses buffered AND counted at frame-accept time
    (``obs_tokens_out``), every remote call raising TransportError once
    the wire is severed, and ``salvage()`` still readable (the buffer
    is parent-side state — no socket needed)."""

    queue_capacity = 32
    default_max_new_tokens = 32
    rpc_inflight = 0
    rpc_retries = 0

    def __init__(self):
        self.obs_tokens_out = 0
        self.obs_responses_out = 0
        self._placed = {}
        self._buffer = []
        self.severed = False

    def _gate(self):
        if self.severed:
            from pipe_tpu.fleet import TransportError
            raise TransportError("wire cut (test)")

    def validate(self, prompt_len, max_new_tokens):
        pass

    def place(self, req):
        self._gate()
        req.attempts += 1
        self._placed[req.id] = req

    def poll(self):
        self._gate()
        out, self._buffer = self._buffer, []
        return out

    def evict_queued(self):
        self._gate()
        return []

    def cancel(self, request_id):
        self._gate()
        return False

    def drain(self):
        self._gate()

    def health(self):
        self._gate()
        from pipe_tpu.fleet import ReplicaHealth
        return ReplicaHealth()

    @property
    def drained(self):
        return not self._placed

    @property
    def idle(self):
        return not self._placed and not self._buffer

    @property
    def queue_depth(self):
        return len(self._placed)

    live_slots = 0

    def close(self):
        pass

    def obs_view(self):
        return None

    def accept_response(self, resp):
        """What the pump thread does on a ``response`` frame."""
        self._placed.pop(resp.request_id, None)
        self._buffer.append(resp)
        self.obs_tokens_out += len(resp.tokens)
        self.obs_responses_out += 1

    def salvage(self):
        out, self._buffer = self._buffer, []
        return out


def test_transport_drop_salvages_accepted_responses(registry):
    """A terminal response accepted off the wire (tokens already
    counted into ``obs_tokens_out``) but never polled must be DELIVERED
    by the drop path, not re-run: the request keeps attempts=1, the
    observer's delivered-token reconciliation holds, and the rescue is
    visible in ``serve.fleet.salvaged``."""
    from pipe_tpu.fleet import InProcessTransport
    from pipe_tpu.serve.queue import Response

    clock = [0.0]
    dying = _FrameAcceptTransport()
    healthy = InProcessTransport(
        ServeEngine(FakeBackend(2),
                    RequestQueue(capacity=32, clock=lambda: clock[0]),
                    watchdog=TickWatchdog(stuck_slack_ticks=None)))
    ctl = FleetController(
        [dying, healthy],
        RequestQueue(capacity=32, clock=lambda: clock[0]),
        policy=RouterPolicy(backoff_base_s=0.0))
    try:
        req = ctl.submit([1, 2, 3], max_new_tokens=8)
        clock[0] += 0.01
        ctl.tick()
        assert req.id in dying._placed, "placed on the dying transport"
        # the child finishes; the response frame crosses into the
        # parent (counted) — and THEN the wire dies, un-polled
        dying.accept_response(Response(
            request_id=req.id, tokens=[5] * 8, status="ok",
            finish_reason="length", prompt_len=3, ttft=0.01,
            latency=0.02))
        dying.severed = True
        clock[0] += 0.01
        out = []
        for _ in range(50):
            out.extend(ctl.tick())
            clock[0] += 0.01
            if out:
                break
        assert [r.request_id for r in out] == [req.id]
        assert out[0].status == "ok" and len(out[0].tokens) == 8
        assert req.attempts == 1, "salvaged, not retried"
        rec = FleetObserver(ctl).reconcile()
        assert rec["reconciled"], rec
        assert rec["delivered_tokens"] == 8
        assert rec["per_replica_tokens_out"][0] == 8
        assert registry.counter("serve.fleet.salvaged").value == 1
    finally:
        ctl.close()


# ---------------------------------------------------------------------------
# SLO monitor + Prometheus exposition


def _slo_registry(ttfts=(0.01, 0.02), e2es=(0.1,), delivered=4, ok=4,
                  timed_out=0, shed=0):
    reg = MetricsRegistry()
    for v in ttfts:
        reg.histogram("serve.engine.ttft_sec").observe(v)
    for v in e2es:
        reg.histogram("serve.engine.e2e_sec").observe(v)
    reg.counter("serve.fleet.delivered").inc(delivered)
    reg.counter("serve.fleet.ok").inc(ok)
    reg.counter("serve.engine.timed_out").inc(timed_out)
    reg.counter("serve.engine.shed").inc(shed)
    return reg


def test_slo_verdict_ok_and_observed_fields():
    mon = SloMonitor(SloTargets(ttft_p99_s=1.0, goodput_min=0.9))
    v = mon.verdict(_slo_registry())
    assert v["ok"] and v["violations"] == []
    assert v["observed"]["goodput"] == 1.0
    assert v["observed"]["delivered"] == 4
    assert v["targets"] == {"ttft_p99_s": 1.0, "goodput_min": 0.9}


def test_slo_verdict_flags_max_and_min_sense_violations():
    mon = SloMonitor(SloTargets(ttft_p99_s=0.001, goodput_min=0.95))
    v = mon.verdict(_slo_registry(ttfts=(0.5,), delivered=10, ok=5))
    bad = {x["slo"] for x in v["violations"]}
    assert not v["ok"] and bad == {"ttft_p99_s", "goodput_min"}
    miss = SloMonitor(SloTargets(deadline_miss_max=0.1)).verdict(
        _slo_registry(delivered=10, ok=8, timed_out=2))
    assert not miss["ok"]
    assert miss["observed"]["deadline_miss_rate"] == pytest.approx(0.2)


def test_prometheus_text_renders_all_instrument_kinds():
    reg = _slo_registry()
    reg.gauge(labelled("serve.fleet.replica.state", replica=0)).set(0)
    reg.timer("serve.engine.host_sec").observe(0.5)
    text = prometheus_text(reg)
    assert "# TYPE serve_fleet_delivered counter" in text
    assert "serve_fleet_delivered 4" in text
    assert 'serve_fleet_replica_state{replica="0"} 0' in text
    assert "serve_engine_host_sec_count 1" in text
    assert 'serve_engine_ttft_sec_bucket{le="+Inf"} 2' in text
    assert "serve_engine_ttft_sec_count 2" in text


# ---------------------------------------------------------------------------
# the shared observer matrix: one contract over all three fleet modes


def _proc_spec(**kw):
    base = dict(
        lm_cfg=dict(CFG_KW),
        num_slots=2, max_len=48, init_seed=0,
        gen=dict(max_new_tokens=8, temperature=0.0),
        decode_chunk=1, heartbeat_interval_s=0.05,
    )
    base.update(kw)
    return ReplicaSpec(**base)


def _make_fleet(mode, n=2, capacity=64):
    trace_buf = TraceBuffer(maxlen=100_000)
    if mode == "proc":
        transports = [ProcessReplicaTransport(_proc_spec())
                      for _ in range(n)]
        ctl = FleetController(
            transports, RequestQueue(capacity=capacity),
            policy=RouterPolicy(backoff_base_s=0.0,
                                heartbeat_timeout_s=5.0),
            event_log=trace_buf)
        return ctl, trace_buf
    engines = [ServeEngine(FakeBackend(2),
                           RequestQueue(capacity=capacity),
                           watchdog=TickWatchdog(stuck_slack_ticks=None))
               for _ in range(n)]
    ctl = Router(engines, RequestQueue(capacity=capacity),
                 policy=RouterPolicy(backoff_base_s=0.0),
                 event_log=trace_buf,
                 async_tick=(mode == "thread"))
    return ctl, trace_buf


def _run_to_idle(ctl, timeout_s=120.0):
    deadline = time.monotonic() + timeout_s
    while not ctl.idle:
        ctl.tick()
        time.sleep(0.005)
        assert time.monotonic() < deadline, "fleet never went idle"


MODES = ["inproc", "thread",
         pytest.param("proc", marks=pytest.mark.slow)]


@pytest.mark.parametrize("mode", MODES)
def test_observer_contract_across_fleet_modes(mode, registry):
    ctl, trace_buf = _make_fleet(mode)
    try:
        ids = [ctl.submit([1 + i, 2, 3], max_new_tokens=4, seed=i).id
               for i in range(6)]
        _run_to_idle(ctl)
    finally:
        ctl.close()
    obs = FleetObserver(ctl, parent_events=trace_buf.drain())

    # reconciliation: delivery-synchronized per-replica token counters
    # sum to the parent ledger's delivered total, in every mode
    rec = obs.reconcile()
    assert rec["reconciled"], rec
    assert rec["delivered_tokens"] == sum(
        len(ctl.response(i).tokens) for i in ids)

    per = obs.per_replica()
    assert set(per) == {0, 1}
    for view in per.values():
        assert view["state"] == "healthy"
        if mode == "proc":
            assert view["shipped"] and view["staleness_s"] is not None
            assert view["obs_seq"] >= 0
        else:
            assert not view["shipped"] and view["staleness_s"] == 0.0
    assert sum(v["responses_out"] for v in per.values()) == len(ids)

    # the merged rollup carries fleet counters AND engine histograms
    # (shipped over the wire in proc mode, shared registry otherwise)
    roll = obs.rollup()
    assert roll.counter("serve.fleet.delivered").value == len(ids)
    assert roll.histogram("serve.engine.ttft_sec").count >= len(ids)

    # every submitted id reconstructs into exactly one stitched trace
    # with the full lifecycle — including engine-side stages (inherited
    # event log in-process; shipped child events over the wire)
    by_req = obs.stitch_by_request()
    owners = {}
    for key, recs in obs.stitch().items():
        for r in recs:
            if r.get("request") is not None:
                owners.setdefault(int(r["request"]), set()).add(key)
    for i in ids:
        assert i in by_req, f"request {i} lost from the stitched traces"
        assert len(owners[i]) == 1, f"request {i} split across traces"
        stages = {r.get("stage") for r in by_req[i]}
        assert {"queued", "placed", "prefill", "terminal",
                "delivered"} <= stages, (i, stages)

    if mode == "proc":
        assert registry.counter("serve.fleet.obs_frames").value > 0
        for rep in ctl.replicas:
            census = rep.transport._frame_census
            assert census.get("obs", 0) > 0, census


# ---------------------------------------------------------------------------
# acceptance drill: N=3 proc fleet, SIGKILL one child mid-flight


@pytest.mark.slow
def test_proc_drill_sigkill_reconciles_and_stitches(registry):
    trace_buf = TraceBuffer(maxlen=100_000)
    transports = [ProcessReplicaTransport(_proc_spec())
                  for _ in range(3)]
    ctl = FleetController(transports, RequestQueue(capacity=512),
                          policy=RouterPolicy(backoff_base_s=0.0,
                                              heartbeat_timeout_s=5.0),
                          event_log=trace_buf)
    ids = []
    try:
        def submit_one(i):
            ids.append(ctl.submit([i % 40 + 1, 2, 3],
                                  max_new_tokens=4, seed=i).id)

        for i in range(12):
            submit_one(i)
        # kill only once the victim HOLDS work, so at least one request
        # demonstrably fails over (same idiom as test_fleet.py)
        deadline = time.monotonic() + 60.0
        while True:
            ctl.tick()
            if transports[2]._inflight:
                break
            time.sleep(0.01)
            if ctl.idle and len(ids) < 256:
                for _ in range(12):
                    submit_one(len(ids))
            assert time.monotonic() < deadline, "victim never got work"
        victim_inflight = list(transports[2]._inflight)
        transports[2]._proc.kill()
        _run_to_idle(ctl)
    finally:
        ctl.close()

    obs = FleetObserver(ctl, parent_events=trace_buf.drain())

    # 1) merged rollups reconcile: per-replica delivery-synchronized
    #    token counters sum to the parent-observed delivered total —
    #    ACROSS the SIGKILL (tokens ride the same frame as the
    #    response, so a lost child can't desynchronize the ledger)
    rec = obs.reconcile()
    assert rec["reconciled"], rec
    assert rec["tokens_out_sum"] == sum(
        len(ctl.response(i).tokens) for i in ids)

    # 2) a stitched timeline for EVERY delivered id, each in exactly
    #    one trace
    by_req = obs.stitch_by_request()
    owners = {}
    for key, recs in obs.stitch().items():
        for r in recs:
            if r.get("request") is not None:
                owners.setdefault(int(r["request"]), set()).add(key)
    for i in ids:
        assert ctl.response(i) is not None, "id vanished across SIGKILL"
        assert i in by_req, f"request {i} lost from the stitched traces"
        assert len(owners[i]) == 1, f"request {i} split across traces"

    # 3) a failed-over id shows BOTH placements in ONE trace, ordered
    #    by attempt
    failed_over = [i for i in ids
                   if len([r for r in by_req[i]
                           if r.get("stage") == "placed"]) >= 2]
    assert failed_over, f"no failover observed (victim held "\
        f"{victim_inflight})"
    for i in failed_over:
        placed = [r for r in by_req[i] if r.get("stage") == "placed"]
        attempts = [r["attempts"] for r in placed]
        assert len(set(attempts)) == len(attempts) >= 2
        assert attempts == sorted(attempts), "placements out of order"

    # 4) the obs plane itself showed up on the wire and in metrics
    assert registry.counter("serve.fleet.obs_frames").value > 0
    per = obs.per_replica()
    assert any(v["staleness_s"] is not None for v in per.values())


# ---------------------------------------------------------------------------
# zero-overhead pledge


@pytest.mark.slow
def test_telemetry_disabled_ships_zero_obs_frames():
    tr = ProcessReplicaTransport(_proc_spec(telemetry=False))
    try:
        q = RequestQueue()
        req = q.submit([5, 6, 7], max_new_tokens=4, seed=0)
        tr.place(req)
        got = []
        deadline = time.monotonic() + 120.0
        while not got:
            got.extend(tr.poll())
            time.sleep(0.02)
            assert time.monotonic() < deadline
        # several heartbeat periods: any obs shipping would have fired
        time.sleep(0.5)
        census = dict(tr._frame_census)
    finally:
        tr.close()
    assert census.get("hb", 0) > 0, census          # wire was alive
    assert census.get("obs", 0) == 0, census        # and carried no obs
    reg, age, seq, events = tr.obs_view()
    assert age is None and events == []


def test_decode_hlo_byte_identical_under_obs_plane(registry):
    import jax

    from pipe_tpu.inference import GenerationConfig
    from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
    from pipe_tpu.serve import SingleDeviceSlotBackend

    model = PipelinedLM(LMConfig(**CFG_KW), 1)
    params = model.init(jax.random.key(0))

    def lowered():
        be = SingleDeviceSlotBackend(
            model, params, num_slots=2, max_len=24,
            gen=GenerationConfig(max_new_tokens=4, temperature=0.0))
        return be._decode_jit.lower(
            be._block_stack, be._pre, be._post, be._caches, be._tok,
            be._pos, be._key_data).as_text(), be

    base, _ = lowered()

    # telemetry OFF (the child worker's spec.telemetry=False path)
    prev = get_registry()
    set_registry(null_registry())
    try:
        off, _ = lowered()
    finally:
        set_registry(prev)
    assert off == base

    # full obs plane ON: live registry, TraceBuffer event log, traced
    # requests actually served through the engine
    text, be = lowered()
    eng = ServeEngine(be, RequestQueue(), event_log=TraceBuffer())
    eng.submit([1, 2, 3], max_new_tokens=4, seed=0)
    out = eng.run_until_idle()
    assert out and out[0].status == "ok"
    after, _ = lowered()
    assert base == text == after
