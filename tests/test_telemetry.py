"""Unified runtime telemetry (docs/observability.md): registry semantics,
structured JSONL events, StepReport math, xplane round-trip + per-stage
timeline attribution, and the executors' dispatch instrumentation.

The no-op contract matters as much as the happy path: a disabled registry
must hand back shared null instruments (no allocation, no clock reads) and
``NULL_EVENT_LOG`` must swallow spans without touching the filesystem —
the Trainer leaves its telemetry call sites unconditional on that basis.
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core import microbatch as mb
from pipe_tpu.core.schedule import bubble_fraction
from pipe_tpu.obs import events as ev
from pipe_tpu.obs.meters import stage_timeline_from_trace
from pipe_tpu.obs.telemetry import (MetricsRegistry, NULL_INSTRUMENT,
                                    StepReport, get_registry, null_registry,
                                    set_registry, train_flops_per_token)
from pipe_tpu.obs.xplane import (TraceEvent, TraceLine, TracePlane,
                                 encode_xspace, parse_xspace)

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))

import timeline_report  # noqa: E402

WIDTH = 8


@pytest.fixture
def registry():
    """Fresh registry installed as the process default; restored after."""
    prev = get_registry()
    reg = MetricsRegistry()
    set_registry(reg)
    yield reg
    set_registry(prev)


# ---------- registry semantics ----------

def test_counter_gauge_timer_histogram(registry):
    registry.counter("c").inc()
    registry.counter("c").inc(4)
    assert registry.counter("c").value == 5
    registry.gauge("g").set(2.5)
    assert registry.gauge("g").value == 2.5
    t = registry.timer("t")
    t.observe(1.0)
    t.observe(2.0)
    assert t.count == 2 and t.total == 3.0 and t.last == 2.0
    # EWMA after [1.0, 2.0] at alpha=0.1: 1.0 then 0.9*1.0 + 0.1*2.0
    assert t.ewma == pytest.approx(1.1)
    h = registry.histogram("h")
    for v in [0.001, 0.002, 0.004, 1.0]:
        h.observe(v)
    s = h.summary()
    assert s["count"] == 4 and s["min"] == 0.001 and s["max"] == 1.0
    assert s["sum"] == pytest.approx(1.007)
    # percentiles report the bucket's upper edge: monotone, >= the value
    assert h.percentile(0.5) >= 0.002
    assert h.percentile(0.99) >= 1.0


def test_instruments_are_interned_per_name(registry):
    assert registry.counter("x") is registry.counter("x")
    assert registry.timer("y") is registry.timer("y")


def test_timer_context_manager(registry):
    with registry.timer("ctx").time():
        pass
    assert registry.timer("ctx").count == 1
    with registry.histogram("hctx").time():
        pass
    assert registry.histogram("hctx").summary()["count"] == 1


def test_snapshot_and_scalars(registry):
    registry.counter("a.b").inc(3)
    registry.gauge("a.g").set(7.0)
    registry.timer("a.t").observe(0.5)
    registry.histogram("a.h").observe(0.25)
    snap = registry.snapshot()
    assert snap["a.b"] == 3
    assert snap["a.g"] == 7.0
    assert snap["a.t"]["count"] == 1
    assert snap["a.h"]["count"] == 1
    flat = registry.scalars()
    assert flat["a.b"] == 3.0 and flat["a.g"] == 7.0
    assert "a.t.ewma" in flat and "a.h.p50" in flat
    registry.reset()
    assert registry.snapshot() == {}


# ---------- no-op contract when disabled ----------

def test_disabled_registry_hands_back_shared_null_instrument():
    reg = null_registry()
    assert reg.counter("anything") is NULL_INSTRUMENT
    assert reg.histogram("other") is NULL_INSTRUMENT
    # nothing is allocated or recorded
    reg.counter("anything").inc(10)
    reg.gauge("g").set(1.0)
    with reg.timer("t").time():
        pass
    assert reg.snapshot() == {}


def test_disabled_registry_no_observe_calls(monkeypatch):
    """Call-count check: the null time() context must not route through
    observe (zero per-use overhead beyond a dict-free attribute hop)."""
    calls = []
    monkeypatch.setattr(type(NULL_INSTRUMENT), "observe",
                        lambda self, s: calls.append(s))
    reg = MetricsRegistry(enabled=False)
    for _ in range(100):
        with reg.timer("t").time():
            pass
        reg.counter("c").inc()
    assert calls == []
    assert reg._instruments == {}


def test_null_event_log_writes_nothing(tmp_path):
    log = ev.NULL_EVENT_LOG
    with log.span(ev.STEP, step=0):
        log.event("anything", x=1)
    log.flush()
    log.close()
    assert os.listdir(tmp_path) == []


# ---------- structured event log ----------

def test_event_log_jsonl_roundtrip_nested_spans(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with ev.EventLog(path) as log:
        with log.span(ev.STEP, step=0) as step_id:
            with log.span(ev.STAGE, stage=1) as stage_id:
                with log.span(ev.MICROBATCH, microbatch=2):
                    pass
            log.event("profile_trace", path="/tmp/x")
        assert stage_id != step_id
    records = ev.EventLog.read(path)
    assert records[0]["kind"] == "log_open"
    by_kind = {}
    for r in records:
        by_kind.setdefault(r["kind"], []).append(r)
    # spans close inside-out: each child links its parent's id
    mbr, = by_kind[ev.MICROBATCH]
    st, = by_kind[ev.STAGE]
    sp, = by_kind[ev.STEP]
    assert mbr["parent"] == st["id"] and st["parent"] == sp["id"]
    assert sp["parent"] is None and sp["step"] == 0
    assert all(r["dur"] >= 0 for r in (mbr, st, sp))
    assert by_kind["profile_trace"][0]["parent"] == sp["id"]
    # every line is independently json-parseable (the JSONL contract)
    with open(path) as f:
        for line in f:
            json.loads(line)


def test_event_log_metrics_snapshot(tmp_path, registry):
    registry.counter("k").inc(2)
    path = str(tmp_path / "events.jsonl")
    with ev.EventLog(path) as log:
        log.metrics_snapshot(registry)
    records = ev.EventLog.read(path)
    snap = [r for r in records if r["kind"] == "metrics"][0]
    assert snap["metrics"]["k"] == 2


# ---------- StepReport math ----------

def test_step_report_synthetic_timings():
    r = StepReport.compute(step=3, wall_sec=0.5, tokens=4096, n_stages=4,
                           chunks=8, checkpoint="except_last",
                           schedule="1f1b",
                           analytic_bubble=bubble_fraction(8, 4))
    assert r.tokens_per_sec == pytest.approx(8192.0)
    assert r.tokens_per_sec_per_chip == pytest.approx(2048.0)
    assert r.analytic_bubble == pytest.approx((4 - 1) / (8 + 4 - 1))
    assert r.mfu is None  # no model_cfg: throughput-only report
    j = r.to_json()
    assert j["metric"] == "train_tokens_per_sec_per_chip"
    assert j["value"] == pytest.approx(2048.0)
    assert j["unit"] == "tokens/s/chip"
    assert j["analytic_bubble"] == pytest.approx(
        round(bubble_fraction(8, 4), 4))
    for k in ("n_stages", "chunks", "checkpoint", "schedule", "mfu", "hfu",
              "measured_bubble", "measured_bubble_method", "final_loss"):
        assert k in j


def test_step_report_mfu_math():
    from pipe_tpu.models.transformer_lm import LMConfig
    cfg = LMConfig().tiny()
    req_tok, hw_tok = train_flops_per_token(cfg, "never", 4)
    # peak chosen so per-chip flops run at half of it => mfu = 0.5 exactly
    tokens, wall, n = 1000, 2.0, 2
    per_chip = tokens / wall / n
    r = StepReport.compute(step=0, wall_sec=wall, tokens=tokens, n_stages=n,
                           chunks=4, checkpoint="never", model_cfg=cfg,
                           peak_flops=req_tok * per_chip * 2)
    assert r.mfu == pytest.approx(0.5)
    assert r.hfu == pytest.approx(0.5 * hw_tok / req_tok)
    assert r.hfu >= r.mfu  # hardware flops include recompute


def test_step_report_scalar_items():
    r = StepReport.compute(step=0, wall_sec=1.0, tokens=100, loss=2.0,
                           analytic_bubble=0.3,
                           memory={"cpu:0": {"peak_bytes_in_use": 2 ** 30}})
    items = dict(r.scalar_items())
    assert items["telemetry/tokens_per_sec"] == pytest.approx(100.0)
    assert items["telemetry/loss"] == 2.0
    assert items["telemetry/analytic_bubble"] == pytest.approx(0.3)
    assert items["telemetry/peak_gib/cpu:0"] == pytest.approx(1.0)


# ---------- xplane round-trip + timeline attribution ----------

def _synthetic_planes(ms=1_000_000):
    """Two device planes running an m=4, n=2 forward wave: stage j busy
    1ms per chunk, chunk i at cycle i + j."""
    planes = []
    for j in range(2):
        evs = [TraceEvent(name=f"jit_step/chunk{i}-stage{j}/fusion",
                          start_ns=(i + j) * ms, duration_ns=ms)
               for i in range(4)]
        planes.append(TracePlane(
            name=f"/device:TPU:{j}",
            lines=[TraceLine(name="XLA Ops", timestamp_ns=0, events=evs)]))
    return planes


def test_xplane_encode_parse_roundtrip():
    planes = _synthetic_planes()
    parsed = parse_xspace(encode_xspace(planes))
    assert [p.name for p in parsed] == [p.name for p in planes]
    for orig, back in zip(planes, parsed):
        assert [l.name for l in back.lines] == [l.name for l in orig.lines]
        for lo, lb in zip(orig.lines, back.lines):
            assert [(e.name, e.start_ns, e.duration_ns) for e in lb.events] \
                == [(e.name, e.start_ns, e.duration_ns) for e in lo.events]


def test_stage_timeline_from_synthetic_device_trace(tmp_path):
    with open(tmp_path / "host.xplane.pb", "wb") as f:
        f.write(encode_xspace(_synthetic_planes()))
    tl = stage_timeline_from_trace(str(tmp_path))
    assert tl["source"] == "device"
    assert sorted(tl["stages"]) == [0, 1]
    for j in (0, 1):
        st = tl["stages"][j]
        assert st["busy_sec"] == pytest.approx(4e-3)
        assert sorted(st["chunks"]) == [0, 1, 2, 3]
    lo, hi = tl["span"]
    assert (hi - lo) / 1e9 == pytest.approx(5e-3)  # cycles 0..4 inclusive


def test_stage_timeline_graceful_without_tagged_events(tmp_path):
    tl = stage_timeline_from_trace(str(tmp_path))  # empty dir
    assert tl == {"source": None, "span": (0.0, 0.0), "stages": {}}


def test_timeline_report_summary_and_render(tmp_path):
    with open(tmp_path / "host.xplane.pb", "wb") as f:
        f.write(encode_xspace(_synthetic_planes()))
    tl = stage_timeline_from_trace(str(tmp_path))
    summary = timeline_report.summarize(tl, "1f1b", 4, 2)
    assert summary["source"] == "device"
    assert summary["analytic_bubble"] == pytest.approx(bubble_fraction(4, 2))
    # 2 stages x 4ms busy over a 5ms span => 1 - 8/10
    assert summary["measured_bubble"] == pytest.approx(0.2)
    text = timeline_report.render(tl, summary, width=40)
    assert "stage 0|" in text and "stage 1|" in text

    empty = stage_timeline_from_trace(str(tmp_path / "nope"))
    fallback = timeline_report.render(
        empty, timeline_report.summarize(empty, "1f1b", 4, 2), width=40)
    assert "no chunk{i}-stage{j}" in fallback


# ---------- executor dispatch instrumentation ----------

def _uniform_pipe(n_stages=2):
    from pipe_tpu import Linear, Pipe, Sequential
    from pipe_tpu.parallel.mesh import make_mesh
    seq = Sequential([Linear(WIDTH) for _ in range(4)])
    params = seq.init(jax.random.key(0), jnp.zeros((2, WIDTH)))
    mesh = make_mesh(n_stages, 1, devices=jax.devices()[:n_stages])
    pipe = Pipe(seq, chunks=4, checkpoint="never", mesh=mesh,
                schedule="1f1b")
    grouped, off = [], 0
    for wdt in pipe.balance:
        grouped.append(params[off:off + wdt])
        off += wdt
    packed = pipe.shard_params(grouped)
    return pipe, packed


def _mse(out, tgt):
    return jnp.mean((out - tgt[:, None]) ** 2, axis=-1)


def test_uniform_fastpath_taken_and_gauged(registry):
    pipe, packed = _uniform_pipe()
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    y = jnp.sum(jnp.sin(x), axis=-1)
    loss, grads = pipe.loss_and_grad(packed, x, targets=y, loss_fn=_mse)
    assert pipe._train_executor.uniform_fastpath is True
    assert registry.gauge("pipe.uniform_fastpath").value == 1
    assert registry.counter("pipe.lowerings.fastpath").value >= 1

    # pin the fast path against the general switch lowering
    from pipe_tpu.parallel.hetero_scheduled import HeteroScheduledPipeline
    orig = HeteroScheduledPipeline._branches_uniform
    HeteroScheduledPipeline._branches_uniform = \
        lambda self, low, *, train: False
    try:
        pipe_sw, packed_sw = _uniform_pipe()
        loss_sw, grads_sw = pipe_sw.loss_and_grad(packed_sw, x, targets=y,
                                                  loss_fn=_mse)
    finally:
        HeteroScheduledPipeline._branches_uniform = orig
    assert pipe_sw._train_executor.uniform_fastpath is False
    assert registry.gauge("pipe.uniform_fastpath").value == 0
    assert registry.counter("pipe.lowerings.switch").value >= 1
    np.testing.assert_allclose(float(loss), float(loss_sw), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(grads),
                    jax.tree_util.tree_leaves(grads_sw)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_uniform_probe_verdict_cached(registry):
    """A re-lowering with identical (treedefs, boundary shapes, train)
    must reuse the cached verdict — counted as a hit, not re-traced."""
    from pipe_tpu.parallel.hetero_scheduled import HeteroScheduledPipeline
    pipe, packed = _uniform_pipe()
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    y = jnp.sum(jnp.sin(x), axis=-1)

    def run():
        # a FRESH jit wrapper always retraces, re-running the executor's
        # Python lowering (where the probe lives)
        return jax.jit(lambda p, xx, yy: pipe.loss_and_grad(
            p, xx, targets=yy, loss_fn=_mse))(packed, x, y)

    run()
    misses0 = registry.counter("pipe.uniform_probe.cache_misses").value
    assert misses0 >= 1
    probes = []
    orig = HeteroScheduledPipeline._probe_branches_uniform
    HeteroScheduledPipeline._probe_branches_uniform = \
        lambda self, low, *, train: probes.append(1) or orig(
            self, low, train=train)
    try:
        run()
    finally:
        HeteroScheduledPipeline._probe_branches_uniform = orig
    assert probes == []
    assert registry.counter("pipe.uniform_probe.cache_hits").value >= 1
    assert registry.counter(
        "pipe.uniform_probe.cache_misses").value == misses0


def test_scheduled_lowering_counters(registry):
    """The raw table executor counts LOWERINGS (trace-time events — the
    compile/retrace signal): a cached jit call adds none, a fresh jit
    wrapper adds one."""
    from pipe_tpu.ops.layers import Linear
    from pipe_tpu.parallel.mesh import make_mesh
    from pipe_tpu.parallel.scheduled import ScheduledPipeline
    from pipe_tpu.parallel.spmd import stack_stage_params

    layer = Linear(WIDTH)
    params = [layer.init(jax.random.fold_in(jax.random.key(0), j),
                         jnp.zeros((1, WIDTH))) for j in range(2)]

    def stage_fn(p, h, ctx):
        return jnp.tanh(layer.apply(p, h))

    def pre_fn(p, x, ctx):
        return x

    def post_fn(p, h, x_mb, ctx):
        return jnp.sum((h - 1.0) ** 2, axis=-1)

    mesh = make_mesh(2, 1, devices=jax.devices()[:2])
    sched = ScheduledPipeline(mesh, stage_fn, pre_fn=pre_fn, post_fn=post_fn,
                              checkpoint="never", schedule="1f1b")
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    xs, _ = mb.stack_scatter(x, 4)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    stacked = stack_stage_params(params)

    ctr = registry.counter("scheduled.loss_and_grad.lowerings")
    before = ctr.value
    f = jax.jit(sched.loss_and_grad)
    f(stacked, {}, {}, xs, w)
    assert ctr.value == before + 1
    f(stacked, {}, {}, xs, w)          # compile-cache hit: no retrace
    assert ctr.value == before + 1
    # a distinct function object forces a retrace => one more lowering
    jax.jit(lambda *a: sched.loss_and_grad(*a))(stacked, {}, {}, xs, w)
    assert ctr.value == before + 2
    assert registry.gauge("scheduled.cycles").value > 0


# ---------- train-loop smoke: JSONL + StepReport on CPU ----------

def test_trainer_emits_events_and_step_reports(tmp_path, registry):
    import dataclasses as dc
    from pipe_tpu.data import lm_text
    from pipe_tpu.models.transformer_lm import LMConfig
    from pipe_tpu.train.loop import Trainer, TrainerConfig

    model_cfg = dc.replace(LMConfig().tiny(), n_layers=2)
    cfg = TrainerConfig(batch_size=8, eval_batch_size=8, bptt=16, chunks=4,
                        checkpoint="never", n_stages=2, schedule="gpipe",
                        telemetry_dir=str(tmp_path))
    rng = np.random.RandomState(0)
    source = lm_text.batchify(
        rng.randint(0, model_cfg.vocab, size=4096).astype(np.int32), 8)
    trainer = Trainer(model_cfg, cfg, devices=jax.devices()[:2])
    state, metrics = trainer.train_epoch(source, max_steps=3, log_every=2)
    trainer.events.close()

    path = tmp_path / "events.jsonl"
    assert path.exists()
    records = ev.EventLog.read(str(path))
    kinds = [r["kind"] for r in records]
    assert kinds[0] == "log_open"
    assert kinds.count(ev.STEP) == 3
    reports = [r for r in records if r["kind"] == "step_report"]
    assert len(reports) == 3
    for r in reports:
        assert r["analytic_bubble"] == pytest.approx(
            round(bubble_fraction(cfg.chunks, cfg.n_stages), 4))
        assert r["tokens"] == cfg.batch_size * cfg.bptt
        assert r["unit"] == "tokens/s/chip"
        assert r["mfu"] is not None and 0 <= r["mfu"] <= 1
    assert reports[0]["compile_inclusive"] is True
    assert reports[-1]["compile_inclusive"] is False
    # the same run feeds the process registry + a final snapshot record
    assert registry.counter("train.steps").value == 3
    snaps = [r for r in records if r["kind"] == "metrics"]
    assert snaps and snaps[-1]["metrics"]["train.steps"] == 3
