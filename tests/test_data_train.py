"""Data pipeline + trainer + checkpoint/resume tests.

Data parity targets: the reference driver's tokenize/vocab/batchify/get_batch
semantics (``main.py:76-113``). Trainer: loss decreases on the synthetic
corpus; checkpoint save → restore resumes bit-identically.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.data import lm_text
from pipe_tpu.models.transformer_lm import LMConfig
from pipe_tpu.train.loop import Trainer, TrainerConfig
from pipe_tpu.train.state import restore_checkpoint, save_checkpoint


# --- data ---

def test_basic_english_tokenize():
    toks = lm_text.basic_english_tokenize("Hello, World! (it's nice; very)")
    assert toks == ["hello", ",", "world", "!", "(", "it", "'", "s",
                    "nice", "very", ")"]


def test_vocab_unk_default():
    v = lm_text.Vocab([["a", "b", "a"]])
    assert v["a"] != v["b"]
    assert v["zzz"] == v[lm_text.Vocab.UNK] == 0
    assert v(["a", "zzz"]) == [v["a"], 0]


def test_data_process_drops_empty_lines():
    v = lm_text.Vocab([["a", "b"]])
    ids = lm_text.data_process(["a b", "", "   ", "b"], v)
    assert ids.tolist() == [v["a"], v["b"], v["b"]]


def test_batchify_shape_and_trim():
    data = np.arange(26, dtype=np.int32)
    out = lm_text.batchify(data, 4)  # 26 -> 24 -> [6, 4]
    assert out.shape == (6, 4)
    # lane k holds tokens [k*6, (k+1)*6): contiguous text per column
    np.testing.assert_array_equal(out[:, 0], np.arange(6))
    np.testing.assert_array_equal(out[:, 1], np.arange(6, 12))


def test_get_batch_batch_first_and_shifted():
    src = lm_text.batchify(np.arange(40, dtype=np.int32), 4)  # [10, 4]
    data, target = lm_text.get_batch(src, 0, bptt=5)
    assert data.shape == (4, 5) and target.shape == (4, 5)
    # target is the next token of data within each lane
    np.testing.assert_array_equal(target[:, :-1], data[:, 1:])


def test_synthetic_corpus_deterministic():
    a = lm_text.synthetic_corpus(5000, 100, seed=7)
    b = lm_text.synthetic_corpus(5000, 100, seed=7)
    assert a == b and len(a) > 100


# --- trainer ---

def tiny_trainer(tmp_seed=0, **cfg_kw):
    model_cfg = dataclasses.replace(LMConfig().tiny(), n_layers=2)
    cfg = TrainerConfig(batch_size=8, eval_batch_size=8,
                        bptt=model_cfg.seq_len, chunks=2, n_stages=2,
                        n_data=1, lr=1e-2, **cfg_kw)
    return Trainer(model_cfg, cfg), model_cfg, cfg


@pytest.fixture(scope="module")
def corpus():
    lines = lm_text.synthetic_corpus(30_000, 99, seed=3)
    vocab = lm_text.Vocab(map(lm_text.basic_english_tokenize, lines))
    ids = lm_text.data_process(lines, vocab)
    return lm_text.batchify(ids, 8), vocab


def test_train_loss_decreases(corpus):
    source, vocab = corpus
    trainer, model_cfg, cfg = tiny_trainer()
    assert model_cfg.vocab >= len(vocab)
    state, m = trainer.train_epoch(source, state=None, max_steps=12,
                                   log_every=0)
    first_loss = float(trainer.evaluate(source, state, max_steps=2))
    assert m["steps"] == 12
    assert m["loss"] < np.log(model_cfg.vocab)  # under uniform-guess loss
    assert np.isfinite(first_loss)


def test_eval_matches_train_path(corpus):
    source, _ = corpus
    trainer, _, _ = tiny_trainer()
    state = trainer.init_state()
    l = trainer.evaluate(source, state, max_steps=2)
    assert np.isfinite(l) and l > 0


def test_checkpoint_roundtrip(tmp_path, corpus):
    source, _ = corpus
    trainer, _, _ = tiny_trainer()
    state, _ = trainer.train_epoch(source, state=None, max_steps=3,
                                   log_every=0)
    save_checkpoint(str(tmp_path / "ck"), state, int(state.step))

    template = trainer.init_state()
    restored = restore_checkpoint(str(tmp_path / "ck"), template)
    assert int(restored.step) == 3
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # resumed training continues deterministically from the restored state
    s1, _ = trainer.train_epoch(source, epoch=1, state=state, max_steps=2,
                                log_every=0)
    s2, _ = trainer.train_epoch(source, epoch=1, state=restored, max_steps=2,
                                log_every=0)
    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(s2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_steplr_decays_per_epoch(corpus):
    source, _ = corpus
    trainer, _, cfg = tiny_trainer()
    seen = []
    state, _ = trainer.train_epoch(source, epoch=0, state=None, max_steps=1,
                                   log_every=1, log_fn=seen.append)
    state, _ = trainer.train_epoch(source, epoch=3, state=state, max_steps=1,
                                   log_every=1, log_fn=seen.append)
    lr0 = float(seen[0].split("lr ")[1].split(" ")[0])
    lr3 = float(seen[1].split("lr ")[1].split(" ")[0])
    # log prints lr with 3 decimals; compare at that resolution
    assert lr0 == pytest.approx(cfg.lr, abs=5e-4)
    assert lr3 == pytest.approx(cfg.lr * cfg.lr_gamma ** 3, abs=5e-4)


def test_nondivisible_batch_loss_masks_padding(corpus):
    """batch 10, chunks 4 -> stack_scatter pads to 12 rows; the two fake
    rows must not contaminate the loss: trainer loss == plain-model loss
    over the 10 real rows (VERDICT r1 #7)."""
    source, _ = corpus  # batchified with 8 lanes
    wide = lm_text.batchify(np.concatenate([source.T.ravel()] * 2), 10)
    model_cfg = dataclasses.replace(LMConfig().tiny(), n_layers=2)
    cfg4 = TrainerConfig(batch_size=10, eval_batch_size=10,
                         bptt=model_cfg.seq_len, chunks=4, n_stages=2,
                         n_data=1, lr=1e-2)
    trainer4 = Trainer(model_cfg, cfg4)
    state = trainer4.init_state()
    data, target = lm_text.get_batch(wide, 0, cfg4.bptt)
    assert data.shape[0] == 10
    x, w = trainer4._make_x(data, target)
    assert float(jnp.sum(w)) == 10.0
    got = float(trainer4._eval_fn(state.params, x, w))

    # plain (unpipelined, unpadded) reference on the same params
    from pipe_tpu.core.partition import StageCtx
    sp, prep, postp = state.params
    model = trainer4.model
    ctx = StageCtx(key=None, train=False)
    h = model.pre_fn(prep, jnp.asarray(data), ctx)
    for j in range(cfg4.n_stages):
        blocks = jax.tree_util.tree_map(lambda p: p[j], sp)
        h = model.stage_fn(blocks, h, ctx)
    per_row = model.loss_post_fn(postp, h,
                                 {"targets": jnp.asarray(target)}, ctx)
    expected = float(jnp.mean(per_row))
    assert got == pytest.approx(expected, rel=1e-5)


def test_1f1b_trainer(corpus):
    """Trainer with the true-1F1B scheduled executor trains, evals, and its
    first-step loss matches the gpipe (AD) trainer bitwise (same key scheme)."""
    source, _ = corpus
    trainer, model_cfg, _ = tiny_trainer(schedule="1f1b")
    assert trainer.pipe.memory_plan(2)["stash_slots"] == 2
    state, m = trainer.train_epoch(source, max_steps=8, log_every=0)
    assert m["loss"] < np.log(model_cfg.vocab)
    assert np.isfinite(trainer.evaluate(source, state, max_steps=2))

    t_gpipe, _, _ = tiny_trainer(schedule="gpipe")
    s0 = trainer.init_state()
    s0g = t_gpipe.init_state()
    _, l_1f1b = trainer.train_epoch(source, state=s0, max_steps=1, log_every=0)
    _, l_gpipe = t_gpipe.train_epoch(source, state=s0g, max_steps=1,
                                     log_every=0)
    assert l_1f1b["loss"] == l_gpipe["loss"]


def test_zb_h1_trainer(corpus):
    """Trainer with the zero-bubble split-backward schedule trains, evals,
    and its first-step loss matches the 1f1b trainer bitwise (same math,
    same key scheme — only the op order differs)."""
    source, _ = corpus
    with pytest.warns(UserWarning, match="checkpoint='never'"):
        # default checkpoint is a recompute mode: construction warns that
        # the W slots carry no compute, and no cotangent park is allocated
        # (the full backward runs at B).
        trainer, model_cfg, _ = tiny_trainer(schedule="zb-h1")
    plan = trainer.pipe.memory_plan(2)
    assert plan["wstash_slots"] == 0
    t_never, _, _ = tiny_trainer(schedule="zb-h1", checkpoint="never")
    # stored residuals: the designed pairing — B/W split is real, the
    # deferred-W cotangent park exists.
    assert t_never.pipe.memory_plan(2)["wstash_slots"] >= 1
    state, m = trainer.train_epoch(source, max_steps=8, log_every=0)
    assert m["loss"] < np.log(model_cfg.vocab)
    assert np.isfinite(trainer.evaluate(source, state, max_steps=2))

    t_1f1b, _, _ = tiny_trainer(schedule="1f1b")
    s0 = trainer.init_state()
    s0b = t_1f1b.init_state()
    _, l_zb = trainer.train_epoch(source, state=s0, max_steps=1, log_every=0)
    _, l_1f1b = t_1f1b.train_epoch(source, state=s0b, max_steps=1,
                                   log_every=0)
    assert l_zb["loss"] == l_1f1b["loss"]


def test_interleaved_trainer(corpus):
    """Trainer with the interleaved schedule trains and resumes."""
    source, _ = corpus
    model_cfg = dataclasses.replace(LMConfig().tiny(), n_layers=4)
    cfg = TrainerConfig(batch_size=8, eval_batch_size=8,
                        bptt=model_cfg.seq_len, chunks=2, n_stages=2,
                        n_data=1, lr=1e-2, schedule="interleaved",
                        interleave=2)
    trainer = Trainer(model_cfg, cfg)
    assert trainer.n_virtual == 4
    assert trainer.analytic_bubble() < 1 / 3  # better than gpipe's (n-1)/(m+n-1)
    state, m = trainer.train_epoch(source, max_steps=8, log_every=0)
    assert m["loss"] < np.log(model_cfg.vocab)
    l_eval = trainer.evaluate(source, state, max_steps=2)
    assert np.isfinite(l_eval)


def test_interleaved_1f1b_eval_covers_all_virtual_stages():
    """Regression: the eval executor must match the interleaved param layout
    — a plain SpmdPipeline over the device-major [v, ...] shard would
    silently evaluate only interleave group 0's layers."""
    import dataclasses as dc

    from pipe_tpu.core.partition import StageCtx

    cfg = dc.replace(LMConfig().tiny(), n_layers=4, dropout=0.0)
    tc = TrainerConfig(batch_size=8, bptt=cfg.seq_len, chunks=4,
                       checkpoint="except_last", n_stages=2, n_data=1,
                       lr=1e-2, schedule="interleaved-1f1b", interleave=2)
    tr = Trainer(cfg, tc, devices=jax.devices()[:2])
    state = tr.init_state()
    rng = np.random.RandomState(0)
    corpus = rng.randint(0, cfg.vocab, size=4000)
    source = lm_text.batchify(corpus, tc.batch_size)
    got = tr.evaluate(source, state, max_steps=1)

    # serial oracle over ALL v*d virtual stages
    sp, prep, postp = tr.model.init(jax.random.key(tc.seed))
    data, target = lm_text.get_batch(source, 0, tc.bptt)
    h = tr.model.pre_fn(prep, {"tokens": jnp.asarray(data)}, StageCtx())
    for blocks in sp:
        h = tr.model.stage_fn(blocks, h, StageCtx())
    per_row = tr.model.loss_post_fn(
        postp, h, {"targets": jnp.asarray(target)}, StageCtx())
    np.testing.assert_allclose(got, float(jnp.mean(per_row)),
                               rtol=1e-5, atol=1e-6)


def test_autosave_on_stop_signal(tmp_path):
    """install_autosave: the stop flag ends the epoch after the in-flight
    step and a restorable checkpoint exists (the preemption flow)."""
    import os
    import signal

    from pipe_tpu.train.state import latest_step, restore_checkpoint

    model = LMConfig().tiny()
    cfg = TrainerConfig(batch_size=8, bptt=16, chunks=2, n_stages=2,
                        lr=0.05, schedule="gpipe", checkpoint="never")
    ids = np.random.default_rng(17).integers(
        0, model.vocab, size=4096).astype(np.int32)
    src = lm_text.batchify(ids, cfg.batch_size)
    tr = Trainer(model, cfg)
    ckpt = str(tmp_path / "auto")
    tr.install_autosave(ckpt, signals=[signal.SIGUSR1])
    state = tr.init_state()

    lines = []
    fired = {"done": False}
    orig_step = tr._step_fn

    def step_and_signal(*a, **kw):
        out = orig_step(*a, **kw)
        if not fired["done"]:
            fired["done"] = True
            os.kill(os.getpid(), signal.SIGUSR1)  # preemption mid-epoch
        return out

    tr._step_fn = step_and_signal
    state, stats = tr.train_epoch(src, state=state, max_steps=6,
                                  log_every=0, log_fn=lines.append)
    assert stats["steps"] == 1  # stopped right after the in-flight step
    assert any("autosave" in l for l in lines)
    step = latest_step(ckpt)
    assert step == 1
    restored = restore_checkpoint(ckpt, tr.init_state())
    assert int(restored.step) == 1


def test_trainer_generate_from_state():
    """Trainer.generate: stacked train-state params unstack straight into
    the KV-cached generator; greedy output matches a hand-built Generator
    over the same weights."""
    import jax
    import jax.numpy as jnp

    from pipe_tpu.inference import GenerationConfig, Generator
    from pipe_tpu.parallel.spmd import unstack_stage_params

    model = LMConfig().tiny()
    cfg = TrainerConfig(batch_size=8, bptt=16, chunks=2, n_stages=2,
                        lr=0.05, schedule="1f1b", checkpoint="never")
    ids = np.random.default_rng(23).integers(
        0, model.vocab, size=2048).astype(np.int32)
    src = lm_text.batchify(ids, cfg.batch_size)
    tr = Trainer(model, cfg)
    state, _ = tr.train_epoch(src, state=tr.init_state(), max_steps=2,
                              log_every=0)
    prompt = jnp.asarray([[3, 4, 5, 6]], jnp.int32)
    out = np.asarray(tr.generate(state, prompt, max_new_tokens=6))
    assert out.shape == (1, 6)
    assert (out >= 0).all() and (out < model.vocab).all()

    sp = jax.tree_util.tree_map(np.asarray, state.params[0])
    ref = Generator(tr.model, GenerationConfig(max_new_tokens=6,
                                               temperature=0.0)).generate(
        (unstack_stage_params(sp, 2),
         jax.tree_util.tree_map(np.asarray, state.params[1]),
         jax.tree_util.tree_map(np.asarray, state.params[2])), prompt)
    np.testing.assert_array_equal(out, np.asarray(ref))


def test_checkpoint_roundtrip_bf16_moments(tmp_path, corpus):
    """mu_dtype='bfloat16': save -> restore round-trips the bf16 moment
    leaves (the template's dtypes match what training produced) and
    resumed training continues deterministically."""
    source, _ = corpus
    trainer, _, _ = tiny_trainer(mu_dtype="bfloat16")
    state, _ = trainer.train_epoch(source, state=None, max_steps=3,
                                   log_every=0)
    import jax.numpy as jnp
    assert any(l.dtype == jnp.bfloat16
               for l in jax.tree_util.tree_leaves(state.opt_state)
               if hasattr(l, "dtype"))
    save_checkpoint(str(tmp_path / "ckb"), state, int(state.step))
    restored = restore_checkpoint(str(tmp_path / "ckb"),
                                  trainer.init_state())
    for a, b in zip(jax.tree_util.tree_leaves(state.opt_state),
                    jax.tree_util.tree_leaves(restored.opt_state)):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    s1, _ = trainer.train_epoch(source, epoch=1, state=state, max_steps=2,
                                log_every=0)
    s2, _ = trainer.train_epoch(source, epoch=1, state=restored,
                                max_steps=2, log_every=0)
    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(s2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
