"""Model-zoo tests: the BASELINE.json config families, pipelined.

Each family asserts the core transparency property (pipelined == plain
Sequential) plus the composition its BASELINE config names:

* GPT-2 (#3): 4 stages, @skippable embedding shortcut, through BOTH the
  emulator and Pipe(mesh=);
* BERT (#4): MLM masking + loss semantics, 4-device x v=2 interleaved
  executor (the 8-virtual-stage shape);
* ViT (#5): image inputs, odd token count, uneven balance through
  Pipe(mesh=), scalar-per-row loss.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu import Pipe
from pipe_tpu.core import microbatch as mb
from pipe_tpu.core.partition import StageCtx
from pipe_tpu.models.bert import BertConfig, PipelinedBERT, mask_tokens
from pipe_tpu.models.bert import build_sequential as build_bert
from pipe_tpu.models.gpt2 import GPT2Config, PipelinedGPT2
from pipe_tpu.models.gpt2 import build_sequential as build_gpt2
from pipe_tpu.models.vit import PipelinedViT, ViTConfig
from pipe_tpu.models.vit import build_sequential as build_vit
from pipe_tpu.parallel.interleaved import (InterleavedSpmdPipeline,
                                           stack_interleaved_params)
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.scheduled import ScheduledPipeline
from pipe_tpu.parallel.spmd import SpmdPipeline, stack_stage_params


def stage_mesh(n_stages):
    return make_mesh(n_stages, 1, devices=jax.devices()[:n_stages])


# ---------------- GPT-2 (BASELINE config #3) ----------------

def test_gpt2_pipelined_matches_sequential():
    cfg = GPT2Config().tiny()
    model = PipelinedGPT2(cfg, n_stages=4)
    sp, prep, postp = model.init(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, cfg.seq_len),
                                0, cfg.vocab, jnp.int32)
    targets = jnp.roll(tokens, -1, axis=-1)

    # plain forward: chain the stage fns serially
    h = model.pre_fn(prep, {"tokens": tokens}, StageCtx())
    for blocks in sp:
        h = model.stage_fn(blocks, h, StageCtx())
    plain = model.loss_post_fn(postp, h, {"targets": targets}, StageCtx())

    spmd = SpmdPipeline(stage_mesh(4), model.stage_fn, pre_fn=model.pre_fn,
                        post_fn=model.loss_post_fn, post_with_batch=True)
    x, _ = mb.stack_scatter({"tokens": tokens, "targets": targets}, 2)
    per_row = spmd(stack_stage_params(sp), prep, postp, x)
    np.testing.assert_allclose(np.asarray(per_row.reshape(-1)),
                               np.asarray(plain), rtol=2e-5, atol=2e-5)


def test_gpt2_embed_skip_through_pipe_and_mesh():
    """Config #3's composition: 4-stage GPT-2 with a @skippable cross-stage
    residual, emulator vs compiled mesh executor."""
    cfg = GPT2Config().tiny()
    seq = build_gpt2(cfg, embed_skip=True)
    # 8 layers: embed+stash | 2 blocks | 2 blocks | join+head
    balance = [2, 2, 2, 2]
    emu = Pipe(seq, chunks=2, checkpoint="never", balance=balance)
    mesh_pipe = Pipe(seq, chunks=2, checkpoint="never",
                     mesh=stage_mesh(4), balance=balance)
    tokens0 = jnp.zeros((2, cfg.seq_len), jnp.int32)
    sp = mesh_pipe.init(jax.random.key(0), tokens0)
    tokens = jax.random.randint(jax.random.key(1), (4, cfg.seq_len),
                                0, cfg.vocab, jnp.int32)
    np.testing.assert_allclose(np.asarray(mesh_pipe(sp, tokens)),
                               np.asarray(emu(sp, tokens)),
                               rtol=2e-5, atol=2e-5)


def test_gpt2_trains_through_scheduled_1f1b():
    cfg = dataclasses.replace(GPT2Config().tiny(), dropout=0.1)
    model = PipelinedGPT2(cfg, n_stages=2)
    sp, prep, postp = model.init(jax.random.key(0))
    sched = ScheduledPipeline(stage_mesh(2), model.stage_fn,
                              pre_fn=model.pre_fn,
                              post_fn=model.loss_post_fn,
                              checkpoint="except_last", schedule="1f1b")
    tokens = jax.random.randint(jax.random.key(1), (8, cfg.seq_len),
                                0, cfg.vocab, jnp.int32)
    x, _ = mb.stack_scatter({"tokens": tokens,
                             "targets": jnp.roll(tokens, -1, -1)}, 4)
    w = jnp.ones(x["tokens"].shape[:2], jnp.float32)
    stacked = stack_stage_params(sp)

    @jax.jit
    def step(stacked, prep, postp):
        loss, grads = sched.loss_and_grad(stacked, prep, postp, x, w,
                                          key=jax.random.key(2))
        return loss, grads

    loss, (g_sp, g_pre, g_post) = step(stacked, prep, postp)
    assert np.isfinite(float(loss))
    gnorm = sum(float(jnp.sum(jnp.abs(g)))
                for g in jax.tree_util.tree_leaves((g_sp, g_pre, g_post)))
    assert gnorm > 0.0


# ---------------- BERT (BASELINE config #4) ----------------

def test_mask_tokens_statistics():
    cfg = BertConfig().tiny()
    tokens = jax.random.randint(jax.random.key(0), (64, cfg.seq_len),
                                2, cfg.vocab, jnp.int32)
    masked, weights = mask_tokens(jax.random.key(1), tokens, cfg)
    rate = float(jnp.mean(weights))
    assert 0.10 < rate < 0.20              # ~15% selected
    # corrupted positions are a subset of selected positions
    changed = (masked != tokens)
    assert bool(jnp.all(weights[changed] == 1.0))
    # roughly 80% of selected became [MASK]
    sel = weights == 1.0
    frac_mask = float(jnp.sum((masked == cfg.mask_token_id) & sel)
                      / jnp.sum(sel))
    assert 0.6 < frac_mask < 0.95


def test_bert_mlm_loss_only_counts_masked_positions():
    cfg = BertConfig().tiny()
    model = PipelinedBERT(cfg, n_virtual=4)
    sp, prep, postp = model.init(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, cfg.seq_len),
                                0, cfg.vocab, jnp.int32)
    h = model.pre_fn(prep, {"tokens": tokens}, StageCtx())
    for blocks in sp:
        h = model.stage_fn(blocks, h, StageCtx())
    w1 = jnp.zeros((2, cfg.seq_len)).at[:, 0].set(1.0)
    l1 = model.loss_post_fn(postp, h, {"targets": tokens,
                                       "mlm_weights": w1}, StageCtx())
    # changing an unmasked target must not change the loss
    t2 = tokens.at[:, 5].set((tokens[:, 5] + 1) % cfg.vocab)
    l2 = model.loss_post_fn(postp, h, {"targets": t2,
                                       "mlm_weights": w1}, StageCtx())
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    # changing the masked target must
    t3 = tokens.at[:, 0].set((tokens[:, 0] + 1) % cfg.vocab)
    l3 = model.loss_post_fn(postp, h, {"targets": t3,
                                       "mlm_weights": w1}, StageCtx())
    assert not np.allclose(np.asarray(l1), np.asarray(l3))


def test_bert_interleaved_matches_plain():
    """The 8-virtual-stage interleaved shape (4 devices x v=2)."""
    cfg = dataclasses.replace(BertConfig().tiny(), n_layers=8)
    model = PipelinedBERT(cfg, n_virtual=8)
    sp, prep, postp = model.init(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, cfg.seq_len),
                                0, cfg.vocab, jnp.int32)
    masked, weights = mask_tokens(jax.random.key(2), tokens, cfg)

    h = model.pre_fn(prep, {"tokens": masked}, StageCtx())
    for blocks in sp:
        h = model.stage_fn(blocks, h, StageCtx())
    plain = model.loss_post_fn(
        postp, h, {"targets": tokens, "mlm_weights": weights}, StageCtx())

    ipipe = InterleavedSpmdPipeline(
        stage_mesh(4), model.stage_fn, v=2, pre_fn=model.pre_fn,
        post_fn=model.loss_post_fn, post_with_batch=True)
    x, _ = mb.stack_scatter({"tokens": masked, "targets": tokens,
                             "mlm_weights": weights}, 4)
    per_row = ipipe(stack_interleaved_params(sp, 4), prep, postp, x)
    np.testing.assert_allclose(np.asarray(per_row.reshape(-1)),
                               np.asarray(plain), rtol=2e-5, atol=2e-5)


# ---------------- ViT (BASELINE config #5) ----------------

def test_vit_pipelined_matches_sequential():
    cfg = ViTConfig().tiny()
    model = PipelinedViT(cfg, n_stages=4)
    sp, prep, postp = model.init(jax.random.key(0))
    images = jax.random.normal(
        jax.random.key(1),
        (4, cfg.image_size, cfg.image_size, cfg.channels))
    labels = jax.random.randint(jax.random.key(2), (4,), 0, cfg.n_classes)

    h = model.pre_fn(prep, {"images": images}, StageCtx())
    for blocks in sp:
        h = model.stage_fn(blocks, h, StageCtx())
    plain = model.loss_post_fn(postp, h, {"labels": labels}, StageCtx())

    spmd = SpmdPipeline(stage_mesh(4), model.stage_fn, pre_fn=model.pre_fn,
                        post_fn=model.loss_post_fn, post_with_batch=True)
    x, _ = mb.stack_scatter({"images": images, "labels": labels}, 2)
    per_row = spmd(stack_stage_params(sp), prep, postp, x)
    np.testing.assert_allclose(np.asarray(per_row.reshape(-1)),
                               np.asarray(plain), rtol=2e-5, atol=2e-5)
    # odd token count (n_patches + 1) rules out the flash tiling
    assert cfg.n_tokens % 2 == 1


def test_vit_uneven_balance_through_pipe_mesh():
    """Config #5's composition: uneven stage balance, image shapes."""
    cfg = ViTConfig().tiny()
    seq = build_vit(cfg)                    # 6 layers: embed, 4 blocks, head
    balance = [1, 3, 2]
    emu = Pipe(seq, chunks=2, checkpoint="except_last", balance=balance)
    mesh_pipe = Pipe(seq, chunks=2, checkpoint="except_last",
                     mesh=stage_mesh(3), balance=balance)
    img0 = jnp.zeros((2, cfg.image_size, cfg.image_size, cfg.channels))
    sp = mesh_pipe.init(jax.random.key(0), img0)
    images = jax.random.normal(
        jax.random.key(1),
        (4, cfg.image_size, cfg.image_size, cfg.channels))
    np.testing.assert_allclose(np.asarray(mesh_pipe(sp, images)),
                               np.asarray(emu(sp, images)),
                               rtol=2e-5, atol=2e-5)


def test_vit_gradients_flow():
    cfg = ViTConfig().tiny()
    model = PipelinedViT(cfg, n_stages=2)
    sp, prep, postp = model.init(jax.random.key(0))
    spmd = SpmdPipeline(stage_mesh(2), model.stage_fn, pre_fn=model.pre_fn,
                        post_fn=model.loss_post_fn, post_with_batch=True,
                        checkpoint="except_last")
    images = jax.random.normal(
        jax.random.key(1),
        (4, cfg.image_size, cfg.image_size, cfg.channels))
    labels = jax.random.randint(jax.random.key(2), (4,), 0, cfg.n_classes)
    x, _ = mb.stack_scatter({"images": images, "labels": labels}, 2)
    stacked = stack_stage_params(sp)

    def loss(stacked, prep, postp):
        return jnp.mean(spmd(stacked, prep, postp, x,
                             key=jax.random.key(3), train=True))

    g = jax.grad(loss, argnums=(0, 1, 2))(stacked, prep, postp)
    leaves = jax.tree_util.tree_leaves(g)
    assert all(np.isfinite(np.asarray(l)).all() for l in leaves)
    assert sum(float(jnp.sum(jnp.abs(l))) for l in leaves) > 0.0


def test_bert_through_interleaved_1f1b():
    """BASELINE config #4's exact pairing: BERT MLM pretraining under the
    interleaved 1F1B schedule (manual executor, both passes from one
    table) — loss matches the plain chain."""
    from pipe_tpu.core.schedule import InterleavedOneFOneBSchedule

    cfg = dataclasses.replace(BertConfig().tiny(), n_layers=8)
    model = PipelinedBERT(cfg, n_virtual=8)          # 4 devices x v=2
    sp, prep, postp = model.init(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (8, cfg.seq_len),
                                2, cfg.vocab, jnp.int32)
    masked, weights = mask_tokens(jax.random.key(2), tokens, cfg)

    from pipe_tpu.core.partition import StageCtx
    h = model.pre_fn(prep, {"tokens": masked}, StageCtx())
    for blocks in sp:
        h = model.stage_fn(blocks, h, StageCtx())
    plain_rows = model.loss_post_fn(
        postp, h, {"targets": tokens, "mlm_weights": weights}, StageCtx())
    # the executor's loss divides by sum(w) over ROWS (w = per-row weight 1)
    plain = float(jnp.mean(plain_rows))

    sched = ScheduledPipeline(
        stage_mesh(4), model.stage_fn, pre_fn=model.pre_fn,
        post_fn=model.loss_post_fn, checkpoint="except_last",
        schedule=InterleavedOneFOneBSchedule(interleave=2))
    x, _ = mb.stack_scatter({"tokens": masked, "targets": tokens,
                             "mlm_weights": weights}, 4)
    w = jnp.ones(x["tokens"].shape[:2], jnp.float32)
    stacked = stack_interleaved_params(sp, 4)
    loss, grads = jax.jit(
        lambda a, b, c: sched.loss_and_grad(a, b, c, x, w))(
        stacked, prep, postp)
    np.testing.assert_allclose(float(loss), plain, rtol=1e-5)
    gnorm = sum(float(jnp.sum(jnp.abs(g)))
                for g in jax.tree_util.tree_leaves(grads))
    assert np.isfinite(gnorm) and gnorm > 0.0
