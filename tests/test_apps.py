"""Driver CLIs run end-to-end on the virtual CPU mesh (the reference's
runnable-tutorial-as-integration-test pattern, SURVEY §4)."""

import pytest

from pipe_tpu.apps import lm_tutorial, zoo


def test_lm_tutorial_tiny(capsys):
    rc = lm_tutorial.main(["except_last", "--tiny", "--steps", "3",
                           "--schedule", "1f1b"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "loss" in out and "val loss" in out


@pytest.mark.parametrize("family,schedule", [
    ("gpt2", "1f1b"),
    ("bert", "interleaved-1f1b"),
    ("vit", "gpipe"),
])
def test_zoo_families(family, schedule, capsys):
    rc = zoo.main([family, "--tiny", "--steps", "2",
                   "--schedule", schedule])
    assert rc == 0
    out = capsys.readouterr().out
    assert "final loss" in out


def test_generate_cli_single_and_pipelined(capsys):
    from pipe_tpu.apps import generate

    rc = generate.main(["--tiny", "--max-new", "5", "--prompt", "3,4,5"])
    assert rc == 0
    single = capsys.readouterr().out.strip().splitlines()
    assert len(single) == 1 and len(single[0].split(",")) == 5

    rc = generate.main(["--tiny", "--stages", "2", "--max-new", "5",
                        "--prompt", "3,4,5"])
    assert rc == 0
    piped = capsys.readouterr().out.strip().splitlines()
    assert len(piped) == 2
    # greedy: pipelined rows match the single-device row token-for-token
    assert piped[0] == piped[1] == single[0]


def test_generate_cli_rejects_bad_prompt(capsys):
    from pipe_tpu.apps import generate

    assert generate.main(["--tiny", "--prompt", "999999"]) == 2


def test_generate_cli_resume_roundtrip(tmp_path, capsys):
    """Train -> save -> serve the checkpoint at a DIFFERENT stage count;
    restored weights (not fresh init) must drive the sample."""
    import numpy as np

    from pipe_tpu.apps import generate
    from pipe_tpu.data import lm_text
    from pipe_tpu.models.transformer_lm import LMConfig
    from pipe_tpu.train.loop import Trainer, TrainerConfig
    from pipe_tpu.train.state import save_checkpoint

    model = LMConfig().tiny()
    cfg = TrainerConfig(batch_size=8, bptt=16, chunks=2, n_stages=2,
                        lr=0.05, schedule="gpipe", checkpoint="never")
    ids = np.random.default_rng(11).integers(
        0, model.vocab, size=2048).astype(np.int32)
    src = lm_text.batchify(ids, cfg.batch_size)
    tr = Trainer(model, cfg)
    state, _ = tr.train_epoch(src, state=tr.init_state(), max_steps=2,
                              log_every=0)
    ckpt = str(tmp_path / "ck")
    save_checkpoint(ckpt, state, 1)

    args = ["--tiny", "--max-new", "6", "--prompt", "3,4,5"]
    assert generate.main(args + ["--resume", ckpt]) == 0
    restored = capsys.readouterr().out.strip().splitlines()
    # 2-stage checkpoint served pipelined on 4 stages: same tokens
    assert generate.main(args + ["--resume", ckpt, "--stages", "4"]) == 0
    re4 = capsys.readouterr().out.strip().splitlines()
    assert len(re4) == 4 and all(r == restored[0] for r in re4)
    # fresh init differs (proves the restore took)
    assert generate.main(args) == 0
    fresh = capsys.readouterr().out.strip().splitlines()
    assert fresh[0] != restored[0]


def test_generate_cli_resume_interleaved_layout(tmp_path, capsys):
    """Interleaved training stacks virtual stages device-major-permuted;
    the layout record must make serving reconstruct the TRUE layer order
    (without it, layers [0,2,1,3] would silently serve as [0,1,2,3])."""
    import jax
    import numpy as np

    from pipe_tpu.apps import generate
    from pipe_tpu.data import lm_text
    from pipe_tpu.inference import GenerationConfig, Generator
    from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
    from pipe_tpu.train.loop import Trainer, TrainerConfig

    model = LMConfig().tiny()  # 4 layers = 2 stages x interleave 2
    cfg = TrainerConfig(batch_size=8, bptt=16, chunks=2, n_stages=2,
                        interleave=2, lr=0.05, schedule="interleaved-1f1b",
                        checkpoint="never")
    ids = np.random.default_rng(13).integers(
        0, model.vocab, size=2048).astype(np.int32)
    src = lm_text.batchify(ids, cfg.batch_size)
    tr = Trainer(model, cfg)
    state, _ = tr.train_epoch(src, state=tr.init_state(), max_steps=1,
                              log_every=0)
    ckpt = str(tmp_path / "ck")
    tr.save(ckpt, state)

    assert generate.main(["--tiny", "--resume", ckpt, "--max-new", "6",
                          "--prompt", "3,4,5"]) == 0
    served = capsys.readouterr().out.strip().splitlines()[0]

    # ground truth: un-permute the trained stacked params by hand and run
    # the single-device generator over them in true layer order
    ssp = jax.tree_util.tree_map(np.asarray, state.params[0])
    d, v = 2, 2
    flat = []
    for vs in range(4):
        row = (vs % d) * v + vs // d
        flat.append(jax.tree_util.tree_map(lambda a: a[row], ssp[0]))
    m1 = PipelinedLM(model, 1)
    pre = jax.tree_util.tree_map(np.asarray, state.params[1])
    post = jax.tree_util.tree_map(np.asarray, state.params[2])
    ref = Generator(m1, GenerationConfig(max_new_tokens=6,
                                         temperature=0.0)).generate(
        ([flat], pre, post), np.asarray([[3, 4, 5]], dtype=np.int32))
    ref_row = ",".join(str(int(t)) for t in np.asarray(ref)[0])
    assert served == ref_row


def test_generator_position_table_guard():
    import jax.numpy as jnp
    import pytest as pt

    from pipe_tpu.inference import GenerationConfig, Generator
    from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM

    model = PipelinedLM(LMConfig().tiny(), 1)
    params = None  # never reached
    g = Generator(model, GenerationConfig(max_new_tokens=10_000))
    with pt.raises(ValueError, match="positional table"):
        g.generate(params, jnp.zeros((1, 4), jnp.int32))


def test_generate_cli_gpt2_family(capsys):
    from pipe_tpu.apps import generate

    args = ["--tiny", "--family", "gpt2", "--max-new", "5",
            "--prompt", "3,4,5"]
    assert generate.main(args) == 0
    single = capsys.readouterr().out.strip().splitlines()
    assert len(single) == 1 and len(single[0].split(",")) == 5
    assert generate.main(args + ["--stages", "2"]) == 0
    piped = capsys.readouterr().out.strip().splitlines()
    assert piped == [single[0], single[0]]


def test_generate_cli_context_shards(capsys):
    from pipe_tpu.apps import generate

    base = ["--tiny", "--max-new", "5", "--prompt", "3,4,5,6,1,2,3,4"]
    assert generate.main(base) == 0
    single = capsys.readouterr().out.strip().splitlines()
    assert generate.main(base + ["--context-shards", "4"]) == 0
    ctx = capsys.readouterr().out.strip().splitlines()
    assert ctx == single  # sharded prompt cache, same tokens
    # indivisible prompt rejected cleanly
    assert generate.main(["--tiny", "--prompt", "1,2,3",
                          "--context-shards", "4"]) == 2
