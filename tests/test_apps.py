"""Driver CLIs run end-to-end on the virtual CPU mesh (the reference's
runnable-tutorial-as-integration-test pattern, SURVEY §4)."""

import pytest

from pipe_tpu.apps import lm_tutorial, zoo


def test_lm_tutorial_tiny(capsys):
    rc = lm_tutorial.main(["except_last", "--tiny", "--steps", "3",
                           "--schedule", "1f1b"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "loss" in out and "val loss" in out


@pytest.mark.parametrize("family,schedule", [
    ("gpt2", "1f1b"),
    ("bert", "interleaved-1f1b"),
    ("vit", "gpipe"),
])
def test_zoo_families(family, schedule, capsys):
    rc = zoo.main([family, "--tiny", "--steps", "2",
                   "--schedule", schedule])
    assert rc == 0
    out = capsys.readouterr().out
    assert "final loss" in out
