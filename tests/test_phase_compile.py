"""Phase-compiled executor tests: switch-free steady state, bitwise parity.

The phase compiler (``core.schedule.compile_phases``) re-times an op table
into warmup/steady/cooldown phases; ``ScheduledPipeline`` then lowers the
ramps as straight-line unrolled stage calls and the steady state as a
fixed-body ``lax.scan`` with NO per-cycle ``lax.switch`` over op codes.
The contract under test:

* the phased program computes the SAME bits as the interpreted table
  executor — loss and every grad leaf ``assert_array_equal`` across
  schedules (gpipe / 1f1b / interleaved-1f1b / zb-h1), checkpoint modes,
  skip lanes, and PP x DP meshes;
* the one documented exception: ``remat_policy`` configs, where XLA fuses
  the policy-remat backward differently inlined vs inside a switch branch
  (loss stays bitwise; grads agree to a few ulp — pinned tight, not
  merely allclose);
* rejected tables fall back LOUDLY: ``phase_compile=True`` on a table the
  compiler cannot phase warns with the reason and bumps the
  ``scheduled.phase.rejected`` counter, and the interpreted fallback still
  trains;
* the uniform-partition front-door probe (satellite of the same contract:
  no silent degradation) warns naming the exception when its trace fails,
  and the switch fallback still trains.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core import microbatch as mb
from pipe_tpu.core.schedule import (InterleavedOneFOneBSchedule,
                                    compile_phases, get_schedule)
from pipe_tpu.obs.telemetry import MetricsRegistry, set_registry
from pipe_tpu.ops.layers import Linear
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.scheduled import ScheduledPipeline
from pipe_tpu.parallel.spmd import stack_stage_params

WIDTH = 8


@pytest.fixture
def registry():
    reg = MetricsRegistry(enabled=True)
    prev = set_registry(reg)
    yield reg
    set_registry(prev)


def make_stage(n_stages, key):
    layer = Linear(WIDTH)
    params = [layer.init(jax.random.fold_in(key, j), jnp.zeros((1, WIDTH)))
              for j in range(n_stages)]

    def stage_fn(p, h, ctx):
        return jnp.tanh(layer.apply(p, h))

    return stage_fn, params


def pre_fn(p, x, ctx):
    return x


def post_fn(p, h, x_mb, ctx):
    return jnp.sum((h - 1.0) ** 2, axis=-1)


def run_pair(mesh, stage_fn, stacked, xs, w, *, schedule, checkpoint,
             m, key=None, remat_policy=None, skip_lanes=None,
             expect_scan=True):
    """One (phased, interpreted) loss/grad pair on identical inputs.

    Asserts the phased pipeline really did take the phase-compiled
    lowering (an accepted program with, when ``expect_scan``, a non-empty
    steady-state scan) — so a quiet fallback can never masquerade as
    parity.
    """
    out = []
    for phase in (True, False):
        pipe = ScheduledPipeline(
            mesh, stage_fn, pre_fn=pre_fn, post_fn=post_fn,
            checkpoint=checkpoint, schedule=schedule,
            remat_policy=remat_policy, skip_lanes=skip_lanes,
            phase_compile=phase)
        if phase:
            prog = pipe._phase_program(m)
            assert prog is not None, "phase compiler rejected the table"
            if expect_scan:
                assert prog.scan_cycles > 0, (
                    "steady state did not lower to a scan")
        loss, grads = jax.jit(pipe.loss_and_grad)(
            stacked, {}, {}, xs, w, key=key)
        out.append((loss, grads))
    return out


def assert_bitwise(pair):
    (l_p, g_p), (l_i, g_i) = pair
    np.testing.assert_array_equal(np.asarray(l_p), np.asarray(l_i))
    for a, b in zip(jax.tree_util.tree_leaves(g_p),
                    jax.tree_util.tree_leaves(g_i)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------- the core parity matrix: schedules x checkpoint modes ----------

@pytest.mark.parametrize("schedule", ["gpipe", "1f1b", "zb-h1"])
@pytest.mark.parametrize("checkpoint", ["never", "except_last", "always"])
def test_phased_bitwise_parity(schedule, checkpoint):
    d, m = 4, 8
    stage_fn, params = make_stage(d, jax.random.key(0))
    mesh = make_mesh(d, 1, devices=jax.devices()[:d])
    x = jax.random.normal(jax.random.key(1), (2 * m, WIDTH))
    xs, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    assert_bitwise(run_pair(
        mesh, stage_fn, stack_stage_params(params), xs, w,
        schedule=schedule, checkpoint=checkpoint, m=m,
        key=jax.random.key(9)))


def test_phased_bitwise_parity_interleaved():
    """Interleaved-1f1b v=2: rigid fb2 hop chains never form a dense
    steady state at v>1, so the accepted program is fully unrolled — the
    parity contract holds for a scan-free phased program too."""
    d, v, m = 2, 2, 4
    stage_fn, params = make_stage(v * d, jax.random.key(0))
    from pipe_tpu.parallel.interleaved import stack_interleaved_params
    mesh = make_mesh(d, 1, devices=jax.devices()[:d])
    x = jax.random.normal(jax.random.key(1), (2 * m, WIDTH))
    xs, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    assert_bitwise(run_pair(
        mesh, stage_fn, stack_interleaved_params(params, d), xs, w,
        schedule=InterleavedOneFOneBSchedule(interleave=v),
        checkpoint="never", m=m, key=jax.random.key(9),
        expect_scan=False))


def test_phased_bitwise_parity_pp_dp():
    """PP x DP (stage axis x data axis): the phased lowering runs inside
    the same shard_map, so the psum'd grads must stay bitwise too."""
    d, n_data, m = 2, 2, 8
    stage_fn, params = make_stage(d, jax.random.key(0))
    mesh = make_mesh(d, n_data)
    x = jax.random.normal(jax.random.key(1), (4 * m, WIDTH))
    xs, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    assert_bitwise(run_pair(
        mesh, stage_fn, stack_stage_params(params), xs, w,
        schedule="1f1b", checkpoint="except_last", m=m,
        key=jax.random.key(9)))


@pytest.mark.parametrize("checkpoint", ["never", "except_last"])
def test_phased_bitwise_parity_skip_lanes(checkpoint):
    """Skip lanes ride the same forward/reverse rings in the phased
    program — a 0 -> 3 skip stays bitwise vs the interpreted executor."""
    from pipe_tpu.parallel.scheduled import SkipLanes
    d, m = 4, 8
    key = jax.random.key(0)
    params = [{"w": jax.random.normal(jax.random.fold_in(key, jj),
                                      (WIDTH, WIDTH)) * 0.3,
               "b": jnp.zeros((WIDTH,))} for jj in range(d)]
    lanes = SkipLanes(pairs=((0, 3),),
                      specs=(jax.ShapeDtypeStruct((2, WIDTH),
                                                  jnp.float32),))

    def stage_fn(p, h, ctx, pops):
        h1 = jnp.tanh(h @ p["w"] + p["b"])
        out = jnp.where(jnp.asarray(ctx.stage == 3), h1 + pops[0], h1)
        sk = jnp.where(jnp.asarray(ctx.stage == 0), h1,
                       jnp.zeros_like(h1))
        return out, (sk,)

    mesh = make_mesh(d, 1, devices=jax.devices()[:d])
    x = jax.random.normal(jax.random.key(1), (2 * m, WIDTH))
    xs, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    assert_bitwise(run_pair(
        mesh, stage_fn, stack_stage_params(params), xs, w,
        schedule="1f1b", checkpoint=checkpoint, m=m,
        skip_lanes=lanes))


def test_phased_policy_mode_ulp_tolerance():
    """The ONE non-bitwise configuration, pinned tight: under
    ``remat_policy`` XLA fuses the policy-remat backward differently when
    the stage body is inlined (phased) vs inside a switch branch
    (interpreted). Loss stays bitwise; grads were measured 2.8e-9 apart
    (a few ulp) — asserted at 1e-8 so a real divergence still fails."""
    d, m = 4, 8
    stage_fn, params = make_stage(d, jax.random.key(0))
    mesh = make_mesh(d, 1, devices=jax.devices()[:d])
    x = jax.random.normal(jax.random.key(1), (2 * m, WIDTH))
    xs, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    (l_p, g_p), (l_i, g_i) = run_pair(
        mesh, stage_fn, stack_stage_params(params), xs, w,
        schedule="1f1b", checkpoint="except_last", m=m,
        key=jax.random.key(9),
        remat_policy=jax.checkpoint_policies.dots_saveable)
    np.testing.assert_array_equal(np.asarray(l_p), np.asarray(l_i))
    for a, b in zip(jax.tree_util.tree_leaves(g_p),
                    jax.tree_util.tree_leaves(g_i)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=1e-8)


# ---------- the compiler itself ----------

def test_compile_phases_verdicts():
    """Direct compiler contract: dense steady state for the uniform
    schedules at (m=8, d=4); d == 1 rejected; segment cycle counts
    partition the full table."""
    m, d = 8, 4
    for name in ("gpipe", "1f1b", "zb-h1"):
        s = get_schedule(name)
        t = s.op_tables(m, d)
        grp = t[2] if len(t) > 2 else None
        v = compile_phases(t[0], t[1], grp, m=m, d=d, v=1)
        assert v.accepted, (name, v.reason)
        prog = v.program
        assert prog.scan_cycles > 0
        assert prog.unrolled_cycles + prog.scan_cycles == prog.cycles
        covered = sum(seg.t1 - seg.t0 for seg in prog.segments)
        assert covered == prog.cycles

    t = get_schedule("1f1b").op_tables(m, 1)
    v1 = compile_phases(t[0], t[1], None, m=m, d=1, v=1)
    assert not v1.accepted and "d == 1" in v1.reason


def test_rejected_table_falls_back_loudly(registry):
    """phase_compile=True on a table the compiler rejects (interleaved
    v=2 at large m never phases — fb2 ramps blow the unroll budget) must
    warn with the compiler's reason, bump scheduled.phase.rejected, and
    the interpreted fallback must still train correctly."""
    from pipe_tpu.parallel.interleaved import stack_interleaved_params
    d, v, m = 2, 2, 16
    stage_fn, params = make_stage(v * d, jax.random.key(0))
    mesh = make_mesh(d, 1, devices=jax.devices()[:d])
    x = jax.random.normal(jax.random.key(1), (2 * m, WIDTH))
    xs, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    stacked = stack_interleaved_params(params, d)

    pipe = ScheduledPipeline(
        mesh, stage_fn, pre_fn=pre_fn, post_fn=post_fn,
        checkpoint="never",
        schedule=InterleavedOneFOneBSchedule(interleave=v),
        phase_compile=True)
    with pytest.warns(UserWarning, match="rejected"):
        loss, grads = jax.jit(pipe.loss_and_grad)(stacked, {}, {}, xs, w)
    assert registry.counter("scheduled.phase.rejected").value >= 1
    assert registry.gauge("scheduled.phase.active").value == 0

    # the fallback is the interpreted executor, bit-for-bit
    ref = ScheduledPipeline(
        mesh, stage_fn, pre_fn=pre_fn, post_fn=post_fn,
        checkpoint="never",
        schedule=InterleavedOneFOneBSchedule(interleave=v),
        phase_compile=False)
    loss_ref, grads_ref = jax.jit(ref.loss_and_grad)(stacked, {}, {}, xs, w)
    assert_bitwise([(loss, grads), (loss_ref, grads_ref)])


def test_accepted_table_counts_and_gauges(registry):
    d, m = 4, 8
    stage_fn, params = make_stage(d, jax.random.key(0))
    mesh = make_mesh(d, 1, devices=jax.devices()[:d])
    x = jax.random.normal(jax.random.key(1), (2 * m, WIDTH))
    xs, _ = mb.stack_scatter(x, m)
    w = jnp.ones(xs.shape[:2], jnp.float32)
    pipe = ScheduledPipeline(mesh, stage_fn, pre_fn=pre_fn, post_fn=post_fn,
                             checkpoint="never", schedule="1f1b",
                             phase_compile=True)
    jax.jit(pipe.loss_and_grad)(stack_stage_params(params), {}, {}, xs, w)
    assert registry.counter("scheduled.phase.compiled").value >= 1
    assert registry.gauge("scheduled.phase.active").value == 1
    assert registry.gauge("scheduled.phase.scan_cycles").value > 0
    plan = pipe.memory_plan(m)
    assert plan["phase_scan_cycles"] == \
        registry.gauge("scheduled.phase.scan_cycles").value
    assert plan["phase_unrolled_cycles"] + plan["phase_scan_cycles"] \
        == pipe._phase_program(m).cycles


def test_auto_mode_off_on_cpu():
    """The tri-state default: phase_compile=None keeps the interpreted
    executor on CPU meshes (the masked ramp cycles are serialized host
    work there), while explicit True forces the phased lowering."""
    d, m = 2, 4
    stage_fn, params = make_stage(d, jax.random.key(0))
    mesh = make_mesh(d, 1, devices=jax.devices()[:d])
    auto = ScheduledPipeline(mesh, stage_fn, pre_fn=pre_fn, post_fn=post_fn,
                             checkpoint="never", schedule="1f1b")
    assert auto._phase_program(m) is None
    forced = ScheduledPipeline(mesh, stage_fn, pre_fn=pre_fn,
                               post_fn=post_fn, checkpoint="never",
                               schedule="1f1b", phase_compile=True)
    assert forced._phase_program(m) is not None


# ---------- front door plumbing + probe-failure loudness ----------

def _mse(out, tgt):
    return jnp.mean((out - tgt) ** 2, axis=-1)


def _front_door(phase, n_stages=2, chunks=4):
    from pipe_tpu import Linear as PLinear
    from pipe_tpu import Pipe, Sequential
    seq = Sequential([PLinear(WIDTH) for _ in range(4)])
    mesh = make_mesh(n_stages, 1, devices=jax.devices()[:n_stages])
    pipe = Pipe(seq, chunks=chunks, checkpoint="never", mesh=mesh,
                schedule="1f1b", phase_compile=phase)
    x = jax.random.normal(jax.random.key(1), (16, WIDTH))
    packed = pipe.shard_params(pipe.init(jax.random.key(0), x))
    return pipe, packed, x


def test_front_door_phase_compile_plumbing():
    """Pipe(mesh=, phase_compile=True) reaches the inner ScheduledPipeline
    and produces the same loss/grads as the interpreted front door.

    Jitted, per the loss_and_grad contract: the phased lowering unrolls
    the ramps, so the un-jitted path re-traces a much larger program
    every call — fine once under jit, pathological eagerly."""
    y = jax.random.normal(jax.random.key(2), (16, WIDTH))
    out = []
    for phase in (True, False):
        pipe, packed, x = _front_door(phase)
        step = jax.jit(lambda p, x, y: pipe.loss_and_grad(
            p, x, targets=y, loss_fn=_mse))
        loss, grads = step(packed, x, y)
        assert pipe._train_executor.uniform_fastpath is True
        out.append((loss, grads))
    (l_p, g_p), (l_i, g_i) = out
    np.testing.assert_array_equal(np.asarray(l_p), np.asarray(l_i))
    for a, b in zip(jax.tree_util.tree_leaves(g_p),
                    jax.tree_util.tree_leaves(g_i)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_uniform_probe_failure_warns_and_trains():
    """A probe trace failure (satellite of VERDICT r5 #3: no silent
    degradation) warns naming the exception and falls back to the switch
    executor, which still trains to the interpreted result."""
    from pipe_tpu.core.packing import StageParamPack
    from pipe_tpu.parallel.hetero_scheduled import HeteroScheduledPipeline
    y = jax.random.normal(jax.random.key(2), (16, WIDTH))

    pipe_ref, packed_ref, x = _front_door(None)
    loss_ref, _ = pipe_ref.loss_and_grad(packed_ref, x, targets=y,
                                         loss_fn=_mse)

    # Inject the failure INSIDE the probe's trace loop only: abstract_tree
    # raises for the duration of _probe_branches_uniform (the probe's
    # try/except owns the warning), and the rest of the lowering — which
    # also calls abstract_tree — stays healthy so the fallback can train.
    orig_probe = HeteroScheduledPipeline._probe_branches_uniform
    orig_at = StageParamPack.abstract_tree

    def boom(self, s):
        raise RuntimeError("injected probe failure")

    def probe_with_broken_trace(self, low, *, train):
        StageParamPack.abstract_tree = boom
        try:
            return orig_probe(self, low, train=train)
        finally:
            StageParamPack.abstract_tree = orig_at

    HeteroScheduledPipeline._probe_branches_uniform = probe_with_broken_trace
    try:
        pipe, packed, x = _front_door(None)
        with pytest.warns(UserWarning,
                          match="RuntimeError: injected probe failure"):
            loss, grads = pipe.loss_and_grad(packed, x, targets=y,
                                             loss_fn=_mse)
    finally:
        HeteroScheduledPipeline._probe_branches_uniform = orig_probe
        StageParamPack.abstract_tree = orig_at
    assert pipe._train_executor.uniform_fastpath is False
    np.testing.assert_allclose(float(loss), float(loss_ref),
                               rtol=1e-5, atol=1e-6)
    assert np.isfinite(jnp.asarray(loss))
    assert all(np.all(np.isfinite(np.asarray(g)))
               for g in jax.tree_util.tree_leaves(grads))
