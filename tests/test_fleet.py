"""pipe_tpu.fleet: transport-split control plane, process replicas,
mesh carving.

Tier-1 runs the fast in-process twins (stub backends, wire codec over
socketpairs, spawn-refusal, topology arithmetic, KV handoff payloads
on a real paged backend). The ``slow`` tier spawns REAL child
interpreters through :class:`ProcessReplicaTransport` and drills the
wire: place/poll across the socket, child-initiated reconnect after a
transport drop (kill the wire, not the replica), SIGKILL failover.
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from pipe_tpu.fleet import (FleetController, FleetSpawnError,
                            InProcessTransport, ProcessReplicaTransport,
                            ReplicaSpec, ReplicaTransport, RouterPolicy,
                            TransportError, carve_replica_meshes,
                            check_spawn_capability, replica_device_plan)
from pipe_tpu.fleet.proc import (_pack, _spawn_env, _unpack, recv_frame,
                                 send_frame)
from pipe_tpu.resilience import TickWatchdog
from pipe_tpu.serve import (HEALTHY, RETIRED, RequestQueue, ServeEngine)
from test_router import FakeBackend

# ---------------------------------------------------------------------------
# wire codec


def test_codec_roundtrips_nested_messages_and_ndarrays():
    msg = {
        "op": "import_prefix",
        "rpc": 7,
        "payload": {
            "codec": "int8",
            "blocks": [
                {"k": np.arange(24, dtype=np.int8).reshape(2, 3, 4),
                 "scale": np.ones((2, 1, 4), np.float32) * 0.5,
                 "hash": 123456789},
            ],
            "prompt": list(range(16)),
        },
    }
    out = _unpack(_pack(msg))
    assert out["op"] == "import_prefix" and out["rpc"] == 7
    blk = out["payload"]["blocks"][0]
    np.testing.assert_array_equal(blk["k"],
                                  msg["payload"]["blocks"][0]["k"])
    assert blk["k"].dtype == np.int8
    np.testing.assert_array_equal(blk["scale"],
                                  msg["payload"]["blocks"][0]["scale"])
    assert blk["scale"].dtype == np.float32
    assert out["payload"]["prompt"] == list(range(16))


def test_framing_survives_split_reads_and_interleaved_senders():
    a, b = socket.socketpair()
    try:
        lock = threading.Lock()
        msgs = [{"op": "hb", "i": i, "v": np.full((3,), i, np.int32)}
                for i in range(5)]
        threads = [threading.Thread(target=send_frame,
                                    args=(a, m, lock)) for m in msgs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        got = sorted((recv_frame(b)["i"] for _ in msgs))
        assert got == [0, 1, 2, 3, 4]
    finally:
        a.close()
        b.close()


def test_recv_frame_returns_none_on_clean_eof():
    a, b = socket.socketpair()
    a.close()
    try:
        assert recv_frame(b) is None
    finally:
        b.close()


def test_frame_length_prefix_is_4_byte_big_endian():
    a, b = socket.socketpair()
    try:
        frame = send_frame(a, {"x": 1})
        (n,) = struct.unpack(">I", frame[:4])
        assert n == len(frame) - 4
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# spawn discipline (runtime/_multiproc_check)


def test_spawn_refusal_names_the_failure_and_the_remedy():
    with pytest.raises(FleetSpawnError) as ei:
        check_spawn_capability("/nonexistent/python3")
    msg = str(ei.value)
    assert msg.startswith("cannot spawn JAX child processes")
    assert "--fleet inproc" in msg          # the remedy is in the error


def test_spawn_refusal_blocks_transport_construction():
    with pytest.raises(FleetSpawnError):
        ProcessReplicaTransport(ReplicaSpec(lm_cfg={}),
                                executable="/nonexistent/python3")


def test_spawn_env_discipline():
    env = _spawn_env(repo_root="/r", jax_platform="cpu")
    assert env["PYTHONPATH"] == "/r"
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "XLA_FLAGS" not in env


def test_spawn_capability_passes_on_this_host():
    check_spawn_capability()                # should not raise here


# ---------------------------------------------------------------------------
# topology: carving the device grid into replica sub-meshes


def test_replica_device_plan_contiguous_and_shaped():
    plan = replica_device_plan(4, 2, n_devices=16)
    assert [(rd.start, rd.stop) for rd in plan] == \
        [(0, 4), (4, 8), (8, 12), (12, 16)]
    for rd in plan:
        assert rd.n_stages == 2 and rd.n_data == 2
        assert rd.n_devices == 4


def test_replica_device_plan_rejects_indivisible_grids():
    with pytest.raises(ValueError, match="do not split"):
        replica_device_plan(3, 1, n_devices=16)
    with pytest.raises(ValueError, match="do not fold"):
        replica_device_plan(2, 3, n_devices=16)
    with pytest.raises(ValueError, match="needs"):
        replica_device_plan(2, 2, n_data=3, n_devices=16)


def test_replica_device_plan_rejects_process_straddle():
    # 8 devices/process, 16 devices, 2 replicas: per=8 — aligned
    replica_device_plan(2, 2, n_devices=16, devices_per_process=8)
    # per=6 straddles an 8-device process boundary
    with pytest.raises(ValueError, match="straddles"):
        replica_device_plan(4, 2, n_data=3, n_devices=24,
                            devices_per_process=8)


def test_carve_replica_meshes_on_local_devices():
    import jax
    devices = jax.devices()              # conftest forces 8 CPU devices
    meshes = carve_replica_meshes(2, 2, devices=devices)
    assert len(meshes) == 2
    for i, mesh in enumerate(meshes):
        assert mesh.devices.size == 4
        assert mesh.shape["stage"] == 2
        assert set(mesh.devices.flatten()) == set(devices[4 * i:4 * i + 4]), \
            "contiguous, non-interleaved carve"


# ---------------------------------------------------------------------------
# FleetController over InProcessTransport (fast twins of the slow tier)


def _controller(n, *, async_tick=False, **policy_kw):
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    policy_kw.setdefault("backoff_base_s", 0.0)
    transports = [
        InProcessTransport(
            ServeEngine(FakeBackend(2),
                        RequestQueue(capacity=32, clock=clock),
                        watchdog=TickWatchdog(stuck_slack_ticks=None)),
            async_tick=async_tick)
        for _ in range(n)]
    ctl = FleetController(transports,
                          RequestQueue(capacity=32, clock=clock),
                          policy=RouterPolicy(**policy_kw))
    return ctl, t


def _run(ctl, t, max_ticks=300, pace_s=0.0):
    out = []
    for _ in range(max_ticks):
        if ctl.idle:
            return out
        t[0] += 0.01
        out.extend(ctl.tick())
        if pace_s:
            time.sleep(pace_s)
    raise AssertionError(f"fleet not idle: {ctl.counts()}")


def test_controller_serves_through_transport_interface():
    ctl, t = _controller(2)
    ids = [ctl.submit([3, 4, 5], max_new_tokens=4).id for _ in range(6)]
    out = _run(ctl, t)
    assert sorted(r.request_id for r in out) == sorted(ids)
    assert all(r.status == "ok" for r in out)
    ctl.close()


def test_async_tick_transport_delivers_via_buffer():
    ctl, t = _controller(2, async_tick=True)
    try:
        ids = [ctl.submit([1, 2], max_new_tokens=3).id for _ in range(5)]
        out = []
        deadline = time.monotonic() + 30.0
        while not ctl.idle:
            t[0] += 0.01
            out.extend(ctl.tick())
            time.sleep(0.005)
            assert time.monotonic() < deadline
        assert sorted(r.request_id for r in out) == sorted(ids)
        assert all(r.status == "ok" for r in out)
    finally:
        ctl.close()


def test_async_idle_never_lies_between_tick_and_buffer():
    # the async transport must report busy until the response is IN
    # the buffer — an unlocked read mid-tick would let run-to-idle
    # loops exit with deliveries still in flight
    for _ in range(5):
        ctl, t = _controller(1, async_tick=True)
        try:
            rid = ctl.submit([1], max_new_tokens=2).id
            out = []
            deadline = time.monotonic() + 30.0
            while not ctl.idle:
                t[0] += 0.01
                out.extend(ctl.tick())
                assert time.monotonic() < deadline
            out.extend(ctl.tick())
            assert [r.request_id for r in out] == [rid]
        finally:
            ctl.close()


class _SeveredTransport:
    """A transport whose wire can be cut: once ``severed``, every
    remote call raises TransportError (the engine behind it may be
    perfectly healthy — the fleet can't know). NOT a ReplicaTransport
    subclass: inherited default methods would shadow the __getattr__
    delegation. Local reads (queue_depth/capacity, counters) stay
    ungated, matching the real process transport where they never
    touch the socket."""

    # local state reads never touch the socket on the real process
    # transport either — only remote calls are gated
    _LOCAL = frozenset(["queue_depth", "queue_capacity", "live_slots",
                        "default_max_new_tokens", "rpc_inflight",
                        "rpc_retries", "close", "idle", "drained"])

    def __init__(self, inner):
        object.__setattr__(self, "inner", inner)
        object.__setattr__(self, "severed", False)

    def __getattr__(self, name):
        inner = object.__getattribute__(self, "inner")
        attr = getattr(inner, name)
        if name in _SeveredTransport._LOCAL:
            return attr
        if self.severed:
            raise TransportError("wire cut (test)")
        if callable(attr):
            def call(*a, **k):
                if self.severed:
                    raise TransportError("wire cut (test)")
                return attr(*a, **k)
            return call
        return attr


def _severed_controller(n):
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    transports = [
        _SeveredTransport(InProcessTransport(
            ServeEngine(FakeBackend(2),
                        RequestQueue(capacity=32, clock=clock),
                        watchdog=TickWatchdog(stuck_slack_ticks=None))))
        for _ in range(n)]
    ctl = FleetController(transports,
                          RequestQueue(capacity=32, clock=clock),
                          policy=RouterPolicy(backoff_base_s=0.0))
    return ctl, t


def test_transport_drop_retires_replica_and_fails_over():
    ctl, t = _severed_controller(2)
    ids = [ctl.submit([2, 3], max_new_tokens=8).id for _ in range(6)]
    t[0] += 0.01
    ctl.tick()                      # some requests in flight on both
    ctl.replicas[0].transport.severed = True
    out = _run(ctl, t)
    got = sorted(r.request_id for r in out)
    assert got == sorted(set(ids)), "every id exactly one terminal"
    assert all(r.status == "ok" for r in out)
    assert ctl.replicas[0].state == RETIRED
    assert ctl.replicas[1].state == HEALTHY
    ctl.close()


def test_transport_drop_of_last_replica_fails_work_loudly():
    ctl, t = _severed_controller(1)
    ids = [ctl.submit([2], max_new_tokens=4).id for _ in range(3)]
    t[0] += 0.01
    ctl.tick()
    ctl.replicas[0].transport.severed = True
    out = _run(ctl, t)
    assert sorted(r.request_id for r in out) == sorted(ids), \
        "every id exactly one terminal, even with the whole fleet gone"
    for i in ids:
        resp = ctl.response(i)
        assert resp is not None, "no request may vanish"
        assert resp.status in ("ok", "error")


# ---------------------------------------------------------------------------
# KV handoff payloads on a real paged backend (the bytes that cross
# the wire)


CFG_KW = dict(vocab=61, d_model=16, nhead=2, d_ff=32, n_layers=2,
              seq_len=64, dropout=0.0)


@pytest.fixture(scope="module")
def paged_pair():
    import jax

    from pipe_tpu.inference import GenerationConfig
    from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
    from pipe_tpu.serve import SingleDeviceSlotBackend
    model = PipelinedLM(LMConfig(**CFG_KW), 1)
    params = model.init(jax.random.key(0))

    def backend():
        return SingleDeviceSlotBackend(
            model, params, num_slots=2, max_len=48,
            gen=GenerationConfig(max_new_tokens=4, temperature=0.0),
            kv_block_size=8, kv_pool_blocks=24, prefill_chunk=8)
    return backend


def _serve(backend, prompt):
    eng = ServeEngine(backend, RequestQueue())
    eng.submit(list(prompt), max_new_tokens=4, seed=0)
    out = eng.run_until_idle()
    assert len(out) == 1 and out[0].status == "ok"
    return out[0].tokens


def test_export_import_moves_blocks_and_preserves_tokens(paged_pair):
    prompt = [(i * 7) % 53 + 1 for i in range(32)]   # 4 full blocks
    home, dest = paged_pair(), paged_pair()
    ref = _serve(home, prompt)                       # caches the prefix
    payload = home.export_prefix_payload(prompt, codec="raw")
    assert payload is not None and payload["hashes"]
    n_exported = len(payload["hashes"])
    assert dest.pool.cached_prefix_blocks(prompt) == 0
    seated = dest.import_prefix_payload(payload)
    assert seated == n_exported
    assert dest.pool.cached_prefix_blocks(prompt) == n_exported
    # raw codec is bitwise: decode from the imported prefix must match
    assert _serve(dest, prompt) == ref


def test_import_skips_already_cached_blocks(paged_pair):
    prompt = [(i * 5) % 51 + 1 for i in range(24)]
    home, dest = paged_pair(), paged_pair()
    _serve(home, prompt)
    payload = home.export_prefix_payload(prompt, codec="int8")
    assert payload is not None
    first = dest.import_prefix_payload(payload)
    assert first > 0
    again = dest.import_prefix_payload(payload)
    assert again == 0, "re-import of cached hashes must be a no-op"


def test_export_returns_none_when_nothing_cached(paged_pair):
    backend = paged_pair()
    assert backend.export_prefix_payload([1, 2, 3, 4, 5, 6, 7, 8],
                                         codec="raw") is None


# ---------------------------------------------------------------------------
# real child processes (slow tier; fast twins above pin the semantics)


def _proc_spec(**kw):
    base = dict(
        lm_cfg=dict(CFG_KW),
        num_slots=2, max_len=48, init_seed=0,
        gen=dict(max_new_tokens=8, temperature=0.0),
        decode_chunk=1, heartbeat_interval_s=0.05,
    )
    base.update(kw)
    return ReplicaSpec(**base)


def _wait(pred, timeout_s=60.0, dt=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(dt)
    return False


@pytest.mark.slow
def test_process_replica_place_poll_roundtrip():
    from pipe_tpu.serve.queue import RequestQueue as RQ
    q = RQ()
    tr = ProcessReplicaTransport(_proc_spec())
    try:
        req = q.submit([5, 6, 7], max_new_tokens=4, seed=0)
        tr.place(req)
        got = []
        assert _wait(lambda: (got.extend(tr.poll()) or got), 120.0)
        assert got[0].request_id == req.id
        assert got[0].status == "ok"
        assert len(got[0].tokens) == 4
        h = tr.health()
        assert h.alive and h.heartbeat_age_s < 5.0
    finally:
        tr.close()


@pytest.mark.slow
def test_process_replica_survives_transport_drop_not_replica():
    # kill the WIRE (both directions), not the process: the child
    # re-dials the parent's listener and pending RPCs are re-sent
    from pipe_tpu.serve.queue import RequestQueue as RQ
    q = RQ()
    tr = ProcessReplicaTransport(_proc_spec())
    try:
        req = q.submit([1, 2, 3], max_new_tokens=3, seed=0)
        tr.place(req)
        got = []
        assert _wait(lambda: (got.extend(tr.poll()) or got), 120.0)
        assert got[0].status == "ok"
        tr.drop_connection()
        req2 = q.submit([4, 5, 6], max_new_tokens=3, seed=1)
        deadline = time.monotonic() + 60.0
        while True:                    # place may race the re-dial
            try:
                tr.place(req2)
                break
            except TransportError:
                assert time.monotonic() < deadline, "never reconnected"
                time.sleep(0.1)
        got = []
        assert _wait(lambda: (got.extend(tr.poll()) or got), 120.0)
        assert got[0].request_id == req2.id and got[0].status == "ok"
    finally:
        tr.close()


@pytest.mark.slow
def test_sigkilled_child_reports_dead_and_controller_fails_over():
    specs = [_proc_spec() for _ in range(2)]
    transports = [ProcessReplicaTransport(s) for s in specs]
    ctl = FleetController(transports,
                          policy=RouterPolicy(backoff_base_s=0.0,
                                              heartbeat_timeout_s=5.0))
    try:
        ids = []

        def submit_one(i):
            ids.append(ctl.submit([i + 1, i + 2],
                                  max_new_tokens=4, seed=i).id)

        for i in range(8):
            submit_one(i)
        # kill only once the victim actually HOLDS work: a kill while
        # its in-flight set is empty lets the controller reach idle
        # without ever touching the dead transport, and the state
        # assertion below would be vacuous
        deadline = time.monotonic() + 60.0
        while True:
            ctl.tick()
            # check right after the tick, NOT after a sleep: a warm
            # replica serves these tiny requests in ~20ms, so the
            # in-flight window only exists straight off the placing
            # tick (responses drain asynchronously on the reader
            # thread, no parent tick needed)
            if transports[1]._inflight:
                break
            time.sleep(0.01)
            if ctl.idle and len(ids) < 256:  # drained first: feed more
                for _ in range(8):
                    submit_one(len(ids))
            assert time.monotonic() < deadline, "victim never got work"
        transports[1]._proc.kill()
        deadline = time.monotonic() + 120.0
        while not ctl.idle:
            ctl.tick()
            time.sleep(0.01)
            assert time.monotonic() < deadline
        for i in ids:
            resp = ctl.response(i)
            assert resp is not None, "request vanished across SIGKILL"
        assert ctl.replicas[1].state == RETIRED
        assert ctl.replicas[0].state == HEALTHY
    finally:
        ctl.close()

# ---------------------------------------------------------------------------
# disaggregated prefill/decode fleet (fleet/disagg.py)


def _disagg_controller(roles, **policy_kw):
    from pipe_tpu.fleet import DisaggController
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    policy_kw.setdefault("backoff_base_s", 0.0)
    transports = [
        InProcessTransport(
            ServeEngine(FakeBackend(2),
                        RequestQueue(capacity=32, clock=clock),
                        watchdog=TickWatchdog(stuck_slack_ticks=None),
                        phase=role))
        for role in roles]
    ctl = DisaggController(transports,
                           RequestQueue(capacity=32, clock=clock),
                           policy=RouterPolicy(**policy_kw))
    return ctl, t


def test_transport_role_defaults_from_engine_phase():
    ctl, _ = _disagg_controller(("prefill", "decode", "mixed"))
    assert [r.role for r in ctl.replicas] == ["prefill", "decode", "mixed"]
    ctl.close()


def test_disagg_two_phase_flow_delivers_exactly_once():
    ctl, t = _disagg_controller(("prefill", "decode", "mixed"))
    ids = [ctl.submit([3, 4, 5], max_new_tokens=4).id for _ in range(6)]
    out = _run(ctl, t)
    assert sorted(r.request_id for r in out) == sorted(ids)
    assert all(r.status == "ok" for r in out)
    # the client sees the FULL budget — never the one-token shadow
    assert all(len(r.tokens) == 4 for r in out)
    pre, dec, mix = (r.transport for r in ctl.replicas)
    assert pre.obs_responses_out == 6, "every prefill ran on the pool"
    assert dec.obs_responses_out == 6, "every decode ran on the pool"
    assert mix.obs_responses_out == 0, "mixed untouched while pools healthy"
    # shadow terminals were consumed, not delivered (one token each)
    assert ctl.obs_shadow_tokens == 6
    ctl.close()


def test_disagg_reconciles_tokens_including_shadows():
    from pipe_tpu.obs.fleet_obs import FleetObserver
    ctl, t = _disagg_controller(("prefill", "decode"))
    for _ in range(4):
        ctl.submit([1, 2], max_new_tokens=3)
    _run(ctl, t)
    rec = FleetObserver(ctl).reconcile()
    assert rec["shadow_tokens"] == 4
    assert rec["reconciled"], rec
    ctl.close()


def test_disagg_no_decode_replica_serves_on_mixed_and_recovers():
    from pipe_tpu.fleet import SUSPECT
    ctl, t = _disagg_controller(("prefill", "decode", "mixed"),
                                recover_healthy_ticks=10_000)
    dec = ctl.replicas[1]
    dec.state = SUSPECT                 # decode pool entirely sick
    rid = ctl.submit([2, 3], max_new_tokens=4).id
    out = _run(ctl, t)
    assert [r.request_id for r in out] == [rid]
    assert out[0].status == "ok" and len(out[0].tokens) == 4
    assert dec.transport.obs_responses_out == 0
    assert ctl.replicas[2].transport.obs_responses_out == 1, \
        "decode phase fell back to the mixed replica"
    # pool recovery: the replica returns HEALTHY and takes decode again
    dec.state = HEALTHY
    dec.healthy_streak = 0
    rid2 = ctl.submit([4, 5], max_new_tokens=4).id
    out2 = _run(ctl, t)
    assert [r.request_id for r in out2] == [rid2]
    assert dec.transport.obs_responses_out == 1, \
        "recovered decode replica rejoined its role pool"
    ctl.close()


def test_disagg_no_role_replicas_at_all_parks_until_recovery():
    # both role pools sick and no mixed replica: requests wait (parked /
    # front) instead of dying, then serve when a pool recovers
    from pipe_tpu.fleet import SUSPECT
    ctl, t = _disagg_controller(("prefill", "decode"),
                                recover_healthy_ticks=10_000)
    ctl.replicas[0].state = SUSPECT
    rid = ctl.submit([1, 2], max_new_tokens=2).id
    for _ in range(5):
        t[0] += 0.01
        assert ctl.tick() == []
    assert ctl.response(rid) is None, "request must not fail while sick"
    ctl.replicas[0].state = HEALTHY
    out = _run(ctl, t)
    assert [r.request_id for r in out] == [rid]
    assert out[0].status == "ok"
    ctl.close()


def test_phase_less_requests_only_land_on_mixed_replicas():
    # a plain FleetController request (no phase tag) must never reach a
    # prefill-only or decode-only engine — they would reject it
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    transports = [
        InProcessTransport(
            ServeEngine(FakeBackend(2),
                        RequestQueue(capacity=32, clock=clock),
                        watchdog=TickWatchdog(stuck_slack_ticks=None),
                        phase=role))
        for role in ("prefill", "mixed")]
    ctl = FleetController(transports,
                          RequestQueue(capacity=32, clock=clock),
                          policy=RouterPolicy(backoff_base_s=0.0))
    ids = [ctl.submit([1, 2], max_new_tokens=4).id for _ in range(4)]
    out = _run(ctl, t)
    assert sorted(r.request_id for r in out) == sorted(ids)
    assert transports[0].obs_responses_out == 0
    assert transports[1].obs_responses_out == 4
    ctl.close()


def test_prefill_only_engine_rejects_unclamped_requests():
    eng = ServeEngine(FakeBackend(2), RequestQueue(), phase="prefill")
    with pytest.raises(ValueError, match="prefill-only"):
        eng.submit([1, 2, 3], max_new_tokens=4)
    eng.submit([1, 2, 3], max_new_tokens=1)      # the clamped form


def test_engine_rejects_unknown_phase():
    with pytest.raises(ValueError, match="phase"):
        ServeEngine(FakeBackend(2), RequestQueue(), phase="verify")


def test_decode_headroom_validation_names_the_overflow():
    from pipe_tpu.inference import GenerationConfig
    gen = GenerationConfig(max_new_tokens=4)
    gen.check_decode_headroom(16, 4, bucket_max_len=16)   # fits
    with pytest.raises(ValueError) as ei:
        gen.check_decode_headroom(60, 4, bucket_max_len=16)
    msg = str(ei.value)
    assert "decode-only" in msg
    assert "60" in msg and "exceeds" in msg
    assert "by 44 rows" in msg, "the overflow is named"


def test_decode_only_engine_refuses_cold_multi_block_prompt(paged_pair):
    eng = ServeEngine(paged_pair(), RequestQueue(), phase="decode")
    prompt = [(i * 7) % 53 + 1 for i in range(16)]   # 2 full blocks
    with pytest.raises(ValueError, match="decode-only"):
        eng.submit(list(prompt), max_new_tokens=4)


def test_decode_only_engine_serves_after_prefix_import(paged_pair):
    prompt = [(i * 7) % 53 + 1 for i in range(16)]
    home, dest = paged_pair(), paged_pair()
    ref = _serve(home, prompt)
    payload = home.export_prefix_payload(prompt, codec="raw")
    assert dest.import_prefix_payload(payload) > 0
    eng = ServeEngine(dest, RequestQueue(), phase="decode")
    eng.submit(list(prompt), max_new_tokens=4, seed=0)
    out = eng.run_until_idle()
    assert len(out) == 1 and out[0].status == "ok"
    assert out[0].tokens == ref, "decode from imported KV is bitwise"


def _paged_disagg(paged_pair, roles):
    from pipe_tpu.fleet import DisaggController
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    transports = [
        _SeveredTransport(InProcessTransport(
            ServeEngine(paged_pair(),
                        RequestQueue(capacity=8, clock=clock),
                        watchdog=TickWatchdog(stuck_slack_ticks=None),
                        phase=role)))
        for role in roles]
    ctl = DisaggController(transports,
                           RequestQueue(capacity=8, clock=clock),
                           policy=RouterPolicy(backoff_base_s=0.0))
    return ctl, t


def test_disagg_ships_kv_and_decodes_from_imported_blocks(paged_pair):
    ctl, t = _paged_disagg(paged_pair, ("prefill", "decode"))
    prompt = [(i * 7) % 53 + 1 for i in range(16)]   # 2 full blocks
    rid = ctl.submit(list(prompt), max_new_tokens=4, seed=0).id
    out = _run(ctl, t)
    assert [r.request_id for r in out] == [rid]
    assert out[0].status == "ok" and len(out[0].tokens) == 4
    dec = ctl.replicas[1].transport
    assert dec.obs_responses_out == 1
    assert dec.cached_prefix_blocks(prompt) == 2, \
        "decode replica resumed from shipped blocks, not a re-prefill"
    ctl.close()


def test_disagg_prefill_death_mid_handoff_replaces_exactly_once(
        paged_pair):
    # the handoff race: the prefill replica dies after the prefix is
    # cached (shadow consumed) but before the decode import completes —
    # the export comes up dead, the ship is cold, the decode-only
    # engine refuses, and the request re-places on the mixed replica
    # for an ordinary prefill. Exactly one client terminal.
    ctl, t = _paged_disagg(paged_pair, ("prefill", "decode", "mixed"))
    prompt = [(i * 7) % 53 + 1 for i in range(16)]
    req = ctl.submit(list(prompt), max_new_tokens=4, seed=0)
    # run until the shadow is consumed (request flipped to decode)...
    for _ in range(300):
        t[0] += 0.01
        ctl.tick()
        if req.phase == "decode":
            break
    else:
        raise AssertionError("prefill phase never completed")
    # ...then kill the prefill replica's wire BEFORE decode placement
    ctl.replicas[0].transport.severed = True
    out = _run(ctl, t)
    assert [r.request_id for r in out] == [req.id], "exactly one terminal"
    assert out[0].status == "ok" and len(out[0].tokens) == 4
    assert ctl.replicas[0].state == RETIRED
    assert ctl.replicas[2].transport.obs_responses_out == 1, \
        "mixed replica served the decode end-to-end after the cold ship"
    ctl.close()


def test_disagg_cold_ship_without_mixed_reprefills(paged_pair):
    # a static prefill/decode fleet (no mixed replica anywhere): the
    # cached prefix vanishes between the shadow and the decode
    # placement (pool pressure evicted it — the apps/serve --tiny
    # drive hits this with 12 requests against a 16-block pool). The
    # cold ship makes the decode-only engine refuse, and with no mixed
    # replica to re-prefill on, the request must flip BACK to its
    # prefill phase for a fresh prefix rather than park forever.
    ctl, t = _paged_disagg(paged_pair, ("prefill", "decode"))
    prompt = [(i * 7) % 53 + 1 for i in range(16)]
    req = ctl.submit(list(prompt), max_new_tokens=4, seed=0)
    for _ in range(300):
        t[0] += 0.01
        ctl.tick()
        if req.phase == "decode":
            break
    else:
        raise AssertionError("prefill phase never completed")
    assert ctl.replicas[0].transport.invalidate_prefix(prompt) > 0, \
        "the prefix must actually be evicted for the drill to bite"
    out = _run(ctl, t)
    assert [r.request_id for r in out] == [req.id], "exactly one terminal"
    assert out[0].status == "ok" and len(out[0].tokens) == 4
    from pipe_tpu.obs.telemetry import get_registry
    assert get_registry().snapshot()["serve.fleet.disagg_reprefill"] >= 1
    # the second pass went through the full pipeline: fresh prefix on
    # the prefill replica, shipped, decoded on the decode replica
    assert ctl.replicas[1].transport.obs_responses_out == 1
    assert ctl.obs_shadow_tokens == 2, "two shadow passes, one delivery"
    ctl.close()


def test_disagg_decode_death_before_import_ack_replaces_exactly_once(
        paged_pair):
    # the other half of the race: the DECODE replica dies between the
    # export and its import ack — the ship degrades to cold, the
    # controller drops the dead transport on the place attempt, and the
    # request re-places (still exactly once) on the mixed replica
    ctl, t = _paged_disagg(paged_pair, ("prefill", "decode", "mixed"))
    prompt = [(i * 7) % 53 + 1 for i in range(16)]
    req = ctl.submit(list(prompt), max_new_tokens=4, seed=0)
    for _ in range(300):
        t[0] += 0.01
        ctl.tick()
        if req.phase == "decode":
            break
    else:
        raise AssertionError("prefill phase never completed")
    ctl.replicas[1].transport.severed = True
    out = _run(ctl, t)
    assert [r.request_id for r in out] == [req.id], "exactly one terminal"
    assert out[0].status == "ok" and len(out[0].tokens) == 4
    assert ctl.replicas[1].state == RETIRED
    assert ctl.replicas[2].transport.obs_responses_out == 1
    ctl.close()


# ---------------------------------------------------------------------------
# role-asymmetric topology


def test_role_device_plan_asymmetric_contiguous():
    from pipe_tpu.fleet import role_device_plan
    plan = role_device_plan([("prefill", 1, 4), ("decode", 2, 1),
                             ("decode", 2, 1)], n_devices=8)
    assert [(rd.role, rd.start, rd.stop) for rd in plan] == \
        [("prefill", 0, 4), ("decode", 4, 6), ("decode", 6, 8)]
    assert plan[0].n_data == 4 and plan[1].n_stages == 2


def test_role_device_plan_rejects_bad_inputs():
    from pipe_tpu.fleet import role_device_plan
    with pytest.raises(ValueError, match="role must be"):
        role_device_plan([("verify", 1, 1)], n_devices=1)
    with pytest.raises(ValueError, match="grid has"):
        role_device_plan([("prefill", 1, 4), ("decode", 1, 2)],
                         n_devices=8)
    # unequal shares can misalign even when each share divides the
    # process size: replica 1 starts at device 2 and would span [2, 6)
    with pytest.raises(ValueError, match="process boundary"):
        role_device_plan([("prefill", 1, 2), ("decode", 1, 4),
                          ("decode", 1, 2)], n_devices=8,
                         devices_per_process=4)
    # aligned version of the same shapes passes
    role_device_plan([("prefill", 1, 4), ("decode", 1, 2),
                      ("decode", 1, 2)], n_devices=8,
                     devices_per_process=4)


def test_carve_role_meshes_on_local_devices():
    import jax

    from pipe_tpu.fleet import carve_role_meshes
    devices = jax.devices()              # conftest forces 8 CPU devices
    meshes = carve_role_meshes([("prefill", 1, 4), ("decode", 2, 2)],
                               devices=devices)
    assert len(meshes) == 2
    assert meshes[0].devices.size == 4 and meshes[0].shape["data"] == 4
    assert meshes[1].shape["stage"] == 2
    assert set(meshes[0].devices.flatten()) == set(devices[:4])


# ---------------------------------------------------------------------------
# the cost-driven role planner


def test_suggest_roles_sizes_split_from_phase_costs():
    from pipe_tpu.fleet import suggest_roles
    s = suggest_roles(4, prompt_len=64, max_new_tokens=16,
                      prefill_token_s=2.0, decode_token_s=1.0)
    assert s.source == "args"
    assert s.roles == ["prefill", "prefill", "prefill", "decode"]
    assert s.n_prefill == 3 and s.n_decode == 1
    # decode-heavy workload flips the ratio, but neither pool empties
    s2 = suggest_roles(4, prompt_len=8, max_new_tokens=64,
                       prefill_token_s=1.0, decode_token_s=1.0)
    assert s2.roles == ["prefill", "decode", "decode", "decode"]
    assert 0.0 < s2.prefill_frac < 0.2


def test_suggest_roles_single_replica_stays_mixed():
    from pipe_tpu.fleet import suggest_roles
    s = suggest_roles(1, prompt_len=32, max_new_tokens=32)
    assert s.roles == ["mixed"] and s.n_prefill == 0


def test_suggest_roles_reads_telemetry_histograms():
    from pipe_tpu.fleet import suggest_roles
    from pipe_tpu.obs.telemetry import MetricsRegistry
    reg = MetricsRegistry()
    # measured: 64-token prefill in 0.64s (10ms/token), 2ms/decode-token
    for _ in range(10):
        reg.histogram("serve.engine.ttft_sec").observe(0.64)
        reg.histogram("serve.engine.token_sec").observe(0.002)
    s = suggest_roles(4, prompt_len=64, max_new_tokens=32, registry=reg)
    assert s.source == "telemetry"
    assert s.prefill_token_s == pytest.approx(0.01)
    assert s.n_prefill == 3, s        # prefill dominates 640ms vs 64ms
    empty = suggest_roles(2, prompt_len=16, max_new_tokens=16,
                          registry=MetricsRegistry())
    assert empty.source == "uniform"
    assert empty.roles == ["prefill", "decode"]


# ---------------------------------------------------------------------------
# TCP wire: replica bound on a real host/port (slow tier)


@pytest.mark.slow
def test_process_replica_on_bound_host_place_poll_reconnect_heartbeat():
    # the acceptance drill: a replica reached via a bound host/port —
    # not the loopback default — passes the place/poll, reconnect-with-
    # RPC-replay and heartbeat contracts unchanged
    from pipe_tpu.serve.queue import RequestQueue as RQ
    q = RQ()
    tr = ProcessReplicaTransport(_proc_spec(), bind_host="0.0.0.0")
    try:
        assert tr._bind_host == "0.0.0.0"
        assert tr._advertise_host == "127.0.0.1"   # wildcard auto-map
        req = q.submit([5, 6, 7], max_new_tokens=4, seed=0)
        tr.place(req)
        got = []
        assert _wait(lambda: (got.extend(tr.poll()) or got), 120.0)
        assert got[0].request_id == req.id and got[0].status == "ok"
        h = tr.health()
        assert h.alive and h.heartbeat_age_s < 5.0
        tr.drop_connection()                       # reconnect + replay
        req2 = q.submit([4, 5, 6], max_new_tokens=3, seed=1)
        deadline = time.monotonic() + 60.0
        while True:
            try:
                tr.place(req2)
                break
            except TransportError:
                assert time.monotonic() < deadline, "never reconnected"
                time.sleep(0.1)
        got = []
        assert _wait(lambda: (got.extend(tr.poll()) or got), 120.0)
        assert got[0].request_id == req2.id and got[0].status == "ok"
    finally:
        tr.close()


@pytest.mark.slow
def test_process_replica_spec_role_reaches_child_engine():
    # role ships through the spec handshake: a prefill-only child must
    # reject an unclamped request over the wire with the engine's error
    from pipe_tpu.serve.queue import RequestQueue as RQ
    q = RQ()
    tr = ProcessReplicaTransport(_proc_spec(role="prefill"))
    try:
        assert tr.role == "prefill"
        bad = q.submit([1, 2, 3], max_new_tokens=4, seed=0)
        deadline = time.monotonic() + 60.0
        while True:
            try:
                with pytest.raises(ValueError, match="prefill-only"):
                    tr.place(bad)
                break
            except TransportError:
                assert time.monotonic() < deadline
                time.sleep(0.1)
        ok = q.submit([1, 2, 3], max_new_tokens=1, seed=0)
        tr.place(ok)
        got = []
        assert _wait(lambda: (got.extend(tr.poll()) or got), 120.0)
        assert got[0].request_id == ok.id and got[0].status == "ok"
        assert len(got[0].tokens) == 1
    finally:
        tr.close()
