"""pipe_tpu.fleet: transport-split control plane, process replicas,
mesh carving.

Tier-1 runs the fast in-process twins (stub backends, wire codec over
socketpairs, spawn-refusal, topology arithmetic, KV handoff payloads
on a real paged backend). The ``slow`` tier spawns REAL child
interpreters through :class:`ProcessReplicaTransport` and drills the
wire: place/poll across the socket, child-initiated reconnect after a
transport drop (kill the wire, not the replica), SIGKILL failover.
"""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from pipe_tpu.fleet import (FleetController, FleetSpawnError,
                            InProcessTransport, ProcessReplicaTransport,
                            ReplicaSpec, ReplicaTransport, RouterPolicy,
                            TransportError, carve_replica_meshes,
                            check_spawn_capability, replica_device_plan)
from pipe_tpu.fleet.proc import (_pack, _spawn_env, _unpack, recv_frame,
                                 send_frame)
from pipe_tpu.resilience import TickWatchdog
from pipe_tpu.serve import (HEALTHY, RETIRED, RequestQueue, ServeEngine)
from test_router import FakeBackend

# ---------------------------------------------------------------------------
# wire codec


def test_codec_roundtrips_nested_messages_and_ndarrays():
    msg = {
        "op": "import_prefix",
        "rpc": 7,
        "payload": {
            "codec": "int8",
            "blocks": [
                {"k": np.arange(24, dtype=np.int8).reshape(2, 3, 4),
                 "scale": np.ones((2, 1, 4), np.float32) * 0.5,
                 "hash": 123456789},
            ],
            "prompt": list(range(16)),
        },
    }
    out = _unpack(_pack(msg))
    assert out["op"] == "import_prefix" and out["rpc"] == 7
    blk = out["payload"]["blocks"][0]
    np.testing.assert_array_equal(blk["k"],
                                  msg["payload"]["blocks"][0]["k"])
    assert blk["k"].dtype == np.int8
    np.testing.assert_array_equal(blk["scale"],
                                  msg["payload"]["blocks"][0]["scale"])
    assert blk["scale"].dtype == np.float32
    assert out["payload"]["prompt"] == list(range(16))


def test_framing_survives_split_reads_and_interleaved_senders():
    a, b = socket.socketpair()
    try:
        lock = threading.Lock()
        msgs = [{"op": "hb", "i": i, "v": np.full((3,), i, np.int32)}
                for i in range(5)]
        threads = [threading.Thread(target=send_frame,
                                    args=(a, m, lock)) for m in msgs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        got = sorted((recv_frame(b)["i"] for _ in msgs))
        assert got == [0, 1, 2, 3, 4]
    finally:
        a.close()
        b.close()


def test_recv_frame_returns_none_on_clean_eof():
    a, b = socket.socketpair()
    a.close()
    try:
        assert recv_frame(b) is None
    finally:
        b.close()


def test_frame_length_prefix_is_4_byte_big_endian():
    a, b = socket.socketpair()
    try:
        frame = send_frame(a, {"x": 1})
        (n,) = struct.unpack(">I", frame[:4])
        assert n == len(frame) - 4
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# spawn discipline (runtime/_multiproc_check)


def test_spawn_refusal_names_the_failure_and_the_remedy():
    with pytest.raises(FleetSpawnError) as ei:
        check_spawn_capability("/nonexistent/python3")
    msg = str(ei.value)
    assert msg.startswith("cannot spawn JAX child processes")
    assert "--fleet inproc" in msg          # the remedy is in the error


def test_spawn_refusal_blocks_transport_construction():
    with pytest.raises(FleetSpawnError):
        ProcessReplicaTransport(ReplicaSpec(lm_cfg={}),
                                executable="/nonexistent/python3")


def test_spawn_env_discipline():
    env = _spawn_env(repo_root="/r", jax_platform="cpu")
    assert env["PYTHONPATH"] == "/r"
    assert env["JAX_PLATFORMS"] == "cpu"
    assert "XLA_FLAGS" not in env


def test_spawn_capability_passes_on_this_host():
    check_spawn_capability()                # should not raise here


# ---------------------------------------------------------------------------
# topology: carving the device grid into replica sub-meshes


def test_replica_device_plan_contiguous_and_shaped():
    plan = replica_device_plan(4, 2, n_devices=16)
    assert [(rd.start, rd.stop) for rd in plan] == \
        [(0, 4), (4, 8), (8, 12), (12, 16)]
    for rd in plan:
        assert rd.n_stages == 2 and rd.n_data == 2
        assert rd.n_devices == 4


def test_replica_device_plan_rejects_indivisible_grids():
    with pytest.raises(ValueError, match="do not split"):
        replica_device_plan(3, 1, n_devices=16)
    with pytest.raises(ValueError, match="do not fold"):
        replica_device_plan(2, 3, n_devices=16)
    with pytest.raises(ValueError, match="needs"):
        replica_device_plan(2, 2, n_data=3, n_devices=16)


def test_replica_device_plan_rejects_process_straddle():
    # 8 devices/process, 16 devices, 2 replicas: per=8 — aligned
    replica_device_plan(2, 2, n_devices=16, devices_per_process=8)
    # per=6 straddles an 8-device process boundary
    with pytest.raises(ValueError, match="straddles"):
        replica_device_plan(4, 2, n_data=3, n_devices=24,
                            devices_per_process=8)


def test_carve_replica_meshes_on_local_devices():
    import jax
    devices = jax.devices()              # conftest forces 8 CPU devices
    meshes = carve_replica_meshes(2, 2, devices=devices)
    assert len(meshes) == 2
    for i, mesh in enumerate(meshes):
        assert mesh.devices.size == 4
        assert mesh.shape["stage"] == 2
        assert set(mesh.devices.flatten()) == set(devices[4 * i:4 * i + 4]), \
            "contiguous, non-interleaved carve"


# ---------------------------------------------------------------------------
# FleetController over InProcessTransport (fast twins of the slow tier)


def _controller(n, *, async_tick=False, **policy_kw):
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    policy_kw.setdefault("backoff_base_s", 0.0)
    transports = [
        InProcessTransport(
            ServeEngine(FakeBackend(2),
                        RequestQueue(capacity=32, clock=clock),
                        watchdog=TickWatchdog(stuck_slack_ticks=None)),
            async_tick=async_tick)
        for _ in range(n)]
    ctl = FleetController(transports,
                          RequestQueue(capacity=32, clock=clock),
                          policy=RouterPolicy(**policy_kw))
    return ctl, t


def _run(ctl, t, max_ticks=300, pace_s=0.0):
    out = []
    for _ in range(max_ticks):
        if ctl.idle:
            return out
        t[0] += 0.01
        out.extend(ctl.tick())
        if pace_s:
            time.sleep(pace_s)
    raise AssertionError(f"fleet not idle: {ctl.counts()}")


def test_controller_serves_through_transport_interface():
    ctl, t = _controller(2)
    ids = [ctl.submit([3, 4, 5], max_new_tokens=4).id for _ in range(6)]
    out = _run(ctl, t)
    assert sorted(r.request_id for r in out) == sorted(ids)
    assert all(r.status == "ok" for r in out)
    ctl.close()


def test_async_tick_transport_delivers_via_buffer():
    ctl, t = _controller(2, async_tick=True)
    try:
        ids = [ctl.submit([1, 2], max_new_tokens=3).id for _ in range(5)]
        out = []
        deadline = time.monotonic() + 30.0
        while not ctl.idle:
            t[0] += 0.01
            out.extend(ctl.tick())
            time.sleep(0.005)
            assert time.monotonic() < deadline
        assert sorted(r.request_id for r in out) == sorted(ids)
        assert all(r.status == "ok" for r in out)
    finally:
        ctl.close()


def test_async_idle_never_lies_between_tick_and_buffer():
    # the async transport must report busy until the response is IN
    # the buffer — an unlocked read mid-tick would let run-to-idle
    # loops exit with deliveries still in flight
    for _ in range(5):
        ctl, t = _controller(1, async_tick=True)
        try:
            rid = ctl.submit([1], max_new_tokens=2).id
            out = []
            deadline = time.monotonic() + 30.0
            while not ctl.idle:
                t[0] += 0.01
                out.extend(ctl.tick())
                assert time.monotonic() < deadline
            out.extend(ctl.tick())
            assert [r.request_id for r in out] == [rid]
        finally:
            ctl.close()


class _SeveredTransport:
    """A transport whose wire can be cut: once ``severed``, every
    remote call raises TransportError (the engine behind it may be
    perfectly healthy — the fleet can't know). NOT a ReplicaTransport
    subclass: inherited default methods would shadow the __getattr__
    delegation. Local reads (queue_depth/capacity, counters) stay
    ungated, matching the real process transport where they never
    touch the socket."""

    # local state reads never touch the socket on the real process
    # transport either — only remote calls are gated
    _LOCAL = frozenset(["queue_depth", "queue_capacity", "live_slots",
                        "default_max_new_tokens", "rpc_inflight",
                        "rpc_retries", "close", "idle", "drained"])

    def __init__(self, inner):
        object.__setattr__(self, "inner", inner)
        object.__setattr__(self, "severed", False)

    def __getattr__(self, name):
        inner = object.__getattribute__(self, "inner")
        attr = getattr(inner, name)
        if name in _SeveredTransport._LOCAL:
            return attr
        if self.severed:
            raise TransportError("wire cut (test)")
        if callable(attr):
            def call(*a, **k):
                if self.severed:
                    raise TransportError("wire cut (test)")
                return attr(*a, **k)
            return call
        return attr


def _severed_controller(n):
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    transports = [
        _SeveredTransport(InProcessTransport(
            ServeEngine(FakeBackend(2),
                        RequestQueue(capacity=32, clock=clock),
                        watchdog=TickWatchdog(stuck_slack_ticks=None))))
        for _ in range(n)]
    ctl = FleetController(transports,
                          RequestQueue(capacity=32, clock=clock),
                          policy=RouterPolicy(backoff_base_s=0.0))
    return ctl, t


def test_transport_drop_retires_replica_and_fails_over():
    ctl, t = _severed_controller(2)
    ids = [ctl.submit([2, 3], max_new_tokens=8).id for _ in range(6)]
    t[0] += 0.01
    ctl.tick()                      # some requests in flight on both
    ctl.replicas[0].transport.severed = True
    out = _run(ctl, t)
    got = sorted(r.request_id for r in out)
    assert got == sorted(set(ids)), "every id exactly one terminal"
    assert all(r.status == "ok" for r in out)
    assert ctl.replicas[0].state == RETIRED
    assert ctl.replicas[1].state == HEALTHY
    ctl.close()


def test_transport_drop_of_last_replica_fails_work_loudly():
    ctl, t = _severed_controller(1)
    ids = [ctl.submit([2], max_new_tokens=4).id for _ in range(3)]
    t[0] += 0.01
    ctl.tick()
    ctl.replicas[0].transport.severed = True
    out = _run(ctl, t)
    assert sorted(r.request_id for r in out) == sorted(ids), \
        "every id exactly one terminal, even with the whole fleet gone"
    for i in ids:
        resp = ctl.response(i)
        assert resp is not None, "no request may vanish"
        assert resp.status in ("ok", "error")


# ---------------------------------------------------------------------------
# KV handoff payloads on a real paged backend (the bytes that cross
# the wire)


CFG_KW = dict(vocab=61, d_model=16, nhead=2, d_ff=32, n_layers=2,
              seq_len=64, dropout=0.0)


@pytest.fixture(scope="module")
def paged_pair():
    import jax

    from pipe_tpu.inference import GenerationConfig
    from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM
    from pipe_tpu.serve import SingleDeviceSlotBackend
    model = PipelinedLM(LMConfig(**CFG_KW), 1)
    params = model.init(jax.random.key(0))

    def backend():
        return SingleDeviceSlotBackend(
            model, params, num_slots=2, max_len=48,
            gen=GenerationConfig(max_new_tokens=4, temperature=0.0),
            kv_block_size=8, kv_pool_blocks=24, prefill_chunk=8)
    return backend


def _serve(backend, prompt):
    eng = ServeEngine(backend, RequestQueue())
    eng.submit(list(prompt), max_new_tokens=4, seed=0)
    out = eng.run_until_idle()
    assert len(out) == 1 and out[0].status == "ok"
    return out[0].tokens


def test_export_import_moves_blocks_and_preserves_tokens(paged_pair):
    prompt = [(i * 7) % 53 + 1 for i in range(32)]   # 4 full blocks
    home, dest = paged_pair(), paged_pair()
    ref = _serve(home, prompt)                       # caches the prefix
    payload = home.export_prefix_payload(prompt, codec="raw")
    assert payload is not None and payload["hashes"]
    n_exported = len(payload["hashes"])
    assert dest.pool.cached_prefix_blocks(prompt) == 0
    seated = dest.import_prefix_payload(payload)
    assert seated == n_exported
    assert dest.pool.cached_prefix_blocks(prompt) == n_exported
    # raw codec is bitwise: decode from the imported prefix must match
    assert _serve(dest, prompt) == ref


def test_import_skips_already_cached_blocks(paged_pair):
    prompt = [(i * 5) % 51 + 1 for i in range(24)]
    home, dest = paged_pair(), paged_pair()
    _serve(home, prompt)
    payload = home.export_prefix_payload(prompt, codec="int8")
    assert payload is not None
    first = dest.import_prefix_payload(payload)
    assert first > 0
    again = dest.import_prefix_payload(payload)
    assert again == 0, "re-import of cached hashes must be a no-op"


def test_export_returns_none_when_nothing_cached(paged_pair):
    backend = paged_pair()
    assert backend.export_prefix_payload([1, 2, 3, 4, 5, 6, 7, 8],
                                         codec="raw") is None


# ---------------------------------------------------------------------------
# real child processes (slow tier; fast twins above pin the semantics)


def _proc_spec(**kw):
    base = dict(
        lm_cfg=dict(CFG_KW),
        num_slots=2, max_len=48, init_seed=0,
        gen=dict(max_new_tokens=8, temperature=0.0),
        decode_chunk=1, heartbeat_interval_s=0.05,
    )
    base.update(kw)
    return ReplicaSpec(**base)


def _wait(pred, timeout_s=60.0, dt=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(dt)
    return False


@pytest.mark.slow
def test_process_replica_place_poll_roundtrip():
    from pipe_tpu.serve.queue import RequestQueue as RQ
    q = RQ()
    tr = ProcessReplicaTransport(_proc_spec())
    try:
        req = q.submit([5, 6, 7], max_new_tokens=4, seed=0)
        tr.place(req)
        got = []
        assert _wait(lambda: (got.extend(tr.poll()) or got), 120.0)
        assert got[0].request_id == req.id
        assert got[0].status == "ok"
        assert len(got[0].tokens) == 4
        h = tr.health()
        assert h.alive and h.heartbeat_age_s < 5.0
    finally:
        tr.close()


@pytest.mark.slow
def test_process_replica_survives_transport_drop_not_replica():
    # kill the WIRE (both directions), not the process: the child
    # re-dials the parent's listener and pending RPCs are re-sent
    from pipe_tpu.serve.queue import RequestQueue as RQ
    q = RQ()
    tr = ProcessReplicaTransport(_proc_spec())
    try:
        req = q.submit([1, 2, 3], max_new_tokens=3, seed=0)
        tr.place(req)
        got = []
        assert _wait(lambda: (got.extend(tr.poll()) or got), 120.0)
        assert got[0].status == "ok"
        tr.drop_connection()
        req2 = q.submit([4, 5, 6], max_new_tokens=3, seed=1)
        deadline = time.monotonic() + 60.0
        while True:                    # place may race the re-dial
            try:
                tr.place(req2)
                break
            except TransportError:
                assert time.monotonic() < deadline, "never reconnected"
                time.sleep(0.1)
        got = []
        assert _wait(lambda: (got.extend(tr.poll()) or got), 120.0)
        assert got[0].request_id == req2.id and got[0].status == "ok"
    finally:
        tr.close()


@pytest.mark.slow
def test_sigkilled_child_reports_dead_and_controller_fails_over():
    specs = [_proc_spec() for _ in range(2)]
    transports = [ProcessReplicaTransport(s) for s in specs]
    ctl = FleetController(transports,
                          policy=RouterPolicy(backoff_base_s=0.0,
                                              heartbeat_timeout_s=5.0))
    try:
        ids = []

        def submit_one(i):
            ids.append(ctl.submit([i + 1, i + 2],
                                  max_new_tokens=4, seed=i).id)

        for i in range(8):
            submit_one(i)
        # kill only once the victim actually HOLDS work: a kill while
        # its in-flight set is empty lets the controller reach idle
        # without ever touching the dead transport, and the state
        # assertion below would be vacuous
        deadline = time.monotonic() + 60.0
        while True:
            ctl.tick()
            # check right after the tick, NOT after a sleep: a warm
            # replica serves these tiny requests in ~20ms, so the
            # in-flight window only exists straight off the placing
            # tick (responses drain asynchronously on the reader
            # thread, no parent tick needed)
            if transports[1]._inflight:
                break
            time.sleep(0.01)
            if ctl.idle and len(ids) < 256:  # drained first: feed more
                for _ in range(8):
                    submit_one(len(ids))
            assert time.monotonic() < deadline, "victim never got work"
        transports[1]._proc.kill()
        deadline = time.monotonic() + 120.0
        while not ctl.idle:
            ctl.tick()
            time.sleep(0.01)
            assert time.monotonic() < deadline
        for i in ids:
            resp = ctl.response(i)
            assert resp is not None, "request vanished across SIGKILL"
        assert ctl.replicas[1].state == RETIRED
        assert ctl.replicas[0].state == HEALTHY
    finally:
        ctl.close()
