"""Native batch prefetcher (csrc/pipetpu_prefetch.cpp + data/native.py).

Contracts: batch-for-batch parity with the inline ``get_batch`` walk the
trainer otherwise runs (slice + transpose, full batches only), strict
ordering through the ring at every depth, clean exhaustion/close behavior,
and end-to-end: a Trainer with ``prefetch_depth>0`` sees bitwise-identical
batches, so its losses match the inline run exactly.
"""

import dataclasses

import numpy as np
import pytest

from pipe_tpu.data import lm_text
from pipe_tpu.data.native import BatchPrefetcher, prefetch_available

pytestmark = pytest.mark.skipif(not prefetch_available(),
                                reason="no C++ toolchain for the native lib")


def _expected(src, bptt):
    out = []
    for b in range(lm_text.num_batches(src, bptt)):
        d, t = lm_text.get_batch(src, b * bptt, bptt)
        if d.shape[1] < bptt:
            break
        out.append((d, t))
    return out


@pytest.mark.parametrize("depth", [1, 2, 3, 8])
@pytest.mark.parametrize("nrows,bsz,bptt", [
    (41, 4, 5),    # non-divisible: short tail dropped
    (33, 8, 4),    # (nrows-1) divisible by bptt
    (5, 2, 5),     # fewer usable rows than bptt: zero batches
    (200, 3, 7),
])
def test_prefetch_matches_get_batch(depth, nrows, bsz, bptt):
    src = np.random.default_rng(nrows + bsz).integers(
        0, 1000, size=(nrows, bsz)).astype(np.int32)
    expected = _expected(src, bptt)
    with BatchPrefetcher(src, bptt, depth=depth) as pf:
        assert pf.num_batches == len(expected)
        got = [(d.copy(), t.copy()) for d, t in pf]
    assert len(got) == len(expected)
    for (d, t), (ed, et) in zip(got, expected):
        np.testing.assert_array_equal(d, ed)
        np.testing.assert_array_equal(t, et)


def test_prefetch_slot_views_are_ring_slots():
    # the yielded arrays are views into a depth-slot ring (the documented
    # overwrite contract): with depth=2, batches b and b+2 share storage
    src = np.arange(31 * 4, dtype=np.int32).reshape(31, 4)
    with BatchPrefetcher(src, 5, depth=2) as pf:
        addrs = [d.__array_interface__["data"][0] for d, _ in pf]
    assert len(addrs) == 6
    assert addrs[0] == addrs[2] == addrs[4]
    assert addrs[1] == addrs[3] == addrs[5]
    assert addrs[0] != addrs[1]


def test_prefetch_early_close_joins_producer():
    src = np.random.default_rng(0).integers(
        0, 100, size=(10_001, 16)).astype(np.int32)
    pf = BatchPrefetcher(src, 10, depth=2)
    it = iter(pf)
    next(it)
    pf.close()          # must join the producer thread without deadlock
    with pytest.raises(StopIteration):
        next(it)


def test_prefetch_validates_args():
    src = np.zeros((10, 2), np.int32)
    with pytest.raises(ValueError):
        BatchPrefetcher(src[0], 5)
    with pytest.raises(ValueError):
        BatchPrefetcher(src, 0)
    with pytest.raises(ValueError):
        BatchPrefetcher(src, 5, depth=0)


def test_trainer_losses_identical_with_prefetch():
    from pipe_tpu.models.transformer_lm import LMConfig
    from pipe_tpu.train.loop import Trainer, TrainerConfig

    model = LMConfig(vocab=64, d_model=32, nhead=4, d_ff=64, n_layers=2,
                     seq_len=16, dropout=0.0)
    cfg = TrainerConfig(batch_size=8, bptt=16, chunks=2, n_stages=2,
                        n_data=1, lr=0.05, schedule="gpipe",
                        checkpoint="never")
    ids = np.random.default_rng(9).integers(0, 64, size=2048).astype(np.int32)
    src = lm_text.batchify(ids, cfg.batch_size)

    def run(c):
        tr = Trainer(model, c)
        _, stats = tr.train_epoch(src, state=tr.init_state(), max_steps=3,
                                  log_every=0)
        return stats

    base = run(cfg)
    pf = run(dataclasses.replace(cfg, prefetch_depth=2))
    assert pf["steps"] == base["steps"] > 0
    assert pf["loss"] == pytest.approx(base["loss"], rel=0, abs=0)
