"""Memory-capped schedules through the flagship Pipe(mesh=) API (VERDICT r2
#2): ``Pipe(module, chunks, checkpoint, mesh, schedule='1f1b')`` — the
literal capability statement of the target — trains with the min(m, n)
activation cap; zb-h1 and interleaved-1f1b ride the same lowering.

The reference counterpart: its fork/join machinery exists exactly so
backward frees activations early (reference ``pipeline.py:128-132``) behind
the ``Pipe`` constructor (``pipe.py:308-314``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu import Lambda, Linear, Pipe, Sequential
from pipe_tpu.parallel.mesh import make_mesh

WIDTH = 8


def make_mlp(key, depth=4, width=WIDTH):
    seq = Sequential([Linear(width) for _ in range(depth)])
    params = seq.init(key, jnp.zeros((2, width)))
    return seq, params


def _regroup(flat_params, balance):
    out, off = [], 0
    for w in balance:
        out.append(flat_params[off:off + w])
        off += w
    return out


def stage_mesh(n_stages, n_data=1):
    return make_mesh(n_stages, n_data,
                     devices=jax.devices()[:n_stages * n_data])


def mse_loss(out, tgt):
    return jnp.mean((out - tgt[:, None]) ** 2, axis=-1)


def ref_loss_and_grad(seq, params, x, y):
    def ref(p):
        return jnp.mean(mse_loss(seq.apply(p, x), y))
    return jax.value_and_grad(ref)(params)


# ---------- transparency matrix ----------

@pytest.mark.parametrize("schedule", ["1f1b", "zb-h1", "gpipe"])
@pytest.mark.parametrize("checkpoint", ["never", "except_last", "always"])
def test_loss_and_grad_transparency(schedule, checkpoint):
    seq, params = make_mlp(jax.random.key(0))
    pipe = Pipe(seq, chunks=4, checkpoint=checkpoint, mesh=stage_mesh(2),
                schedule=schedule)
    packed = pipe.shard_params(_regroup(params, pipe.balance))
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    y = jnp.sum(jnp.sin(x), axis=-1)

    loss, g = pipe.loss_and_grad(packed, x, targets=y, loss_fn=mse_loss)
    rl, rg = ref_loss_and_grad(seq, params, x, y)
    np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(pipe.unshard_grads(g)),
                    jax.tree_util.tree_leaves(_regroup(rg, pipe.balance))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_interleaved_1f1b_through_pipe():
    """v=2 on a 2-device stage axis: 4 partitions, virtual stage s on
    device s % 2, device-major packed rows."""
    seq, params = make_mlp(jax.random.key(0))
    pipe = Pipe(seq, chunks=4, checkpoint="except_last", mesh=stage_mesh(2),
                schedule="interleaved-1f1b")
    assert pipe.n_stages == 4
    packed = pipe.shard_params([[p] for p in params])
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    y = jnp.sum(jnp.sin(x), axis=-1)

    loss, g = pipe.loss_and_grad(packed, x, targets=y, loss_fn=mse_loss)
    rl, rg = ref_loss_and_grad(seq, params, x, y)
    np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(pipe.unshard_grads(g)),
                    jax.tree_util.tree_leaves([[p] for p in rg])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)
    # round-trip respects the device-major row permutation
    back = pipe.unshard_params(packed)
    for a, b in zip(jax.tree_util.tree_leaves(back),
                    jax.tree_util.tree_leaves([[p] for p in params])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # forward for interleaved placements: the op tables with BWD masked
    # to IDLE (the eval-mode pipeline) — outputs equal the plain chain
    out = pipe(packed, x)
    ref_out = x
    for p, layer in zip(params, seq):
        ref_out = layer.apply(p, ref_out)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=1e-5, atol=1e-6)


def test_uneven_balance_and_multi_value_boundary_1f1b():
    """Uneven splits + a tuple boundary: the packed carrier makes every
    partition ring-compatible, so 1F1B needs no uniformity from the model."""
    split = Lambda(lambda x: (x, jnp.sum(x, axis=-1, keepdims=True)),
                   name="split")
    merge = Lambda(lambda x, s: x * s, name="merge")
    seq = Sequential([Linear(WIDTH), split, merge, Linear(16), Linear(WIDTH)])
    params = seq.init(jax.random.key(0), jnp.zeros((2, WIDTH)))
    balance = [3, 2]
    pipe = Pipe(seq, chunks=4, checkpoint="except_last", mesh=stage_mesh(2),
                schedule="1f1b", balance=balance)
    packed = pipe.shard_params(_regroup(params, balance))
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    y = jnp.sum(jnp.sin(x), axis=-1)

    loss, g = pipe.loss_and_grad(packed, x, targets=y, loss_fn=mse_loss)
    rl, rg = ref_loss_and_grad(seq, params, x, y)
    np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(pipe.unshard_grads(g)),
                    jax.tree_util.tree_leaves(_regroup(rg, balance))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_1f1b_with_data_axis_and_nondivisible_batch():
    """PP x DP with batch 7 over chunks=4, data=2: padded rows are masked
    out of the loss and gradients."""
    seq, params = make_mlp(jax.random.key(0))
    pipe = Pipe(seq, chunks=4, checkpoint="never",
                mesh=stage_mesh(2, n_data=2), schedule="1f1b")
    packed = pipe.shard_params(_regroup(params, pipe.balance))
    x = jax.random.normal(jax.random.key(1), (7, WIDTH))
    y = jnp.sum(jnp.sin(x), axis=-1)

    loss, g = pipe.loss_and_grad(packed, x, targets=y, loss_fn=mse_loss)
    rl, rg = ref_loss_and_grad(seq, params, x, y)
    np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(pipe.unshard_grads(g)),
                    jax.tree_util.tree_leaves(_regroup(rg, pipe.balance))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_memory_plan_reachable_and_capped():
    """The 1F1B memory story from the Pipe object: min(m, n) stashed inputs
    per stage vs GPipe's m."""
    seq, _ = make_mlp(jax.random.key(0))
    p_1f1b = Pipe(seq, chunks=8, mesh=stage_mesh(2), schedule="1f1b")
    p_gpipe = Pipe(seq, chunks=8, mesh=stage_mesh(2), schedule="gpipe")
    plan_1f1b = p_1f1b.memory_plan()
    plan_gpipe = p_gpipe.memory_plan()
    assert plan_1f1b["stash_slots"] == min(8, 2) == 2
    assert plan_gpipe["stash_slots"] == 8
    assert p_1f1b.memory_plan(chunks=4)["stash_slots"] == 2


def test_dropout_determinism_1f1b():
    from pipe_tpu import Dropout
    seq = Sequential([Linear(WIDTH), Dropout(0.5), Linear(WIDTH)])
    pipe = Pipe(seq, chunks=2, checkpoint="except_last", mesh=stage_mesh(2),
                schedule="1f1b", balance=[2, 1])
    packed = pipe.init_sharded(jax.random.key(0), jnp.zeros((2, WIDTH)))
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    y = jnp.sum(x, axis=-1)

    la, _ = pipe.loss_and_grad(packed, x, targets=y, loss_fn=mse_loss,
                               key=jax.random.key(5))
    lb, _ = pipe.loss_and_grad(packed, x, targets=y, loss_fn=mse_loss,
                               key=jax.random.key(5))
    lc, _ = pipe.loss_and_grad(packed, x, targets=y, loss_fn=mse_loss,
                               key=jax.random.key(6))
    assert float(la) == float(lb)
    assert float(la) != float(lc)


def test_jit_train_step_1f1b():
    import optax
    seq, params = make_mlp(jax.random.key(0))
    pipe = Pipe(seq, chunks=4, checkpoint="except_last", mesh=stage_mesh(2),
                schedule="1f1b")
    packed = pipe.shard_params(_regroup(params, pipe.balance))
    tx = optax.sgd(0.05)
    opt = tx.init(packed)
    x = jax.random.normal(jax.random.key(1), (16, WIDTH))
    y = jnp.sum(jnp.sin(x), axis=-1)

    @jax.jit
    def step(pk, opt):
        loss, g = pipe.loss_and_grad(pk, x, targets=y, loss_fn=mse_loss)
        upd, opt = tx.update(g, opt, pk)
        return optax.apply_updates(pk, upd), opt, loss

    ref_p = params
    ref_opt = tx.init(params)

    @jax.jit
    def ref_step(p, opt):
        def f(p):
            return jnp.mean(mse_loss(seq.apply(p, x), y))
        loss, g = jax.value_and_grad(f)(p)
        upd, opt = tx.update(g, opt, p)
        return optax.apply_updates(p, upd), opt, loss

    losses, ref_losses = [], []
    for _ in range(30):
        packed, opt, loss = step(packed, opt)
        ref_p, ref_opt, ref_loss = ref_step(ref_p, ref_opt)
        losses.append(float(loss))
        ref_losses.append(float(ref_loss))
    # Trajectory parity with the UNPIPELINED reference under the same
    # optimizer is the train-step property; a fixed "drops k-fold" bar on
    # this tiny linear model is init-sensitive and says nothing about the
    # pipeline. Progress still asserted so a frozen step can't pass.
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-4)
    assert losses[-1] < losses[0], (losses[0], losses[-1])


# ---------- validation ----------

def test_loss_and_grad_requires_packed_params():
    seq, params = make_mlp(jax.random.key(0))
    pipe = Pipe(seq, chunks=2, mesh=stage_mesh(2), schedule="1f1b")
    sp = _regroup(params, pipe.balance)
    with pytest.raises(TypeError):
        pipe.loss_and_grad(sp, jnp.ones((4, WIDTH)),
                           targets=jnp.ones((4,)), loss_fn=mse_loss)


def test_loss_and_grad_requires_mesh():
    seq, _ = make_mlp(jax.random.key(0))
    pipe = Pipe(seq, chunks=2, n_stages=2)
    with pytest.raises(ValueError):
        pipe.loss_and_grad({}, jnp.ones((4, WIDTH)), loss_fn=mse_loss)


def _skip_seq():
    """[Linear+stash][Linear][Linear][pop+Linear]: the skip jumps stages
    0 -> 3 — the reference's portal path inside the training fence
    (``pipeline.py:136-138``)."""
    from pipe_tpu.core.partition import StageCtx
    from pipe_tpu.extras.skip import skippable, stash, pop
    from pipe_tpu.ops.layers import Module

    @skippable(stash=["z"])
    class S(Module):
        def init(self, key, *a):
            return {}

        def apply(self, p, x, ctx=StageCtx()):
            stash("z", x)
            return x

    @skippable(pop=["z"])
    class Po(Module):
        def init(self, key, *a):
            return {}

        def apply(self, p, x, ctx=StageCtx()):
            return x + pop("z")

    return Sequential([Linear(WIDTH), S(), Linear(WIDTH), Linear(WIDTH),
                       Po(), Linear(WIDTH)])


@pytest.mark.parametrize("schedule", ["1f1b", "gpipe"])
@pytest.mark.parametrize("checkpoint", ["never", "except_last", "always"])
def test_skippable_through_table_executor(schedule, checkpoint):
    """@skippable models train through the memory-capped table executor
    (VERDICT r3 #2): loss AND grads equal the serial emulator — the skip
    value rides a forward ring lane into a FIFO park at its destination,
    and its pop cotangent rides the reverse lane back to the stash site."""
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    y = jnp.sum(jnp.sin(x), axis=-1)

    ref = Pipe(_skip_seq(), chunks=4, checkpoint="except_last", n_stages=4,
               balance=[2, 1, 1, 2])
    params = ref.init(jax.random.key(0), x)

    def ref_loss(ps):
        return jnp.mean(mse_loss(ref(ps, x), y))

    exp_loss = float(ref_loss(params))
    exp_grads = jax.grad(ref_loss)(params)

    pipe = Pipe(_skip_seq(), chunks=4, checkpoint=checkpoint,
                mesh=stage_mesh(4), schedule=schedule, balance=[2, 1, 1, 2])
    packed = pipe.shard_params(pipe.init(jax.random.key(0), x))
    loss, grads = jax.jit(lambda p: pipe.loss_and_grad(
        p, x, targets=y, loss_fn=mse_loss))(packed)
    assert float(loss) == pytest.approx(exp_loss, rel=1e-5)
    got = pipe.unshard_grads(grads)
    for a, b in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(exp_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_skippable_table_executor_with_remat_policy():
    """Skip lanes compose with selective remat on the dynamic scan."""
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    y = jnp.sum(jnp.sin(x), axis=-1)
    results = []
    for policy in (None, jax.checkpoint_policies.dots_saveable):
        pipe = Pipe(_skip_seq(), chunks=4, checkpoint="except_last",
                    mesh=stage_mesh(4), schedule="1f1b",
                    balance=[2, 1, 1, 2], remat_policy=policy)
        packed = pipe.shard_params(pipe.init(jax.random.key(0), x))
        loss, grads = jax.jit(lambda p: pipe.loss_and_grad(
            p, x, targets=y, loss_fn=mse_loss))(packed)
        results.append((float(loss), grads))
    (l0, g0), (l1, g1) = results
    assert l0 == pytest.approx(l1, rel=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("balance", [[2, 1, 1, 2], [2, 1, 2, 1]],
                         ids=["cross-device-lane", "same-device-lane"])
@pytest.mark.parametrize("checkpoint", ["never", "except_last"])
def test_skippable_interleaved(balance, checkpoint):
    """@skippable models train AND eval through interleaved (v > 1)
    placements: each lane takes one direct permute src%d -> dst%d (no
    hop-by-hop relay), so a transiting value can never collide with a
    fresh stash — the hazard that used to exclude v > 1. Both lane
    geometries are covered: endpoints on different devices (0 -> 3 at
    d=2) and on the SAME device (0 -> 2 at d=2), where the lane register
    itself is the transport."""
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    y = jnp.sum(jnp.sin(x), axis=-1)

    ref = Pipe(_skip_seq(), chunks=4, checkpoint="except_last", n_stages=4,
               balance=balance)
    params = ref.init(jax.random.key(0), x)

    def ref_loss(ps):
        return jnp.mean(mse_loss(ref(ps, x), y))

    exp_loss = float(ref_loss(params))
    exp_grads = jax.grad(ref_loss)(params)
    exp_out = ref(params, x)

    pipe = Pipe(_skip_seq(), chunks=4, checkpoint=checkpoint,
                mesh=stage_mesh(2), schedule="interleaved-1f1b",
                balance=balance)
    packed = pipe.shard_params(params)
    loss, grads = jax.jit(lambda p: pipe.loss_and_grad(
        p, x, targets=y, loss_fn=mse_loss))(packed)
    assert float(loss) == pytest.approx(exp_loss, rel=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(pipe.unshard_grads(grads)),
                    jax.tree_util.tree_leaves(exp_grads)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)
    # forward/eval: the FWD-masked tables run the same lanes (no reverse)
    got = pipe(packed, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(exp_out),
                               rtol=1e-5, atol=1e-6)


def test_stage_count_validation_interleaved():
    seq, _ = make_mlp(jax.random.key(0))  # 4 layers
    with pytest.raises(ValueError):
        # interleaved v=2 on 4 mesh stages needs 8 partitions; 4 layers
        # can't split into 8
        Pipe(seq, chunks=2, mesh=stage_mesh(4), schedule="interleaved-1f1b")


def test_integer_inputs_through_table_executor():
    """Token ids (int32) riding the packed boundary carrier through
    Pipe(mesh=).loss_and_grad — the TUTORIAL shape. Regression: int lanes
    in the carrier yield float0 cotangents from jax.vjp, which must be
    converted at the ring boundaries (concrete placeholder zeros on the
    ring, float0 when seeding) or the backward's lax.cond branches
    disagree on dtypes. Loss/grads must equal the emulator."""
    import dataclasses

    from pipe_tpu.models.common import per_row_ce
    from pipe_tpu.models.transformer_lm import LMConfig, build_sequential

    cfg = dataclasses.replace(LMConfig().tiny(), n_layers=2, dropout=0.0)
    tokens = jax.random.randint(jax.random.key(1), (8, cfg.seq_len), 0,
                                cfg.vocab, jnp.int32)
    targets = jnp.roll(tokens, -1, axis=-1)

    def loss_fn(logits, tgt):
        return per_row_ce(logits, tgt)

    emu = Pipe(build_sequential(cfg), chunks=4, checkpoint="except_last",
               n_stages=2)
    params = emu.init(jax.random.key(0), tokens)

    def emu_loss(ps):
        return jnp.mean(loss_fn(emu(ps, tokens), targets))

    exp_loss = float(emu_loss(params))
    exp_grads = jax.grad(emu_loss)(params)

    for mode in ("never", "except_last"):
        pipe = Pipe(build_sequential(cfg), chunks=4, checkpoint=mode,
                    mesh=stage_mesh(2), schedule="1f1b")
        packed = pipe.shard_params(pipe.init(jax.random.key(0), tokens))
        loss, grads = jax.jit(lambda p, pipe=pipe: pipe.loss_and_grad(
            p, tokens, targets=targets, loss_fn=loss_fn))(packed)
        assert float(loss) == pytest.approx(exp_loss, rel=1e-5)
        for a, b in zip(
                jax.tree_util.tree_leaves(pipe.unshard_grads(grads)),
                jax.tree_util.tree_leaves(exp_grads)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6)
