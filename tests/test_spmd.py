"""SPMD executor tests on the virtual 8-device CPU mesh.

The key assertions: the shard_map+ppermute pipeline is numerically transparent
— same outputs and gradients as the plain (unpipelined) model — across stage
counts, checkpoint modes, and a combined (stage, data) mesh. This is the
upstream ``test_transparency`` property (SURVEY §4) applied to the compiled
executor.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.core import microbatch as mb
from pipe_tpu.core.partition import StageCtx
from pipe_tpu.ops.layers import Linear, Sequential
from pipe_tpu.parallel.mesh import make_mesh
from pipe_tpu.parallel.spmd import SpmdPipeline, stack_stage_params

WIDTH = 8


def make_homogeneous(n_stages, key):
    """n_stages identical-structure stages, each one Linear block."""
    layer = Linear(WIDTH)
    params = [layer.init(jax.random.fold_in(key, j), jnp.zeros((1, WIDTH)))
              for j in range(n_stages)]

    def stage_fn(p, h, ctx):
        return jnp.tanh(layer.apply(p, h))

    return stage_fn, params


def reference_forward(stage_fn, params_list, x):
    h = x
    for p in params_list:
        h = stage_fn(p, h, StageCtx())
    return h


@pytest.mark.parametrize("n_stages", [1, 2, 4, 8])
def test_forward_transparency(n_stages):
    key = jax.random.key(0)
    stage_fn, params = make_homogeneous(n_stages, key)
    mesh = make_mesh(n_stages, 1)
    pipe = SpmdPipeline(mesh, stage_fn)
    stacked = stack_stage_params(params)

    chunks = 4
    x = jax.random.normal(jax.random.key(1), (16, WIDTH))
    xs, bs = mb.stack_scatter(x, chunks)

    out = pipe(stacked, {}, {}, xs)
    got = mb.stack_gather(out, bs)
    expected = reference_forward(stage_fn, params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-6)


def test_pre_post_fns():
    """Embed-style pre on stage 0, decode-style post on stage n-1."""
    n_stages = 4
    key = jax.random.key(0)
    stage_fn, params = make_homogeneous(n_stages, key)
    emb = Linear(WIDTH)
    dec = Linear(3)
    pre_p = emb.init(jax.random.key(10), jnp.zeros((1, 5)))
    post_p = dec.init(jax.random.key(11), jnp.zeros((1, WIDTH)))

    def pre_fn(p, x, ctx):
        return emb.apply(p, x)

    def post_fn(p, h, ctx):
        return dec.apply(p, h)

    mesh = make_mesh(n_stages, 1)
    pipe = SpmdPipeline(mesh, stage_fn, pre_fn=pre_fn, post_fn=post_fn)
    stacked = stack_stage_params(params)

    x = jax.random.normal(jax.random.key(1), (8, 5))
    xs, bs = mb.stack_scatter(x, 4)
    out = mb.stack_gather(pipe(stacked, pre_p, post_p, xs), bs)

    expected = dec.apply(post_p,
                         reference_forward(stage_fn, params,
                                           emb.apply(pre_p, x)))
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-5, atol=1e-6)
    assert out.shape == (8, 3)


@pytest.mark.parametrize("checkpoint", ["never", "except_last", "always"])
def test_gradient_transparency(checkpoint):
    n_stages = 4
    key = jax.random.key(0)
    stage_fn, params = make_homogeneous(n_stages, key)
    mesh = make_mesh(n_stages, 1)
    pipe = SpmdPipeline(mesh, stage_fn, checkpoint=checkpoint)
    stacked = stack_stage_params(params)

    x = jax.random.normal(jax.random.key(1), (16, WIDTH))
    xs, bs = mb.stack_scatter(x, 4)

    def pipe_loss(sp):
        out = mb.stack_gather(pipe(sp, {}, {}, xs, train=True), bs)
        return jnp.mean(out ** 2)

    def plain_loss(plist):
        return jnp.mean(reference_forward(stage_fn, plist, x) ** 2)

    got = jax.grad(pipe_loss)(stacked)
    expected = stack_stage_params(
        jax.grad(plain_loss)([p for p in params]))
    for g, e in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(expected)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                   rtol=1e-4, atol=1e-6)


def test_stage_data_mesh():
    """(stage=4, data=2) mesh: data-parallel pipeline, grads averaged right."""
    n_stages, n_data = 4, 2
    key = jax.random.key(0)
    stage_fn, params = make_homogeneous(n_stages, key)
    mesh = make_mesh(n_stages, n_data)
    pipe = SpmdPipeline(mesh, stage_fn)
    stacked = stack_stage_params(params)

    x = jax.random.normal(jax.random.key(1), (16, WIDTH))
    xs, bs = mb.stack_scatter(x, 4)

    out = mb.stack_gather(pipe(stacked, {}, {}, xs), bs)
    expected = reference_forward(stage_fn, params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                               rtol=1e-5, atol=1e-6)

    def pipe_loss(sp):
        o = mb.stack_gather(pipe(sp, {}, {}, xs, train=True), bs)
        return jnp.mean(o ** 2)

    def plain_loss(plist):
        return jnp.mean(reference_forward(stage_fn, plist, x) ** 2)

    got = jax.grad(pipe_loss)(stacked)
    expected_g = stack_stage_params(jax.grad(plain_loss)(list(params)))
    for g, e in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(expected_g)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                   rtol=1e-4, atol=1e-6)


def test_jit_and_train_loop():
    """A jitted SGD loop through the SPMD pipeline converges."""
    n_stages = 2
    stage_fn, params = make_homogeneous(n_stages, jax.random.key(0))
    mesh = make_mesh(n_stages, 1)
    pipe = SpmdPipeline(mesh, stage_fn, checkpoint="except_last")
    stacked = stack_stage_params(params)

    x = jax.random.normal(jax.random.key(1), (32, WIDTH))
    y = jnp.tanh(jnp.roll(x, 1, axis=-1))
    xs, bs = mb.stack_scatter(x, 4)

    @jax.jit
    def step(sp):
        def loss_fn(sp):
            out = mb.stack_gather(pipe(sp, {}, {}, xs, train=True), bs)
            return jnp.mean((out - y) ** 2)
        l, g = jax.value_and_grad(loss_fn)(sp)
        return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, sp, g), l

    losses = []
    for _ in range(80):
        stacked, l = step(stacked)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.4, losses[::10]


def test_loss_in_post_fn():
    """post_fn computing per-example loss (avoids materializing logits)."""
    n_stages = 2
    stage_fn, params = make_homogeneous(n_stages, jax.random.key(0))
    mesh = make_mesh(n_stages, 1)
    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    y = jnp.ones((8, WIDTH))
    xs, bs = mb.stack_scatter(x, 2)

    def post_fn(p, h, ctx):
        # per-row squared error against the target rows riding in p
        return jnp.sum((h - p["target"]) ** 2, axis=-1)

    # thread targets per microbatch? simplest: same target rows for all
    pipe = SpmdPipeline(mesh, stage_fn, post_fn=post_fn)
    stacked = stack_stage_params(params)
    per_row = pipe(stacked, {}, {"target": jnp.ones((WIDTH,))}, xs)
    assert per_row.shape == (2, 4)
    expected = jnp.sum(
        (reference_forward(stage_fn, params, x) - 1.0) ** 2, axis=-1)
    np.testing.assert_allclose(np.asarray(per_row.reshape(-1)),
                               np.asarray(expected), rtol=1e-5, atol=1e-5)


def test_skip_as_carried_pytree_lane():
    """Skip connections on the compiled path: the activation is a pytree and
    a skip is an extra leaf riding the same ppermute ring (the SPMD
    equivalent of the emulator's portal dataflow — reference skip/ package).

    Stage 0 stashes its input into the skip lane; the last stage pops it as
    a residual. Transparency vs the same computation done serially.
    """
    n_stages = 4
    key = jax.random.key(0)
    layer = Linear(WIDTH)
    params = [layer.init(jax.random.fold_in(key, j), jnp.zeros((1, WIDTH)))
              for j in range(n_stages)]

    def stage_fn(p, h, ctx):
        j = jax.lax.axis_index("stage")
        act, skip = h["act"], h["skip"]
        skip = jnp.where(j == 0, act, skip)          # stash at stage 0
        act = jnp.tanh(layer.apply(p, act))
        act = jnp.where(j == n_stages - 1, act + skip, act)  # pop at last
        return {"act": act, "skip": skip}

    def pre_fn(p, x, ctx):
        return {"act": x, "skip": jnp.zeros_like(x)}

    def post_fn(p, h, ctx):
        return h["act"]

    mesh = make_mesh(n_stages, 1)
    pipe = SpmdPipeline(mesh, stage_fn, pre_fn=pre_fn, post_fn=post_fn)
    stacked = stack_stage_params(params)

    x = jax.random.normal(jax.random.key(1), (8, WIDTH))
    xs, bs = mb.stack_scatter(x, 4)
    got = mb.stack_gather(pipe(stacked, {}, {}, xs), bs)

    h = x
    for j, p in enumerate(params):
        h = jnp.tanh(layer.apply(p, h))
    expected = h + x   # skip residual from stage 0's input
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-6)

    # gradients flow through the skip lane
    g = jax.grad(lambda x: jnp.sum(pipe(stacked, {}, {},
                                        mb.stack_scatter(x, 4)[0])))(x)
    assert np.isfinite(np.asarray(g)).all()

def test_remat_post_parity():
    """remat_post trades the post's vocab-scale loss residuals for a decode
    recompute; same explicit key replays, so loss AND grads must be
    identical (bitwise up to reduction order) with the flag on or off —
    including with dropout active through the remat'd post path."""
    from pipe_tpu.models.transformer_lm import LMConfig, PipelinedLM

    cfg = LMConfig(vocab=64, d_model=16, nhead=2, d_ff=32, n_layers=2,
                   seq_len=8, dropout=0.2)
    model = PipelinedLM(cfg, 2)
    sp, prep, postp = model.init(jax.random.key(0))
    stacked = stack_stage_params(sp)
    mesh = make_mesh(2, 1)
    tokens = jax.random.randint(jax.random.key(1), (4, cfg.seq_len), 0,
                                cfg.vocab, jnp.int32)
    x, _ = mb.stack_scatter({"tokens": tokens,
                             "targets": jnp.roll(tokens, -1, -1)}, 2)
    key = jax.random.key(2)

    results = []
    for flag in (False, True):
        pipe = SpmdPipeline(mesh, model.stage_fn, pre_fn=model.pre_fn,
                            post_fn=model.loss_post_fn, post_with_batch=True,
                            checkpoint="except_last", remat_post=flag)

        def loss_fn(sp_, prep_, postp_):
            return jnp.mean(pipe(sp_, prep_, postp_, x, key=key, train=True))

        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
            stacked, prep, postp)
        results.append((loss, grads))

    (l0, g0), (l1, g1) = results
    np.testing.assert_allclose(np.asarray(l0), np.asarray(l1), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-7)
