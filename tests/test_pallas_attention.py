"""Flash-attention kernel tests (interpret mode on CPU).

Parity bar: forward and all three gradients match the XLA reference
attention to float32 tolerance, causal and non-causal, across block
tilings — including tilings smaller than the sequence (the streaming path).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.ops.pallas_attention import flash_attention, supports
from pipe_tpu.ops.ring_attention import blockwise_attention_reference


def qkv(key, b=2, s=64, h=2, d=16, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(kk, (b, s, h, d), dtype) for kk in ks)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("blocks", [(64, 64), (32, 32), (16, 32), (32, 16)])
def test_forward_parity(causal, blocks):
    q, k, v = qkv(jax.random.key(0))
    bq, bk = blocks
    got = flash_attention(q, k, v, causal=causal, block_q=bq, block_k=bk)
    exp = blockwise_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_gradient_parity(causal):
    q, k, v = qkv(jax.random.key(1), s=32)

    def flash_loss(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
        return jnp.sum(o ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(blockwise_attention_reference(
            q, k, v, causal=causal) ** 2)

    g1 = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-5)


def test_jit_value_and_grad():
    q, k, v = qkv(jax.random.key(2), s=32)

    @jax.jit
    def step(q, k, v):
        return jax.value_and_grad(
            lambda q: jnp.sum(flash_attention(q, k, v, block_q=16,
                                              block_k=16)))(q)

    val, g = step(q, k, v)
    assert np.isfinite(float(val)) and g.shape == q.shape


def test_bf16_forward():
    q, k, v = qkv(jax.random.key(3), dtype=jnp.bfloat16)
    got = flash_attention(q, k, v, causal=True)
    exp = blockwise_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(exp, np.float32),
                               rtol=5e-2, atol=5e-2)
    assert got.dtype == jnp.bfloat16


def test_supports_gate():
    assert supports(128)
    assert supports(96, block=32)
    assert not supports(100)   # not divisible by min tile
    assert not supports(4)     # below min tile
    q, k, v = qkv(jax.random.key(4), s=24)
    with pytest.raises(ValueError, match="not divisible"):
        flash_attention(q, k, v, block_q=16, block_k=16)


def test_matches_layers_attention():
    """Same semantics as the MHA building block's attention (no dropout)."""
    from pipe_tpu.ops.layers import dot_product_attention
    q, k, v = qkv(jax.random.key(5), s=32)
    got = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    exp = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                               rtol=2e-5, atol=2e-6)
