"""Flash-attention kernel tests (interpret mode on CPU).

Parity bar: forward and all three gradients match the XLA reference
attention to float32 tolerance, causal and non-causal, across block
tilings — including tilings smaller than the sequence (the streaming path).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pipe_tpu.ops.pallas_attention import flash_attention, supports
from pipe_tpu.ops.ring_attention import blockwise_attention_reference


def qkv(key, b=2, s=64, h=2, d=16, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(kk, (b, s, h, d), dtype) for kk in ks)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("blocks", [(64, 64), (32, 32), (16, 32), (32, 16)])
def test_forward_parity(causal, blocks):
    q, k, v = qkv(jax.random.key(0))
    bq, bk = blocks
    got = flash_attention(q, k, v, causal=causal, block_q=bq, block_k=bk)
    exp = blockwise_attention_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                               rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_gradient_parity(causal):
    q, k, v = qkv(jax.random.key(1), s=32)

    def flash_loss(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
        return jnp.sum(o ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(blockwise_attention_reference(
            q, k, v, causal=causal) ** 2)

    g1 = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-5)


def test_jit_value_and_grad():
    q, k, v = qkv(jax.random.key(2), s=32)

    @jax.jit
    def step(q, k, v):
        return jax.value_and_grad(
            lambda q: jnp.sum(flash_attention(q, k, v, block_q=16,
                                              block_k=16)))(q)

    val, g = step(q, k, v)
    assert np.isfinite(float(val)) and g.shape == q.shape


def test_bf16_forward():
    q, k, v = qkv(jax.random.key(3), dtype=jnp.bfloat16)
    got = flash_attention(q, k, v, causal=True)
    exp = blockwise_attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(exp, np.float32),
                               rtol=5e-2, atol=5e-2)
    assert got.dtype == jnp.bfloat16


def test_supports_gate():
    assert supports(128)
    assert supports(96, block=32)
    assert not supports(100)   # not divisible by min tile
    assert not supports(4)     # below min tile
    q, k, v = qkv(jax.random.key(4), s=24)
    with pytest.raises(ValueError, match="not divisible"):
        flash_attention(q, k, v, block_q=16, block_k=16)


def test_matches_layers_attention():
    """Same semantics as the MHA building block's attention (no dropout)."""
    from pipe_tpu.ops.layers import dot_product_attention
    q, k, v = qkv(jax.random.key(5), s=32)
    got = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    exp = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                               rtol=2e-5, atol=2e-6)


def test_dropout_requires_tpu_in_interpret_mode():
    q, k, v = qkv(jax.random.key(6), s=32)
    with pytest.raises(NotImplementedError, match="TPU PRNG"):
        flash_attention(q, k, v, dropout_rate=0.2,
                        dropout_key=jax.random.key(0), interpret=True)
    with pytest.raises(ValueError, match="requires dropout_key"):
        flash_attention(q, k, v, dropout_rate=0.2, interpret=False)


def test_mha_dropout_routes_xla_on_cpu():
    """On CPU, a dropout-bearing train step must use the XLA path (flash
    interpret mode has no PRNG) — this exercises the routing, not numerics."""
    from pipe_tpu.core.partition import StageCtx
    from pipe_tpu.ops.layers import MultiHeadAttention
    x = jax.random.normal(jax.random.key(0), (2, 32, 64))
    mha = MultiHeadAttention(64, 4, dropout=0.5, impl="flash")
    p = mha.init(jax.random.key(1), x)
    ctx = StageCtx(key=jax.random.key(2), train=True)
    out = mha.apply(p, x, ctx=ctx)  # would raise if routed to flash interpret
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.parametrize("causal", [False, True])
def test_dropout_vjp_algebra_with_stub_mask(monkeypatch, causal):
    """Full dropout fwd+bwd algebra on CPU via a deterministic mask stub.

    Replaces the TPU PRNG mask with a pure jnp function of
    (seed, bh, iq, ik), reconstructs the identical full-matrix mask for an
    XLA oracle `(softmax(s) [causal-masked]) * mask @ v`, and checks forward
    and all three gradients — covering the seeding consistency of the three
    kernels and the pre-dropout-normalizer gradient algebra that only ever
    runs compiled on TPU.
    """
    import math as _math

    from pipe_tpu.ops import pallas_attention as pa

    rate = 0.3

    def fake_mask(seed, bh, iq, ik, shape, r):
        a = jax.lax.broadcasted_iota(jnp.int32, shape, 0)
        b = jax.lax.broadcasted_iota(jnp.int32, shape, 1)
        z = (a * 7 + b * 13 + bh * 31 + iq * 17 + ik * 11 + seed) % 10
        keep = z >= jnp.int32(r * 10)
        return jnp.where(keep, 1.0 / (1.0 - r), 0.0).astype(jnp.float32)

    monkeypatch.setattr(pa, "_drop_mask", fake_mask)
    pa._make.cache_clear()

    b, s, h, d = 1, 32, 2, 8
    bq = bk = 16
    key = jax.random.key(0)
    q, k, v = qkv(key, b=b, s=s, h=h, d=d)

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    scale = 1.0 / _math.sqrt(d)
    attend = pa._make(causal, scale, bq, bk, True, rate)
    seed0 = jnp.zeros((1,), jnp.int32)

    # oracle: assemble the identical full mask per (bh, q-block, k-block)
    mask_full = np.zeros((b * h, s, s), np.float32)
    for bh_i in range(b * h):
        for iq in range(s // bq):
            for ik in range(s // bk):
                blk = fake_mask(0, bh_i, iq, ik, (bq, bk), rate)
                mask_full[bh_i, iq * bq:(iq + 1) * bq,
                          ik * bk:(ik + 1) * bk] = np.asarray(blk)
    mask_full = jnp.asarray(mask_full)

    def oracle(q3, k3, v3):
        sc = jnp.einsum("zqd,zkd->zqk", q3, k3) * scale
        if causal:
            cm = jnp.tril(jnp.ones((s, s), bool))
            sc = jnp.where(cm, sc, -jnp.inf)
        w = jax.nn.softmax(sc, axis=-1)
        return jnp.einsum("zqk,zkd->zqd", w * mask_full, v3)

    q3, k3, v3 = to3(q), to3(k), to3(v)
    got = attend(q3, k3, v3, seed0)
    exp = oracle(q3, k3, v3)
    np.testing.assert_allclose(np.asarray(got), np.asarray(exp),
                               rtol=2e-5, atol=2e-6)

    g_got = jax.grad(lambda q3, k3, v3: jnp.sum(
        attend(q3, k3, v3, seed0) ** 2), argnums=(0, 1, 2))(q3, k3, v3)
    g_exp = jax.grad(lambda q3, k3, v3: jnp.sum(
        oracle(q3, k3, v3) ** 2), argnums=(0, 1, 2))(q3, k3, v3)
    for a, e in zip(g_got, g_exp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e),
                                   rtol=5e-4, atol=1e-5)
    pa._make.cache_clear()


def test_dropout_rate_validation():
    q, k, v = qkv(jax.random.key(8), s=16)
    for bad in (1.0, 1.5, -0.1):
        with pytest.raises(ValueError, match="dropout_rate"):
            flash_attention(q, k, v, dropout_rate=bad,
                            dropout_key=jax.random.key(0))
